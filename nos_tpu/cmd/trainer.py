"""nos-tpu-trainer — the training binary gang-scheduled worker pods run.

This is the data-plane half of the gang contract
(config/operator/samples/gang-jobset.yaml, examples/llama3_70b_v5p.py): the
scheduler places one pod per TPU host of an ICI slice; each pod runs this
binary, which

1. initializes ``jax.distributed`` from the gang environment when running
   multi-host (GKE TPU pods get the coordinator/world from the TPU env;
   single-process runs skip it);
2. builds the ``ParallelLayout`` mesh over the visible devices —
   dp/fsdp/tp/sp/ep via ``make_train_step``, or the pipelined step when
   ``pp > 1``;
3. trains the decoder transformer on synthetic (or memory-mapped) token
   batches, logging loss and steps/s;
4. checkpoints through ``nos_tpu.train.CheckpointManager`` and resumes
   from the latest step on restart — the preemption/reschedule story the
   quota scheduler relies on.
"""
from __future__ import annotations

import argparse
import logging
import os
import time
from dataclasses import dataclass, fields
from typing import Optional, Sequence

logger = logging.getLogger("nos_tpu.trainer")


@dataclass
class TrainerConfig:
    # model (defaults are test-sized; production configs come from --config)
    vocab: int = 32000
    d_model: int = 512
    n_layers: int = 4
    n_heads: int = 8
    n_kv_heads: int = 0
    d_ff: int = 1408
    max_seq: int = 512
    n_experts: int = 0
    sp_strategy: str = "ring"          # ring | ulysses (sp axis attention)
    # memory/recompute trade (models/transformer.TransformerConfig):
    # full | dots | except_mlp | minimal, and the chunked lm head
    remat_policy: str = "full"
    loss_chunk: int = 0
    # layout
    dp: int = 1
    fsdp: int = 1
    tp: int = 1
    pp: int = 1
    sp: int = 1
    ep: int = 1
    n_microbatches: int = 2            # pp only
    # pp schedule: "1f1b" (P-bounded activation memory; no sp) or
    # "gpipe" (composes with sp/ring attention for dense long-context)
    pipeline_schedule: str = "1f1b"    # 1f1b | gpipe | interleaved
    # interleaved schedule only: layer chunks per stage (bubble ~ 1/v);
    # params are stored chunk-major, recorded in the checkpoint stamp
    virtual_stages: int = 2
    # run
    steps: int = 10
    batch_size: int = 8
    seq_len: int = 256
    learning_rate: float = 3e-4
    # optimizer (train/optim.py): linear warmup into constant|cosine,
    # global-norm clipping, on-device gradient accumulation
    lr_schedule: str = "constant"
    warmup_steps: int = 0
    min_lr_ratio: float = 0.0
    weight_decay: float = 0.01
    adam_b1: float = 0.9
    adam_b2: float = 0.95
    grad_clip: float = 0.0
    accum_steps: int = 1
    seed: int = 0
    log_every: int = 10
    # data: glob of memory-mapped token shards (train/data.py); empty =
    # deterministic synthetic batches. prefetch = batches staged ahead
    # onto devices (host paging + transfer overlap compute); 0 disables
    # prefetching entirely (synchronous per-step assembly, no thread)
    data_path: str = ""
    prefetch: int = 2
    # held-out evaluation: every eval_every steps, mean loss over
    # eval_steps deterministic batches from eval_data_path (0 = off)
    eval_data_path: str = ""
    eval_every: int = 0
    eval_steps: int = 4
    # checkpointing: step cadence, plus an optional wall-clock cadence
    # (0 = off) — with variable step times (compile stalls, input
    # hiccups, MoE load imbalance) a pure step count can leave long
    # unprotected gaps; whichever cadence fires first saves
    checkpoint_dir: str = ""
    checkpoint_every: int = 100
    checkpoint_every_s: float = 0.0
    # preemption: catch SIGTERM (GKE spot/maintenance eviction sends it,
    # then waits terminationGracePeriodSeconds), finish the in-flight
    # step, checkpoint, and exit cleanly so the rescheduled gang resumes
    # from the signal, not from the last periodic save
    handle_sigterm: bool = True
    # multi-host only: the stop flag and the time-cadence verdict must be
    # agreed collectively (allgather/broadcast), and a per-step host sync
    # can serialize JAX's async dispatch on fast steps. Agree every N
    # steps instead — one fused allgather carries both flags. N=8 keeps
    # detection lag ~8 step times, well inside a 30s grace period for
    # any real training step; single-host polls its local flag for free
    # every step regardless.
    host_sync_every: int = 8
    # profiling: when set, a jax.profiler trace of steps [profile_start,
    # profile_start+profile_steps) is written here (viewable in
    # TensorBoard/XProf — the TPU tracing story)
    profile_dir: str = ""
    profile_start: int = 2
    profile_steps: int = 3
    # lifecycle integration: when BOTH are set, the binary watches its
    # own node for preemption/maintenance notices on the control plane
    # (nos_tpu/lifecycle) and turns them into the graceful-stop event —
    # the same checkpoint-banking path SIGTERM takes, but triggered by
    # the notice's lead time instead of the eviction itself. node_name
    # comes from the downward API (spec.nodeName) in the gang manifests.
    node_name: str = ""
    lifecycle_api: str = ""
    # misc
    log_level: str = "info"
    bf16: bool = True
    # Prometheus scrape endpoint (0 = off): /metrics + /healthz via the
    # shared HealthServer, like every control-plane binary. Exposes
    # nos_tpu_train_* (steps, tokens, step-seconds, loss, eval loss,
    # checkpoint saves, preemption exits)
    metrics_port: int = 0

    @classmethod
    def from_yaml_file(cls, path: str) -> "TrainerConfig":
        import yaml

        with open(path) as f:
            data = yaml.safe_load(f) or {}
        known = {f.name for f in fields(cls)}
        unknown = set(data) - known
        if unknown:
            raise ValueError(f"{path}: unknown trainer config keys {sorted(unknown)}")
        return cls(**data)


def _maybe_init_distributed() -> None:
    """Multi-host init. Two triggers (single-process runs stay untouched):

    - explicit env: COORDINATOR_ADDRESS (+ NUM_PROCESSES, PROCESS_ID) — the
      contract the gang manifests set (examples/llama3_70b_v5p.py
      worker_pods(), config/operator/samples/gang-jobset.yaml): worker 0's
      pod address as coordinator, gang-size and gang-worker as world/rank;
    - TPU pod auto-detect: on a multi-host GKE TPU slice the TPU env
      (TPU_WORKER_HOSTNAMES) carries the topology and
      jax.distributed.initialize() reads it natively with no arguments."""
    import jax

    if os.environ.get("COORDINATOR_ADDRESS"):
        jax.distributed.initialize(
            coordinator_address=os.environ["COORDINATOR_ADDRESS"],
            num_processes=int(os.environ.get("NUM_PROCESSES", "1")),
            process_id=int(os.environ.get("PROCESS_ID", "0")),
        )
    elif len(os.environ.get("TPU_WORKER_HOSTNAMES", "").split(",")) > 1:
        jax.distributed.initialize()


def train(cfg: TrainerConfig, stop_event=None) -> float:
    """Run the configured training job; returns the final loss.

    ``stop_event`` (threading.Event) requests a graceful early exit: the
    loop finishes the current step, checkpoints it, and returns. When
    ``cfg.handle_sigterm`` is set and this is the main thread, SIGTERM
    sets the event — the Kubernetes preemption contract (pod deletion →
    SIGTERM → grace period → SIGKILL), so an evicted gang worker banks
    its progress instead of losing up to ``checkpoint_every`` steps."""
    import signal
    import threading

    import jax
    import jax.numpy as jnp
    import optax

    from nos_tpu.models import transformer as tfm
    from nos_tpu.parallel.layout import ParallelLayout
    from nos_tpu.parallel.mesh import build_mesh, data_sharding

    layout = ParallelLayout(dp=cfg.dp, fsdp=cfg.fsdp, tp=cfg.tp, pp=cfg.pp,
                            sp=cfg.sp, ep=cfg.ep)
    mesh = build_mesh(layout, jax.devices()[:layout.chips])
    model_cfg = tfm.TransformerConfig(
        vocab=cfg.vocab, d_model=cfg.d_model, n_layers=cfg.n_layers,
        n_heads=cfg.n_heads, n_kv_heads=cfg.n_kv_heads, d_ff=cfg.d_ff,
        max_seq=cfg.max_seq, n_experts=cfg.n_experts,
        sp_strategy=cfg.sp_strategy,
        remat_policy=cfg.remat_policy, loss_chunk=cfg.loss_chunk,
        dtype=jnp.bfloat16 if cfg.bf16 else jnp.float32,
    )

    pipelined = cfg.pp > 1
    if pipelined:
        from nos_tpu.parallel.pipeline import (
            make_pipeline_train_step, pipeline_param_shardings,
        )

        shardings = pipeline_param_shardings(mesh, model_cfg)
    else:
        shardings = tfm.param_shardings(mesh, model_cfg)

    interleaved = pipelined and cfg.pipeline_schedule == "interleaved"

    def fresh_params():
        p = tfm.init_params(jax.random.PRNGKey(cfg.seed), model_cfg)
        if interleaved:
            # chunk-major layer order: the interleaved schedule's params
            # layout (checkpoints store this order too — stamped as
            # layer_order so a schedule drift fails by name)
            from nos_tpu.parallel.pipeline import interleave_params

            p = interleave_params(p, cfg.pp, cfg.virtual_stages)
        return p

    if jax.process_count() == 1:
        params = jax.device_put(fresh_params(), shardings)
    else:
        # multi-host: host arrays can't be device_put onto non-addressable
        # devices; compile the init with the target shardings instead so
        # every process materializes only its shards
        params = jax.jit(fresh_params, out_shardings=shardings)()
    from nos_tpu.train.optim import build_optimizer

    optimizer = build_optimizer(
        cfg.learning_rate, cfg.steps, warmup_steps=cfg.warmup_steps,
        schedule=cfg.lr_schedule, min_lr_ratio=cfg.min_lr_ratio,
        weight_decay=cfg.weight_decay, b1=cfg.adam_b1, b2=cfg.adam_b2,
        grad_clip=cfg.grad_clip, accum_steps=cfg.accum_steps)
    opt_state = optimizer.init(params)

    ckpt = None
    start_step = 0
    if cfg.checkpoint_dir:
        from nos_tpu.train import CheckpointManager
        from nos_tpu.train.checkpoint import model_arch_dict

        ckpt = CheckpointManager(cfg.checkpoint_dir)
        # stamp (or, on resume, validate against) the architecture so a
        # config drift between runs fails by field name, not shape error
        ckpt.write_model_config(model_arch_dict(cfg))
        latest = ckpt.latest()
        if latest is not None:
            params, opt_state = ckpt.restore(
                latest, params_template=params,
                opt_state_template=opt_state, mesh=mesh)
            start_step = latest
            logger.info("resumed from checkpoint step %d", latest)

    # donate params+opt_state: without donation XLA double-buffers both
    # across the step (peak HBM + one full params+optimizer copy), which
    # is exactly the margin that decides the largest fitting batch on a
    # real chip. The loop rebinds both from the step's outputs, and the
    # preemption/checkpoint paths only touch the POST-step values, so
    # the invalidated input buffers are never read. (CPU test runs just
    # log a donation-unused warning.)
    if pipelined:
        step_fn = jax.jit(make_pipeline_train_step(
            model_cfg, optimizer, mesh, n_microbatches=cfg.n_microbatches,
            schedule=cfg.pipeline_schedule,
            virtual_stages=cfg.virtual_stages), donate_argnums=(0, 1))
    else:
        step_fn = jax.jit(tfm.make_train_step(model_cfg, optimizer, mesh),
                          donate_argnums=(0, 1))

    def put(x, sharding):
        if jax.process_count() == 1:
            return jax.device_put(x, sharding)
        # every process holds the same deterministic global batch; each
        # materializes only the shards its devices own
        return jax.make_array_from_callback(
            x.shape, sharding, lambda idx: x[idx])

    dataset = None
    if cfg.data_path:
        from nos_tpu.train.data import TokenDataset

        dataset = TokenDataset(cfg.data_path, cfg.seq_len,
                               seed=cfg.seed + 1)
        logger.info("dataset: %d shards, %d tokens",
                    len(dataset.paths), dataset.n_tokens)

    eval_fn = eval_dataset = eval_batches = None
    if cfg.eval_every > 0 and cfg.eval_data_path:
        from nos_tpu.train.data import TokenDataset

        eval_dataset = TokenDataset(cfg.eval_data_path, cfg.seq_len,
                                    seed=cfg.seed + 2)
        if pipelined:
            from nos_tpu.parallel.pipeline import (
                pipeline_1f1b_loss_fn, pipeline_loss_fn,
            )

            # eval matches the training schedule: loss-only 1F1B and
            # interleaved run their cheap forward-only tables; gpipe
            # (the sp-composing schedule) evaluates with its own forward
            if interleaved:
                from nos_tpu.parallel.pipeline import (
                    pipeline_interleaved_loss_fn,
                )

                eval_fn = jax.jit(lambda p, b: pipeline_interleaved_loss_fn(
                    p, model_cfg, b, mesh, cfg.n_microbatches,
                    cfg.virtual_stages))
            else:
                ploss = (pipeline_1f1b_loss_fn
                         if cfg.pipeline_schedule == "1f1b"
                         else pipeline_loss_fn)
                eval_fn = jax.jit(lambda p, b: ploss(
                    p, model_cfg, b, mesh, cfg.n_microbatches))
        else:
            eval_fn = jax.jit(
                lambda p, b: tfm.loss_fn(p, model_cfg, b, mesh))

    def batch_for(step: int):
        # deterministic per step (dataset sampling is a pure function of
        # (seed, step); synthetic uses fold_in) so a resumed run replays
        # exactly the stream an uninterrupted one would have seen
        if dataset is not None:
            # every process assembles the global batch (tens of MB even at
            # large global sizes — memmap windows, not the corpus);
            # `put`'s make_array_from_callback then transfers only the
            # shards this process's devices own. The per-process slicing
            # API (dataset.batch(..., process_index/process_count)) is for
            # custom loops that feed process-local arrays directly.
            host = dataset.batch(step, cfg.batch_size)
            return {k: put(v, data_sharding(mesh))
                    for k, v in host.items()}
        key = jax.random.fold_in(jax.random.PRNGKey(cfg.seed + 1), step)
        tokens = jax.random.randint(
            key, (cfg.batch_size, cfg.seq_len), 0, cfg.vocab)
        return {
            "tokens": put(tokens, data_sharding(mesh)),
            "targets": put(jnp.roll(tokens, -1, axis=1), data_sharding(mesh)),
        }

    stop = stop_event if stop_event is not None else threading.Event()
    handler_installed = False
    prev_handler = None

    will_install = cfg.handle_sigterm and \
        threading.current_thread() is threading.main_thread()
    fused_sync = None           # multi-host only; None => local flag path
    stop_requested = None
    if jax.process_count() > 1:
        # The allgather is a COLLECTIVE: every process must run it or
        # none, and they must decide identically — so the decision keys
        # on cfg.handle_sigterm alone (config is gang-wide; thread-ness
        # and per-call stop_event need not be). A process whose handler
        # didn't install still participates with a never-set flag.
        if cfg.handle_sigterm:
            # gang workers may receive SIGTERM steps apart; a per-process
            # flag would make the early breaker abandon the collective
            # step/save its peers are still in and deadlock everyone
            # until SIGKILL. Agree on a step-keyed cadence (every
            # cfg.host_sync_every steps — deterministic from gang-wide
            # config, so all processes sync together): ONE two-int32
            # allgather per sync carries both the stop flag and the
            # time-cadence checkpoint verdict, so all workers bank the
            # SAME step together without a per-step host round-trip
            # stalling async dispatch.
            import numpy as np
            from jax.experimental import multihost_utils

            def fused_sync(due_local: bool):
                """One collective for both per-cadence questions: did ANY
                process see SIGTERM, and is a time-cadence save due by
                process 0's clock (clocks differ per host, so rank 0
                arbitrates)."""
                flags = np.asarray(multihost_utils.process_allgather(
                    np.asarray([stop.is_set(), due_local], np.int32)))
                flags = flags.reshape(-1, 2)
                return bool(flags[:, 0].any()), bool(flags[0, 1])
        elif stop_event is not None:
            raise ValueError(
                "stop_event on a multi-host run requires handle_sigterm: "
                "true — without the per-step flag agreement an early "
                "breaker deadlocks the gang's collectives")
        else:
            stop_requested = lambda: False  # noqa: E731
    elif stop_event is not None or will_install:
        stop_requested = stop.is_set
    else:   # no source can ever set the flag: skip even the local check
        stop_requested = lambda: False  # noqa: E731

    from nos_tpu.utils.metrics import default_registry

    reg = default_registry()
    m_steps = reg.counter(
        "nos_tpu_train_steps_total", "Training steps completed")
    m_tokens = reg.counter(
        "nos_tpu_train_tokens_total", "Tokens consumed by training")
    m_step_s = reg.histogram(
        "nos_tpu_train_step_seconds",
        "Avg wall time per step, observed at log boundaries (per-step "
        "timing would force a device sync every step)",
        buckets=(0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0))
    m_saves = reg.counter(
        "nos_tpu_train_checkpoint_saves_total", "Checkpoints saved")
    m_preempt = reg.counter(
        "nos_tpu_train_preemptions_total",
        "Graceful preemption exits (SIGTERM/stop event, step banked)")
    g_loss = reg.gauge("nos_tpu_train_loss", "Most recent training loss")
    g_eval = reg.gauge(
        "nos_tpu_train_eval_loss", "Most recent held-out eval loss")

    loss = float("nan")
    preempted = False
    last_saved = start_step
    profiling = False
    profiled = not (cfg.profile_dir and cfg.profile_steps > 0)
    profile_stop = 0
    t0 = time.perf_counter()
    last_log_t, last_log_step = t0, start_step
    last_save_t = t0
    time_cadence_collective = (ckpt is not None
                               and cfg.checkpoint_every_s > 0
                               and jax.process_count() > 1)
    if time_cadence_collective:
        import numpy as _np
        from jax.experimental import multihost_utils as _mh_utils
    from nos_tpu.train.data import prefetch_to_device

    if cfg.prefetch > 0:
        batches = prefetch_to_device(
            batch_for, start_step, cfg.steps - start_step,
            depth=cfg.prefetch)
    else:   # synchronous: no background thread, nothing staged ahead
        batches = (batch_for(s) for s in range(start_step, cfg.steps))
    try:
        # install inside the try so any exception between here and the
        # loop still restores the handler — a leaked one would swallow
        # the real eviction signal later in this process's life
        if cfg.handle_sigterm and \
                threading.current_thread() is threading.main_thread():
            prev_handler = signal.signal(
                signal.SIGTERM, lambda *_: stop.set())
            handler_installed = True
        for step, batch in zip(range(start_step, cfg.steps), batches):
            if not profiled and step >= cfg.profile_start:
                # >= so a checkpoint-resumed run past profile_start traces
                jax.profiler.start_trace(cfg.profile_dir)
                profiling, profiled = True, True
                profile_stop = step + cfg.profile_steps
            params, opt_state, loss_arr = step_fn(
                params, opt_state, batch)
            m_steps.inc()
            # per-process SHARE of the global batch, so a Prometheus
            # sum() over a gang's pods reads true global throughput
            m_tokens.inc(cfg.batch_size * cfg.seq_len
                         / max(jax.process_count(), 1))
            if profiling and step + 1 >= profile_stop:
                jax.block_until_ready(loss_arr)
                jax.profiler.stop_trace()
                profiling = False
                logger.info("profiler trace written to %s", cfg.profile_dir)
            sync_now = ((step + 1) % max(cfg.host_sync_every, 1) == 0
                        or step + 1 == cfg.steps)
            due_by_time = None      # resolved below on the local path
            if fused_sync is not None:
                if sync_now:
                    due_local = (
                        ckpt is not None and cfg.checkpoint_every_s > 0
                        and time.perf_counter() - last_save_t
                        >= cfg.checkpoint_every_s)
                    stop_now, due_by_time = fused_sync(due_local)
                else:
                    stop_now, due_by_time = False, False
            else:
                stop_now = stop_requested()
            if stop_now:
                # preemption: bank the step just completed (synchronous —
                # the grace period is short, so this runs BEFORE eval and
                # the periodic save, not after) and leave. The state is
                # labeled with the TRUE step count so resume replays the
                # exact stream an uninterrupted run would have seen.
                preempted = True
                jax.block_until_ready(loss_arr)
                loss = float(loss_arr)
                if ckpt is not None and last_saved != step + 1:
                    ckpt.save(step + 1, params, opt_state)
                    last_saved = step + 1
                    m_saves.inc()
                m_preempt.inc()
                g_loss.set(loss)
                logger.info(
                    "stop requested (preemption): checkpointed step %d/%d, "
                    "exiting cleanly", step + 1, cfg.steps)
                break
            if (step + 1) % cfg.log_every == 0 or step + 1 == cfg.steps:
                jax.block_until_ready(loss_arr)
                loss = float(loss_arr)
                g_loss.set(loss)
                now = time.perf_counter()
                dt = now - t0
                done = step + 1 - start_step
                m_step_s.observe((now - last_log_t)
                                 / max(step + 1 - last_log_step, 1))
                last_log_t, last_log_step = now, step + 1
                logger.info("step %d/%d loss %.4f (%.2f steps/s)",
                            step + 1, cfg.steps, loss, done / max(dt, 1e-9))
            if eval_fn is not None and (step + 1) % cfg.eval_every == 0:
                if eval_batches is None:
                    # the eval set is deterministic — stage it onto the
                    # devices once, reuse every trigger
                    eval_batches = [
                        {k: put(v, data_sharding(mesh))
                         for k, v in
                         eval_dataset.batch(i, cfg.batch_size).items()}
                        for i in range(cfg.eval_steps)
                    ]
                losses = [eval_fn(params, eb) for eb in eval_batches]
                mean = sum(float(x) for x in losses) / len(losses)
                g_eval.set(mean)
                logger.info("step %d eval loss %.4f (%d batches)",
                            step + 1, mean, cfg.eval_steps)
            if due_by_time is None:
                # local path: single-host, or multi-host without the
                # SIGTERM fused sync (handle_sigterm: false)
                due_by_time = (ckpt is not None
                               and cfg.checkpoint_every_s > 0
                               and time.perf_counter() - last_save_t
                               >= cfg.checkpoint_every_s)
                if time_cadence_collective:
                    # the save is a COLLECTIVE (orbax sharded write):
                    # clocks differ per host, so process 0's verdict is
                    # broadcast — on the same step-keyed cadence as the
                    # fused path (sync_now is deterministic gang-wide, so
                    # the short-circuit is identical on every process)
                    due_by_time = sync_now and bool(
                        _mh_utils.broadcast_one_to_all(
                            _np.asarray(due_by_time)))
            if ckpt is not None and (
                    (step + 1) % cfg.checkpoint_every == 0 or due_by_time):
                # async: serialization overlaps the next steps' compute
                # (params are immutable arrays — the snapshot is safe);
                # close() at exit fences the last in-flight save
                ckpt.save(step + 1, params, opt_state, wait=False)
                last_saved = step + 1
                last_save_t = time.perf_counter()
                m_saves.inc()
        # success path: final save only when steps actually ran to the
        # configured end (a restart whose restored step already meets
        # cfg.steps must not relabel old state, and a preempted exit must
        # not label partial progress as cfg.steps); finally fences+closes
        if ckpt is not None and not preempted and start_step < cfg.steps \
                and last_saved != cfg.steps:
            ckpt.save(cfg.steps, params, opt_state)
            m_saves.inc()
    finally:
        # release the prefetch producer (and the device batches it holds)
        # immediately on every exit path, not at GC time — an OOM retry
        # needs that memory back now
        batches.close()
        # stop the trace on every exit path (incl. step_fn raising) so a
        # retry/next train() in this process doesn't find the profiler
        # already active; window-past-end also lands here
        if profiling:
            try:
                jax.block_until_ready(loss_arr)
            except Exception:
                pass
            jax.profiler.stop_trace()
            logger.info("profiler trace written to %s", cfg.profile_dir)
        # fence any in-flight async save on EVERY exit path — an
        # exception retry must not race a background writer over the
        # checkpoint directory
        if ckpt is not None:
            ckpt.close()
        if handler_installed:
            # restore even a None previous handler (installed from C):
            # SIG_DFL is the honest stand-in python can express
            signal.signal(signal.SIGTERM,
                          prev_handler if prev_handler is not None
                          else signal.SIG_DFL)
    return loss


def main(argv: Optional[Sequence[str]] = None) -> None:
    parser = argparse.ArgumentParser(prog="nos-tpu-trainer", description=__doc__)
    parser.add_argument("--config", default="", help="trainer config YAML")
    parser.add_argument(
        "--log-format", choices=("text", "json"), default="text",
        help="log line format; json emits one object per line with "
             "trace_id/span_id injected when a tracing span is active")
    args = parser.parse_args(argv)

    cfg = TrainerConfig.from_yaml_file(args.config) if args.config \
        else TrainerConfig()
    from nos_tpu.cmd import setup_logging as _shared_setup_logging
    _shared_setup_logging(
        0, args.log_format,
        numeric_level=getattr(logging, cfg.log_level.upper(), 20))
    _maybe_init_distributed()
    health = None
    if cfg.metrics_port:
        from nos_tpu.cmd.serve import HealthServer

        health = HealthServer(host="0.0.0.0", port=cfg.metrics_port).start()
        logger.info("metrics on %s/metrics", health.address)
    stop_event = None
    notice_mgr = None
    if cfg.node_name and cfg.lifecycle_api:
        # preemption-notice watcher: a maintenance/preemption notice on
        # THIS pod's node sets the stop event train() consumes, banking a
        # checkpoint inside the notice's lead time (lifecycle/events.py)
        import threading

        from nos_tpu.kube.controller import Manager
        from nos_tpu.kube.httpapi import RemoteApiServer
        from nos_tpu.lifecycle.events import preemption_signal_controller

        stop_event = threading.Event()
        notice_mgr = Manager(RemoteApiServer(cfg.lifecycle_api))
        notice_mgr.add_controller(
            preemption_signal_controller(cfg.node_name, stop_event))
        threading.Thread(target=notice_mgr.run, daemon=True).start()
        logger.info("watching node %s for preemption/maintenance notices",
                    cfg.node_name)
    try:
        final = train(cfg, stop_event=stop_event)
    finally:
        if notice_mgr is not None:
            notice_mgr.stop()
        if health is not None:
            health.stop()
    logger.info("training done, final loss %.4f", final)


if __name__ == "__main__":
    main()
