"""nos-tpu-harvest — the diurnal chip harvester (ISSUE 12).

Hosts ``harvest.HarvestController``: keeps ``--max-gangs`` preemptible
training JobSet gangs parked in ``--namespace`` under a scheduling
hold, releases a gang to the nos scheduler whenever the pool's
ElasticQuota slack has covered a whole gang for ``--launch-stable``
seconds (gang admission's all-or-nothing placement is the real launch
gate), and — when quota reclaim fires and the scheduler stamps a
``nos.ai/reclaim-notice-deadline`` on a gang — runs the graceful
reclaim protocol: checkpoint (bounded by ``--checkpoint-budget``),
fence, gang-evict through the lifecycle eviction machinery, witnessed
resume on the next trough's rebind.

The trainer seam rides pod annotations (checkpoint requests / fences /
resume steps the training job polls) with ``--checkpoint-root`` as the
witness: the durable step is read from the gang's orbax checkpoint
directory on shared storage (``<root>/<gang>``), so a resume restarts
only from evidence the harvester can see. Without a checkpoint root the
harvester still conserves quota semantics — it just cannot credit
banked progress (documented degradation, not an error).
"""
from __future__ import annotations

import argparse
from typing import Optional, Sequence

from nos_tpu.cmd import serve
from nos_tpu.harvest import (
    AnnotationTrainerBridge, HarvestConfig, HarvestController,
)
from nos_tpu.kube.client import Client
from nos_tpu.kube.controller import Manager
from nos_tpu.kube.leaderelection import LeaderElectionConfig


def build(server, cfg: HarvestConfig, trainer=None,
          leader_election: bool = True,
          identity: str = "harvest-0") -> Manager:
    election = None
    if leader_election:
        election = LeaderElectionConfig(
            lease_name=f"nos-tpu-harvest-{cfg.name}-leader",
            identity=identity)
    mgr = Manager(server, leader_election=election)
    ctl = HarvestController(cfg, trainer=trainer)
    mgr.add_controller(ctl.controller())
    mgr.stats = ctl.stats           # HealthServer /stats route
    return mgr


def main(argv: Optional[Sequence[str]] = None) -> None:
    parser = argparse.ArgumentParser(prog="nos-tpu-harvest",
                                     description=__doc__)
    serve.common_flags(parser, config=False)
    parser.add_argument("--name", default="harvest",
                        help="harvest plane name (the nos.ai/harvest "
                             "label value on gang pods)")
    parser.add_argument("--namespace", default="batch",
                        help="the borrower namespace the training gangs "
                             "run in (its ElasticQuota min may be 0 — "
                             "the pure-scavenger shape)")
    parser.add_argument(
        "--resource", default="google.com/tpu",
        help="resource name each gang worker requests")
    parser.add_argument(
        "--gang-size", type=int, default=2,
        help="workers (hosts) per training JobSet gang")
    parser.add_argument(
        "--chips-per-worker", type=float, default=8.0,
        help="chips each gang worker requests")
    parser.add_argument(
        "--topology", default="4x4",
        help="slice topology the gang requires (the "
             "nos.ai/tpu-topology annotation gang placement honors)")
    parser.add_argument(
        "--max-gangs", type=int, default=2,
        help="gang slots the harvester maintains (parked when the pool "
             "has no slack)")
    parser.add_argument(
        "--checkpoint-budget", type=float, default=30.0,
        help="seconds a reclaim-noticed gang may spend banking a "
             "checkpoint before the fence+gang-evict is forced anyway "
             "(keep at or under the scheduler's reclaim grace window)")
    parser.add_argument(
        "--checkpoint-interval", type=float, default=60.0,
        help="the training jobs' checkpoint cadence — the unit the "
             "work-conservation invariant is stated in (lost work per "
             "reclaim <= one interval + save duration + budget)")
    parser.add_argument(
        "--launch-stable", type=float, default=15.0,
        help="seconds quota slack must cover a whole gang before a "
             "parked gang is released to the scheduler")
    parser.add_argument(
        "--interval", type=float, default=5.0,
        help="seconds between reconcile passes")
    parser.add_argument(
        "--priority", type=int, default=-10,
        help="pod priority for gang workers (preemption victim order; "
             "keep it below first-party batch workloads)")
    parser.add_argument(
        "--trainer-image", default="nos-tpu-trainer",
        help="container image the gang worker pods run")
    parser.add_argument(
        "--checkpoint-root", default="",
        help="shared-storage root of the gangs' orbax checkpoint "
             "directories (<root>/<gang>): the WITNESS a quota-reclaim "
             "resume is gated on; empty = no banked-progress credit")
    parser.add_argument(
        "--identity", default="harvest-0",
        help="leader-election identity (pod name in-cluster)")
    parser.add_argument(
        "--no-leader-election", action="store_true",
        help="single-replica deployments may skip the Lease")
    args = parser.parse_args(argv)

    serve.setup_observability(args)
    cfg = HarvestConfig(
        name=args.name, namespace=args.namespace,
        resource=args.resource,
        gang_size=args.gang_size,
        chips_per_worker=args.chips_per_worker,
        topology=args.topology,
        max_gangs=args.max_gangs,
        checkpoint_budget_s=args.checkpoint_budget,
        checkpoint_interval_s=args.checkpoint_interval,
        launch_stable_s=args.launch_stable,
        reconcile_interval_s=args.interval,
        priority=args.priority,
        image=args.trainer_image,
    )
    server = serve.connect(args)
    trainer = AnnotationTrainerBridge(
        Client(server), checkpoint_root=args.checkpoint_root or None)
    mgr = build(server, cfg, trainer=trainer,
                leader_election=not args.no_leader_election,
                identity=args.identity)
    serve.run_daemon(mgr, args.health_port, args.health_host)


if __name__ == "__main__":
    main()
