"""nos-tpu-scheduler — the quota- and gang-aware scheduler.

Analog of cmd/scheduler/scheduler.go:43-59 (a kube-scheduler with the
CapacityScheduling plugin registered). The plugin args come from a config
file the way the reference's KubeSchedulerConfiguration carries
CapacitySchedulingArgs (pkg/api/scheduler/types.go:20-27).
"""
from __future__ import annotations

import argparse
from typing import Optional, Sequence

from nos_tpu.api.configs import CapacitySchedulingArgs
from nos_tpu.api.scheduler_config import load_scheduler_config
from nos_tpu.cmd import serve
from nos_tpu.kube.controller import Manager
from nos_tpu.scheduler import Scheduler
from nos_tpu.tpu.resource_calc import ResourceCalculator


def build(server, config: Optional[CapacitySchedulingArgs] = None,
          reclaim_grace_s: float = 0.0) -> Manager:
    cfg = config or CapacitySchedulingArgs()
    calc = ResourceCalculator(
        tpu_memory_gb=cfg.tpu_resource_memory_gb,
        nvidia_gpu_memory_gb=cfg.nvidia_gpu_resource_memory_gb,
    )
    mgr = Manager(server, leader_election=cfg.leader_election_config("scheduler"))
    mgr.add_controller(Scheduler(
        calculator=calc, reclaim_grace_s=reclaim_grace_s).controller())
    return mgr


def main(argv: Optional[Sequence[str]] = None) -> None:
    parser = argparse.ArgumentParser(prog="nos-tpu-scheduler", description=__doc__)
    serve.common_flags(parser)
    parser.add_argument(
        "--reclaim-grace-s", type=float, default=0.0,
        help="gang-eviction grace window: preemption of an over-quota "
             "GANG first stamps a nos.ai/reclaim-notice-deadline "
             "annotation (now + grace) and defers the deletion, giving "
             "a notice-aware controller (nos-tpu-harvest) time to "
             "checkpoint-then-gang-evict; 0 = delete immediately "
             "(the pre-harvest behavior)")
    args = parser.parse_args(argv)

    # accepts both the flat snake_case args file and a full
    # KubeSchedulerConfiguration with versioned pluginConfig args
    # (api/scheduler_config — the reference's conversion/defaulting layer)
    cfg = load_scheduler_config(args.config) if args.config \
        else CapacitySchedulingArgs()
    serve.setup_observability(
        args, args.log_level if args.log_level is not None
        else cfg.log_level)
    mgr = build(serve.connect(args), cfg,
                reclaim_grace_s=args.reclaim_grace_s)
    serve.run_daemon(mgr, args.health_port, args.health_host)


if __name__ == "__main__":
    main()
