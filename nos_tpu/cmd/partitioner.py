"""nos-tpu-partitioner — the dynamic partitioning control plane.

Analog of cmd/gpupartitioner/gpupartitioner.go:72-268: cluster-state
node/pod controllers, the batched planning loop, and the known-generations
override file (the analog of the known-MIG-geometries YAML,
gpupartitioner.go:123-135 + 370-380).
"""
from __future__ import annotations

import argparse
from typing import Optional, Sequence

from nos_tpu.api.configs import PartitionerConfig
from nos_tpu.cmd import serve
from nos_tpu.kube.controller import Manager
from nos_tpu.partitioning import (
    NodeController,
    PartitioningController,
    PodController,
)
from nos_tpu.partitioning.state import ClusterState
from nos_tpu.tpu import topology


def build(server, config: Optional[PartitionerConfig] = None) -> Manager:
    cfg = config or PartitionerConfig()
    if cfg.known_generations_file:
        topology.set_known_generations(
            topology.load_generations_file(cfg.known_generations_file)
        )
    state = ClusterState()
    mgr = Manager(server, leader_election=cfg.leader_election_config("partitioner"))
    mgr.add_controller(NodeController(state).controller())
    mgr.add_controller(PodController(state).controller())
    mgr.add_controller(
        PartitioningController(
            state,
            batch_timeout_s=cfg.batch_window_timeout_seconds,
            batch_idle_s=cfg.batch_window_idle_seconds,
        ).controller()
    )
    return mgr


def main(argv: Optional[Sequence[str]] = None) -> None:
    parser = argparse.ArgumentParser(prog="nos-tpu-partitioner", description=__doc__)
    serve.common_flags(parser)
    args = parser.parse_args(argv)

    cfg = PartitionerConfig.from_yaml_file(args.config) if args.config \
        else PartitionerConfig()
    serve.setup_observability(
        args, args.log_level if args.log_level is not None
        else cfg.log_level)
    mgr = build(serve.connect(args), cfg)
    serve.run_daemon(mgr, args.health_port, args.health_host)


if __name__ == "__main__":
    main()
