#!/usr/bin/env python3
"""Serving-throughput bench: the continuous-batching engine
(models/serving.py) at the flagship shape — sustained decode tokens/s
with all slots busy, request latency at mixed prompt lengths, and the
pipelined-dispatch economics (host-blocked time per token at
pipeline_depth 1 vs >= 2, fused decode_steps).

The interesting comparison is against single-request decode
(bench_decode.py): continuous batching amortizes the per-tick weight
read over max_batch requests, so engine tokens/s should approach
batch-B decode tokens/s while serving independent requests. Timing
fence: results are host-side by construction (the engine syncs token
arrays per arrival). Writes ``bench_logs/bench_serve.json`` FIRST (the
artifact of record — the driver's tail buffer has truncated stdout
before), then prints the same JSON line.
"""
import json
import math
import sys
import time

sys.path.insert(0, ".")

import os  # noqa: E402

from bench import MODEL, smoke_overrides  # noqa: E402

MAX_BATCH = 8
PROMPT_LENS = [64, 128, 256, 96, 64, 192, 128, 80]
NEW_TOKENS = 64
PIPELINE_DEPTHS = [1, 2, 4]
FUSED_STEPS = 4
OUT_PATH = os.path.join("bench_logs", "bench_serve.json")

# bench SLO targets for the goodput column (operators set their own via
# nos-tpu-server --slo-ttft-ms/--slo-tpot-ms; these are generous bounds
# a healthy flagship config should clear, so goodput < 1.0 flags a
# regression rather than grading the hardware)
SLO_TTFT_MS = 1000.0
SLO_TPOT_MS = 100.0

# NOS_TPU_BENCH_SMOKE=1: tiny-shape dry run of the exact code path (see
# bench_decode.py) — hardware runs must never be the first execution
SMOKE = os.environ.get("NOS_TPU_BENCH_SMOKE") == "1"
if SMOKE:
    MODEL = smoke_overrides(MODEL)
    MAX_BATCH, PROMPT_LENS, NEW_TOKENS = 2, [16, 24, 16], 6

# pipelined-dispatch section: all slots busy, decode-bound — the
# workload the in-flight window and fused decode_steps exist for. In
# smoke mode this section uses a MID shape, not smoke_overrides: the
# shared smoke model's per-tick decode compute is below the fetch-sync
# measurement floor (~20us), so depth-1 vs depth-2 host-blocked time
# would compare noise with noise. The mid shape keeps per-tick compute
# comparable to per-tick host work — the regime where pipelining is
# decidable — while still finishing in seconds on CPU.
PIPE_MODEL = MODEL
PIPE_BATCH, PIPE_PROMPT, PIPE_NEW = 8, 128, 48
if SMOKE:
    PIPE_MODEL = dict(MODEL, d_model=256, n_layers=4, n_heads=4,
                      n_kv_heads=2, d_ff=1024, vocab=512)
    PIPE_BATCH, PIPE_PROMPT, PIPE_NEW = 8, 48, 24

# paged-KV section: concurrency at a FIXED KV token budget, slot-static
# vs paged, over a mixed-length trace. The budget is what the static
# engine's slots reserve (static_slots x max_len tokens); the paged
# engine gets the SAME budget as a block pool and more slots — the
# claim under test is that block granularity turns the unreserved tail
# of every short request into admitted concurrency (target >= 1.5x
# sustained active slots on the mixed trace).
KV_BLOCK = 16
PAGED_MAX_LEN = 256
PAGED_STATIC_SLOTS = 4
PAGED_SLOTS = 8
PAGED_TRACE = [(48 + 16 * (i % 8), 32) for i in range(16)]
if SMOKE:
    PAGED_MAX_LEN = 128
    PAGED_STATIC_SLOTS = 2
    PAGED_SLOTS = 6
    PAGED_TRACE = [(16 + 8 * (i % 3), 16) for i in range(8)]

# speculative section: the spec engine over PAGED KV at every unpinned
# (pipeline_depth, decode_steps) — TPOT and tokens-per-dispatch, plus
# the structural dispatch gap (depth >= 2 must not be worse than depth
# 1: that inequality is the acceptance gate this PR un-forfeits). The
# draft is a quarter-ish model sharing the target vocab; acceptance on
# random weights is near zero, which is the CONSERVATIVE case for the
# gate (every window pays full draft cost for ~1 committed token).
SPEC_GRID = [(d, t) for d in (1, 2) for t in (1, 4)]
SPEC_DRAFT_N = 4
SPEC_BATCH, SPEC_PROMPT, SPEC_NEW = 4, 32, 24
SPEC_MAX_LEN = 256
if SMOKE:
    SPEC_BATCH, SPEC_PROMPT, SPEC_NEW = 4, 24, 16
    SPEC_MAX_LEN = 128

# int8-vs-bf16 paged concurrency at the SAME HBM byte budget: the bf16
# pool gets the paged section's token budget in BYTES; the int8 pool
# gets the same bytes, which (per-token scale overhead included) buys
# ~1.8-2x the blocks — the sustained-concurrency ratio is the headline
# (acceptance floor 1.5x). Deterministic: slot counts and admission
# order decide it, not timing.
INT8_TRACE = [(48 + 16 * (i % 8), 32) for i in range(24)]
# SAME slot count for both engines: the block pool must be the binding
# constraint (a slot-capped bf16 rep would flatter the ratio down),
# so the only difference between the reps is bytes-per-token
INT8_SLOTS = 16
if SMOKE:
    INT8_TRACE = [(16 + 8 * (i % 3), 16) for i in range(16)]
    INT8_SLOTS = 12

# multi-tenant section (ISSUE 13): request-level elastic quota on a
# paged engine under a FAKE clock (one unit per engine step), so every
# number in the section is STRUCTURAL — admission order, completions,
# sheds, reclaim preemptions — and reruns are byte-identical. Three
# claims, pinned by the smoke test:
#   isolation: a burst tenant driven at 10x its max cannot push the
#     guaranteed tenant's within-horizon goodput below its no-burst
#     baseline (min-guarantee + preemptive reclaim);
#   borrowing: with the burst tenant idle, an elastic config (max
#     unset) out-delivers the hard-partitioned one (max pinned to min)
#     at the same demand — idle capacity is actually lent;
#   bit-exactness: every completed request — the preempted-for-reclaim
#     ones included — matches its generate() reference token-for-token.
MT_STEPS = 96
MT_SLOTS = 4
MT_MAX_LEN = 96
MT_WINDOW = 16.0            # fake-clock rate window (steps)
MT_GOLD_MIN = 4.0           # tokens/step guaranteed to the gold tenant
MT_BURST_MAX = 2.0          # burst ceiling; driven at ~10x this
MT_GOLD_PERIOD, MT_GOLD_NEW = 4, 8       # gold demand: 2 tokens/step
MT_BURST_NEW = 20                        # burst: 20 tokens/step offered
if SMOKE:
    MT_STEPS = 64


# SLO accounting section (ISSUE 20): the chip-second attribution
# ledger + error-budget engine replayed on a deterministic COST-MODEL
# clock — every quantum's duration is computed from the work it
# carries (SA_MS_PER_TOKEN x tokens moved), never measured, so reruns
# are byte-identical by construction and the structural-share claim is
# checkable as exact integer nanoseconds.
SA_MS_PER_TOKEN = 2.0       # modeled chip cost of moving one token
SA_STEADY_S = 360           # phase A: steady mixed traffic
SA_BURST_S = 60             # phase B: burst floods, gold TTFT degrades
SA_FAST_WINDOW_S = 300.0
SA_SLOW_WINDOW_S = 3600.0
SA_BURN_THRESHOLD = 14.4
SA_GOLD_TTFT_MS = 200.0     # gold's p99 objective; phase B misses it


# tiered KV fabric section (ISSUE 17): one replica under prefix-cache
# pressure on a zipf system-prompt trace — tiered (host-RAM demotion,
# promote-on-hit) vs drop-and-recompute. Every number is STRUCTURAL:
# "prefill chip-seconds" is prefill tokens computed (per-token prefill
# cost is shape-fixed, so the token count IS the chip-time axis) and
# TTFT is the prefill tokens a request pays before its first emitted
# token (prefill runs inside the submit window) — reruns are
# byte-identical by construction. Pressure = an HBM prefix cache of
# KF_CACHE_CHAINS entries under KF_SYS system prompts: every prompt
# switch evicts, so the tiered arm's next hit on a demoted chain
# promotes it back (suffix-only prefill) where the drop arm
# re-prefills the whole system prompt.
KF_SYS = 4                  # distinct system prompts, zipf popularity
KF_SYS_BLOCKS = 4           # KV_BLOCK-sized blocks per system prompt
KF_ZIPF_S = 1.1
KF_SUFFIX = 8               # unique per-request suffix tokens
KF_NEW = 8                  # decode tokens per request
KF_REQUESTS = 24
KF_CACHE_CHAINS = 1         # HBM prefix-cache entries: the pressure
KF_HOST_BYTES = 1 << 22     # host tier big enough to hold every chain
if SMOKE:
    KF_SYS_BLOCKS = 2
    KF_REQUESTS = 16


# disaggregation section (ISSUE 15): colocated vs prefill/decode role
# split at EQUAL chips (two engines either way, each on its own
# thread) under a mixed trace — decode-heavy residents plus a stream
# of long-prompt prefill arrivals. The claims:
#   ttft_wins: arrival TTFT p99 beats colocated — a dedicated prefill
#     engine admits arrivals without queueing their chunks behind
#     decode ticks;
#   tpot_flat: resident decode TPOT stays flat while prefills stream
#     (p99/p50 spikiness strictly below colocated's, whose residents
#     stall for every interleaved prefill chunk);
#   bytes: handoff payload bytes per request, bf16 vs int8 — the int8
#     arena ships the quantized blocks + scales, structurally ~0.5x
#     on a bf16 fleet (exact ratio pinned by dtype arithmetic);
#   conserved: the disaggregated pipeline's tokens == the undisturbed
#     colocated engine's, request for request (rerun byte-identical).
DG_MODEL = MODEL
DG_KV_BLOCK = 16
DG_MAX_LEN = 512
DG_CHUNK = 64
DG_RESIDENT, DG_RES_PROMPT, DG_RES_NEW = 8, 32, 128
DG_ARRIVALS, DG_ARR_PROMPT, DG_ARR_NEW = 8, 384, 4
DG_GAP_S = 0.05
if SMOKE:
    # mid shape, not smoke_overrides: the claim compares prefill-chunk
    # stalls against decode ticks, so both must sit above the
    # measurement floor (same reasoning as the pipelined section).
    # Residents must STAY decoding through the whole arrival window —
    # an idle colocated engine would prefill arrivals undisturbed and
    # the comparison would measure nothing.
    DG_MODEL = dict(MODEL, d_model=256, n_layers=4, n_heads=4,
                    n_kv_heads=2, d_ff=1024, vocab=512)
    DG_MAX_LEN = 256
    DG_CHUNK = 32
    DG_RESIDENT, DG_RES_PROMPT, DG_RES_NEW = 4, 8, 96
    DG_ARRIVALS, DG_ARR_PROMPT, DG_ARR_NEW = 4, 160, 2
    DG_GAP_S = 0.12


# stall-free colocated serving section (ISSUE 19): deadline-slack
# budgeted chunked prefill vs the unconditional chunk-per-tick rule on
# ONE colocated engine, under a FAKE cost-model clock: a pure decode
# tick costs one unit; a tick that also forwards a prefill chunk costs
# 1 + CC_CHUNK_COST (per-chunk forward cost is shape-fixed, so chunk
# count IS the time axis — every number structural, reruns
# byte-identical). Residents decode with TPOT-critical deadlines (one
# tick of headroom below their completion floor, so ANY chunk stall
# breaches); a burst of concurrent long prompts arrives mid-stream
# with staggered TTFT deadlines. The claims the smoke test pins:
#   tpot_flat: the budgeted arm's TPOT-slack clamp defers chunk work
#     while resident slack is negative, so resident TPOT p99 holds
#     the 1.0-unit decode floor; the unbudgeted arm stalls every tick
#     of the burst and p99 blows up to 1 + CC_CHUNK_COST;
#   prefill_within_bound: flatness has a price — budgeted prefill
#     throughput stays within CC_PREFILL_BOUND of unbudgeted (the
#     deferred chunks run after decode drains, they are not dropped);
#   edf_orders_by_slack: prefills complete in deadline-slack order,
#     not submit order (the EDF pick);
#   bit_exact: every served token identical to the unbudgeted run —
#     the budget changes WHEN a chunk runs, never its contents;
#   shed at the earliest layer: with chunk backlog queued, an
#     unmeetable deadline is refused at ADMISSION (the backlog term
#     in the loop's estimate), before the engine sees a single token.
CC_CHUNK = 32
CC_BUDGET = 32              # tokens/tick: up to one chunk when slack allows
CC_CHUNK_COST = 4.0         # one chunk forward ~ 4 decode-tick latencies
CC_RESIDENT, CC_RES_PROMPT, CC_RES_NEW = 4, 8, 48
CC_ARRIVALS, CC_ARR_PROMPT, CC_ARR_NEW = 4, 256, 4
CC_MAX_LEN = 320
CC_WARM_TICKS = 8           # resident decode ticks before the burst
CC_PREFILL_BOUND = 2.5      # budgeted prefill throughput within this
if SMOKE:
    CC_RES_NEW = 32
    CC_ARR_PROMPT = 128
    CC_MAX_LEN = 160


def chunked_colocated_section(params, cfg):
    """The stall-free colocated rep (see the CC_* block): runs the SAME
    code path main() ships, callable directly by the smoke test.
    Every value is structural (clock units are decode ticks + chunk
    forwards), so reruns serialize byte-identically."""
    from nos_tpu.cmd.server import ServingLoop
    from nos_tpu.models.errors import DeadlineUnmeetable
    from nos_tpu.models.serving import DecodeServer

    # arrival deadlines DESCEND with submit order: EDF must advance the
    # last-submitted (tightest) prompt first — the opposite of FIFO
    arr_deadlines = [100.0 * (CC_ARRIVALS - i) for i in range(CC_ARRIVALS)]
    res_prompts = [[(3 * i + j) % (cfg.vocab - 2) + 1
                    for j in range(CC_RES_PROMPT)]
                   for i in range(CC_RESIDENT)]
    arr_prompts = [[(5 * i + 7 * j) % (cfg.vocab - 2) + 1
                    for j in range(CC_ARR_PROMPT)]
                   for i in range(CC_ARRIVALS)]

    def run(budget):
        clock = [0.0]
        eng = DecodeServer(params, cfg,
                           max_batch=CC_RESIDENT + CC_ARRIVALS,
                           max_len=CC_MAX_LEN, prefill_chunk=CC_CHUNK,
                           prefill_budget=budget,
                           slack_clock=lambda: clock[0])
        # pin the cost model to the fake clock: one decode tick == one
        # unit, one chunk forward == CC_CHUNK_COST units — slack math
        # is then exact, not sampled
        eng.tick_s_hint = 1.0
        eng.prefill_tok_s_hint = CC_CHUNK_COST / CC_CHUNK
        chunk_mark = [eng.prefill_chunk_tokens]

        def tick():
            """One engine step; returns the clock at which this tick's
            tokens landed. step_finish samples BEFORE it runs chunk
            forwards, so a chunk's cost delays the NEXT tick's tokens,
            not the ones emitted alongside it."""
            eng.step()
            emit_clock = clock[0] + 1.0
            clock[0] = emit_clock + CC_CHUNK_COST * (
                eng.prefill_chunk_tokens - chunk_mark[0]) / CC_CHUNK
            chunk_mark[0] = eng.prefill_chunk_tokens
            return emit_clock

        # TPOT-critical residents: the scheduler evaluates slack right
        # after a tick's token lands (rem_out already decremented) and
        # right before the tick's cost posts to the clock, where a
        # clean decode holds deadline - clock - rem_out at a constant
        # deadline - CC_RES_NEW + 2. A deadline of CC_RES_NEW - 2.5
        # pins that slack at -0.5 ticks — decode exactly at its TPOT
        # budget with zero headroom, so the budgeted scheduler must
        # never stall it for a chunk
        residents = [eng.submit(p, CC_RES_NEW,
                                deadline_s=CC_RES_NEW - 2.5)
                     for p in res_prompts]
        last_emit = 0.0
        for _ in range(CC_WARM_TICKS):
            last_emit = tick()
        burst_clock = clock[0]
        arrivals = [eng.submit(p, CC_ARR_NEW, deadline_s=arr_deadlines[i])
                    for i, p in enumerate(arr_prompts)]
        tpot, finish_order = [], []
        prefill_done_clock = None
        in_queue = set(arrivals)

        def note_prefill_progress():
            nonlocal prefill_done_clock
            queued = {e["req"].rid for e in eng._prefilling}
            for rid in arrivals:
                if rid in in_queue and rid not in queued:
                    in_queue.discard(rid)
                    finish_order.append(rid)
            if prefill_done_clock is None and not eng._prefilling:
                prefill_done_clock = clock[0]

        while not all(eng.progress(r)[1] for r in residents):
            before = [len(eng.progress(r)[0]) for r in residents]
            emit_clock = tick()
            note_prefill_progress()
            emitted = sum(
                len(eng.progress(r)[0]) - b
                for r, b in zip(residents, before))
            if emitted:
                # every active resident emits each tick: the gap
                # between emission points IS its decode TPOT in clock
                # units (1.0 + whatever the PREVIOUS tick's chunk
                # forwards pushed the dispatch back by)
                tpot.extend([emit_clock - last_emit] * emitted)
            last_emit = emit_clock
        while eng.has_work():
            tick()
            note_prefill_progress()
        results = eng.drain()
        prefill_tokens = sum(len(p) for p in arr_prompts)
        prefill_clock = prefill_done_clock - burst_clock
        return {
            "ticks_to_residents_done": round(clock[0], 3),
            "tpot_p50": round(pct(tpot, 0.50), 3),
            "tpot_p99": round(pct(tpot, 0.99), 3),
            "prefill_clock": round(prefill_clock, 3),
            "prefill_tokens_per_clock": round(
                prefill_tokens / prefill_clock, 3),
            "prefill_finish_order": finish_order,
            "budget_spent_tokens": eng.prefill_budget_spent,
            "clamped_ticks": eng.prefill_budget_clamped,
            "overrides": eng.prefill_budget_overrides,
        }, results

    unb, unb_out = run(0)
    bud, bud_out = run(CC_BUDGET)

    # deadline sheds land at the EARLIEST layer that can know: the
    # ServingLoop's admission estimate now carries the engine's chunk
    # backlog, so an unmeetable deadline is refused before the engine
    # sees the request (zero chip work burned on it)
    class _BacklogStub:
        def __init__(self):
            self.pending, self.done, self.ledgers = {}, {}, {}
            self._rid, self.backlog_s = 0, 0.0

        def submit(self, prompt, n, **kw):
            rid = self._rid
            self._rid += 1
            self.pending[rid] = n
            return rid

        def has_work(self):
            return bool(self.pending)

        def step(self):
            for rid, n in list(self.pending.items()):
                self.done[rid] = list(range(n))
                del self.pending[rid]
                # fixed-latency ledger: seeds the loop's rolling
                # TTFT/TPOT estimates deterministically
                self.ledgers[rid] = {
                    "queue_s": 0.0, "ttft_s": 0.01,
                    "e2e_s": 0.01 + 0.0005 * n,
                    "tpot": [(0.0005 * (n - 1), n - 1)] if n > 1 else [],
                    "output_tokens": n,
                }
            return 1

        def pop_ledger(self, rid):
            return self.ledgers.pop(rid, None)

        def progress(self, rid):
            if rid in self.done:
                return list(self.done[rid]), True
            if rid in self.pending:
                return [], False
            return None

        def pop_result(self, rid):
            return self.done.pop(rid, None)

        def prefill_backlog_s(self):
            return self.backlog_s

    stub = _BacklogStub()
    loop = ServingLoop(stub)
    try:
        loop.generate([1], 4, timeout=30)   # seed the EWMA estimates
        submits_before_shed = stub._rid
        stub.backlog_s = 60.0               # a minute of queued chunks
        shed_msg = None
        try:
            loop.generate([2], 3, timeout=30, deadline_s=1.0)
        except DeadlineUnmeetable as e:
            shed_msg = str(e)
        shed = {
            "layer": "admission",
            "sheds": loop.stats()["deadline"]["shed"],
            "mentions_backlog": bool(
                shed_msg and "prefill queued ahead" in shed_msg),
            # the engine never saw the shed request: zero tokens burned
            "engine_submits_during_shed":
                stub._rid - submits_before_shed,
        }
    finally:
        loop.shutdown()

    # the budgeted arm's prefills must finish tightest-deadline first:
    # arrivals were submitted loosest-first, so slack order is exactly
    # REVERSED submit (= rid) order
    bud_edf = bud["prefill_finish_order"] == sorted(
        bud["prefill_finish_order"], reverse=True)
    throughput_ratio = round(
        unb["prefill_tokens_per_clock"]
        / bud["prefill_tokens_per_clock"], 3)
    return {
        "chunk": CC_CHUNK,
        "budget": CC_BUDGET,
        "residents": CC_RESIDENT,
        "arrivals": CC_ARRIVALS,
        "arrival_prompt_tokens": CC_ARR_PROMPT,
        "unbudgeted": unb,
        "budgeted": bud,
        # headline: the TPOT-slack clamp defers chunk work while the
        # TPOT-critical residents decode, so their p99 holds the pure
        # decode floor; the unbudgeted arm stalls every burst tick
        "tpot_flat": bud["tpot_p99"] <= 1.0,
        "tpot_blowup_ratio": round(
            unb["tpot_p99"] / bud["tpot_p99"], 3),
        "prefill_throughput_ratio": throughput_ratio,
        "prefill_bound": CC_PREFILL_BOUND,
        "prefill_within_bound": throughput_ratio <= CC_PREFILL_BOUND,
        "edf_orders_by_slack": bud_edf,
        "bit_exact": unb_out == bud_out,
        "shed": shed,
    }


def _dg_blocks(n_requests, prompt, new):
    per = -(-(prompt + new) // DG_KV_BLOCK) + 1
    return n_requests * per


def pct(xs, q):
    """Nearest-rank percentile — THE one implementation (the disagg
    section and the per-request pipeline stats must never diverge)."""
    xs = sorted(xs)
    return xs[min(len(xs) - 1, max(0, math.ceil(q * len(xs)) - 1))]


def _dg_timed_arm(arm, params, cfg):
    """One timed arm: 'colocated' (two full engines, trace split) or
    'disagg' (one prefill-role + one decode-role engine). Each engine
    ticks on its own thread (the equal-chips model: two pods run
    concurrently); the driver submits residents at t0 and spaces the
    prefill arrivals DG_GAP_S apart. Returns arrival TTFTs, resident
    per-token TPOT samples, outputs keyed by logical request id, and
    the handoff accounting."""
    import threading

    from nos_tpu.models.handoff import decode_handoff, encode_handoff
    from nos_tpu.models.serving import DecodeServer

    import numpy as np

    host_rng = np.random.default_rng(31)
    residents = [[int(x) for x in host_rng.integers(1, cfg.vocab,
                                                    DG_RES_PROMPT)]
                 for _ in range(DG_RESIDENT)]
    arrivals = [[int(x) for x in host_rng.integers(1, cfg.vocab,
                                                   DG_ARR_PROMPT)]
                for _ in range(DG_ARRIVALS)]
    total = DG_RESIDENT + DG_ARRIVALS
    blocks = _dg_blocks(DG_RESIDENT, DG_RES_PROMPT, DG_RES_NEW) \
        + _dg_blocks(DG_ARRIVALS, DG_ARR_PROMPT, DG_ARR_NEW) + 4
    kv = dict(max_len=DG_MAX_LEN, kv_block_size=DG_KV_BLOCK,
              kv_blocks=blocks)

    locks: dict = {}
    rid_of: dict = {}       # (engine id, engine rid) -> logical id
    ledgers: dict = {}      # logical id -> ledger
    outputs: dict = {}      # logical id -> tokens
    stop = threading.Event()

    def ticker(eng):
        lock = locks[id(eng)]
        while not stop.is_set():
            with lock:
                if eng.has_work():
                    eng.step()
                    busy = True
                else:
                    busy = False
                for led in eng.drain_ledgers():
                    lid = rid_of.get((id(eng), led["rid"]))
                    if lid is not None:
                        # a logical request may own TWO ledgers in the
                        # disagg arm: the prefill side's (stamps TTFT)
                        # and the decode side's (stamps TPOT)
                        ledgers.setdefault(lid, []).append(led)
                for rid_ in list(getattr(eng, "_done", {})):
                    lid = rid_of.get((id(eng), rid_))
                    if lid is not None:
                        outputs[lid] = eng.pop_result(rid_)
            if not busy:
                time.sleep(0.002)

    # EVERY engine in both arms shares one max_batch: the decode
    # program's compiled [B, 1] shape must match across arms, or XLA
    # may pick per-shape reduction strategies whose ULP differences
    # flip near-tie argmax on this random-weight model — the engines'
    # batch-composition invariance (and the conservation pin below)
    # is a same-compiled-shape contract
    if arm == "colocated":
        engines = [DecodeServer(params, cfg, max_batch=total,
                                prefill_chunk=DG_CHUNK, **kv)
                   for _ in range(2)]
        pre_targets = engines          # arrivals round-robin both
        movers = []
    else:
        pre = DecodeServer(params, cfg, role="prefill", max_batch=total,
                           prefill_chunk=DG_CHUNK, **kv)
        dec = DecodeServer(params, cfg, role="decode", max_batch=total,
                           **kv)
        engines = [pre, dec]
        pre_targets = [pre]

        def mover():
            # the serving loop's pusher, in-process: encoded payloads
            # adopt into the decode engine through the wire format
            while not stop.is_set():
                with locks[id(pre)]:
                    states = pre.pop_handoffs()
                for st in states:
                    data = encode_handoff(st)
                    with locks[id(dec)]:
                        drid = dec.restore(decode_handoff(data))
                        rid_of[(id(dec), drid)] = \
                            rid_of[(id(pre), st["rid"])]
                if not states:
                    time.sleep(0.002)

        movers = [threading.Thread(target=mover, daemon=True)]
    for eng in engines:
        locks[id(eng)] = threading.Lock()

    def submit(lid, eng, prompt, n):
        with locks[id(eng)]:
            rid = eng.submit(prompt, n)
            rid_of[(id(eng), rid)] = lid

    # warm EVERY compiled shape the trace hits (resident bucket,
    # arrival chunk shapes, decode programs, handoff restore blocks):
    # engines carry per-instance jit wrappers, so each rep would
    # otherwise charge its first arrival's TTFT with XLA compiles
    for p, n in ((residents[0], 2), (arrivals[0], 2)):
        if arm == "colocated":
            for eng in engines:
                eng.submit(p, n)
                eng.drain()
        else:
            pre.submit(p, n)
            while pre.has_work():
                pre.step()
            for st in pre.pop_handoffs():
                dec.restore(decode_handoff(encode_handoff(st)))
            dec.drain()
    for eng in engines:
        eng.drain_ledgers()
    if arm == "disagg":
        pre.handoffs = 0
        pre.handoff_payload_bytes = 0
        pre.handoff_capture_s = 0.0

    threads = [threading.Thread(target=ticker, args=(e,), daemon=True)
               for e in engines] + movers
    t0 = time.perf_counter()
    for i, p in enumerate(residents):
        submit(("res", i), pre_targets[i % len(pre_targets)], p,
               DG_RES_NEW)
    for t in threads:
        t.start()
    for i, p in enumerate(arrivals):
        time.sleep(DG_GAP_S)
        submit(("arr", i), pre_targets[i % len(pre_targets)], p,
               DG_ARR_NEW)
    deadline = time.monotonic() + 600
    while len(outputs) < total and time.monotonic() < deadline:
        time.sleep(0.01)
    stop.set()
    for t in threads:
        t.join(timeout=10)
    wall_s = time.perf_counter() - t0
    assert len(outputs) == total, \
        f"{arm}: {len(outputs)}/{total} completed"

    ttfts = [next(led["ttft_s"] for led in ledgers[("arr", i)]
                  if led.get("ttft_s") is not None) * 1e3
             for i in range(DG_ARRIVALS)]
    tpot = []
    for i in range(DG_RESIDENT):
        for led in ledgers[("res", i)]:
            for gap, n in led.get("tpot") or ():
                tpot.extend([gap / n * 1e3] * n)
    handoff = None
    if arm == "disagg":
        pre = engines[0]
        handoff = {
            "requests": pre.handoffs,
            "payload_bytes": pre.handoff_payload_bytes,
            "bytes_per_request": round(
                pre.handoff_payload_bytes / max(pre.handoffs, 1)),
            "capture_s": round(pre.handoff_capture_s, 4),
        }
    return {
        "wall_s": round(wall_s, 3),
        "completed": len(outputs),
        "arrival_ttft_ms": {
            "p50": round(pct(ttfts, 0.5), 3),
            "p99": round(pct(ttfts, 0.99), 3),
        },
        "resident_tpot_ms": {
            "samples": len(tpot),
            "p50": round(pct(tpot, 0.5), 3),
            "p99": round(pct(tpot, 0.99), 3),
        },
        "handoff": handoff,
    }, outputs


def _dg_structural(params, cfg):
    """The deterministic half of the section (tiny shared model, no
    threads, no clocks): disagg conserves every token vs the
    undisturbed colocated engine, and the handoff byte model per
    kv_dtype. Computed twice by the section; byte-identical reruns are
    the pin."""
    from nos_tpu.models.handoff import (
        decode_handoff, encode_handoff, handoff_nbytes,
    )
    from nos_tpu.models.serving import DecodeServer

    import numpy as np

    host_rng = np.random.default_rng(13)
    reqs = [([int(x) for x in host_rng.integers(1, cfg.vocab,
                                                8 + 4 * (i % 3))],
             6 + 2 * (i % 2)) for i in range(4)]
    kv = dict(max_batch=4, max_len=128, kv_block_size=16, kv_blocks=32)
    out = {}
    for kv_dtype in ("bf16", "int8"):
        co = DecodeServer(params, cfg, kv_dtype=kv_dtype, **kv)
        rids = [co.submit(p, n) for p, n in reqs]
        ref = co.drain()
        want = [ref[r] for r in rids]
        pre = DecodeServer(params, cfg, role="prefill",
                           kv_dtype=kv_dtype, **kv)
        dec = DecodeServer(params, cfg, role="decode",
                           kv_dtype=kv_dtype, **kv)
        for p, n in reqs:
            pre.submit(p, n)
        while pre.has_work():
            pre.step()
        states = pre.pop_handoffs()
        payload = [handoff_nbytes(st) for st in states]
        drids = [dec.restore(decode_handoff(encode_handoff(st)))
                 for st in states]
        got = dec.drain()
        out[kv_dtype] = {
            "conserved": [got[r] for r in drids] == want,
            "handoffs": len(states),
            "payload_bytes": sum(payload),
        }
    out["int8_vs_bf16_bytes"] = round(
        out["int8"]["payload_bytes"] / out["bf16"]["payload_bytes"], 4)
    return out


def disagg_section(params, cfg):
    """Colocated vs disaggregated at equal chips (see the DG_* block).
    ``params``/``cfg`` are the tiny shared model for the structural
    half; the timed arms build DG_MODEL (a mid shape in smoke runs,
    the flagship otherwise)."""
    import jax

    from nos_tpu.models import transformer as tr

    structural = _dg_structural(params, cfg)
    rerun = _dg_structural(params, cfg)
    dg_cfg = tr.TransformerConfig(**DG_MODEL)
    dg_params = params if DG_MODEL == MODEL \
        else tr.init_params(jax.random.PRNGKey(5), dg_cfg)
    # two reps per arm: the first pays the XLA compiles (prefill
    # buckets, chunk shapes, both decode programs), best-of-two taken
    # so a compile or GC pause cannot flip the gate
    colo, colo_out = _dg_timed_arm("colocated", dg_params, dg_cfg)
    disagg, disagg_out = _dg_timed_arm("disagg", dg_params, dg_cfg)
    colo2, _ = _dg_timed_arm("colocated", dg_params, dg_cfg)
    disagg2, _ = _dg_timed_arm("disagg", dg_params, dg_cfg)

    def best(a, b):
        # per-metric best of two (a GC pause or stray compile in one
        # rep must not flip a gate the other rep answers cleanly)
        out = dict(a)
        out["arrival_ttft_ms"] = min(
            (a["arrival_ttft_ms"], b["arrival_ttft_ms"]),
            key=lambda m: m["p99"])
        out["resident_tpot_ms"] = min(
            (a["resident_tpot_ms"], b["resident_tpot_ms"]),
            key=lambda m: m["p99"])
        out["wall_s"] = min(a["wall_s"], b["wall_s"])
        return out

    colo = best(colo, colo2)
    disagg = best(disagg, disagg2)
    return {
        "model": {k: DG_MODEL[k] for k in ("d_model", "n_layers")},
        "chips_per_arm": 2,
        "trace": {
            "residents": DG_RESIDENT,
            "resident_new_tokens": DG_RES_NEW,
            "arrivals": DG_ARRIVALS,
            "arrival_prompt_tokens": DG_ARR_PROMPT,
            "arrival_gap_s": DG_GAP_S,
            "prefill_chunk": DG_CHUNK,
        },
        "colocated": colo,
        "disagg": disagg,
        # timed-arm conservation: both arms produced identical tokens
        # for every logical request (batch-composition invariance
        # carried across the role split)
        "timed_conserved": colo_out == disagg_out,
        "ttft_p99_speedup": round(
            colo["arrival_ttft_ms"]["p99"]
            / max(disagg["arrival_ttft_ms"]["p99"], 1e-9), 3),
        "ttft_wins": disagg["arrival_ttft_ms"]["p99"]
        < colo["arrival_ttft_ms"]["p99"],
        # flatness: the decode plane's TPOT while prefills stream —
        # the colocated residents stall for every interleaved prefill
        # chunk (median AND tail), the dedicated decode engine does not
        "tpot_flat": (disagg["resident_tpot_ms"]["p99"]
                      <= colo["resident_tpot_ms"]["p99"]
                      and disagg["resident_tpot_ms"]["p50"]
                      < colo["resident_tpot_ms"]["p50"]),
        "structural": structural,
        "rerun_identical": structural == rerun,
    }


def multi_tenant_section(params, cfg):
    """The multi-tenant rep (see the MT_* block): runs the SAME code
    path main() ships, callable directly by the smoke test so the
    byte-identical-rerun pin doesn't pay for the whole bench twice.
    Returns a JSON-safe dict with no wall-clock fields."""
    import jax.numpy as jnp
    import numpy as np

    from nos_tpu.models.errors import QueueFull
    from nos_tpu.models.generate import generate
    from nos_tpu.models.serving import DecodeServer
    from nos_tpu.models.tenantquota import TenantQuotaConfig, TenantSpec

    bs = KV_BLOCK
    # pool sized so MT_SLOTS full-length requests fit: the preemptions
    # the section reports are then QUOTA reclaims, not block-pressure
    # relief muddying the story
    per_req = -(-(16 + MT_BURST_NEW + MT_GOLD_NEW) // bs) + 1
    mt_blocks = MT_SLOTS * per_req + 2
    host_rng = np.random.default_rng(23)
    # a small closed set of prompts -> a small closed set of generate()
    # references to verify every completion against
    gold_prompts = [[int(x) for x in host_rng.integers(1, cfg.vocab, 12)]
                    for _ in range(3)]
    burst_prompts = [[int(x) for x in host_rng.integers(1, cfg.vocab, 16)]
                     for _ in range(4)]

    # undisturbed-run references, shared across reps: a handful of
    # (prompt, n) pairs by construction — the closed prompt set above
    ref_cache = {}

    def quota(gold_max, gold_min=MT_GOLD_MIN):
        return TenantQuotaConfig(
            tenants={
                "gold": TenantSpec("gold", min_rate=gold_min,
                                   max_rate=gold_max),
                "burst": TenantSpec("burst", min_rate=0.0,
                                    max_rate=MT_BURST_MAX),
            }, window_s=MT_WINDOW)

    def run(tq, gold_period, with_burst, slots=MT_SLOTS):
        clock = [0.0]
        eng = DecodeServer(params, cfg, max_batch=slots,
                           max_len=MT_MAX_LEN, kv_block_size=bs,
                           kv_blocks=mt_blocks, tenant_quota=tq,
                           tenant_clock=lambda: clock[0])
        sheds = {}
        outputs = {}            # rid -> (tenant, prompt tuple, n)
        done = []               # ledgers completed WITHIN the horizon
        gi = bi = 0
        for t in range(MT_STEPS):
            clock[0] = float(t)
            if t % gold_period == 0:
                p = gold_prompts[gi % len(gold_prompts)]
                gi += 1
                try:
                    rid = eng.submit(p, MT_GOLD_NEW, tenant="gold")
                    outputs[rid] = ("gold", tuple(p), MT_GOLD_NEW)
                except QueueFull as e:
                    sheds[("gold", e.reason)] = \
                        sheds.get(("gold", e.reason), 0) + 1
            if with_burst:
                p = burst_prompts[bi % len(burst_prompts)]
                bi += 1
                try:
                    rid = eng.submit(p, MT_BURST_NEW, tenant="burst")
                    outputs[rid] = ("burst", tuple(p), MT_BURST_NEW)
                except QueueFull as e:
                    sheds[("burst", e.reason)] = \
                        sheds.get(("burst", e.reason), 0) + 1
            if eng.has_work():
                eng.step()
            done.extend(eng.drain_ledgers())
        # horizon closed: goodput is judged on the WITHIN-horizon
        # ledgers only — the tail below drains so bit-exactness covers
        # EVERY admitted request (preempted ones included), but its
        # completions must not flatter a tenant's in-horizon delivery
        horizon_tokens = {}
        horizon_done = {}
        for led in done:
            t_ = led["tenant"]
            horizon_tokens[t_] = horizon_tokens.get(t_, 0) \
                + led["output_tokens"]
            horizon_done[t_] = horizon_done.get(t_, 0) + 1
        while eng.has_work():
            clock[0] += 1.0
            eng.step()
        results = eng.drain()
        eng.drain_ledgers()
        exact = 0
        for rid, (tenant, prompt, n) in outputs.items():
            if rid not in results:
                continue
            if (prompt, n) not in ref_cache:
                ref_cache[(prompt, n)] = [int(x) for x in generate(
                    params, cfg,
                    jnp.asarray([list(prompt)], jnp.int32), n)[0]]
            want = ref_cache[(prompt, n)]
            assert results[rid] == want, (
                f"rid {rid} ({tenant}) diverged from its undisturbed "
                f"generate() run — preempt/resume broke bit-exactness")
            exact += 1
        kv = eng.kv_stats()
        return {
            "submitted": len(outputs),
            "completed": len(results),
            "horizon_tokens": dict(sorted(horizon_tokens.items())),
            "horizon_completions": dict(sorted(horizon_done.items())),
            "sheds": {f"{t_}/{r}": c
                      for (t_, r), c in sorted(sheds.items())},
            "preempts": kv["preempts"],
            "quota_reclaims": kv["tenant_reclaims"],
            "bit_exact_verified": exact,
        }

    base = run(quota(0.0), MT_GOLD_PERIOD, with_burst=False)
    burst = run(quota(0.0), MT_GOLD_PERIOD, with_burst=True)
    # borrowing: gold demands ~8 tokens/step with the burst tenant
    # IDLE. The hard-partitioned configuration is what a fleet without
    # elastic quota deploys — each tenant statically owns half the
    # slots, so gold runs on MT_SLOTS/2 while burst's half sits idle.
    # The elastic configuration shares all MT_SLOTS under the quota:
    # work conservation lends burst's idle capacity to gold, and the
    # SAME quota reclaims it the moment burst returns (the with_burst
    # rep above). Same chips, same trace — more tokens.
    hard = run(quota(0.0), 1, with_burst=False, slots=MT_SLOTS // 2)
    elastic = run(quota(0.0), 1, with_burst=False)
    gold_base = base["horizon_tokens"].get("gold", 0)
    gold_burst = burst["horizon_tokens"].get("gold", 0)
    return {
        "steps": MT_STEPS,
        "slots": MT_SLOTS,
        "window_steps": MT_WINDOW,
        "gold": {"min_rate": MT_GOLD_MIN,
                 "demand_tokens_per_step":
                     round(MT_GOLD_NEW / MT_GOLD_PERIOD, 3)},
        "burst": {"max_rate": MT_BURST_MAX,
                  "demand_tokens_per_step": MT_BURST_NEW,
                  "overdrive": round(MT_BURST_NEW / MT_BURST_MAX, 1)},
        "baseline": base,
        "with_burst": burst,
        "hard_partition": dict(hard, slots=MT_SLOTS // 2),
        "elastic": dict(elastic, slots=MT_SLOTS),
        # the three headline claims (booleans the smoke test pins)
        "isolation_holds": gold_burst >= gold_base,
        "reclaim_exercised": burst["quota_reclaims"] > 0
        and burst["bit_exact_verified"] == burst["completed"],
        "borrow_wins": sum(elastic["horizon_tokens"].values())
        > sum(hard["horizon_tokens"].values()),
    }


def slo_accounting_section():
    """The SLO accounting rep (see the SA_* block): replays a two-phase
    tenant trace through the REAL ChipLedger + SloBudgetEngine on the
    cost-model clock. Phase A is steady mixed traffic inside every
    objective; in phase B the burst tenant floods the replica and
    gold's TTFT degrades past its p99 target, so the fast window's
    burn rate crosses the trip threshold exactly once (the capture
    interval rate-limits the rest of the sustained breach). jax-free
    and measurement-free: callable directly by the NON-slow smoke test
    that pins byte-identical reruns and the structural-share claim."""
    from nos_tpu.models.tenantquota import (
        TenantQuotaConfig, TenantSloSpec, TenantSpec,
    )
    from nos_tpu.obs.slo import (
        IDLE_TENANT, ChipLedger, SloBudgetEngine, objectives_from_quota,
    )

    quota = TenantQuotaConfig(
        tenants={
            "gold": TenantSpec("gold", min_rate=MT_GOLD_MIN,
                               slo=TenantSloSpec(
                                   ttft_p99_ms=SA_GOLD_TTFT_MS,
                                   goodput_floor=0.95)),
            "burst": TenantSpec("burst", max_rate=MT_BURST_MAX),
        }, window_s=MT_WINDOW)
    led = ChipLedger()
    eng = SloBudgetEngine(
        objectives_from_quota(quota),
        fast_window_s=SA_FAST_WINDOW_S, slow_window_s=SA_SLOW_WINDOW_S,
        burn_threshold=SA_BURN_THRESHOLD)
    tokens = {}                 # (tenant, phase) -> structural total
    trip_at = []
    for sec in range(SA_STEADY_S + SA_BURST_S):
        t0 = float(sec)
        if sec < SA_STEADY_S:
            work = {("gold", "decode"): 3, ("burst", "decode"): 1}
            if sec % 10 == 0:   # a fresh gold admission
                work[("gold", "prefill")] = 12
        else:
            work = {("gold", "decode"): 1, ("burst", "decode"): 6,
                    ("burst", "prefill"): 16}
        for k, n in work.items():
            tokens[k] = tokens.get(k, 0) + n
        # quantum duration IS the modeled cost of its work; the rest
        # of each one-second tick accrues to the explicit idle tenant
        dur_s = sum(work.values()) * SA_MS_PER_TOKEN / 1e3
        led.note_quantum(t0, t0 + dur_s, work,
                         {"gold": 64 * 1024, "burst": 32 * 1024})
        # terminal verdicts: one gold completion every 10 s in phase A
        # (inside every objective), one per second in phase B with its
        # TTFT pushed past the target by the flood
        if sec < SA_STEADY_S and sec % 10 == 9:
            eng.note("gold", "ttft_p99", False, t0)
            eng.note("gold", "goodput", False, t0)
        elif sec >= SA_STEADY_S:
            if eng.note("gold", "ttft_p99", True, t0):
                trip_at.append(sec)
            eng.note("gold", "goodput", False, t0)
    horizon = float(SA_STEADY_S + SA_BURST_S)
    snap = led.snapshot()
    totals = led.totals_ns()
    # the structural-share claim, exact: each quantum's duration is
    # tokens x SA_MS_PER_TOKEN and the split is token-weighted, so
    # every (tenant, phase) charge must equal its OWN token count x
    # SA_MS_PER_TOKEN in integer nanoseconds
    per_tok_ns = int(SA_MS_PER_TOKEN * 1e6)
    structural = all(
        totals.get(k, 0) == n * per_tok_ns for k, n in tokens.items())
    return {
        "ms_per_token": SA_MS_PER_TOKEN,
        "steady_s": SA_STEADY_S,
        "burst_s": SA_BURST_S,
        "fast_window_s": SA_FAST_WINDOW_S,
        "burn_threshold": SA_BURN_THRESHOLD,
        "chip_ms": snap["chip_ms"],
        "idle_ms": snap["chip_ms"][IDLE_TENANT]["idle"],
        "kv_byte_seconds": snap["kv_byte_seconds"],
        "slo": eng.snapshot(horizon)["objectives"],
        "trip_at_s": trip_at,
        # the three headline claims (booleans the smoke test pins)
        "attribution_conserved": snap["conserved"],
        "attribution_structural": structural,
        "burst_trips_fast_window_once": len(trip_at) == 1
        and trip_at[0] >= SA_STEADY_S,
    }


def kv_fabric_section(params, cfg):
    """The tiered KV fabric rep (see the KF_* block): runs the SAME
    code path main() ships, callable directly by the smoke test.
    Returns a JSON-safe dict with no wall-clock fields — two fresh
    runs serialize byte-identically."""
    import numpy as np

    from nos_tpu.kvfabric import HostTierStore
    from nos_tpu.models.serving import DecodeServer

    bs = KV_BLOCK
    sys_len = KF_SYS_BLOCKS * bs
    max_len = -(-(sys_len + KF_SUFFIX + KF_NEW + 8) // bs) * bs
    host_rng = np.random.default_rng(17)
    sys_prompts = [[int(x) for x in host_rng.integers(1, cfg.vocab, sys_len)]
                   for _ in range(KF_SYS)]
    # zipf popularity over the system prompts, then a unique suffix per
    # request — the shared-system-prompt serving shape the prefix cache
    # exists for
    w = np.array([1.0 / (r + 1) ** KF_ZIPF_S for r in range(KF_SYS)])
    picks = host_rng.choice(KF_SYS, size=KF_REQUESTS, p=w / w.sum())
    trace = [(int(s),
              [int(x) for x in host_rng.integers(1, cfg.vocab, KF_SUFFIX)])
             for s in picks]
    per_req = -(-(sys_len + KF_SUFFIX + KF_NEW) // bs) + 1

    def run(tiered, cache_chains, blocks):
        host = HostTierStore(KF_HOST_BYTES) if tiered else None
        eng = DecodeServer(params, cfg, max_batch=2, max_len=max_len,
                           kv_block_size=bs, kv_blocks=blocks,
                           kv_dtype="int8",
                           prefix_cache_size=cache_chains,
                           host_tier=host)
        # warm phase: publish every system prompt's chain once, OUTSIDE
        # the measured trace (both arms pay the same cold prefills; the
        # measured difference is then purely what each arm does with an
        # evicted chain — demote-and-promote vs drop-and-recompute)
        for sp in sys_prompts:
            eng.submit(sp + [1], 2, cache_prefix=True)
            while eng.has_work():
                eng.step()
            eng.drain()
        ttft, outputs = [], []
        for si, suffix in trace:
            prompt = sys_prompts[si] + suffix
            saved0 = eng.prefix_tokens_saved
            eng.submit(prompt, KF_NEW, cache_prefix=True)
            while eng.has_work():
                eng.step()
            got = eng.drain()
            outputs.append(next(iter(got.values())))
            ttft.append(len(prompt) - (eng.prefix_tokens_saved - saved0))
        snap = eng.prefix_index_snapshot()
        return {
            "prefill_tokens": sum(ttft),
            "ttft_prefill_tokens": {"p50": pct(ttft, 0.50),
                                    "p99": pct(ttft, 0.99)},
            "prefix_hits": eng.prefix_hits,
            "evicted": snap["evicted"],
            "fabric": snap["fabric"],
            "host_tier": (None if snap["host_tier"] is None
                          else {k: snap["host_tier"][k]
                                for k in ("chains", "bytes")}),
        }, outputs

    # pressure arms share the pool and the 1-chain cache; the
    # no-pressure oracle gets a cache and pool big enough that nothing
    # is ever evicted — its outputs are the bit-exactness reference
    pool = 4 * per_req
    tiered, tiered_out = run(True, KF_CACHE_CHAINS, pool)
    drop, drop_out = run(False, KF_CACHE_CHAINS, pool)
    relief, _ = run(True, KF_CACHE_CHAINS, pool)  # rerun determinism
    assert relief == tiered
    big_pool = KF_SYS * (KF_SYS_BLOCKS + 1) + KF_REQUESTS * 2 + 4 * per_req
    nopress, nopress_out = run(False, KF_SYS + KF_REQUESTS, big_pool)
    return {
        "kv": "paged-int8",
        "trace": {"requests": KF_REQUESTS, "system_prompts": KF_SYS,
                  "system_prompt_tokens": sys_len, "zipf_s": KF_ZIPF_S,
                  "suffix_tokens": KF_SUFFIX, "new_tokens": KF_NEW,
                  "prefix_cache_chains": KF_CACHE_CHAINS},
        "tiered": tiered,
        "drop": drop,
        "no_pressure": {"prefill_tokens": nopress["prefill_tokens"],
                        "prefix_hits": nopress["prefix_hits"]},
        # the acceptance headlines (booleans the smoke test pins):
        # pressure + tiering must beat pressure + drop on BOTH latency
        # percentiles AND total prefill chip-work, with every served
        # token bit-identical to the undisturbed no-pressure run
        "ttft_wins": (
            tiered["ttft_prefill_tokens"]["p50"]
            < drop["ttft_prefill_tokens"]["p50"]
            and tiered["ttft_prefill_tokens"]["p99"]
            < drop["ttft_prefill_tokens"]["p99"]),
        "prefill_chip_ratio": round(
            drop["prefill_tokens"] / max(tiered["prefill_tokens"], 1), 3),
        "bit_exact_vs_no_pressure": tiered_out == nopress_out,
        # the drop arm is NOT held to bit-exactness — re-prefilling an
        # evicted chain recomputes the suffix over the pre-quantization
        # activations, where a hit (promoted or resident) reads the
        # dequantized int8 blocks; under int8 KV the recompute path can
        # drift by a token. Reported, not gated: it is the strongest
        # argument FOR tiering (demote/promote moves the exact bytes,
        # so pressure never changes a served token)
        "drop_bit_exact_vs_no_pressure": drop_out == nopress_out,
    }


def main():
    import jax

    from nos_tpu.models import transformer as tr
    from nos_tpu.models.serving import DecodeServer

    import numpy as np

    cfg = tr.TransformerConfig(**MODEL)
    params = tr.init_params(jax.random.PRNGKey(0), cfg)
    # cache sized to the workload (matching bench_decode's economics:
    # per-tick attention cost scales with cache length)
    max_len = max(PROMPT_LENS) + NEW_TOKENS + 8
    srv = DecodeServer(params, cfg, max_batch=MAX_BATCH, max_len=max_len)

    # host-side prompts built OUTSIDE every timed window
    host_rng = np.random.default_rng(1)
    prompts = [[int(x) for x in host_rng.integers(0, cfg.vocab, size=plen)]
               for plen in PROMPT_LENS]

    # warm: compile EVERY prefill bucket this workload uses + the decode
    # program, so the timed windows measure execution, not XLA
    for plen in sorted({len(p) for p in prompts}):
        srv.submit([1] * plen, 2)
    srv.drain()

    t0 = time.perf_counter()
    for toks in prompts:
        srv.submit(toks, NEW_TOKENS)
    t_submit = time.perf_counter() - t0

    t0 = time.perf_counter()
    results = srv.drain()
    t_decode = time.perf_counter() - t0

    # prefix-cache rep: the system-prompt pattern — every request shares
    # a common head (half the shortest prompt), published once. Measures
    # admission (prefill) wall-clock against the uncached rep above; the
    # decode phase is unaffected by construction.
    sys_len = min(PROMPT_LENS) // 2
    system = [int(x) for x in host_rng.integers(0, cfg.vocab, size=sys_len)]
    shared = [system + p[sys_len:] for p in prompts]
    srv_pc = DecodeServer(params, cfg, max_batch=MAX_BATCH, max_len=max_len,
                          prefix_cache_size=2)
    srv_pc.submit(system + [2], 1, cache_prefix=True)  # publish (+ compile)
    srv_pc.drain()
    # warm the PREFIX-path shapes: suffix buckets and scratch lengths
    # differ from full-prefill buckets, so warming with uncached prompts
    # would leave every timed admit paying an XLA compile
    for toks in shared:
        srv_pc.submit(toks, 2)
    srv_pc.drain()
    srv_pc.prefix_hits = 0
    srv_pc.prefix_tokens_saved = 0
    t0 = time.perf_counter()
    for toks in shared:
        srv_pc.submit(toks, NEW_TOKENS)
    t_submit_pc = time.perf_counter() - t0
    srv_pc.drain()

    # ------------------------------------------------------------------
    # pipelined dispatch economics: an all-slots-busy decode-bound
    # workload at each pipeline depth, reading the engine's own
    # accounting. The headline is dispatch_gap_s — wall time the engine
    # had NO decode tick in flight while decodable slots existed, i.e.
    # the accelerator host-blocked behind bookkeeping. At depth 1 every
    # tick pays the consume->redispatch gap; at depth >= 2 the window
    # only empties at barriers, so the gap drops by construction (the
    # structural claim, robust to machine noise). host_block_s
    # (dispatch calls + token fetches) is reported alongside as
    # sync_path_s. Two reps per depth, best taken, so a GC pause can't
    # flip the comparison the acceptance gate reads.
    pipe_cfg = tr.TransformerConfig(**PIPE_MODEL)
    pipe_params = params if PIPE_MODEL == MODEL \
        else tr.init_params(jax.random.PRNGKey(2), pipe_cfg)
    pipe_prompts = [
        [int(x) for x in host_rng.integers(0, pipe_cfg.vocab, PIPE_PROMPT)]
        for _ in range(PIPE_BATCH)]
    pipe_max_len = PIPE_PROMPT + PIPE_NEW + 8

    def per_request_stats(ledgers):
        """TTFT/TPOT/e2e percentiles + goodput from the engine's
        latency ledgers — the user-experienced view of one rep (the
        submit loop above is effectively instantaneous next to decode,
        so queueing is part of the story the percentiles tell)."""
        ttft = [led["ttft_s"] * 1e3 for led in ledgers
                if led.get("ttft_s") is not None]
        tpot = []
        good = 0
        for led in ledgers:
            gaps = led.get("tpot") or ()
            n = sum(k for _, k in gaps)
            mean_ms = (sum(g for g, _ in gaps) / n) * 1e3 if n else 0.0
            if n:
                tpot.append(mean_ms)
            ok_ttft = led.get("ttft_s") is not None \
                and led["ttft_s"] * 1e3 <= SLO_TTFT_MS
            if ok_ttft and (not n or mean_ms <= SLO_TPOT_MS):
                good += 1
        e2e = [led["e2e_s"] * 1e3 for led in ledgers]

        def pcts(xs):
            if not xs:
                return {"p50": 0.0, "p95": 0.0, "p99": 0.0}
            return {"p50": round(pct(xs, 0.50), 3),
                    "p95": round(pct(xs, 0.95), 3),
                    "p99": round(pct(xs, 0.99), 3)}

        return {
            "requests": len(ledgers),
            "ttft_ms": pcts(ttft),
            "tpot_ms": pcts(tpot),
            "e2e_ms": pcts(e2e),
            "goodput": round(good / len(ledgers), 3) if ledgers else 0.0,
        }

    def pipeline_rep(depth, steps=1):
        eng = DecodeServer(pipe_params, pipe_cfg, max_batch=PIPE_BATCH,
                           max_len=pipe_max_len, pipeline_depth=depth,
                           decode_steps=steps)
        for toks in pipe_prompts:                        # warm compiles
            eng.submit(toks, 2)
        eng.drain()
        eng.drain_ledgers()             # warm-up requests are not data
        best = None
        for _ in range(2):
            for toks in pipe_prompts:
                eng.submit(toks, PIPE_NEW)
            eng.reset_dispatch_stats()      # timing fence: decode only
            t0 = time.perf_counter()
            done = eng.drain()
            wall = time.perf_counter() - t0
            assert len(done) == len(pipe_prompts)
            new = len(pipe_prompts) * (PIPE_NEW - 1)
            rep = {
                "pipeline_depth": depth,
                "decode_steps": steps,
                "decode_s": round(wall, 4),
                "decode_tokens_per_s": round(new / wall),
                "ticks": eng.ticks_dispatched,
                "dispatch_gap_s": round(eng.dispatch_gap_s, 4),
                "host_blocked_us_per_token": round(
                    1e6 * eng.dispatch_gap_s / new, 1),
                "host_overhead_pct": round(
                    100.0 * eng.dispatch_gap_s / wall, 1),
                "sync_path_s": round(eng.host_block_s, 4),
                "per_request": per_request_stats(eng.drain_ledgers()),
            }
            if best is None or rep["host_blocked_us_per_token"] \
                    < best["host_blocked_us_per_token"]:
                best = rep
        return best

    pipeline = [pipeline_rep(d) for d in PIPELINE_DEPTHS]
    fused = pipeline_rep(PIPELINE_DEPTHS[-1], FUSED_STEPS)
    gap_by_depth = {p["pipeline_depth"]: p["host_blocked_us_per_token"]
                    for p in pipeline}

    # ------------------------------------------------------------------
    # paged KV vs slot-static at a fixed KV token budget (see the
    # config block up top). Both engines replay the same mixed-length
    # trace; the measure is SUSTAINED concurrency — mean active slots
    # per tick — plus wall/throughput. Deterministic by construction:
    # admission order and slot counts, not timing, decide the ratio.
    budget_tokens = PAGED_STATIC_SLOTS * PAGED_MAX_LEN
    kv_blocks = budget_tokens // KV_BLOCK + 1    # +1: reserved null block
    trace = [([int(x) for x in host_rng.integers(0, cfg.vocab, plen)], n)
             for plen, n in PAGED_TRACE]

    def concurrency_rep(eng, paged_engine, rep_trace=None):
        rep_trace = trace if rep_trace is None else rep_trace
        for plen in sorted({len(p) for p, _ in rep_trace}):  # warm
            eng.submit([1] * plen, 2)
        eng.drain()
        for toks, n in rep_trace:
            eng.submit(toks, n)
        samples = []
        backlog = []
        t0 = time.perf_counter()
        while eng.has_work():
            eng.step()
            samples.append(len(eng._active))
            if eng._pending:
                # pool-limited ticks: requests are waiting, so active
                # slots == what the KV budget admits — the structural
                # concurrency figure, undiluted by the drain-down tail
                # (a bigger pool finishes its backlog sooner and would
                # otherwise be penalized with more few-active samples)
                backlog.append(len(eng._active))
        wall = time.perf_counter() - t0
        done = eng.drain()
        assert len(done) >= len(rep_trace)
        new_tokens = sum(n for _, n in rep_trace)
        rep = {
            "slots": eng.max_batch,
            "avg_active_slots": round(sum(samples) / len(samples), 3),
            "avg_active_backlogged": round(
                sum(backlog) / len(backlog), 3) if backlog else None,
            "peak_active_slots": max(samples),
            "wall_s": round(wall, 4),
            "tokens_per_s": round(new_tokens / wall),
            "completed": len(done),
        }
        if paged_engine:
            kv = eng.kv_stats()
            rep["preempts"] = kv["preempts"]
            rep["blocks_total"] = kv["blocks_total"]
            # which paged decode-attention formulation the engine's
            # programs traced (NOS_TPU_PAGED_KERNEL): "kernel" = the
            # fused Pallas table walk, "xla" = the gather formulation
            rep["kernel"] = kv["kernel"]
        return rep

    static_rep = concurrency_rep(
        DecodeServer(params, cfg, max_batch=PAGED_STATIC_SLOTS,
                     max_len=PAGED_MAX_LEN), False)
    paged_rep = concurrency_rep(
        DecodeServer(params, cfg, max_batch=PAGED_SLOTS,
                     max_len=PAGED_MAX_LEN, kv_block_size=KV_BLOCK,
                     kv_blocks=kv_blocks), True)
    paged_section = {
        "kv_block_size": KV_BLOCK,
        "kv_blocks": kv_blocks,
        "kernel": paged_rep["kernel"],
        "budget_tokens": budget_tokens,
        "max_len": PAGED_MAX_LEN,
        "trace_requests": len(trace),
        "static": static_rep,
        "paged": paged_rep,
        # the headline: sustained concurrent slots at the same HBM/KV
        # budget (acceptance floor 1.5x on the mixed-length trace)
        "concurrency_ratio": round(
            paged_rep["avg_active_slots"]
            / max(static_rep["avg_active_slots"], 1e-9), 3),
    }

    # ------------------------------------------------------------------
    # speculative decoding over paged KV at every unpinned
    # (pipeline_depth, decode_steps). TPOT here is decode wall per new
    # token (the user-facing per-token latency of the burst); the
    # structural claim — the acceptance gate — is that depth 2 is not
    # worse than depth 1 on the engine's own dispatch-gap accounting
    # AND on TPOT (best of 3 reps, so one GC pause can't flip it).
    from nos_tpu.models.spec_serving import SpeculativeDecodeServer

    spec_tcfg = tr.TransformerConfig(**MODEL)
    spec_dcfg = tr.TransformerConfig(**dict(
        MODEL, d_model=MODEL["d_model"] // 2, n_layers=1,
        d_ff=MODEL["d_ff"] // 2, n_heads=max(2, MODEL["n_heads"] // 2),
        n_kv_heads=1))
    spec_tp = params
    spec_dp = tr.init_params(jax.random.PRNGKey(7), spec_dcfg)
    spec_prompts = [
        [int(x) for x in host_rng.integers(0, spec_tcfg.vocab,
                                           SPEC_PROMPT)]
        for _ in range(SPEC_BATCH)]
    spec_blocks = SPEC_BATCH * (SPEC_MAX_LEN // KV_BLOCK) + 1

    def spec_rep(depth, steps):
        eng = SpeculativeDecodeServer(
            spec_tp, spec_tcfg, spec_dp, spec_dcfg,
            n_draft=SPEC_DRAFT_N, max_batch=SPEC_BATCH,
            max_len=SPEC_MAX_LEN, pipeline_depth=depth,
            decode_steps=steps, kv_block_size=KV_BLOCK,
            kv_blocks=spec_blocks)
        for toks in spec_prompts:                        # warm compiles
            eng.submit(toks, 2)
        eng.drain()
        eng.drain_ledgers()
        best = None
        for _ in range(3):
            for toks in spec_prompts:
                eng.submit(toks, SPEC_NEW)
            eng.reset_dispatch_stats()
            tok0, tick0 = eng.tokens_emitted, eng.ticks_dispatched
            t0 = time.perf_counter()
            done = eng.drain()
            wall = time.perf_counter() - t0
            assert len(done) == len(spec_prompts)
            eng.drain_ledgers()
            new = len(spec_prompts) * (SPEC_NEW - 1)
            ticks = max(1, eng.ticks_dispatched - tick0)
            rep = {
                "pipeline_depth": depth,
                "decode_steps": steps,
                "n_draft": SPEC_DRAFT_N,
                "decode_s": round(wall, 4),
                "tpot_ms": round(1e3 * wall / new, 4),
                "tokens_per_dispatch": round(
                    (eng.tokens_emitted - tok0) / ticks, 3),
                "dispatch_gap_s": round(eng.dispatch_gap_s, 4),
                "host_blocked_us_per_token": round(
                    1e6 * eng.dispatch_gap_s / new, 1),
                "acceptance": round(
                    eng.spec_accepted / max(1, eng.spec_drafted), 4),
            }
            if best is None or rep["tpot_ms"] < best["tpot_ms"]:
                best = rep
        return best

    spec_grid = [spec_rep(d, t) for d, t in SPEC_GRID]
    spec_tpot = {(p["pipeline_depth"], p["decode_steps"]): p["tpot_ms"]
                 for p in spec_grid}
    spec_section = {
        "kv": "paged",
        "grid": spec_grid,
        # the un-forfeited pipelining win, stated as the ISSUE
        # acceptance reads it: the spec engine's own depth-2 TPOT vs
        # its own depth-1 (same decode_steps)
        "tpot_depth1_ms": spec_tpot[(1, 1)],
        "tpot_depth2_ms": spec_tpot[(2, 1)],
        "depth2_not_worse": spec_tpot[(2, 1)] <= spec_tpot[(1, 1)],
    }

    # ------------------------------------------------------------------
    # bf16 vs int8 paged KV at the SAME HBM byte budget. Bytes/token:
    # bf16 = 2 (k+v) x L x Hkv x D x 2B; int8 = 2 x L x Hkv x (D x 1B
    # + 4B f32 scale). The same byte budget therefore buys the int8
    # arena ~1.8-2x the blocks — which the mixed trace converts into
    # sustained concurrent slots (acceptance floor 1.5x).
    hkv = cfg.kv_heads
    bpt_bf16 = 2 * cfg.n_layers * hkv * cfg.head_dim * 2
    bpt_int8 = 2 * cfg.n_layers * hkv * (cfg.head_dim + 4)
    budget_bytes = PAGED_STATIC_SLOTS * PAGED_MAX_LEN * bpt_bf16
    blocks_bf16 = budget_bytes // (KV_BLOCK * bpt_bf16) + 1
    blocks_int8 = budget_bytes // (KV_BLOCK * bpt_int8) + 1
    int8_trace = [
        ([int(x) for x in host_rng.integers(0, cfg.vocab, plen)], n)
        for plen, n in INT8_TRACE]
    bf16_rep = concurrency_rep(
        DecodeServer(params, cfg, max_batch=INT8_SLOTS,
                     max_len=PAGED_MAX_LEN, kv_block_size=KV_BLOCK,
                     kv_blocks=blocks_bf16), True, int8_trace)
    int8_rep = concurrency_rep(
        DecodeServer(params, cfg, max_batch=INT8_SLOTS,
                     max_len=PAGED_MAX_LEN, kv_block_size=KV_BLOCK,
                     kv_blocks=blocks_int8, kv_dtype="int8"),
        True, int8_trace)
    int8_section = {
        "budget_bytes": budget_bytes,
        "kernel": int8_rep["kernel"],
        "bytes_per_token": {"bf16": bpt_bf16, "int8": bpt_int8},
        "kv_blocks": {"bf16": blocks_bf16, "int8": blocks_int8},
        "trace_requests": len(int8_trace),
        "bf16": bf16_rep,
        "int8": int8_rep,
        # the headline: sustained slots at the same HBM byte budget,
        # measured over pool-limited (backlogged) ticks — acceptance
        # floor 1.5x (the byte math alone predicts ~1.8-2x)
        "concurrency_ratio": round(
            (int8_rep["avg_active_backlogged"]
             or int8_rep["avg_active_slots"])
            / max(bf16_rep["avg_active_backlogged"]
                  or bf16_rep["avg_active_slots"], 1e-9), 3),
    }

    # ------------------------------------------------------------------
    # request-level elastic quota (ISSUE 13): isolation, borrowing and
    # bit-exact reclaim on a seeded fake-clock trace — every value
    # structural, so the section is byte-identical across reruns
    mt_section = multi_tenant_section(params, cfg)

    # ------------------------------------------------------------------
    # tiered KV fabric (ISSUE 17): host-RAM demotion vs
    # drop-and-recompute under prefix-cache pressure on the zipf
    # system-prompt trace — structural, byte-identical across reruns
    kf_section = kv_fabric_section(params, cfg)

    # ------------------------------------------------------------------
    # prefill/decode disaggregation (ISSUE 15): colocated vs role-split
    # at equal chips under the mixed trace; handoff byte model bf16 vs
    # int8; conservation + byte-identical structural rerun
    dg_section = disagg_section(params, cfg)

    # ------------------------------------------------------------------
    # stall-free colocated serving (ISSUE 19): per-tick prefill budget
    # + deadline-slack EDF vs the unbudgeted chunk rule on the fake
    # cost-model clock — structural, byte-identical across reruns
    cc_section = chunked_colocated_section(params, cfg)
    sa_section = slo_accounting_section()

    # the first token of each request is emitted by prefill (inside the
    # submit window); the drain window decodes the remaining N-1
    total_new = len(PROMPT_LENS) * (NEW_TOKENS - 1)
    dev = jax.devices()[0]
    result = {
        "metric": "continuous-batching serving, flagship GQA decoder"
                  + (" [SMOKE]" if SMOKE else ""),
        "device": dev.device_kind,
        "platform": jax.default_backend(),
        "max_batch": MAX_BATCH,
        "requests": len(PROMPT_LENS),
        "new_tokens_per_request": NEW_TOKENS,
        "prefill_admit_s": round(t_submit, 3),
        "decode_s": round(t_decode, 3),
        "decode_tokens_per_s": round(total_new / t_decode),
        "completed": len(results),
        # headline for the pipelining PR: host-blocked (dispatch-gap)
        # us/token at the deepest window vs the host-serial engine.
        # vs_baseline = baseline / current (> 1.0 = the pipeline hides
        # host time), matching the bench_sched.json convention; the
        # depth-1 run of the SAME binary is the baseline of record —
        # there was no serving artifact before this round. A fully
        # hidden gap measures 0.0, so both sides carry a 1 us/token
        # epsilon to keep the ratio finite and comparable across rounds.
        "value": gap_by_depth[PIPELINE_DEPTHS[-1]],
        "unit": "us_host_blocked_per_token",
        "vs_baseline": round(
            (gap_by_depth[1] + 1.0)
            / (gap_by_depth[PIPELINE_DEPTHS[-1]] + 1.0), 3),
        # per-request SLO frame for every pipeline rep below: the
        # ledgers grade each config's user-experienced latency against
        # these targets (goodput = fraction meeting both)
        "slo": {"ttft_ms": SLO_TTFT_MS, "tpot_ms": SLO_TPOT_MS},
        "pipeline": pipeline,
        "fused_decode": fused,
        "paged": paged_section,
        "speculative": spec_section,
        "kv_int8": int8_section,
        "multi_tenant": mt_section,
        "kv_fabric": kf_section,
        "disagg": dg_section,
        "chunked_colocated": cc_section,
        "slo_accounting": sa_section,
        "prefix_cache": {
            "shared_prefix_tokens": sys_len,
            "prefill_admit_s": round(t_submit_pc, 3),
            "admit_speedup": round(t_submit / max(t_submit_pc, 1e-9), 2),
            "hits": srv_pc.prefix_hits,
            "tokens_saved": srv_pc.prefix_tokens_saved,
        },
    }
    # file first (artifact of record), stdout line second
    os.makedirs(os.path.dirname(OUT_PATH), exist_ok=True)
    with open(OUT_PATH, "w") as f:
        json.dump(result, f, indent=2)
    print(json.dumps(result))
    return result


if __name__ == "__main__":
    main()
