#!/usr/bin/env python3
"""Serving-throughput bench: the continuous-batching engine
(models/serving.py) at the flagship shape — sustained decode tokens/s
with all slots busy, and request latency at mixed prompt lengths.

The interesting comparison is against single-request decode
(bench_decode.py): continuous batching amortizes the per-tick weight
read over max_batch requests, so engine tokens/s should approach
batch-B decode tokens/s while serving independent requests. Timing
fence: results are host-side by construction (the engine syncs one
array per tick). Prints one JSON line.
"""
import json
import sys
import time

sys.path.insert(0, ".")

import os  # noqa: E402

from bench import MODEL, smoke_overrides  # noqa: E402

MAX_BATCH = 8
PROMPT_LENS = [64, 128, 256, 96, 64, 192, 128, 80]
NEW_TOKENS = 64

# NOS_TPU_BENCH_SMOKE=1: tiny-shape dry run of the exact code path (see
# bench_decode.py) — hardware runs must never be the first execution
SMOKE = os.environ.get("NOS_TPU_BENCH_SMOKE") == "1"
if SMOKE:
    MODEL = smoke_overrides(MODEL)
    MAX_BATCH, PROMPT_LENS, NEW_TOKENS = 2, [16, 24, 16], 6


def main():
    import jax

    from nos_tpu.models import transformer as tr
    from nos_tpu.models.serving import DecodeServer

    import numpy as np

    cfg = tr.TransformerConfig(**MODEL)
    params = tr.init_params(jax.random.PRNGKey(0), cfg)
    # cache sized to the workload (matching bench_decode's economics:
    # per-tick attention cost scales with cache length)
    max_len = max(PROMPT_LENS) + NEW_TOKENS + 8
    srv = DecodeServer(params, cfg, max_batch=MAX_BATCH, max_len=max_len)

    # host-side prompts built OUTSIDE every timed window
    host_rng = np.random.default_rng(1)
    prompts = [[int(x) for x in host_rng.integers(0, cfg.vocab, size=plen)]
               for plen in PROMPT_LENS]

    # warm: compile EVERY prefill bucket this workload uses + the decode
    # program, so the timed windows measure execution, not XLA
    for plen in sorted({len(p) for p in prompts}):
        srv.submit([1] * plen, 2)
    srv.drain()

    t0 = time.perf_counter()
    for toks in prompts:
        srv.submit(toks, NEW_TOKENS)
    t_submit = time.perf_counter() - t0

    t0 = time.perf_counter()
    results = srv.drain()
    t_decode = time.perf_counter() - t0

    # prefix-cache rep: the system-prompt pattern — every request shares
    # a common head (half the shortest prompt), published once. Measures
    # admission (prefill) wall-clock against the uncached rep above; the
    # decode phase is unaffected by construction.
    sys_len = min(PROMPT_LENS) // 2
    system = [int(x) for x in host_rng.integers(0, cfg.vocab, size=sys_len)]
    shared = [system + p[sys_len:] for p in prompts]
    srv_pc = DecodeServer(params, cfg, max_batch=MAX_BATCH, max_len=max_len,
                          prefix_cache_size=2)
    srv_pc.submit(system + [2], 1, cache_prefix=True)  # publish (+ compile)
    srv_pc.drain()
    # warm the PREFIX-path shapes: suffix buckets and scratch lengths
    # differ from full-prefill buckets, so warming with uncached prompts
    # would leave every timed admit paying an XLA compile
    for toks in shared:
        srv_pc.submit(toks, 2)
    srv_pc.drain()
    srv_pc.prefix_hits = 0
    srv_pc.prefix_tokens_saved = 0
    t0 = time.perf_counter()
    for toks in shared:
        srv_pc.submit(toks, NEW_TOKENS)
    t_submit_pc = time.perf_counter() - t0
    srv_pc.drain()

    # the first token of each request is emitted by prefill (inside the
    # submit window); the drain window decodes the remaining N-1
    total_new = len(PROMPT_LENS) * (NEW_TOKENS - 1)
    dev = jax.devices()[0]
    print(json.dumps({
        "metric": "continuous-batching serving, flagship GQA decoder"
                  + (" [SMOKE]" if SMOKE else ""),
        "device": dev.device_kind,
        "platform": jax.default_backend(),
        "max_batch": MAX_BATCH,
        "requests": len(PROMPT_LENS),
        "new_tokens_per_request": NEW_TOKENS,
        "prefill_admit_s": round(t_submit, 3),
        "decode_s": round(t_decode, 3),
        "decode_tokens_per_s": round(total_new / t_decode),
        "completed": len(results),
        "prefix_cache": {
            "shared_prefix_tokens": sys_len,
            "prefill_admit_s": round(t_submit_pc, 3),
            "admit_speedup": round(t_submit / max(t_submit_pc, 1e-9), 2),
            "hits": srv_pc.prefix_hits,
            "tokens_saved": srv_pc.prefix_tokens_saved,
        },
    }))


if __name__ == "__main__":
    main()
