"""Long-context showcase: a 1M-token context on a v5e-256 slice via ring
attention (sequence parallelism), gang-scheduled by the same contract as
the Llama-3-70B example.

Why this shape: at 1M tokens even the ACTIVATIONS of one layer dwarf a
chip (bf16 [1, 1M, 4096] is 8 GB per tensor), and the fp32 attention
scores would be 128 TB if materialized (32 heads x 1M^2). Ring attention
(nos_tpu/ops/
ring_attention.py) never materializes the [S, S] block — each of the
``sp`` devices holds S/sp of the sequence, K/V blocks rotate over ICI
with ``ppermute``, and flash-style online-softmax statistics accumulate
locally — so context length scales linearly with the ring size while
per-chip memory stays constant. That is what makes sp the right axis for
context (and why pp, which shards depth, cannot substitute). When the
model is also too DEEP for fsdp alone, sp composes with pipeline depth
sharding via the GPipe schedule (``TrainerConfig(pp=...,
pipeline_schedule="gpipe")`` — dense models; see parallel/pipeline.py).

The scheduling half is identical to the 70B example: the layout's chip
count maps to a slice topology (``ParallelLayout.required_topology``),
and the gang scheduler places one pod per host on a contiguous ICI
sub-cuboid. Long context changes WHICH axes the layout turns on, not the
scheduling contract — exactly the separation SURVEY §5 ("long-context /
sequence parallelism") prescribes.

Run ``python examples/long_context_1m_v5e.py`` for the plan (no TPU
needed); the worked numbers are asserted in tests/test_example_longctx.py.
"""
from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from nos_tpu import constants                                  # noqa: E402
from nos_tpu.models.transformer import TransformerConfig       # noqa: E402
from nos_tpu.parallel.layout import ParallelLayout             # noqa: E402
from nos_tpu.tpu import topology                               # noqa: E402

GENERATION = "v5e"
NAMESPACE = "long-context"
GANG_NAME = "ctx-1m"

SEQ_LEN = 1 << 20            # 1,048,576 tokens

# A 7B-class GQA decoder: big enough that the context, not the params,
# is the problem being demonstrated.
MODEL = TransformerConfig(
    vocab=128256,
    d_model=4096,
    n_layers=32,
    n_heads=32,
    n_kv_heads=8,
    d_ff=14336,
    max_seq=SEQ_LEN,
    remat_policy="minimal",   # long context: activations are the enemy
    loss_chunk=2048,          # never materialize [B, 1M, 128k] logits
)

# 256 chips: ring of 64 over the sequence, fsdp 4 for the params.
# sp=64 leaves 16k tokens per chip — the ring hop overlaps with block
# compute on ICI, and GQA circulates only the 8 kv heads.
LAYOUT = ParallelLayout(fsdp=4, sp=64)


def activation_gb_per_chip(cfg: TransformerConfig, layout: ParallelLayout,
                           batch: int = 1) -> float:
    """Residual-stream bf16 activations per chip per layer boundary under
    sp sharding (the quantity ring attention keeps constant as S grows)."""
    local_tokens = cfg.max_seq // layout.sp
    return batch * local_tokens * cfg.d_model * 2 / 1024**3


def scores_tb_if_materialized(batch: int = 1) -> float:
    """What full [S, S] fp32 attention scores would cost — the number
    that rules out anything but an online-softmax scheme."""
    return batch * MODEL.n_heads * SEQ_LEN * SEQ_LEN * 4 / 1024**4


def plan() -> dict:
    gen = topology.get_generation(GENERATION)
    topo = LAYOUT.required_topology(GENERATION)
    if topo is None:
        raise ValueError(f"no {GENERATION} topology fits {LAYOUT.chips} chips")
    return {
        "seq_len": SEQ_LEN,
        "chips": LAYOUT.chips,
        "topology": topo.name,
        "hosts": gen.hosts_for(topo),
        "chips_per_host": gen.chips_per_host,
        "tokens_per_chip": SEQ_LEN // LAYOUT.sp,
        "activation_gb_per_chip_per_layer": round(
            activation_gb_per_chip(MODEL, LAYOUT), 3),
        "scores_tb_if_materialized": round(scores_tb_if_materialized(), 1),
        "kv_ring_bytes_per_hop": 2 * MODEL.kv_dim * (SEQ_LEN // LAYOUT.sp) * 2,
    }


def worker_pods() -> list:
    """One pod per v5e host — same gang contract as the 70B example."""
    p = plan()
    pods = []
    for w in range(p["hosts"]):
        pods.append({
            "apiVersion": "v1",
            "kind": "Pod",
            "metadata": {
                "name": f"{GANG_NAME}-worker-{w}",
                "namespace": NAMESPACE,
                "labels": {
                    constants.LABEL_GANG_NAME: GANG_NAME,
                    constants.LABEL_GANG_SIZE: str(p["hosts"]),
                    constants.LABEL_GANG_WORKER: str(w),
                },
                "annotations": {
                    constants.ANNOTATION_TPU_TOPOLOGY: p["topology"],
                },
            },
            "spec": {
                "schedulerName": constants.SCHEDULER_NAME,
                "nodeSelector": {
                    constants.LABEL_TPU_ACCELERATOR: topology.get_generation(
                        GENERATION).name,
                },
                "containers": [{
                    "name": "train",
                    "image": "nos-tpu/trainer:latest",
                    "command": ["python", "-m", "nos_tpu.cmd", "trainer",
                                "--config", "/etc/nos-tpu/trainer.yaml"],
                    "env": [
                        {"name": "COORDINATOR_ADDRESS",
                         "value": f"{GANG_NAME}-worker-0.{NAMESPACE}:8476"},
                        {"name": "NUM_PROCESSES", "value": str(p["hosts"])},
                        {"name": "PROCESS_ID", "value": str(w)},
                    ],
                    "resources": {
                        "limits": {constants.RESOURCE_TPU: p["chips_per_host"]},
                        "requests": {constants.RESOURCE_TPU: p["chips_per_host"]},
                    },
                }],
            },
        })
    return pods


def main() -> None:
    import json

    print(json.dumps(plan(), indent=2))


if __name__ == "__main__":
    main()
