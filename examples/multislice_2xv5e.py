"""Multislice showcase: one training job spanning TWO v5e slices over
DCN, scheduled as a gang of gangs.

Why this shape: a single slice caps out (v5e tops at 256 chips per
slice); growing past it means multiple slices whose only link is the
data-center network — orders of magnitude less bandwidth than ICI. The
design rule that makes this work is the same one
``nos_tpu/parallel/mesh.py`` enforces when laying a mesh over a
multislice device set: **only the data axes (dp/fsdp) may cross the
slice boundary** — their per-step traffic is one gradient all-reduce,
which overlaps with backward compute — while tp/sp/ep/pp collectives
(per-layer, latency-bound) stay inside each slice's ICI.

Both halves of the contract come from ``ParallelLayout``:

- workload side: ``layout.per_slice(n_slices)`` divides the dp axis and
  is what each slice's processes run; ``build_mesh(layout, slice_ids=…)``
  lays the global mesh so slice boundaries land between dp rows (it
  REFUSES layouts where a model axis would straddle DCN).
- scheduler side: ``per_slice(...).required_topology`` is the topology
  annotation EVERY slice's gang carries (identical across slices —
  slices are interchangeable dp replicas), and the jobset labels
  (nos.ai/jobset-name/-slices/-slice) tie the N gangs into one co-atomic
  admission: nothing binds unless every slice gets its own, DISTINCT ICI
  domain (a jobset holding K of N slices would deadlock the cross-slice
  all-reduce exactly like a partial gang deadlocks an ICI collective).

Run ``python examples/multislice_2xv5e.py`` for the plan (no TPU
needed); tests/test_example_multislice.py schedules the jobset end-to-end
on a simulated 2-pool cluster and runs one real training step on a
2-slice virtual mesh.
"""
from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from nos_tpu import constants                                  # noqa: E402
from nos_tpu.parallel.layout import ParallelLayout             # noqa: E402
from nos_tpu.tpu import topology                               # noqa: E402

GENERATION = "v5e"
N_SLICES = 2

# global layout: dp=2 crosses DCN (one row per slice); within a slice,
# tp x sp = 8 fills a 2x4 (8-chip, one-host) slice. Scale the same shape
# up by raising tp/sp per slice and dp across more slices.
GLOBAL_LAYOUT = ParallelLayout(dp=N_SLICES, tp=2, sp=4)


def plan() -> dict:
    per_slice = GLOBAL_LAYOUT.per_slice(N_SLICES)
    topo = per_slice.required_topology(GENERATION)
    gen = topology.get_generation(GENERATION)
    hosts = gen.hosts_for(topo)
    return {
        "global_layout": {
            a: getattr(GLOBAL_LAYOUT, a)
            for a in ("dp", "fsdp", "tp", "pp", "sp", "ep")
        },
        "n_slices": N_SLICES,
        "per_slice_layout": {
            a: getattr(per_slice, a)
            for a in ("dp", "fsdp", "tp", "pp", "sp", "ep")
        },
        "slice_topology": topo.name,
        "hosts_per_slice": hosts,
        "chips_per_slice": topo.chips,
        "dcn_axes": ["dp"],            # the ONLY axes allowed to cross
        "ici_axes": ["tp", "sp"],
        "pod_labels_slice0_worker0": {
            constants.LABEL_JOBSET_NAME: "train",
            constants.LABEL_JOBSET_SLICES: str(N_SLICES),
            constants.LABEL_JOBSET_SLICE: "0",
            constants.LABEL_GANG_NAME: "train-slice-0",
            constants.LABEL_GANG_SIZE: str(hosts),
            constants.LABEL_GANG_WORKER: "0",
        },
        "pod_annotation": {constants.ANNOTATION_TPU_TOPOLOGY: topo.name},
    }


def main() -> None:
    import json

    print(json.dumps(plan(), indent=2))


if __name__ == "__main__":
    main()
