"""Multi-tenant detection serving: the reference's ONLY published
benchmark scenario, planned end-to-end on one dynamically partitioned
v5e host.

The reference demo (rwipfelexo/nos ``demos/gpu-sharing-comparison``)
serves YOLOS-small from N pods sharing one A100 under MIG / MPS /
time-slicing and publishes per-request latency (BASELINE.md). This
example is the TPU twin at the isolation end of that spectrum — the MIG
analog: each tenant owns a hardware-isolated **1x1 sub-slice** of a v5e
host, carved on demand by the partitioning control plane
(nos_tpu/partitioning/subslicing.py) and advertised by the tpuagent as
``nos.ai/tpu-slice-1x1``. Latency per tenant is then flat in the number
of co-resident tenants — the property the reference measures for MIG
(0.342-0.345 s at 1..7 pods) — while the chips tenants don't use remain
carveable for anyone else.

The model each tenant runs is nos_tpu/models/yolos.py — the reference's
exact model family (ViT-small/16 backbone + 100 detection tokens). The
shared-chip ends of the spectrum (multiplex = the MPS analog,
timeslice) are the sharing demo (demos/tpu-sharing-comparison), whose
hardware table hack/bench_babysit.py --queue sharing measures.

Quota-wise the namespace ElasticQuota bounds the tenants in the
resource they request (``nos.ai/tpu-slice-1x1`` — accounting is
bound-keyed, like the reference's MIG-profile quotas), with
``nos_tpu/tpu/resource_calc.py`` deriving the chip-memory equivalent;
max = 2x min lets detection borrow idle capacity and be reclaimed by
in-quota training pods.

Run ``python examples/yolos_multitenant_v5e.py`` for the plan (no TPU
needed); the worked numbers are asserted in tests/test_example_yolos.py.
"""
from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from nos_tpu import constants                                  # noqa: E402
from nos_tpu.models.yolos import YolosConfig                   # noqa: E402
from nos_tpu.tpu import topology                               # noqa: E402

GENERATION = "v5e"
NAMESPACE = "detect"
N_TENANTS = 7                 # the reference's largest published point
SLICE = "1x1"                 # MIG-analog isolation: one chip per tenant

MODEL = YolosConfig()         # YOLOS-small: ViT-small/16 + 100 det tokens

V5E_BF16_TFLOPS = 197.0       # per chip (bench.py PEAK_TFLOPS)


def forward_gflops(cfg: YolosConfig, batch: int = 1) -> float:
    """Analytic matmul GFLOPs of one detection forward (2*m*n*k per
    matmul): patch projection, per-block qkv/proj/mlp + attention at
    S = patches + det tokens, class head, box MLP."""
    s = cfg.n_patches + cfg.n_det_tokens
    d, f, L = cfg.d_model, cfg.d_ff, cfg.n_layers
    patch_dim = cfg.patch * cfg.patch * 3
    per_block = 2 * s * (d * 4 * d          # qkv + output proj
                         + 2 * d * f)       # mlp in + out
    attn = 4 * s * s * d                    # scores + weighted sum
    heads = 2 * cfg.n_det_tokens * (d * (cfg.n_classes + 1)  # class head
                                    + 2 * d * d + d * 4)     # box mlp
    total = (2 * cfg.n_patches * patch_dim * d   # det tokens are learned
             # embeddings, not projections — only image patches matmul here
             + L * (per_block + attn) + heads)
    return batch * total / 1e9


def plan() -> dict:
    gen = topology.get_generation(GENERATION)
    gx, gy = topology.host_grid(GENERATION)
    sx, sy = (int(v) for v in SLICE.split("x"))
    per_host = (gx * gy) // (sx * sy)
    gflops = forward_gflops(MODEL)
    # compute floor at realistic MXU efficiency for a small model (40%)
    floor_ms = gflops / (V5E_BF16_TFLOPS * 1e3 * 0.4) * 1e3
    return {
        "tenants": N_TENANTS,
        "slice_resource": constants.RESOURCE_TPU_SLICE_PREFIX + SLICE,
        "chips_per_host": gen.chips_per_host,
        "host_grid": f"{gx}x{gy}",
        "tenants_per_host": per_host,
        "hosts_needed": -(-N_TENANTS // per_host),
        "spare_slices": per_host - N_TENANTS % per_host
        if N_TENANTS % per_host else 0,
        "forward_gflops": round(gflops, 2),
        "latency_floor_ms": round(floor_ms, 3),
        "reference_mig_s": 0.34425,   # A100 MIG at 7 pods (BASELINE.md)
    }


def tenant_pods() -> list:
    """One serving pod per tenant, each requesting an isolated 1x1
    sub-slice — the shape demos/tpu-sharing-comparison deploys as its
    ``subslice`` overlay."""
    res = constants.RESOURCE_TPU_SLICE_PREFIX + SLICE
    accel = topology.get_generation(GENERATION).name
    pods = []
    for i in range(N_TENANTS):
        pods.append({
            "apiVersion": "v1",
            "kind": "Pod",
            "metadata": {
                "name": f"yolos-tenant-{i}",
                "namespace": NAMESPACE,
            },
            "spec": {
                "schedulerName": constants.SCHEDULER_NAME,
                "nodeSelector": {
                    constants.LABEL_TPU_ACCELERATOR: accel,
                },
                "containers": [{
                    "name": "detect",
                    "image": "nos-tpu/sharing-client:latest",
                    "command": ["python", "/app/client/main.py",
                                "--mode", "subslice"],
                    "resources": {
                        "requests": {res: 1},
                        "limits": {res: 1},
                    },
                }],
            },
        })
    return pods


def quota() -> dict:
    """Namespace ElasticQuota bounding the tenants in the resource they
    REQUEST (1x1 sub-slices — quota accounting is bound-keyed, like the
    reference's MIG-profile quotas; the ResourceCalculator additionally
    derives nos.ai/tpu-memory from slice requests for memory-bounded
    quotas). max = 2x min: detection can borrow idle capacity and be
    reclaimed by in-quota training pods."""
    res = constants.RESOURCE_TPU_SLICE_PREFIX + SLICE
    return {
        "apiVersion": "nos.ai/v1alpha1",
        "kind": "ElasticQuota",
        "metadata": {"name": "detect-quota", "namespace": NAMESPACE},
        "spec": {
            "min": {res: N_TENANTS},
            "max": {res: 2 * N_TENANTS},
        },
    }


if __name__ == "__main__":
    import json

    print(json.dumps({"plan": plan(), "quota": quota(),
                      "pods": len(tenant_pods())}, indent=1))
