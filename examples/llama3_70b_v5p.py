#!/usr/bin/env python3
"""North-star example: a Llama-3-70B-scale training JobSet on a v5p-512.

BASELINE.json's target scenario: nos-tpu schedules and right-sizes a
Llama-3-70B training JobSet onto a v5p-512 GKE node pool. This example
connects the three planes end to end:

1. **workload plane** — a 70B-scale ``TransformerConfig`` and the
   ``ParallelLayout`` that trains it (fsdp x tp x sp), with the HBM
   feasibility math (params + optimizer state sharded by fsdp x tp must
   fit each chip's 95 GB);
2. **scheduling contract** — ``ParallelLayout.required_topology("v5p")``
   names the slice topology the gang needs (8x8x8 = 512 chips); the gang
   labels + topology annotation on each worker pod are exactly what the
   gang scheduler admits and places (nos_tpu/scheduler/gang.py);
3. **manifests** — ``worker_pods()`` emits the 128 worker-pod dicts a
   JobSet controller would create, one per v5p host (4 chips/host).

Run ``python examples/llama3_70b_v5p.py`` to print the plan summary and
write the first worker manifest to stdout.
"""
from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

from nos_tpu import constants                                 # noqa: E402
from nos_tpu.models.transformer import TransformerConfig       # noqa: E402
from nos_tpu.parallel.layout import ParallelLayout             # noqa: E402
from nos_tpu.tpu import topology                               # noqa: E402

GENERATION = "v5p"
NAMESPACE = "llm-training"
GANG_NAME = "llama3-70b"

# Llama-3-70B architecture (public numbers; GQA with 8 kv heads).
# except_mlp remat + a chunked loss head (docs/workload-plane/
# performance-tuning.md): near-dots throughput at a fraction of its
# activation HBM, and the fp32 [B, S, 128k-vocab] logits never
# materialize at once.
LLAMA3_70B = TransformerConfig(
    vocab=128256,
    d_model=8192,
    n_layers=80,
    n_heads=64,
    n_kv_heads=8,
    d_ff=28672,
    max_seq=8192,
    remat_policy="except_mlp",
    loss_chunk=1024,
)

# 512 chips: zero-style param sharding over 64, tensor parallel 4 within a
# host, sequence/context parallel 2 for the 8k context
LAYOUT = ParallelLayout(fsdp=64, tp=4, sp=2)


def param_count(cfg: TransformerConfig) -> int:
    """Decoder transformer parameter count (embeddings + layers + head),
    GQA-aware: k/v projections are d x (kv_heads * head_dim)."""
    d, f, L, v = cfg.d_model, cfg.d_ff, cfg.n_layers, cfg.vocab
    attn = 2 * d * d + 2 * d * cfg.kv_dim            # q,o + k,v
    per_layer = attn + 3 * d * f + 2 * d             # + swiglu + norms
    return v * d + L * per_layer + d + d * v         # embed + layers + head


def hbm_per_chip_gb(cfg: TransformerConfig, layout: ParallelLayout) -> float:
    """Training-state HBM per chip: bf16 params + fp32 grads and Adam
    moments, sharded over the fsdp x tp axes."""
    n = param_count(cfg)
    bytes_total = n * (2 + 4 + 4 + 4)                # params, grads, m, v
    return bytes_total / (layout.fsdp * layout.tp) / 1024**3


def plan() -> dict:
    gen = topology.get_generation(GENERATION)
    topo = LAYOUT.required_topology(GENERATION)
    if topo is None:
        raise ValueError(f"no {GENERATION} topology fits {LAYOUT.chips} chips")
    hosts = gen.hosts_for(topo)
    need_gb = hbm_per_chip_gb(LLAMA3_70B, LAYOUT)
    return {
        "params_b": round(param_count(LLAMA3_70B) / 1e9, 1),
        "chips": LAYOUT.chips,
        "topology": topo.name,
        "hosts": hosts,
        "chips_per_host": gen.chips_per_host,
        "hbm_needed_gb_per_chip": round(need_gb, 1),
        "hbm_available_gb_per_chip": gen.hbm_gb_per_chip,
        "fits": need_gb <= gen.hbm_gb_per_chip,
    }


def worker_pods() -> list:
    """One pod per v5p host, carrying the gang contract the scheduler
    admits (labels) and the topology it must place (annotation)."""
    p = plan()
    pods = []
    for w in range(p["hosts"]):
        pods.append({
            "apiVersion": "v1",
            "kind": "Pod",
            "metadata": {
                "name": f"{GANG_NAME}-worker-{w}",
                "namespace": NAMESPACE,
                "labels": {
                    constants.LABEL_GANG_NAME: GANG_NAME,
                    constants.LABEL_GANG_SIZE: str(p["hosts"]),
                    constants.LABEL_GANG_WORKER: str(w),
                },
                "annotations": {
                    constants.ANNOTATION_TPU_TOPOLOGY: p["topology"],
                },
            },
            "spec": {
                "schedulerName": constants.SCHEDULER_NAME,
                "nodeSelector": {
                    constants.LABEL_TPU_ACCELERATOR: topology.get_generation(
                        GENERATION).name,
                },
                "containers": [{
                    "name": "train",
                    "image": "nos-tpu/trainer:latest",
                    "command": ["python", "-m", "nos_tpu.cmd", "trainer",
                                "--config", "/etc/nos-tpu/trainer.yaml"],
                    # the trainer's multi-host contract
                    # (nos_tpu/cmd/trainer.py::_maybe_init_distributed):
                    # worker 0 is the coordinator, gang size/worker index
                    # give world size and rank
                    "env": [
                        {"name": "COORDINATOR_ADDRESS",
                         "value": f"{GANG_NAME}-worker-0.{NAMESPACE}:8476"},
                        {"name": "NUM_PROCESSES", "value": str(p["hosts"])},
                        {"name": "PROCESS_ID", "value": str(w)},
                    ],
                    "resources": {
                        "limits": {constants.RESOURCE_TPU: p["chips_per_host"]},
                        "requests": {constants.RESOURCE_TPU: p["chips_per_host"]},
                    },
                }],
            },
        })
    return pods


def main() -> None:
    import json

    p = plan()
    print(json.dumps(p, indent=2))
    print(f"\n# first of {p['hosts']} worker pods:")
    print(json.dumps(worker_pods()[0], indent=2))


if __name__ == "__main__":
    main()
