{{/* Common template helpers for the nos-tpu chart. */}}

{{- define "nos-tpu.name" -}}
{{- default .Chart.Name .Values.nameOverride | trunc 63 | trimSuffix "-" -}}
{{- end -}}

{{- define "nos-tpu.namespace" -}}
{{- default .Release.Namespace .Values.namespaceOverride -}}
{{- end -}}

{{- define "nos-tpu.fullname" -}}
{{- printf "%s-%s" .Release.Name (include "nos-tpu.name" .) | trunc 63 | trimSuffix "-" -}}
{{- end -}}

{{- define "nos-tpu.labels" -}}
helm.sh/chart: {{ printf "%s-%s" .Chart.Name .Chart.Version | replace "+" "_" | trunc 63 }}
app.kubernetes.io/name: {{ include "nos-tpu.name" . }}
app.kubernetes.io/instance: {{ .Release.Name }}
app.kubernetes.io/version: {{ .Chart.AppVersion | quote }}
app.kubernetes.io/managed-by: {{ .Release.Service }}
{{- end -}}

{{- define "nos-tpu.tag" -}}
{{- default .Chart.AppVersion .Values.image.tag -}}
{{- end -}}

{{- define "nos-tpu.operator.image" -}}
{{- printf "%s/%s:%s" .Values.image.registry .Values.operator.image.repository (include "nos-tpu.tag" .) -}}
{{- end -}}

{{- define "nos-tpu.scheduler.image" -}}
{{- printf "%s/%s:%s" .Values.image.registry .Values.scheduler.image.repository (include "nos-tpu.tag" .) -}}
{{- end -}}

{{- define "nos-tpu.tpuPartitioner.image" -}}
{{- printf "%s/%s:%s" .Values.image.registry .Values.tpuPartitioner.image.repository (include "nos-tpu.tag" .) -}}
{{- end -}}

{{- define "nos-tpu.tpuAgent.image" -}}
{{- printf "%s/%s:%s" .Values.image.registry .Values.tpuAgent.image.repository (include "nos-tpu.tag" .) -}}
{{- end -}}

{{- define "nos-tpu.devicePlugin.image" -}}
{{- printf "%s/%s:%s" .Values.image.registry .Values.devicePlugin.image.repository (include "nos-tpu.tag" .) -}}
{{- end -}}

{{- define "nos-tpu.apiServer.image" -}}
{{- printf "%s/%s:%s" .Values.image.registry .Values.apiServer.image.repository (include "nos-tpu.tag" .) -}}
{{- end -}}

{{/* URL every component passes as --api. */}}
{{- define "nos-tpu.apiServer.url" -}}
{{- printf "http://%s-apiserver.%s.svc:%d" (include "nos-tpu.fullname" .) (include "nos-tpu.namespace" .) (int .Values.apiServer.port) -}}
{{- end -}}

{{- define "nos-tpu.lifecycle.image" -}}
{{- printf "%s/%s:%s" .Values.image.registry .Values.lifecycle.image.repository (include "nos-tpu.tag" .) -}}
{{- end -}}

{{- define "nos-tpu.gateway.image" -}}
{{- printf "%s/%s:%s" .Values.image.registry .Values.gateway.image.repository (include "nos-tpu.tag" .) -}}
{{- end -}}

{{- define "nos-tpu.fleet.image" -}}
{{- printf "%s/%s:%s" .Values.image.registry .Values.fleet.image.repository (include "nos-tpu.tag" .) -}}
{{- end -}}

{{- define "nos-tpu.harvest.image" -}}
{{- printf "%s/%s:%s" .Values.image.registry .Values.harvest.image.repository (include "nos-tpu.tag" .) -}}
{{- end -}}

{{- define "nos-tpu.serving.image" -}}
{{- printf "%s/%s:%s" .Values.image.registry .Values.serving.image.repository (include "nos-tpu.tag" .) -}}
{{- end -}}

{{- define "nos-tpu.metricsExporter.image" -}}
{{- printf "%s/%s:%s" .Values.image.registry .Values.metricsExporter.image.repository (include "nos-tpu.tag" .) -}}
{{- end -}}

{{/* Shared observability args every control-plane daemon takes:
     structured-log format + tracing sampler / flight-recorder knobs
     (served at /debug/traces next to /metrics). */}}
{{- define "nos-tpu.observabilityArgs" -}}
- --log-format={{ .Values.observability.logFormat }}
- --trace-sampling={{ .Values.observability.tracing.sampling }}
- --trace-recorder-size={{ .Values.observability.tracing.recorderMaxTraces }}
- --trace-slow-threshold={{ .Values.observability.tracing.slowThresholdSeconds }}
{{- end -}}
