#!/usr/bin/env python3
"""Attention-kernel microbench at the flagship shape: forward and
forward+backward wall-clock for each dispatchable implementation
(splash / legacy flash / XLA), so kernel choice and block-size sweeps are
decided by measurement, not vibes. Timing fence is the host transfer
(block_until_ready lies on 'axon' — see bench_mfu.py).

Usage: python bench_attn.py [reps]
Env: NOS_TPU_SPLASH_* block-size overrides are honored (ops/attention.py);
NOS_TPU_ATTN_ONLY=<impl> restricts to one implementation so an
orchestrator can isolate each kernel in its own process (a wedged Mosaic
compile then kills one point, not the whole comparison — the round-3
outage playbook).
Prints one JSON line per impl.
"""
import json
import os
import sys
import time

sys.path.insert(0, ".")

from bench import BATCH, MODEL, SEQ, phase_marker  # noqa: E402
from bench_mfu import host_fence  # noqa: E402

REPS = int(sys.argv[1]) if len(sys.argv) > 1 else 10


def main():
    import jax
    import jax.numpy as jnp

    from nos_tpu.ops import attention as at

    b, s = BATCH, SEQ
    h, kv = MODEL["n_heads"], MODEL["n_kv_heads"]
    d = MODEL["d_model"] // h
    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    q = jax.random.normal(ks[0], (b, h, s, d), jnp.bfloat16)
    k = jax.random.normal(ks[1], (b, kv, s, d), jnp.bfloat16)
    v = jax.random.normal(ks[2], (b, kv, s, d), jnp.bfloat16)

    only = os.environ.get("NOS_TPU_ATTN_ONLY", "")
    impls = [only] if only else ["splash", "flash", "xla"]
    for impl in impls:
        os.environ["NOS_TPU_ATTN_IMPL"] = impl
        eff = at.effective_impl(q.shape, k.shape)
        if eff != impl:
            print(json.dumps({"impl": impl, "skipped": f"dispatches {eff}"}))
            continue

        fwd = jax.jit(lambda q, k, v: at.attention(q, k, v, causal=True))

        def loss(q, k, v):
            return jnp.sum(at.attention(q, k, v, causal=True)
                           .astype(jnp.float32))

        grad = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))

        def phase(name):
            phase_marker(impl, name)

        try:
            phase("fwd_compile")
            t0 = time.perf_counter()
            out = fwd(q, k, v)
            host_fence(out)
            compile_fwd = time.perf_counter() - t0

            phase("fwd_timing")
            t0 = time.perf_counter()
            for _ in range(REPS):
                out = fwd(q, k, v)
            host_fence(out)
            t_fwd = (time.perf_counter() - t0) / REPS

            phase("bwd_compile")
            t0 = time.perf_counter()
            g = grad(q, k, v)
            host_fence(g[0])
            compile_bwd = time.perf_counter() - t0

            phase("bwd_timing")
            t0 = time.perf_counter()
            for _ in range(REPS):
                g = grad(q, k, v)
            host_fence(g[0])
            t_bwd = (time.perf_counter() - t0) / REPS
            phase("done")
        except Exception as e:
            print(json.dumps({"impl": impl,
                              "error": f"{type(e).__name__}: {e}"[:200]}))
            continue

        print(json.dumps({
            "impl": impl,
            "shape": f"b{b} h{h} kv{kv} s{s} d{d} causal bf16",
            "fwd_ms": round(t_fwd * 1e3, 2),
            "fwd_bwd_ms": round(t_bwd * 1e3, 2),
            "compile_fwd_s": round(compile_fwd, 1),
            "compile_bwd_s": round(compile_bwd, 1),
        }), flush=True)


if __name__ == "__main__":
    main()
