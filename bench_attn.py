#!/usr/bin/env python3
"""Attention-kernel microbench, one JSON line per measured point.

Sections (--sections, default both):

- ``attn``: forward and forward+backward wall-clock for each
  dispatchable training-shape implementation (splash / legacy flash /
  XLA) at the flagship shape, so kernel choice and block-size sweeps
  are decided by measurement, not vibes.
- ``paged_decode``: the serving decode step over a PAGED arena —
  XLA-gather formulation vs the fused Pallas table-walk kernel
  (ops.attention.paged_decode_attention) vs the slot-static contiguous
  cache, across context lengths (--paged-ctx, default 1k/4k/16k) and
  kv dtypes bf16/int8. The XLA point materializes the gathered
  timeline (plus a dequantized copy for int8) exactly like
  forward_paged's escape hatch; the kernel point streams arena blocks
  in-kernel with dequant fused into the inner loop. Off-TPU the kernel
  only runs in interpret mode, which measures nothing — those points
  print as skipped unless --paged-interpret forces them (parity
  checks, not perf).

Timing fence is the host transfer (block_until_ready lies on 'axon' —
see bench_mfu.py).

Usage: python bench_attn.py [reps] [--sections attn,paged_decode]
                            [--paged-ctx 1024,4096,16384] ...
Env: NOS_TPU_SPLASH_* block-size overrides are honored
(ops/attention.py); NOS_TPU_ATTN_ONLY=<impl> restricts the attn
section to one implementation and NOS_TPU_PAGED_ONLY=<impl>
(xla|kernel|slot_static) does the same for paged_decode, so an
orchestrator can isolate each kernel in its own process (a wedged
Mosaic compile then kills one point, not the whole comparison — the
round-3 outage playbook).
"""
import argparse
import json
import os
import sys
import time

sys.path.insert(0, ".")

from bench import BATCH, MODEL, SEQ, phase_marker  # noqa: E402
from bench_mfu import host_fence  # noqa: E402

PAGED_IMPLS = ("xla", "kernel", "slot_static")


def parse_args(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("reps", nargs="?", type=int, default=10,
                    help="timed repetitions per point (default 10)")
    ap.add_argument("--sections", default="attn,paged_decode",
                    help="comma list of sections to run: "
                         "attn,paged_decode")
    ap.add_argument("--paged-ctx", default="1024,4096,16384",
                    help="paged_decode context lengths, comma list")
    ap.add_argument("--paged-batch", type=int, default=8,
                    help="paged_decode decode batch (rows)")
    ap.add_argument("--paged-block", type=int, default=128,
                    help="paged-KV block size in tokens")
    ap.add_argument("--paged-interpret", action="store_true",
                    help="run the Pallas kernel points in interpret "
                         "mode off-TPU (exactness probing; the timings "
                         "are meaningless)")
    return ap.parse_args(argv)


def attn_section(reps):
    import jax
    import jax.numpy as jnp

    from nos_tpu.ops import attention as at

    b, s = BATCH, SEQ
    h, kv = MODEL["n_heads"], MODEL["n_kv_heads"]
    d = MODEL["d_model"] // h
    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    q = jax.random.normal(ks[0], (b, h, s, d), jnp.bfloat16)
    k = jax.random.normal(ks[1], (b, kv, s, d), jnp.bfloat16)
    v = jax.random.normal(ks[2], (b, kv, s, d), jnp.bfloat16)

    only = os.environ.get("NOS_TPU_ATTN_ONLY", "")
    impls = [only] if only else ["splash", "flash", "xla"]
    for impl in impls:
        os.environ["NOS_TPU_ATTN_IMPL"] = impl
        eff = at.effective_impl(q.shape, k.shape)
        if eff != impl:
            print(json.dumps({"impl": impl, "skipped": f"dispatches {eff}"}))
            continue

        fwd = jax.jit(lambda q, k, v: at.attention(q, k, v, causal=True))

        def loss(q, k, v):
            return jnp.sum(at.attention(q, k, v, causal=True)
                           .astype(jnp.float32))

        grad = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))

        def phase(name):
            phase_marker(impl, name)

        try:
            phase("fwd_compile")
            t0 = time.perf_counter()
            out = fwd(q, k, v)
            host_fence(out)
            compile_fwd = time.perf_counter() - t0

            phase("fwd_timing")
            t0 = time.perf_counter()
            for _ in range(reps):
                out = fwd(q, k, v)
            host_fence(out)
            t_fwd = (time.perf_counter() - t0) / reps

            phase("bwd_compile")
            t0 = time.perf_counter()
            g = grad(q, k, v)
            host_fence(g[0])
            compile_bwd = time.perf_counter() - t0

            phase("bwd_timing")
            t0 = time.perf_counter()
            for _ in range(reps):
                g = grad(q, k, v)
            host_fence(g[0])
            t_bwd = (time.perf_counter() - t0) / reps
            phase("done")
        except Exception as e:
            print(json.dumps({"impl": impl,
                              "error": f"{type(e).__name__}: {e}"[:200]}))
            continue

        print(json.dumps({
            "impl": impl,
            "shape": f"b{b} h{h} kv{kv} s{s} d{d} causal bf16",
            "fwd_ms": round(t_fwd * 1e3, 2),
            "fwd_bwd_ms": round(t_bwd * 1e3, 2),
            "compile_fwd_s": round(compile_fwd, 1),
            "compile_bwd_s": round(compile_bwd, 1),
        }), flush=True)


def paged_decode_section(args):
    """Decode-step attention over a paged arena, one JSON line per
    (ctx, kv_dtype, impl) point. Shapes ride the flagship MODEL dims;
    every row decodes at pos = ctx - 1 (the worst-case full-context
    step the TPOT tail is made of)."""
    import jax
    import jax.numpy as jnp

    from nos_tpu.models.generate import _cached_attention
    from nos_tpu.ops import attention as at

    reps = args.reps
    b = args.paged_batch
    bs = args.paged_block
    h, hkv = MODEL["n_heads"], MODEL["n_kv_heads"]
    d = MODEL["d_model"] // h
    on_tpu = jax.default_backend() == "tpu"
    only = os.environ.get("NOS_TPU_PAGED_ONLY", "")
    if only and only not in PAGED_IMPLS:
        # fail fast: a typo'd isolation env would otherwise measure
        # the fallthrough path and emit a mislabeled point
        raise SystemExit(
            f"NOS_TPU_PAGED_ONLY must be one of {PAGED_IMPLS}, "
            f"got {only!r}")
    impls = [only] if only else list(PAGED_IMPLS)
    rng = jax.random.PRNGKey(0)

    def point(ctx, kv_dtype, impl):
        base = {"section": "paged_decode", "ctx": ctx,
                "kv_dtype": kv_dtype, "impl": impl,
                "shape": f"b{b} h{h} kv{hkv} d{d} bs{bs}"}
        if impl == "slot_static" and kv_dtype == "int8":
            return dict(base, skipped="int8 requires the paged arena "
                                      "(no slot-static scale storage)")
        os.environ["NOS_TPU_PAGED_KERNEL"] = \
            "1" if impl == "kernel" else "0"
        if impl == "kernel":
            eff = at.effective_paged_impl(d)
            if eff != "kernel":
                return dict(base, skipped=f"dispatches {eff}")
            if not on_tpu and not args.paged_interpret:
                return dict(base, skipped="interpret-only off TPU "
                                          "(--paged-interpret forces)")
        nb = ctx // bs
        ks = jax.random.split(rng, 4)
        q = jax.random.normal(ks[0], (b, h, 1, d), jnp.bfloat16)
        pos = jnp.full((b,), ctx - 1, jnp.int32)
        if impl == "slot_static":
            ck = jax.random.normal(ks[1], (b, hkv, ctx, d), jnp.bfloat16)
            cv = jax.random.normal(ks[2], (b, hkv, ctx, d), jnp.bfloat16)
            step = jax.jit(lambda q, ck, cv, pos: _cached_attention(
                q, ck, cv, pos[:, None], d ** -0.5))
            operands = (q, ck, cv, pos)
        else:
            nb_phys = b * nb + 1
            ka = jax.random.normal(
                ks[1], (nb_phys, hkv, bs, d), jnp.bfloat16)
            va = jax.random.normal(
                ks[2], (nb_phys, hkv, bs, d), jnp.bfloat16)
            table = (1 + jnp.arange(b * nb, dtype=jnp.int32)
                     ).reshape(b, nb)
            if kv_dtype == "int8":
                ka, kscale = at.quantize_kv(ka)
                va, vscale = at.quantize_kv(va)

                if impl == "kernel":
                    def step_fn(q, ka, va, ksc, vsc, table, pos):
                        return at.paged_decode_attention(
                            q, ka, va, table, pos,
                            k_scale=ksc, v_scale=vsc)
                else:
                    def step_fn(q, ka, va, ksc, vsc, table, pos):
                        gk = at.dequantize_kv(
                            at.paged_gather_kv(ka, table),
                            at.paged_gather_scale(ksc, table),
                            jnp.bfloat16)
                        gv = at.dequantize_kv(
                            at.paged_gather_kv(va, table),
                            at.paged_gather_scale(vsc, table),
                            jnp.bfloat16)
                        return _cached_attention(
                            q, gk, gv, pos[:, None], d ** -0.5)
                operands = (q, ka, va, kscale, vscale, table, pos)
            else:
                if impl == "kernel":
                    def step_fn(q, ka, va, table, pos):
                        return at.paged_decode_attention(
                            q, ka, va, table, pos)
                else:
                    def step_fn(q, ka, va, table, pos):
                        return _cached_attention(
                            q, at.paged_gather_kv(ka, table),
                            at.paged_gather_kv(va, table),
                            pos[:, None], d ** -0.5)
                operands = (q, ka, va, table, pos)
            step = jax.jit(step_fn)
        try:
            phase_marker(f"paged_{impl}", f"ctx{ctx}_{kv_dtype}_compile")
            t0 = time.perf_counter()
            out = step(*operands)
            host_fence(out)
            compile_s = time.perf_counter() - t0
            phase_marker(f"paged_{impl}", f"ctx{ctx}_{kv_dtype}_timing")
            t0 = time.perf_counter()
            for _ in range(reps):
                out = step(*operands)
            host_fence(out)
            step_ms = (time.perf_counter() - t0) / reps * 1e3
        except Exception as e:
            return dict(base, error=f"{type(e).__name__}: {e}"[:200])
        # bytes the formulation moves per step (the model the doc
        # carries): every impl reads the live KV once; the XLA paged
        # point ALSO writes + re-reads the gathered bf16 view (and for
        # int8, the materialized dequantized copy is that view)
        kv_bytes = 2 * b * hkv * ctx * d * (1 if kv_dtype == "int8"
                                            else 2)
        scale_bytes = 2 * b * hkv * ctx * 4 if kv_dtype == "int8" else 0
        view_bytes = 2 * b * hkv * ctx * d * 2
        traffic = kv_bytes + scale_bytes
        if impl == "xla":
            traffic += 2 * view_bytes          # write view + read back
        return dict(
            base,
            eff=("kernel" if impl == "kernel"
                 else "xla" if impl == "xla" else "slot_static"),
            interpret=bool(impl == "kernel" and not on_tpu),
            decode_step_ms=round(step_ms, 4),
            compile_s=round(compile_s, 2),
            model_bytes_per_step=traffic,
        )

    for ctx in [int(c) for c in args.paged_ctx.split(",") if c]:
        if ctx % bs:
            # a truncated paged arena vs a full-ctx slot-static cache
            # would be an unfair, mislabeled comparison — refuse the
            # point instead of silently rounding
            raise SystemExit(
                f"--paged-ctx {ctx} must be a multiple of "
                f"--paged-block {bs}")
        for kv_dtype in ("bf16", "int8"):
            for impl in impls:
                print(json.dumps(point(ctx, kv_dtype, impl)), flush=True)


def main(argv=None):
    args = parse_args(argv)
    sections = [s.strip() for s in args.sections.split(",") if s.strip()]
    if "attn" in sections:
        attn_section(args.reps)
    if "paged_decode" in sections:
        paged_decode_section(args)


if __name__ == "__main__":
    main()
