#!/usr/bin/env python3
"""Attention-kernel microbench, one JSON line per measured point.

Sections (--sections, default both):

- ``attn``: forward and forward+backward wall-clock for each
  dispatchable training-shape implementation (splash / legacy flash /
  XLA) at the flagship shape, so kernel choice and block-size sweeps
  are decided by measurement, not vibes.
- ``paged_decode``: the serving decode step over a PAGED arena —
  XLA-gather formulation vs the fused Pallas table-walk kernel
  (ops.attention.paged_decode_attention) vs the slot-static contiguous
  cache, across context lengths (--paged-ctx, default 1k/4k/16k) and
  kv dtypes bf16/int8. The XLA point materializes the gathered
  timeline (plus a dequantized copy for int8) exactly like
  forward_paged's escape hatch; the kernel point streams arena blocks
  in-kernel with dequant fused into the inner loop. S>1 window points
  (--paged-windows, default 4,5,8 — fused decode_steps, a draft_n=4
  verify burst, a prefix-hit suffix bucket) time the same comparison
  at the query widths spec decoding and fused decode actually
  dispatch; slot_static has no windowed serving path, so windows
  compare kernel vs gather only. Off-TPU the kernel only runs in
  interpret mode, which measures nothing — those points print as
  skipped unless --paged-interpret forces them (parity checks, not
  perf).
- ``spec_window_report``: one summary line per (window, kv_dtype) —
  max |kernel - gather| over a ragged-pos batch (every row at a
  different causal depth, the shape a spec verify burst actually has)
  plus the structural HBM byte model for both formulations. This is
  the kernel-vs-gather parity/bytes evidence behind the fleet
  --paged-kernel=on default; the smoke test pins that kernel bytes
  are strictly below gather bytes at every point.

Every emitted point is also collected into
``bench_logs/bench_attn.json`` (the artifact of record — the driver's
tail buffer has truncated stdout before), written before the final
summary line prints.

Timing fence is the host transfer (block_until_ready lies on 'axon' —
see bench_mfu.py).

Usage: python bench_attn.py [reps] [--sections attn,paged_decode]
                            [--paged-ctx 1024,4096,16384] ...
Env: NOS_TPU_SPLASH_* block-size overrides are honored
(ops/attention.py); NOS_TPU_ATTN_ONLY=<impl> restricts the attn
section to one implementation and NOS_TPU_PAGED_ONLY=<impl>
(xla|kernel|slot_static) does the same for paged_decode, so an
orchestrator can isolate each kernel in its own process (a wedged
Mosaic compile then kills one point, not the whole comparison — the
round-3 outage playbook).
"""
import argparse
import json
import os
import sys
import time

sys.path.insert(0, ".")

from bench import BATCH, MODEL, SEQ, phase_marker  # noqa: E402
from bench_mfu import host_fence  # noqa: E402

PAGED_IMPLS = ("xla", "kernel", "slot_static")
OUT_PATH = os.path.join("bench_logs", "bench_attn.json")

# every emitted point lands here too; main() writes the artifact after
# the sections run so a truncated stdout never loses the record
RESULTS = []


def emit(point):
    RESULTS.append(point)
    print(json.dumps(point), flush=True)


def parse_args(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("reps", nargs="?", type=int, default=10,
                    help="timed repetitions per point (default 10)")
    ap.add_argument("--sections", default="attn,paged_decode,"
                                          "spec_window_report",
                    help="comma list of sections to run: "
                         "attn,paged_decode,spec_window_report")
    ap.add_argument("--paged-ctx", default="1024,4096,16384",
                    help="paged_decode context lengths, comma list")
    ap.add_argument("--paged-batch", type=int, default=8,
                    help="paged_decode decode batch (rows)")
    ap.add_argument("--paged-block", type=int, default=128,
                    help="paged-KV block size in tokens")
    ap.add_argument("--paged-windows", default="4,5,8",
                    help="S>1 query-window widths for the windowed "
                         "paged points and the spec report (default "
                         "4 = fused decode_steps, 5 = draft_n=4 "
                         "verify burst, 8 = suffix-prefill bucket)")
    ap.add_argument("--paged-interpret", action="store_true",
                    help="run the Pallas kernel points in interpret "
                         "mode off-TPU (exactness probing; the timings "
                         "are meaningless)")
    return ap.parse_args(argv)


def attn_section(reps):
    import jax
    import jax.numpy as jnp

    from nos_tpu.ops import attention as at

    b, s = BATCH, SEQ
    h, kv = MODEL["n_heads"], MODEL["n_kv_heads"]
    d = MODEL["d_model"] // h
    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    q = jax.random.normal(ks[0], (b, h, s, d), jnp.bfloat16)
    k = jax.random.normal(ks[1], (b, kv, s, d), jnp.bfloat16)
    v = jax.random.normal(ks[2], (b, kv, s, d), jnp.bfloat16)

    only = os.environ.get("NOS_TPU_ATTN_ONLY", "")
    impls = [only] if only else ["splash", "flash", "xla"]
    for impl in impls:
        os.environ["NOS_TPU_ATTN_IMPL"] = impl
        eff = at.effective_impl(q.shape, k.shape)
        if eff != impl:
            emit({"impl": impl, "skipped": f"dispatches {eff}"})
            continue

        fwd = jax.jit(lambda q, k, v: at.attention(q, k, v, causal=True))

        def loss(q, k, v):
            return jnp.sum(at.attention(q, k, v, causal=True)
                           .astype(jnp.float32))

        grad = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))

        def phase(name):
            phase_marker(impl, name)

        try:
            phase("fwd_compile")
            t0 = time.perf_counter()
            out = fwd(q, k, v)
            host_fence(out)
            compile_fwd = time.perf_counter() - t0

            phase("fwd_timing")
            t0 = time.perf_counter()
            for _ in range(reps):
                out = fwd(q, k, v)
            host_fence(out)
            t_fwd = (time.perf_counter() - t0) / reps

            phase("bwd_compile")
            t0 = time.perf_counter()
            g = grad(q, k, v)
            host_fence(g[0])
            compile_bwd = time.perf_counter() - t0

            phase("bwd_timing")
            t0 = time.perf_counter()
            for _ in range(reps):
                g = grad(q, k, v)
            host_fence(g[0])
            t_bwd = (time.perf_counter() - t0) / reps
            phase("done")
        except Exception as e:
            emit({"impl": impl,
                  "error": f"{type(e).__name__}: {e}"[:200]})
            continue

        emit({
            "impl": impl,
            "shape": f"b{b} h{h} kv{kv} s{s} d{d} causal bf16",
            "fwd_ms": round(t_fwd * 1e3, 2),
            "fwd_bwd_ms": round(t_bwd * 1e3, 2),
            "compile_fwd_s": round(compile_fwd, 1),
            "compile_bwd_s": round(compile_bwd, 1),
        })


def paged_decode_section(args):
    """Decode-step attention over a paged arena, one JSON line per
    (ctx, kv_dtype, impl[, s]) point. Shapes ride the flagship MODEL
    dims; every row's window ends at pos ctx - 1 (the worst-case
    full-context step the TPOT tail is made of). S == 1 points compare
    all three impls; the --paged-windows S > 1 points compare kernel
    vs gather only (slot_static has no windowed serving path)."""
    import jax
    import jax.numpy as jnp

    from nos_tpu.models.generate import _cached_attention
    from nos_tpu.ops import attention as at

    reps = args.reps
    b = args.paged_batch
    bs = args.paged_block
    h, hkv = MODEL["n_heads"], MODEL["n_kv_heads"]
    d = MODEL["d_model"] // h
    on_tpu = jax.default_backend() == "tpu"
    only = os.environ.get("NOS_TPU_PAGED_ONLY", "")
    if only and only not in PAGED_IMPLS:
        # fail fast: a typo'd isolation env would otherwise measure
        # the fallthrough path and emit a mislabeled point
        raise SystemExit(
            f"NOS_TPU_PAGED_ONLY must be one of {PAGED_IMPLS}, "
            f"got {only!r}")
    impls = [only] if only else list(PAGED_IMPLS)
    rng = jax.random.PRNGKey(0)

    def point(ctx, kv_dtype, impl, s=1):
        base = {"section": "paged_decode", "ctx": ctx,
                "kv_dtype": kv_dtype, "impl": impl, "s": s,
                "shape": f"b{b} h{h} kv{hkv} s{s} d{d} bs{bs}"}
        if impl == "slot_static" and kv_dtype == "int8":
            return dict(base, skipped="int8 requires the paged arena "
                                      "(no slot-static scale storage)")
        if s >= ctx:
            return dict(base, skipped=f"window {s} needs ctx > {s}")
        os.environ["NOS_TPU_PAGED_KERNEL"] = \
            "1" if impl == "kernel" else "0"
        if impl == "kernel":
            eff = at.effective_paged_impl(d)
            if eff != "kernel":
                return dict(base, skipped=f"dispatches {eff}")
            if not on_tpu and not args.paged_interpret:
                return dict(base, skipped="interpret-only off TPU "
                                          "(--paged-interpret forces)")
        nb = ctx // bs
        ks = jax.random.split(rng, 4)
        q = jax.random.normal(ks[0], (b, h, s, d), jnp.bfloat16)
        # window base: query rows sit at pos..pos+s-1, the last at the
        # full-context frontier ctx - 1 (same tail step as s == 1)
        pos = jnp.full((b,), ctx - s, jnp.int32)

        def rows(pos):
            # per-row absolute query positions for the gather oracle
            return pos[:, None] + jnp.arange(s)[None, :]

        if impl == "slot_static":
            ck = jax.random.normal(ks[1], (b, hkv, ctx, d), jnp.bfloat16)
            cv = jax.random.normal(ks[2], (b, hkv, ctx, d), jnp.bfloat16)
            step = jax.jit(lambda q, ck, cv, pos: _cached_attention(
                q, ck, cv, rows(pos), d ** -0.5))
            operands = (q, ck, cv, pos)
        else:
            nb_phys = b * nb + 1
            ka = jax.random.normal(
                ks[1], (nb_phys, hkv, bs, d), jnp.bfloat16)
            va = jax.random.normal(
                ks[2], (nb_phys, hkv, bs, d), jnp.bfloat16)
            table = (1 + jnp.arange(b * nb, dtype=jnp.int32)
                     ).reshape(b, nb)
            if kv_dtype == "int8":
                ka, kscale = at.quantize_kv(ka)
                va, vscale = at.quantize_kv(va)

                if impl == "kernel":
                    def step_fn(q, ka, va, ksc, vsc, table, pos):
                        return at.paged_decode_attention(
                            q, ka, va, table, pos,
                            k_scale=ksc, v_scale=vsc)
                else:
                    def step_fn(q, ka, va, ksc, vsc, table, pos):
                        gk = at.dequantize_kv(
                            at.paged_gather_kv(ka, table),
                            at.paged_gather_scale(ksc, table),
                            jnp.bfloat16)
                        gv = at.dequantize_kv(
                            at.paged_gather_kv(va, table),
                            at.paged_gather_scale(vsc, table),
                            jnp.bfloat16)
                        return _cached_attention(
                            q, gk, gv, rows(pos), d ** -0.5)
                operands = (q, ka, va, kscale, vscale, table, pos)
            else:
                if impl == "kernel":
                    def step_fn(q, ka, va, table, pos):
                        return at.paged_decode_attention(
                            q, ka, va, table, pos)
                else:
                    def step_fn(q, ka, va, table, pos):
                        return _cached_attention(
                            q, at.paged_gather_kv(ka, table),
                            at.paged_gather_kv(va, table),
                            rows(pos), d ** -0.5)
                operands = (q, ka, va, table, pos)
            step = jax.jit(step_fn)
        tag = f"ctx{ctx}_{kv_dtype}" + (f"_s{s}" if s > 1 else "")
        try:
            phase_marker(f"paged_{impl}", f"{tag}_compile")
            t0 = time.perf_counter()
            out = step(*operands)
            host_fence(out)
            compile_s = time.perf_counter() - t0
            phase_marker(f"paged_{impl}", f"{tag}_timing")
            t0 = time.perf_counter()
            for _ in range(reps):
                out = step(*operands)
            host_fence(out)
            step_ms = (time.perf_counter() - t0) / reps * 1e3
        except Exception as e:
            return dict(base, error=f"{type(e).__name__}: {e}"[:200])
        # bytes the formulation moves per step (the model the doc
        # carries): every impl reads the live KV once; the XLA paged
        # point ALSO writes + re-reads the gathered bf16 view (and for
        # int8, the materialized dequantized copy is that view). The
        # view traffic is independent of s — a wider query window
        # amortizes it over s tokens, but the kernel pays none of it
        # at any width
        kv_bytes = 2 * b * hkv * ctx * d * (1 if kv_dtype == "int8"
                                            else 2)
        scale_bytes = 2 * b * hkv * ctx * 4 if kv_dtype == "int8" else 0
        view_bytes = 2 * b * hkv * ctx * d * 2
        traffic = kv_bytes + scale_bytes
        if impl == "xla":
            traffic += 2 * view_bytes          # write view + read back
        return dict(
            base,
            eff=("kernel" if impl == "kernel"
                 else "xla" if impl == "xla" else "slot_static"),
            interpret=bool(impl == "kernel" and not on_tpu),
            decode_step_ms=round(step_ms, 4),
            compile_s=round(compile_s, 2),
            model_bytes_per_step=traffic,
        )

    windows = [int(w) for w in args.paged_windows.split(",") if w]
    for ctx in [int(c) for c in args.paged_ctx.split(",") if c]:
        if ctx % bs:
            # a truncated paged arena vs a full-ctx slot-static cache
            # would be an unfair, mislabeled comparison — refuse the
            # point instead of silently rounding
            raise SystemExit(
                f"--paged-ctx {ctx} must be a multiple of "
                f"--paged-block {bs}")
        for kv_dtype in ("bf16", "int8"):
            for impl in impls:
                emit(point(ctx, kv_dtype, impl))
        # S>1 windows: the verify-burst / fused-decode / suffix shapes
        # — kernel vs the gather oracle only
        for s in windows:
            for kv_dtype in ("bf16", "int8"):
                for impl in impls:
                    if impl == "slot_static":
                        continue
                    emit(point(ctx, kv_dtype, impl, s))


def spec_window_report_section(args):
    """Kernel-vs-gather spec-grid report: for each (window, kv_dtype)
    the max |kernel - gather| over a RAGGED-pos batch (every row's
    window ends at a different causal depth — the shape a speculative
    verify burst over mixed-age slots actually has) plus the
    structural HBM byte model of both formulations. One JSON line per
    point; the smoke test pins kernel bytes strictly below gather
    bytes and parity within the fuzz tolerance."""
    import jax
    import jax.numpy as jnp

    from nos_tpu.models.generate import _cached_attention
    from nos_tpu.ops import attention as at

    b = args.paged_batch
    bs = args.paged_block
    h, hkv = MODEL["n_heads"], MODEL["n_kv_heads"]
    d = MODEL["d_model"] // h
    on_tpu = jax.default_backend() == "tpu"
    eff = at.effective_paged_impl(d)
    if eff != "kernel":
        emit({"section": "spec_window_report",
              "skipped": f"dispatches {eff}"})
        return
    if not on_tpu and not args.paged_interpret:
        emit({"section": "spec_window_report",
              "skipped": "interpret-only off TPU "
                         "(--paged-interpret forces)"})
        return
    # smallest requested ctx: parity is shape-generic and interpret
    # mode is O(slow), so the report probes the cheapest arena
    ctx = min(int(c) for c in args.paged_ctx.split(",") if c)
    nb = ctx // bs
    nb_phys = b * nb + 1
    for s in [int(w) for w in args.paged_windows.split(",") if w]:
        if s >= ctx:
            emit({"section": "spec_window_report", "s": s,
                  "skipped": f"window {s} needs ctx > {s}"})
            continue
        for kv_dtype in ("bf16", "int8"):
            ks = jax.random.split(
                jax.random.fold_in(jax.random.PRNGKey(1), s), 3)
            q = jax.random.normal(ks[0], (b, h, s, d), jnp.bfloat16)
            ka = jax.random.normal(
                ks[1], (nb_phys, hkv, bs, d), jnp.bfloat16)
            va = jax.random.normal(
                ks[2], (nb_phys, hkv, bs, d), jnp.bfloat16)
            table = (1 + jnp.arange(b * nb, dtype=jnp.int32)
                     ).reshape(b, nb)
            # ragged window bases: a linear ramp from 0 to the deepest
            # legal base, so dead-tail elision and per-row masking are
            # both on the hook
            pos = jnp.asarray(
                [(ctx - s) * i // max(1, b - 1) for i in range(b)],
                jnp.int32)
            rows = pos[:, None] + jnp.arange(s)[None, :]
            if kv_dtype == "int8":
                ka_q, ksc = at.quantize_kv(ka)
                va_q, vsc = at.quantize_kv(va)
                got = at.paged_decode_attention(
                    q, ka_q, va_q, table, pos, k_scale=ksc, v_scale=vsc)
                gk = at.dequantize_kv(
                    at.paged_gather_kv(ka_q, table),
                    at.paged_gather_scale(ksc, table), jnp.bfloat16)
                gv = at.dequantize_kv(
                    at.paged_gather_kv(va_q, table),
                    at.paged_gather_scale(vsc, table), jnp.bfloat16)
            else:
                got = at.paged_decode_attention(q, ka, va, table, pos)
                gk = at.paged_gather_kv(ka, table)
                gv = at.paged_gather_kv(va, table)
            want = _cached_attention(q, gk, gv, rows, d ** -0.5)
            diff = float(jnp.max(jnp.abs(
                got.astype(jnp.float32) - want.astype(jnp.float32))))
            kv_bytes = 2 * b * hkv * ctx * d * (1 if kv_dtype == "int8"
                                                else 2)
            scale_bytes = (2 * b * hkv * ctx * 4
                           if kv_dtype == "int8" else 0)
            view_bytes = 2 * b * hkv * ctx * d * 2
            kernel_bytes = kv_bytes + scale_bytes
            gather_bytes = kernel_bytes + 2 * view_bytes
            emit({
                "section": "spec_window_report", "s": s, "ctx": ctx,
                "kv_dtype": kv_dtype,
                "shape": f"b{b} h{h} kv{hkv} s{s} d{d} bs{bs}",
                "max_abs_diff": diff,
                "kernel_bytes": kernel_bytes,
                "gather_bytes": gather_bytes,
                "bytes_ratio": round(gather_bytes / kernel_bytes, 2),
            })


def main(argv=None):
    args = parse_args(argv)
    del RESULTS[:]            # repeated main() calls (tests) start clean
    sections = [s.strip() for s in args.sections.split(",") if s.strip()]
    if "attn" in sections:
        attn_section(args.reps)
    if "paged_decode" in sections:
        paged_decode_section(args)
    if "spec_window_report" in sections:
        spec_window_report_section(args)
    os.makedirs(os.path.dirname(OUT_PATH), exist_ok=True)
    with open(OUT_PATH, "w") as f:
        json.dump({"sections": sections, "points": RESULTS}, f, indent=2)
    print(json.dumps({"artifact": OUT_PATH, "points": len(RESULTS)}),
          flush=True)


if __name__ == "__main__":
    main()
