"""The hardware-queue babysitter machine itself (hack/bench_babysit.py):
queue execution, gating, requeue attribution, and the incremental
artifacts (landed.json + the bench_best.json pointer bench.py adopts).
The real tunnel can be down for a whole round — the machine must be
provably correct before a rare window spends itself on it."""
import importlib.util
import json
import os


def load_bb(tmp_path):
    spec = importlib.util.spec_from_file_location(
        "bb_under_test", os.path.join(os.path.dirname(__file__), "..",
                                      "hack", "bench_babysit.py"))
    bb = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bb)
    bb.LOGDIR = str(tmp_path)
    bb.PROBE_RETRY_WAIT_S = 0.01
    return bb


def _item(name, code, requires=None, timeout=30):
    return (name, ["-c", code], {}, timeout, requires)


OK_MFU = ("import json; print(json.dumps({'mfu_pct': 41.0, 'batch': 8, "
          "'remat_policy': 'full', 'attn_impl': 'flash'}))")
BETTER_MFU = ("import json; print(json.dumps({'mfu_pct': 43.5, 'batch': 16, "
              "'remat_policy': 'except_mlp', 'loss_chunk': 512, "
              "'attn_impl': 'flash'}))")


def test_queue_runs_gates_and_lands_incrementally(tmp_path, monkeypatch):
    bb = load_bb(tmp_path)
    monkeypatch.setattr(bb, "probe", lambda: True)
    queue = [
        _item("parity_flash", "print('{\"max_abs_diff\": 0.01}')"),
        _item("mfu_a", OK_MFU, requires="parity_flash"),
        _item("mfu_b", BETTER_MFU, requires="parity_flash"),
        _item("parity_splash", "import sys; sys.exit(1)"),     # gate FAILS
        _item("mfu_splash", OK_MFU, requires="parity_splash"),  # must skip
    ]
    queue = [(n, a, e, t, r, 0) for n, a, e, t, r in queue]
    summary = {"items": {}}
    bb.run_queue(queue, summary, lambda extra=None: None)

    assert summary["items"]["parity_flash"] == "ok"
    assert summary["items"]["mfu_a"] == "ok"
    assert summary["items"]["mfu_b"] == "ok"
    assert summary["items"]["parity_splash"] == "rc=1"
    assert summary["items"]["mfu_splash"].startswith("skipped: gate")

    # incremental artifacts landed DURING the queue, not only at drain
    landed = json.load(open(tmp_path / "landed.json"))
    assert landed["items"]["mfu_b"]["mfu_pct"] == 43.5
    assert "mfu_splash" not in landed["items"]
    best = json.loads(open(tmp_path / "bench_best.json").readline())
    assert best["winning_config"] == {
        "attn_impl": "flash", "batch": 16, "remat_policy": "except_mlp",
        "loss_chunk": 512, "mfu_pct": 43.5}


def test_tunnel_death_requeues_at_head(tmp_path, monkeypatch):
    bb = load_bb(tmp_path)
    # item times out; post-mortem probe says tunnel DEAD -> requeue at
    # head; second attempt (tunnel back) succeeds
    probes = iter([True,          # pre-item probe, attempt 1
                   False,         # post-timeout attribution: tunnel died
                   True,          # pre-item probe, attempt 2
                   ])
    monkeypatch.setattr(bb, "probe", lambda: next(probes, True))
    calls = {"n": 0}
    real_run = bb.run_item

    def flaky_run(name, argv, env, timeout_s, attempt):
        calls["n"] += 1
        if calls["n"] == 1:
            return "timeout"
        return real_run(name, argv, env, timeout_s, attempt)

    monkeypatch.setattr(bb, "run_item", flaky_run)
    queue = [(n, a, e, t, r, 0) for n, a, e, t, r in
             [_item("mfu_x", OK_MFU)]]
    summary = {"items": {}}
    bb.run_queue(queue, summary, lambda extra=None: None)
    assert summary["items"]["mfu_x"] == "ok"
    assert calls["n"] == 2


def test_wedged_item_with_live_tunnel_is_failed_not_requeued(
        tmp_path, monkeypatch):
    bb = load_bb(tmp_path)
    monkeypatch.setattr(bb, "probe", lambda: True)   # tunnel alive
    monkeypatch.setattr(bb, "run_item",
                        lambda *a, **k: "timeout")
    queue = [(n, a, e, t, r, 0) for n, a, e, t, r in
             [_item("mfu_wedge", OK_MFU)]]
    summary = {"items": {}}
    bb.run_queue(queue, summary, lambda extra=None: None)
    assert summary["items"]["mfu_wedge"] == "failed: wedged with tunnel up"


def test_select_best_ignores_non_ok_and_non_mfu(tmp_path):
    bb = load_bb(tmp_path)
    (tmp_path / "mfu_good.out").write_text(
        json.dumps({"mfu_pct": 40.0, "batch": 8,
                    "remat_policy": "full", "attn_impl": "flash"}) + "\n")
    (tmp_path / "mfu_failed.out").write_text(
        json.dumps({"mfu_pct": 99.0}) + "\n")
    (tmp_path / "decode.out").write_text(
        json.dumps({"mfu_pct": 98.0}) + "\n")
    best = bb.select_best({"items": {
        "mfu_good": "ok", "mfu_failed": "rc=1", "decode": "ok"}})
    assert best["mfu_pct"] == 40.0
