"""Accept-reject speculative sampling (models/speculative.py,
temperature > 0): every emitted token must be distributed EXACTLY as
target-only sampling — verified against the analytically computed target
distribution, not another sampler.

Reference parity note: the reference repo has no generation path; this
is the workload plane's exactness bar (SURVEY §2.7), mirroring the
greedy bit-exactness suite in test_speculative.py.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from nos_tpu.models import transformer as tfm
from nos_tpu.models.generate import _truncate_logits, generate
from nos_tpu.models.speculative import speculative_generate

VOCAB = 13          # small vocab -> tight empirical-distribution test


def cfg_kw(**kw):
    base = dict(vocab=VOCAB, d_model=16, n_layers=2, n_heads=2, d_ff=32,
                max_seq=64, dtype=jnp.float32)
    base.update(kw)
    return tfm.TransformerConfig(**base)


TARGET = cfg_kw()
DRAFT = cfg_kw(d_model=8, n_layers=1, d_ff=16)
PROMPT_ROW = [1, 7, 3]


# Lazily built, module-scoped: a module-level init_params here runs at
# pytest COLLECTION time (imports happen for every selected-or-not run)
# and its device buffers then sit live under the entire suite — enough
# native pressure on this toolchain to help tip later allocation-heavy
# modules (orbax async saves in test_trainer) into native crashes.
@pytest.fixture(scope="module")
def PARAMS():
    return tfm.init_params(jax.random.PRNGKey(0), TARGET)


@pytest.fixture(scope="module")
def DRAFT_P():
    return tfm.init_params(jax.random.PRNGKey(9), DRAFT)


def exact_next_dist(params, cfg, prompt_row, temperature, top_k=0,
                    top_p=0.0):
    """The closed-form distribution generate() samples the next token
    from: softmax of the tempered, truncated last-position logits."""
    from nos_tpu.models.generate import forward_with_cache, init_cache

    prompt = jnp.asarray([prompt_row], jnp.int32)
    cache = init_cache(cfg, 1, cfg.max_seq)
    logits, _ = forward_with_cache(params, cfg, prompt, cache)
    t = logits[0, -1] / temperature
    return np.asarray(jax.nn.softmax(_truncate_logits(t, top_k, top_p)))


def spec_first_token_counts(params, draft_p, draft_cfg, temperature,
                            top_k=0, top_p=0.0, batches=8, rows=256):
    """Empirical first-token distribution from speculative sampling:
    ``rows`` identical prompts per call (independent streams), several
    calls with fresh keys."""
    prompt = jnp.tile(jnp.asarray([PROMPT_ROW], jnp.int32), (rows, 1))
    counts = np.zeros(VOCAB)
    for i in range(batches):
        out = speculative_generate(
            params, TARGET, draft_p, draft_cfg, prompt, 1, n_draft=4,
            temperature=temperature, top_k=top_k, top_p=top_p,
            rng=jax.random.PRNGKey(100 + i))
        toks = np.asarray(out[:, len(PROMPT_ROW)])
        counts += np.bincount(toks, minlength=VOCAB)
    return counts / counts.sum()


def tv(a, b):
    return 0.5 * float(np.abs(np.asarray(a) - np.asarray(b)).sum())


def test_distribution_matches_target_bad_draft(PARAMS, DRAFT_P):
    """Draft disagrees often (both accept and reject paths hot): the
    emitted-token distribution must still be the target's, exactly."""
    p_exact = exact_next_dist(PARAMS, TARGET, PROMPT_ROW, 1.0)
    freq = spec_first_token_counts(PARAMS, DRAFT_P, DRAFT, 1.0)
    assert tv(freq, p_exact) < 0.07, (freq, p_exact)


def test_distribution_matches_target_perfect_draft(PARAMS):
    """Draft == target: acceptance prob 1 everywhere; still the target
    distribution (and the residual fallback must not fire nonsense)."""
    p_exact = exact_next_dist(PARAMS, TARGET, PROMPT_ROW, 0.7)
    freq = spec_first_token_counts(PARAMS, PARAMS, TARGET, 0.7)
    assert tv(freq, p_exact) < 0.07


def test_distribution_matches_under_top_k_top_p(PARAMS, DRAFT_P):
    """Truncation applies to draft and target alike; emitted tokens keep
    the truncated target distribution and never leave its support."""
    p_exact = exact_next_dist(PARAMS, TARGET, PROMPT_ROW, 1.0,
                              top_k=5, top_p=0.9)
    freq = spec_first_token_counts(PARAMS, DRAFT_P, DRAFT, 1.0,
                                   top_k=5, top_p=0.9)
    assert np.all(freq[p_exact == 0.0] == 0.0), "left the nucleus"
    assert tv(freq, p_exact) < 0.07


def test_multi_token_stays_in_truncated_support(PARAMS, DRAFT_P):
    """Over a longer sampled generation every token must lie in the
    target's truncated support given its own prefix (teacher-forced
    replay)."""
    from nos_tpu.models.generate import forward_with_cache, init_cache

    prompt = jnp.asarray([PROMPT_ROW, [2, 2, 5]], jnp.int32)
    out = speculative_generate(
        PARAMS, TARGET, DRAFT_P, DRAFT, prompt, 8, n_draft=3,
        temperature=0.8, top_k=4, rng=jax.random.PRNGKey(5))
    out_np = np.asarray(out)
    b, total = out_np.shape
    cache = init_cache(TARGET, b, TARGET.max_seq)
    logits, _ = forward_with_cache(PARAMS, TARGET, out, cache)
    for pos in range(prompt.shape[1] - 1, total - 1):
        step = logits[:, pos] / 0.8
        allowed = np.asarray(_truncate_logits(step, 4, 0.0))
        for r in range(b):
            tok = out_np[r, pos + 1]
            assert allowed[r, tok] > np.finfo(np.float32).min, (
                f"row {r} pos {pos + 1}: token {tok} outside top-4")


def test_rng_required_and_param_validation(PARAMS, DRAFT_P):
    prompt = jnp.asarray([PROMPT_ROW], jnp.int32)
    with pytest.raises(ValueError, match="rng"):
        speculative_generate(PARAMS, TARGET, DRAFT_P, DRAFT, prompt, 4,
                             temperature=0.5)
    with pytest.raises(ValueError, match="top_k/top_p"):
        speculative_generate(PARAMS, TARGET, DRAFT_P, DRAFT, prompt, 4,
                             top_k=3)
    with pytest.raises(ValueError, match="top_p"):
        speculative_generate(PARAMS, TARGET, DRAFT_P, DRAFT, prompt, 4,
                             temperature=0.5, top_p=1.5,
                             rng=jax.random.PRNGKey(0))


def test_sampling_is_deterministic_given_key(PARAMS, DRAFT_P):
    prompt = jnp.asarray([PROMPT_ROW], jnp.int32)
    a = speculative_generate(PARAMS, TARGET, DRAFT_P, DRAFT, prompt, 6,
                             temperature=0.9, rng=jax.random.PRNGKey(3))
    b = speculative_generate(PARAMS, TARGET, DRAFT_P, DRAFT, prompt, 6,
                             temperature=0.9, rng=jax.random.PRNGKey(3))
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
