"""tpuagent: plan differ, native layer (real C++ build), reporter/actuator
(model: reference migagent plan_test.go 617 LoC + reporter/actuator int
tests)."""
import json
import os

import pytest

from nos_tpu import constants
from nos_tpu.agents.plan import BoardState, PartitionConfigPlan
from nos_tpu.agents.tpu_native import MockTpuClient, TpuNativeClient, load_native
from nos_tpu.agents.tpuagent import TpuAgent
from nos_tpu.kube import ApiServer, Manager
from nos_tpu.kube.client import Client
from nos_tpu.kube.objects import (
    Container,
    Node,
    NodeStatus,
    ObjectMeta,
    Pod,
    PodSpec,
    PodStatus,
)
from nos_tpu.tpu.slice import Profile

P11, P22, P24 = Profile(1, 1), Profile(2, 2), Profile(2, 4)


# ---------------------------------------------------------------------------
# plan differ
# ---------------------------------------------------------------------------

def test_plan_noop_when_equal():
    plan = PartitionConfigPlan(
        desired={0: {P11: 4, P22: 1}},
        actual={0: BoardState(geometry={P11: 4, P22: 1})},
    )
    assert plan.is_empty() and plan.is_valid()
    assert plan.summary() == "no-op"


def test_plan_creates_and_deletes():
    plan = PartitionConfigPlan(
        desired={0: {P11: 8}},
        actual={0: BoardState(geometry={P24: 1})},
    )
    kinds = {(op.kind, op.profile, op.quantity) for op in plan.ops}
    assert ("create", P11, 8) in kinds
    assert ("delete", P24, 1) in kinds
    assert plan.is_valid()


def test_plan_refuses_to_delete_used():
    plan = PartitionConfigPlan(
        desired={0: {P11: 8}},
        actual={0: BoardState(geometry={P22: 2}, used={P22: 1})},
    )
    assert not plan.is_valid()
    assert "cannot delete" in plan.errors[0]


def test_plan_partial_delete_of_free_is_valid():
    plan = PartitionConfigPlan(
        desired={0: {P22: 1, P11: 4}},
        actual={0: BoardState(geometry={P22: 2}, used={P22: 1})},
    )
    assert plan.is_valid()


def test_plan_zero_quantities_ignored():
    plan = PartitionConfigPlan(
        desired={0: {P11: 0, P24: 1}},
        actual={0: BoardState(geometry={P24: 1})},
    )
    assert plan.is_empty()


# ---------------------------------------------------------------------------
# native layer (builds the real C++ library)
# ---------------------------------------------------------------------------

@pytest.fixture
def native(tmp_path, monkeypatch):
    lib = load_native()
    if lib is None:
        pytest.skip("native toolchain unavailable")
    monkeypatch.setenv("NOS_TPU_STATE_FILE", str(tmp_path / "partition.json"))
    monkeypatch.setenv("NOS_TPU_CHIP_COUNT", "8")
    return TpuNativeClient(lib)


def test_native_chip_count_and_health(native, monkeypatch):
    assert native.chip_count() == 8
    assert native.chip_healthy(0)
    assert native.chip_healthy(7)
    assert not native.chip_healthy(8)
    assert not native.chip_healthy(-1)
    monkeypatch.setenv("NOS_TPU_UNHEALTHY_CHIPS", "2,5")
    assert not native.chip_healthy(2)
    assert not native.chip_healthy(5)
    assert native.chip_healthy(3)


def test_native_metadata_env_and_file(native, monkeypatch, tmp_path):
    monkeypatch.setenv("NOS_TPU_META_ACCELERATOR_TYPE", "v5litepod-8")
    assert native.metadata("accelerator-type") == "v5litepod-8"
    assert native.accelerator_type() == "v5litepod-8"
    env_file = tmp_path / "tpu-env"
    env_file.write_text("TPU_TOPOLOGY = '2x4'\nWORKER_ID=3\n")
    monkeypatch.setenv("NOS_TPU_ENV_FILE", str(env_file))
    assert native.metadata("TPU_TOPOLOGY") == "2x4"
    assert native.worker_id() == 3
    assert native.metadata("missing-key") is None


def test_native_partition_roundtrip(native):
    boards = {0: {P11: 4, P22: 1}}
    native.apply_partition(boards, "plan-7")
    got, plan = native.read_partition()
    assert got == boards
    assert plan == "plan-7"
    native.clear_partition()
    got, plan = native.read_partition()
    assert got == {} and plan == ""


def test_native_partition_survives_reload(native, tmp_path):
    native.apply_partition({0: {P24: 1}}, "p1")
    fresh = TpuNativeClient(load_native())
    got, plan = fresh.read_partition()
    assert got == {0: {P24: 1}} and plan == "p1"


def test_native_partition_atomic_file(native, tmp_path):
    native.apply_partition({0: {P11: 8}}, "p2")
    raw = json.loads((tmp_path / "partition.json").read_text())
    assert raw["plan"] == "p2"
    assert raw["boards"]["0"]["1x1"] == 8
    assert not os.path.exists(tmp_path / "partition.json.tmp")


# ---------------------------------------------------------------------------
# agent reporter/actuator against the API server
# ---------------------------------------------------------------------------

def v5e_node(name="v5e-0", annotations=None):
    return Node(
        metadata=ObjectMeta(
            name=name,
            labels={
                constants.LABEL_TPU_ACCELERATOR: "tpu-v5-lite-podslice",
                constants.LABEL_TPU_TOPOLOGY: "2x4",
                constants.LABEL_PARTITIONING: constants.PARTITIONING_SUBSLICING,
            },
            annotations=annotations or {},
        ),
        status=NodeStatus(capacity={"cpu": 96}, allocatable={"cpu": 96}),
    )


def agent_rig(annotations=None, mock=None):
    server = ApiServer()
    mgr = Manager(server)
    tpu = mock or MockTpuClient(chips=8)
    agent = TpuAgent("v5e-0", tpu, report_interval_s=None)
    for c in agent.controllers():
        mgr.add_controller(c)
    server.create(v5e_node(annotations=annotations))
    return server, mgr, tpu, agent


def test_actuator_applies_spec_and_reporter_reports():
    server, mgr, tpu, agent = agent_rig(annotations={
        "nos.ai/spec-tpu-0-1x1": "4",
        "nos.ai/spec-tpu-0-2x2": "1",
        constants.ANNOTATION_PARTITIONING_PLAN: "plan-1",
    })
    mgr.run_until_idle()
    boards, plan = tpu.read_partition()
    assert boards == {0: {P11: 4, P22: 1}}
    assert plan == "plan-1"
    node = server.get("Node", "v5e-0")
    assert node.metadata.annotations["nos.ai/status-tpu-0-1x1-free"] == "4"
    assert node.metadata.annotations["nos.ai/status-tpu-0-2x2-free"] == "1"
    assert node.metadata.annotations[constants.ANNOTATION_REPORTED_PARTITIONING_PLAN] == "plan-1"
    assert node.status.allocatable["nos.ai/tpu-slice-1x1"] == 4


def test_reporter_counts_used_slices_from_bound_pods():
    server, mgr, tpu, agent = agent_rig(annotations={
        "nos.ai/spec-tpu-0-1x1": "4",
        constants.ANNOTATION_PARTITIONING_PLAN: "p1",
    })
    mgr.run_until_idle()
    server.create(Pod(
        metadata=ObjectMeta(name="user", namespace="team-a"),
        spec=PodSpec(containers=[Container(requests={"nos.ai/tpu-slice-1x1": 2})],
                     node_name="v5e-0"),
        status=PodStatus(phase="Running"),
    ))
    mgr.run_until_idle()
    node = server.get("Node", "v5e-0")
    assert node.metadata.annotations["nos.ai/status-tpu-0-1x1-used"] == "2"
    assert node.metadata.annotations["nos.ai/status-tpu-0-1x1-free"] == "2"


def test_actuator_refuses_to_destroy_used_slices():
    server, mgr, tpu, agent = agent_rig(annotations={
        "nos.ai/spec-tpu-0-2x2": "2",
        constants.ANNOTATION_PARTITIONING_PLAN: "p1",
    })
    mgr.run_until_idle()
    # a pod uses one 2x2 slice
    server.create(Pod(
        metadata=ObjectMeta(name="user", namespace="team-a"),
        spec=PodSpec(containers=[Container(requests={"nos.ai/tpu-slice-2x2": 1})],
                     node_name="v5e-0"),
        status=PodStatus(phase="Running"),
    ))
    mgr.run_until_idle()
    # a hostile plan wants to wipe the board to 8x1x1
    def bad_spec(n):
        n.metadata.annotations.pop("nos.ai/spec-tpu-0-2x2")
        n.metadata.annotations["nos.ai/spec-tpu-0-1x1"] = "8"
        n.metadata.annotations[constants.ANNOTATION_PARTITIONING_PLAN] = "p2"
    server.patch("Node", "v5e-0", "", bad_spec)
    mgr.run_until_idle()
    boards, plan = tpu.read_partition()
    assert boards == {0: {P22: 2}}     # untouched
    assert plan == "p1"


def test_agent_ignores_other_nodes():
    server, mgr, tpu, agent = agent_rig()
    other = v5e_node("other-node", annotations={
        "nos.ai/spec-tpu-0-1x1": "8",
        constants.ANNOTATION_PARTITIONING_PLAN: "px",
    })
    server.create(other)
    mgr.run_until_idle()
    boards, _ = tpu.read_partition()
    assert boards == {}               # agent only acts on its own node


def test_agent_startup_resume_from_persisted_state():
    tpu = MockTpuClient(chips=8)
    tpu.apply_partition({0: {P24: 1}}, "old-plan")
    server, mgr, tpu, agent = agent_rig(mock=tpu)
    agent.startup_cleanup(Manager(server).client)
    mgr.run_until_idle()
    node = server.get("Node", "v5e-0")
    # reporter re-published reality from persisted state
    assert node.metadata.annotations["nos.ai/status-tpu-0-2x4-free"] == "1"
    assert node.metadata.annotations[constants.ANNOTATION_REPORTED_PARTITIONING_PLAN] == "old-plan"


def test_native_decode_rejects_bad_board_key(native, tmp_path):
    from nos_tpu.agents.tpu_native import TpuClientError

    (tmp_path / "partition.json").write_text('{"boards": {"abc": {}}}')
    with pytest.raises(TpuClientError):
        native.read_partition()


# ---------------------------------------------------------------------------
# configured-deployment guard: NOS_TPU_NATIVE_LIB must never silently fall
# back to the mock device layer
# ---------------------------------------------------------------------------
def test_missing_configured_native_lib_raises(monkeypatch):
    from nos_tpu.agents.tpu_native import TpuClientError, _build_native
    monkeypatch.setenv("NOS_TPU_NATIVE_LIB", "/nonexistent/libtpuagent.so")
    with pytest.raises(TpuClientError):
        _build_native()


def test_unloadable_configured_native_lib_raises(monkeypatch, tmp_path):
    from nos_tpu.agents.tpu_native import TpuClientError
    bogus = tmp_path / "libtpuagent.so"
    bogus.write_bytes(b"not an ELF shared object")
    monkeypatch.setenv("NOS_TPU_NATIVE_LIB", str(bogus))
    with pytest.raises(TpuClientError):
        load_native()


def test_cmd_build_does_not_mask_configured_lib_error(monkeypatch):
    from nos_tpu.agents.tpu_native import TpuClientError
    from nos_tpu.cmd import tpuagent as agent_cmd
    monkeypatch.setenv("NOS_TPU_NATIVE_LIB", "/nonexistent/libtpuagent.so")
    with pytest.raises(TpuClientError):
        agent_cmd.build(ApiServer(), "n0")


# ---------------------------------------------------------------------------
# failure detection: chip health -> annotations + allocatable
# ---------------------------------------------------------------------------

def unhealthy_rig(unhealthy):
    server = ApiServer()
    mgr = Manager(server)
    tpu = MockTpuClient(chips=8, unhealthy=set(unhealthy))
    agent = TpuAgent("v5e-0", tpu, report_interval_s=None)
    for c in agent.controllers():
        mgr.add_controller(c)
    node = v5e_node()
    node.status.capacity["google.com/tpu"] = 8
    node.status.allocatable["google.com/tpu"] = 8
    server.create(node)
    return server, mgr, tpu


def test_reporter_surfaces_unhealthy_chips():
    server, mgr, tpu = unhealthy_rig({1, 5})
    mgr.run_until_idle()
    node = server.get("Node", "v5e-0")
    assert node.metadata.annotations[
        constants.ANNOTATION_UNHEALTHY_CHIPS] == "1,5"
    # unpartitioned host: allocatable shrinks by the unhealthy count
    assert node.status.allocatable["google.com/tpu"] == 6


def test_reporter_restores_allocatable_when_chips_heal():
    server, mgr, tpu = unhealthy_rig({0})
    mgr.run_until_idle()
    assert server.get("Node", "v5e-0").status.allocatable["google.com/tpu"] == 7
    tpu.unhealthy = set()
    # re-trigger a report (idempotent recompute from capacity)
    Client(server).patch("Node", "v5e-0", "",
                         lambda n: n.metadata.labels.update({"poke": "1"}))
    mgr.run_until_idle()
    node = server.get("Node", "v5e-0")
    assert node.status.allocatable["google.com/tpu"] == 8
    assert constants.ANNOTATION_UNHEALTHY_CHIPS not in node.metadata.annotations


# ---------------------------------------------------------------------------
# GCE metadata-server HTTP client (native, VERDICT r2 missing #4)
# ---------------------------------------------------------------------------

class _MetaHandler:
    """Stand-in GCE metadata server: real HTTP over a real socket, hit by
    the C client in libtpuagent (not by python)."""

    attrs = {
        "accelerator-type": "v5litepod-8",
        "tpu-env": "ACCELERATOR_TYPE: 'v5litepod-8'",
    }


@pytest.fixture
def meta_server(monkeypatch):
    import threading
    from http.server import BaseHTTPRequestHandler, HTTPServer

    class Handler(BaseHTTPRequestHandler):
        def log_message(self, fmt, *args):
            pass

        def do_GET(self):
            if self.headers.get("Metadata-Flavor") != "Google":
                self.send_response(403)
                self.end_headers()
                return
            prefix = "/computeMetadata/v1/instance/attributes/"
            if self.path.startswith(prefix):
                key = self.path[len(prefix):]
                if key in _MetaHandler.attrs:
                    body = _MetaHandler.attrs[key].encode()
                    self.send_response(200)
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                    return
            self.send_response(404)
            self.send_header("Content-Length", "0")
            self.end_headers()

    httpd = HTTPServer(("127.0.0.1", 0), Handler)
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    host, port = httpd.server_address[:2]
    monkeypatch.setenv("NOS_TPU_METADATA_SERVER", f"{host}:{port}")
    monkeypatch.delenv("NOS_TPU_ENV_FILE", raising=False)
    monkeypatch.delenv("NOS_TPU_META_ACCELERATOR_TYPE", raising=False)
    yield httpd
    httpd.shutdown()
    httpd.server_close()


def test_metadata_http_get(native, meta_server):
    got = native.metadata_http("instance/attributes/accelerator-type")
    assert got == "v5litepod-8"


def test_metadata_http_missing_key_is_none(native, meta_server):
    assert native.metadata_http("instance/attributes/nope") is None


def test_metadata_falls_through_to_http(native, meta_server):
    # no env var, no env file -> the native lookup reaches the (real HTTP)
    # metadata server, the production path on a TPU VM
    assert native.metadata("accelerator-type") == "v5litepod-8"


def test_metadata_env_file_still_wins_over_http(native, meta_server, tmp_path):
    env_file = tmp_path / "tpu-env"
    env_file.write_text("accelerator-type = 'v4-16'\n")
    os.environ["NOS_TPU_ENV_FILE"] = str(env_file)
    try:
        assert native.metadata("accelerator-type") == "v4-16"
    finally:
        del os.environ["NOS_TPU_ENV_FILE"]


def test_metadata_http_unreachable_server(native, monkeypatch):
    monkeypatch.setenv("NOS_TPU_METADATA_SERVER", "127.0.0.1:1")
    assert native.metadata_http("instance/attributes/accelerator-type") is None
