"""Input pipeline (train/data.py): deterministic resumable sampling,
memory-mapped shards, per-process slicing, device prefetch."""
import threading

import numpy as np
import pytest

from nos_tpu.train.data import (
    TokenDataset,
    prefetch_to_device,
    write_token_shards,
)


@pytest.fixture()
def shards(tmp_path):
    rng = np.random.default_rng(0)
    arrs = [rng.integers(0, 1000, size=n, dtype=np.uint32)
            for n in (500, 300, 700)]
    write_token_shards(str(tmp_path), arrs)
    return str(tmp_path / "shard_*.bin"), arrs


def test_batches_are_deterministic_and_resumable(shards):
    pattern, _ = shards
    a = TokenDataset(pattern, seq_len=16, seed=3)
    b = TokenDataset(pattern, seq_len=16, seed=3)   # a "resumed" process
    for step in (0, 7, 1000):
        ba, bb = a.batch(step, 4), b.batch(step, 4)
        np.testing.assert_array_equal(ba["tokens"], bb["tokens"])
    # different steps / seeds give different batches
    assert not np.array_equal(a.batch(0, 4)["tokens"],
                              a.batch(1, 4)["tokens"])
    assert not np.array_equal(
        TokenDataset(pattern, seq_len=16, seed=4).batch(0, 4)["tokens"],
        a.batch(0, 4)["tokens"])


def test_targets_are_next_tokens_and_windows_real(shards):
    pattern, arrs = shards
    ds = TokenDataset(pattern, seq_len=8)
    b = ds.batch(0, 8)
    assert b["tokens"].shape == (8, 8) and b["targets"].shape == (8, 8)
    # true next-token prediction: target row = token row shifted by one
    np.testing.assert_array_equal(b["tokens"][:, 1:], b["targets"][:, :-1])
    # every window must appear verbatim in some shard
    blobs = [a.tolist() for a in arrs]

    def appears(row):
        r = row.tolist()
        return any(
            r == blob[i:i + len(r)]
            for blob in blobs
            for i in range(0, len(blob) - len(r) + 1)
        )
    assert appears(np.concatenate([b["tokens"][0], b["targets"][0][-1:]]))


def test_process_slicing_partitions_global_batch(shards):
    pattern, _ = shards
    ds = TokenDataset(pattern, seq_len=8)
    full = ds.batch(5, 8)["tokens"]
    got = [ds.batch(5, 8, process_index=i, process_count=4)["tokens"]
           for i in range(4)]
    # row r of the global batch lives on process r % 4
    for i in range(4):
        np.testing.assert_array_equal(got[i], full[i::4])
    with pytest.raises(ValueError, match="divisible"):
        ds.batch(0, 6, process_count=4)


def test_shard_validation(tmp_path):
    with pytest.raises(FileNotFoundError):
        TokenDataset(str(tmp_path / "nope_*.bin"), seq_len=8)
    write_token_shards(str(tmp_path), [np.arange(4, dtype=np.uint32)])
    with pytest.raises(ValueError, match="full window"):
        TokenDataset(str(tmp_path / "shard_*.bin"), seq_len=8)


def test_meta_dtype_respected(tmp_path):
    write_token_shards(str(tmp_path), [np.arange(100, dtype=np.uint16)],
                       dtype=np.uint16)
    ds = TokenDataset(str(tmp_path / "shard_*.bin"), seq_len=8)
    b = ds.batch(0, 2)
    assert b["tokens"].dtype == np.int32          # widened for embedding
    assert b["tokens"].max() < 100


def test_prefetch_yields_in_order_and_overlaps():
    produced = []

    def batch_for(step):
        produced.append(step)
        return {"step": step}

    got = [b["step"] for b in
           prefetch_to_device(batch_for, 10, 5, depth=2)]
    assert got == [10, 11, 12, 13, 14]
    assert sorted(produced) == got


def test_prefetch_applies_put_and_bounds_lookahead():
    gate = threading.Event()
    staged = []

    def batch_for(step):
        staged.append(step)
        return step

    it = prefetch_to_device(batch_for, 0, 10,
                            put=lambda s: s * 2, depth=2)
    first = next(it)
    assert first == 0
    # with depth=2 the producer may run at most 2 ahead of consumption
    gate.wait(0.2)
    assert len(staged) <= 4
    assert next(it) == 2


def test_prefetch_surfaces_producer_errors():
    def batch_for(step):
        if step == 2:
            raise RuntimeError("shard read failed")
        return step

    it = prefetch_to_device(batch_for, 0, 5, depth=1)
    assert next(it) == 0
    assert next(it) == 1
    with pytest.raises(RuntimeError, match="shard read failed"):
        next(it)
