"""Self-healing serving (ISSUE 7 tentpole): supervised engine restarts,
request deadlines, and the seeded serving-chaos machinery.

Two layers, mirroring the implementation split:

- jax-free stub-engine tests drive ServingLoop + EngineSupervisor +
  FaultInjector through restarts, watchdog trips, deadline expiry, lost
  requests, budget exhaustion, and the shutdown-during-recovery race —
  the exactly-once outcome discipline is the invariant everywhere, and
  the slow-marked multi-seed soak hammers it under random fault/client
  schedules.
- real-engine tests pin the headline contract: a greedy request resumed
  across an injected engine restart is BIT-IDENTICAL to an undisturbed
  run, at every (pipeline_depth, decode_steps) in {1,2} x {1,4}, in
  both swap (byte-exact KV restore) and recompute (re-prefill) modes.
"""
import threading
import time

import pytest

from nos_tpu.cmd.server import OUTCOMES, ServingLoop
from nos_tpu.models.errors import DeadlineExceeded, DeadlineUnmeetable
from nos_tpu.models.supervision import EngineSupervisor, FaultInjector
from nos_tpu.utils.metrics import default_registry


# ---------------------------------------------------------------------------
# stub engine: a split-protocol token mill honoring the DecodeServer
# surface the loop relies on (progress = generated-only, pop_result =
# prompt + generated, capture/restore for the supervisor)
# ---------------------------------------------------------------------------

class StubEngine:
    def __init__(self, tokens_per_tick: int = 1):
        self.reqs = {}          # rid -> {"prompt", "out", "n"}
        self.done = {}          # rid -> {"prompt", "out"}
        self.ledgers = {}       # rid -> fixed-latency ledger snapshot
        self.next_rid = 0
        self.tokens_per_tick = tokens_per_tick

    def submit(self, prompt, max_new_tokens, **kw):
        rid = self.next_rid
        self.next_rid += 1
        self.reqs[rid] = {"prompt": list(prompt), "out": [],
                          "n": max_new_tokens}
        return rid

    # deterministic token rule: next token == absolute position, so a
    # restarted engine continuing from restored state produces exactly
    # the sequence an undisturbed run would — any duplication or gap in
    # the stream is visible in the output itself
    def _mint(self, d):
        d["out"].append(len(d["prompt"]) + len(d["out"]))

    def capture_resumable(self):
        sts = [{"rid": r, "prompt": d["prompt"], "out": list(d["out"]),
                "max_new_tokens": d["n"]}
               for r, d in sorted(self.reqs.items())]
        sts += [{"rid": r, "prompt": d["prompt"], "out": list(d["out"]),
                 "max_new_tokens": len(d["out"]), "done": True}
                for r, d in sorted(self.done.items())]
        return sts

    def restore(self, state):
        rid = self.next_rid
        self.next_rid += 1
        d = {"prompt": list(state["prompt"]), "out": list(state["out"]),
             "n": int(state["max_new_tokens"])}
        if state.get("done"):
            self.done[rid] = d
        else:
            self.reqs[rid] = d
        return rid

    def has_work(self):
        return bool(self.reqs)

    def step_begin(self):
        return object()

    def step_wait(self, handle):
        time.sleep(0.0005)

    def step_finish(self, handle):
        emitted = 0
        for rid, d in list(self.reqs.items()):
            for _ in range(self.tokens_per_tick):
                self._mint(d)
                emitted += 1
                if len(d["out"]) >= d["n"]:
                    break
            if len(d["out"]) >= d["n"]:
                self.done[rid] = d
                del self.reqs[rid]
                # fixed-latency ledger: seeds the loop's rolling
                # TTFT/TPOT estimates deterministically (10 ms TTFT,
                # 0.5 ms/token) for the deadline-admission tests
                n = len(d["out"])
                self.ledgers[rid] = {
                    "queue_s": 0.0, "ttft_s": 0.01,
                    "e2e_s": 0.01 + 0.0005 * n,
                    "tpot": [(0.0005 * (n - 1), n - 1)] if n > 1 else [],
                    "output_tokens": n,
                }
        return emitted

    def pop_ledger(self, rid):
        return self.ledgers.pop(rid, None)

    def progress(self, rid):
        if rid in self.done:
            return list(self.done[rid]["out"]), True
        d = self.reqs.get(rid)
        if d is None:
            return None
        return list(d["out"]), False

    def pop_result(self, rid):
        d = self.done.pop(rid, None)
        return None if d is None else d["prompt"] + d["out"]

    def cancel(self, rid):
        d = self.reqs.pop(rid, None)
        if d is None:
            return False
        self.done[rid] = d
        return True


def outcome_totals():
    c = default_registry().counter(
        "nos_tpu_serve_requests_total", "", ("outcome",))
    return {o: c.value(o) for o in OUTCOMES}


def outcome_delta(before):
    after = outcome_totals()
    return {o: after[o] - before[o] for o in OUTCOMES}


def make_loop(injector=None, factory=lambda: StubEngine(), **kw):
    wrap = injector.wrap if injector is not None else (lambda e: e)
    kw.setdefault("restart_backoff_s", 0.01)
    kw.setdefault("restart_budget", 4)
    return ServingLoop(wrap(factory()),
                       engine_factory=lambda: wrap(factory()), **kw)


def expected_tokens(prompt, n):
    return list(prompt) + list(range(len(prompt), len(prompt) + n))


# ---------------------------------------------------------------------------
# supervised restarts over the stub
# ---------------------------------------------------------------------------

def test_restart_resumes_all_requests_exactly_once():
    before = outcome_totals()
    inj = FaultInjector(schedule={3: "error", 7: "error"})
    loop = make_loop(inj)
    outs = {}

    def worker(i):
        outs[i] = loop.generate([100 + i], 12, timeout=30)

    threads = [threading.Thread(target=worker, args=(i,))
               for i in range(3)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(60)
    try:
        assert loop._sup.restarts == 2
        assert loop._sup.resumed["recompute"] == 6 and loop._sup.lost == 0
        for i in range(3):
            assert outs[i] == expected_tokens([100 + i], 12)
        d = outcome_delta(before)
        assert d["finished"] == 3
        assert sum(d.values()) == 3         # exactly one outcome each
        assert loop.healthy and not loop.recovering
        # episodes report MTTR for the chaos bench
        eps = loop.stats()["supervisor"]["episodes"]
        assert len(eps) == 2 and all(e["mttr_s"] >= 0 for e in eps)
    finally:
        loop.shutdown()


def test_budget_exhaustion_is_terminal_and_drains_failed():
    before = outcome_totals()
    inj = FaultInjector(schedule={2: "error", 4: "error", 6: "error"})
    loop = make_loop(inj, restart_budget=2)
    outs, errs = {}, {}

    def worker(i):
        try:
            outs[i] = loop.generate([1], 50, timeout=30)
        except RuntimeError as e:
            errs[i] = e

    threads = [threading.Thread(target=worker, args=(i,))
               for i in range(2)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(60)
    try:
        assert not loop.healthy             # third failure: terminal
        assert errs and not outs
        d = outcome_delta(before)
        assert d["failed"] == 2 and sum(d.values()) == 2
    finally:
        loop.shutdown()


def test_no_factory_keeps_terminal_failure_behavior():
    """restart_budget/engine_factory absent == the pre-supervision
    contract: first engine failure flips /healthz."""
    before = outcome_totals()
    inj = FaultInjector(schedule={1: "error"})
    eng = inj.wrap(StubEngine())
    loop = ServingLoop(eng)
    with pytest.raises(RuntimeError, match="serving loop failed"):
        loop.generate([1], 10, timeout=30)
    try:
        assert not loop.healthy
        d = outcome_delta(before)
        assert d["failed"] == 1 and sum(d.values()) == 1
    finally:
        loop.shutdown()


def test_engine_without_capture_loses_requests_accounted_failed():
    before = outcome_totals()

    class Bare(StubEngine):
        capture_resumable = property()      # AttributeError on access

    inj = FaultInjector(schedule={1: "error"})
    loop = ServingLoop(
        inj.wrap(Bare()), engine_factory=lambda: inj.wrap(Bare()),
        restart_budget=2, restart_backoff_s=0.01)
    with pytest.raises(RuntimeError):
        loop.generate([1], 30, timeout=30)
    try:
        d = outcome_delta(before)
        # nothing captured -> the in-flight request is simply gone from
        # the rebuilt engine; the stream observes the vanish and the
        # teardown accounts it exactly once
        assert sum(d.values()) == 1
        assert loop.healthy                 # the restart itself worked
    finally:
        loop.shutdown()


def test_restore_failure_accounts_lost_exactly_once():
    before = outcome_totals()

    class RestoreBoom(StubEngine):
        def __init__(self, fresh=False):
            super().__init__()
            self.fresh = fresh

        def restore(self, state):
            if self.fresh:
                raise ValueError("cannot restore here")
            return super().restore(state)

    inj = FaultInjector(schedule={1: "error"})
    loop = ServingLoop(
        inj.wrap(RestoreBoom()),
        engine_factory=lambda: inj.wrap(RestoreBoom(fresh=True)),
        restart_budget=2, restart_backoff_s=0.01)
    with pytest.raises(RuntimeError, match="lost in engine restart"):
        loop.generate([1], 30, timeout=30)
    try:
        d = outcome_delta(before)
        assert d["failed"] == 1 and sum(d.values()) == 1
        assert loop._sup.lost == 1
        lost = default_registry().counter(
            "nos_tpu_serve_requests_lost_total", "")
        assert lost.total() >= 1
    finally:
        loop.shutdown()


def test_watchdog_trips_on_hung_tick_and_recovers():
    before = outcome_totals()
    inj = FaultInjector(schedule={3: "hang"}, hang_s=1.0)
    loop = make_loop(inj, watchdog_s=0.15)
    out = loop.generate([5], 20, timeout=30)
    try:
        assert out == expected_tokens([5], 20)
        assert loop._sup.restarts == 1
        eps = loop.stats()["supervisor"]["episodes"]
        assert eps[0]["cause"] == "watchdog"
        trips = default_registry().counter(
            "nos_tpu_serve_watchdog_trips_total", "")
        assert trips.total() >= 1
        d = outcome_delta(before)
        assert d["finished"] == 1 and sum(d.values()) == 1
        # the superseded (stuck) ticker must exit once it unblocks and
        # leave the recovered loop serving normally
        time.sleep(1.0)
        assert loop.healthy
        assert loop.generate([6], 3, timeout=30) == \
            expected_tokens([6], 3)
    finally:
        loop.shutdown()


def test_watchdog_without_supervisor_fails_terminally():
    """watchdogSeconds > 0 with restartBudget = 0 (no engine factory)
    must still arm the watchdog: a validated trip then goes TERMINAL —
    /healthz flips and orchestration restarts the pod — instead of the
    loop wedging forever behind a green health check."""
    before = outcome_totals()
    trips = default_registry().counter(
        "nos_tpu_serve_watchdog_trips_total", "")
    t0 = trips.total()
    inj = FaultInjector(schedule={2: "hang"}, hang_s=1.0)
    loop = ServingLoop(inj.wrap(StubEngine()), watchdog_s=0.15)
    assert loop._monitor_thread is not None
    with pytest.raises(RuntimeError, match="watchdog"):
        loop.generate([1], 30, timeout=30)
    try:
        assert not loop.healthy
        assert trips.total() - t0 == 1
        d = outcome_delta(before)
        assert d["failed"] == 1 and sum(d.values()) == 1
    finally:
        loop.shutdown()


def test_recovering_rejects_submits_and_resumes_streams():
    """Mid-recovery, new submissions get EngineRecovering while already-
    admitted streams ride through the restart."""
    from nos_tpu.models.errors import EngineRecovering

    gate = threading.Event()

    def factory():
        gate.wait(10)
        return StubEngine()

    inj = FaultInjector(schedule={2: "error"})
    loop = ServingLoop(
        inj.wrap(StubEngine()),
        engine_factory=lambda: inj.wrap(factory()),
        restart_budget=2, restart_backoff_s=0.01)
    outs = {}

    def worker():
        outs[0] = loop.generate([9], 10, timeout=30)

    t = threading.Thread(target=worker)
    t.start()
    deadline = time.monotonic() + 10
    while not loop.recovering and time.monotonic() < deadline:
        time.sleep(0.005)
    try:
        assert loop.recovering
        assert loop.healthy                 # NOT terminal
        with pytest.raises(EngineRecovering):
            loop.generate([1], 2, timeout=5)
        gate.set()
        t.join(30)
        assert outs[0] == expected_tokens([9], 10)
        assert not loop.recovering
    finally:
        gate.set()
        loop.shutdown()


def test_shutdown_during_recovery_drains_captured_failed():
    """The drain-during-shutdown race (ISSUE 7 bugfix satellite):
    shutdown() landing while a recovery is rebuilding must cancel the
    recovery deterministically — captured requests drain as ``failed``
    exactly once, the loop dies terminally, nothing hangs."""
    before = outcome_totals()
    gate = threading.Event()

    def slow_factory():
        gate.wait(30)                       # recovery parks here
        return StubEngine()

    inj = FaultInjector(schedule={2: "error"})
    loop = ServingLoop(
        inj.wrap(StubEngine()),
        engine_factory=lambda: inj.wrap(slow_factory()),
        restart_budget=2, restart_backoff_s=0.01)
    errs = {}

    def worker(i):
        try:
            loop.generate([i], 40, timeout=30)
        except RuntimeError as e:
            errs[i] = e

    threads = [threading.Thread(target=worker, args=(i,))
               for i in range(2)]
    for t in threads:
        t.start()
    deadline = time.monotonic() + 10
    while not loop.recovering and time.monotonic() < deadline:
        time.sleep(0.005)
    assert loop.recovering
    t0 = time.monotonic()
    loop.shutdown()             # must interrupt the parked rebuild
    took = time.monotonic() - t0
    gate.set()
    for t in threads:
        t.join(30)
    assert took < 10, f"shutdown blocked {took:.1f}s on recovery"
    assert not loop.healthy
    assert len(errs) == 2
    d = outcome_delta(before)
    assert d["failed"] == 2 and sum(d.values()) == 2


# ---------------------------------------------------------------------------
# request deadlines
# ---------------------------------------------------------------------------

def test_deadline_expires_mid_decode_exactly_once():
    before = outcome_totals()
    loop = ServingLoop(StubEngine())
    # slow the mill: ~0.5ms/tick, 10000 tokens would take ~5s
    with pytest.raises(DeadlineExceeded):
        loop.generate([1], 10_000, timeout=30, deadline_s=0.1)
    try:
        d = outcome_delta(before)
        assert d["deadline"] == 1 and sum(d.values()) == 1
        stats = loop.stats()
        assert stats["deadline"]["expired"] == 1
        assert stats["deadline"]["active"] == 0     # cleaned up
        # the engine slot was cancelled, not left decoding
        assert not loop.engine.has_work()
    finally:
        loop.shutdown()


def test_deadline_admission_shed_when_estimates_say_unmeetable():
    before = outcome_totals()
    loop = ServingLoop(StubEngine())
    try:
        # seed the rolling estimates with one completed request
        loop.generate([1], 40, timeout=30)
        assert loop.stats()["deadline"]["est_ttft_s"] is not None
        assert loop.stats()["deadline"]["est_tpot_s"] is not None
        # ~0.5 ms/token: 100k tokens cannot land inside 1 ms
        with pytest.raises(DeadlineUnmeetable):
            loop.generate([1], 100_000, timeout=30, deadline_s=0.001)
        d = outcome_delta(before)
        assert d["deadline"] == 1 and d["finished"] == 1
        assert sum(d.values()) == 2
        assert loop.stats()["deadline"]["shed"] == 1
        # a generous deadline still admits
        assert loop.generate([2], 3, timeout=30, deadline_s=60.0) \
            == expected_tokens([2], 3)
    finally:
        loop.shutdown()


def test_deadline_shed_probe_breaks_estimate_lockin():
    """Estimates only refresh on completions, so a stale-high estimate
    that sheds 100% of deadline traffic would never decay — every Nth
    consecutive shed must be admitted as a probe whose completion
    unlocks admission again."""
    from nos_tpu.cmd.server import DEADLINE_PROBE_EVERY

    loop = ServingLoop(StubEngine())
    try:
        loop.generate([1], 5, timeout=30)   # seed the estimates
        # poison them: pretend the engine got slow (est ~2.2s for a
        # 3-token request vs a 1s deadline -> every admission sheds)
        loop._est_ttft_s, loop._est_tpot_s = 2.0, 0.1
        outcomes = []
        admitted_streak = 0
        for _ in range(30 * DEADLINE_PROBE_EVERY):
            try:
                loop.generate([2], 3, timeout=30, deadline_s=1.0)
                outcomes.append("admitted")
                admitted_streak += 1
                if admitted_streak >= 2:
                    break               # admitted on MERIT, not probe
            except DeadlineUnmeetable:
                outcomes.append("shed")
                admitted_streak = 0
        # the first N-1 attempts shed, the Nth was the probe...
        assert outcomes[:DEADLINE_PROBE_EVERY] == \
            ["shed"] * (DEADLINE_PROBE_EVERY - 1) + ["admitted"]
        # ...and probe completions (the stub reports ~10ms latencies)
        # decayed the EWMA until admission unlocked on merit — two
        # consecutive admissions cannot both be probes
        assert admitted_streak >= 2, outcomes
        assert loop._est_ttft_s < 2.0
    finally:
        loop.shutdown()


def test_deadline_validation_and_explicit_zero_opts_out():
    loop = ServingLoop(StubEngine(), default_deadline_s=0.0001)
    try:
        with pytest.raises(ValueError, match="deadline_s"):
            loop.generate([1], 2, deadline_s=-1.0)
        # the fleet default applies when the field is omitted...
        with pytest.raises(DeadlineExceeded):
            loop.generate([1], 10_000, timeout=30)
        # ...and an EXPLICIT deadline_s=0 opts out of it — the only
        # wire value that can request unbounded completion
        assert loop.generate([2], 5, timeout=30, deadline_s=0) \
            == expected_tokens([2], 5)
    finally:
        loop.shutdown()


def test_default_deadline_applies_and_restart_preserves_deadlines():
    """A request's deadline keeps ticking across a restart: one that
    expired during the outage is shed at restore time, not resumed."""
    before = outcome_totals()
    gate = threading.Event()

    def slow_factory():
        gate.wait(5)
        return StubEngine()

    inj = FaultInjector(schedule={2: "error"})
    loop = ServingLoop(
        inj.wrap(StubEngine()),
        engine_factory=lambda: inj.wrap(slow_factory()),
        restart_budget=2, restart_backoff_s=0.01,
        default_deadline_s=0.2)
    errs, outs = {}, {}

    def worker():
        try:
            outs[0] = loop.generate([1], 50, timeout=30)
        except DeadlineExceeded as e:
            errs[0] = e

    t = threading.Thread(target=worker)
    t.start()
    deadline = time.monotonic() + 10
    while not loop.recovering and time.monotonic() < deadline:
        time.sleep(0.005)
    time.sleep(0.3)                     # outlive the 0.2s deadline
    gate.set()
    t.join(30)
    try:
        assert errs and not outs
        d = outcome_delta(before)
        assert d["deadline"] == 1 and sum(d.values()) == 1
        assert loop._sup.resumed == {"swap": 0, "recompute": 0}
    finally:
        gate.set()
        loop.shutdown()


# ---------------------------------------------------------------------------
# FaultInjector / supervisor units
# ---------------------------------------------------------------------------

def test_fault_injector_seeded_schedule_is_deterministic():
    a = FaultInjector(seed=7, p_error=0.2, p_hang=0.1)
    b = FaultInjector(seed=7, p_error=0.2, p_hang=0.1)
    kinds_a, kinds_b = [], []
    for inj, kinds in ((a, kinds_a), (b, kinds_b)):
        for _ in range(200):
            try:
                inj.before_dispatch(None)
                kinds.append(inj.injected[-1]["kind"]
                             if inj.injected and
                             inj.injected[-1]["tick"] == inj.tick - 1
                             else None)
            except RuntimeError:
                kinds.append("error")
            inj.before_wait = lambda: None  # don't actually sleep
    assert kinds_a == kinds_b
    assert "error" in kinds_a


def test_fault_injector_rejects_unknown_kind():
    with pytest.raises(ValueError, match="unknown fault kinds"):
        FaultInjector(schedule={0: "meteor"})


def test_supervisor_backoff_is_seeded_and_bounded():
    a = EngineSupervisor(lambda: None, restart_budget=5, backoff_s=0.5,
                         backoff_max_s=2.0, seed=3)
    b = EngineSupervisor(lambda: None, restart_budget=5, backoff_s=0.5,
                         backoff_max_s=2.0, seed=3)
    da = [a.backoff_delay(i) for i in range(5)]
    db = [b.backoff_delay(i) for i in range(5)]
    assert da == db                     # seeded jitter: reproducible
    assert all(d <= 2.0 * 1.25 + 1e-9 for d in da)
    assert all(d >= 0 for d in da)
    with pytest.raises(ValueError):
        EngineSupervisor(lambda: None, restart_budget=-1)


def test_chaos_engine_proxy_mirrors_inner_surface():
    inj = FaultInjector()
    eng = StubEngine()
    proxy = inj.wrap(eng)
    assert hasattr(proxy, "step_begin") and hasattr(proxy, "cancel")
    assert not hasattr(proxy, "kv_stats")
    rid = proxy.submit([1], 2)
    assert proxy.progress(rid) == ([], False)
    proxy.step_begin()
    proxy.step_wait(None)
    proxy.step_finish(None)
    assert inj.tick == 1
    # attribute WRITES delegate too: the serving loop assigns
    # engine.compile_events = [] to drain the compile ledger, and a
    # proxy-shadowed copy would silently fork from the real engine
    proxy.compile_events = ["x"]
    assert eng.compile_events == ["x"]
    assert "compile_events" not in vars(proxy)


# ---------------------------------------------------------------------------
# seeded chaos soak: every submitted request reaches exactly one
# terminal outcome under random faults, disconnects and deadlines
# ---------------------------------------------------------------------------

# ---------------------------------------------------------------------------
# real engine: a greedy request resumed across an injected restart is
# BIT-IDENTICAL to an undisturbed run — swap restores the KV bytes,
# recompute re-prefills prompt + out[:-1] (chunking-invariant), and the
# slot-static engine recomputes over the shared cache row
# ---------------------------------------------------------------------------

MODEL = dict(vocab=64, d_model=32, n_layers=2, n_heads=4, n_kv_heads=2,
             d_ff=64, max_seq=64)


@pytest.fixture(scope="module")
def real_params():
    import jax
    import jax.numpy as jnp

    from nos_tpu.models import transformer as tfm

    cfg = tfm.TransformerConfig(**MODEL, dtype=jnp.float32)
    return tfm.init_params(jax.random.PRNGKey(0), cfg), cfg


def _check_bit_exact_resume(real_params, mk_engine, depth, steps,
                            want_mode):
    import jax.numpy as jnp

    from nos_tpu.models.generate import generate

    params, cfg = real_params
    inj = FaultInjector(schedule={2: "error"})
    loop = ServingLoop(
        inj.wrap(mk_engine(depth, steps)),
        engine_factory=lambda: inj.wrap(mk_engine(depth, steps)),
        restart_budget=2, restart_backoff_s=0.01)
    prompts = [[1, 2, 3], [7, 8]]
    outs = {}

    def worker(i):
        outs[i] = loop.generate(prompts[i], 10, timeout=180)

    threads = [threading.Thread(target=worker, args=(i,))
               for i in range(len(prompts))]
    for t in threads:
        t.start()
    for t in threads:
        t.join(300)
    try:
        assert loop._sup.restarts == 1, "fault did not trigger a restart"
        assert loop._sup.lost == 0
        assert loop._sup.resumed[want_mode] >= 1, loop._sup.resumed
        for i, p in enumerate(prompts):
            want = [int(t) for t in generate(
                params, cfg, jnp.asarray([p], jnp.int32), 10)[0]]
            assert outs.get(i) == want, (
                f"depth={depth} steps={steps}: resumed request {i} "
                f"diverged from the undisturbed run")
    finally:
        loop.shutdown()


def _paged(real_params, swap):
    from nos_tpu.models.serving import DecodeServer

    params, cfg = real_params

    def mk(depth, steps):
        return DecodeServer(params, cfg, max_batch=2,
                            pipeline_depth=depth, decode_steps=steps,
                            kv_block_size=8, kv_blocks=17, kv_swap=swap)
    return mk


def _static(real_params):
    from nos_tpu.models.serving import DecodeServer

    params, cfg = real_params

    def mk(depth, steps):
        return DecodeServer(params, cfg, max_batch=2,
                            pipeline_depth=depth, decode_steps=steps)
    return mk


def test_restart_resume_bit_exact_swap(real_params):
    _check_bit_exact_resume(real_params, _paged(real_params, True),
                            2, 4, "swap")


def test_restart_resume_bit_exact_recompute(real_params):
    _check_bit_exact_resume(real_params, _paged(real_params, False),
                            1, 1, "recompute")


def test_restart_resume_bit_exact_slot_static(real_params):
    _check_bit_exact_resume(real_params, _static(real_params),
                            2, 4, "recompute")


@pytest.mark.slow
@pytest.mark.parametrize("depth", [1, 2])
@pytest.mark.parametrize("steps", [1, 4])
@pytest.mark.parametrize("swap", [True, False])
def test_restart_resume_bit_exact_matrix(real_params, depth, steps,
                                         swap):
    """The full (pipeline_depth, decode_steps) x (swap, recompute)
    matrix of the ISSUE 7 coverage satellite."""
    _check_bit_exact_resume(real_params, _paged(real_params, swap),
                            depth, steps, "swap" if swap else "recompute")


@pytest.mark.slow
def test_restart_resume_bit_exact_speculative(real_params):
    """The speculative engine resumes too: target AND draft caches
    re-prefill over the committed sequence, so greedy accept/reject
    decisions — and the committed tokens — are undisturbed."""
    import jax
    import jax.numpy as jnp

    from nos_tpu.models import transformer as tfm
    from nos_tpu.models.generate import generate
    from nos_tpu.models.spec_serving import SpeculativeDecodeServer

    params, cfg = real_params
    dmodel = dict(MODEL, d_model=16, n_layers=1, n_heads=2,
                  n_kv_heads=1, d_ff=32)
    dcfg = tfm.TransformerConfig(**dmodel, dtype=jnp.float32)
    dparams = tfm.init_params(jax.random.PRNGKey(1), dcfg)

    def mk(depth, steps):
        return SpeculativeDecodeServer(params, cfg, dparams, dcfg,
                                       n_draft=3, max_batch=2)

    inj = FaultInjector(schedule={2: "error"})
    loop = ServingLoop(
        inj.wrap(mk(1, 1)), engine_factory=lambda: inj.wrap(mk(1, 1)),
        restart_budget=2, restart_backoff_s=0.01)
    try:
        out = loop.generate([1, 2, 3], 10, timeout=300)
        assert loop._sup.restarts == 1 and loop._sup.lost == 0
        want = [int(t) for t in generate(
            params, cfg, jnp.asarray([[1, 2, 3]], jnp.int32), 10)[0]]
        assert out == want
    finally:
        loop.shutdown()


@pytest.mark.slow
@pytest.mark.parametrize("seed", range(8))
def test_chaos_soak_outcome_conservation(seed):
    import random

    rng = random.Random(1000 + seed)
    before = outcome_totals()
    inj = FaultInjector(seed=seed, p_error=0.04, p_hang=0.01,
                        p_slow=0.05, hang_s=0.6, slow_s=0.01)
    loop = make_loop(inj, restart_budget=64, watchdog_s=0.2)
    n_requests = 16
    submitted = []
    lock = threading.Lock()

    def worker(i):
        prompt = [i] * rng.randint(1, 3)
        n = rng.randint(3, 40)
        deadline = rng.choice([None, None, None, 0.05, 2.0])
        disconnect = rng.random() < 0.2
        try:
            stream = loop.stream(prompt, n, timeout=60,
                                 deadline_s=deadline)
        except Exception:
            with lock:
                submitted.append(("rejected", i))
            return
        with lock:
            submitted.append(("admitted", i))
        try:
            got = list(prompt)
            for k, delta in enumerate(stream):
                got.extend(delta)
                if disconnect and k >= 1:
                    stream.close()
                    return
            assert got == expected_tokens(prompt, n), got
        except Exception:
            pass
        finally:
            stream.close()

    threads = [threading.Thread(target=worker, args=(i,))
               for i in range(n_requests)]
    for t in threads:
        rng.random() and time.sleep(rng.random() * 0.01)
        t.start()
    for t in threads:
        t.join(120)
    try:
        # let any trailing reap/abandon accounting land
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            d = outcome_delta(before)
            if sum(d.values()) >= n_requests and not loop.engine.reqs:
                break
            time.sleep(0.05)
        d = outcome_delta(before)
        assert sum(d.values()) == n_requests, (
            f"seed {seed}: outcome conservation violated: {d} "
            f"(submitted {n_requests})")
        assert all(v >= 0 for v in d.values()), d
        # no leaked engine state on the final engine
        assert not loop.engine.reqs
    finally:
        loop.shutdown()


# ---------------------------------------------------------------------------
# burst-tenant adversary over a supervised restart (ISSUE 13 chaos
# satellite): quota reclaim preempts the over-quota tenant, the engine
# then DIES, and the rebuilt engine restores the preempted requests —
# per-tenant conservation + no cross-tenant double-finish
# ---------------------------------------------------------------------------

def test_tenant_burst_adversary_restart_conserves_per_tenant(
        real_params):
    """A burst tenant holds every slot; a guaranteed tenant's arrival
    reclaims one (bit-exact preempt); an injected engine failure then
    kills the engine with the preempted request still PENDING — the
    rebuilt engine must restore everything under the right tenants.
    Pins: every request finishes exactly once, bit-identical to its
    OWN prompt's undisturbed run (a cross-tenant double-finish or
    restore mix-up would corrupt some output), per-tenant token
    accounting matches what each tenant's requests actually produced,
    and the reclaim preemption is charged to the burst tenant."""
    import jax.numpy as jnp

    from nos_tpu.models.generate import generate
    from nos_tpu.models.serving import DecodeServer
    from nos_tpu.models.tenantquota import (
        TenantQuotaConfig, TenantSpec,
    )

    params, cfg = real_params
    tq = TenantQuotaConfig(
        tenants={"gold": TenantSpec("gold", min_rate=1000.0),
                 "burst": TenantSpec("burst", max_rate=1000.0)},
        window_s=8.0)

    def mk():
        return DecodeServer(params, cfg, max_batch=2, kv_block_size=8,
                            kv_blocks=33, kv_swap=True,
                            tenant_quota=tq)

    inj = FaultInjector(schedule={4: "error"})
    loop = ServingLoop(inj.wrap(mk()),
                       engine_factory=lambda: inj.wrap(mk()),
                       restart_budget=2, restart_backoff_s=0.01,
                       tenant_quota=tq)
    reg = default_registry()
    tok_c = reg.counter("nos_tpu_serve_tenant_tokens_total", "",
                        ("tenant",))
    pre_c = reg.counter("nos_tpu_serve_tenant_preempt_total", "",
                        ("tenant", "mode"))
    tok0 = {t: tok_c.value(t) for t in ("gold", "burst")}
    pre0 = pre_c.value("burst", "swap")

    prompts = {"burst-0": ([1, 2, 3], 8), "burst-1": ([4, 5, 6], 8),
               "gold-0": ([7, 8], 6)}
    outs = {}

    def worker(name, tenant, prompt, n):
        outs[name] = loop.generate(list(prompt), n, timeout=180,
                                   tenant=tenant)

    bthreads = [threading.Thread(
        target=worker, args=(k, "burst", *prompts[k]))
        for k in ("burst-0", "burst-1")]
    for t in bthreads:
        t.start()
    # wait until burst holds BOTH slots so gold's arrival must reclaim
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        snap = getattr(loop.engine, "tenant_snapshot", lambda: None)()
        if snap and snap["burst"]["active"] == 2:
            break
        time.sleep(0.005)
    gthread = threading.Thread(
        target=worker, args=("gold-0", "gold", *prompts["gold-0"]))
    gthread.start()
    for t in bthreads + [gthread]:
        t.join(300)
    try:
        assert loop._sup.restarts == 1, "fault did not fire"
        assert loop._sup.lost == 0
        # no cross-tenant double-finish / restore mix-up: each output
        # is ITS OWN prompt's undisturbed run, token for token
        for name, (prompt, n) in prompts.items():
            want = [int(t) for t in generate(
                params, cfg, jnp.asarray([prompt], jnp.int32), n)[0]]
            assert outs.get(name) == want, name
        # per-tenant conservation: tokens accounted under each tenant
        # == what that tenant's finished requests produced
        assert tok_c.value("gold") - tok0["gold"] == 6
        assert tok_c.value("burst") - tok0["burst"] == 16
        # the reclaim was charged to the burst tenant (swap mode)
        assert pre_c.value("burst", "swap") - pre0 >= 1
    finally:
        loop.shutdown()
