"""Real-Kubernetes REST binding (VERDICT r1 #1).

K8sApiServer (nos_tpu/kube/rest.py) speaks genuine k8s REST — kubeconfig
bearer auth, camelCase manifests, quantity strings, /status and /binding
subresources, 409 semantics, chunked watch streams — against the
kube-apiserver emulator (nos_tpu/kube/k8s_sim.py, the envtest analog;
reference suite_int_test.go:58-60). The e2e here runs the REAL operator +
scheduler managers over this wire: pods enter as raw k8s JSON the way GKE
would deliver them, and come back bound with capacity labels and quota
status.used computed.
"""
import json
import time
import urllib.request

import pytest

from nos_tpu import constants
from nos_tpu.kube.k8s_sim import K8sSim
from nos_tpu.kube.rest import K8sApiServer

TPU = constants.RESOURCE_TPU
TOKEN = "test-bearer-token"


@pytest.fixture()
def sim():
    s = K8sSim(token=TOKEN).start()
    yield s
    s.stop()


@pytest.fixture()
def api(sim, tmp_path):
    kubeconfig = tmp_path / "kubeconfig"
    kubeconfig.write_text(f"""
apiVersion: v1
kind: Config
current-context: sim
contexts:
- name: sim
  context: {{cluster: sim, user: sim-user}}
clusters:
- name: sim
  cluster: {{server: "{sim.url}"}}
users:
- name: sim-user
  user: {{token: "{TOKEN}"}}
""")
    api = K8sApiServer(kubeconfig=str(kubeconfig))
    yield api


def raw(sim, method, path, body=None, token=TOKEN):
    data = json.dumps(body).encode() if body is not None else None
    req = urllib.request.Request(
        sim.url + path, data=data, method=method,
        headers={"Authorization": f"Bearer {token}",
                 "Content-Type": "application/json"})
    with urllib.request.urlopen(req) as resp:
        payload = resp.read()
        return json.loads(payload) if payload else {}


def k8s_node(name, pool="pool-a", topo="4x4", chips=8):
    return {
        "apiVersion": "v1", "kind": "Node",
        "metadata": {"name": name, "labels": {
            constants.LABEL_TPU_ACCELERATOR: "tpu-v5-lite-podslice",
            constants.LABEL_TPU_TOPOLOGY: topo,
            constants.LABEL_NODEPOOL: pool,
        }},
        "spec": {"taints": [
            {"key": TPU, "value": "present", "effect": "NoSchedule"}]},
        "status": {"capacity": {TPU: str(chips), "cpu": "96"},
                   "allocatable": {TPU: str(chips), "cpu": "96"}},
    }


def k8s_pod(name, ns="team-a", chips=8):
    return {
        "apiVersion": "v1", "kind": "Pod",
        "metadata": {"name": name, "namespace": ns},
        "spec": {
            "schedulerName": constants.SCHEDULER_NAME,
            "containers": [{"name": "main", "resources": {
                "requests": {TPU: str(chips), "cpu": "4"}}}],
            "tolerations": [{"key": TPU, "operator": "Exists",
                             "effect": "NoSchedule"}],
        },
        "status": {"phase": "Pending", "conditions": [
            {"type": "PodScheduled", "status": "False",
             "reason": "Unschedulable"}]},
    }


# ---------------------------------------------------------------------------
# adapter-level semantics over the real wire
# ---------------------------------------------------------------------------

def test_auth_is_enforced(sim):
    with pytest.raises(urllib.error.HTTPError) as e:
        raw(sim, "GET", "/api/v1/nodes", token="wrong")
    assert e.value.code == 401


def test_crud_roundtrip_native_manifests(sim, api):
    raw(sim, "POST", "/api/v1/nodes", k8s_node("n1"))
    node = api.get("Node", "n1")
    assert node.status.allocatable[TPU] == 8          # "8" -> 8
    assert node.spec.taints[0].key == TPU

    from nos_tpu.api.quota import make_elastic_quota
    api.create(make_elastic_quota("qa", "team-a", min={TPU: 8}))
    d = raw(sim, "GET",
            "/apis/nos.ai/v1alpha1/namespaces/team-a/elasticquotas/qa")
    assert d["spec"]["min"][TPU] == "8"               # quantity string

    listed = api.list("ElasticQuota", namespace="team-a")
    assert len(listed) == 1 and listed[0].spec.min[TPU] == 8


def test_conflict_and_subresource_semantics(sim, api):
    raw(sim, "POST", "/api/v1/namespaces/ns/pods", k8s_pod("p", ns="ns"))
    pod = api.get("Pod", "p", "ns")

    # direct nodeName write must be refused by the server (422 -> ApiError)
    from nos_tpu.kube.apiserver import ApiError, Conflict
    stale = api.get("Pod", "p", "ns")

    def set_label(p):
        p.metadata.labels["x"] = "1"
    api.patch("Pod", "p", "ns", set_label)

    # stale update -> Conflict
    stale.metadata.labels["y"] = "2"
    with pytest.raises(Conflict):
        api.update(stale)

    # status travels via the /status subresource: phase change lands
    def set_phase(p):
        p.status.phase = "Running"
    api.patch("Pod", "p", "ns", set_phase)
    d = raw(sim, "GET", "/api/v1/namespaces/ns/pods/p")
    assert d["status"]["phase"] == "Running"
    assert d["metadata"]["labels"]["x"] == "1"


def test_bind_goes_through_binding_subresource(sim, api):
    raw(sim, "POST", "/api/v1/nodes", k8s_node("n1"))
    raw(sim, "POST", "/api/v1/namespaces/ns/pods", k8s_pod("p", ns="ns"))

    def bind(p):
        p.spec.node_name = "n1"
    api.patch("Pod", "p", "ns", bind)
    d = raw(sim, "GET", "/api/v1/namespaces/ns/pods/p")
    assert d["spec"]["nodeName"] == "n1"
    # a second bind attempt conflicts at the subresource
    with pytest.raises(urllib.error.HTTPError) as e:
        raw(sim, "POST", "/api/v1/namespaces/ns/pods/p/binding",
            {"target": {"name": "n2"}})
    assert e.value.code == 409


def test_watch_stream_delivers_events(sim, api):
    sub = api.subscribe(["Pod"])
    try:
        raw(sim, "POST", "/api/v1/namespaces/ns/pods", k8s_pod("w1", ns="ns"))
        deadline = time.monotonic() + 5
        seen = []
        while time.monotonic() < deadline and not seen:
            ev = sub.pop()
            if ev is not None and ev.obj.metadata.name == "w1":
                seen.append(ev)
            else:
                time.sleep(0.02)
        assert seen and seen[0].type == "ADDED"
        assert seen[0].obj.spec.scheduler_name == constants.SCHEDULER_NAME
    finally:
        api.unsubscribe(sub)


def test_crd_registration(sim, api):
    applied = api.ensure_crds("config/operator/crd/bases")
    assert any("elasticquotas.nos.ai" in n for n in applied)
    # idempotent
    assert api.ensure_crds("config/operator/crd/bases") == applied


# ---------------------------------------------------------------------------
# the full control plane against the real wire
# ---------------------------------------------------------------------------

def pump(managers, seconds=6.0, settle=0.08):
    """Pump async managers until the system converges (watch events arrive
    on live HTTP streams, so run_until_idle alone can't see the future)."""
    deadline = time.monotonic() + seconds
    while time.monotonic() < deadline:
        worked = sum(m.run_until_idle() for m in managers)
        if not worked:
            time.sleep(settle)


def test_e2e_operator_and_scheduler_over_k8s_rest(sim, api):
    from nos_tpu.cmd import operator as op_cmd, scheduler as sched_cmd

    api.ensure_crds("config/operator/crd/bases")
    op = op_cmd.build(api)
    sched = sched_cmd.build(api)

    # cluster arrives as raw k8s JSON (what GKE would hold)
    raw(sim, "POST", "/api/v1/nodes", k8s_node("pool-a-w0"))
    raw(sim, "POST", "/api/v1/nodes", k8s_node("pool-a-w1"))
    raw(sim, "POST", "/apis/nos.ai/v1alpha1/namespaces/team-a/elasticquotas",
        {"apiVersion": "nos.ai/v1alpha1", "kind": "ElasticQuota",
         "metadata": {"name": "qa", "namespace": "team-a"},
         # cpu is a core resource: bounded at 0 unless the quota grants it
         # (reference sumGreaterThan semantics), so grant both currencies
         "spec": {"min": {TPU: "16", "cpu": "64"}}})
    raw(sim, "POST", "/api/v1/namespaces/team-a/pods", k8s_pod("train-a"))
    raw(sim, "POST", "/api/v1/namespaces/team-a/pods", k8s_pod("train-b"))

    pump([op, sched])

    a = raw(sim, "GET", "/api/v1/namespaces/team-a/pods/train-a")
    b = raw(sim, "GET", "/api/v1/namespaces/team-a/pods/train-b")
    bound = sorted([a["spec"].get("nodeName", ""), b["spec"].get("nodeName", "")])
    assert bound == ["pool-a-w0", "pool-a-w1"], bound

    # mark Running as the kubelet would; operator computes used + labels
    for name in ("train-a", "train-b"):
        d = raw(sim, "GET", f"/api/v1/namespaces/team-a/pods/{name}")
        d["status"]["phase"] = "Running"
        raw(sim, "PUT", f"/api/v1/namespaces/team-a/pods/{name}/status", d)
    pump([op, sched], seconds=4.0)

    q = raw(sim, "GET",
            "/apis/nos.ai/v1alpha1/namespaces/team-a/elasticquotas/qa")
    assert q["status"]["used"].get(TPU) == "16", q["status"]
    a = raw(sim, "GET", "/api/v1/namespaces/team-a/pods/train-a")
    assert a["metadata"]["labels"].get(constants.LABEL_CAPACITY) == \
        constants.CAPACITY_IN_QUOTA

    for m in (op, sched):
        m.stop()


def test_bind_patch_applies_status_over_the_wire(sim, api):
    """Regression: the trimmed bind path must still land the status
    facet. A scheduler bind sets nodeName (via binding) AND clears the
    nomination / sets PodScheduled=True (via /status with the
    post-binding resourceVersion) — round 3's first cut silently lost
    the status PUT to a stale-RV 409."""
    raw(sim, "POST", "/api/v1/namespaces/ns/pods", k8s_pod("bindme", ns="ns"))
    # simulate a prior nomination
    api.patch("Pod", "bindme", "ns",
              lambda p: setattr(p.status, "nominated_node_name", "n-old"))

    from nos_tpu.kube.objects import PodCondition

    def bind(p):
        p.spec.node_name = "n-new"
        p.status.nominated_node_name = ""
        p.status.conditions = [PodCondition(type="PodScheduled", status="True")]

    api.patch("Pod", "bindme", "ns", bind)
    got = api.get("Pod", "bindme", "ns")
    assert got.spec.node_name == "n-new"
    assert got.status.nominated_node_name == ""
    assert any(c.type == "PodScheduled" and c.status == "True"
               for c in got.status.conditions)


def test_field_selector_filters_server_side(sim, api):
    """Pod spec.nodeName / status.phase indexes ride the wire as
    fieldSelector (the selectors a real apiserver evaluates itself) —
    verified by hitting the raw HTTP endpoint AND through the adapter."""
    for i, node in enumerate(("node-a", "node-b", "")):
        p = k8s_pod(f"fs-{i}")
        if node:
            p["spec"]["nodeName"] = node
        raw(sim, "POST", "/api/v1/namespaces/team-a/pods", p)

    got = raw(sim, "GET",
              "/api/v1/namespaces/team-a/pods?fieldSelector=spec.nodeName%3Dnode-a")
    assert [o["metadata"]["name"] for o in got["items"]] == ["fs-0"]

    via_adapter = api.list("Pod", "team-a", index=("spec.nodeName", "node-b"))
    assert [p.metadata.name for p in via_adapter] == ["fs-1"]

    pending = api.list("Pod", "team-a", index=("status.phase", "Pending"))
    assert {p.metadata.name for p in pending} == {"fs-0", "fs-1", "fs-2"}

    # the other operator forms a real apiserver accepts: == and !=
    eq = raw(sim, "GET", "/api/v1/namespaces/team-a/pods"
             "?fieldSelector=spec.nodeName%3D%3Dnode-a")
    assert [o["metadata"]["name"] for o in eq["items"]] == ["fs-0"]
    ne = raw(sim, "GET", "/api/v1/namespaces/team-a/pods"
             "?fieldSelector=spec.nodeName%21%3Dnode-a")
    assert [o["metadata"]["name"] for o in ne["items"]] == ["fs-1", "fs-2"]

    # unsupported field labels draw kube's 400, not a silent wrong answer
    import urllib.error
    with pytest.raises(urllib.error.HTTPError) as exc:
        raw(sim, "GET", "/api/v1/namespaces/team-a/pods"
            "?fieldSelector=status.hostIP%3D10.0.0.1")
    assert exc.value.code == 400

    # a pod stored without a status block still counts as Pending (kube
    # defaults the phase; the adapter codec does too)
    bare = k8s_pod("fs-bare")
    del bare["status"]
    raw(sim, "POST", "/api/v1/namespaces/team-a/pods", bare)
    pending2 = api.list("Pod", "team-a", index=("status.phase", "Pending"))
    assert "fs-bare" in {p.metadata.name for p in pending2}
