"""Full-stack end-to-end: every real component wired together — node/pod
controllers + partitioning controller + REAL TpuAgent (mock native layer) +
quota operator + scheduler — the in-process equivalent of the reference's
whole deployment (SURVEY §3.2 + §3.3 + §3.4 in one loop)."""
from nos_tpu import constants
from nos_tpu.agents.tpu_native import MockTpuClient
from nos_tpu.agents.tpuagent import TpuAgent
from nos_tpu.api.quota import make_elastic_quota
from nos_tpu.api.webhooks import register_quota_webhooks
from nos_tpu.kube import ApiServer, Manager
from nos_tpu.kube.objects import (
    Container,
    Node,
    NodeStatus,
    ObjectMeta,
    Pod,
    PodCondition,
    PodSpec,
    PodStatus,
)
from nos_tpu.partitioning import (
    NodeController,
    PartitioningController,
    PodController,
)
from nos_tpu.partitioning.state import ClusterState
from nos_tpu.quota.controller import ElasticQuotaReconciler
from nos_tpu.scheduler import Scheduler

SLICE_11 = "nos.ai/tpu-slice-1x1"
SLICE_22 = "nos.ai/tpu-slice-2x2"


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


def v5e_node(name):
    return Node(
        metadata=ObjectMeta(
            name=name,
            labels={
                constants.LABEL_TPU_ACCELERATOR: "tpu-v5-lite-podslice",
                constants.LABEL_TPU_TOPOLOGY: "2x4",
                constants.LABEL_PARTITIONING: constants.PARTITIONING_SUBSLICING,
            },
        ),
        status=NodeStatus(capacity={"cpu": 96}, allocatable={"cpu": 96}),
    )


def slice_pod(name, resource, qty=1, ns="team-a"):
    return Pod(
        metadata=ObjectMeta(name=name, namespace=ns),
        spec=PodSpec(
            containers=[Container(requests={resource: qty})],
            scheduler_name=constants.SCHEDULER_NAME,
        ),
        status=PodStatus(
            phase="Pending",
            conditions=[
                PodCondition(type="PodScheduled", status="False", reason="Unschedulable")
            ],
        ),
    )


def full_stack(node_names):
    server = ApiServer()
    register_quota_webhooks(server)
    clock = FakeClock()
    mgr = Manager(server, clock=clock)
    state = ClusterState()
    mgr.add_controller(NodeController(state).controller())
    mgr.add_controller(PodController(state).controller())
    mgr.add_controller(
        PartitioningController(state, batch_timeout_s=60, batch_idle_s=10,
                               clock=clock).controller()
    )
    agents = {}
    for name in node_names:
        agent = TpuAgent(name, MockTpuClient(chips=8), report_interval_s=None)
        agents[name] = agent
        for c in agent.controllers():
            mgr.add_controller(c)
    mgr.add_controller(ElasticQuotaReconciler().controller())
    mgr.add_controller(Scheduler().controller())
    return server, mgr, clock, agents


def pump_batch(mgr, clock):
    mgr.run_until_idle()
    clock.advance(11)
    mgr.run_until_idle()


def test_pods_flow_through_entire_stack():
    server, mgr, clock, agents = full_stack(["v5e-0"])
    server.create(make_elastic_quota("qa", "team-a", min={SLICE_11: 8}))
    server.create(v5e_node("v5e-0"))
    mgr.run_until_idle()

    # virgin node: initialized by control plane, actuated by the REAL agent
    node = server.get("Node", "v5e-0")
    assert node.metadata.annotations.get("nos.ai/status-tpu-0-2x4-free") == "1"

    for i in range(4):
        server.create(slice_pod(f"p{i}", SLICE_11))
    pump_batch(mgr, clock)

    # partitioner re-planned; agent actuated; scheduler bound all pods
    for i in range(4):
        pod = server.get("Pod", f"p{i}", "team-a")
        assert pod.spec.node_name == "v5e-0", f"p{i} not scheduled"

    # mark them running: the agent must now report used slices and the
    # quota operator must account + label them
    for i in range(4):
        p = server.get("Pod", f"p{i}", "team-a")
        p.status.phase = "Running"
        server.update(p)
    mgr.run_until_idle()
    node = server.get("Node", "v5e-0")
    assert node.metadata.annotations.get("nos.ai/status-tpu-0-1x1-used") == "4"
    eq = server.get("ElasticQuota", "qa", "team-a")
    assert eq.status.used == {SLICE_11: 4}
    for i in range(4):
        p = server.get("Pod", f"p{i}", "team-a")
        assert p.metadata.labels[constants.LABEL_CAPACITY] == "in-quota"


def test_mixed_profiles_two_nodes():
    server, mgr, clock, agents = full_stack(["v5e-0", "v5e-1"])
    for n in ("v5e-0", "v5e-1"):
        server.create(v5e_node(n))
    mgr.run_until_idle()

    # 8 singles + 2 quads: needs both nodes with different geometries
    for i in range(8):
        server.create(slice_pod(f"s{i}", SLICE_11))
    for i in range(2):
        server.create(slice_pod(f"q{i}", SLICE_22))
    pump_batch(mgr, clock)
    # one more batch round in case the first plan only covered part
    pump_batch(mgr, clock)

    unscheduled = [
        p.metadata.name for p in server.list("Pod") if not p.spec.node_name
    ]
    assert unscheduled == [], f"unscheduled: {unscheduled}"
    # geometry sanity: across both nodes there are >=8 singles and >=2 quads
    total_11 = total_22 = 0
    for n in ("v5e-0", "v5e-1"):
        boards, _ = agents[n].tpu.read_partition()
        for g in boards.values():
            from nos_tpu.tpu.slice import Profile

            total_11 += g.get(Profile(1, 1), 0)
            total_22 += g.get(Profile(2, 2), 0)
    assert total_11 >= 8 and total_22 >= 2
