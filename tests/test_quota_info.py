"""Quota accounting math (model: reference elasticquotainfo_test.go, 881 LoC).

Includes the reference's worked guaranteed-overquota example
(elasticquotainfo.go getAggregatedOverquotas doc comment).
"""
from nos_tpu.quota.info import (
    QuotaInfo,
    QuotaInfos,
    greater_than,
    sum_greater_than,
    sum_less_than_equal,
)

TPU = "google.com/tpu"


def qi(name, ns, min=None, max=None, used=None, namespaces=None):
    return QuotaInfo(
        name=name,
        namespace=ns,
        namespaces=set(namespaces or [ns]),
        min=dict(min or {}),
        max=dict(max) if max is not None else None,
        used=dict(used or {}),
    )


# ---------------------------------------------------------------------------
# comparison primitives
# ---------------------------------------------------------------------------

def test_sum_greater_than_core_resources_always_bounded():
    # cpu/memory default to bound 0 when absent from y
    assert sum_greater_than({"cpu": 1}, {}, {})
    assert sum_greater_than({"memory": 1}, {}, {TPU: 4})
    assert not sum_greater_than({}, {}, {})


def test_sum_greater_than_scalars_unbounded_when_absent():
    # a scalar not listed in y is unconstrained
    assert not sum_greater_than({TPU: 100}, {}, {"cpu": 1000})
    assert sum_greater_than({TPU: 5}, {}, {"cpu": 1000, TPU: 4})


def test_sum_greater_than_sums_both_sides():
    assert sum_greater_than({TPU: 2}, {TPU: 3}, {TPU: 4})
    assert not sum_greater_than({TPU: 2}, {TPU: 2}, {TPU: 4})


def test_sum_less_than_equal_is_negation():
    assert sum_less_than_equal({TPU: 2}, {TPU: 2}, {TPU: 4})
    assert not sum_less_than_equal({TPU: 3}, {TPU: 2}, {TPU: 4})


# ---------------------------------------------------------------------------
# QuotaInfo bounds
# ---------------------------------------------------------------------------

def test_used_over_min_with():
    info = qi("a", "ns-a", min={TPU: 8}, used={TPU: 6})
    assert not info.used_over_min_with({TPU: 2})
    assert info.used_over_min_with({TPU: 3})


def test_used_over_max_unenforced_when_absent():
    info = qi("a", "ns-a", min={TPU: 2}, used={TPU: 100})
    assert not info.used_over_max_with({TPU: 100})   # no max -> never over
    info2 = qi("b", "ns-b", min={TPU: 2}, max={TPU: 4}, used={TPU: 3})
    assert not info2.used_over_max_with({TPU: 1})
    assert info2.used_over_max_with({TPU: 2})


def test_reserve_unreserve_roundtrip():
    info = qi("a", "ns-a", min={TPU: 8})
    info.reserve({TPU: 4, "cpu": 2})
    assert info.used == {TPU: 4, "cpu": 2}
    info.unreserve({TPU: 4, "cpu": 2})
    assert info.used == {TPU: 0, "cpu": 0}


def test_add_delete_pod_idempotent():
    from nos_tpu.kube.objects import Container, ObjectMeta, Pod, PodSpec

    info = qi("a", "team-a", min={TPU: 8})
    pod = Pod(
        metadata=ObjectMeta(name="p1", namespace="team-a"),
        spec=PodSpec(containers=[Container(requests={TPU: 4})]),
    )
    info.add_pod_if_not_present(pod)
    info.add_pod_if_not_present(pod)     # no double counting
    assert info.used[TPU] == 4
    info.delete_pod_if_present(pod)
    info.delete_pod_if_present(pod)
    assert info.used[TPU] == 0


def test_clone_independence():
    info = qi("a", "ns-a", min={TPU: 8}, used={TPU: 2})
    c = info.clone()
    c.reserve({TPU: 1})
    assert info.used == {TPU: 2}


# ---------------------------------------------------------------------------
# QuotaInfos aggregates + guaranteed overquotas
# ---------------------------------------------------------------------------

def make_reference_example() -> QuotaInfos:
    """The reference's worked example (cpu in millicores -> cores here):
    A: min 100m used 350m; B: min 50m used 0; C: min 200m used 50m.
    Aggregated overquota = 0.05 + 0.15 = 0.2 cores."""
    infos = QuotaInfos()
    infos.add(qi("a", "ns-a", min={"cpu": 0.1}, used={"cpu": 0.35}))
    infos.add(qi("b", "ns-b", min={"cpu": 0.05}, used={"cpu": 0.0}))
    infos.add(qi("c", "ns-c", min={"cpu": 0.2}, used={"cpu": 0.05}))
    return infos


def test_aggregated_overquotas_reference_example():
    infos = make_reference_example()
    assert abs(infos.aggregated_overquotas()["cpu"] - 0.2) < 1e-9


def test_guaranteed_overquotas_proportional_to_min_share():
    infos = make_reference_example()
    # total min = 0.35; shares: a 2/7, b 1/7, c 4/7 of 0.2 cores,
    # floored at millicore granularity
    assert abs(infos.guaranteed_overquotas("ns-a")["cpu"] - 0.057) < 1e-9
    assert abs(infos.guaranteed_overquotas("ns-b")["cpu"] - 0.028) < 1e-9
    assert abs(infos.guaranteed_overquotas("ns-c")["cpu"] - 0.114) < 1e-9


def test_guaranteed_overquotas_tpu_chips_floored_whole():
    infos = QuotaInfos()
    infos.add(qi("a", "ns-a", min={TPU: 3}, used={TPU: 0}))
    infos.add(qi("b", "ns-b", min={TPU: 5}, used={TPU: 5}))
    # overquota = 3 (all of a's unused min); a's share 3/8 -> 1.125 -> 1 chip
    assert infos.guaranteed_overquotas("ns-a")[TPU] == 1
    assert infos.guaranteed_overquotas("ns-b")[TPU] == 1  # 15/8 -> 1


def test_guaranteed_overquotas_unknown_namespace_raises():
    import pytest

    with pytest.raises(KeyError):
        QuotaInfos().guaranteed_overquotas("nope")


def test_aggregated_used_over_min_with():
    infos = make_reference_example()
    # total used = 0.4, total min = 0.35 -> already over; any request is over
    assert infos.aggregated_used_over_min_with({"cpu": 0.001})
    infos2 = QuotaInfos()
    infos2.add(qi("a", "ns-a", min={TPU: 8}, used={TPU: 2}))
    assert not infos2.aggregated_used_over_min_with({TPU: 6})
    assert infos2.aggregated_used_over_min_with({TPU: 7})


def test_composite_info_counted_once_in_aggregates():
    infos = QuotaInfos()
    composite = qi("comp", "ns-x", min={TPU: 8}, used={TPU: 4},
                   namespaces=["ns-x", "ns-y", "ns-z"])
    infos.add(composite)
    assert infos.aggregated_min() == {TPU: 8}       # not 24
    assert infos.aggregated_used() == {TPU: 4}


def test_infos_replace_preserves_used_and_pods():
    infos = QuotaInfos()
    old = qi("a", "ns-a", min={TPU: 4}, used={TPU: 2}, namespaces=["ns-a", "ns-b"])
    old.pods.add("ns-a/p1")
    infos.add(old)
    new = qi("a", "ns-a", min={TPU: 8}, namespaces=["ns-a"])
    infos.replace_info(old, new)
    assert infos["ns-a"].min == {TPU: 8}
    assert infos["ns-a"].used == {TPU: 2}
    assert "ns-a/p1" in infos["ns-a"].pods
    assert "ns-b" not in infos


def test_guaranteed_overquotas_resource_only_in_own_min():
    # b's min lists a resource nobody else bounds: b gets the whole
    # overquota for it (share = 1).
    infos = QuotaInfos()
    infos.add(qi("a", "ns-a", min={"cpu": 1.0}, used={"cpu": 1.0}))
    infos.add(qi("b", "ns-b", min={TPU: 4, "cpu": 1.0}, used={}))
    g = infos.guaranteed_overquotas("ns-b")
    assert g[TPU] == 4


def test_guaranteed_overquotas_zero_total_min_is_zero():
    infos = QuotaInfos()
    infos.add(qi("a", "ns-a", min={TPU: 0}))
    assert infos.guaranteed_overquotas("ns-a")[TPU] == 0


def test_aggregated_overquotas_clamps_overused_quotas():
    # a quota using MORE than its min contributes 0 headroom, not negative
    infos = QuotaInfos()
    infos.add(qi("a", "ns-a", min={TPU: 4}, used={TPU: 10}))
    infos.add(qi("b", "ns-b", min={TPU: 4}, used={TPU: 1}))
    assert infos.aggregated_overquotas() == {TPU: 3}


def test_guaranteed_overquotas_memory_floored_to_whole_bytes():
    gib = 1024 ** 3
    infos = QuotaInfos()
    infos.add(qi("a", "ns-a", min={"memory": gib}, used={"memory": 0}))
    infos.add(qi("b", "ns-b", min={"memory": 2 * gib}, used={"memory": 2 * gib}))
    g = infos.guaranteed_overquotas("ns-a")["memory"]
    assert g == float(int(g))          # whole bytes
    assert abs(g - gib / 3) < 1        # a's third of its own unused GiB


def test_guaranteed_overquotas_composite_counted_once():
    infos = QuotaInfos()
    infos.add(qi("comp", "ns-x", min={TPU: 4}, used={TPU: 0},
                 namespaces=["ns-x", "ns-y"]))
    infos.add(qi("b", "ns-b", min={TPU: 4}, used={TPU: 4}))
    # total min 8 (composite once), overquota 4; comp share = 4/8*4 = 2
    assert infos.guaranteed_overquotas("ns-x")[TPU] == 2
    assert infos.guaranteed_overquotas("ns-y")[TPU] == 2


def test_infos_replace_covers_new_namespace():
    infos = QuotaInfos()
    old = qi("a", "ns-a", min={TPU: 4}, namespaces=["ns-a"])
    infos.add(old)
    new = qi("a", "ns-a", min={TPU: 4}, namespaces=["ns-a", "ns-b"])
    infos.replace_info(old, new)
    assert infos["ns-b"] is infos["ns-a"]


def test_infos_remove():
    infos = QuotaInfos()
    info = qi("comp", "ns-x", min={TPU: 4}, namespaces=["ns-x", "ns-y"])
    infos.add(info)
    infos.remove(info)
    assert "ns-x" not in infos and "ns-y" not in infos


def test_sum_greater_than_exact_equality_is_not_greater():
    # bound comparisons are >, never >= (a request exactly filling min/max
    # is allowed)
    assert not sum_greater_than({TPU: 4}, {TPU: 4}, {TPU: 8})
    assert not greater_than({"cpu": 0.0}, {})


def test_infos_clone_preserves_aliasing():
    infos = QuotaInfos()
    composite = qi("comp", "ns-x", min={TPU: 8}, namespaces=["ns-x", "ns-y"])
    infos.add(composite)
    c = infos.clone()
    assert c["ns-x"] is c["ns-y"]            # aliasing preserved
    c["ns-x"].reserve({TPU: 1})
    assert infos["ns-x"].used.get(TPU, 0) == 0   # deep-copied


# ---------------------------------------------------------------------------
# floor rounding at granularity boundaries (VERDICT r3 next #7)
# ---------------------------------------------------------------------------

def infos(*qs):
    out = QuotaInfos()
    for q in qs:
        out.add(q)
    return out


def test_guaranteed_overquotas_cpu_floors_at_millicores():
    # overquota cpu 1, a's share 1/3 -> 0.333... floored to 333 millicores
    qa = qi("qa", "ns-a", min={"cpu": 1}, used={"cpu": 0})
    qb = qi("qb", "ns-b", min={"cpu": 2}, used={"cpu": 2})
    got = infos(qa, qb).guaranteed_overquotas("ns-a")
    assert got["cpu"] == 0.333


def test_guaranteed_overquotas_exact_integer_share_not_eroded():
    # 3/7 of 7 chips is exactly 3; float arithmetic gives
    # 3.0000000000000004 or 2.9999999999999996 depending on evaluation
    # order — the epsilon in _floor_quantity must keep the floor at 3,
    # never 2
    qa = qi("qa", "ns-a", min={TPU: 3}, used={TPU: 0})
    qb = qi("qb", "ns-b", min={TPU: 4}, used={TPU: 0})
    got = infos(qa, qb).guaranteed_overquotas("ns-a")
    assert got[TPU] == 3.0
    # and the denominator-49 case (1/49 * 49)
    q1 = qi("q1", "ns-1", min={TPU: 1}, used={TPU: 0})
    q2 = qi("q2", "ns-2", min={TPU: 48}, used={TPU: 0})
    assert infos(q1, q2).guaranteed_overquotas("ns-1")[TPU] == 1.0


def test_guaranteed_overquotas_sum_never_exceeds_aggregate():
    """Conservation: Σ over quotas of guaranteed ≤ aggregated overquota,
    whatever the share fractions (the floors donate the remainder) —
    the reference pins the percentage-sum analog of this."""
    tables = [
        {"qa": ("ns-a", 1, 0), "qb": ("ns-b", 2, 1), "qc": ("ns-c", 4, 0)},
        {"qa": ("ns-a", 3, 2), "qb": ("ns-b", 5, 0), "qc": ("ns-c", 7, 7)},
        {"qa": ("ns-a", 1, 0), "qb": ("ns-b", 1, 0), "qc": ("ns-c", 1, 0)},
        {"qa": ("ns-a", 9, 11), "qb": ("ns-b", 6, 2), "qc": ("ns-c", 2, 0)},
    ]
    for table in tables:
        qs = infos(*[
            qi(name, ns, min={TPU: mn}, used={TPU: us})
            for name, (ns, mn, us) in table.items()
        ])
        agg = qs.aggregated_overquotas().get(TPU, 0)
        total = sum(
            qs.guaranteed_overquotas(ns)[TPU]
            for ns in ("ns-a", "ns-b", "ns-c")
        )
        assert total <= agg, (table, total, agg)


def test_guaranteed_overquotas_resource_absent_from_own_min_is_zero():
    # a quota gets no guaranteed share of a resource it declares no min
    # for (its pct of that resource's total min is 0)
    qa = qi("qa", "ns-a", min={TPU: 4}, used={TPU: 0})
    qb = qi("qb", "ns-b", min={TPU: 4, "cpu": 2}, used={})
    got = infos(qa, qb).guaranteed_overquotas("ns-a")
    assert "cpu" not in got      # only resources in a's own min appear


def test_guaranteed_overquotas_zero_used_idle_cluster_returns_full_share():
    # wholly idle cluster: every quota's guaranteed share is its
    # proportional slice of the full aggregated min
    qa = qi("qa", "ns-a", min={TPU: 2}, used={})
    qb = qi("qb", "ns-b", min={TPU: 6}, used={})
    got_a = infos(qa, qb).guaranteed_overquotas("ns-a")
    got_b = infos(qa, qb).guaranteed_overquotas("ns-b")
    assert got_a[TPU] == 2.0 and got_b[TPU] == 6.0


# ---------------------------------------------------------------------------
# granularity-boundary rounding (VERDICT r4 ask #10): values that are
# mathematically exact at the granularity boundary must not be eroded by
# float representation, and values just under it must floor DOWN.
# ---------------------------------------------------------------------------

def test_guaranteed_overquotas_millicore_boundary_not_eroded():
    """cpu shares of 1/3 over 0.3 idle cores: each quota's exact share is
    100m; binary-float products (0.3 * (1/3) = 0.09999999...) must still
    land ON the boundary, not at 99m."""
    infos = QuotaInfos()
    for ns in ("ns-a", "ns-b", "ns-c"):
        infos.add(qi(f"q-{ns}", ns, min={"cpu": 0.1}, used={"cpu": 0.0}))
    # one consumer uses nothing: aggregated overquota = 0.3 cores
    for ns in ("ns-a", "ns-b", "ns-c"):
        g = infos.guaranteed_overquotas(ns)["cpu"]
        assert abs(g - 0.1) < 1e-12, (ns, g)


def test_guaranteed_overquotas_chip_boundary_floor_vs_exact():
    """Chips: a 3-way split of 8 chips guarantees floor(8/3)=2 each (the
    lost remainder stays first-come-first-served), while a 4-way split of
    8 is exactly 2 — no erosion, no inflation."""
    infos = QuotaInfos()
    for ns in ("a", "b", "c"):
        infos.add(qi(f"q-{ns}", ns, min={TPU: 4}, used={TPU: 1}))
    # aggregated overquota = 3 * 3 = 9; share 1/3 -> exact 3.0 each
    for ns in ("a", "b", "c"):
        assert infos.guaranteed_overquotas(ns)[TPU] == 3.0
    infos2 = QuotaInfos()
    infos2.add(qi("q-a", "a", min={TPU: 5}, used={TPU: 0}))
    infos2.add(qi("q-b", "b", min={TPU: 3}, used={TPU: 0}))
    # aggregated = 8; a: 8 * 5/8 = 5 exact; b: 8 * 3/8 = 3 exact
    assert infos2.guaranteed_overquotas("a")[TPU] == 5.0
    assert infos2.guaranteed_overquotas("b")[TPU] == 3.0
    infos3 = QuotaInfos()
    infos3.add(qi("q-a", "a", min={TPU: 4}, used={TPU: 0}))
    infos3.add(qi("q-b", "b", min={TPU: 4}, used={TPU: 0}))
    infos3.add(qi("q-c", "c", min={TPU: 3}, used={TPU: 3}))
    # aggregated = 8; a,b: 8 * 4/11 = 2.909 -> floored to 2 whole chips
    assert infos3.guaranteed_overquotas("a")[TPU] == 2.0
    assert infos3.guaranteed_overquotas("c")[TPU] == 2.0   # 8*3/11=2.18


def test_guaranteed_overquotas_sub_slice_scalars_floored_whole():
    """Sub-slice scalar resources (nos.ai/tpu-slice-1x1) are countable
    units like chips: fractional guarantees floor to whole slices."""
    res = "nos.ai/tpu-slice-1x1"
    infos = QuotaInfos()
    infos.add(qi("q-a", "a", min={res: 2}, used={res: 0}))
    infos.add(qi("q-b", "b", min={res: 1}, used={res: 1}))
    # aggregated overquota = 2; a: 2 * 2/3 = 1.33 -> 1; b: 2/3 -> 0
    assert infos.guaranteed_overquotas("a")[res] == 1.0
    assert infos.guaranteed_overquotas("b")[res] == 0.0
