"""Partition-config plan differ scenario tables.

Model: reference internal/controllers/migagent/plan/plan_test.go (617 LoC) —
desired-vs-actual diffing, used-slice protection, multi-board plans,
deterministic op ordering. Complements the agent-level plan tests in
test_tpuagent.py.
"""
from nos_tpu.agents.plan import BoardState, Operation, PartitionConfigPlan
from nos_tpu.tpu.slice import Profile

P11 = Profile(1, 1)
P12 = Profile(1, 2)
P22 = Profile(2, 2)
P24 = Profile(2, 4)


def plan(desired, actual):
    return PartitionConfigPlan(desired=desired, actual=actual)


# ---------------------------------------------------------------------------
# no-op detection
# ---------------------------------------------------------------------------

def test_empty_everything_is_noop():
    p = plan({}, {})
    assert p.is_empty() and p.is_valid()
    assert p.summary() == "no-op"


def test_equal_geometries_noop():
    p = plan(
        {0: {P22: 1, P11: 4}},
        {0: BoardState(geometry={P11: 4, P22: 1})},
    )
    assert p.is_empty()


def test_zero_quantity_entries_equal_absent():
    p = plan(
        {0: {P22: 1, P12: 0}},
        {0: BoardState(geometry={P22: 1, P11: 0})},
    )
    assert p.is_empty()


# ---------------------------------------------------------------------------
# create / delete deltas
# ---------------------------------------------------------------------------

def test_creates_on_virgin_board():
    p = plan({0: {P22: 2}}, {})
    assert p.ops == [Operation("create", 0, P22, 2)]


def test_deletes_when_board_absent_from_desired():
    p = plan({}, {0: BoardState(geometry={P12: 3})})
    assert p.ops == [Operation("delete", 0, P12, 3)]
    assert p.is_valid()          # all free, deletable


def test_quantity_delta_create():
    p = plan({0: {P11: 4}}, {0: BoardState(geometry={P11: 1})})
    assert p.ops == [Operation("create", 0, P11, 3)]


def test_quantity_delta_delete_partial():
    p = plan({0: {P11: 1}}, {0: BoardState(geometry={P11: 4})})
    assert p.ops == [Operation("delete", 0, P11, 3)]


def test_profile_swap_creates_and_deletes():
    p = plan({0: {P24: 1}}, {0: BoardState(geometry={P12: 4})})
    assert Operation("delete", 0, P12, 4) in p.ops
    assert Operation("create", 0, P24, 1) in p.ops
    assert len(p.ops) == 2


# ---------------------------------------------------------------------------
# used-slice protection (reference: delete candidates must be free,
# plan.go:113-135)
# ---------------------------------------------------------------------------

def test_delete_of_used_slices_invalid():
    p = plan(
        {0: {P11: 1}},
        {0: BoardState(geometry={P11: 4}, used={P11: 3})},
    )
    assert not p.is_valid()
    assert "only 1 free" in p.errors[0]


def test_delete_exactly_the_free_slices_valid():
    p = plan(
        {0: {P11: 2}},
        {0: BoardState(geometry={P11: 4}, used={P11: 2})},
    )
    assert p.is_valid()
    assert p.ops == [Operation("delete", 0, P11, 2)]


def test_used_other_profile_does_not_block():
    p = plan(
        {0: {P22: 1}},
        {0: BoardState(geometry={P22: 1, P12: 2}, used={P22: 1})},
    )
    assert p.is_valid()
    assert p.ops == [Operation("delete", 0, P12, 2)]


# ---------------------------------------------------------------------------
# multi-board plans + deterministic ordering
# ---------------------------------------------------------------------------

def test_multi_board_independent_diffs():
    p = plan(
        {0: {P22: 2}, 1: {P11: 4}},
        {
            0: BoardState(geometry={P22: 1}),
            1: BoardState(geometry={P11: 4}),
            2: BoardState(geometry={P12: 2}),
        },
    )
    assert p.ops == [
        Operation("create", 0, P22, 1),
        Operation("delete", 2, P12, 2),
    ]


def test_ops_ordered_by_board_then_profile():
    p = plan(
        {1: {P11: 1, P24: 1}, 0: {P12: 1}},
        {0: BoardState(), 1: BoardState()},
    )
    assert [(o.board, o.profile) for o in p.ops] == [
        (0, P12), (1, P11), (1, P24),
    ]


def test_summary_lists_all_ops():
    p = plan({0: {P22: 1}}, {0: BoardState(geometry={P12: 2})})
    s = p.summary()
    assert "create 1x2x2@board0" in s and "delete 2x1x2@board0" in s


def test_invalid_plan_still_reports_all_ops():
    # validation failure doesn't truncate the diff — the actuator needs the
    # full picture to log what it refused to do
    p = plan(
        {0: {P11: 0}, 1: {P22: 1}},
        {0: BoardState(geometry={P11: 2}, used={P11: 2}), 1: BoardState()},
    )
    assert not p.is_valid()
    assert Operation("create", 1, P22, 1) in p.ops
    assert Operation("delete", 0, P11, 2) in p.ops
