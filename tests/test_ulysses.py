"""Ulysses all-to-all sequence parallelism: exactness vs full attention,
GQA support, constraint errors."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from nos_tpu.ops.attention import xla_attention
from nos_tpu.ops.ulysses import ulysses_attention_sharded
from nos_tpu.parallel.layout import ParallelLayout
from nos_tpu.parallel.mesh import build_mesh

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs 8 virtual devices")


def qkv(b=2, h=8, hkv=None, s=32, d=16, seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    q = jax.random.normal(ks[0], (b, h, s, d), jnp.float32)
    k = jax.random.normal(ks[1], (b, hkv or h, s, d), jnp.float32)
    v = jax.random.normal(ks[2], (b, hkv or h, s, d), jnp.float32)
    return q, k, v


@pytest.mark.parametrize("sp", [2, 4, 8])
@pytest.mark.parametrize("causal", [False, True])
def test_matches_full_attention(sp, causal):
    mesh = build_mesh(ParallelLayout(sp=sp), jax.devices()[:sp])
    q, k, v = qkv()
    ref = xla_attention(q, k, v, causal=causal)
    got = ulysses_attention_sharded(mesh, q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_gqa_kv_heads_supported():
    mesh = build_mesh(ParallelLayout(sp=2), jax.devices()[:2])
    q, k, v = qkv(h=8, hkv=2)
    ref = xla_attention(q, k, v, causal=True)
    got = ulysses_attention_sharded(mesh, q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_head_divisibility_enforced():
    mesh = build_mesh(ParallelLayout(sp=4), jax.devices()[:4])
    q, k, v = qkv(h=8, hkv=2)       # kv heads 2 not divisible by sp=4
    with pytest.raises(ValueError, match="divisible"):
        ulysses_attention_sharded(mesh, q, k, v)


def test_matches_ring_attention():
    from nos_tpu.ops.ring_attention import ring_attention_sharded

    mesh = build_mesh(ParallelLayout(sp=4), jax.devices()[:4])
    q, k, v = qkv(h=8, s=64)
    ring = ring_attention_sharded(mesh, q, k, v, causal=True)
    uly = ulysses_attention_sharded(mesh, q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(uly), np.asarray(ring),
                               rtol=1e-4, atol=1e-4)


def test_transformer_sp_strategies_agree():
    """The full model under sp sharding produces the same logits with ring
    and with ulysses attention (and both match the unsharded forward)."""
    from nos_tpu.models import transformer as tfm

    cfg_kw = dict(vocab=64, d_model=32, n_layers=2, n_heads=4, d_ff=64,
                  max_seq=32, dtype=jnp.float32)
    params = tfm.init_params(jax.random.PRNGKey(0),
                             tfm.TransformerConfig(**cfg_kw))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, 64)
    ref = tfm.forward(params, tfm.TransformerConfig(**cfg_kw), tokens)

    mesh = build_mesh(ParallelLayout(dp=2, sp=2), jax.devices()[:4])
    outs = {}
    for strategy in ("ring", "ulysses"):
        cfg = tfm.TransformerConfig(sp_strategy=strategy, **cfg_kw)
        sharded = jax.device_put(params, tfm.param_shardings(mesh, cfg))
        outs[strategy] = jax.jit(
            lambda p, t, c=cfg: tfm.forward(p, c, t, mesh))(sharded, tokens)
        np.testing.assert_allclose(np.asarray(outs[strategy]), np.asarray(ref),
                                   rtol=2e-4, atol=2e-4)

    with pytest.raises(ValueError, match="sp_strategy"):
        tfm.TransformerConfig(sp_strategy="nope", **cfg_kw)
