"""YOLOS detection family: shapes, GIoU math, TPU-native (Sinkhorn)
bipartite matching vs brute-force optimum, set-criterion overfit.

The reference benchmarks exactly this model family
(demos/gpu-sharing-comparison/client/main.py:18-19 — hustvl/yolos-small).
"""
import itertools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from nos_tpu.models import yolos
from nos_tpu.models.yolos import (YolosConfig, cxcywh_to_xyxy,
                                  generalized_box_iou, set_criterion,
                                  sinkhorn_match)

TINY = YolosConfig(image_size=32, patch=8, d_model=32, n_layers=2,
                   n_heads=2, d_ff=64, n_det_tokens=8, n_classes=5,
                   dtype=jnp.float32)


def test_forward_shapes_and_dtypes():
    params = yolos.init_params(jax.random.PRNGKey(0), TINY)
    images = jnp.zeros((3, 32, 32, 3))
    logits, boxes = jax.jit(yolos.forward, static_argnums=1)(params, TINY, images)
    assert logits.shape == (3, 8, 6)        # n_classes + no-object
    assert boxes.shape == (3, 8, 4)
    assert logits.dtype == jnp.float32 and boxes.dtype == jnp.float32
    assert bool(jnp.all((boxes >= 0) & (boxes <= 1)))


def test_yolos_small_param_count():
    """YOLOS-small rides a ~22M-param ViT-small backbone (the scale the
    reference README cites); the TPU twin must land at the same scale
    for the latency comparison to be fair."""
    cfg = YolosConfig()
    params = yolos.init_params(jax.random.PRNGKey(0), cfg)
    n = yolos.param_count(params)
    assert 18e6 < n < 30e6, f"param count {n/1e6:.1f}M not YOLOS-small scale"


def test_giou_identity_and_disjoint():
    a = jnp.array([[0.0, 0.0, 1.0, 1.0]])
    b = jnp.array([[0.0, 0.0, 1.0, 1.0], [2.0, 2.0, 3.0, 3.0]])
    g = generalized_box_iou(a, b)
    assert g.shape == (1, 2)
    assert np.isclose(float(g[0, 0]), 1.0)
    assert float(g[0, 1]) < 0.0             # disjoint: penalized below zero


def _giou_ref(a, b):
    """Straight-line numpy GIoU for one box pair."""
    ax1, ay1, ax2, ay2 = a
    bx1, by1, bx2, by2 = b
    inter = max(0.0, min(ax2, bx2) - max(ax1, bx1)) * \
        max(0.0, min(ay2, by2) - max(ay1, by1))
    area = (ax2 - ax1) * (ay2 - ay1) + (bx2 - bx1) * (by2 - by1) - inter
    iou = inter / area if area > 0 else 0.0
    hull = (max(ax2, bx2) - min(ax1, bx1)) * (max(ay2, by2) - min(ay1, by1))
    return iou - (hull - area) / hull if hull > 0 else iou


def test_giou_matches_reference_on_random_boxes():
    rng = np.random.default_rng(7)
    pts = rng.uniform(0, 1, (20, 2, 2, 2))
    boxes = np.concatenate([pts.min(axis=2), pts.max(axis=2)], axis=-1)
    ours = generalized_box_iou(jnp.asarray(boxes[:, 0]), jnp.asarray(boxes[:, 1]))
    for i in range(20):
        assert np.isclose(float(ours[i, i]),
                          _giou_ref(boxes[i, 0], boxes[i, 1]), atol=1e-5)


def _brute_force_cost(cost, t_real):
    q = cost.shape[0]
    return min(sum(cost[p[i], i] for i in range(t_real))
               for p in itertools.permutations(range(q), t_real))


@pytest.mark.parametrize("seed", range(10))
def test_sinkhorn_matches_brute_force_optimum(seed):
    rng = np.random.default_rng(seed)
    q, t_real, t_pad = 6, 3, 2
    cost = rng.uniform(0, 1, (q, t_real + t_pad)).astype(np.float32)
    mask = np.array([True] * t_real + [False] * t_pad)
    assign = np.asarray(sinkhorn_match(jnp.asarray(cost), jnp.asarray(mask)))
    real = assign[:t_real]
    assert len(set(real.tolist())) == t_real, "assignment must be one-to-one"
    ours = sum(cost[real[i], i] for i in range(t_real))
    best = _brute_force_cost(cost, t_real)
    assert ours <= best + 1e-3, f"seed {seed}: {ours} vs optimal {best}"


@pytest.mark.parametrize("seed", range(6))
def test_sinkhorn_near_ties_and_larger_instances(seed):
    """Adversarial matching: near-tie costs (quantized to 0.1 so many
    assignments are almost equivalent) and T=5/Q=10. The greedy-hardened
    plan must stay one-to-one and within 5% of the brute-force optimum
    even when Sinkhorn's soft plan is nearly uniform across ties."""
    rng = np.random.default_rng(100 + seed)
    q, t = 10, 5
    cost = (rng.integers(0, 10, (q, t)) / 10.0).astype(np.float32)
    mask = np.ones(t, bool)
    assign = np.asarray(sinkhorn_match(jnp.asarray(cost), jnp.asarray(mask)))
    assert len(set(assign.tolist())) == t
    ours = sum(cost[assign[i], i] for i in range(t))
    best = _brute_force_cost(cost, t)
    assert ours <= best + max(0.05 * abs(best), 0.051), (ours, best)


def test_sinkhorn_all_padded_is_safe():
    cost = jnp.ones((4, 3))
    assign = sinkhorn_match(cost, jnp.zeros((3,), bool))
    assert assign.shape == (3,)             # no NaN/crash; values unused


def test_set_criterion_perfect_prediction_low_loss():
    """Logits peaked on the right class at the right box -> near-zero
    class/l1/giou; a shuffled prediction must cost strictly more."""
    t_boxes = jnp.array([[[0.2, 0.2, 0.1, 0.1], [0.7, 0.7, 0.2, 0.2]]])
    t_labels = jnp.array([[1, 3]])
    logits = jnp.full((1, 4, 6), -10.0)
    logits = logits.at[0, 0, 1].set(10.0).at[0, 2, 3].set(10.0)
    logits = logits.at[0, 1, 5].set(10.0).at[0, 3, 5].set(10.0)  # no-object
    boxes = jnp.tile(jnp.array([[0.5, 0.5, 0.5, 0.5]]), (1, 4, 1))
    boxes = boxes.at[0, 0].set(t_boxes[0, 0]).at[0, 2].set(t_boxes[0, 1])
    good = set_criterion(logits, boxes, t_labels, t_boxes)
    assert float(good["class"]) < 0.01
    assert float(good["l1"]) < 1e-6
    assert float(good["giou"]) < 1e-5

    bad = set_criterion(jnp.roll(logits, 1, axis=1), boxes, t_labels, t_boxes)
    assert float(bad["total"]) > float(good["total"]) + 1.0


def test_set_criterion_rejects_more_targets_than_queries():
    with pytest.raises(ValueError, match="targets exceed"):
        set_criterion(jnp.zeros((1, 2, 6)), jnp.zeros((1, 2, 4)),
                      jnp.zeros((1, 5), jnp.int32), jnp.zeros((1, 5, 4)))


def test_set_criterion_handles_empty_image():
    """An all-padded target set trains pure no-object classification."""
    logits = jnp.zeros((1, 4, 6))
    boxes = jnp.full((1, 4, 4), 0.5)
    losses = set_criterion(logits, boxes,
                           jnp.full((1, 2), -1, jnp.int32),
                           jnp.zeros((1, 2, 4)))
    assert float(losses["l1"]) == 0.0 and float(losses["giou"]) == 0.0
    assert np.isclose(float(losses["class"]), np.log(6), atol=1e-4)


def test_overfit_two_boxes():
    """The full train path (forward -> matching -> criterion -> grad)
    drives loss down and recovers the target boxes on one image."""
    import optax

    params = yolos.init_params(jax.random.PRNGKey(0), TINY)
    image = jax.random.uniform(jax.random.PRNGKey(1), (1, 32, 32, 3))
    t_labels = jnp.array([[2, 4]])
    t_boxes = jnp.array([[[0.25, 0.25, 0.2, 0.2], [0.75, 0.75, 0.3, 0.3]]])

    opt = optax.adam(3e-3)
    state = opt.init(params)

    @jax.jit
    def step(params, state):
        def loss_fn(p):
            logits, boxes = yolos.forward(p, TINY, image)
            return set_criterion(logits, boxes, t_labels, t_boxes)["total"]
        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, state = opt.update(grads, state)
        return optax.apply_updates(params, updates), state, loss

    first = None
    for i in range(150):
        params, state, loss = step(params, state)
        if first is None:
            first = float(loss)
    assert float(loss) < 0.35 * first, (first, float(loss))

    logits, boxes = yolos.forward(params, TINY, image)
    out = yolos.postprocess(logits, boxes, top_k=2)
    assert set(np.asarray(out["labels"][0]).tolist()) == {2, 4}
    got = np.sort(np.asarray(out["boxes"][0]), axis=0)
    want = np.sort(np.asarray(cxcywh_to_xyxy(t_boxes[0])), axis=0)
    assert np.abs(got - want).max() < 0.15


def test_postprocess_topk_ordering():
    logits = jnp.array([[[0.0, 5.0, 0.0], [3.0, 0.0, 0.0], [0.0, 0.0, 9.0]]])
    boxes = jnp.tile(jnp.array([[0.5, 0.5, 0.2, 0.2]]), (1, 3, 1))
    out = yolos.postprocess(logits, boxes, top_k=2)
    # query 2's best real class prob is tiny (mass on no-object) -> the
    # two confident real-class queries win, highest score first
    assert np.asarray(out["labels"][0]).tolist() == [1, 0]
    assert float(out["scores"][0, 0]) > float(out["scores"][0, 1])
