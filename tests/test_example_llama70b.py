"""The north-star example (examples/llama3_70b_v5p.py) wired through the
real gang scheduler: the 128-worker gang it emits is admitted and placed
onto a complete v5p 8x8x8 ICI domain."""
import importlib.util
import os

import pytest

from nos_tpu.scheduler import framework as fw
from nos_tpu.scheduler.gang import GangScheduler

from conftest import example_pod_from_manifest, example_pool


def load_example():
    path = os.path.join(os.path.dirname(__file__), "..", "examples",
                        "llama3_70b_v5p.py")
    spec = importlib.util.spec_from_file_location("llama3_70b_v5p", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


EX = load_example()


def test_plan_numbers():
    p = EX.plan()
    assert p["params_b"] == pytest.approx(70.6, abs=0.2)
    assert p["chips"] == 512
    assert p["topology"] == "8x8x8"
    assert p["hosts"] == 128
    assert p["fits"] is True


def pod_from_manifest(m):
    return example_pod_from_manifest(m)


def v5p_pool(pool: str, hosts: int):
    return example_pool(pool, hosts, "tpu-v5p-slice", "8x8x8", 4)


def test_gang_admitted_and_placed_on_v5p_512():
    members = [pod_from_manifest(m) for m in EX.worker_pods()]
    assert len(members) == 128
    gs = GangScheduler(fw.SchedulerFramework())
    admission = gs.admit(members)
    assert admission.ok, admission.reason

    snapshot = fw.Snapshot.build(v5p_pool("v5p-512-pool", 128), [])
    placement, reason = gs.place(members, snapshot)
    assert placement is not None, reason
    assert len(placement.nodes) == 128
    # worker i lands on the domain's i-th host (torus alignment)
    assert placement.nodes[0] == "v5p-512-pool-000"
    assert placement.nodes[127] == "v5p-512-pool-127"


def test_gang_rejected_on_incomplete_pool():
    members = [pod_from_manifest(m) for m in EX.worker_pods()]
    gs = GangScheduler(fw.SchedulerFramework())
    snapshot = fw.Snapshot.build(v5p_pool("short-pool", 96), [])
    placement, reason = gs.place(members, snapshot)
    assert placement is None
    assert "incomplete" in reason


def test_gqa_model_forward_and_counts():
    import jax
    import jax.numpy as jnp

    from nos_tpu.models import transformer as tfm

    cfg = tfm.TransformerConfig(
        vocab=64, d_model=32, n_layers=2, n_heads=8, n_kv_heads=2,
        d_ff=64, max_seq=16, dtype=jnp.float32)
    params = tfm.init_params(jax.random.PRNGKey(0), cfg)
    assert params["layers"]["wk"].shape == (2, 32, 2 * cfg.head_dim)
    logits = tfm.forward(params, cfg, jnp.zeros((2, 8), jnp.int32))
    assert logits.shape == (2, 8, 64)
    assert bool(jnp.all(jnp.isfinite(logits)))
    with pytest.raises(ValueError, match="n_kv_heads"):
        tfm.TransformerConfig(n_heads=8, n_kv_heads=3)


def test_gqa_attention_matches_repeated_kv_reference():
    """Grouped attention (no kv materialization) must equal plain MHA over
    explicitly repeated kv — on both the xla path and the ring path."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from nos_tpu.ops.attention import xla_attention
    from nos_tpu.ops.ring_attention import ring_attention_sharded
    from nos_tpu.parallel.layout import ParallelLayout
    from nos_tpu.parallel.mesh import build_mesh

    b, h, hkv, s, d = 2, 8, 2, 16, 8
    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    q = jax.random.normal(ks[0], (b, h, s, d), jnp.float32)
    k = jax.random.normal(ks[1], (b, hkv, s, d), jnp.float32)
    v = jax.random.normal(ks[2], (b, hkv, s, d), jnp.float32)
    k_rep = jnp.repeat(k, h // hkv, axis=1)
    v_rep = jnp.repeat(v, h // hkv, axis=1)

    ref = xla_attention(q, k_rep, v_rep, causal=True)
    got = xla_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)

    mesh = build_mesh(ParallelLayout(sp=4), jax.devices()[:4])
    ring = ring_attention_sharded(mesh, q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(ring), np.asarray(ref),
                               rtol=1e-4, atol=1e-4)


def test_gqa_transformer_under_tp_and_sp_sharding():
    """GQA composes with tensor parallel (kv heads sharded over tp) and
    sequence parallel (ring attention circulates only kv heads)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from nos_tpu.models import transformer as tfm
    from nos_tpu.parallel.layout import ParallelLayout
    from nos_tpu.parallel.mesh import build_mesh

    cfg = tfm.TransformerConfig(
        vocab=64, d_model=32, n_layers=2, n_heads=8, n_kv_heads=4,
        d_ff=64, max_seq=32, dtype=jnp.float32)
    params = tfm.init_params(jax.random.PRNGKey(0), cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, cfg.vocab)
    ref = tfm.forward(params, cfg, tokens)              # unsharded reference

    mesh = build_mesh(ParallelLayout(dp=2, tp=2, sp=2), jax.devices()[:8])
    sharded = jax.device_put(params, tfm.param_shardings(mesh, cfg))
    got = jax.jit(lambda p, t: tfm.forward(p, cfg, t, mesh))(sharded, tokens)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)
