"""Gang scheduling + ICI locality (BASELINE.json config 4: multi-host
gang-schedule of a JAX JobSet with topology-aware placement)."""
import pytest

from nos_tpu import constants
from nos_tpu.api.quota import make_elastic_quota
from nos_tpu.kube import ApiServer, Manager
from nos_tpu.kube.objects import (
    Container,
    Node,
    NodeStatus,
    ObjectMeta,
    Pod,
    PodCondition,
    PodSpec,
    PodStatus,
)
from nos_tpu.parallel.layout import ParallelLayout
from nos_tpu.scheduler import Scheduler
from nos_tpu.tpu.ici import group_ici_domains

TPU = "google.com/tpu"


def slice_host(name, pool, topo="4x4", gen="tpu-v5-lite-podslice"):
    """One host (node) of a multi-host TPU slice node pool."""
    return Node(
        metadata=ObjectMeta(
            name=name,
            labels={
                constants.LABEL_TPU_ACCELERATOR: gen,
                constants.LABEL_TPU_TOPOLOGY: topo,
                constants.LABEL_NODEPOOL: pool,
            },
        ),
        status=NodeStatus(
            capacity={TPU: 8, "cpu": 96},
            allocatable={TPU: 8, "cpu": 96},
        ),
    )


def gang_pod(job, worker, size, topo="4x4", ns="team-a", tpu=8):
    return Pod(
        metadata=ObjectMeta(
            name=f"{job}-{worker}",
            namespace=ns,
            labels={
                constants.LABEL_GANG_NAME: job,
                constants.LABEL_GANG_SIZE: str(size),
                constants.LABEL_GANG_WORKER: str(worker),
            },
            annotations={constants.ANNOTATION_TPU_TOPOLOGY: topo},
        ),
        spec=PodSpec(
            containers=[Container(requests={TPU: tpu})],
            scheduler_name=constants.SCHEDULER_NAME,
        ),
        status=PodStatus(
            phase="Pending",
            conditions=[
                PodCondition(type="PodScheduled", status="False", reason="Unschedulable")
            ],
        ),
    )


def make_pool(server, pool, hosts, topo="4x4"):
    for i in range(hosts):
        server.create(slice_host(f"{pool}-w{i}", pool, topo))


def rig():
    server = ApiServer()
    mgr = Manager(server)
    mgr.add_controller(Scheduler().controller())
    return server, mgr


# ---------------------------------------------------------------------------
# ICI domain grouping
# ---------------------------------------------------------------------------

def test_group_ici_domains():
    nodes = [slice_host(f"a-w{i}", "pool-a") for i in range(2)]
    nodes += [slice_host(f"b-w{i}", "pool-b", topo="2x4") for i in range(1)]
    nodes.append(Node(metadata=ObjectMeta(name="plain")))   # not a TPU node
    domains = group_ici_domains(nodes)
    assert set(domains) == {"pool-a", "pool-b"}
    assert domains["pool-a"].hosts == 2
    assert [n.metadata.name for n in domains["pool-a"].nodes] == ["a-w0", "a-w1"]
    # v5e 4x4 = 16 chips = 2 hosts -> complete; 2x4 = 1 host -> complete
    assert domains["pool-a"].is_complete()
    assert domains["pool-b"].is_complete()


def test_incomplete_domain_detected():
    nodes = [slice_host("a-w0", "pool-a", topo="4x8")]   # 4x8 needs 4 hosts
    domains = group_ici_domains(nodes)
    assert not domains["pool-a"].is_complete()


def test_layout_to_gang_contract():
    """ParallelLayout -> topology -> gang size: the workload-plane contract
    the gang annotations carry."""
    layout = ParallelLayout(dp=2, tp=8)        # 16 chips
    topo = layout.required_topology("v5e")
    assert topo.name == "4x4"
    assert layout.hosts_required("v5e") == 2


# ---------------------------------------------------------------------------
# gang placement end-to-end
# ---------------------------------------------------------------------------

def test_gang_places_all_or_nothing_waits_for_members():
    server, mgr = rig()
    make_pool(server, "pool-a", 2)
    # only 1 of 2 members exists -> nothing binds
    server.create(gang_pod("train", 0, 2))
    mgr.run_until_idle()
    p0 = server.get("Pod", "train-0", "team-a")
    assert p0.spec.node_name == ""
    assert any("waiting for gang" in c.message for c in p0.status.conditions)
    # second member arrives -> whole gang binds onto pool-a in worker order
    server.create(gang_pod("train", 1, 2))
    mgr.run_until_idle()
    assert server.get("Pod", "train-0", "team-a").spec.node_name == "pool-a-w0"
    assert server.get("Pod", "train-1", "team-a").spec.node_name == "pool-a-w1"


def test_gang_requires_matching_topology_domain():
    server, mgr = rig()
    make_pool(server, "pool-a", 2, topo="4x4")
    for w in range(4):
        server.create(gang_pod("big", w, 4, topo="4x8"))   # needs 4-host 4x8
    mgr.run_until_idle()
    p = server.get("Pod", "big-0", "team-a")
    assert p.spec.node_name == ""
    assert any("4x8 does not fit in 4x4" in c.message
               for c in p.status.conditions)


def test_gang_never_spans_pools():
    """Two 1-host-free pools cannot host a 2-host gang (DCN crossing)."""
    server, mgr = rig()
    make_pool(server, "pool-a", 2)
    make_pool(server, "pool-b", 2)
    # occupy one host in each pool
    for pool in ("pool-a", "pool-b"):
        server.create(Pod(
            metadata=ObjectMeta(name=f"busy-{pool}", namespace="x"),
            spec=PodSpec(containers=[Container(requests={TPU: 8})],
                         node_name=f"{pool}-w0"),
            status=PodStatus(phase="Running"),
        ))
    for w in range(2):
        server.create(gang_pod("train", w, 2))
    mgr.run_until_idle()
    for w in range(2):
        assert server.get("Pod", f"train-{w}", "team-a").spec.node_name == ""


def test_gang_picks_free_pool():
    server, mgr = rig()
    make_pool(server, "pool-a", 2)
    make_pool(server, "pool-b", 2)
    # pool-a busy
    server.create(Pod(
        metadata=ObjectMeta(name="busy", namespace="x"),
        spec=PodSpec(containers=[Container(requests={TPU: 8})], node_name="pool-a-w0"),
        status=PodStatus(phase="Running"),
    ))
    for w in range(2):
        server.create(gang_pod("train", w, 2))
    mgr.run_until_idle()
    assert server.get("Pod", "train-0", "team-a").spec.node_name == "pool-b-w0"
    assert server.get("Pod", "train-1", "team-a").spec.node_name == "pool-b-w1"


def test_two_gangs_two_pools_no_interleave():
    server, mgr = rig()
    make_pool(server, "pool-a", 2)
    make_pool(server, "pool-b", 2)
    for job in ("j1", "j2"):
        for w in range(2):
            server.create(gang_pod(job, w, 2))
    mgr.run_until_idle()
    placements = {}
    for job in ("j1", "j2"):
        pools = set()
        for w in range(2):
            node = server.get("Pod", f"{job}-{w}", "team-a").spec.node_name
            assert node
            pools.add(node.rsplit("-w", 1)[0])
        assert len(pools) == 1, f"{job} spans pools {pools}"
        placements[job] = pools.pop()
    assert placements["j1"] != placements["j2"]


def test_gang_quota_all_or_nothing():
    server, mgr = rig()
    make_pool(server, "pool-a", 2)
    server.create(make_elastic_quota("qa", "team-a", min={TPU: 8}, max={TPU: 8}))
    # gang needs 16 chips but max is 8 -> nothing binds (not even worker 0)
    for w in range(2):
        server.create(gang_pod("train", w, 2))
    mgr.run_until_idle()
    for w in range(2):
        p = server.get("Pod", f"train-{w}", "team-a")
        assert p.spec.node_name == ""
        assert any("quota" in c.message for c in p.status.conditions)


def test_gang_invalid_worker_indexes_rejected():
    server, mgr = rig()
    make_pool(server, "pool-a", 2)
    server.create(gang_pod("train", 0, 2))
    server.create(gang_pod("train", 0, 2).__class__(  # duplicate worker 0
        metadata=ObjectMeta(
            name="train-dup", namespace="team-a",
            labels={
                constants.LABEL_GANG_NAME: "train",
                constants.LABEL_GANG_SIZE: "2",
                constants.LABEL_GANG_WORKER: "0",
            },
            annotations={constants.ANNOTATION_TPU_TOPOLOGY: "4x4"},
        ),
        spec=PodSpec(containers=[Container(requests={TPU: 8})],
                     scheduler_name=constants.SCHEDULER_NAME),
        status=PodStatus(phase="Pending", conditions=[
            PodCondition(type="PodScheduled", status="False", reason="Unschedulable")]),
    ))
    mgr.run_until_idle()
    p = server.get("Pod", "train-0", "team-a")
    assert p.spec.node_name == ""
    assert any("worker indexes" in c.message for c in p.status.conditions)


def test_gang_frees_and_reschedules():
    """A finished gang releases its slice; the next gang takes it."""
    server, mgr = rig()
    make_pool(server, "pool-a", 2)
    for w in range(2):
        server.create(gang_pod("first", w, 2))
    mgr.run_until_idle()
    # first gang done
    for w in range(2):
        server.delete("Pod", f"first-{w}", "team-a")
    for w in range(2):
        server.create(gang_pod("second", w, 2))
    mgr.run_until_idle()
    for w in range(2):
        assert server.get("Pod", f"second-{w}", "team-a").spec.node_name


def test_gang_partial_bind_recovery():
    """Crash between bind patches: worker 0 bound, worker 1 not. The next
    cycle must complete the gang on the same domain, worker-aligned."""
    server, mgr = rig()
    make_pool(server, "pool-a", 2)
    make_pool(server, "pool-b", 2)
    p0 = gang_pod("train", 0, 2)
    p0.spec.node_name = "pool-a-w0"   # pre-bound (partial prior cycle)
    server.create(p0)
    server.create(gang_pod("train", 1, 2))
    mgr.run_until_idle()
    assert server.get("Pod", "train-1", "team-a").spec.node_name == "pool-a-w1"


def test_gang_partial_bind_wrong_host_blocks():
    """A bound member sitting on a host that doesn't match its worker index
    must not be 'completed' into a torus-misaligned placement."""
    server, mgr = rig()
    make_pool(server, "pool-a", 2)
    p0 = gang_pod("train", 0, 2)
    p0.spec.node_name = "pool-a-w1"   # worker 0 on host 1: misaligned
    server.create(p0)
    server.create(gang_pod("train", 1, 2))
    mgr.run_until_idle()
    assert server.get("Pod", "train-1", "team-a").spec.node_name == ""


def test_gang_partial_bind_recovery_under_tight_quota():
    """Regression: admit() must not double-count already-bound members.
    Quota max fits the whole gang exactly (16 chips); worker 0 is already
    bound (its 8 chips are in QuotaInfo.used via state sync). Counting it
    again would compute 8 + 16 > 16 and wedge the gang forever."""
    server, mgr = rig()
    make_pool(server, "pool-a", 2)
    server.create(make_elastic_quota("qa", "team-a", min={TPU: 16}, max={TPU: 16}))
    p0 = gang_pod("train", 0, 2)
    p0.spec.node_name = "pool-a-w0"   # partial bind from a crashed cycle
    server.create(p0)
    server.create(gang_pod("train", 1, 2))
    mgr.run_until_idle()
    assert server.get("Pod", "train-1", "team-a").spec.node_name == "pool-a-w1"


# ---------------------------------------------------------------------------
# sub-cuboid placement (VERDICT r1 #4): gangs smaller than the pool
# ---------------------------------------------------------------------------

V5P = "tpu-v5p-slice"


def v5p_pool(server, pool, topo):
    """v5p pool (4 chips/host, 3D torus). 2x2x4 = 16 chips = 4 hosts."""
    from nos_tpu.tpu import topology as topo_mod
    gen = topo_mod.get_generation(V5P)
    t = topo_mod.find_slice_topology(V5P, topo)
    for i in range(gen.hosts_for(t)):
        n = slice_host(f"{pool}-w{i}", pool, topo, gen=V5P)
        n.status.capacity = {TPU: 4, "cpu": 96}
        n.status.allocatable = {TPU: 4, "cpu": 96}
        server.create(n)


def test_subcuboid_gang_on_larger_pool():
    """A 2x2x2 gang (2 hosts) occupies a contiguous half of an idle
    2x2x4 pool (4 hosts) instead of going unschedulable."""
    server, mgr = rig()
    v5p_pool(server, "pool-a", "2x2x4")
    for w in range(2):
        server.create(gang_pod("half", w, 2, topo="2x2x2", tpu=4))
    mgr.run_until_idle()
    # offset packs toward origin: workers on hosts 0,1 (contiguous along z)
    assert server.get("Pod", "half-0", "team-a").spec.node_name == "pool-a-w0"
    assert server.get("Pod", "half-1", "team-a").spec.node_name == "pool-a-w1"


def test_two_subcuboid_gangs_share_pool():
    """Two 2x2x2 gangs coexist on one 2x2x4 pool on disjoint contiguous
    blocks."""
    server, mgr = rig()
    v5p_pool(server, "pool-a", "2x2x4")
    for w in range(2):
        server.create(gang_pod("g1", w, 2, topo="2x2x2", tpu=4))
        server.create(gang_pod("g2", w, 2, topo="2x2x2", tpu=4))
    mgr.run_until_idle()
    g1 = {server.get("Pod", f"g1-{w}", "team-a").spec.node_name for w in range(2)}
    g2 = {server.get("Pod", f"g2-{w}", "team-a").spec.node_name for w in range(2)}
    assert g1 == {"pool-a-w0", "pool-a-w1"}
    assert g2 == {"pool-a-w2", "pool-a-w3"}


def test_two_4x4_gangs_share_8x8_pool_contiguously():
    """VERDICT done-criterion: two 4x4 gangs coexist on an 8x8 pool; each
    occupies an axis-aligned contiguous block of the host grid."""
    from nos_tpu.tpu.ici import group_ici_domains
    server, mgr = rig()
    make_pool(server, "pool-a", 8, topo="8x8")   # v5e 8x8 = 64 chips = 8 hosts
    for w in range(2):
        server.create(gang_pod("g1", w, 2, topo="4x4"))
        server.create(gang_pod("g2", w, 2, topo="4x4"))
    mgr.run_until_idle()

    domain = group_ici_domains(server.list("Node"))["pool-a"]
    shape = domain.host_shape                    # (4, 2) hosts
    names = [n.metadata.name for n in domain.nodes]

    def grid_coords(gang):
        out = []
        for w in range(2):
            node = server.get("Pod", f"{gang}-{w}", "team-a").spec.node_name
            assert node, f"{gang}-{w} not bound"
            idx = names.index(node)
            out.append((idx // shape[1], idx % shape[1]))
        return out

    c1, c2 = grid_coords("g1"), grid_coords("g2")
    assert not (set(c1) & set(c2))
    for coords in (c1, c2):
        # contiguous 2x1 block of the host grid: same column, adjacent rows
        (r0, col0), (r1, col1) = coords
        assert col0 == col1 and abs(r1 - r0) == 1


def test_exact_pool_preferred_over_carving():
    """Tightest fit: an exact-size 2x2x2 pool wins over carving a corner
    out of an idle 2x2x4 pool (which stays whole for bigger gangs)."""
    server, mgr = rig()
    v5p_pool(server, "pool-big", "2x2x4")
    v5p_pool(server, "pool-small", "2x2x2")
    for w in range(2):
        server.create(gang_pod("job", w, 2, topo="2x2x2", tpu=4))
    mgr.run_until_idle()
    for w in range(2):
        node = server.get("Pod", f"job-{w}", "team-a").spec.node_name
        assert node.startswith("pool-small"), node


def test_subcuboid_host_misaligned_topology_rejected():
    """A topology whose chip dims don't align to host boundaries can never
    be placed (no valid host tiling)."""
    from nos_tpu.tpu import topology as topo_mod
    assert topo_mod.host_shape(V5P, topo_mod.SliceTopology((3, 2, 2))) is None
    # and legal ones do align
    assert topo_mod.host_shape(V5P, topo_mod.SliceTopology((2, 2, 4))) == (1, 1, 4)
    assert topo_mod.host_shape("tpu-v5-lite-podslice",
                               topo_mod.SliceTopology((8, 8))) == (4, 2)
    # dimensionality mismatch (2D request vs 3D pool or vice versa) is
    # never a sub-topology — guards against zip-truncation double-binding
    assert not topo_mod.is_sub_topology(
        V5P, topo_mod.SliceTopology((2, 2, 2)), topo_mod.SliceTopology((4, 4)))
    assert topo_mod.is_sub_topology(
        V5P, topo_mod.SliceTopology((2, 2, 2)), topo_mod.SliceTopology((2, 2, 4)))


def test_host_order_natural_sort_large_pool():
    """Worker order must survive unpadded numeric suffixes: in a 16-host
    pool, 'w2' precedes 'w10' (natural sort), and an explicit host-index
    label overrides the name entirely."""
    from nos_tpu.tpu.ici import group_ici_domains, host_order_key
    nodes = [slice_host(f"pool-w{i}", "pool-a", topo="16x16") for i in range(16)]
    import random
    random.Random(7).shuffle(nodes)
    domains = group_ici_domains(nodes)
    order = [n.metadata.name for n in domains["pool-a"].nodes]
    assert order == [f"pool-w{i}" for i in range(16)]

    # label override wins over names
    labeled = [slice_host(f"host-{c}", "pool-b", topo="4x4") for c in "ab"]
    labeled[0].metadata.labels[constants.LABEL_TPU_HOST_INDEX] = "1"
    labeled[1].metadata.labels[constants.LABEL_TPU_HOST_INDEX] = "0"
    domains = group_ici_domains(labeled)
    assert [n.metadata.name for n in domains["pool-b"].nodes] == ["host-b", "host-a"]


def test_subcuboid_on_large_pool_uses_numeric_worker_order():
    """End-to-end: a 2-host gang carved from a 16-host v5e 8x16 pool
    lands on a contiguous host-grid block — not scrambled by
    lexicographic name order (w10 < w2)."""
    server, mgr = rig()
    make_pool(server, "pool-a", 16, topo="8x16")    # names w0..w15, unpadded
    for w in range(2):
        server.create(gang_pod("edge", w, 2, topo="4x4"))
    mgr.run_until_idle()
    bound = [server.get("Pod", f"edge-{w}", "team-a").spec.node_name
             for w in range(2)]
    from nos_tpu.tpu.ici import group_ici_domains
    domain = group_ici_domains(server.list("Node"))["pool-a"]
    names = [n.metadata.name for n in domain.nodes]
    shape = domain.host_shape                        # (8, 4)
    coords = [(names.index(b) // shape[1], names.index(b) % shape[1])
              for b in bound]
    (r0, c0), (r1, c1) = coords
    assert c0 == c1 and abs(r1 - r0) == 1            # contiguous block
