"""Continuous-batching decode serving (models/serving.py): slot reuse,
per-row cache depths, and bit-exact equivalence with generate()."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from nos_tpu.models import transformer as tfm
from nos_tpu.models.generate import forward_with_cache, generate, init_cache
from nos_tpu.models.serving import DecodeServer


def cfg_kw(**kw):
    base = dict(vocab=64, d_model=32, n_layers=2, n_heads=4, n_kv_heads=2,
                d_ff=64, max_seq=64, dtype=jnp.float32)
    base.update(kw)
    return tfm.TransformerConfig(**base)


CFG = cfg_kw()


@pytest.fixture(scope="module")
def params():
    return tfm.init_params(jax.random.PRNGKey(0), CFG)


def ref(params, prompt, n):
    out = generate(params, CFG, jnp.asarray([prompt], jnp.int32), n)
    return [int(t) for t in out[0]]


def test_vector_pos_matches_lockstep_rows(params):
    """forward_with_cache with a [B] pos vector must agree with running
    each row in its own scalar-pos cache at its own depth."""
    c = init_cache(CFG, 2, per_row_pos=True)
    # row 0 prefilled with 5 tokens, row 1 with 2 — different depths
    p0 = jnp.asarray([[3, 1, 4, 1, 5]], jnp.int32)
    p1 = jnp.asarray([[2, 7]], jnp.int32)
    s0 = init_cache(CFG, 1)
    s1 = init_cache(CFG, 1)
    _, s0 = forward_with_cache(params, CFG, p0, s0)
    _, s1 = forward_with_cache(params, CFG, p1, s1)
    c["k"] = c["k"].at[:, 0].set(s0["k"][:, 0]).at[:, 1].set(s1["k"][:, 0])
    c["v"] = c["v"].at[:, 0].set(s0["v"][:, 0]).at[:, 1].set(s1["v"][:, 0])
    c["pos"] = jnp.asarray([5, 2], jnp.int32)

    tok = jnp.asarray([[9], [8]], jnp.int32)
    got, c2 = forward_with_cache(params, CFG, tok, c)
    want0, _ = forward_with_cache(params, CFG, tok[:1], s0)
    want1, _ = forward_with_cache(params, CFG, tok[1:], s1)
    np.testing.assert_allclose(np.asarray(got[0]), np.asarray(want0[0]),
                               rtol=2e-4, atol=2e-4)
    np.testing.assert_allclose(np.asarray(got[1]), np.asarray(want1[0]),
                               rtol=2e-4, atol=2e-4)
    np.testing.assert_array_equal(np.asarray(c2["pos"]), [6, 3])


def test_server_matches_generate_per_request(params):
    srv = DecodeServer(params, CFG, max_batch=4)
    prompts = [[1, 2, 3], [60, 61], [7, 7, 7, 7, 7], [5]]
    rids = [srv.submit(p, 6) for p in prompts]
    results = srv.drain()
    for rid, p in zip(rids, prompts):
        assert results[rid] == ref(params, p, 6), f"request {rid}"


def test_slot_recycling_more_requests_than_slots(params):
    srv = DecodeServer(params, CFG, max_batch=2)
    prompts = [[i + 1, i + 2] for i in range(5)]
    rids = [srv.submit(p, 4 + (i % 3)) for i, p in enumerate(prompts)]
    results = srv.drain()
    assert len(results) == 5
    for i, (rid, p) in enumerate(zip(rids, prompts)):
        assert results[rid] == ref(params, p, 4 + (i % 3))


def test_late_arrivals_join_mid_flight(params):
    srv = DecodeServer(params, CFG, max_batch=3)
    r0 = srv.submit([1, 2, 3, 4], 8)
    for _ in range(3):
        srv.step()
    r1 = srv.submit([9, 9], 5)          # admitted while r0 is mid-decode
    results = srv.drain()
    assert results[r0] == ref(params, [1, 2, 3, 4], 8)
    assert results[r1] == ref(params, [9, 9], 5)


def test_validation(params):
    srv = DecodeServer(params, CFG, max_batch=2)
    with pytest.raises(ValueError, match="empty"):
        srv.submit([], 4)
    with pytest.raises(ValueError, match="exceeds"):
        srv.submit([1] * 60, 10)


def test_drain_returns_only_new_results_and_clears(params):
    srv = DecodeServer(params, CFG, max_batch=2)
    a = srv.submit([1, 2], 3)
    first = srv.drain()
    assert set(first) == {a}
    b = srv.submit([3, 4], 3)
    second = srv.drain()
    assert set(second) == {b}          # a's result was forgotten
    assert second[b] == ref(params, [3, 4], 3)


def test_random_schedules_stay_exact(params):
    """Crash-prober: random prompt lengths (spanning several prefill
    buckets), budgets, and arrival points over a 2-slot engine must stay
    bit-exact vs generate() for every request."""
    rng = np.random.default_rng(42)
    for trial in range(3):
        srv = DecodeServer(params, CFG, max_batch=2)
        n_req = int(rng.integers(3, 6))
        # lengths up to 40 hit the 8/16/32/64 buckets, not just the min
        reqs = [([int(t) for t in rng.integers(0, 64, rng.integers(1, 41))],
                 int(rng.integers(1, 7))) for _ in range(n_req)]
        rids = []
        for p, n in reqs:
            rids.append(srv.submit(p, n))
            # random interleaving: sometimes tick between submissions
            for _ in range(int(rng.integers(0, 3))):
                srv.step()
        results = srv.drain()
        for rid, (p, n) in zip(rids, reqs):
            assert results[rid] == ref(params, p, n), (trial, rid, p, n)


def test_random_schedules_compose_all_features(params):
    """Composition prober: random engine config (chunked prefill on/off,
    prefix cache on/off), random prefix publish/reuse, random mid-flight
    cancels, random interleavings — every surviving request stays
    bit-exact vs generate(). The single-feature probers above localize a
    failure; this one exists to catch feature INTERACTIONS."""
    rng = np.random.default_rng(7)
    # stratified over the config grid so no combination is left to the
    # luck of a fixed seed (see the spec-engine twin in
    # test_spec_serving.py for the review that motivated this)
    for trial, (chunk, pcache) in enumerate(
            [(0, 0), (8, 2), (16, 0), (0, 2), (16, 2)]):
        srv = DecodeServer(params, CFG, max_batch=2, prefill_chunk=chunk,
                           prefix_cache_size=pcache)
        system = [int(t) for t in rng.integers(0, 64, 12)]
        rids, reqs, canceled = [], [], set()
        for _ in range(int(rng.integers(3, 7))):
            if pcache and rng.random() < 0.5:
                p = system + [int(t) for t in
                              rng.integers(0, 64, rng.integers(1, 20))]
            else:
                p = [int(t) for t in rng.integers(0, 64, rng.integers(1, 41))]
            n = int(rng.integers(1, 7))
            kw = {"cache_prefix": True} \
                if pcache and rng.random() < 0.5 else {}
            rids.append(srv.submit(p, n, **kw))
            reqs.append((p, n))
            if rng.random() < 0.3:
                j = int(rng.integers(0, len(rids)))
                # cancel() is False for already-finished rids: those must
                # STAY in the exactness check below
                if rids[j] not in canceled and srv.cancel(rids[j]):
                    canceled.add(rids[j])
            for _ in range(int(rng.integers(0, 4))):
                srv.step()
        results = srv.drain()
        for rid, (p, n) in zip(rids, reqs):
            if rid in canceled:
                continue        # canceled: absent or truncated, both fine
            assert results[rid] == ref(params, p, n), \
                (trial, chunk, pcache, rid, p, n)


def test_engine_serves_int8_params(params):
    """The quantized pytree drops into the engine unchanged — int8
    serving must match int8 generate() exactly."""
    from nos_tpu.models.quant import quantize_params

    qp = quantize_params(params)
    srv = DecodeServer(qp, CFG, max_batch=2)
    rid = srv.submit([1, 2, 3], 5)
    results = srv.drain()
    want = [int(t) for t in
            generate(qp, CFG, jnp.asarray([[1, 2, 3]], jnp.int32), 5)[0]]
    assert results[rid] == want


def test_cancel_frees_slot_and_truncates(params):
    srv = DecodeServer(params, CFG, max_batch=1)   # one slot: queuing visible
    rid_a = srv.submit([1, 2], 32)            # occupies the only slot
    rid_b = srv.submit([3], 4)                # queued behind it
    for _ in range(3):
        srv.step()
    assert srv.cancel(rid_a)                  # truncate at current output
    out_a = srv.pop_result(rid_a)
    # prefill emitted token 1, then 3 decode steps: prompt + 4 tokens
    assert out_a == [1, 2] + out_a[2:] and len(out_a) == 2 + 4
    results = srv.drain()                     # b got the freed slot
    assert len(results[rid_b]) == 1 + 4
    assert not srv.cancel(rid_a)              # unknown rid now


def test_cancel_pending_request_never_decodes(params):
    srv = DecodeServer(params, CFG, max_batch=1)
    rid_a = srv.submit([1], 8)
    rid_b = srv.submit([2], 8)                # pending
    assert srv.cancel(rid_b)
    assert srv.pop_result(rid_b) == [2]       # prompt only, zero decoded
    results = srv.drain()
    assert len(results[rid_a]) == 1 + 8


# ---------------------------------------------------------------------------
# prefix cache
# ---------------------------------------------------------------------------

def test_prefix_reuse_is_exact(params):
    # a request sharing a published prefix must produce EXACTLY the
    # tokens of the uncached path — prefix KV reuse is a compute saving,
    # never a numerics change
    system = [7, 3, 5, 9, 2, 4, 1, 8]
    srv = DecodeServer(params, CFG, max_batch=2, prefix_cache_size=4)
    srv.submit(system + [11, 12], 6, cache_prefix=True)
    srv.drain()
    assert not srv.prefix_hits            # nothing cached before publish

    rid = srv.submit(system + [11, 12], 6)
    got = srv.drain()[rid]
    assert srv.prefix_hits == 1           # identical prompt: plen-1 reused
    assert srv.prefix_tokens_saved == len(system) + 1
    assert got == ref(params, system + [11, 12], 6)


def test_prefix_partial_overlap_and_sampling(params):
    system = [7, 3, 5, 9, 2, 4, 1, 8]
    srv = DecodeServer(params, CFG, max_batch=2, prefix_cache_size=4)
    srv.submit(system, 2, cache_prefix=True)
    srv.drain()

    # different suffixes over the shared prefix, greedy and sampled
    uncached = DecodeServer(params, CFG, max_batch=2)
    for suffix, sampling in ([13, 14], {}), ([15], dict(
            temperature=0.9, top_k=8, seed=42)):
        r1 = srv.submit(system + suffix, 5, **sampling)
        got1 = srv.drain()[r1]
        r2 = uncached.submit(system + suffix, 5, **sampling)
        got2 = uncached.drain()[r2]
        assert got1 == got2, (suffix, sampling)
    assert srv.prefix_hits == 2
    assert srv.prefix_tokens_saved == 2 * len(system)


def test_prefix_identical_prompt_still_needs_last_token(params):
    # prompt == cached prefix: reuse is capped at plen-1 so the final
    # token still runs to produce the next-token logits
    prompt = [5, 6, 7, 8]
    srv = DecodeServer(params, CFG, max_batch=1, prefix_cache_size=2)
    srv.submit(prompt, 3, cache_prefix=True)
    srv.drain()
    rid = srv.submit(prompt, 3)
    assert srv.drain()[rid] == ref(params, prompt, 3)


def test_prefix_lru_eviction(params):
    srv = DecodeServer(params, CFG, max_batch=1, prefix_cache_size=2)
    for base in ([1, 2, 3], [4, 5, 6], [7, 8, 9]):   # third evicts first
        srv.submit(base, 1, cache_prefix=True)
        srv.drain()
    assert len(srv._prefixes) == 2
    assert (None, (1, 2, 3)) not in srv._prefixes
    rid = srv.submit([1, 2, 3, 10], 3)               # evicted: no hit
    got = srv.drain()[rid]
    assert srv.prefix_hits == 0
    assert got == ref(params, [1, 2, 3, 10], 3)


def test_prefix_shrinks_to_fit_instead_of_discarding(params):
    # when prefix + padded-suffix bucket would overrun max_len, m shrinks
    # to keep partial reuse (the long prompts where savings matter most)
    srv = DecodeServer(params, CFG, max_batch=1, max_len=32,
                       prefix_cache_size=2)
    base = list(range(1, 21))                 # 20-token system prompt
    srv.submit(base, 1, cache_prefix=True)
    srv.drain()
    prompt = base + list(range(40, 50))       # plen 30: 20+_bucket(10)=36>32
    rid = srv.submit(prompt, 1)
    got = srv.drain()[rid]
    assert srv.prefix_hits == 1
    assert srv.prefix_tokens_saved == 16      # shrunk from 20 to fit
    assert got == ref(params, prompt, 1)


def test_trivial_prefix_overlap_not_counted(params):
    # a shared head too small to shrink the suffix bucket must not route
    # through the prefix path (same compute, extra copies) nor count as
    # savings in the metrics
    srv = DecodeServer(params, CFG, max_batch=1, prefix_cache_size=2)
    srv.submit([1, 2, 3], 1, cache_prefix=True)
    srv.drain()
    rid = srv.submit([1, 9, 9, 9, 9, 9], 2)   # shares only the first token
    got = srv.drain()[rid]
    assert srv.prefix_hits == 0
    assert srv.prefix_tokens_saved == 0
    assert got == ref(params, [1, 9, 9, 9, 9, 9], 2)


def test_republish_refreshes_lru_position(params):
    # re-publishing an existing key must move it to most-recently-used:
    # dict assignment alone keeps the OLD insertion slot, which would
    # evict the hot system prompt on the next publish
    srv = DecodeServer(params, CFG, max_batch=1, prefix_cache_size=2)
    for base in ([1, 2, 3], [4, 5, 6], [1, 2, 3], [7, 8, 9]):
        srv.submit(base, 1, cache_prefix=True)
        srv.drain()
    # keys are (scope, tokens): scope None outside tenant quota (the
    # tenant-scoped prefix cache partitions by request tenant)
    assert (None, (1, 2, 3)) in srv._prefixes      # republished: survived
    assert (None, (4, 5, 6)) not in srv._prefixes  # oldest: evicted


# ---------------------------------------------------------------------------
# stop tokens
# ---------------------------------------------------------------------------

def test_stop_token_truncates_and_frees_slot(params):
    srv = DecodeServer(params, CFG, max_batch=1)
    full = ref(params, [4, 5], 12)              # find a token to stop on
    stop = full[2 + 4]                          # 5th generated token
    rid_a = srv.submit([4, 5], 12, stop_tokens=[stop])
    rid_b = srv.submit([9], 2)                  # queued behind a
    results = srv.drain()
    got = results[rid_a]
    first_at = full.index(stop, 2)              # fires at FIRST occurrence
    assert got == full[:first_at + 1]
    assert got[-1] == stop                      # EOS included (HF convention)
    assert len(results[rid_b]) == 1 + 2         # slot freed for b


def test_stop_token_in_prefill_first_token(params):
    full = ref(params, [4, 5], 3)
    first = full[2]                             # token emitted by prefill
    srv = DecodeServer(params, CFG, max_batch=1)
    rid = srv.submit([4, 5], 8, stop_tokens=[first])
    assert srv.drain()[rid] == [4, 5, first]    # terminated immediately


def test_stop_token_never_seen_runs_to_max(params):
    srv = DecodeServer(params, CFG, max_batch=1)
    rid = srv.submit([4, 5], 6, stop_tokens=[63])   # assume 63 unseen
    got = srv.drain()[rid]
    want = ref(params, [4, 5], 6)
    assert got == want or got[-1] == 63


def test_max_pending_bounds_admission(params):
    """With all slots busy and the waiting line at max_pending, submit
    raises QueueFull; capacity freed by completion re-opens admission."""
    from nos_tpu.models.serving import QueueFull

    srv = DecodeServer(params, CFG, max_batch=1, max_pending=1)
    first = srv.submit([1, 2, 3], 30)
    srv.step()                       # first occupies the only slot
    srv.submit([4, 5], 30)           # fills the single waiting spot
    with pytest.raises(QueueFull, match="max_pending=1"):
        srv.submit([6], 2)
    results = srv.drain()            # everything completes
    assert len(results) == 2 and first in results
    srv.submit([7], 2)               # queue drained: admission re-opens
