"""Chaos acceptance suite (ISSUE 2): under a fixed-seed fault schedule
mixing node kill, lease expiry and maintenance notices against a cluster
with multi-host gangs —

- every displaced gang is rebound atomically (all-or-nothing, one ICI
  domain);
- zero pods are double-bound (no over-commit, no split gangs);
- whole-slice eviction fires on single-host failure;
- the run is bit-reproducible given the seed;
- the ``nos_lifecycle_*`` detection-latency / MTTR histograms are
  populated.

Fast storms run in tier-1; the multi-seed soak is ``slow``."""
import pytest

from nos_tpu import observability as obs
from nos_tpu.lifecycle.chaos import ChaosHarness, seeded_faults

# the pinned acceptance seed: its schedule mixes kill, lease expiry and
# maintenance (asserted below so a generator change cannot silently
# weaken the scenario)
SEED = 7


def test_seeded_schedule_is_deterministic():
    nodes = [f"n-{i}" for i in range(8)]
    a = seeded_faults(123, nodes, 60.0, n_faults=6)
    b = seeded_faults(123, list(reversed(nodes)), 60.0, n_faults=6)
    assert a == b                     # node-order independent
    assert a != seeded_faults(124, nodes, 60.0, n_faults=6)
    assert all(f.at <= 0.55 * 60.0 for f in a)
    assert all(f.recover_at <= 0.85 * 60.0 for f in a if f.recover_at)


def test_fixed_seed_storm_repairs_all_gangs_atomically():
    harness = ChaosHarness(seed=SEED)
    kinds = {f.kind for f in harness.faults}
    # the acceptance mix: node kill + lease expiry + maintenance at least
    assert {"kill", "expire", "maintenance"} <= kinds, kinds

    det_before, _ = obs.LIFECYCLE_DETECTION.observations()
    mttr_before, _ = obs.LIFECYCLE_MTTR.observations()
    report = harness.run()

    # zero double-binds, and every invariant held on every tick
    assert report.double_binds == 0, report.invariant_violations
    assert report.invariant_violations == []
    # whole-slice eviction fired for single-host failures
    assert report.slice_evictions >= 1
    # every displaced gang is rebound atomically by the end of the run
    assert report.unrepaired_gangs == []
    assert report.unbound_pods_final == 0
    assert len(report.mttr_s) >= 1
    assert len(report.detection_s) >= 1
    # histograms populated
    det_after, _ = obs.LIFECYCLE_DETECTION.observations()
    mttr_after, _ = obs.LIFECYCLE_MTTR.observations()
    assert det_after > det_before
    assert mttr_after > mttr_before


def test_fixed_seed_storm_is_bit_reproducible():
    a = ChaosHarness(seed=SEED).run()
    b = ChaosHarness(seed=SEED).run()
    assert a.log == b.log
    assert a.fingerprint() == b.fingerprint()
    # and a different seed takes a different path
    c = ChaosHarness(seed=SEED + 1).run()
    assert c.fingerprint() != a.fingerprint()


def test_watch_flap_does_not_strand_work():
    """A storm forced to flap-only: the stream drop + re-list must leave
    the world fully bound (the re-list purges stale cache entries)."""
    harness = ChaosHarness(seed=3, n_faults=3, kinds=("flap",),
                           duration_s=30.0)
    report = harness.run()
    assert sum(1 for f in report.faults if f.kind == "flap") == 3
    assert report.double_binds == 0
    assert report.unbound_pods_final == 0


@pytest.mark.slow
def test_chaos_soak_many_seeds():
    """Long soak: every seed in a band must satisfy the acceptance
    invariants (the loop-until-dry version of the fixed-seed test)."""
    for seed in range(16):
        report = ChaosHarness(seed=seed, duration_s=90.0,
                              n_faults=8).run()
        assert report.double_binds == 0, (seed, report.invariant_violations)
        assert report.unrepaired_gangs == [], (seed, report.unrepaired_gangs)
        assert report.unbound_pods_final == 0, seed
