"""Board geometry state machine (model: reference pkg/gpu/mig/gpu_test.go)."""
import pytest

from nos_tpu.tpu.host import TpuBoard
from nos_tpu.tpu.slice import Profile

P11, P22, P24 = Profile(1, 1), Profile(2, 2), Profile(2, 4)


def test_init_geometry_uses_fewest_slices():
    b = TpuBoard(generation="v5e")
    b.init_geometry()
    assert b.geometry == {P24: 1}
    assert b.free == {P24: 1} and b.used == {}


def test_init_geometry_noop_when_partitioned():
    b = TpuBoard(generation="v5e", free={P11: 8})
    b.init_geometry()
    assert b.geometry == {P11: 8}


def test_can_apply_geometry_never_deletes_used():
    b = TpuBoard(generation="v5e", used={P22: 1}, free={P22: 1})
    assert b.can_apply_geometry({P22: 2})
    assert b.can_apply_geometry({P22: 1, P11: 4})
    assert not b.can_apply_geometry({P11: 8})      # would delete the used 2x2
    assert not b.can_apply_geometry({P24: 1})      # ditto
    assert not b.can_apply_geometry({P22: 3})      # not a legal tiling


def test_apply_geometry_recomputes_free():
    b = TpuBoard(generation="v5e", used={P22: 1}, free={P22: 1})
    b.apply_geometry({P22: 1, P11: 4})
    assert b.used == {P22: 1}
    assert b.free == {P11: 4}


def test_apply_illegal_geometry_raises():
    b = TpuBoard(generation="v5e", used={P24: 1})
    with pytest.raises(ValueError):
        b.apply_geometry({P11: 8})


def test_update_geometry_for_repartitions_to_demand():
    b = TpuBoard(generation="v5e")
    b.init_geometry()                       # 1x(2x4), all free
    changed = b.update_geometry_for({P11: 3})
    assert changed
    assert b.free.get(P11, 0) >= 3


def test_update_geometry_for_prefers_less_fragmentation():
    b = TpuBoard(generation="v5e")
    b.init_geometry()
    b.update_geometry_for({P22: 1})
    # both {2x2:2} and {2x2:1,1x1:4} provide one 2x2; fewest-slices tie-break
    assert b.geometry == {P22: 2}


def test_update_geometry_noop_when_demand_already_served():
    b = TpuBoard(generation="v5e", free={P11: 8})
    assert not b.update_geometry_for({P11: 2})
    assert b.geometry == {P11: 8}


def test_update_geometry_respects_used_slices():
    b = TpuBoard(generation="v5e", used={P22: 1}, free={P22: 1})
    changed = b.update_geometry_for({P11: 4})
    assert changed
    assert b.used == {P22: 1}
    assert b.free == {P11: 4}


def test_update_geometry_impossible_demand_returns_false():
    b = TpuBoard(generation="v5e", used={P22: 2})   # board full with used slices
    assert not b.update_geometry_for({P11: 1})
    assert b.geometry == {P22: 2}


def test_reserve_release_roundtrip():
    b = TpuBoard(generation="v5e", free={P11: 2})
    assert b.reserve(P11)
    assert b.used == {P11: 1} and b.free == {P11: 1}
    assert b.reserve(P11)
    assert not b.reserve(P11)               # none free
    b.release(P11)
    assert b.free == {P11: 1}
    with pytest.raises(ValueError):
        b.release(P22)


def test_clone_is_independent():
    b = TpuBoard(generation="v5e", free={P11: 8})
    c = b.clone()
    c.reserve(P11)
    assert b.free == {P11: 8}
