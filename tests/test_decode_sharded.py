"""Tensor-parallel decode over a device mesh: generate() and the
continuous-batching DecodeServer run with params sharded by
transformer.param_shardings and the KV cache sharded by
generate.cache_shardings (KV heads over ``tp``), and the tokens are
IDENTICAL to the single-device run — sharding splits the matmuls and
cache reads, never the math. This is the serving analog of the training
plane's dryrun_multichip: the reference has no model plane at all
(SURVEY §2.7); this pins the distributed-inference contract of ours."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from nos_tpu.models import transformer as tfm
from nos_tpu.models.generate import cache_shardings, generate
from nos_tpu.models.serving import DecodeServer

CFG = tfm.TransformerConfig(
    vocab=64, d_model=32, n_layers=2, n_heads=4, n_kv_heads=2,
    d_ff=64, max_seq=64, dtype=jnp.float32)


@pytest.fixture(scope="module")
def params():
    return tfm.init_params(jax.random.PRNGKey(0), CFG)


@pytest.fixture(scope="module")
def mesh():
    devs = np.asarray(jax.devices()[:8]).reshape(4, 2)
    return Mesh(devs, ("dp", "tp"))


@pytest.fixture(scope="module")
def sharded_params(params, mesh):
    return jax.device_put(params, tfm.param_shardings(mesh, CFG))


def toks(arr):
    return np.asarray(arr).tolist()


def test_generate_greedy_invariant_to_tp(params, sharded_params):
    prompt = jnp.asarray([[3, 1, 4, 1, 5], [2, 7, 1, 8, 2]], jnp.int32)
    want = generate(params, CFG, prompt, 12)
    got = jax.jit(
        lambda p: generate(p, CFG, prompt, 12))(sharded_params)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_generate_sampled_invariant_to_tp(params, sharded_params, mesh):
    # Fixed after two seed-old failing rounds: the sharded logits
    # differ from single-device only by tp reduction-order ULPs
    # (greedy argmax absorbs those — the greedy twins above always
    # passed), but the categorical draw itself diverged because GSPMD
    # propagates the vocab sharding backward into the threefry
    # program, whose partitioned lowering draws DIFFERENT gumbel bits.
    # generate(mesh=...) now canonicalizes every sampling decision
    # onto a replicated f32 logit row (generate.replicated_logits), so
    # the sharded engine runs the exact single-device sampling program
    # — same bits, same stream.
    prompt = jnp.asarray([[5, 9, 2]], jnp.int32)
    kw = dict(temperature=0.8, top_k=16, top_p=0.9,
              rng=jax.random.PRNGKey(7))
    want = generate(params, CFG, prompt, 10, **kw)
    got = jax.jit(
        lambda p: generate(p, CFG, prompt, 10, mesh=mesh,
                           **kw))(sharded_params)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_cache_shardings_shape_and_validation(mesh):
    shd = cache_shardings(mesh, CFG, per_row_pos=True)
    assert shd["k"].spec == P(None, None, "tp", None, None)
    assert shd["pos"].spec == P(None)
    bad = tfm.TransformerConfig(
        vocab=64, d_model=48, n_layers=2, n_heads=3, n_kv_heads=3,
        d_ff=64, max_seq=64, dtype=jnp.float32)
    with pytest.raises(ValueError, match="not divisible by tp"):
        cache_shardings(mesh, bad)


def test_server_tokens_invariant_to_mesh(params, sharded_params, mesh):
    """The full engine — bucketed prefill, install, continuous decode,
    slot recycling — over the mesh, token-identical to the unsharded
    engine, greedy and sampled slots mixed in one batch. The sampled
    slots are the seed-old regression: fixed by the engine
    canonicalizing every sampling decision onto a replicated f32 row
    (see test_generate_sampled_invariant_to_tp)."""
    reqs = [
        ([3, 1, 4, 1, 5], 8, dict()),
        ([2, 7], 10, dict(temperature=0.7, top_k=8, seed=3)),
        ([9, 9, 1, 2], 6, dict(temperature=0.5, top_p=0.8, seed=11)),
    ]

    def run(srv):
        rids = [srv.submit(p, n, **kw) for p, n, kw in reqs]
        out = srv.drain()
        return [out[r] for r in rids]

    want = run(DecodeServer(params, CFG, max_batch=2))
    got = run(DecodeServer(sharded_params, CFG, max_batch=2, mesh=mesh))
    assert got == want
    # cache actually lives sharded: the heads axis spans the tp axis
    srv = DecodeServer(sharded_params, CFG, max_batch=2, mesh=mesh)
    assert srv.cache["k"].sharding.spec == P(None, None, "tp", None, None)


def test_server_prefix_cache_under_mesh(params, sharded_params, mesh):
    sys_prompt = [7, 3, 7, 3, 7, 3, 7, 3, 7, 3, 7, 3]

    def run(srv):
        a = srv.submit(sys_prompt + [1], 4, cache_prefix=True)
        srv.drain()
        b = srv.submit(sys_prompt + [2], 4)
        srv.drain()
        return srv.pop_result(a), srv.pop_result(b), srv.prefix_hits

    pa, pb, _ = run(DecodeServer(params, CFG, max_batch=2,
                                 prefix_cache_size=4))
    sa, sb, hits = run(DecodeServer(sharded_params, CFG, max_batch=2,
                                    prefix_cache_size=4, mesh=mesh))
    assert (sa, sb) == (pa, pb)
    assert hits >= 1


def test_int8_generate_and_server_invariant_to_tp(params, mesh):
    """tp + int8 compose: the quantized tree sharded by
    quant_param_shardings produces the SAME tokens as single-device
    int8 decode, through generate() and the serving engine."""
    from nos_tpu.models.quant import quant_param_shardings, quantize_params

    qp = quantize_params(params)
    qp_sharded = jax.device_put(qp, quant_param_shardings(mesh, CFG))

    prompt = jnp.asarray([[3, 1, 4, 1, 5]], jnp.int32)
    want = generate(qp, CFG, prompt, 10)
    got = jax.jit(lambda p: generate(p, CFG, prompt, 10))(qp_sharded)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))

    plain = DecodeServer(qp, CFG, max_batch=2)
    r0 = plain.submit([3, 1, 4, 1, 5], 6)
    plain_out = plain.drain()[r0]
    srv = DecodeServer(qp_sharded, CFG, max_batch=2, mesh=mesh)
    r1 = srv.submit([3, 1, 4, 1, 5], 6)
    assert srv.drain()[r1] == plain_out
