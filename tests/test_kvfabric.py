"""Fleet-wide KV fabric (ISSUE 17): tiered prefix cache with host-RAM
demotion and cross-replica chain migration.

The acceptance invariants this file pins:
- a demote -> promote round trip is BYTE-identical at the KV-plane
  level (k/v AND the int8 scale planes — the chain's bytes never
  change, they only move tiers), and the served tokens stay bit-exact;
- cross-tenant chains never match nor migrate across scopes: a
  demoted chain is invisible to other tenants' misses, and an
  exported chain is rejected on ingest under a different tenant;
- the host tier is a bounded LRU over payload BYTES: inserting past
  capacity evicts oldest-first, an entry larger than the whole store
  is rejected outright;
- a promotion racing a concurrent decode step on a real paged engine
  is safe — both the in-flight request and the promoted-prefix
  request finish bit-identical to generate();
- export_chain/ingest_chain move a chain between two REAL engines
  with bit-exact downstream decode, and a digest mismatch rejects.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from nos_tpu.kvfabric import (
    FleetPrefixIndex, HostTierStore, chain_digest, decode_chain,
    encode_chain,
)
from nos_tpu.models import transformer as tfm
from nos_tpu.models.generate import generate
from nos_tpu.models.serving import DecodeServer
from nos_tpu.models.tenantquota import TenantQuotaConfig, TenantSpec

CFG = tfm.TransformerConfig(vocab=64, d_model=32, n_layers=2, n_heads=4,
                            n_kv_heads=2, d_ff=64, max_seq=64,
                            dtype=jnp.float32)


@pytest.fixture(scope="module")
def params():
    return tfm.init_params(jax.random.PRNGKey(0), CFG)


def ref(params, prompt, n):
    out = generate(params, CFG, jnp.asarray([prompt], jnp.int32), n)
    return [int(t) for t in out[0]]


def fabric_engine(params, host_bytes=1 << 20, prefix_blocks=8, **kw):
    kw.setdefault("max_batch", 2)
    kw.setdefault("kv_block_size", 8)
    kw.setdefault("kv_blocks", 24)
    kw.setdefault("kv_dtype", "int8")
    host = HostTierStore(host_bytes) if host_bytes else None
    eng = DecodeServer(params, CFG, prefix_cache_size=prefix_blocks,
                       host_tier=host, **kw)
    return eng, host


def swap_bytes(eng, blocks):
    """The chain's KV planes as raw bytes, per array key — the
    bytes-pin the tiering must preserve exactly."""
    swap = eng._swap_payload(list(blocks), len(blocks))
    return {k: np.asarray(v).tobytes()
            for k, v in swap.items() if k != "nblk"}


def quota(share_prefix=False):
    return TenantQuotaConfig(
        tenants={"gold": TenantSpec("gold"),
                 "burst": TenantSpec("burst")},
        window_s=8.0, share_prefix=share_prefix)


# ---------------------------------------------------------------------------
# codec
# ---------------------------------------------------------------------------

def test_chain_digest_embeds_scope():
    toks = [1, 2, 3, 4]
    assert chain_digest(toks) == chain_digest(list(toks))
    assert chain_digest(toks) != chain_digest(toks, "gold")
    assert chain_digest(toks, "gold") != chain_digest(toks, "burst")
    # token boundaries are unambiguous: [1, 23] vs [12, 3]
    assert chain_digest([1, 23]) != chain_digest([12, 3])


def test_encode_decode_chain_roundtrip_bytes():
    rng = np.random.default_rng(0)
    swap = {
        "k": rng.integers(-128, 127, (2, 3, 2, 8, 8), dtype=np.int8),
        "v": rng.integers(-128, 127, (2, 3, 2, 8, 8), dtype=np.int8),
        "k_scale": rng.random((2, 3, 2, 1, 8), dtype=np.float32),
        "v_scale": rng.random((2, 3, 2, 1, 8), dtype=np.float32),
        "nblk": 3,
    }
    data = encode_chain("gold", [5, 6, 7], swap)
    state = decode_chain(data)
    assert state["scope"] == "gold" and state["tokens"] == [5, 6, 7]
    for key in ("k", "v", "k_scale", "v_scale"):
        out = state["swap"][key]
        assert out.dtype == swap[key].dtype
        assert out.tobytes() == swap[key].tobytes(), key


def test_decode_chain_rejects_foreign_payload():
    from nos_tpu.models.handoff import encode_handoff
    blob = encode_handoff({"swap": {"k": np.zeros((1, 1), np.int8)}})
    with pytest.raises(ValueError):
        decode_chain(blob)


# ---------------------------------------------------------------------------
# host tier
# ---------------------------------------------------------------------------

def _swap(n=1, fill=0):
    return {"k": np.full((2, n, 2, 8, 8), fill, np.int8),
            "v": np.full((2, n, 2, 8, 8), fill, np.int8),
            "nblk": n}


def test_host_tier_capacity_bound_evicts_lru():
    one = sum(np.asarray(v).nbytes for k, v in _swap().items()
              if k != "nblk")
    store = HostTierStore(2 * one)
    assert store.put(None, [1] * 8, _swap(fill=1))
    assert store.put(None, [2] * 8, _swap(fill=2))
    assert len(store) == 2 and store.nbytes == 2 * one
    # a read refreshes LRU order: chain 1 becomes most-recent…
    assert store.match(None, [1] * 8 + [9] * 8, 8) is not None
    assert store.get((None, tuple([1] * 8))) is not None
    # …so inserting a third evicts chain 2, not chain 1
    assert store.put(None, [3] * 8, _swap(fill=3))
    assert len(store) == 2 and store.nbytes == 2 * one
    assert store.match(None, [2] * 8, 8) is None
    assert store.match(None, [1] * 8, 8) is not None
    assert store.counts["evicted"] == 1


def test_host_tier_rejects_oversize_chain():
    store = HostTierStore(16)           # smaller than any real payload
    assert not store.put(None, [1] * 8, _swap())
    assert len(store) == 0 and store.counts["rejected"] == 1


def test_host_tier_match_is_scope_filtered():
    store = HostTierStore(1 << 20)
    assert store.put("gold", [1] * 8, _swap())
    assert store.match("gold", [1] * 16, 16) is not None
    assert store.match("burst", [1] * 16, 16) is None
    assert store.match(None, [1] * 16, 16) is None


def test_host_tier_longest_match_wins():
    store = HostTierStore(1 << 20)
    store.put(None, [1] * 8, _swap(1))
    store.put(None, [1] * 16, _swap(2))
    key = store.match(None, [1] * 24, 24)
    assert key is not None and len(key[1]) == 16
    # cap bounds the usable prefix: only the short chain fits under 8
    key = store.match(None, [1] * 24, 8)
    assert key is not None and len(key[1]) == 8


# ---------------------------------------------------------------------------
# fleet index
# ---------------------------------------------------------------------------

def test_fleet_index_sync_ages_out_missing_replicas():
    idx = FleetPrefixIndex()
    row = {"digest": "abc", "len": 16, "tier": "hbm"}
    idx.sync({"rep-0": {"chains": [row]}, "rep-1": {"chains": [row]}})
    assert len(idx.holders("abc")) == 2
    assert idx.holders("abc", exclude="rep-0") == [("rep-1", row)]
    # rep-1 left the scrape set (departed or unscrapable): aged out
    idx.sync({"rep-0": {"chains": [row]}})
    assert [n for n, _ in idx.holders("abc")] == ["rep-0"]
    # a replica that stops reporting the section ages out too
    idx.sync({"rep-0": None})
    assert idx.holders("abc") == []
    assert idx.stats() == {"replicas": 0, "chains": 0}


# ---------------------------------------------------------------------------
# demote -> promote on a real paged engine
# ---------------------------------------------------------------------------

def test_demote_promote_roundtrip_byte_identical(params):
    eng, host = fabric_engine(params, prefix_blocks=1)
    sys_a, sys_b = [7] * 8, [9] * 8
    eng.submit(sys_a + [1, 2], 4, cache_prefix=True)
    eng.drain()
    key = (None, tuple(sys_a))
    blocks = dict(eng._pindex.chain_items())[key]
    before = swap_bytes(eng, blocks)
    # publishing a second chain into a 1-block cache demotes the first
    eng.submit(sys_b + [3, 4], 4, cache_prefix=True)
    eng.drain()
    assert eng._fabric["demote"] == 1
    assert eng._pindex.evicted == {"drop": 0, "demote": 1}
    assert host.match(None, sys_a, 8) == key
    # a prefix miss on the demoted chain promotes it back, bit-exact
    out = eng.submit(sys_a + [5, 6], 6)
    res = eng.drain()
    assert eng._fabric["promote"] == 1
    assert host.match(None, sys_a, 8) is None   # one tier at a time
    assert res[out] == ref(params, sys_a + [5, 6], 6)
    blocks = dict(eng._pindex.chain_items())[key]
    after = swap_bytes(eng, blocks)
    assert set(after) == {"k", "v", "k_scale", "v_scale"}
    for plane, want in before.items():
        assert after[plane] == want, f"{plane} changed across tiers"


def test_demotion_falls_back_to_drop_without_host_room(params):
    # a host tier too small for any chain: eviction counts as a drop,
    # the engine keeps working, nothing is promoted later
    eng, host = fabric_engine(params, host_bytes=16, prefix_blocks=1)
    eng.submit([7] * 8 + [1], 3, cache_prefix=True)
    eng.drain()
    eng.submit([9] * 8 + [2], 3, cache_prefix=True)
    eng.drain()
    assert eng._pindex.evicted == {"drop": 1, "demote": 0}
    assert len(host) == 0 and host.counts["rejected"] == 1
    rid = eng.submit([7] * 8 + [1, 2], 4)
    res = eng.drain()
    assert eng._fabric["promote"] == 0
    assert res[rid] == ref(params, [7] * 8 + [1, 2], 4)


def test_promote_races_concurrent_decode(params):
    # the oracle is the SAME int8 engine without any tiering traffic
    # (int8 KV quantization legitimately drifts from fp32 generate()
    # over a long decode; the invariant here is that a promotion
    # landing mid-flight changes NOTHING for either request)
    sys_a = [7] * 8
    oracle, _ = fabric_engine(params, host_bytes=0, prefix_blocks=8)
    oracle.submit(sys_a + [1, 2], 4, cache_prefix=True)
    oracle.drain()
    o0 = oracle.submit([4, 5], 24)
    oracle.step()
    o1 = oracle.submit(sys_a + [5, 6], 6)
    want = oracle.drain()

    eng, host = fabric_engine(params, prefix_blocks=1)
    eng.submit(sys_a + [1, 2], 4, cache_prefix=True)
    eng.drain()
    eng.submit([9] * 8 + [3], 4, cache_prefix=True)
    eng.drain()
    assert eng._fabric["demote"] == 1
    # a long request decodes IN FLIGHT while the promote dispatches
    r0 = eng.submit([4, 5], 24)
    eng.step()
    r1 = eng.submit(sys_a + [5, 6], 6)
    res = eng.drain()
    assert eng._fabric["promote"] == 1
    assert res[r0] == want[o0]
    assert res[r1] == want[o1]
    # quiescent pool stays balanced after the cross-tier traffic
    held = eng._pindex.block_count
    assert eng._alloc.used_count == held


def test_failed_promote_that_evicts_the_matched_chain_re_matches(params):
    """Regression: a promotion whose ingest FAILS can still have run
    evict_lru — and that sweep can take the very chain the caller's
    pre-promotion match returned (a COW-shared chain's eviction frees
    zero blocks, so the index empties and the ingest still comes up
    short). _promote_from_host used to return the stale pre-eviction
    (m, mkey) on that path; take(mkey) then raises KeyError. The
    contract now: the returned (m, mkey) is ALWAYS a fresh match —
    take-able or (0, None) — after any ingest attempt."""
    from nos_tpu.models.serving import _Request

    sys8, sys32 = [7] * 8, [7] * 32

    # oracle: same int8 quantization, no tiering traffic
    oracle, _ = fabric_engine(params, host_bytes=0, prefix_blocks=8)
    o0 = oracle.submit(sys8 + [2], 8)
    o1 = oracle.submit(sys32 + [9], 2)
    want = oracle.drain()

    # donor builds the 4-block chain payload the host tier will hold
    donor, _ = fabric_engine(params, prefix_blocks=8)
    donor.submit(sys32 + [1], 2, cache_prefix=True)
    donor.drain()
    dblocks = dict(donor._pindex.chain_items())[(None, tuple(sys32))]
    payload = donor._swap_payload(list(dblocks), len(dblocks))

    # 5-block pool (one reserved): chain A ([7]*8) published + a live
    # request COW-sharing it leaves 3 free — the 4-block host chain
    # can't land, and evicting A frees nothing (r0 holds its block)
    eng, host = fabric_engine(params, prefix_blocks=4, kv_blocks=6)
    assert host.put(None, tuple(sys32), payload)
    eng.submit(sys8 + [1], 2, cache_prefix=True)
    eng.drain()
    r0 = eng.submit(sys8 + [2], 8)          # COW-shares A's block
    eng.step()

    # drive the promotion exactly as admission would: match hits A
    # (m=8), the host tier holds a strictly longer chain, and the
    # ingest's eviction sweep takes A with it before coming up short
    probe = _Request(rid=-1, prompt=sys32 + [9], max_new_tokens=2)
    m, mkey = eng._pindex.match(probe.prompt, len(probe.prompt) - 1,
                                None)
    assert (m, mkey) == (8, (None, tuple(sys8)))
    m2, mkey2 = eng._promote_from_host(probe, m, mkey,
                                       len(probe.prompt))
    assert eng._fabric["promote"] == 0      # the ingest came up dry
    assert eng._fabric["demote"] == 1       # ...after demoting A
    chains = dict(eng._pindex.chain_items())
    assert (None, tuple(sys8)) not in chains
    # pre-fix this returned the stale (8, A-key): take(mkey2) would
    # KeyError and kill the admission
    assert (m2, mkey2) == (0, None)
    assert host.get((None, tuple(sys32))) is not None

    # end-to-end: the same squeeze through real admission parks the
    # request (headroom), and r0's completion lets the retried
    # admission promote the host chain for real — bit-exact decode
    r1 = eng.submit(sys32 + [9], 2)
    res = eng.drain()
    assert eng._fabric["promote"] == 1
    assert host.get((None, tuple(sys32))) is None    # moved tiers
    assert host.get((None, tuple(sys8))) is not None  # A still demoted
    assert res[r0] == want[o0]
    assert res[r1] == want[o1]
    # quiescent pool stays balanced after the cross-tier traffic
    assert eng._alloc.used_count == eng._pindex.block_count


def test_bf16_chains_tier_byte_identical(params):
    # the fabric is dtype-agnostic: no scale planes under bf16, and
    # the k/v planes still round-trip bit-exact
    eng, host = fabric_engine(params, prefix_blocks=1, kv_dtype="bf16")
    sys_a = [7] * 8
    eng.submit(sys_a + [1], 3, cache_prefix=True)
    eng.drain()
    key = (None, tuple(sys_a))
    before = swap_bytes(eng, dict(eng._pindex.chain_items())[key])
    assert set(before) == {"k", "v"}
    eng.submit([9] * 8 + [2], 3, cache_prefix=True)
    eng.drain()
    rid = eng.submit(sys_a + [5], 4)
    res = eng.drain()
    # promote re-publishes sys_a into the 1-block cache, which in turn
    # demotes the OTHER chain — the tiers keep trading, nothing drops
    assert eng._fabric == {"demote": 2, "promote": 1, "ingest": 0,
                           "ingest_rejected": 0}
    assert res[rid] == ref(params, sys_a + [5], 4)
    after = swap_bytes(eng, dict(eng._pindex.chain_items())[key])
    assert after == before


# ---------------------------------------------------------------------------
# tenant isolation
# ---------------------------------------------------------------------------

def test_cross_tenant_chains_never_match_nor_migrate(params):
    eng, host = fabric_engine(params, prefix_blocks=1,
                              tenant_quota=quota())
    sys_a = [7] * 8
    eng.submit(sys_a + [1], 3, cache_prefix=True, tenant="gold")
    eng.drain()
    eng.submit([9] * 8 + [2], 3, cache_prefix=True, tenant="gold")
    eng.drain()
    assert eng._fabric["demote"] == 1
    assert host.match("gold", sys_a, 8) is not None
    # another tenant's identical prompt must NOT promote gold's chain
    rid = eng.submit(sys_a + [5], 4, tenant="burst")
    res = eng.drain()
    assert eng._fabric["promote"] == 0
    assert host.match("gold", sys_a, 8) is not None  # still gold's
    assert res[rid] == ref(params, sys_a + [5], 4)
    # gold's own miss does promote it
    rid = eng.submit(sys_a + [6], 4, tenant="gold")
    res = eng.drain()
    assert eng._fabric["promote"] == 1
    assert res[rid] == ref(params, sys_a + [6], 4)


def test_ingest_rejects_cross_tenant_chain(params):
    eng, _ = fabric_engine(params, prefix_blocks=4,
                           tenant_quota=quota())
    sys_a = [7] * 8
    eng.submit(sys_a + [1], 3, cache_prefix=True, tenant="gold")
    eng.drain()
    digest = chain_digest(sys_a, "gold")
    blob = eng.export_chain(digest)
    assert blob is not None
    peer, _ = fabric_engine(params, prefix_blocks=4,
                            tenant_quota=quota())
    # the chain is scoped to gold: adopting it for burst (or for the
    # unscoped default) would cross the tenant side channel
    assert not peer.ingest_chain(blob, tenant="burst")
    assert peer._fabric["ingest_rejected"] == 1
    assert peer.ingest_chain(blob, tenant="gold")
    assert peer._fabric["ingest"] == 1


# ---------------------------------------------------------------------------
# cross-engine migration (the peer-pull payload path)
# ---------------------------------------------------------------------------

def test_export_ingest_between_engines_bit_exact(params):
    src, _ = fabric_engine(params, prefix_blocks=4)
    sys_a = [7] * 8
    src.submit(sys_a + [1, 2], 4, cache_prefix=True)
    src.drain()
    digest = chain_digest(sys_a)
    blob = src.export_chain(digest)
    assert blob is not None
    assert src.export_chain("no-such-digest") is None

    dst, _ = fabric_engine(params, prefix_blocks=4)
    assert dst.ingest_chain(blob, expect_digest=digest)
    assert dst._fabric["ingest"] == 1
    # the adopted chain serves a prefix hit with bit-exact output
    before_saved = dst._pindex.stats()["tokens_saved"]
    rid = dst.submit(sys_a + [5, 6], 6)
    res = dst.drain()
    assert res[rid] == ref(params, sys_a + [5, 6], 6)
    assert dst._pindex.stats()["tokens_saved"] > before_saved
    # digest mismatch (corrupt fetch / stale index) rejects cleanly
    assert not dst.ingest_chain(blob, expect_digest="deadbeef")
    assert dst._fabric["ingest_rejected"] == 1


def test_export_serves_host_tier_chains(params):
    eng, host = fabric_engine(params, prefix_blocks=1)
    sys_a = [7] * 8
    eng.submit(sys_a + [1], 3, cache_prefix=True)
    eng.drain()
    eng.submit([9] * 8 + [2], 3, cache_prefix=True)
    eng.drain()
    assert host.match(None, sys_a, 8) is not None   # demoted
    blob = eng.export_chain(chain_digest(sys_a))
    assert blob is not None                          # host tier serves it
    state = decode_chain(blob)
    assert state["scope"] is None and state["tokens"] == sys_a
    assert state["swap"]["nblk"] == 1


# ---------------------------------------------------------------------------
# /stats prefix_index section
# ---------------------------------------------------------------------------

def test_prefix_index_snapshot_reports_both_tiers(params):
    eng, host = fabric_engine(params, prefix_blocks=1)
    eng.submit([7] * 8 + [1], 3, cache_prefix=True)
    eng.drain()
    eng.submit([9] * 8 + [2], 3, cache_prefix=True)
    eng.drain()
    snap = eng.stats()["prefix_index"]
    tiers = {row["digest"]: row["tier"] for row in snap["chains"]}
    assert tiers == {chain_digest([9] * 8): "hbm",
                     chain_digest([7] * 8): "host"}
    for row in snap["chains"]:
        assert row["len"] == 8 and row["nbytes"] > 0
    assert snap["evicted"] == {"drop": 0, "demote": 1}
    assert snap["fabric"]["demote"] == 1
    assert snap["host_tier"]["chains"] == 1
    assert snap["host_tier"]["capacity_bytes"] == 1 << 20


def test_prefix_index_absent_without_paging(params):
    eng = DecodeServer(params, CFG, max_batch=2)
    assert eng.stats()["prefix_index"] is None


def test_host_tier_requires_prefix_cache(params):
    with pytest.raises(ValueError):
        DecodeServer(params, CFG, max_batch=2, kv_block_size=8,
                     kv_blocks=24, host_tier=HostTierStore(1 << 20))
