"""Indexed-sweep parity: the free-capacity index must be invisible.

ISSUE 1's tentpole rebuilds the scheduler hot path around a free-capacity
index (capindex.FreeCapacityIndex), copy-on-write snapshot clones, and a
capped preemption search. The contract is that ALL of it is pure
mechanism: placements, rotation cursors, nominations and victim choices
must be bit-identical to the brute-force sweep (``use_index=False``).
These tests schedule randomized pod mixes — singles, gangs,
anti-affinity, taints, selectors, quota-driven preemption — through both
modes and assert identical outcomes, plus unit pins for the COW clone
and the rewritten Snapshot.remove_nominated.
"""
import random

import pytest

from nos_tpu import constants
from nos_tpu.api.quota import make_elastic_quota
from nos_tpu.kube import ApiServer, Manager
from nos_tpu.kube.objects import (
    Affinity,
    Container,
    LabelSelector,
    Node,
    NodeSpec,
    NodeStatus,
    ObjectMeta,
    Pod,
    PodAffinityTerm,
    PodSpec,
    PodStatus,
    Taint,
    Toleration,
    resources_fit,
)
from nos_tpu.scheduler import Scheduler
from nos_tpu.scheduler import framework as fw
from nos_tpu.scheduler.capindex import INDEXED_RESOURCES

TPU = constants.RESOURCE_TPU
SCHED = constants.SCHEDULER_NAME
HOSTNAME = "kubernetes.io/hostname"
TPU_TAINT = Taint(key=TPU, value="present", effect="NoSchedule")
TOLERATION = Toleration(key=TPU, operator="Exists")


# ---------------------------------------------------------------------------
# builders
# ---------------------------------------------------------------------------

def tpu_node(name, pool, topo="2x2x2", chips=4, tainted=True):
    return Node(
        metadata=ObjectMeta(name=name, labels={
            constants.LABEL_TPU_ACCELERATOR: "tpu-v5p-slice",
            constants.LABEL_TPU_TOPOLOGY: topo,
            constants.LABEL_NODEPOOL: pool,
            HOSTNAME: name,
        }),
        spec=NodeSpec(taints=[TPU_TAINT] if tainted else []),
        status=NodeStatus(capacity={TPU: chips, "cpu": 96},
                          allocatable={TPU: chips, "cpu": 96}),
    )


def cpu_node(name, cpu=32):
    return Node(
        metadata=ObjectMeta(name=name, labels={HOSTNAME: name, "kind": "cpu"}),
        status=NodeStatus(capacity={"cpu": cpu, "memory": 64},
                          allocatable={"cpu": cpu, "memory": 64}),
    )


def single(name, ns, tpu=0, cpu=0.0, tolerate=True, priority=None,
           labels=None, anti_on=None, selector=None):
    req = {}
    if tpu:
        req[TPU] = tpu
    if cpu:
        req["cpu"] = cpu
    affinity = None
    if anti_on:
        affinity = Affinity(pod_anti_affinity_required=[
            PodAffinityTerm(
                label_selector=LabelSelector(match_labels={"app": anti_on}),
                topology_key=HOSTNAME,
            )
        ])
    return Pod(
        metadata=ObjectMeta(name=name, namespace=ns, labels=dict(labels or {})),
        spec=PodSpec(
            containers=[Container(requests=req)],
            scheduler_name=SCHED,
            priority=priority,
            node_selector=dict(selector or {}),
            tolerations=[TOLERATION] if tolerate else [],
            affinity=affinity,
        ),
        status=PodStatus(phase="Pending"),
    )


def gang_pod(job, ns, worker, size, topo, chips):
    return Pod(
        metadata=ObjectMeta(
            name=f"{job}-{worker:03d}", namespace=ns,
            labels={
                constants.LABEL_GANG_NAME: job,
                constants.LABEL_GANG_SIZE: str(size),
                constants.LABEL_GANG_WORKER: str(worker),
            },
            annotations={constants.ANNOTATION_TPU_TOPOLOGY: topo},
        ),
        spec=PodSpec(
            containers=[Container(requests={TPU: chips})],
            scheduler_name=SCHED,
            tolerations=[TOLERATION],
        ),
        status=PodStatus(phase="Pending"),
    )


def random_cluster(rng):
    nodes = []
    for pool in range(rng.randint(2, 4)):
        for host in range(2):   # 2x2x2 v5p pools: 2 hosts x 4 chips
            nodes.append(tpu_node(f"pool{pool}-w{host}", f"pool{pool}"))
    for i in range(rng.randint(2, 6)):
        nodes.append(cpu_node(f"cpu-{i}", cpu=rng.choice([8, 16, 32])))
    return nodes


def random_pods(rng):
    pods = []
    for g in range(rng.randint(0, 2)):
        for w in range(2):
            pods.append(gang_pod(f"job-{g}", "team-a", w, 2, "2x2x2", 4))
    for i in range(rng.randint(3, 10)):
        kind = rng.random()
        if kind < 0.4:
            pods.append(single(f"tpu-{i}", "team-a",
                               tpu=rng.choice([1, 2, 4]),
                               tolerate=rng.random() < 0.9))
        elif kind < 0.8:
            pods.append(single(f"cpu-{i}", "team-a",
                               cpu=rng.choice([2, 4, 8]),
                               selector={"kind": "cpu"}
                               if rng.random() < 0.5 else None))
        else:
            # more cpu than any node has -> stays pending
            pods.append(single(f"fat-{i}", "team-a", cpu=1024))
    # exclusive singles: required anti-affinity against their own label,
    # hostname topology — at most one per node, second may go unbound
    for i in range(rng.randint(0, 3)):
        pods.append(single(f"anti-{i}", "team-a", cpu=1,
                           labels={"app": "anti"}, anti_on="anti",
                           selector={"kind": "cpu"}))
    rng.shuffle(pods)
    return pods


def run_scenario(seed, use_index):
    """Schedule one randomized mix; return the observable outcome."""
    rng = random.Random(seed)
    server = ApiServer()
    mgr = Manager(server)
    mgr.add_controller(Scheduler(use_index=use_index).controller())
    for n in random_cluster(rng):
        server.create(n)
    server.create(make_elastic_quota("q-a", "team-a", min={TPU: 1024}))
    mgr.run_until_idle()
    for p in random_pods(rng):
        server.create(p)
    mgr.run_until_idle()
    return {
        (p.metadata.namespace, p.metadata.name): (
            p.spec.node_name,
            p.status.nominated_node_name,
            tuple(sorted((c.type, c.status, c.reason)
                         for c in p.status.conditions)),
        )
        for p in server.list("Pod")
    }


# ---------------------------------------------------------------------------
# end-to-end parity
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("seed", range(8))
def test_full_scheduler_parity_random(seed):
    """Same pods, same cluster: indexed and brute-force schedulers must
    produce identical placements, nominations, and conditions."""
    indexed = run_scenario(seed, use_index=True)
    brute = run_scenario(seed, use_index=False)
    assert indexed == brute


@pytest.mark.parametrize("seed", range(6))
def test_find_feasible_parity_random(seed):
    """Framework-level lockstep: chosen node, status code AND rotation
    cursor match after every sweep, while placements mutate the snapshot
    between sweeps."""
    rng = random.Random(1000 + seed)
    nodes = random_cluster(rng)
    fwk_i = fw.SchedulerFramework(use_index=True)
    fwk_b = fw.SchedulerFramework(use_index=False)
    snap_i = fw.Snapshot.build(nodes, [])
    snap_b = fw.Snapshot.build(nodes, [])
    for i in range(25):
        tpu = rng.choice([0, 1, 2, 4])
        cpu = rng.choice([0, 2, 8, 24])
        pod = single(f"p{i}", "ns", tpu=tpu, cpu=cpu)
        state_i: fw.CycleState = {}
        state_b: fw.CycleState = {}
        fwk_i.run_pre_filter(state_i, pod, snap_i)
        fwk_b.run_pre_filter(state_b, pod, snap_b)
        node_i, st_i = fwk_i.find_feasible(state_i, pod, snap_i)
        node_b, st_b = fwk_b.find_feasible(state_b, pod, snap_b)
        assert node_i == node_b, f"sweep {i}: {node_i} != {node_b}"
        assert st_i.code == st_b.code
        assert fwk_i._next_start_node == fwk_b._next_start_node, \
            f"cursor diverged on sweep {i}"
        if node_i is not None:
            bound = single(f"p{i}", "ns", tpu=tpu, cpu=cpu)
            bound.spec.node_name = node_i
            bound.status.phase = "Running"
            snap_i[node_i].add_pod(bound)
            snap_b[node_i].add_pod(bound)


@pytest.mark.parametrize("seed", range(6))
def test_capacity_index_matches_bruteforce_feasible_set(seed):
    """candidates(req) must equal the set of nodes whose available()
    covers the request on every indexed resource — computed brute-force
    with the exact resources_fit tolerance."""
    rng = random.Random(2000 + seed)
    nodes = random_cluster(rng)
    pods = []
    for i, n in enumerate(nodes):
        if rng.random() < 0.6:
            load = {}
            alloc = n.status.allocatable
            if TPU in alloc and rng.random() < 0.7:
                load[TPU] = rng.randint(0, int(alloc[TPU]))
            load["cpu"] = rng.randint(0, int(alloc.get("cpu", 0)))
            p = single(f"load-{i}", "ns", tpu=load.get(TPU, 0),
                       cpu=load.get("cpu", 0))
            p.spec.node_name = n.metadata.name
            p.status.phase = "Running"
            pods.append(p)
    snap = fw.Snapshot.build(nodes, pods)
    idx = snap.capacity_index()
    def brute(indexed_req):
        return {
            name for name, info in snap.items()
            if resources_fit(indexed_req, info.available())
        }

    for req in ({TPU: 4}, {TPU: 1}, {"cpu": 8}, {TPU: 2, "cpu": 50},
                {"cpu": 0}, {"memory": 32}, {"memory": 65}):
        got = idx.candidates(req)
        indexed_req = {r: v for r, v in req.items()
                       if r in INDEXED_RESOURCES and v > 0}
        if not indexed_req:
            assert got is None
            continue
        want = brute(indexed_req)
        if got is None:
            # the low-pruning-value bailout: legal only when the index
            # would have kept more than 3/4 of the cluster anyway (the
            # sweep then just runs brute-force, which is equivalent)
            assert len(want) * 4 > len(snap) * 3, \
                f"req {req}: bailout hid real pruning ({len(want)}/{len(snap)})"
            continue
        assert got == want, f"req {req}: {sorted(got)} != {sorted(want)}"
    # incremental maintenance: bind one more pod, the index must follow
    name = sorted(snap)[0]
    extra = single("extra", "ns", cpu=snap[name].available().get("cpu", 0))
    extra.spec.node_name = name
    extra.status.phase = "Running"
    snap[name].add_pod(extra)
    got = idx.candidates({"cpu": 1})
    want = brute({"cpu": 1})
    assert got == want or (got is None and len(want) * 4 > len(snap) * 3)


# ---------------------------------------------------------------------------
# preemption parity + screen conservativeness
# ---------------------------------------------------------------------------

def preemption_world(use_index):
    server = ApiServer()
    mgr = Manager(server)
    sched = Scheduler(use_index=use_index)
    mgr.add_controller(sched.controller())
    for i in range(4):
        server.create(tpu_node(f"pre-w{i}", f"prepool{i}", topo="2x2x1",
                               chips=4))
    server.create(make_elastic_quota("q-a", "team-a", min={TPU: 8}))
    server.create(make_elastic_quota("q-b", "team-b", min={TPU: 8}))
    mgr.run_until_idle()
    # team-b borrows everything (over-quota labeled), then team-a arrives
    over = {constants.LABEL_CAPACITY: constants.CAPACITY_OVER_QUOTA}
    for i in range(4):
        p = single(f"borrow-{i}", "team-b", tpu=4, labels=over)
        p.spec.node_name = f"pre-w{i}"
        p.status.phase = "Running"
        server.create(p)
    mgr.run_until_idle()
    server.create(single("claim", "team-a", tpu=4, priority=100))
    mgr.run_until_idle()
    victims = sorted(
        p.metadata.name
        for p in server.list("Pod")
        if p.metadata.namespace == "team-b"
        and p.metadata.deletion_timestamp is None
    )
    claim = server.get("Pod", "claim", "team-a")
    return claim.spec.node_name, claim.status.nominated_node_name, victims


def test_preemption_parity():
    indexed = preemption_world(True)
    brute = preemption_world(False)
    assert indexed == brute
    # and not vacuously: the claim actually landed (bound after the
    # requeue, or at least nominated), with a victim evicted
    node_name, nominated, victims = indexed
    assert node_name or nominated, "preemption never happened in either mode"
    assert len(victims) < 4, "no victim was evicted"


def test_preemption_screen_is_conservative():
    """Nodes the preemption screen rejects (no pods, or allocatable below
    the request on an indexed resource) must be exactly the nodes where
    victim selection can never succeed."""
    from nos_tpu.scheduler.capacity import CapacityScheduling
    from nos_tpu.scheduler.capindex import allocatable_covers

    cs = CapacityScheduling()
    running = Pod(
        metadata=ObjectMeta(name="r", namespace="ns"),
        spec=PodSpec(containers=[Container(requests={TPU: 4})],
                     node_name="busy", priority=0),
        status=PodStatus(phase="Running"),
    )
    nodes = [tpu_node("busy", "pp", chips=4),
             tpu_node("empty", "pp", chips=4),
             tpu_node("small", "pp", chips=2)]
    snap = fw.Snapshot.build(nodes, [running], cs.calc)
    preemptor = single("want", "ns", tpu=4, priority=10)
    state: fw.CycleState = {}
    cs.pre_filter(state, preemptor, snap)
    for name in snap:
        screened_in = bool(snap[name].pods) and allocatable_covers(
            snap[name], preemptor.request())
        if not screened_in:
            assert cs._select_victims_on_node(
                state, preemptor, snap[name], snapshot=snap) is None, \
                f"screen dropped viable candidate {name}"
    # and the index-side enumeration agrees with the brute predicate
    got = snap.capacity_index().preempt_candidates(preemptor.request())
    want = [n for n in sorted(snap)
            if snap[n].pods and allocatable_covers(snap[n],
                                                   preemptor.request())]
    assert got == want == ["busy"]


# ---------------------------------------------------------------------------
# COW clone + remove_nominated units
# ---------------------------------------------------------------------------

def test_cow_clone_isolation_both_directions():
    node = tpu_node("cow-n0", "cowpool")
    resident = single("resident", "ns", tpu=1)
    resident.spec.node_name = "cow-n0"
    resident.status.phase = "Running"
    snap = fw.Snapshot.build([node], [resident])
    clone = snap.clone()
    # shared until mutation
    assert clone["cow-n0"].pods is snap["cow-n0"].pods
    assert clone["cow-n0"].node is snap["cow-n0"].node

    # clone-side mutation stays private
    newpod = single("newpod", "ns", tpu=1)
    newpod.spec.node_name = "cow-n0"
    clone["cow-n0"].add_pod(newpod)
    assert len(clone["cow-n0"].pods) == 2
    assert len(snap["cow-n0"].pods) == 1

    # source-side mutation after cloning must not leak into a pristine clone
    clone2 = snap.clone()
    other = single("other", "ns", tpu=1)
    other.spec.node_name = "cow-n0"
    snap["cow-n0"].add_pod(other)
    assert len(snap["cow-n0"].pods) == 2
    assert len(clone2["cow-n0"].pods) == 1

    # node object detaches on own_node()
    clone2["cow-n0"].own_node()
    clone2["cow-n0"].node.status.allocatable[TPU] = 99
    assert snap["cow-n0"].node.status.allocatable[TPU] == 4

    # capacity view of source and clone diverge correctly post-mutation
    assert snap["cow-n0"].available()[TPU] == 2
    assert clone2["cow-n0"].available()[TPU] == 98


def test_remove_nominated_touches_only_own_node():
    nodes = [tpu_node(f"nom-{i}", "nompool") for i in range(3)]
    snap = fw.Snapshot.build(nodes, [])
    pods = []
    for i in range(3):
        p = single(f"nominee-{i}", "ns", tpu=1)
        p.status.nominated_node_name = f"nom-{i}"
        snap.add_nominated(p)
        pods.append(p)
    untouched = snap._nominated["nom-1"]
    snap.remove_nominated(pods[0])
    # emptied key is dropped, other nodes' lists untouched (identity!)
    assert "nom-0" not in snap._nominated
    assert snap._nominated["nom-1"] is untouched
    assert [p.metadata.name for p in snap.nominated_for("nom-1")] == \
        ["nominee-1"]
    # pod with no nomination: no-op
    snap.remove_nominated(single("plain", "ns", tpu=1))
    assert set(snap._nominated) == {"nom-1", "nom-2"}
    # second nominee on the same node: removal keeps the sibling
    extra = single("nominee-extra", "ns", tpu=1)
    extra.status.nominated_node_name = "nom-2"
    snap.add_nominated(extra)
    snap.remove_nominated(pods[2])
    assert [p.metadata.name for p in snap.nominated_for("nom-2")] == \
        ["nominee-extra"]
