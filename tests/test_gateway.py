"""Fleet front door (ISSUE 11): the prefix-affinity gateway's routing
kernel, exactly-once retry semantics over REAL ServingLoops (the PR 7
StubEngine/FaultInjector harness), global admission, deadline
propagation, and the scale-from-zero door queue + activator loop
through the real FleetController. All jax-free."""
import threading
import time
import urllib.request

import pytest
from test_serving_chaos import (
    StubEngine, expected_tokens, outcome_delta, outcome_totals,
)

from nos_tpu import constants
from nos_tpu.cmd.server import ServingLoop
from nos_tpu.fleet import FleetConfig, FleetController, PolicyConfig
from nos_tpu.fleet.sim import SimFleet
from nos_tpu.gateway import (
    GatewayRouter, HashRing, PodDiscovery, Replica, ReplicaUnreachable,
    RouterConfig, affinity_pick, prefix_key,
)
from nos_tpu.kube import ApiServer
from nos_tpu.kube.client import Client
from nos_tpu.kube.objects import (
    ConfigMap, Container, ObjectMeta, Pod, PodSpec, PodStatus,
)
from nos_tpu.models.errors import (
    DeadlineExceeded, EngineRecovering, QueueFull,
)
from nos_tpu.models.supervision import FaultInjector


# ---------------------------------------------------------------------------
# prefix_key: the block-chain arithmetic shared with kvblocks
# ---------------------------------------------------------------------------
def test_prefix_key_block_arithmetic_matches_prefix_index():
    bs = 16
    sys_prompt = list(range(100, 100 + 3 * bs))    # 3 full blocks
    # same leading full blocks -> same key, whatever the tail
    a = prefix_key(sys_prompt + [1, 2, 3], bs, affinity_blocks=4)
    b = prefix_key(sys_prompt + [9] * 40, bs, affinity_blocks=4)
    assert a is not None
    # with affinity_blocks=4 and only 3 shared full blocks, the longer
    # prompt keys its 4th block too — prompts diverging after the
    # shared prefix scatter unless the cap sits at/below it
    assert a != b
    a3 = prefix_key(sys_prompt + [1, 2, 3], bs, affinity_blocks=3)
    b3 = prefix_key(sys_prompt + [9] * 40, bs, affinity_blocks=3)
    assert a3 == b3 is not None
    # no full block -> no key (nothing shareable to colocate); the same
    # ``len(prompt) // block_size`` rule PrefixBlockIndex publishes by
    assert prefix_key(list(range(bs - 1)), bs) is None
    assert prefix_key([], bs) is None
    # divergence INSIDE the keyed depth -> different keys
    other = list(sys_prompt)
    other[5] += 1
    assert prefix_key(other, bs, 3) != a3
    with pytest.raises(ValueError):
        prefix_key([1, 2, 3], 0)


# ---------------------------------------------------------------------------
# ring stability
# ---------------------------------------------------------------------------
def _owners(ring, keys):
    return {k: ring.lookup(k)[0] for k in keys}


def test_ring_stability_under_add_drain_death():
    ring = HashRing()
    for n in ("r1", "r2", "r3"):
        ring.add(n)
    keys = [prefix_key(list(range(i, i + 64)), 16) for i in range(300)]
    base = _owners(ring, keys)

    # ADD: only ~1/N of the key space moves, the rest stay home
    ring.add("r4")
    after_add = _owners(ring, keys)
    moved = sum(1 for k in keys if base[k] != after_add[k])
    assert 0 < moved < len(keys) / 2
    # every moved key moved TO the new replica, never shuffled between
    # survivors (the consistent-hashing contract)
    assert all(after_add[k] == "r4" for k in keys
               if base[k] != after_add[k])

    # DEATH/DRAIN (remove): the removed replica's keys redistribute,
    # everyone else's stay put
    ring.remove("r4")
    assert _owners(ring, keys) == base
    ring.remove("r2")
    after_rm = _owners(ring, keys)
    assert all(after_rm[k] == base[k] for k in keys
               if base[k] != "r2")
    assert all(after_rm[k] in ("r1", "r3") for k in keys)

    # membership is restorable bit-identically (ring points derive from
    # the name): a replica bouncing not-ready -> ready re-owns exactly
    # its old keys
    ring.add("r2")
    assert _owners(ring, keys) == base


def test_ring_sync_and_lookup_order():
    ring = HashRing(vnodes=16)
    ring.sync(["a", "b", "c"])
    assert ring.nodes() == ["a", "b", "c"]
    key = prefix_key(list(range(64)), 16)
    order = ring.lookup(key)
    assert sorted(order) == ["a", "b", "c"]      # all distinct
    assert ring.lookup(key, n=2) == order[:2]
    ring.sync(["a"])
    assert ring.lookup(key) == ["a"]
    ring.sync([])
    assert ring.lookup(key) == []


def test_affinity_pick_bounded_imbalance():
    ring = HashRing()
    ring.sync(["a", "b", "c"])
    key = prefix_key(list(range(64)), 16)
    owner = ring.lookup(key)[0]
    others = [n for n in ("a", "b", "c") if n != owner]
    even = {n: 1.0 for n in ("a", "b", "c")}

    got, route = affinity_pick(key, ring, even, ["a", "b", "c"], 4.0)
    assert (got, route) == (owner, "affinity")
    # owner overloaded beyond the bound: locality yields to balance
    loads = dict(even)
    loads[owner] = 10.0
    # the next ring candidate within bound keeps partial affinity
    got2, route2 = affinity_pick(key, ring, loads, ["a", "b", "c"], 4.0)
    assert got2 == ring.lookup(key)[1] and route2 == "affinity"
    # ALL ring candidates overloaded -> least-loaded fallback
    loads = {n: 10.0 for n in ("a", "b", "c")}
    loads[others[0]] = 1.0
    got3, route3 = affinity_pick(key, ring, loads, ["a", "b", "c"], 4.0)
    assert route3 in ("affinity", "fallback")
    assert got3 == others[0] or loads[got3] <= loads[others[0]] + 4.0
    # no key -> least-loaded
    got4, route4 = affinity_pick(None, ring, loads, ["a", "b", "c"], 4.0)
    assert (got4, route4) == (others[0], "no_key")
    # nobody admitting
    assert affinity_pick(key, ring, {}, [], 4.0) == (None, "no_replicas")


# ---------------------------------------------------------------------------
# router over real ServingLoops
# ---------------------------------------------------------------------------
def loop_transport(rep: Replica, req: dict):
    loop = rep.handle
    if loop is None:
        raise ReplicaUnreachable(f"{rep.name} has no loop")
    return loop.generate(req["prompt"], req["max_new_tokens"],
                         timeout=60, deadline_s=req.get("deadline_s"))


def loop_stream_transport(rep: Replica, req: dict):
    loop = rep.handle
    if loop is None:
        raise ReplicaUnreachable(f"{rep.name} has no loop")
    return loop.stream(req["prompt"], req["max_new_tokens"],
                       timeout=60, deadline_s=req.get("deadline_s"))


class LoopRefresher:
    """The discovery loop's role for in-process tests: polls the
    ServingLoops' own health/drain state into the router table."""

    def __init__(self, router, loops, interval_s=0.005):
        self.router = router
        self.loops = loops          # name -> ServingLoop (mutable)
        self.interval_s = interval_s
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True)

    def refresh_once(self):
        self.router.update([
            Replica(name=name, handle=lp,
                    ready=(lp.healthy and not lp.draining
                           and not lp.recovering),
                    draining=lp.draining, stats=lp.stats())
            for name, lp in sorted(self.loops.items())])

    def _run(self):
        while not self._stop.is_set():
            try:
                self.refresh_once()
            except Exception:   # noqa: BLE001 — keep last view
                pass
            self._stop.wait(self.interval_s)

    def __enter__(self):
        self.refresh_once()
        self._thread.start()
        return self

    def __exit__(self, *exc):
        self._stop.set()
        self._thread.join(timeout=5)


def run_gateway_trace(router, n_requests, new_tokens):
    results, errors = {}, {}

    def worker(i):
        try:
            toks, replica, attempts = router.dispatch(
                [100 + i], new_tokens)
            results[i] = (toks, replica, attempts)
        except Exception as e:      # noqa: BLE001 — asserted by callers
            errors[i] = e

    threads = [threading.Thread(target=worker, args=(i,))
               for i in range(n_requests)]
    for t in threads:
        t.start()
    return threads, results, errors


def join_all(threads, timeout=60):
    for t in threads:
        t.join(timeout)
    assert not any(t.is_alive() for t in threads), "stuck request"


def test_gateway_exactly_once_under_drain_restart_and_kill():
    """The productionized chaos router (ISSUE 11 tentpole): one replica
    drains mid-trace, one rides a supervised restart (injected step
    errors -> 503s), one is KILLED outright — every request completes
    exactly once with exact tokens, fleet-wide outcome conservation
    holds (finished == N, no double-finish)."""
    before = outcome_totals()
    inj = FaultInjector(schedule={5: "error", 13: "error"})
    loops = {
        "r0": ServingLoop(StubEngine(tokens_per_tick=2)),
        "r1": ServingLoop(inj.wrap(StubEngine(tokens_per_tick=2)),
                          engine_factory=lambda: inj.wrap(
                              StubEngine(tokens_per_tick=2)),
                          restart_budget=4, restart_backoff_s=0.01),
        "r2": ServingLoop(StubEngine(tokens_per_tick=2)),
        "r3": ServingLoop(StubEngine(tokens_per_tick=2)),
    }
    router = GatewayRouter(
        RouterConfig(max_attempts=20, backoff_s=0.005,
                     backoff_max_s=0.05),
        transport=loop_transport)
    try:
        with LoopRefresher(router, loops):
            threads, results, errors = run_gateway_trace(
                router, n_requests=18, new_tokens=120)
            time.sleep(0.01)        # work is mid-flight everywhere
            loops["r0"].begin_drain()
            time.sleep(0.01)
            loops["r3"].shutdown()  # death: displaced work requeues
            join_all(threads)
        assert errors == {}
        assert len(results) == 18
        for i, (toks, _rep, _att) in results.items():
            assert toks == expected_tokens([100 + i], 120), f"req {i}"
        delta = outcome_delta(before)
        assert delta["finished"] == 18
        # gateway-side ledger: every request earned exactly one outcome
        snap = router.stats()
        assert snap["requests"]["completed"] == 18
        assert snap["requests"]["failed"] == 0
    finally:
        for lp in loops.values():
            lp.shutdown()


def test_gateway_affinity_routes_shared_prefixes_to_one_replica():
    """Requests sharing a leading block-chain land on ONE replica (its
    PrefixBlockIndex would hold the blocks); distinct prefixes spread
    across the ring."""
    bs = 16
    loops = {f"r{i}": ServingLoop(StubEngine(tokens_per_tick=8))
             for i in range(4)}
    router = GatewayRouter(
        RouterConfig(block_size=bs, affinity_blocks=2,
                     max_imbalance=50.0),
        transport=loop_transport)
    try:
        with LoopRefresher(router, loops):
            prefixes = [[1000 + 7 * p + j for j in range(2 * bs)]
                        for p in range(8)]
            homes = {}
            for p, pref in enumerate(prefixes):
                reps = set()
                for i in range(4):
                    toks, rep, _ = router.dispatch(pref + [p, i], 4)
                    assert toks[:len(pref)] == pref
                    reps.add(rep)
                homes[p] = reps
            # every prefix has exactly one home while the fleet is
            # stable and imbalance never binds
            assert all(len(r) == 1 for r in homes.values())
            # and the keys spread over more than one replica
            assert len({next(iter(r)) for r in homes.values()}) > 1
            assert router.stats()["routes"].get("affinity", 0) == 32
    finally:
        for lp in loops.values():
            lp.shutdown()


def test_gateway_streaming_passthrough_and_preflight_retry():
    """Streaming: deltas concatenate to the exact unary tokens; a
    draining replica shed BEFORE the first byte retries elsewhere."""
    loops = {"r0": ServingLoop(StubEngine(tokens_per_tick=3)),
             "r1": ServingLoop(StubEngine(tokens_per_tick=3))}
    router = GatewayRouter(
        RouterConfig(max_attempts=8, backoff_s=0.002),
        transport=loop_transport, stream_transport=loop_stream_transport)
    try:
        with LoopRefresher(router, loops) as ref:
            out = []
            for delta in router.stream([7], 30):
                out.extend(delta)
            assert out == list(range(1, 31))
            # drain one replica and pin stale-table retry: the router's
            # view still says ready, the loop sheds, the stream retries
            # on the survivor before any byte is out
            loops["r0"].begin_drain()
            loops["r1"].begin_drain()
            ref.refresh_once()
            # both draining: no admitting replica -> door queue; undrain
            # r1 in the background to flush
            def undrain():
                time.sleep(0.05)
                loops["r1"].cancel_drain()
            threading.Thread(target=undrain, daemon=True).start()
            out2 = []
            for delta in router.stream([9], 12):
                out2.extend(delta)
            assert out2 == list(range(1, 13))
    finally:
        for lp in loops.values():
            lp.shutdown()


# ---------------------------------------------------------------------------
# deadline propagation
# ---------------------------------------------------------------------------
def test_deadline_budget_shrinks_across_queueing_and_retries():
    """The replica receives the REMAINING budget, not the original:
    time burned by a shed+backoff comes out of what is forwarded (the
    X-Request-Deadline-S discipline, transport-agnostic)."""
    class FakeClock:
        def __init__(self):
            self.t = 100.0

        def __call__(self):
            return self.t

    clock = FakeClock()
    seen = []
    fail_first = {"n": 1}

    def transport(rep, req):
        clock.t += 0.5              # the attempt itself takes time
        if fail_first["n"]:
            fail_first["n"] -= 1
            raise QueueFull("busy")
        seen.append(req["deadline_s"])
        return req["prompt"]

    router = GatewayRouter(
        RouterConfig(max_attempts=4, backoff_s=0.0),
        transport=transport, clock=clock,
        sleep=lambda s: setattr(clock, "t", clock.t + s))
    router.update([Replica(name="a", handle=None),
                   Replica(name="b", handle=None)])
    toks, _, attempts = router.dispatch([1, 2, 3], 1, deadline_s=10.0)
    assert attempts == 2
    assert len(seen) == 1
    # first attempt consumed 0.5s: the retry forwards < 10 - 0.5
    assert seen[0] <= 9.5
    assert seen[0] > 8.0

    # a budget fully spent at the gateway sheds WITHOUT reaching a
    # replica, as DeadlineExceeded
    def slow_transport(rep, req):
        clock.t += 6.0
        raise QueueFull("busy")

    router2 = GatewayRouter(
        RouterConfig(max_attempts=4, backoff_s=0.0),
        transport=slow_transport, clock=clock,
        sleep=lambda s: setattr(clock, "t", clock.t + s))
    router2.update([Replica(name="a", handle=None),
                    Replica(name="b", handle=None)])
    with pytest.raises(DeadlineExceeded):
        router2.dispatch([1], 1, deadline_s=10.0)
    assert router2.stats()["requests"]["deadline"] == 1


def test_http_transport_sets_deadline_header():
    from nos_tpu.cmd.gateway import HttpReplicaTransport

    tr = HttpReplicaTransport()
    req, timeout = tr._request(
        Replica(name="r", handle="http://10.0.0.1:8000"),
        {"prompt": [1], "max_new_tokens": 4, "deadline_s": 3.25,
         "sampling": {"temperature": 0.5}}, stream=False)
    assert req.get_header("X-request-deadline-s") == "3.250"
    assert timeout <= 3.25 + 5.0
    import json as _json
    body = _json.loads(req.data)
    assert body["temperature"] == 0.5 and body["prompt"] == [1]
    with pytest.raises(ReplicaUnreachable):
        tr._request(Replica(name="r", handle=None),
                    {"prompt": [1], "max_new_tokens": 1,
                     "sampling": {}}, stream=False)


def test_deadline_expires_while_parked_at_the_door():
    router = GatewayRouter(
        RouterConfig(door_wait_s=30.0),
        transport=lambda rep, req: req["prompt"])
    t0 = time.monotonic()
    with pytest.raises(DeadlineExceeded):
        router.dispatch([1], 1, deadline_s=0.15)
    assert time.monotonic() - t0 < 5.0
    assert router.stats()["requests"]["deadline"] == 1


def test_inflight_survives_discovery_refresh_mid_request():
    """Discovery replaces the Replica objects wholesale every poll; a
    request in flight across a refresh must still settle the live
    table's in-flight count back to zero (regression: the decrement
    used to land on the stale pre-refresh object, creeping load() up
    forever and eventually shedding an idle fleet)."""
    release = threading.Event()
    entered = threading.Event()

    def transport(rep, req):
        entered.set()
        release.wait(10)
        return req["prompt"]

    router = GatewayRouter(RouterConfig(), transport=transport)
    router.update([Replica(name="a", handle=None)])
    t = threading.Thread(
        target=lambda: router.dispatch([1, 2], 1), daemon=True)
    t.start()
    assert entered.wait(5)
    assert router.stats()["replicas"]["a"]["inflight"] == 1
    # discovery refresh races the in-flight request
    router.update([Replica(name="a", handle=None)])
    assert router.stats()["replicas"]["a"]["inflight"] == 1
    release.set()
    t.join(10)
    assert router.stats()["replicas"]["a"]["inflight"] == 0
    # a replica that left mid-flight prunes once settled
    assert "a" in router._inflight
    router.update([])
    router.update([Replica(name="a", handle=None)])
    assert router.stats()["replicas"]["a"]["inflight"] == 0


def test_retry_exhaustion_preserves_capacity_shed_wire_shape():
    """All attempts shed 429: the router must re-raise QueueFull with
    the last reason so the HTTP layer answers 429 + Retry-After, not a
    502 server fault (regression: a bare RuntimeError used to take the
    generic arm)."""
    def transport(rep, req):
        raise QueueFull("pool dry", reason="hbm_admission")

    router = GatewayRouter(
        RouterConfig(max_attempts=3, backoff_s=0.0),
        transport=transport, sleep=lambda s: None)
    router.update([Replica(name="a", handle=None),
                   Replica(name="b", handle=None)])
    with pytest.raises(QueueFull) as e:
        router.dispatch([1], 1)
    assert e.value.reason == "hbm_admission"
    assert router.stats()["requests"]["failed"] == 1
    # non-capacity exhaustion still reads as a failure
    def dead(rep, req):
        raise ReplicaUnreachable("gone")

    router2 = GatewayRouter(
        RouterConfig(max_attempts=2, backoff_s=0.0),
        transport=dead, sleep=lambda s: None)
    router2.update([Replica(name="a", handle=None)])
    with pytest.raises(RuntimeError) as e:
        router2.dispatch([1], 1)
    assert not isinstance(e.value, QueueFull)


def test_gateway_http_stream_shed_is_json_429_not_sse_200():
    """A streaming request shed at the door must answer the same JSON
    429 the unary path answers (regression: the lazy stream generator
    used to let do_POST commit a 200 before the shed surfaced)."""
    import json as _json

    from nos_tpu.cmd.gateway import make_http_server as make_gw_server

    router = GatewayRouter(
        RouterConfig(max_door_queue=4, door_wait_s=0.05),
        transport=lambda rep, req: req["prompt"],
        stream_transport=lambda rep, req: iter([req["prompt"]]))
    gw_httpd = make_gw_server(router, 0, "web")
    threading.Thread(target=gw_httpd.serve_forever, daemon=True).start()
    gw = f"http://127.0.0.1:{gw_httpd.server_address[1]}"
    try:
        req = urllib.request.Request(
            gw + "/v1/generate",
            data=_json.dumps({"prompt": [1], "max_new_tokens": 2,
                              "stream": True}).encode(),
            headers={"Content-Type": "application/json"}, method="POST")
        with pytest.raises(urllib.error.HTTPError) as e:
            urllib.request.urlopen(req, timeout=10)
        assert e.value.code == 429
        body = _json.loads(e.value.read())
        assert body["reason"] == "no_ready_replicas"
        assert e.value.headers.get("Retry-After") == "1"
    finally:
        gw_httpd.shutdown()


def test_controller_gateway_source_outage_falls_back_to_configmap():
    """gateway_source wired but unreachable: the durable ConfigMap
    annotation must still activate a scaled-to-zero fleet (regression:
    an unreachable source used to read as zero pressure and strand the
    queued burst)."""
    server = ApiServer()
    client = Client(server)
    server.create(ConfigMap(
        metadata=ObjectMeta(
            name="nos-tpu-gateway-web", namespace="serve",
            annotations={constants.ANNOTATION_GATEWAY_QUEUED: "7"}),
        data={}))

    def broken_source():
        raise OSError("gateway unreachable")

    ctl = FleetController(
        FleetConfig(name="web", namespace="serve",
                    policy=PolicyConfig(min_replicas=0, max_replicas=4,
                                        max_step_up=2)),
        gateway_source=broken_source, clock=lambda: 1000.0)

    class _NullSpan:
        recording = False

        def set_attr(self, *a, **k):
            pass

    ctl._reconcile(client, _NullSpan())
    assert ctl.stats()["signals"]["gateway_queued"] == 7
    assert ctl.stats()["decision"]["reason"] == "activation"
    assert len(client.list("Pod", namespace="serve")) == 2


# ---------------------------------------------------------------------------
# global admission
# ---------------------------------------------------------------------------
def test_global_admission_sheds_on_fleet_pending_and_hbm():
    router = GatewayRouter(
        RouterConfig(admit_pending_per_replica=2.0),
        transport=lambda rep, req: req["prompt"])
    router.update([
        Replica(name="a", stats={"pending": {"depth": 5},
                                 "active_slots": 2}),
        Replica(name="b", stats={"pending": {"depth": 4},
                                 "active_slots": 1}),
    ])
    with pytest.raises(QueueFull) as e:
        router.dispatch([1], 1)
    assert e.value.reason == "fleet_queue_full"
    assert router.stats()["shed"] == {"fleet_queue_full": 1}

    hbm_router = GatewayRouter(
        RouterConfig(admit_hbm_frac=0.9),
        transport=lambda rep, req: req["prompt"])
    hbm_router.update([
        Replica(name="a", stats={"kv": {"hbm": {"in_use": 95,
                                                "limit": 100}}}),
        Replica(name="b", stats={"kv": {"hbm": {"in_use": 99,
                                                "limit": 100}}}),
    ])
    with pytest.raises(QueueFull) as e:
        hbm_router.dispatch([1], 1)
    assert e.value.reason == "fleet_hbm_admission"
    # ONE replica under the bar is enough to admit (the pick spreads)
    hbm_router.update([
        Replica(name="a", stats={"kv": {"hbm": {"in_use": 10,
                                                "limit": 100}}}),
        Replica(name="b", stats={"kv": {"hbm": {"in_use": 99,
                                                "limit": 100}}}),
    ])
    toks, _, _ = hbm_router.dispatch([1], 1)
    assert toks == [1]


# ---------------------------------------------------------------------------
# scale-from-zero: door queue + flush + the activator loop
# ---------------------------------------------------------------------------
def test_door_queue_parks_and_flushes_on_first_ready():
    """With no admitting replica, requests park FIFO at the door and
    the activation signal fires; the first ready replica flushes the
    queue and every parked request completes."""
    signals = []
    loops = {}
    router = GatewayRouter(
        RouterConfig(door_wait_s=30.0, max_attempts=8,
                     backoff_s=0.002),
        transport=loop_transport, on_activation=signals.append)
    try:
        threads, results, errors = run_gateway_trace(
            router, n_requests=6, new_tokens=20)
        deadline = time.monotonic() + 10
        while (router.stats()["door_queue"] < 6
               and time.monotonic() < deadline):
            time.sleep(0.002)
        snap = router.stats()
        assert snap["door_queue"] == 6
        assert snap["door_queue_peak"] == 6
        assert max(signals) == 6        # the activation signal fired
        # first replica turns ready -> flush
        loops["r0"] = ServingLoop(StubEngine(tokens_per_tick=4))
        with LoopRefresher(router, loops):
            join_all(threads)
        assert errors == {}
        assert len(results) == 6
        for i, (toks, rep, _) in results.items():
            assert toks == expected_tokens([100 + i], 20)
            assert rep == "r0"
        assert router.stats()["door_queue"] == 0
        assert 0 in signals             # and cleared back to zero
    finally:
        for lp in loops.values():
            lp.shutdown()


def test_door_queue_bounds_and_no_ready_shed_reasons():
    router = GatewayRouter(
        RouterConfig(max_door_queue=0, door_wait_s=0.05),
        transport=lambda rep, req: req["prompt"])
    with pytest.raises(QueueFull) as e:
        router.dispatch([1], 1)
    assert e.value.reason == "door_queue_full"

    router2 = GatewayRouter(
        RouterConfig(max_door_queue=4, door_wait_s=0.05),
        transport=lambda rep, req: req["prompt"])
    with pytest.raises(QueueFull) as e:
        router2.dispatch([1], 1)
    assert e.value.reason == "no_ready_replicas"
    shed = router2.stats()["shed"]
    assert shed == {"no_ready_replicas": 1}


def _fleet_pod(name, fleet, namespace, phase="Running"):
    return Pod(
        metadata=ObjectMeta(name=name, namespace=namespace,
                            labels={constants.LABEL_FLEET: fleet}),
        spec=PodSpec(containers=[Container(
            requests={constants.RESOURCE_TPU: 4.0})]),
        status=PodStatus(phase=phase, pod_ip="10.0.0.9"))


def test_controller_treats_gateway_queue_as_pressure_at_zero():
    """THE activator satellite: a min_replicas=0 fleet with ZERO pods
    registers gateway door-queue pressure and starts replicas — via
    the injected gateway_source AND via the ConfigMap annotation
    fallback. Without a signal it stays asleep (no 0->1->0 flap)."""
    def reconcile_once(gateway_source=None, stamp_annotation=None):
        server = ApiServer()
        client = Client(server)
        if stamp_annotation is not None:
            server.create(ConfigMap(
                metadata=ObjectMeta(
                    name="nos-tpu-gateway-web", namespace="serve",
                    annotations={constants.ANNOTATION_GATEWAY_QUEUED:
                                 str(stamp_annotation)}),
                data={}))
        ctl = FleetController(
            FleetConfig(name="web", namespace="serve",
                        policy=PolicyConfig(min_replicas=0,
                                            max_replicas=4,
                                            max_step_up=2)),
            gateway_source=gateway_source, clock=lambda: 1000.0)
        ctl._reconcile(client, _NullSpan())
        pods = client.list("Pod", namespace="serve")
        return ctl, pods

    class _NullSpan:
        recording = False

        def set_attr(self, *a, **k):
            pass

    # no gateway signal: a scaled-to-zero fleet stays asleep
    ctl, pods = reconcile_once()
    assert pods == []
    assert ctl.stats()["signals"]["gateway_queued"] == 0

    # injected gateway_source: door queue -> activation scale-up
    ctl, pods = reconcile_once(
        gateway_source=lambda: {"door_queue": 9})
    assert len(pods) == 2           # magnitude 9/4 -> capped at step 2
    assert ctl.stats()["decision"] == {"direction": "up",
                                       "reason": "activation"}
    assert ctl.stats()["signals"]["gateway_queued"] == 9

    # ConfigMap annotation fallback (the gateway binary's stamp)
    ctl, pods = reconcile_once(stamp_annotation=3)
    assert len(pods) == 1
    assert ctl.stats()["decision"]["reason"] == "activation"

    # a stale zero annotation keeps the fleet asleep
    ctl, pods = reconcile_once(stamp_annotation=0)
    assert pods == []


def test_discovery_mirrors_controller_readiness_rules():
    server = ApiServer()
    client = Client(server)
    for pod in (
        _fleet_pod("web-r1", "web", "serve"),
        _fleet_pod("web-r2", "web", "serve"),
        _fleet_pod("web-r3", "web", "serve", phase="Pending"),
        _fleet_pod("other-r1", "other", "serve"),
    ):
        server.create(pod)
    client.patch("Pod", "web-r2", "serve",
                 lambda p: p.metadata.annotations.update(
                     {constants.ANNOTATION_FLEET_DRAIN: "scale-down"}))

    stats = {
        "web-r1": {"healthy": True, "draining": False,
                   "recovering": False},
        "web-r2": {"healthy": True, "draining": False,
                   "recovering": False},
    }
    disc = PodDiscovery(
        client, "web", "serve",
        stats_source=lambda pod: stats.get(pod.metadata.name))
    reps = {r.name: r for r in disc.poll()}
    # Running pods of THIS fleet only; the Pending one is invisible
    assert set(reps) == {"web-r1", "web-r2"}
    assert reps["web-r1"].ready and not reps["web-r1"].draining
    # the drain ANNOTATION alone (controller-marked) flips readiness,
    # even while the replica itself still admits — same rule the
    # controller steers by
    assert reps["web-r2"].draining and not reps["web-r2"].ready
    # an unscrapable replica is known but not ready (down, not gone)
    stats.pop("web-r1")
    reps = {r.name: r for r in disc.poll()}
    assert not reps["web-r1"].ready and not reps["web-r1"].draining


# ---------------------------------------------------------------------------
# sim <-> gateway: shared ring, pluggable policies
# ---------------------------------------------------------------------------
def test_sim_prefix_affinity_shares_the_production_ring():
    class Clock:
        t = 0.0

        def __call__(self):
            return self.t

    clock = Clock()
    fleet = SimFleet(clock, router="prefix_affinity", block_size=16,
                     affinity_blocks=2, prefix_chains=8,
                     max_imbalance=100.0)
    for i in range(4):
        fleet.add_replica(f"r{i}")
    sys_prompt = list(range(200, 232))       # 2 full blocks
    # the sim's routing decision must equal the production kernel's
    ring = HashRing()
    ring.sync([f"r{i}" for i in range(4)])
    key = prefix_key(sys_prompt, 16, 2)
    expected_home = ring.lookup(key)[0]
    for _ in range(6):
        fleet.submit(tokens=10, prompt=sys_prompt)
    fleet.tick(1.0)
    home = [name for name, rep in fleet.replicas.items()
            if rep.load() or rep.prefix_hits or rep.prefix_misses]
    assert home == [expected_home]
    rep = fleet.replicas[expected_home]
    # first admission cold, the rest hit the chain
    assert rep.prefix_misses == 1
    assert rep.prefix_hits >= 1

    with pytest.raises(ValueError):
        SimFleet(clock, router="bogus")


def test_sim_router_policies_conserve_and_diverge():
    """All three policies are lossless on the same seeded trace;
    affinity gets a strictly better fleet-wide prefix-hit rate."""
    class Clock:
        def __init__(self):
            self.t = 0.0

        def __call__(self):
            return self.t

    import random as _r

    def run(policy):
        clock = Clock()
        fleet = SimFleet(clock, router=policy, block_size=16,
                         affinity_blocks=2, prefix_chains=3,
                         prefill_s=1.0, max_imbalance=4.0, seed=3)
        for i in range(3):
            fleet.add_replica(f"r{i}")
        rng = _r.Random(11)
        prompts = [[700 + 31 * p + j for j in range(32)]
                   for p in range(12)]
        for step in range(300):
            if step < 240:
                fleet.submit(tokens=rng.randint(5, 20),
                             prompt=prompts[rng.randrange(12)])
            fleet.tick(1.0)
            clock.t += 1.0
        rep = fleet.report()
        assert rep["conservation_ok"]
        assert rep["completed"] == rep["submitted"] > 0
        return rep

    reports = {p: run(p) for p in ("least_loaded", "random",
                                   "prefix_affinity")}
    aff = reports["prefix_affinity"]["prefix"]["hit_rate"]
    assert aff > reports["least_loaded"]["prefix"]["hit_rate"]
    assert aff > reports["random"]["prefix"]["hit_rate"]
    assert reports["prefix_affinity"]["routes"].get("affinity", 0) > 0
    # routes count ADMISSIONS, not attempts: a saturated head-of-queue
    # request re-decided every tick must not inflate the split
    routed = reports["prefix_affinity"]
    assert sum(routed["routes"].values()) == routed["submitted"]


# ---------------------------------------------------------------------------
# wire-level: the whole front door over real sockets
# ---------------------------------------------------------------------------
def test_gateway_http_proxies_unary_and_sse_over_real_sockets():
    """End to end on the wire: a REAL serving HTTP server (StubEngine
    ServingLoop behind cmd/server's surface) fronted by the REAL
    gateway HTTP server + HttpReplicaTransport — unary and SSE
    streaming both proxy exact tokens, the response names the replica,
    and a draining replica 503 retries to the survivor."""
    import json as _json

    from nos_tpu.cmd.gateway import (
        HttpReplicaTransport, make_http_server as make_gw_server,
    )
    from nos_tpu.cmd.server import ServerConfig, make_http_server

    loops = {"r0": ServingLoop(StubEngine(tokens_per_tick=4)),
             "r1": ServingLoop(StubEngine(tokens_per_tick=4))}
    backends = {}
    for name, lp in loops.items():
        httpd = make_http_server(ServerConfig(port=0), lp)
        threading.Thread(target=httpd.serve_forever,
                         daemon=True).start()
        backends[name] = (
            httpd, f"http://127.0.0.1:{httpd.server_address[1]}")

    transport = HttpReplicaTransport(timeout_s=30.0)
    router = GatewayRouter(
        RouterConfig(max_attempts=8, backoff_s=0.002),
        transport=transport.send,
        stream_transport=transport.send_stream)
    router.update([Replica(name=n, handle=url)
                   for n, (_h, url) in sorted(backends.items())])
    gw_httpd = make_gw_server(router, 0, "web")
    threading.Thread(target=gw_httpd.serve_forever, daemon=True).start()
    gw = f"http://127.0.0.1:{gw_httpd.server_address[1]}"
    try:
        # unary through the door
        req = urllib.request.Request(
            gw + "/v1/generate",
            data=_json.dumps({"prompt": [7], "max_new_tokens": 12,
                              "deadline_s": 30}).encode(),
            headers={"Content-Type": "application/json"}, method="POST")
        with urllib.request.urlopen(req, timeout=30) as r:
            body = _json.loads(r.read())
        assert body["tokens"] == expected_tokens([7], 12)
        assert body["replica"] in backends and body["attempts"] == 1

        # SSE streaming through the door
        req = urllib.request.Request(
            gw + "/v1/generate",
            data=_json.dumps({"prompt": [9], "max_new_tokens": 8,
                              "stream": True}).encode(),
            headers={"Content-Type": "application/json"}, method="POST")
        toks, done = [], False
        with urllib.request.urlopen(req, timeout=30) as r:
            for raw in r:
                line = raw.strip()
                if not line.startswith(b"data: "):
                    continue
                data = line[len(b"data: "):]
                if data == b"[DONE]":
                    done = True
                    break
                toks.extend(_json.loads(data)["tokens"])
        assert done and toks == list(range(1, 9))

        # a draining replica 503s (reason=draining): the gateway rides
        # it to the survivor — clients never see the drain
        loops["r0"].begin_drain()
        loops["r1"].begin_drain()
        loops["r0"].cancel_drain()      # exactly one survivor
        for i in range(4):
            req = urllib.request.Request(
                gw + "/v1/generate",
                data=_json.dumps({"prompt": [30 + i],
                                  "max_new_tokens": 5}).encode(),
                headers={"Content-Type": "application/json"},
                method="POST")
            with urllib.request.urlopen(req, timeout=30) as r:
                body = _json.loads(r.read())
            assert body["tokens"] == expected_tokens([30 + i], 5)
        # gateway /stats serves the router snapshot
        snap = _json.loads(urllib.request.urlopen(
            gw + "/stats", timeout=10).read())
        assert snap["fleet"] == "web"
        assert snap["requests"]["completed"] >= 6
        # /stats drift guard (ISSUE 20 satellite): the wire payload is
        # exactly the documented key contract
        from test_metrics_docs import GATEWAY_STATS_KEYS
        assert set(snap) == GATEWAY_STATS_KEYS, (
            f"gateway /stats drifted from the documented contract: "
            f"extra {sorted(set(snap) - GATEWAY_STATS_KEYS)}, missing "
            f"{sorted(GATEWAY_STATS_KEYS - set(snap))}")
        # gateway /metrics exports the nos_tpu_gateway_* family
        metrics = urllib.request.urlopen(
            gw + "/metrics", timeout=10).read().decode()
        assert "nos_tpu_gateway_requests_total" in metrics
    finally:
        gw_httpd.shutdown()
        for httpd, _url in backends.values():
            httpd.shutdown()
        for lp in loops.values():
            lp.shutdown()


def test_http_transport_maps_replica_errors():
    import json as _json

    from nos_tpu.cmd.gateway import HttpReplicaTransport

    class FakeHTTPError(urllib.error.HTTPError):
        def __init__(self, code, payload):
            self._payload = _json.dumps(payload).encode()
            urllib.error.HTTPError.__init__(
                self, "http://x", code, "err", {}, None)

        def read(self):
            return self._payload

    tr = HttpReplicaTransport()
    with pytest.raises(QueueFull) as e:
        tr._raise_for(FakeHTTPError(
            429, {"error": "full", "reason": "hbm_admission"}))
    assert e.value.reason == "hbm_admission"
    with pytest.raises(EngineRecovering):
        tr._raise_for(FakeHTTPError(
            503, {"error": "restarting", "reason": "recovering"}))
    with pytest.raises(RuntimeError):
        tr._raise_for(FakeHTTPError(
            503, {"error": "draining", "reason": "draining"}))
    with pytest.raises(DeadlineExceeded):
        tr._raise_for(FakeHTTPError(504, {"error": "late"}))
    from nos_tpu.models.errors import Infeasible
    with pytest.raises(Infeasible):
        tr._raise_for(FakeHTTPError(
            400, {"error": "too big", "infeasible": True}))
    with pytest.raises(ValueError):
        tr._raise_for(FakeHTTPError(400, {"error": "bad json"}))


# ---------------------------------------------------------------------------
# request-level elastic quota at the door (ISSUE 13)
# ---------------------------------------------------------------------------
def _tenant_cfg(json_text='{"tenants": {"gold": {"min_rate": 100},'
                          ' "burst": {"max_rate": 10}}}'):
    from nos_tpu.models.tenantquota import TenantQuotaConfig

    return TenantQuotaConfig.from_json(json_text)


def test_tenant_quota_door_shed_from_scraped_stats():
    """The gateway aggregates the replicas' per-tenant rates (the
    /stats ``tenants`` sections the engines now publish) and sheds a
    tenant at/over its FLEET-WIDE max with the same tenant_quota slug
    the replicas use — before the request reaches any replica."""
    router = GatewayRouter(
        RouterConfig(tenant_config=_tenant_cfg()),
        transport=lambda rep, req: req["prompt"])
    router.update([
        Replica(name="a", stats={"tenants": {
            "burst": {"rate_tokens_per_s": 6.0},
            "gold": {"rate_tokens_per_s": 50.0}}}),
        Replica(name="b", stats={"tenants": {
            "burst": {"rate_tokens_per_s": 5.0}}}),
    ])
    assert router.fleet_tenant_rate("burst") == 11.0
    with pytest.raises(QueueFull) as e:
        router.dispatch([1], 1, tenant="burst")
    assert e.value.reason == "tenant_quota"
    st = router.stats()
    assert st["shed"] == {"tenant_quota": 1}
    assert st["tenant_shed"] == {"burst": 1}
    assert st["config"]["tenant_quota"]["tenants"]["burst"][
        "max_rate"] == 10
    # gold has no max — admitted at any rate; unknown tenants resolve
    # to the default tenant (no max either)
    toks, _, _ = router.dispatch([1], 1, tenant="gold")
    assert toks == [1]
    toks, _, _ = router.dispatch([1], 1, tenant="nobody")
    assert toks == [1]
    # below the fleet max the burst tenant admits too
    router.update([Replica(name="a", stats={"tenants": {
        "burst": {"rate_tokens_per_s": 3.0}}})])
    toks, _, _ = router.dispatch([1], 1, tenant="burst")
    assert toks == [1]


def test_tenant_quota_retry_cap_and_forwarding():
    """Per-replica tenant_quota sheds burn a SMALL dedicated retry
    budget (a burst tenant backs off on its quota instead of walking
    the fleet), the exhaustion re-raises as 429-shaped QueueFull with
    the reason preserved, and the tenant forwards to the replica in
    the request's sampling."""
    attempts = []

    def shedding_transport(rep, req):
        attempts.append((rep.name, req["sampling"].get("tenant")))
        raise QueueFull("tenant 'burst' is at/over its max",
                        reason="tenant_quota")

    router = GatewayRouter(
        RouterConfig(max_attempts=12, tenant_quota_attempts=2,
                     tenant_config=_tenant_cfg()),
        transport=shedding_transport, sleep=lambda s: None)
    router.update([Replica(name="a"), Replica(name="b"),
                   Replica(name="c")])
    with pytest.raises(QueueFull) as e:
        router.dispatch([1], 1, tenant="burst")
    assert e.value.reason == "tenant_quota"
    # exactly tenant_quota_attempts attempts — not max_attempts
    assert len(attempts) == 2
    assert all(t == "burst" for _, t in attempts)
    assert router.stats()["requests"]["failed"] == 1

    # ordinary capacity sheds still get the full ladder
    attempts.clear()

    def capacity_shed(rep, req):
        attempts.append(rep.name)
        raise QueueFull("full", reason="queue_full")

    router2 = GatewayRouter(
        RouterConfig(max_attempts=5, tenant_quota_attempts=2,
                     tenant_config=_tenant_cfg()),
        transport=capacity_shed, sleep=lambda s: None)
    router2.update([Replica(name="a"), Replica(name="b")])
    with pytest.raises(QueueFull) as e:
        router2.dispatch([1], 1, tenant="burst")
    assert e.value.reason == "queue_full"
    assert len(attempts) == 5


def test_prefix_key_tenant_scoping_and_opt_out():
    """Tenant-scoped affinity keys (the routing twin of the replicas'
    tenant-scoped PrefixBlockIndex chains): same prompt, different
    tenants -> different keys; share_prefix collapses the scope."""
    bs = 16
    prompt = list(range(2 * bs))
    k_none = prefix_key(prompt, bs)
    k_a = prefix_key(prompt, bs, tenant="a")
    k_b = prefix_key(prompt, bs, tenant="b")
    assert len({k_none, k_a, k_b}) == 3         # all disjoint
    assert prefix_key(prompt, bs, tenant="a") == k_a   # stable

    router = GatewayRouter(
        RouterConfig(tenant_config=_tenant_cfg()),
        transport=lambda rep, req: req["prompt"])
    assert router._key_scope("gold") == "gold"
    assert router._key_scope("nobody") == "default"     # resolved
    assert router._key_scope(None) == "default"
    shared = GatewayRouter(
        RouterConfig(tenant_config=_tenant_cfg(
            '{"share_prefix": true, "tenants": {}}')),
        transport=lambda rep, req: req["prompt"])
    assert shared._key_scope("gold") is None            # opt-out
    # no tenant config: legacy tenant-free keys even for labeled
    # traffic — the replicas only scope their caches under a tenant
    # config, and splitting keys they don't scope by would scatter a
    # shared prefix across replicas for no isolation gain
    bare = GatewayRouter(RouterConfig(),
                         transport=lambda rep, req: req["prompt"])
    assert bare._key_scope("x") is None
    assert bare._key_scope(None) is None


def test_tenant_rides_streams_and_admission():
    """The stream path shares the door admission and the forwarding:
    an over-fleet-max tenant's stream sheds tenant_quota before the
    first byte; an admitted stream forwards the tenant."""
    seen = {}

    def stream_transport(rep, req):
        seen["tenant"] = req["sampling"].get("tenant")
        yield [1, 2]
        yield [3]

    router = GatewayRouter(
        RouterConfig(tenant_config=_tenant_cfg()),
        stream_transport=stream_transport)
    router.update([Replica(name="a", stats={"tenants": {
        "burst": {"rate_tokens_per_s": 50.0}}})])
    gen = router.stream([1], 4, tenant="burst")
    with pytest.raises(QueueFull) as e:
        next(gen)
    assert e.value.reason == "tenant_quota"
    out = list(router.stream([1], 4, tenant="gold"))
    assert out == [[1, 2], [3]]
    assert seen["tenant"] == "gold"


# ---------------------------------------------------------------------------
# prefill/decode disaggregation (ISSUE 15): role-aware routing + the
# two-phase handoff follow. Real-engine conservation is pinned in
# tests/test_serving_sharded.py and test_server_cmd.py; here the
# routing state machine itself, jax-free.
# ---------------------------------------------------------------------------
def _disagg_router(adopted, fail_resume=0):
    from nos_tpu.gateway.router import HandoffResumeError  # noqa: F401

    calls = {"prefill": 0, "resume": 0}

    def transport(rep, req):
        assert rep.role != "decode", \
            "a decode replica must never receive a NEW request"
        calls["prefill"] += 1
        if rep.role == "prefill":
            rid = len(adopted)
            adopted[rid] = list(req["prompt"]) + [900 + i for i in
                                                  range(req["max_new_tokens"])]
            return {"handoff": {"target": "decode-0", "rid": rid}}
        return list(req["prompt"]) + [7]

    def resume(rep, desc, rem):
        assert rep.name == "decode-0"
        calls["resume"] += 1
        if calls["resume"] <= fail_resume:
            raise ReplicaUnreachable("decode hiccup")
        return adopted[desc["rid"]]

    def resume_stream(rep, desc, rem):
        full = adopted[desc["rid"]]
        yield full[-2:-1]
        yield full[-1:]

    router = GatewayRouter(
        RouterConfig(max_attempts=4, backoff_s=0.0, block_size=2),
        transport=transport, resume_transport=resume,
        resume_stream_transport=resume_stream, sleep=lambda s: None)
    router.update([
        Replica(name="prefill-0", role="prefill"),
        Replica(name="decode-0", role="decode"),
    ])
    return router, calls


def test_gateway_routes_to_prefill_and_resumes_at_decode():
    adopted = {}
    router, calls = _disagg_router(adopted)
    # the decode replica is known but NOT in the new-request ring
    snap = router.stats()
    assert snap["ready_replicas"] == 1
    assert snap["ring"]["replicas"] == ["prefill-0"]
    assert snap["replicas"]["decode-0"]["role"] == "decode"

    toks, name, attempts = router.dispatch([1, 2, 3, 4], 3)
    assert name == "prefill-0" and attempts == 1
    assert toks == [1, 2, 3, 4, 900, 901, 902]

    # streaming: phase 1 unary to the prefill replica, deltas from the
    # decode replica
    out = []
    for delta in router.stream([5, 6, 7, 8], 2):
        out.extend(delta)
    assert out == [900, 901]
    assert router.stats()["handoffs"] == 2


def test_gateway_handoff_resume_retries_then_fails_terminally():
    from nos_tpu.gateway.router import HandoffResumeError

    # one transient decode hiccup: resumed on the retry, ONE prefill
    adopted = {}
    router, calls = _disagg_router(adopted, fail_resume=1)
    toks, _, _ = router.dispatch([1, 2], 2)
    assert toks == [1, 2, 900, 901]
    assert calls["prefill"] == 1 and calls["resume"] == 2

    # permanent decode failure: phase 2 exhausts its attempts and the
    # request fails TERMINALLY — the prefill replica is never asked to
    # re-prefill (the KV already moved)
    adopted = {}
    router, calls = _disagg_router(adopted, fail_resume=99)
    with pytest.raises(HandoffResumeError):
        router.dispatch([1, 2], 2)
    assert calls["prefill"] == 1
    assert router.stats()["requests"]["failed"] == 1


def test_discovery_parses_role_from_config_echo():
    server = ApiServer()
    client = Client(server)
    for name, role in (("pre-0", "prefill"), ("dec-0", "decode"),
                       ("co-0", None)):
        client.create(Pod(
            metadata=ObjectMeta(
                name=name, namespace="serving",
                labels={constants.LABEL_FLEET: "f"}),
            spec=PodSpec(containers=[Container()]),
            status=PodStatus(phase="Running")))

    def stats_source(pod):
        role = {"pre-0": "prefill", "dec-0": "decode"}.get(
            pod.metadata.name)
        snap = {"healthy": True}
        if role:
            snap["config"] = {"role": role}
        return snap

    disc = PodDiscovery(client, "f", "serving", stats_source)
    got = {r.name: r.role for r in disc.poll()}
    assert got == {"pre-0": "prefill", "dec-0": "decode",
                   "co-0": "colocated"}


# ---------------------------------------------------------------------------
# fleet-wide KV fabric (ISSUE 17): the gateway's peer-pull plane
# ---------------------------------------------------------------------------

def _chain_stats(prompt, bs=16, scope=None, tier="hbm"):
    """A replica /stats ``prefix_index`` section holding the prompt's
    full-block chain — the shape serving.prefix_index_snapshot emits."""
    from nos_tpu.kvfabric import chain_digest
    n = (len(prompt) // bs) * bs
    return {"prefix_index": {
        "chains": [{"digest": chain_digest(prompt[:n], scope),
                    "len": n, "tier": tier, "nbytes": n * 64,
                    "scope": scope}]}}


def test_fleet_index_ages_out_unscrapable_replicas():
    """A replica that stops answering /stats (empty snapshot) or
    leaves the fleet must drop out of the fleet prefix index on the
    next discovery pass — a stale entry is a wasted fetch against a
    dead pod on the latency path."""
    router = GatewayRouter(RouterConfig(fabric=True),
                           transport=lambda rep, req: req["prompt"])
    prompt = list(range(32))
    router.update([
        Replica(name="a", handle="http://a:8000"),
        Replica(name="b", handle="http://b:8000",
                stats=_chain_stats(prompt)),
    ])
    assert router.stats()["kv_fabric"]["chains"] == 1
    # b's /stats stopped answering: discovery hands it empty stats
    router.update([
        Replica(name="a", handle="http://a:8000"),
        Replica(name="b", handle="http://b:8000", stats={}),
    ])
    assert router.stats()["kv_fabric"]["chains"] == 0
    # and a replica absent from discovery entirely ages out too
    router.update([Replica(name="b", handle="http://b:8000",
                           stats=_chain_stats(prompt))])
    assert router.stats()["kv_fabric"]["chains"] == 1
    router.update([Replica(name="a", handle="http://a:8000")])
    assert router.stats()["kv_fabric"]["chains"] == 0


def test_fabric_attaches_one_peer_pull_offer():
    """Routed replica cold + a peer warm on the prompt's chain -> the
    dispatched request carries exactly ONE kv_sources offer naming the
    peer's /v1/kvchain/<digest>; the transport body forwards it."""
    from nos_tpu.kvfabric import chain_digest
    seen = {}

    def transport(rep, req):
        seen["req"] = req
        return req["prompt"]

    router = GatewayRouter(RouterConfig(fabric=True),
                           transport=transport)
    prompt = list(range(40))                    # 2 full blocks of 16
    router.update([
        Replica(name="a", handle="http://a:8000"),
        Replica(name="b", handle="http://b:8000", draining=True,
                stats=_chain_stats(prompt, tier="host")),
    ])
    _, name, _ = router.dispatch(prompt, 4)
    assert name == "a"                  # b is draining: never routed
    digest = chain_digest(prompt[:32])
    assert seen["req"]["kv_sources"] == [{
        "url": f"http://b:8000/v1/kvchain/{digest}",
        "digest": digest, "len": 32, "replica": "b"}]
    assert router.stats()["kv_fabric"]["offered"] == 1
    # the HTTP transport forwards the offer in the POST body and
    # stamps the fleet's fabric token on it (replicas drop tokenless
    # offers); the token never rides requests WITHOUT an offer
    from nos_tpu.cmd.gateway import HttpReplicaTransport
    from nos_tpu.kvfabric import FABRIC_TOKEN_HEADER
    import json as _json
    request, _ = HttpReplicaTransport(fabric_token="fleet-secret") \
        ._request(Replica(name="a", handle="http://a:8000"),
                  seen["req"], stream=False)
    assert _json.loads(request.data)["kv_sources"] == \
        seen["req"]["kv_sources"]
    tok_key = FABRIC_TOKEN_HEADER.capitalize()  # urllib's storage key
    assert request.headers[tok_key] == "fleet-secret"
    bare = dict(seen["req"])
    bare.pop("kv_sources")
    request, _ = HttpReplicaTransport(fabric_token="fleet-secret") \
        ._request(Replica(name="a", handle="http://a:8000"), bare,
                  stream=False)
    assert tok_key not in request.headers
    # a tokenless transport (fabric off) forwards the offer bare
    request, _ = HttpReplicaTransport()._request(
        Replica(name="a", handle="http://a:8000"), seen["req"],
        stream=False)
    assert tok_key not in request.headers


def test_fabric_no_offer_when_routed_replica_is_warmest():
    calls = []
    router = GatewayRouter(RouterConfig(fabric=True),
                           transport=lambda rep, req: calls.append(req)
                           or req["prompt"])
    prompt = list(range(40))
    # the routed replica holds the SAME 2-block chain: nothing to pull
    router.update([
        Replica(name="a", handle="http://a:8000",
                stats=_chain_stats(prompt)),
        Replica(name="b", handle="http://b:8000", draining=True,
                stats=_chain_stats(prompt)),
    ])
    router.dispatch(prompt, 4)
    assert "kv_sources" not in calls[-1]
    # a peer holding only a SHORTER chain than the routed replica's
    # own is not worth a fetch either
    router.update([
        Replica(name="a", handle="http://a:8000",
                stats=_chain_stats(prompt)),
        Replica(name="b", handle="http://b:8000", draining=True,
                stats=_chain_stats(prompt[:16])),
    ])
    router.dispatch(prompt, 4)
    assert "kv_sources" not in calls[-1]
    assert router.stats()["kv_fabric"]["offered"] == 0


def test_fabric_off_attaches_nothing_and_skips_the_index():
    calls = []
    router = GatewayRouter(RouterConfig(),        # fabric defaults off
                           transport=lambda rep, req: calls.append(req)
                           or req["prompt"])
    prompt = list(range(40))
    router.update([
        Replica(name="a", handle="http://a:8000"),
        Replica(name="b", handle="http://b:8000", draining=True,
                stats=_chain_stats(prompt)),
    ])
    router.dispatch(prompt, 4)
    assert "kv_sources" not in calls[-1]
    snap = router.stats()["kv_fabric"]
    assert snap == {"replicas": 0, "chains": 0, "enabled": False,
                    "offered": 0}
    assert router.stats()["config"]["fabric"] is False
    assert router.stats()["config"]["fabric_max_blocks"] == 32


def test_fabric_offers_are_tenant_scope_exact():
    """Digests embed the tenant scope: a peer's chain published under
    another tenant's scope can never be offered to this tenant's
    request — the lookup key itself differs, isolation needs no
    filter."""
    calls = []
    router = GatewayRouter(
        RouterConfig(fabric=True, tenant_config=_tenant_cfg()),
        transport=lambda rep, req: calls.append(req)
        or req["prompt"])
    prompt = list(range(40))
    router.update([
        Replica(name="a", handle="http://a:8000"),
        Replica(name="b", handle="http://b:8000", draining=True,
                stats=_chain_stats(prompt, scope="gold")),
    ])
    router.dispatch(prompt, 4, tenant="burst")
    assert "kv_sources" not in calls[-1]
    router.dispatch(prompt, 4, tenant="gold")
    assert calls[-1]["kv_sources"][0]["replica"] == "b"


def test_gateway_door_strips_client_supplied_kv_sources():
    """kv_sources is fleet-internal: a client posting its own offer to
    the gateway door would steer a replica's outbound fetcher (blind
    SSRF) and seed its prefix cache (poisoning) — the door strips the
    field; only the router may attach one."""
    import json as _json

    from nos_tpu.cmd.gateway import make_http_server as make_gw_server

    calls = []
    router = GatewayRouter(
        RouterConfig(),
        transport=lambda rep, req: calls.append(req) or req["prompt"])
    router.update([Replica(name="a", handle="http://a:8000")])
    gw_httpd = make_gw_server(router, 0, "web")
    threading.Thread(target=gw_httpd.serve_forever, daemon=True).start()
    gw = f"http://127.0.0.1:{gw_httpd.server_address[1]}"
    try:
        req = urllib.request.Request(
            gw + "/v1/generate",
            data=_json.dumps({
                "prompt": [1, 2], "max_new_tokens": 2,
                "kv_sources": [{"url": "file:///etc/passwd",
                                "digest": "aa"}]}).encode(),
            headers={"Content-Type": "application/json"}, method="POST")
        with urllib.request.urlopen(req, timeout=10) as r:
            assert r.status == 200
        assert calls and "kv_sources" not in calls[-1]
        assert "kv_sources" not in calls[-1].get("sampling", {})
    finally:
        gw_httpd.shutdown()


def test_gateway_main_refuses_tokenless_fabric():
    """--kv-fabric=on without --kv-fabric-token is a startup error,
    not a silent no-op: every replica drops tokenless peer-pull
    offers, so the fabric would never move a byte."""
    from nos_tpu.cmd import gateway as gateway_mod
    with pytest.raises(SystemExit):
        gateway_mod.main(["--kv-fabric", "on"])


def test_parse_replica_stats_carries_prefix_index():
    from nos_tpu.fleet.policy import parse_replica_stats
    sec = _chain_stats(list(range(32)))["prefix_index"]
    st = parse_replica_stats("r", {"healthy": True,
                                   "prefix_index": sec})
    assert st.prefix_index == sec
    # absent / malformed / unscrapable all read as None
    assert parse_replica_stats("r", {"healthy": True}).prefix_index \
        is None
    assert parse_replica_stats(
        "r", {"prefix_index": "junk"}).prefix_index is None
    assert parse_replica_stats("r", None).prefix_index is None
