"""Pipelined decode dispatch (models/serving.py): the in-flight tick
window (pipeline_depth) and fused multi-step decode (decode_steps).

The hard invariants this file pins:
- greedy outputs stay bit-identical to generate() at EVERY
  (pipeline_depth, decode_steps) combination — late-observed
  completions roll back by pos-reset, never by numerics;
- sampled streams are (seed, absolute-position)-keyed, so they are
  invariant to pipeline depth and fusion width too;
- batch-composition changes (admission install, cancel) are pipeline
  barriers that flush the window before mutating slot bindings;
- admission behavior (QueueFull) is unchanged by pipelining;
- the speculative engine pins both knobs to 1 and regresses nothing.

Engine reuse note: pipeline_depth is HOST-side state (the window bound)
— it never enters the compiled program — so tests share one drained
engine per (decode_steps, max_batch) and set ``eng.pipeline_depth``
directly instead of paying an XLA compile per grid point. decode_steps
IS compiled (the lax.scan length), so T=1 and T=4 get separate engines.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from nos_tpu.models import transformer as tfm
from nos_tpu.models.generate import generate
from nos_tpu.models.serving import DecodeServer, QueueFull

CFG = tfm.TransformerConfig(vocab=64, d_model=32, n_layers=2, n_heads=4,
                            n_kv_heads=2, d_ff=64, max_seq=64,
                            dtype=jnp.float32)

# the ISSUE grid: depth {1, 2, 4} x fused steps {1, 4}
GRID = [(d, t) for d in (1, 2, 4) for t in (1, 4)]


@pytest.fixture(scope="module")
def params():
    return tfm.init_params(jax.random.PRNGKey(0), CFG)


@pytest.fixture(scope="module")
def engines(params):
    """Shared drained engines keyed by (decode_steps, max_batch);
    at(depth, steps, mb) retunes the host-side window bound."""
    cache = {}

    def at(depth, steps=1, mb=2):
        eng = cache.get((steps, mb))
        if eng is None:
            eng = DecodeServer(params, CFG, max_batch=mb,
                               decode_steps=steps)
            cache[(steps, mb)] = eng
        assert not eng.has_work(), "previous test left work behind"
        eng.pipeline_depth = depth
        eng.max_pending = 0
        return eng

    return at


def ref(params, prompt, n):
    out = generate(params, CFG, jnp.asarray([prompt], jnp.int32), n)
    return [int(t) for t in out[0]]


@pytest.mark.parametrize("depth,steps", GRID)
def test_greedy_bit_exact_across_grid(engines, params, depth, steps):
    # 3 requests over 2 slots with unequal budgets: slot recycling (a
    # barrier-admission mid-pipeline) happens inside the run
    srv = engines(depth, steps)
    prompts = [([1, 2, 3], 6), ([60, 61], 9), ([7, 7, 7, 7, 7], 5)]
    rids = [srv.submit(p, n) for p, n in prompts]
    res = srv.drain()
    for rid, (p, n) in zip(rids, prompts):
        assert res[rid] == ref(params, p, n), (depth, steps, rid)


@pytest.mark.parametrize("depth,steps", [(4, 1), (2, 4)])
def test_late_arrival_joins_as_barrier(engines, params, depth, steps):
    srv = engines(depth, steps)
    r0 = srv.submit([1, 2, 3, 4], 12)
    for _ in range(3):
        srv.step()
    r1 = srv.submit([9, 9], 5)          # admission flushes the window
    assert not srv._inflight
    res = srv.drain()
    assert res[r0] == ref(params, [1, 2, 3, 4], 12)
    assert res[r1] == ref(params, [9, 9], 5)


def test_stop_token_late_detection_rolls_back(engines, params):
    # the stop token is produced early in the run but OBSERVED up to
    # depth*steps ticks late: output must truncate exactly at its first
    # occurrence, and the over-decoded slot must recycle cleanly (the
    # next request through that slot stays bit-exact)
    full = ref(params, [4, 5], 16)
    stop = full[2 + 3]                    # 4th generated token
    first_at = full.index(stop, 2)
    srv = engines(4, 4)
    rid = srv.submit([4, 5], 16, stop_tokens=[stop])
    s = next(s for s, r in srv._active.items() if r.rid == rid)
    res = srv.drain()
    assert res[rid] == full[:first_at + 1]
    assert res[rid][-1] == stop
    assert int(srv.cache["pos"][s]) == 0      # rollback: pos reset
    nxt = srv.submit([9, 8, 7], 6)            # recycled slot: still exact
    assert srv.drain()[nxt] == ref(params, [9, 8, 7], 6)


def test_max_new_reached_mid_window_rolls_back(engines, params):
    # a 2-token-budget decode at depth 4 over-decodes up to 3 extra
    # ticks; the overrun must be invisible in the result and the slot
    # reusable immediately
    srv = engines(4, 1, mb=1)
    rid = srv.submit([4, 5], 2)
    res = srv.drain()
    assert res[rid] == ref(params, [4, 5], 2)
    nxt = srv.submit([1, 2, 3], 8)
    assert srv.drain()[nxt] == ref(params, [1, 2, 3], 8)


def test_cancel_mid_flight_is_a_barrier(engines, params):
    srv = engines(4, 1, mb=1)
    rid_a = srv.submit([1, 2], 32)
    rid_b = srv.submit([3], 4)                # queued behind a
    for _ in range(3):
        srv.step()
    assert srv._inflight                      # ticks genuinely in flight
    assert srv.cancel(rid_a)
    assert not srv._inflight                  # barrier flushed the window
    out_a = srv.pop_result(rid_a)
    assert out_a[:2] == [1, 2]
    # truncated at the flushed length: prompt + first token + the
    # decode ticks that had landed by the barrier
    assert len(out_a) < 2 + 32
    results = srv.drain()                     # b got the freed slot
    assert results[rid_b] == ref(params, [3], 4)


def test_queue_full_unchanged_under_pipelining(engines, params):
    srv = engines(4, 1, mb=1)
    srv.max_pending = 1
    try:
        first = srv.submit([1, 2, 3], 30)
        srv.step()
        srv.submit([4, 5], 30)
        with pytest.raises(QueueFull, match="max_pending=1"):
            srv.submit([6], 2)
        results = srv.drain()
        assert len(results) == 2 and first in results
        srv.submit([7], 2)                    # admission re-opens
        srv.drain()
    finally:
        srv.max_pending = 0


@pytest.mark.parametrize("depth,steps", [(2, 1), (4, 4)])
def test_sampled_streams_invariant_to_depth(engines, params, depth, steps):
    kw = dict(temperature=0.9, top_k=8, seed=17)
    base = engines(1, 1)
    r = base.submit([4, 5], 8, **kw)
    want = base.drain()[r]

    srv = engines(depth, steps)
    r1 = srv.submit([4, 5], 8, **kw)                      # same seed
    r2 = srv.submit([9, 9], 8, temperature=1.2, seed=5)   # noisy neighbour
    res = srv.drain()
    assert res[r1] == want, (depth, steps)
    assert len(res[r2]) == 2 + 8


def test_chunked_prefill_composes_with_pipelining(params):
    # a long prompt chunk-prefills while other slots decode through the
    # in-flight window; both requests stay exact
    srv = DecodeServer(params, CFG, max_batch=2, pipeline_depth=4,
                       prefill_chunk=8)
    r0 = srv.submit([1, 2, 3], 10)
    for _ in range(2):
        srv.step()
    long = list(range(1, 31))                 # 30 tokens: several chunks
    r1 = srv.submit(long, 5)
    res = srv.drain()
    assert res[r0] == ref(params, [1, 2, 3], 10)
    assert res[r1] == ref(params, long, 5)


def test_split_step_protocol_and_token_accounting(engines, params):
    # step_begin/step_wait/step_finish compose to step(), and every
    # token is credited exactly once even when barrier flushes consume
    # arrivals between phases
    srv = engines(2, 1)
    rids = [srv.submit([1, 2], 4), srv.submit([9], 6)]
    total = 2                                 # prefill emitted 2 already
    while srv.has_work():
        h = srv.step_begin()
        srv.step_wait(h)
        total += srv.step_finish(h)
    res = srv.drain()
    assert total == 4 + 6
    assert res[rids[0]] == ref(params, [1, 2], 4)
    assert res[rids[1]] == ref(params, [9], 6)
    assert srv.tokens_emitted >= 4 + 6 - 2    # engine-side cumulative


def test_window_fills_to_depth_and_drains(engines, params):
    srv = engines(4, 1, mb=1)
    srv.reset_dispatch_stats()
    srv.submit([1, 2], 16)
    srv.step()
    # one step dispatched up to depth ticks and consumed the oldest
    assert len(srv._inflight) == 3
    assert srv.ticks_dispatched == 4
    srv.drain()
    assert not srv._inflight                  # drain leaves nothing behind


def test_dispatch_stats_accumulate(engines, params):
    srv = engines(2, 1)
    srv.reset_dispatch_stats()
    tokens0 = srv.tokens_emitted
    srv.submit([1, 2, 3], 8)
    srv.drain()
    assert srv.ticks_dispatched > 0
    assert srv.host_block_s > 0.0
    assert srv.tokens_emitted - tokens0 >= 7


def test_depth1_pays_a_dispatch_gap_deeper_windows_hide_it(
        engines, params):
    # the structural claim behind nos_tpu_serve_dispatch_gap_seconds
    # and the bench acceptance gate: at depth 1 the window empties on
    # every consume (gap grows per tick); at depth >= 2 it only empties
    # at barriers
    srv = engines(1, 1, mb=1)
    srv.reset_dispatch_stats()
    srv.submit([1, 2], 12)
    srv.drain()
    gap1 = srv.dispatch_gap_s
    assert gap1 > 0.0

    srv = engines(4, 1, mb=1)
    srv.reset_dispatch_stats()
    srv.submit([1, 2], 12)
    srv.drain()
    assert srv.dispatch_gap_s < gap1          # window hides the gap


def test_validation(params):
    with pytest.raises(ValueError, match="pipeline_depth"):
        DecodeServer(params, CFG, pipeline_depth=0)
    with pytest.raises(ValueError, match="decode_steps"):
        DecodeServer(params, CFG, decode_steps=0)


# ---------------------------------------------------------------------------
# speculative engine: the dispatch knobs are HONORED (ISSUE 10 unpinned
# the clamp) and output stays exact — the full grid lives in
# tests/test_spec_paged.py; this pins the template integration
# ---------------------------------------------------------------------------

def test_speculative_engine_honors_pipeline_and_stays_exact(params):
    from nos_tpu.models.spec_serving import SpeculativeDecodeServer

    dcfg = tfm.TransformerConfig(vocab=64, d_model=16, n_layers=1,
                                 n_heads=2, n_kv_heads=1, d_ff=32,
                                 max_seq=64, dtype=jnp.float32)
    dparams = tfm.init_params(jax.random.PRNGKey(1), dcfg)
    srv = SpeculativeDecodeServer(
        params, CFG, dparams, dcfg, n_draft=3, max_batch=2,
        pipeline_depth=2, decode_steps=2)     # honored, not clamped
    assert srv.pipeline_depth == 2
    assert srv.decode_steps == 2
    r1 = srv.submit([4, 5], 10)
    r2 = srv.submit([9, 8, 7], 8)
    res = srv.drain()
    assert res[r1] == ref(params, [4, 5], 10)
    assert res[r2] == ref(params, [9, 8, 7], 8)
    # the window genuinely pipelines: more than one tick may be in
    # flight between steps (ticks dispatched outruns arrivals consumed
    # at some point is hard to observe post-drain; assert the knob
    # reached the template instead)
    assert srv._spec_tick is not None


# two representative corners stay tier-1 (both dtypes, both k values,
# complementary to the pair test_serving_sharded.py keeps); the full
# grid rides -m slow — each case compiles TWO spec engines (kernel on
# + gather oracle) and the tier-1 wall budget is shared
@pytest.mark.parametrize("k,T,kv_dtype", [
    pytest.param(1, 1, "bf16", marks=pytest.mark.slow),
    pytest.param(1, 1, "int8", marks=pytest.mark.slow),
    (1, 4, "bf16"),
    pytest.param(1, 4, "int8", marks=pytest.mark.slow),
    pytest.param(2, 1, "bf16", marks=pytest.mark.slow),
    pytest.param(2, 1, "int8", marks=pytest.mark.slow),
    pytest.param(2, 4, "bf16", marks=pytest.mark.slow),
    (2, 4, "int8"),
])
def test_spec_kernel_on_matches_gather_oracle_over_grid(
        params, monkeypatch, k, T, kv_dtype):
    """ISSUE 16 acceptance, single-host leg: the paged speculative
    engine with the fused kernel ON commits token-for-token what the
    XLA gather formulation commits, across the full (n_draft,
    decode_steps) x dtype grid with greedy AND seeded-sampled slots.
    The kernel's verify bursts ride S>1 query windows; a width-S
    window accumulates exactly what S sequential S==1 steps would, so
    neither the accept/reject walk nor the residual draws can see the
    formulation."""
    from nos_tpu.models.spec_serving import SpeculativeDecodeServer

    dcfg = tfm.TransformerConfig(vocab=64, d_model=16, n_layers=1,
                                 n_heads=2, n_kv_heads=1, d_ff=32,
                                 max_seq=64, dtype=jnp.float32)
    dparams = tfm.init_params(jax.random.PRNGKey(1), dcfg)
    reqs = [([4, 5], 10, dict()),
            ([9, 8, 7], 8, dict(temperature=0.6, top_k=8, seed=7))]

    def trace():
        srv = SpeculativeDecodeServer(
            params, CFG, dparams, dcfg, n_draft=k, decode_steps=T,
            max_batch=2, max_len=64, kv_block_size=8, kv_blocks=24,
            kv_dtype=kv_dtype)
        rids = [srv.submit(p, n, **kw) for p, n, kw in reqs]
        out = srv.drain()
        return [out[r] for r in rids], srv.kv_stats()["kernel"]

    monkeypatch.setenv("NOS_TPU_PAGED_KERNEL", "1")
    on, echo_on = trace()
    monkeypatch.setenv("NOS_TPU_PAGED_KERNEL", "0")
    off, echo_off = trace()
    assert (echo_on, echo_off) == ("kernel", "xla")
    assert on == off, (k, T, kv_dtype)


def test_random_schedules_stay_exact_under_pipelining(engines, params):
    """Crash-prober twin of test_serving.test_random_schedules_stay_exact
    with the pipeline on: random lengths, budgets, arrival points, AND
    random step interleavings between submissions — every surviving
    request bit-exact at (depth 3, steps 4), a deliberately odd corner
    of the grid."""
    rng = np.random.default_rng(23)
    for trial in range(2):
        srv = engines(3, 4)
        n_req = int(rng.integers(3, 6))
        reqs = [([int(t) for t in rng.integers(0, 64, rng.integers(1, 41))],
                 int(rng.integers(1, 7))) for _ in range(n_req)]
        rids = []
        for p, n in reqs:
            rids.append(srv.submit(p, n))
            for _ in range(int(rng.integers(0, 3))):
                srv.step()
        results = srv.drain()
        for rid, (p, n) in zip(rids, reqs):
            assert results[rid] == ref(params, p, n), (trial, rid, p, n)
