"""Decode-tick phase profiler (ISSUE 18): the serving-loop tick
decomposed into assemble / dispatch / wait / sample / bookkeep under
the one-clock-read discipline — the nos_tpu_serve_tick_phase_seconds
histogram, the /stats rolling breakdown, and the /debug/profile
Perfetto export of the last N ticks. Jax-free: stub engines, the real
ServingLoop + HTTP surface."""
import json
import threading
import time
import urllib.error
import urllib.request

import pytest

from nos_tpu.cmd.server import (
    TICK_PHASES, ServerConfig, ServingLoop, make_http_server,
)
from test_trace_stitching import _InstantEngine, fresh_recorder


class _SplitEngine(_InstantEngine):
    """Split-step stub with a visible wait phase and an assemble stamp
    (the DecodeServer seam: ``last_assemble_s`` is host work inside
    step_begin minus its dispatch call)."""

    last_assemble_s = 0.0

    def step_begin(self):
        t0 = time.perf_counter()
        time.sleep(0.002)       # host-side assemble work
        self.last_assemble_s = time.perf_counter() - t0
        return object()

    def step_wait(self, handle):
        time.sleep(0.004)       # the "device" computes

    def step_finish(self, handle):
        return self.step()


def test_tick_phases_in_stats_and_histogram():
    loop = ServingLoop(_SplitEngine())
    try:
        loop.generate([1, 2], 2, timeout=30)
        snap = loop.stats()["tick_phases"]
        assert snap["window"] >= 1
        assert set(snap["seconds"]) == set(TICK_PHASES)
        assert all(v >= 0.0 for v in snap["seconds"].values())
        # the split protocol's signature: a real wait phase, and the
        # assemble stamp carved out of the pre-dispatch host time
        assert snap["seconds"]["wait"] > 0.0
        assert snap["seconds"]["assemble"] > 0.0
        # every phase label observed, one histogram sample per phase
        # per tick
        n = None
        for ph in TICK_PHASES:
            child = loop.h_tick_phase.labels(ph)
            assert child.count >= 1
            n = child.count if n is None else n
            assert child.count == n, "phases must tick in lockstep"
    finally:
        loop.shutdown()


def test_tick_phases_whole_step_engine_buckets_under_dispatch():
    """step()-only engines (no split protocol) can't be decomposed:
    the whole step lands under ``dispatch`` and wait/sample stay
    zero — phases never lie about a seam that wasn't measured."""
    loop = ServingLoop(_InstantEngine())
    try:
        loop.generate([1], 2, timeout=30)
        snap = loop.stats()["tick_phases"]
        assert snap["window"] >= 1
        assert snap["seconds"]["dispatch"] >= 0.0
        assert snap["seconds"]["wait"] == 0.0
        assert snap["seconds"]["sample"] == 0.0
        assert snap["seconds"]["assemble"] == 0.0
    finally:
        loop.shutdown()


def test_profile_trace_shape_and_recorder_isolation():
    loop = ServingLoop(_SplitEngine())
    try:
        # no ticks yet: a valid, empty Perfetto document
        assert loop.profile_trace() == {"traceEvents": [],
                                        "displayTimeUnit": "ms"}
        loop.generate([1, 2, 3], 3, timeout=30)
        with fresh_recorder() as rec:
            doc = loop.profile_trace(last_n=8)
            # synthesized spans must NEVER feed the flight recorder —
            # /debug/profile is a read, not a write
            assert rec.trace_ids() == []
        evs = [e for e in doc["traceEvents"] if e["ph"] == "X"]
        assert evs, "at least one tick drawn"
        roots = [e for e in evs if e["name"] == "serve.tick"]
        kids = [e for e in evs if e["name"].startswith("tick.")]
        assert roots and kids
        assert {e["name"] for e in kids} <= {
            "tick." + ph for ph in TICK_PHASES}
        # one Perfetto lane: every tick shares the synthetic trace id
        assert len({e["tid"] for e in evs}) == 1
        # children tile their root: phase spans sit inside the tick
        r0 = roots[0]
        for e in kids:
            if e["args"]["trace_id"] == r0["args"]["trace_id"]:
                assert e["ts"] >= r0["ts"] - 1e-6
        # last_n bounds the window
        one = loop.profile_trace(last_n=1)
        assert len([e for e in one["traceEvents"]
                    if e.get("name") == "serve.tick"]) == 1
    finally:
        loop.shutdown()


def test_debug_profile_endpoint_over_http():
    loop = ServingLoop(_SplitEngine())
    httpd = make_http_server(ServerConfig(port=0), loop)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    url = f"http://127.0.0.1:{httpd.server_address[1]}"
    try:
        body = json.dumps({"prompt": [5], "max_new_tokens": 2}).encode()
        req = urllib.request.Request(
            url + "/v1/generate", data=body,
            headers={"Content-Type": "application/json"}, method="POST")
        with urllib.request.urlopen(req, timeout=30) as r:
            json.loads(r.read())
        with urllib.request.urlopen(url + "/debug/profile?ticks=4",
                                    timeout=10) as r:
            doc = json.loads(r.read())
        assert doc["displayTimeUnit"] == "ms"
        names = {e["name"] for e in doc["traceEvents"]
                 if e["ph"] == "X"}
        assert "serve.tick" in names
        assert any(n.startswith("tick.") for n in names)
        # a garbage ?ticks is a clean 400, not a 500
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(url + "/debug/profile?ticks=soon",
                                   timeout=10)
        assert ei.value.code == 400
    finally:
        httpd.shutdown()
        loop.shutdown()


def test_phase_histogram_carries_tick_exemplars():
    """A slow phase must link to the concrete serve.tick trace that
    produced it: the labeled histogram observes with the tick span's
    trace_id, surfacing OpenMetrics exemplars."""
    loop = ServingLoop(_SplitEngine())
    try:
        loop.generate([1], 2, timeout=30)
        child = loop.h_tick_phase.labels("wait")
        assert child.exemplars is not None
        assert any(ex is not None for ex in child.exemplars)
    finally:
        loop.shutdown()


# ---------------------------------------------------------------------------
# bench_profile: TTFT decomposition over stitched traces
# ---------------------------------------------------------------------------

def _journey(tid="a" * 32, t0=100.0):
    """A deterministic disaggregated journey as span dicts (fixed
    floats — byte-reproducibility needs identical inputs, and the
    decomposition itself must add no entropy)."""
    return [
        {"name": "gateway.request", "component": "gateway",
         "trace_id": tid, "span_id": "r" * 16, "parent_id": None,
         "start": t0, "end": t0 + 2.0,
         "attrs": {"door_wait_s": 0.25, "attempts": 2}},
        {"name": "gateway.attempt", "component": "gateway",
         "trace_id": tid, "span_id": "a1" * 8, "parent_id": "r" * 16,
         "start": t0 + 0.3, "end": t0 + 0.35,
         "attrs": {"attempt": 1, "outcome": "unreachable"}},
        {"name": "gateway.attempt", "component": "gateway",
         "trace_id": tid, "span_id": "a2" * 8, "parent_id": "r" * 16,
         "start": t0 + 0.4, "end": t0 + 2.0,
         "attrs": {"attempt": 2, "outcome": "completed"}},
        {"name": "serve.request", "component": "server",
         "trace_id": tid, "span_id": "p" * 16, "parent_id": "a2" * 8,
         "start": t0 + 0.45, "end": t0 + 1.0,
         "attrs": {"role": "prefill", "queue_ms": 50.0,
                   "ttft_ms": 500.0}},
        {"name": "serve.request", "component": "server",
         "trace_id": tid, "span_id": "d" * 16, "parent_id": "p" * 16,
         "start": t0 + 1.2, "end": t0 + 2.0,
         "attrs": {"role": "decode", "adopted": True,
                   "ttft_ms": 80.0}},
    ]


def test_ttft_decomposition_values():
    import bench_profile

    row = bench_profile.decompose_ttft(_journey())
    assert row == {
        "trace_id": "a" * 32,
        "door_wait_s": 0.25,
        # winning (completed) attempt start - root start - door wait
        "route_s": pytest.approx(0.15),
        "queue_s": pytest.approx(0.05),
        # prefill ttft minus its queueing share
        "prefill_s": pytest.approx(0.45),
        # prefill span end -> decode span start (ship + adopt)
        "handoff_s": pytest.approx(0.2),
        "first_decode_tick_s": pytest.approx(0.08),
        "attempts": 2,
    }
    # colocated journey: no prefill/decode pair, no handoff phases
    colo = [s for s in _journey() if s["attrs"].get("role") != "decode"]
    colo[-1]["attrs"]["role"] = "colocated"
    row2 = bench_profile.decompose_ttft(colo)
    assert row2["handoff_s"] is None
    assert row2["first_decode_tick_s"] is None
    assert row2["queue_s"] == pytest.approx(0.05)
    # a span set with no gateway root is not a journey
    assert bench_profile.decompose_ttft(
        [s for s in _journey() if s["name"] != "gateway.request"]) is None


def test_ttft_artifact_is_byte_reproducible(tmp_path):
    import bench_profile

    spans = _journey() + _journey(tid="b" * 32, t0=500.0)
    p1 = tmp_path / "one.json"
    p2 = tmp_path / "two.json"
    bench_profile.write_ttft_artifact(spans, path=str(p1))
    # same spans, shuffled order: the artifact must not depend on
    # input ordering (traces are sorted, keys canonicalized)
    bench_profile.write_ttft_artifact(list(reversed(spans)),
                                      path=str(p2))
    b1, b2 = p1.read_bytes(), p2.read_bytes()
    assert b1 == b2, "TTFT artifact must be byte-reproducible"
    doc = json.loads(b1)
    assert doc["section"] == "ttft_decomposition"
    assert doc["journeys"] == 2
    assert [r["trace_id"] for r in doc["requests"]] == \
        ["a" * 32, "b" * 32]
