from nos_tpu.utils.generic import filter_list, unordered_equal, min_by, max_by
from nos_tpu.utils.stat import iter_permutations
from nos_tpu.kube.quantity import parse_quantity, format_quantity


def test_unordered_equal():
    assert unordered_equal([1, 2, 2], [2, 1, 2])
    assert not unordered_equal([1, 2], [1, 2, 2])
    assert not unordered_equal([1, 3], [1, 2])
    assert unordered_equal([{"a": 1}], [{"a": 1}])  # unhashable items


def test_filter_min_max():
    assert filter_list([1, 2, 3, 4], lambda x: x % 2 == 0) == [2, 4]
    assert min_by([3, 1, 2], lambda x: x) == 1
    assert max_by([], lambda x: x) is None


def test_iter_permutations_dedup():
    perms = list(iter_permutations(["a", "a", "b"]))
    assert len(perms) == 3  # 3!/2! distinct
    assert ["a", "a", "b"] in perms and ["b", "a", "a"] in perms


def test_iter_permutations_limit():
    perms = list(iter_permutations([1, 2, 3, 4], limit=5))
    assert len(perms) == 5


def test_parse_quantity():
    assert parse_quantity("500m") == 0.5
    assert parse_quantity("4") == 4.0
    assert parse_quantity("10Gi") == 10 * 2**30
    assert parse_quantity("1k") == 1000.0
    assert parse_quantity(7) == 7.0
    assert format_quantity(4.0) == "4"


def test_parse_quantity_invalid():
    import pytest

    with pytest.raises(ValueError):
        parse_quantity("abc")
    with pytest.raises(ValueError):
        parse_quantity("1Xx")


def test_parse_quantity_nano_micro():
    assert abs(parse_quantity("100n") - 1e-7) < 1e-15
    assert abs(parse_quantity("250u") - 25e-5) < 1e-12


def test_iter_permutations_duplicates_fast():
    # 10 equal items: must yield exactly 1 permutation quickly (not 10! work)
    perms = list(iter_permutations(["x"] * 10))
    assert perms == [["x"] * 10]
