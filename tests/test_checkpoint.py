"""Training-plane checkpoint/resume (nos_tpu/train/checkpoint.py): save
under one sharding, resume under another, training continues bit-identical."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from nos_tpu.models import transformer as tfm
from nos_tpu.parallel.layout import ParallelLayout
from nos_tpu.parallel.mesh import build_mesh, data_sharding
from nos_tpu.train import CheckpointManager

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs 8 virtual devices")


def cfg():
    return tfm.TransformerConfig(vocab=64, d_model=32, n_layers=2, n_heads=4,
                                 d_ff=64, max_seq=16, dtype=jnp.float32)


def setup(layout, c, seed=0):
    import optax

    mesh = build_mesh(layout, jax.devices()[:layout.chips])
    params = jax.device_put(
        tfm.init_params(jax.random.PRNGKey(seed), c),
        tfm.param_shardings(mesh, c))
    opt = optax.adamw(1e-3)
    step = jax.jit(tfm.make_train_step(c, opt, mesh))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 16), 0, c.vocab)
    batch = {"tokens": jax.device_put(tokens, data_sharding(mesh)),
             "targets": jax.device_put(tokens, data_sharding(mesh))}
    return mesh, params, opt, step, batch


def test_save_restore_roundtrip_across_meshes(tmp_path):
    c = cfg()
    mesh, params, opt, step, batch = setup(ParallelLayout(dp=2, tp=2), c)
    opt_state = opt.init(params)
    params, opt_state, loss0 = step(params, opt_state, batch)

    mgr = CheckpointManager(str(tmp_path / "ckpt"))
    mgr.save(1, params, opt_state)
    assert mgr.latest() == 1

    # resume on a DIFFERENT layout: fsdp4 instead of dp2 x tp2
    mesh2, params2_init, opt2, step2, batch2 = setup(ParallelLayout(fsdp=4), c)
    tmpl_p = jax.device_put(params2_init, tfm.param_shardings(mesh2, c))
    tmpl_o = opt2.init(tmpl_p)
    r_params, r_opt = mgr.restore(params_template=tmpl_p,
                                  opt_state_template=tmpl_o, mesh=mesh2)
    mgr.close()

    # restored values equal the saved ones
    for a, b in zip(jax.tree.leaves(params), jax.tree.leaves(r_params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    # continuing training from the restored state matches continuing from
    # the original state
    p_ref, _, loss_ref = step(params, opt_state, batch)
    p_res, _, loss_res = step2(r_params, r_opt, batch2)
    np.testing.assert_allclose(float(loss_res), float(loss_ref), rtol=1e-5)


def test_latest_and_retention(tmp_path):
    from nos_tpu.train.checkpoint import latest_step

    c = cfg()
    _, params, opt, step, batch = setup(ParallelLayout(dp=2), c)
    opt_state = opt.init(params)
    # the manager-free witness (the harvester's reclaim-resume gate)
    # reads the same storage truth, including "nothing committed yet"
    assert latest_step(str(tmp_path / "ckpt")) is None
    mgr = CheckpointManager(str(tmp_path / "ckpt"), max_to_keep=2)
    for s in (1, 2, 3):
        mgr.save(s, params, opt_state)
    assert mgr.latest() == 3
    assert sorted(mgr.manager.all_steps()) == [2, 3]   # retention pruned 1
    assert latest_step(str(tmp_path / "ckpt")) == 3
    assert latest_step(str(tmp_path / "never-written")) is None
    mgr.close()


def test_wait_within_bounds_an_async_save(tmp_path):
    """The budgeted fence the reclaim-notice discipline uses: True when
    the background commit lands inside the budget (and the checkpoint
    really is durable by then), monotone-safe to call again after."""
    c = cfg()
    _, params, opt, step, batch = setup(ParallelLayout(dp=2), c)
    opt_state = opt.init(params)
    mgr = CheckpointManager(str(tmp_path / "ckpt"))
    mgr.save(1, params, opt_state, wait=False)
    assert mgr.wait_within(30.0) is True
    assert mgr.latest() == 1
    # idle manager: an immediate re-fence returns at once
    assert mgr.wait_within(0.1) is True
    mgr.close()


def test_relative_directory_saves(tmp_path, monkeypatch):
    """A pod spec saying `checkpoint_dir: ckpt` must work: orbax rejects
    relative paths deep inside save(), so the manager absolutizes."""
    monkeypatch.chdir(tmp_path)
    c = cfg()
    mesh, params, opt, step, batch = setup(ParallelLayout(dp=2, tp=2), c)
    opt_state = opt.init(params)
    mgr = CheckpointManager("ckpt")
    mgr.save(1, params, opt_state)
    mgr.close()
    assert CheckpointManager(str(tmp_path / "ckpt")).latest() == 1


def test_restore_empty_dir_raises(tmp_path):
    mgr = CheckpointManager(str(tmp_path / "none"))
    with pytest.raises(FileNotFoundError):
        mgr.restore(params_template={}, opt_state_template={})
    mgr.close()


def test_async_save_completes_by_close(tmp_path):
    """wait=False returns while orbax serializes in the background;
    close() fences, after which a fresh manager sees the step."""
    import jax
    import jax.numpy as jnp
    import optax

    from nos_tpu.models import transformer as tfm
    from nos_tpu.train import CheckpointManager

    cfg = tfm.TransformerConfig(vocab=32, d_model=16, n_layers=1, n_heads=2,
                                d_ff=32, max_seq=16, dtype=jnp.float32)
    params = tfm.init_params(jax.random.PRNGKey(0), cfg)
    opt = optax.adamw(1e-3)
    state = opt.init(params)

    m = CheckpointManager(str(tmp_path))
    m.save(3, params, state, wait=False)
    m.close()

    m2 = CheckpointManager(str(tmp_path))
    assert m2.latest() == 3
    restored = m2.restore_params(params_template=params)
    assert jnp.allclose(restored["embed"], params["embed"])
    m2.close()


def test_pre_layer_order_stamp_defaults_to_canonical(tmp_path):
    """An OLD checkpoint stamp (no layer_order field) must be treated as
    canonical order: resuming it under the interleaved schedule is the
    exact drift the stamp exists to reject, and key-skipping comparison
    would silently pass it."""
    import json
    import os

    import pytest

    from nos_tpu.train import CheckpointManager

    d = str(tmp_path / "ckpt")
    os.makedirs(d)
    with open(os.path.join(d, "model_config.json"), "w") as f:
        json.dump({"vocab": 64, "d_model": 32, "n_layers": 4,
                   "n_heads": 4, "n_kv_heads": 4, "d_ff": 64,
                   "n_experts": 0}, f)   # pre-layer_order era stamp
    ck = CheckpointManager(d)
    expect = {"vocab": 64, "d_model": 32, "n_layers": 4, "n_heads": 4,
              "n_kv_heads": 4, "d_ff": 64, "n_experts": 0,
              "layer_order": "interleaved:pp=2,v=2"}
    with pytest.raises(ValueError, match="layer_order"):
        ck.validate_model_config(expect)
    # canonical consumer of the old stamp stays fine
    ck.validate_model_config({**expect, "layer_order": "canonical"})


def test_interleave_rejects_indivisible_layers():
    import jax
    import pytest

    from nos_tpu.models import transformer as tfm
    from nos_tpu.parallel.pipeline import interleave_params

    cfg = tfm.TransformerConfig(vocab=32, d_model=16, n_layers=6,
                                n_heads=2, d_ff=32, max_seq=16)
    params = tfm.init_params(jax.random.PRNGKey(0), cfg)
    with pytest.raises(ValueError, match="DROP"):
        interleave_params(params, 2, 2)    # 6 % 4 != 0
