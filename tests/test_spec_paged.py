"""Fast-path speculative decoding (ISSUE 10 tentpole): the spec engine
unpinned from pipeline_depth=decode_steps=1 and brought onto paged KV.

Acceptance invariants pinned here:
- greedy output stays bit-identical to plain target decoding at EVERY
  (pipeline_depth, decode_steps) in {1,2} x {1,4}, slot-static AND
  paged — including across a COW fork and a preempt-and-resume in both
  modes (swap and recompute);
- sampled spec streams are reproducible and invariant to the dispatch
  knobs and to paging (the RNG keys on (seed, absolute position,
  sub-stream), never on dispatch shape);
- DRAFT-cache coherence (the ride-along bugfix): fork() and preempt()
  must keep the draft's KV in lockstep with the committed sequence.
  Greedy token output CANNOT catch a stale draft (accept-reject
  guarantees target tokens whatever the draft proposes), so the sharp
  probe is acceptance itself: with draft == target every verify window
  must accept ALL proposals — any post-fork/post-preempt acceptance
  drop means the draft cache drifted;
- block accounting: both pools (target + draft) balance at quiescence,
  and verify-window rollback trims speculated-ahead tail blocks back
  to the committed footprint once the in-flight window drains.
"""
import jax
import jax.numpy as jnp
import pytest

from nos_tpu.models import transformer as tfm
from nos_tpu.models.generate import generate
from nos_tpu.models.kvblocks import blocks_for
from nos_tpu.models.serving import QueueFull  # noqa: F401 (fork shed)
from nos_tpu.models.spec_serving import SpeculativeDecodeServer

TARGET = dict(vocab=64, d_model=32, n_layers=2, n_heads=4, n_kv_heads=2,
              d_ff=64, max_seq=64, dtype=jnp.float32)
DRAFT = dict(vocab=64, d_model=16, n_layers=1, n_heads=2, n_kv_heads=1,
             d_ff=32, max_seq=64, dtype=jnp.float32)

TCFG = tfm.TransformerConfig(**TARGET)
DCFG = tfm.TransformerConfig(**DRAFT)

# the ISSUE acceptance grid: every previously-pinned combination
GRID = [(d, t) for d in (1, 2) for t in (1, 4)]


@pytest.fixture(scope="module")
def models():
    return (tfm.init_params(jax.random.PRNGKey(0), TCFG),
            tfm.init_params(jax.random.PRNGKey(1), DCFG))


def ref(tp, prompt, n):
    return [int(t) for t in
            generate(tp, TCFG, jnp.asarray([prompt], jnp.int32), n)[0]]


def mk(models, *, depth=1, steps=1, paged=True, blocks=24, mb=2, **kw):
    tp, dp = models
    if paged:
        kw.update(kv_block_size=8, kv_blocks=blocks)
    return SpeculativeDecodeServer(
        tp, TCFG, dp, DCFG, n_draft=3, max_batch=mb,
        pipeline_depth=depth, decode_steps=steps, **kw)


def assert_pools_balanced(srv):
    """Quiescent invariant for BOTH pools: target blocks all free or
    prefix-held, draft blocks all free (the draft never publishes)."""
    assert not srv.has_work()
    held = srv._pindex.block_count if srv._pindex is not None else 0
    assert srv._alloc.used_count == held, (srv._alloc.used_count, held)
    assert srv._d_alloc.used_count == 0, srv._d_alloc.used_count
    assert not srv._deferred and not srv._d_deferred
    assert all(not t for t in srv._tables)
    assert all(not t for t in srv._d_tables)


# ---------------------------------------------------------------------------
# greedy bit-exactness across the unpinned grid
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("depth,steps", GRID)
@pytest.mark.parametrize("paged", [False, True])
def test_spec_greedy_bit_exact_across_grid(models, depth, steps, paged):
    tp, _ = models
    srv = mk(models, depth=depth, steps=steps, paged=paged)
    # 3 requests over 2 slots: slot recycling + draft-row recycling
    prompts = [([4, 5], 10), ([9, 8, 7], 8), ([7, 7, 7, 7], 5)]
    rids = [srv.submit(p, n) for p, n in prompts]
    res = srv.drain()
    for rid, (p, n) in zip(rids, prompts):
        assert res[rid] == ref(tp, p, n), (depth, steps, paged, rid)
    if paged:
        assert_pools_balanced(srv)


@pytest.mark.parametrize("depth,steps", GRID)
def test_spec_cow_fork_bit_exact_across_grid(models, depth, steps):
    tp, _ = models
    srv = mk(models, depth=depth, steps=steps, blocks=40)
    r0 = srv.submit([4, 5], 16)
    srv.step()
    f0 = srv.fork(r0)
    assert srv._alloc.shared_count() > 0      # target blocks shared
    # the DRAFT is copied, never shared (it writes every round)
    assert srv._d_alloc.shared_count() == 0
    res = srv.drain()
    want = ref(tp, [4, 5], 16)
    assert res[r0] == want, (depth, steps, "source")
    assert res[f0] == want, (depth, steps, "fork")
    assert_pools_balanced(srv)


@pytest.mark.parametrize("mode", ["swap", "recompute"])
@pytest.mark.parametrize("depth,steps", GRID)
def test_spec_preempt_resume_bit_exact_across_grid(models, depth, steps,
                                                   mode):
    tp, _ = models
    srv = mk(models, depth=depth, steps=steps, blocks=40)
    r0 = srv.submit([4, 5], 20)
    r1 = srv.submit([9, 8, 7], 8)
    for _ in range(2):
        srv.step()
    assert srv.preempt(r0, mode)
    assert srv.kv_stats()["preempts"][mode] >= 1
    res = srv.drain()
    assert res[r0] == ref(tp, [4, 5], 20), (depth, steps, mode)
    assert res[r1] == ref(tp, [9, 8, 7], 8), (depth, steps, mode)
    assert_pools_balanced(srv)


# ---------------------------------------------------------------------------
# sampled streams: reproducible, knob- and paging-invariant
# ---------------------------------------------------------------------------

def test_spec_sampled_streams_invariant_to_knobs_and_paging(models):
    kw = dict(temperature=0.9, top_k=8, seed=17)
    base = mk(models, depth=1, steps=1, paged=False)
    r = base.submit([4, 5], 8, **kw)
    want = base.drain()[r]
    for depth, steps in [(2, 1), (1, 4), (2, 4)]:
        for paged in (False, True):
            srv = mk(models, depth=depth, steps=steps, paged=paged)
            r1 = srv.submit([4, 5], 8, **kw)
            r2 = srv.submit([9, 9], 8, temperature=1.2, seed=5)
            res = srv.drain()
            assert res[r1] == want, (depth, steps, paged)
            assert len(res[r2]) == 2 + 8


# ---------------------------------------------------------------------------
# draft-cache coherence (the ride-along bugfix): with draft == target,
# every verify window must accept everything — forever, across fork and
# preempt. A stale draft row shows up as an acceptance drop.
# ---------------------------------------------------------------------------

def mk_self_draft(models, **kw):
    tp, _ = models
    kw.setdefault("pipeline_depth", 2)
    kw.setdefault("decode_steps", 1)
    kw.setdefault("kv_block_size", 8)
    kw.setdefault("kv_blocks", 48)
    return SpeculativeDecodeServer(tp, TCFG, tp, TCFG, n_draft=3,
                                   max_batch=3, **kw)


def assert_full_acceptance(srv):
    assert srv.spec_drafted > 0
    assert srv.spec_accepted == srv.spec_drafted, (
        f"acceptance {srv.spec_accepted}/{srv.spec_drafted}: the draft "
        f"cache diverged from the committed sequence")


def test_fork_keeps_draft_cache_coherent(models):
    tp, _ = models
    srv = mk_self_draft(models)
    r0 = srv.submit([4, 5], 14)
    for _ in range(2):
        srv.step()
    f0 = srv.fork(r0)
    res = srv.drain()
    want = ref(tp, [4, 5], 14)
    assert res[r0] == want and res[f0] == want
    # the sharp probe: the FORK's windows accepted everything too —
    # before the fix the fork's draft rows held garbage, so its rounds
    # would reject and acceptance would sag below 100%
    assert_full_acceptance(srv)
    assert_pools_balanced(srv)


@pytest.mark.parametrize("mode", ["swap", "recompute"])
def test_preempt_keeps_draft_cache_coherent(models, mode):
    tp, _ = models
    srv = mk_self_draft(models)
    r0 = srv.submit([4, 5], 18)
    r1 = srv.submit([9, 8], 8)
    for _ in range(2):
        srv.step()
    assert srv.preempt(r0, mode)
    res = srv.drain()
    assert res[r0] == ref(tp, [4, 5], 18), mode
    assert res[r1] == ref(tp, [9, 8], 8), mode
    assert_full_acceptance(srv)
    assert_pools_balanced(srv)


def test_draft_pos_tracks_committed_across_fork_and_preempt(models):
    srv = mk_self_draft(models)
    r0 = srv.submit([4, 5], 16)
    for _ in range(2):
        srv.step()
    srv.fork(r0)
    srv._flush()
    for s, req in srv._active.items():
        # invariant: draft processed == committed[:-1]
        want = len(req.prompt) + len(req.out) - 1
        assert int(srv.d_cache["pos"][s]) == want, (s, req.rid)
    srv.drain()
    assert_pools_balanced(srv)


def test_sampled_stream_bitexact_across_preempt(models):
    """Sampled accept-reject draws depend on the draft's q — a stale
    draft changes the sample PATH. The preempted-and-resumed run must
    reproduce the undisturbed run token-for-token."""
    kw = dict(temperature=0.8, top_k=8, seed=23)
    srv = mk(models, depth=1, steps=1, blocks=40)
    r = srv.submit([4, 5], 12, **kw)
    want = srv.drain()[r]

    srv2 = mk(models, depth=1, steps=1, blocks=40)
    r2 = srv2.submit([4, 5], 12, **kw)
    for _ in range(2):
        srv2.step()
    assert srv2.preempt(r2, "recompute")
    assert srv2.drain()[r2] == want
    assert_pools_balanced(srv2)


# ---------------------------------------------------------------------------
# paged-specific discipline
# ---------------------------------------------------------------------------

def test_rollback_trims_speculative_tail_blocks(models):
    """After a window drains, no slot may hold blocks past its
    committed footprint — speculated-ahead writes were rolled back by
    pos, and their tail blocks must return to the pool with their
    table entries zeroed to the null block."""
    srv = mk(models, depth=1, steps=1, blocks=24)
    srv.submit([4, 5], 16)
    for _ in range(3):
        srv.step()
    assert not srv._inflight
    for s, req in srv._active.items():
        need = blocks_for(len(req.prompt) + len(req.out) - 1, 8)
        assert len(srv._tables[s]) <= need, (s, srv._tables[s])
        assert len(srv._d_tables[s]) <= need
        # device table tail beyond the host table is the null block
        row = [int(x) for x in srv._table[s]]
        assert all(x == 0 for x in row[len(srv._tables[s]):])
    srv.drain()
    assert_pools_balanced(srv)


def test_paged_spec_drops_slotstatic_headroom_guard(models):
    """Slot-static spec submits reserve pipeline*steps*n_draft
    positions of headroom below max_len; PAGED submits need none
    (overrun writes null-route), so paging WIDENS the servable range."""
    static = mk(models, depth=2, steps=1, paged=False, mb=1)
    window = 2 * 1 * 3
    plen = 64 - 4 - window + 1          # static guard trips by 1
    with pytest.raises(ValueError, match="draft window"):
        static.submit(list(range(1, plen + 1)), 4)
    tp, _ = models
    paged = mk(models, depth=2, steps=1, mb=1, blocks=24)
    rid = paged.submit(list(range(1, plen + 1)), 4)
    res = paged.drain()
    assert res[rid] == ref(tp, list(range(1, plen + 1)), 4)
    assert_pools_balanced(paged)


def test_spec_prefix_cache_composes_with_paging(models):
    tp, _ = models
    system = list(range(1, 20))         # 19 tokens -> 2 full blocks
    srv = mk(models, depth=2, steps=1, blocks=40,
             prefix_cache_size=8)
    srv.submit(system + [33], 2, cache_prefix=True)
    srv.drain()
    rid = srv.submit(system + [40, 41], 6)
    res = srv.drain()
    assert srv.kv_stats()["prefix"]["hits"] == 1
    assert res[rid] == ref(tp, system + [40, 41], 6)
    srv._pindex.clear()
    srv.prefix_hits = srv.prefix_tokens_saved = 0
    assert_pools_balanced(srv)


def test_spec_chunked_prefill_composes_with_paging(models):
    tp, _ = models
    srv = mk(models, depth=2, steps=1, blocks=40, prefill_chunk=8)
    r0 = srv.submit([1, 2, 3], 8)
    for _ in range(2):
        srv.step()
    long = list(range(1, 31))
    r1 = srv.submit(long, 5)
    res = srv.drain()
    assert res[r0] == ref(tp, [1, 2, 3], 8)
    assert res[r1] == ref(tp, long, 5)
    assert_pools_balanced(srv)


def test_spec_stats_surface(models):
    srv = mk(models, depth=2, steps=1)
    rid = srv.submit([1, 2, 3], 6)
    srv.drain()
    srv.pop_result(rid)
    st = srv.stats()
    spec = st["speculative"]
    assert spec["n_draft"] == 3
    assert spec["drafted"] > 0
    assert 0 <= spec["accepted"] <= spec["drafted"]
    dkv = spec["draft_kv"]
    assert dkv["blocks_total"] == dkv["blocks_free"] + dkv["blocks_used"]
    assert st["pipeline"]["depth"] == 2
    # window events parked for the serving loop's histogram
    assert srv.spec_window_events


def test_spec_int8_kv_self_consistent_across_depth(models):
    """int8 KV under speculation: the (1,1) run IS the reference —
    every other (depth, steps) must reproduce it token-for-token
    (same quantize/dequantize path, same accept/reject math)."""
    base = mk(models, depth=1, steps=1, blocks=40, kv_dtype="int8")
    prompts = [([4, 5], 10), ([9, 8, 7], 8)]
    rids = [base.submit(p, n) for p, n in prompts]
    res0 = base.drain()
    want = [res0[r] for r in rids]
    for depth, steps in [(2, 1), (2, 4)]:
        srv = mk(models, depth=depth, steps=steps, blocks=40,
                 kv_dtype="int8")
        rids = [srv.submit(p, n) for p, n in prompts]
        res = srv.drain()
        assert [res[r] for r in rids] == want, (depth, steps)
        assert_pools_balanced(srv)


def test_chunked_admission_reserves_draft_blocks(models):
    """The draft pool's install blocks are reserved at chunked-
    admission start (review finding): decoders growing draft blocks
    across the prefill ticks must not be able to drain the pool out
    from under the pending install — NoFreeBlocks escaping step()
    would kill the serving loop. decode_steps=4 makes the decoder
    outrun the chunked prefill, the same squeeze shape the target's
    reservation test uses."""
    tp, _ = models
    srv = mk(models, depth=1, steps=4, blocks=12, prefill_chunk=8,
             kv_swap=False)
    r0 = srv.submit(list(range(1, 8)), 20)
    long = list(range(1, 33))
    r1 = srv.submit(long, 2)
    # the chunked admission (if taken) holds a draft reservation
    if srv._prefilling:
        rid = srv._prefilling[0]["req"].rid
        assert rid not in srv._chunked_dreserved \
            or srv._chunked_dreserved[rid]
    res = srv.drain()
    assert res[r0] == ref(tp, list(range(1, 8)), 20)
    assert res[r1] == ref(tp, long, 2)
    assert not srv._chunked_dreserved
    assert_pools_balanced(srv)


def test_cancel_mid_prefill_releases_draft_reservation(models):
    srv = mk(models, depth=1, steps=1, blocks=40, prefill_chunk=8)
    r0 = srv.submit([1, 2, 3], 6)
    long = list(range(1, 31))
    r1 = srv.submit(long, 5)
    assert srv._prefilling
    assert srv._chunked_dreserved.get(r1)
    used = srv._d_alloc.used_count
    assert srv.cancel(r1)
    assert r1 not in srv._chunked_dreserved
    assert srv._d_alloc.used_count < used
    res = srv.drain()
    tp, _ = models
    assert res[r0] == ref(tp, [1, 2, 3], 6)
    assert_pools_balanced(srv)


def test_fork_requires_paging_still(models):
    srv = mk(models, paged=False)
    srv.submit([1, 2], 4)
    with pytest.raises(RuntimeError, match="paged"):
        srv.fork(0)
    srv.drain()
