"""Fleet-level drain chaos (ISSUE 8 acceptance): scale-down drains are
LOSSLESS — every in-flight request on a drained replica finishes or is
requeued and completes, exactly once. Rides the PR 7 harness: real
ServingLoops over the deterministic StubEngine token mill (next token
== absolute position, so any duplicated or dropped work is visible in
the output itself), plus the seeded FaultInjector for the
drain-during-restart interplay.

The router here plays the role the Service + client retries play in a
real fleet: a request shed by a draining/dead replica is resubmitted to
a surviving one.
"""
import threading
import time

from test_serving_chaos import StubEngine, outcome_delta, outcome_totals

from nos_tpu.cmd.server import DrainingError, ServingLoop
from nos_tpu.models.errors import EngineRecovering, QueueFull
from nos_tpu.models.supervision import FaultInjector


def expected_tokens(prompt, n):
    return list(prompt) + [len(prompt) + i for i in range(n)]


class FleetRouter:
    """Round-robin over non-draining replicas with retry-on-shed: the
    fleet-level requeue path a drained replica's in-flight work takes."""

    def __init__(self, loops):
        self.loops = loops
        self._rr = 0
        self._lock = threading.Lock()

    def _pick(self, exclude):
        with self._lock:
            order = list(range(len(self.loops)))
            order = order[self._rr:] + order[:self._rr]
            self._rr = (self._rr + 1) % len(self.loops)
        for i in order:
            loop = self.loops[i]
            if i not in exclude and loop.healthy and not loop.draining:
                return i, loop
        return None, None

    def run(self, prompt, n, attempts=12, **kw):
        """Returns (tokens, tries). Retries until a replica delivers.
        Extra kwargs (e.g. ``tenant=``) forward to the serving loop."""
        tried = set()
        last = None
        for _ in range(attempts):
            i, loop = self._pick(tried)
            if loop is None:
                tried = set()       # all excluded: widen and back off
                time.sleep(0.01)
                continue
            try:
                return loop.generate(list(prompt), n, timeout=60,
                                     **kw), i
            except (DrainingError, QueueFull, EngineRecovering,
                    TimeoutError, RuntimeError) as e:
                last = e
                tried.add(i)
                continue
        raise AssertionError(f"request never completed: {last}")


def run_fleet_trace(loops, n_requests, new_tokens):
    router = FleetRouter(loops)
    results = {}
    errors = {}

    def worker(i):
        prompt = [100 + i]
        try:
            toks, replica = router.run(prompt, new_tokens)
            results[i] = (toks, replica)
        except Exception as e:      # noqa: BLE001 — asserted below
            errors[i] = e

    threads = [threading.Thread(target=worker, args=(i,))
               for i in range(n_requests)]
    for t in threads:
        t.start()
    return threads, results, errors


def join_all(threads, timeout=60):
    for t in threads:
        t.join(timeout)
    assert not any(t.is_alive() for t in threads), "stuck request"


def test_graceful_drain_finishes_in_flight_work_losslessly():
    """A drained replica keeps decoding what it admitted (admission
    stops, /readyz flips); requests shed at its door complete on the
    survivors. Every request finishes exactly once, tokens exact."""
    before = outcome_totals()
    loops = [ServingLoop(StubEngine(tokens_per_tick=4))
             for _ in range(3)]
    try:
        threads, results, errors = run_fleet_trace(
            loops, n_requests=18, new_tokens=60)
        time.sleep(0.005)
        # the controller's step 2: stop admitting, let work finish
        loops[0].begin_drain()
        assert loops[0].wait_idle(timeout=30)
        join_all(threads)
        assert errors == {}
        assert len(results) == 18
        for i, (toks, _) in results.items():
            assert toks == expected_tokens([100 + i], 60), f"req {i}"
        # conservation across the whole fleet: each request earned
        # exactly one ``finished`` somewhere
        delta = outcome_delta(before)
        assert delta["finished"] == 18
        assert delta["failed"] == 0
    finally:
        for lp in loops:
            lp.shutdown()


def test_drain_timeout_requeues_unfinished_work_exactly_once():
    """The drain budget expires with work still in flight (the
    controller releases the pod anyway): displaced requests are
    requeued by the router and complete on survivors — outcome
    conservation holds, nothing completes twice, tokens stay exact."""
    before = outcome_totals()
    loops = [ServingLoop(StubEngine(tokens_per_tick=1))
             for _ in range(3)]
    try:
        threads, results, errors = run_fleet_trace(
            loops, n_requests=15, new_tokens=300)
        time.sleep(0.02)            # work is mid-flight everywhere
        # drain budget ~0: the release path (pod delete / SIGTERM)
        loops[0].begin_drain()
        loops[0].wait_idle(timeout=0.01)
        loops[0].shutdown()
        join_all(threads)
        assert errors == {}
        assert len(results) == 15
        for i, (toks, _) in results.items():
            assert toks == expected_tokens([100 + i], 300), f"req {i}"
        # the shed replica's in-flight work really was displaced and
        # completed elsewhere
        displaced = [i for i, (_, replica) in results.items()
                     if replica != 0]
        assert displaced, "drain displaced nothing — test lost its bite"
        delta = outcome_delta(before)
        # exactly one finish per request; the killed replica's
        # interrupted admissions drained as failed/cancelled, never as
        # a second finish
        assert delta["finished"] == 15
        assert delta["failed"] >= 0
        assert sum(max(0, int(v)) for v in delta.values()) >= 15
    finally:
        for lp in loops:
            lp.shutdown()


def test_drain_during_supervised_restart_interplay():
    """Drain one replica while another is mid-supervised-restart (the
    PR 7 injector): the router rides out both — 503s from the
    recovering replica, sheds from the draining one — and every
    request still completes exactly once with exact tokens."""
    before = outcome_totals()
    inj = FaultInjector(schedule={6: "error"})
    loops = [
        ServingLoop(StubEngine(tokens_per_tick=2)),
        ServingLoop(inj.wrap(StubEngine(tokens_per_tick=2)),
                    engine_factory=lambda: inj.wrap(
                        StubEngine(tokens_per_tick=2)),
                    restart_budget=4, restart_backoff_s=0.01),
        ServingLoop(StubEngine(tokens_per_tick=2)),
    ]
    try:
        threads, results, errors = run_fleet_trace(
            loops, n_requests=12, new_tokens=120)
        time.sleep(0.01)
        loops[0].begin_drain()
        loops[0].wait_idle(timeout=30)
        join_all(threads)
        assert errors == {}
        assert len(results) == 12
        for i, (toks, _) in results.items():
            assert toks == expected_tokens([100 + i], 120), f"req {i}"
        delta = outcome_delta(before)
        assert delta["finished"] == 12
    finally:
        for lp in loops:
            lp.shutdown()


def test_burst_tenant_adversary_over_restart_conserves_per_tenant():
    """ISSUE 13 chaos satellite, fleet edition: tenant-tagged traffic
    (a guaranteed tenant + a burst adversary at many times its share)
    rides the retrying router across replicas while one replica dies
    through a supervised restart mid-flight. Pins per-tenant outcome
    conservation — submitted == finished + rejected per tenant, tagged
    by tenant at the CLIENT — and no cross-tenant double-finish after
    the rebuilt engine restores its captured requests (every finished
    output is exact for its own prompt, and the fleet-wide finished
    total is exactly the per-tenant finished sum)."""
    from nos_tpu.models.tenantquota import TenantQuotaConfig

    before = outcome_totals()
    tq = TenantQuotaConfig.from_json(
        '{"tenants": {"gold": {"min_rate": 1000},'
        ' "burst": {"max_rate": 1000}}}')
    inj = FaultInjector(schedule={6: "error"})
    loops = [
        ServingLoop(StubEngine(tokens_per_tick=2), tenant_quota=tq),
        ServingLoop(inj.wrap(StubEngine(tokens_per_tick=2)),
                    engine_factory=lambda: inj.wrap(
                        StubEngine(tokens_per_tick=2)),
                    restart_budget=4, restart_backoff_s=0.01,
                    tenant_quota=tq),
        ServingLoop(StubEngine(tokens_per_tick=2), tenant_quota=tq),
    ]
    router = FleetRouter(loops)
    reqs = [("gold", i) for i in range(4)] \
        + [("burst", i) for i in range(12)]
    results, errors = {}, {}

    def worker(tenant, i):
        prompt = [100 + i if tenant == "gold" else 200 + i]
        try:
            toks, replica = router.run(prompt, 80, tenant=tenant)
            results[(tenant, i)] = (toks, replica, list(prompt))
        except Exception as e:      # noqa: BLE001 — asserted below
            errors[(tenant, i)] = e

    threads = [threading.Thread(target=worker, args=r) for r in reqs]
    for t in threads:
        t.start()
    join_all(threads, timeout=120)
    try:
        assert errors == {}
        assert len(results) == len(reqs)
        # per-tenant conservation at the client: every tagged request
        # finished exactly once
        by_tenant = {}
        for (tenant, _i), (toks, _rep, prompt) in results.items():
            by_tenant[tenant] = by_tenant.get(tenant, 0) + 1
            # no cross-tenant double-finish / restore mix-up: the
            # output is ITS OWN prompt's token mill, exactly
            assert toks == expected_tokens(prompt, 80), (tenant, _i)
        assert by_tenant == {"gold": 4, "burst": 12}
        # fleet-wide ledger agrees: exactly one finish per request —
        # the restarted replica's restored requests did not finish a
        # second time anywhere
        delta = outcome_delta(before)
        assert delta["finished"] == len(reqs)
    finally:
        for lp in loops:
            lp.shutdown()
