"""Foundation coverage the lifecycle controller relies on (ISSUE 2
satellite): K8sSim watch bisect-resume semantics, and lease-expiry
detection staying precise under concurrent node churn.

The K8sSim tests talk raw HTTP (the shape a resuming informer sends); a
resumed ``?watch=true&resourceVersion=N`` stream must deliver exactly
the events with rv > N, in rv order, regardless of how much unrelated
history the log holds or how hard writers are churning concurrently."""
import json
import threading
import time
import urllib.request

import pytest

from nos_tpu import constants
from nos_tpu.kube.k8s_sim import K8sSim


@pytest.fixture()
def sim():
    s = K8sSim().start()
    yield s
    s.stop()


def _post(sim, path, body):
    req = urllib.request.Request(
        sim.url + path, data=json.dumps(body).encode(), method="POST",
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req) as resp:
        return json.loads(resp.read())


def _delete(sim, path):
    req = urllib.request.Request(sim.url + path, method="DELETE")
    with urllib.request.urlopen(req) as resp:
        return json.loads(resp.read())


def _node(name):
    return {"apiVersion": "v1", "kind": "Node",
            "metadata": {"name": name}}


def _read_watch(sim, path, since, want, timeout_s=5.0):
    """Open a resumed watch and read up to ``want`` events (list of
    (rv, type, name)); closes the stream when satisfied."""
    req = urllib.request.Request(
        f"{sim.url}{path}?watch=true&resourceVersion={since}")
    out = []
    resp = urllib.request.urlopen(req, timeout=timeout_s)
    try:
        deadline = time.monotonic() + timeout_s
        while len(out) < want and time.monotonic() < deadline:
            line = resp.readline()
            if not line:
                break
            ev = json.loads(line)
            meta = ev["object"]["metadata"]
            out.append((int(meta["resourceVersion"]), ev["type"],
                        meta["name"]))
    finally:
        resp.close()
    return out


def test_watch_bisect_resume_replays_only_later_events(sim):
    for i in range(5):
        _post(sim, "/api/v1/nodes", _node(f"early-{i}"))
    since = int(_post(sim, "/api/v1/nodes",
                      _node("marker"))["metadata"]["resourceVersion"])
    for i in range(5):
        _post(sim, "/api/v1/nodes", _node(f"late-{i}"))

    got = _read_watch(sim, "/api/v1/nodes", since, want=5)
    assert [name for _, _, name in got] == [f"late-{i}" for i in range(5)]
    rvs = [rv for rv, _, _ in got]
    assert all(rv > since for rv in rvs)
    assert rvs == sorted(rvs)


def test_watch_resume_from_zero_replays_everything(sim):
    for i in range(3):
        _post(sim, "/api/v1/nodes", _node(f"n-{i}"))
    got = _read_watch(sim, "/api/v1/nodes", 0, want=3)
    assert [name for _, _, name in got] == ["n-0", "n-1", "n-2"]


def test_watch_resume_under_concurrent_node_churn(sim):
    """Writers churn nodes while a late subscriber resumes mid-log: the
    resumed stream must be gap-free, duplicate-free, strictly
    rv-ascending, and include nothing at or before its resume point."""
    for i in range(10):
        _post(sim, "/api/v1/nodes", _node(f"seed-{i}"))
    since = int(_post(sim, "/api/v1/nodes",
                      _node("resume-marker"))["metadata"]["resourceVersion"])

    n_churn = 30
    def churn():
        for i in range(n_churn):
            _post(sim, "/api/v1/nodes", _node(f"churn-{i}"))
            if i % 3 == 0:
                _delete(sim, f"/api/v1/nodes/churn-{i}")

    writers = [threading.Thread(target=churn)]
    for w in writers:
        w.start()
    # ADDED for every churn node + DELETED for every third
    want = n_churn + len(range(0, n_churn, 3))
    got = _read_watch(sim, "/api/v1/nodes", since, want=want, timeout_s=10)
    for w in writers:
        w.join()

    assert len(got) == want, (len(got), want)
    rvs = [rv for rv, _, _ in got]
    assert all(rv > since for rv in rvs)
    assert rvs == sorted(rvs) and len(set(rvs)) == len(rvs)
    # ADDED/DELETED pair up per churned name
    adds = {n for _, t, n in got if t == "ADDED"}
    dels = {n for _, t, n in got if t == "DELETED"}
    assert adds == {f"churn-{i}" for i in range(n_churn)}
    assert dels == {f"churn-{i}" for i in range(0, n_churn, 3)}


def test_lease_expiry_detection_precise_under_node_churn():
    """In-proc foundation: while unrelated nodes churn (create/delete
    every tick), exactly the heartbeat-dead node is fenced — churn events
    must neither mask the expiry nor false-positive a live node — and the
    displaced gang still lands atomically."""
    from nos_tpu.kube.objects import Node, NodeStatus, ObjectMeta
    from tests.test_lifecycle_controller import Rig

    rig = Rig()
    rig.gang()
    rig.settle(1.0)
    victim = sorted(rig.bound_nodes().values())[0]
    rig.renewing.discard(victim)

    # churn: a rolling set of non-TPU nodes appearing and vanishing
    for step in range(12):
        name = f"churn-{step}"
        rig.server.create(Node(
            metadata=ObjectMeta(name=name),
            status=NodeStatus(capacity={"cpu": 4}, allocatable={"cpu": 4}),
        ))
        if step >= 2:
            rig.server.delete("Node", f"churn-{step - 2}")
        rig.settle(0.5)

    fenced = [
        n.metadata.name for n in rig.server.list("Node")
        if n.metadata.annotations.get(constants.ANNOTATION_LIFECYCLE_CORDONED)
    ]
    assert fenced == [victim]
    after = rig.bound_nodes()
    assert len(after) == 2 and victim not in after.values()
    pools = {n.rsplit("-w", 1)[0] for n in after.values()}
    assert len(pools) == 1
