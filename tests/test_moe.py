"""Mixture-of-Experts FFN + expert-parallel transformer (ops/moe.py)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from nos_tpu.ops.moe import expert_capacity, moe_ffn, top2_gating


def test_expert_capacity_floor():
    assert expert_capacity(seq=64, n_experts=8, capacity_factor=1.0) == 16
    assert expert_capacity(seq=2, n_experts=8, capacity_factor=1.0) == 1


def test_top2_gating_shapes_and_weights_normalized():
    rng = jax.random.PRNGKey(0)
    logits = jax.random.normal(rng, (2, 16, 4), jnp.float32)
    combine, dispatch, aux = top2_gating(logits, capacity=8)
    assert combine.shape == (2, 16, 4, 8)
    assert dispatch.shape == (2, 16, 4, 8)
    # with ample capacity every token keeps both experts: weights sum to 1
    totals = np.asarray(jnp.sum(combine, axis=(2, 3)))
    np.testing.assert_allclose(totals, 1.0, atol=1e-5)
    assert float(aux) > 0


def test_top2_gating_respects_capacity():
    # all tokens prefer expert 0; capacity 2 keeps only the first 2 top-1
    # assignments per batch row
    logits = jnp.tile(jnp.array([10.0, 0.0]), (1, 6, 1))      # [1, 6, 2]
    combine, dispatch, _ = top2_gating(logits, capacity=2)
    per_expert = np.asarray(jnp.sum(dispatch, axis=(0, 1, 3)))  # tokens kept
    assert per_expert[0] == 2          # expert 0 full at capacity
    assert per_expert[1] <= 2          # overflow went to the runner-up


def test_top2_gating_buffer_slots_unique():
    rng = jax.random.PRNGKey(1)
    logits = jax.random.normal(rng, (2, 32, 4), jnp.float32)
    _, dispatch, _ = top2_gating(logits, capacity=16)
    # no (expert, slot) receives two tokens from the same batch row
    per_slot = np.asarray(jnp.sum(dispatch, axis=1))           # [B, E, C]
    assert per_slot.max() <= 1


def test_moe_ffn_matches_dense_reference_with_ample_capacity():
    """With capacity >= seq*2/E the dense einsum path must equal the naive
    per-token top-2 mixture computed in plain numpy-style code."""
    rng = jax.random.PRNGKey(2)
    b, s, d, f, e = 2, 8, 16, 32, 2
    ks = jax.random.split(rng, 5)
    h = jax.random.normal(ks[0], (b, s, d), jnp.float32)
    router = jax.random.normal(ks[1], (d, e), jnp.float32)
    w_gate = jax.random.normal(ks[2], (e, d, f), jnp.float32) * 0.1
    w_up = jax.random.normal(ks[3], (e, d, f), jnp.float32) * 0.1
    w_down = jax.random.normal(ks[4], (e, f, d), jnp.float32) * 0.1

    out, _ = moe_ffn(h, router, w_gate, w_up, w_down, capacity_factor=4.0)

    gates = jax.nn.softmax(h @ router, axis=-1)                # [B,S,E]
    expert_out = []
    for i in range(e):
        gate = jax.nn.silu(h @ w_gate[i])
        expert_out.append((gate * (h @ w_up[i])) @ w_down[i])
    expert_out = jnp.stack(expert_out, axis=2)                 # [B,S,E,d]
    # top-2 = all experts when e == 2; weights renormalize to 1 -> plain mix
    ref = jnp.einsum("bse,bsed->bsd", gates, expert_out)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-5)


def test_dropped_tokens_produce_zero_output():
    # capacity 1, 4 tokens all preferring expert 0 of 2: tokens beyond the
    # buffers contribute nothing (residual path carries them in the model)
    h = jnp.ones((1, 4, 8), jnp.float32)
    router = jnp.zeros((8, 2), jnp.float32).at[0, 0].set(5.0)
    w = jnp.ones((2, 8, 8), jnp.float32)
    out, _ = moe_ffn(h, router, w, w, jnp.ones((2, 8, 8)), capacity_factor=0.25)
    # identical tokens: the kept slots produce identical outputs; ensure at
    # least one token was dropped (zero row) under the tiny capacity
    norms = np.asarray(jnp.linalg.norm(out, axis=-1))[0]
    assert (norms == 0).sum() >= 1


# ---------------------------------------------------------------------------
# expert-parallel transformer on the virtual mesh
# ---------------------------------------------------------------------------

@pytest.mark.skipif(len(jax.devices()) < 8, reason="needs 8 virtual devices")
def test_moe_transformer_trains_with_ep_axis():
    import optax

    from nos_tpu.models import transformer as tfm
    from nos_tpu.parallel.layout import ParallelLayout
    from nos_tpu.parallel.mesh import build_mesh, data_sharding

    layout = ParallelLayout(dp=2, tp=2, ep=2)
    mesh = build_mesh(layout, jax.devices()[:8])
    cfg = tfm.TransformerConfig(
        vocab=128, d_model=32, n_layers=2, n_heads=4, d_ff=64,
        max_seq=32, dtype=jnp.float32, n_experts=4,
    )
    params = tfm.init_params(jax.random.PRNGKey(0), cfg)
    shardings = tfm.param_shardings(mesh, cfg)
    params = jax.device_put(params, shardings)
    # expert weights really live on the ep axis
    spec = shardings["layers"]["w_gate"].spec
    assert any(a == "ep" or (isinstance(a, tuple) and "ep" in a) for a in spec)

    optimizer = optax.adamw(1e-3)
    opt_state = optimizer.init(params)
    step = jax.jit(tfm.make_train_step(cfg, optimizer, mesh))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 32), 0, cfg.vocab)
    batch = {"tokens": jax.device_put(tokens, data_sharding(mesh)),
             "targets": jax.device_put(tokens, data_sharding(mesh))}
    params, opt_state, loss = step(params, opt_state, batch)
    assert jnp.isfinite(loss)
    # second step reuses the compiled program and the loss moves
    _, _, loss2 = step(params, opt_state, batch)
    assert jnp.isfinite(loss2)


def test_dense_transformer_unchanged_by_moe_fields():
    from nos_tpu.models import transformer as tfm

    cfg = tfm.TransformerConfig(vocab=64, d_model=16, n_layers=1, n_heads=2,
                                d_ff=32, max_seq=16, dtype=jnp.float32)
    params = tfm.init_params(jax.random.PRNGKey(0), cfg)
    assert "w_router" not in jax.tree.leaves(
        {k: 1 for k in params["layers"]})  # no router params in dense mode
    tokens = jnp.zeros((1, 8), jnp.int32)
    logits = tfm.forward(params, cfg, tokens)
    assert logits.shape == (1, 8, 64)
