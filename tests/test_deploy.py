"""Deployment-layer validation (SURVEY §2.6 L8: helm chart, kustomize
mirrors, Dockerfiles, kind config — analog of the reference's
helm-charts/nos + config/ + build/ + hack/kind).

Helm templates contain Go-template directives and cannot be YAML-parsed
directly; they get structural checks (balanced delimiters, referenced
values exist). The config/ mirrors are plain YAML and are parsed and
cross-checked against the component configs they feed.
"""
import glob
import os
import re

import pytest
import yaml

from nos_tpu.api import configs

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CHART = os.path.join(REPO, "helm-charts", "nos-tpu")
CONFIG = os.path.join(REPO, "config")


# ---------------------------------------------------------------------------
# Helm chart
# ---------------------------------------------------------------------------
def test_chart_metadata_parses():
    with open(os.path.join(CHART, "Chart.yaml")) as f:
        chart = yaml.safe_load(f)
    assert chart["name"] == "nos-tpu"
    assert chart["apiVersion"] == "v2"
    with open(os.path.join(CHART, "values.yaml")) as f:
        values = yaml.safe_load(f)
    for key in ("operator", "scheduler", "tpuPartitioner", "tpuAgent",
                "metricsExporter", "tpuMemoryGB"):
        assert key in values, f"values.yaml missing {key}"
    # reference parity: batch windows 60/10 (values.yaml:276,283)
    assert values["tpuPartitioner"]["batchWindowTimeoutSeconds"] == 60
    assert values["tpuPartitioner"]["batchWindowIdleSeconds"] == 10
    assert values["tpuAgent"]["reportConfigIntervalSeconds"] == 10


def _templates():
    pats = os.path.join(CHART, "templates", "**", "*.yaml")
    return sorted(glob.glob(pats, recursive=True))


def test_templates_exist_for_every_component():
    names = [os.path.relpath(t, CHART) for t in _templates()]
    joined = "\n".join(names)
    for frag in ("apiserver/deployment_apiserver",
                 "operator/deployment_operator", "operator/rbac_operator",
                 "scheduler/deployment_scheduler",
                 "tpu-partitioner/deployment_tpu-partitioner",
                 "tpu-partitioner/configmap_known-tpu-topologies",
                 "tpuagent/daemonset_tpuagent", "pod_metrics-exporter",
                 "fleet/deployment_fleet", "fleet/rbac_fleet",
                 "gateway/deployment_gateway", "gateway/rbac_gateway",
                 "harvest/deployment_harvest", "harvest/rbac_harvest"):
        assert frag in joined, f"missing template {frag}"


def test_workload_templates_dial_the_apiserver():
    """Every CONTROL-PLANE workload container must pass --api
    (serve.connect exits otherwise) and the apiserver deployment itself
    must exist. The serving pod is exempt: nos-tpu-server is a
    workload-plane model server the operator stack schedules — it has
    no --api flag and talks to nothing but its clients."""
    for t in _templates():
        with open(t) as f:
            text = f.read()
        if re.search(r"kind: (Deployment|DaemonSet)", text) \
                and "component: apiserver" not in text \
                and "component: serving" not in text:
            assert "--api=" in text, f"{t}: workload without --api"


def test_templates_balanced_delimiters():
    for path in _templates():
        with open(path) as f:
            text = f.read()
        assert text.count("{{") == text.count("}}"), path
        opens = len(re.findall(r"\{\{-?\s*(?:if|range|with)\b", text))
        closes = len(re.findall(r"\{\{-?\s*end\s*-?\}\}", text))
        assert opens == closes, f"{path}: {opens} open blocks, {closes} ends"


def test_template_values_references_exist():
    """Every .Values.foo.bar referenced by a template resolves in values.yaml."""
    with open(os.path.join(CHART, "values.yaml")) as f:
        values = yaml.safe_load(f)

    def resolve(path):
        cur = values
        for part in path:
            if not isinstance(cur, dict) or part not in cur:
                return False
            cur = cur[part]
        return True

    tpl_files = _templates() + sorted(
        glob.glob(os.path.join(CHART, "templates", "**", "*.tpl"),
                  recursive=True)
    )
    for path in tpl_files:
        with open(path) as f:
            text = f.read()
        for m in re.finditer(r"\.Values\.([A-Za-z0-9_.]+)", text):
            parts = m.group(1).split(".")
            # nameOverride/nameOverride-style optional keys use `default`
            if parts[-1] in ("nameOverride", "namespaceOverride"):
                continue
            assert resolve(parts), f"{path}: .Values.{m.group(1)} not in values.yaml"


def test_chart_crds_match_config_bases():
    """The chart's crds/ dir must stay identical to config/operator/crd/bases."""
    for name in ("nos.ai_elasticquotas.yaml", "nos.ai_compositeelasticquotas.yaml"):
        with open(os.path.join(CHART, "crds", name)) as f:
            chart_crd = f.read()
        with open(os.path.join(CONFIG, "operator", "crd", "bases", name)) as f:
            base_crd = f.read()
        assert chart_crd == base_crd, f"{name}: chart copy diverged"


def test_crd_schemas_valid():
    for name, kind in (
        ("nos.ai_elasticquotas.yaml", "ElasticQuota"),
        ("nos.ai_compositeelasticquotas.yaml", "CompositeElasticQuota"),
    ):
        with open(os.path.join(CONFIG, "operator", "crd", "bases", name)) as f:
            crd = yaml.safe_load(f)
        assert crd["kind"] == "CustomResourceDefinition"
        assert crd["spec"]["group"] == "nos.ai"
        assert crd["spec"]["names"]["kind"] == kind
        v = crd["spec"]["versions"][0]
        assert v["name"] == "v1alpha1" and v["served"] and v["storage"]
        props = v["schema"]["openAPIV3Schema"]["properties"]
        assert "spec" in props and "status" in props
        assert "used" in props["status"]["properties"]


# ---------------------------------------------------------------------------
# config/ kustomize mirrors — plain YAML, deep-checked
# ---------------------------------------------------------------------------
def _manifests():
    out = []
    for path in sorted(glob.glob(os.path.join(CONFIG, "**", "*.yaml"),
                                 recursive=True)):
        with open(path) as f:
            for doc in yaml.safe_load_all(f):
                if doc:
                    out.append((path, doc))
    return out


def test_config_manifests_parse_and_have_kind():
    docs = _manifests()
    assert len(docs) >= 15
    for path, doc in docs:
        assert "kind" in doc, f"{path}: document without kind"
        if doc["kind"] != "Kustomization":
            assert doc.get("metadata", {}).get("name"), f"{path}: unnamed object"


def test_config_embedded_component_configs_load():
    """The YAML embedded in each config/ ConfigMap must round-trip through
    the actual component config dataclass (catches key drift)."""
    kinds = {
        "operator-config.yaml": configs.OperatorConfig,
        "scheduler-config.yaml": configs.CapacitySchedulingArgs,
        "partitioner-config.yaml": configs.PartitionerConfig,
        "tpuagent-config.yaml": configs.TpuAgentConfig,
    }
    seen = set()
    for path, doc in _manifests():
        if doc["kind"] != "ConfigMap":
            continue
        for key, payload in (doc.get("data") or {}).items():
            if key not in kinds:
                continue
            seen.add(key)
            data = yaml.safe_load(payload)
            cfg = kinds[key](**data)
            cfg.validate()
    assert seen == set(kinds), f"config maps missing for {set(kinds) - seen}"


def test_config_rbac_covers_each_serviceaccount():
    sas, bindings = set(), set()
    for _, doc in _manifests():
        if doc["kind"] == "ServiceAccount":
            sas.add(doc["metadata"]["name"])
        if doc["kind"] == "ClusterRoleBinding":
            for s in doc.get("subjects", []):
                bindings.add(s["name"])
    assert sas, "no ServiceAccounts in config/"
    assert sas <= bindings, f"ServiceAccounts without bindings: {sas - bindings}"


def test_kustomization_resources_exist():
    for path in sorted(glob.glob(os.path.join(CONFIG, "**", "kustomization.yaml"),
                                 recursive=True)):
        with open(path) as f:
            kust = yaml.safe_load(f)
        base = os.path.dirname(path)
        for res in kust.get("resources", []):
            assert os.path.exists(os.path.join(base, res)), f"{path}: {res} missing"


def test_samples_valid():
    path = os.path.join(CONFIG, "operator", "samples", "gang-jobset.yaml")
    with open(path) as f:
        pod = yaml.safe_load(f)
    labels = pod["metadata"]["labels"]
    assert labels["nos.ai/gang-name"]
    assert int(labels["nos.ai/gang-size"]) == 4
    assert pod["metadata"]["annotations"]["nos.ai/tpu-topology"] == "4x4"
    assert pod["spec"]["schedulerName"] == "nos-scheduler"


# ---------------------------------------------------------------------------
# build/ + hack/
# ---------------------------------------------------------------------------
def test_dockerfiles_exist_per_component():
    for c in ("apiserver", "operator", "scheduler", "partitioner", "tpuagent",
              "metricsexporter", "trainer", "server"):
        path = os.path.join(REPO, "build", c, "Dockerfile")
        assert os.path.exists(path), f"missing {path}"
        with open(path) as f:
            text = f.read()
        assert "FROM" in text and "ENTRYPOINT" in text
    with open(os.path.join(REPO, "build", "tpuagent", "Dockerfile")) as f:
        agent = f.read()
    assert "native/tpuagent" in agent, "tpuagent image must build the C++ layer"


def test_kind_cluster_config():
    with open(os.path.join(REPO, "hack", "kind", "cluster.yaml")) as f:
        cluster = yaml.safe_load(f)
    roles = [n["role"] for n in cluster["nodes"]]
    assert roles.count("worker") >= 2, "need >=2 workers for multi-node tests"


def test_console_scripts_resolve():
    """Every [project.scripts] entry points at an importable main()."""
    import importlib
    try:
        import tomllib
    except ImportError:  # pragma: no cover - py<3.11
        pytest.skip("tomllib unavailable")
    with open(os.path.join(REPO, "pyproject.toml"), "rb") as f:
        proj = tomllib.load(f)
    for name, target in proj["project"]["scripts"].items():
        mod_name, func = target.split(":")
        mod = importlib.import_module(mod_name)
        assert callable(getattr(mod, func)), f"{name}: {target} not callable"


# ---------------------------------------------------------------------------
# demos/tpu-sharing-comparison manifests
# ---------------------------------------------------------------------------

DEMO = os.path.join(REPO, "demos", "tpu-sharing-comparison")


def test_demo_manifests_parse_and_cover_all_modes():
    for mode in ("multiplex", "timeslice", "subslice"):
        overlay = os.path.join(DEMO, "manifests", "overlays", mode)
        for name in ("kustomization.yaml", "patch.yaml"):
            with open(os.path.join(overlay, name)) as f:
                assert yaml.safe_load(f)
    base = os.path.join(DEMO, "manifests", "base")
    docs = []
    for path in sorted(glob.glob(os.path.join(base, "*.yaml"))):
        with open(path) as f:
            docs.extend(d for d in yaml.safe_load_all(f) if d)
    kinds = {d["kind"] for d in docs}
    assert {"Namespace", "Deployment", "PodMonitor", "Kustomization"} <= kinds


def test_demo_subslice_overlay_requests_partition_resource():
    with open(os.path.join(DEMO, "manifests", "overlays", "subslice",
                           "patch.yaml")) as f:
        patch = yaml.safe_load(f)
    limits = patch["spec"]["template"]["spec"]["containers"][0]["resources"]["limits"]
    assert any(k.startswith("nos.ai/tpu-slice-") for k in limits)


def test_kind_e2e_script_runs_or_skips():
    """hack/kind/run-e2e.sh is the scripted real-apiserver runbook
    (VERDICT r2 next #10). Exit 2 = environment can't run it (no kind /
    no container runtime) -> skip; 0 = the full stack bound a pod against
    a real kube-apiserver; anything else is a genuine failure."""
    import subprocess

    script = os.path.join(REPO, "hack", "kind", "run-e2e.sh")
    assert os.access(script, os.X_OK)
    proc = subprocess.run(["bash", script], capture_output=True, text=True,
                          timeout=600)
    if proc.returncode == 2:
        pytest.skip(f"kind e2e unavailable: {proc.stdout.strip()[-100:]}")
    assert proc.returncode == 0, proc.stdout[-2000:] + proc.stderr[-2000:]


def test_serving_deployment_passes_slo_and_telemetry_args():
    """The serving Deployment template must plumb the SLO + device-
    telemetry knobs from values.yaml to nos-tpu-server flags (the flags
    exist on the binary — drift between template and parser fails the
    server's own tests; this pins the template side)."""
    path = os.path.join(CHART, "templates", "serving",
                        "deployment_server.yaml")
    with open(path) as f:
        text = f.read()
    for flag, value in (
        ("--slo-ttft-ms", ".Values.serving.slo.ttftMs"),
        ("--slo-tpot-ms", ".Values.serving.slo.tpotMs"),
        ("--slo-fast-window-s", ".Values.serving.slo.fastWindowSeconds"),
        ("--slo-slow-window-s", ".Values.serving.slo.slowWindowSeconds"),
        ("--slo-burn-threshold", ".Values.serving.slo.burnThreshold"),
        ("--slo-capture-interval-s",
         ".Values.serving.slo.captureIntervalSeconds"),
        ("--device-stats-interval",
         ".Values.serving.deviceStatsIntervalSeconds"),
    ):
        assert flag in text, f"serving deployment missing {flag}"
        assert value in text, f"serving deployment missing {value}"
    with open(os.path.join(CHART, "values.yaml")) as f:
        values = yaml.safe_load(f)
    # error-budget window defaults must match the binary's flag
    # defaults — drift makes fleet burn rates replica-dependent
    assert values["serving"]["slo"] == {
        "ttftMs": 0, "tpotMs": 0, "fastWindowSeconds": 300,
        "slowWindowSeconds": 3600, "burnThreshold": 14.4,
        "captureIntervalSeconds": 300}
    assert values["serving"]["deviceStatsIntervalSeconds"] == 10


def test_serving_deployment_passes_paged_kv_args():
    """The serving Deployment must plumb the paged-KV knobs
    (serving.kv.*) to nos-tpu-server flags, and the chart defaults must
    ship paging OFF (slot-static) with swap-mode preemption selected
    for whoever turns it on."""
    path = os.path.join(CHART, "templates", "serving",
                        "deployment_server.yaml")
    with open(path) as f:
        text = f.read()
    for flag, value in (
        ("--kv-block-size", ".Values.serving.kv.blockSize"),
        ("--kv-blocks", ".Values.serving.kv.blocks"),
        ("--kv-swap", ".Values.serving.kv.swap"),
    ):
        assert flag in text, f"serving deployment missing {flag}"
        assert value in text, f"serving deployment missing {value}"
    # the flag takes on|off, not a raw boolean
    assert 'ternary "on" "off"' in text
    with open(os.path.join(CHART, "values.yaml")) as f:
        values = yaml.safe_load(f)
    assert values["serving"]["kv"] == {
        "blockSize": 0, "blocks": 0, "swap": True, "dtype": "bf16",
        "pagedKernel": True, "hostTierBytes": 0, "fabricToken": ""}


def test_serving_deployment_passes_kv_dtype_and_speculative_args():
    """The serving Deployment must plumb serving.kv.dtype and the
    serving.speculative.* block to nos-tpu-server flags (ISSUE 10
    satellite — no dead knobs: every value lands in a flag the server
    validates). Defaults ship bf16 KV and speculation OFF; the
    speculative flags render only when a draft checkpoint is set, so a
    plain deployment's args stay clean."""
    path = os.path.join(CHART, "templates", "serving",
                        "deployment_server.yaml")
    with open(path) as f:
        text = f.read()
    for flag, value in (
        ("--kv-dtype", ".Values.serving.kv.dtype"),
        ("--draft-checkpoint-dir",
         ".Values.serving.speculative.draftCheckpointDir"),
        ("--draft-n-tokens", ".Values.serving.speculative.nTokens"),
    ):
        assert flag in text, f"serving deployment missing {flag}"
        assert value in text, f"serving deployment missing {value}"
    # speculative args are conditional on the draft checkpoint
    assert "if .Values.serving.speculative.draftCheckpointDir" in text
    with open(os.path.join(CHART, "values.yaml")) as f:
        values = yaml.safe_load(f)
    assert values["serving"]["kv"]["dtype"] == "bf16"
    assert values["serving"]["speculative"] == {
        "draftCheckpointDir": "", "nTokens": 4}
    # README documents every new knob (the rows are the operator's
    # discovery surface; an undocumented knob is half-dead)
    with open(os.path.join(CHART, "README.md")) as f:
        readme = f.read()
    for row in ("serving.kv.dtype", "serving.speculative.draftCheckpointDir",
                "serving.speculative.nTokens"):
        assert row in readme, f"helm README missing {row} row"


def test_serving_deployment_passes_paged_kernel_arg():
    """The serving Deployment must plumb serving.kv.pagedKernel to
    --paged-kernel=on|off (the fused Pallas decode-attention kernel's
    fleet knob), with the chart default matching the binary's
    ServerConfig default (ON since the ISSUE 16 spec-grid parity
    burn-in — the XLA gather formulation stays the documented
    --paged-kernel=off escape hatch and parity oracle), and a README
    row so the knob is discoverable."""
    path = os.path.join(CHART, "templates", "serving",
                        "deployment_server.yaml")
    with open(path) as f:
        text = f.read()
    assert "--paged-kernel=" in text, "serving deployment missing flag"
    assert 'ternary "on" "off" .Values.serving.kv.pagedKernel' in text
    with open(os.path.join(CHART, "values.yaml")) as f:
        values = yaml.safe_load(f)
    assert values["serving"]["kv"]["pagedKernel"] is True
    # chart default == code default (rendered through the ternary)
    from nos_tpu.cmd.server import ServerConfig
    rendered = "on" if values["serving"]["kv"]["pagedKernel"] else "off"
    assert rendered == ServerConfig().paged_kernel
    with open(os.path.join(CHART, "README.md")) as f:
        readme = f.read()
    assert "serving.kv.pagedKernel" in readme, "helm README missing row"


def test_kv_fabric_knobs_reach_flags_with_code_defaults():
    """The tiered KV-fabric knobs (ISSUE 17) must land in flags on both
    planes — serving.kv.hostTierBytes -> --kv-host-tier-bytes on the
    server, gateway.fabric.enabled/maxBlocks -> --kv-fabric=on|off /
    --kv-fabric-max-blocks on the gateway — with chart defaults equal
    to the code defaults (fabric OFF, host tier 0 bytes: the escape
    hatch is the default) and README rows for discoverability."""
    spath = os.path.join(CHART, "templates", "serving",
                         "deployment_server.yaml")
    with open(spath) as f:
        stext = f.read()
    assert "--kv-host-tier-bytes=" in stext, "serving missing flag"
    assert ".Values.serving.kv.hostTierBytes" in stext
    # the fleet fabric secret renders only when set (no empty-string
    # flag noise) on BOTH planes
    assert "--kv-fabric-token=" in stext
    assert "{{- if .Values.serving.kv.fabricToken }}" in stext

    gpath = os.path.join(CHART, "templates", "gateway",
                         "deployment_gateway.yaml")
    with open(gpath) as f:
        gtext = f.read()
    assert "--kv-fabric=" in gtext, "gateway missing --kv-fabric"
    assert 'ternary "on" "off" .Values.gateway.fabric.enabled' in gtext
    assert "--kv-fabric-max-blocks=" in gtext
    assert ".Values.gateway.fabric.maxBlocks" in gtext
    assert "--kv-fabric-token=" in gtext
    assert "{{- if .Values.gateway.fabric.token }}" in gtext

    with open(os.path.join(CHART, "values.yaml")) as f:
        values = yaml.safe_load(f)
    assert values["serving"]["kv"]["hostTierBytes"] == 0
    assert values["serving"]["kv"]["fabricToken"] == ""
    assert values["gateway"]["fabric"] == {"enabled": False,
                                           "maxBlocks": 32,
                                           "token": ""}
    from nos_tpu.cmd.server import ServerConfig

    assert ServerConfig().kv_host_tier_bytes == \
        values["serving"]["kv"]["hostTierBytes"]
    from nos_tpu.gateway.router import RouterConfig

    rendered = "on" if values["gateway"]["fabric"]["enabled"] else "off"
    assert (RouterConfig().fabric is True) == (rendered == "on")
    assert RouterConfig().fabric_max_blocks == \
        values["gateway"]["fabric"]["maxBlocks"]

    with open(os.path.join(CHART, "README.md")) as f:
        readme = f.read()
    for row in ("serving.kv.hostTierBytes", "serving.kv.fabricToken",
                "gateway.fabric.enabled", "gateway.fabric.maxBlocks",
                "gateway.fabric.token"):
        assert row in readme, f"helm README missing {row} row"


def test_serving_deployment_passes_supervisor_and_deadline_args():
    """The serving Deployment must plumb the self-healing knobs
    (serving.supervisor.*, serving.deadline.*) to nos-tpu-server flags
    (ISSUE 7 satellite), and the chart defaults must ship supervised
    restarts ON (budget 2) with the watchdog and default deadline off —
    self-healing by default, no behavior change for latency contracts."""
    path = os.path.join(CHART, "templates", "serving",
                        "deployment_server.yaml")
    with open(path) as f:
        text = f.read()
    for flag, value in (
        ("--restart-budget", ".Values.serving.supervisor.restartBudget"),
        ("--watchdog-s", ".Values.serving.supervisor.watchdogSeconds"),
        ("--default-deadline-s",
         ".Values.serving.deadline.defaultSeconds"),
    ):
        assert flag in text, f"serving deployment missing {flag}"
        assert value in text, f"serving deployment missing {value}"
    with open(os.path.join(CHART, "values.yaml")) as f:
        values = yaml.safe_load(f)
    assert values["serving"]["supervisor"] == {
        "restartBudget": 2, "watchdogSeconds": 0}
    assert values["serving"]["deadline"] == {"defaultSeconds": 0}


def test_serving_sample_valid():
    """The serving Deployment sample must parse, and its embedded config
    must construct a real ServerConfig (drift between the sample and the
    binary's schema fails here)."""
    import yaml

    from nos_tpu.cmd.server import ServerConfig

    path = os.path.join(CONFIG, "operator", "samples",
                        "serving-deployment.yaml")
    with open(path) as f:
        docs = list(yaml.safe_load_all(f))
    dep, cm = docs
    assert dep["kind"] == "Deployment"
    tmpl = dep["spec"]["template"]["spec"]
    assert tmpl["schedulerName"] == "nos-scheduler"
    ctr = tmpl["containers"][0]
    assert ctr["resources"]["requests"]["nos.ai/tpu-slice-2x2"] == 1
    assert ctr["livenessProbe"]["httpGet"]["path"] == "/healthz"
    cfg = ServerConfig(**yaml.safe_load(cm["data"]["server.yaml"]))
    assert cfg.int8 and cfg.checkpoint_dir == "/ckpt"


def test_fleet_deployment_passes_policy_and_quota_args():
    """The fleet Deployment template (ISSUE 8 satellite) must plumb the
    fleet identity, quota sizing, and every policy knob to nos-tpu-fleet
    flags, and the chart defaults must match the binary's."""
    path = os.path.join(CHART, "templates", "fleet",
                        "deployment_fleet.yaml")
    with open(path) as f:
        text = f.read()
    for flag, value in [
        ("--fleet", ".Values.fleet.fleetName"),
        ("--chips-per-replica", ".Values.fleet.chipsPerReplica"),
        ("--resource", ".Values.fleet.resource"),
        ("--min-replicas", ".Values.fleet.minReplicas"),
        ("--max-replicas", ".Values.fleet.maxReplicas"),
        ("--interval", ".Values.fleet.reconcileIntervalSeconds"),
        ("--drain-timeout", ".Values.fleet.drainTimeoutSeconds"),
        ("--replica-url-template", ".Values.fleet.replicaUrlTemplate"),
        ("--queue-high", ".Values.fleet.policy.queueHigh"),
        ("--queue-low", ".Values.fleet.policy.queueLow"),
        ("--goodput-floor", ".Values.fleet.policy.goodputFloor"),
        ("--goodput-ceiling", ".Values.fleet.policy.goodputCeiling"),
        ("--ttft-p99-high-ms", ".Values.fleet.policy.ttftP99HighMs"),
        ("--oldest-wait-high-s",
         ".Values.fleet.policy.oldestWaitHighSeconds"),
        ("--up-stable", ".Values.fleet.policy.upStableSeconds"),
        ("--down-stable", ".Values.fleet.policy.downStableSeconds"),
        ("--up-cooldown", ".Values.fleet.policy.upCooldownSeconds"),
        ("--down-cooldown", ".Values.fleet.policy.downCooldownSeconds"),
        ("--max-step-up", ".Values.fleet.policy.maxStepUp"),
        ("--max-step-down", ".Values.fleet.policy.maxStepDown"),
    ]:
        assert flag in text, f"fleet deployment missing {flag}"
        assert value in text, f"fleet deployment missing {value}"
    # RBAC exists alongside (pods RW + quotas RO + leases)
    rbac = os.path.join(CHART, "templates", "fleet", "rbac_fleet.yaml")
    with open(rbac) as f:
        rbac_text = f.read()
    assert "elasticquotas" in rbac_text
    assert "delete" in rbac_text
    with open(os.path.join(CHART, "values.yaml")) as f:
        values = yaml.safe_load(f)
    assert values["fleet"]["enabled"] is False
    assert values["fleet"]["chipsPerReplica"] == 4
    assert values["fleet"]["minReplicas"] == 1
    assert values["fleet"]["maxReplicas"] == 8
    assert values["fleet"]["policy"] == {
        "queueHigh": 4, "queueLow": 0.5,
        "goodputFloor": 0.90, "goodputCeiling": 0.98,
        "ttftP99HighMs": 0, "oldestWaitHighSeconds": 0,
        "upStableSeconds": 15, "downStableSeconds": 60,
        "upCooldownSeconds": 30, "downCooldownSeconds": 120,
        "maxStepUp": 2, "maxStepDown": 1,
    }
    # the activator wire: --gateway-url renders only when the value is
    # set (empty default falls back to the ConfigMap annotation), and
    # the fleet may read the gateway's ConfigMap
    assert "--gateway-url={{ .Values.fleet.gatewayUrl }}" in text
    assert "if .Values.fleet.gatewayUrl" in text
    assert values["fleet"]["gatewayUrl"] == ""
    assert "configmaps" in rbac_text


def test_harvest_deployment_passes_gang_and_reclaim_args():
    """The harvest Deployment template (ISSUE 12 satellite) must plumb
    the plane identity, gang geometry, and every reclaim knob to
    nos-tpu-harvest flags, and the chart defaults must match the
    binary's HarvestConfig defaults."""
    from nos_tpu.harvest import HarvestConfig

    path = os.path.join(CHART, "templates", "harvest",
                        "deployment_harvest.yaml")
    with open(path) as f:
        text = f.read()
    for flag, value in [
        ("--name", ".Values.harvest.name"),
        ("--namespace", ".Values.harvest.namespace"),
        ("--resource", ".Values.harvest.resource"),
        ("--gang-size", ".Values.harvest.gangSize"),
        ("--chips-per-worker", ".Values.harvest.chipsPerWorker"),
        ("--topology", ".Values.harvest.topology"),
        ("--max-gangs", ".Values.harvest.maxGangs"),
        ("--checkpoint-budget",
         ".Values.harvest.checkpointBudgetSeconds"),
        ("--checkpoint-interval",
         ".Values.harvest.checkpointIntervalSeconds"),
        ("--launch-stable", ".Values.harvest.launchStableSeconds"),
        ("--interval", ".Values.harvest.reconcileIntervalSeconds"),
        ("--priority", ".Values.harvest.priority"),
        ("--trainer-image", ".Values.harvest.trainerImage"),
    ]:
        assert flag in text, f"harvest deployment missing {flag}"
        assert value in text, f"harvest deployment missing {value}"
    # the witness renders only when shared storage is configured
    assert "--checkpoint-root={{ .Values.harvest.checkpointRoot }}" \
        in text
    assert "if .Values.harvest.checkpointRoot" in text
    # RBAC exists alongside (pods RW — evictions — + quotas RO + leases)
    rbac = os.path.join(CHART, "templates", "harvest",
                        "rbac_harvest.yaml")
    with open(rbac) as f:
        rbac_text = f.read()
    assert "elasticquotas" in rbac_text
    assert "delete" in rbac_text
    with open(os.path.join(CHART, "values.yaml")) as f:
        values = yaml.safe_load(f)
    hv = values["harvest"]
    cfg = HarvestConfig()
    assert hv["enabled"] is False
    assert hv["name"] == cfg.name
    assert hv["namespace"] == cfg.namespace
    assert hv["resource"] == cfg.resource
    assert hv["gangSize"] == cfg.gang_size
    assert hv["chipsPerWorker"] == cfg.chips_per_worker
    assert hv["topology"] == cfg.topology
    assert hv["maxGangs"] == cfg.max_gangs
    assert hv["checkpointBudgetSeconds"] == cfg.checkpoint_budget_s
    assert hv["checkpointIntervalSeconds"] == cfg.checkpoint_interval_s
    assert hv["launchStableSeconds"] == cfg.launch_stable_s
    assert hv["reconcileIntervalSeconds"] == cfg.reconcile_interval_s
    assert hv["priority"] == cfg.priority
    assert hv["trainerImage"] == cfg.image
    assert hv["checkpointRoot"] == ""
    # the scheduler side of the reclaim handshake: the grace knob is
    # plumbed, defaults OFF (pre-harvest behavior), and the budget the
    # chart ships stays inside the window an operator would enable
    sched = os.path.join(CHART, "templates", "scheduler",
                         "deployment_scheduler.yaml")
    with open(sched) as f:
        sched_text = f.read()
    assert "--reclaim-grace-s={{ .Values.scheduler.reclaimGraceSeconds }}" \
        in sched_text
    assert values["scheduler"]["reclaimGraceSeconds"] == 0


def test_gateway_deployment_passes_routing_and_door_args():
    """The gateway Deployment template (ISSUE 11 satellite) must plumb
    the fleet identity, affinity/admission/door/retry knobs to
    nos-tpu-gateway flags, ship a Service in front, and default
    disabled like the fleet controller it pairs with."""
    path = os.path.join(CHART, "templates", "gateway",
                        "deployment_gateway.yaml")
    with open(path) as f:
        text = f.read()
    for flag, value in [
        ("--fleet", ".Values.gateway.fleetName"),
        ("--port", ".Values.gateway.port"),
        ("--replica-url-template", ".Values.gateway.replicaUrlTemplate"),
        ("--discovery-interval",
         ".Values.gateway.discoveryIntervalSeconds"),
        ("--block-size", ".Values.gateway.affinity.blockSize"),
        ("--affinity-blocks", ".Values.gateway.affinity.blocks"),
        ("--max-imbalance", ".Values.gateway.affinity.maxImbalance"),
        ("--admit-pending-per-replica",
         ".Values.gateway.admission.pendingPerReplica"),
        ("--admit-hbm-frac", ".Values.gateway.admission.hbmFrac"),
        ("--max-door-queue", ".Values.gateway.door.maxQueue"),
        ("--door-wait", ".Values.gateway.door.waitSeconds"),
        ("--retry-attempts", ".Values.gateway.retry.attempts"),
        ("--retry-backoff", ".Values.gateway.retry.backoffSeconds"),
        ("--slo-burn-threshold", ".Values.gateway.slo.burnThreshold"),
        ("--harvest-url", ".Values.gateway.slo.harvestUrl"),
    ]:
        assert flag in text, f"gateway deployment missing {flag}"
        assert value in text, f"gateway deployment missing {value}"
    # clients dial the gateway Service, not replica pods
    assert "kind: Service" in text
    rbac = os.path.join(CHART, "templates", "gateway",
                        "rbac_gateway.yaml")
    with open(rbac) as f:
        rbac_text = f.read()
    assert "pods" in rbac_text and "configmaps" in rbac_text
    with open(os.path.join(CHART, "values.yaml")) as f:
        values = yaml.safe_load(f)
    gw = values["gateway"]
    assert gw["enabled"] is False
    assert gw["fleetName"] == values["fleet"]["fleetName"]
    assert gw["replicaUrlTemplate"] == values["fleet"]["replicaUrlTemplate"]
    # chart defaults must match the binary's flag defaults
    assert gw["port"] == 8080
    assert gw["affinity"] == {"blockSize": 16, "blocks": 4,
                              "maxImbalance": 4}
    assert gw["admission"] == {"pendingPerReplica": 0, "hbmFrac": 0}
    assert gw["door"] == {"maxQueue": 256, "waitSeconds": 30}
    assert gw["retry"] == {"attempts": 12, "backoffSeconds": 0.05}
    assert gw["slo"] == {"burnThreshold": 14.4, "harvestUrl": ""}


def test_tenant_quota_args_plumbed_on_both_binaries():
    """ISSUE 13 satellite: serving.tenants.* and gateway.tenants.*
    must plumb --tenant-config (conditionally: an empty config renders
    NO flag, keeping tenancy off by default) on both deployments, the
    chart defaults must equal the code defaults, and the README must
    document the rows."""
    import yaml

    spath = os.path.join(CHART, "templates", "serving",
                         "deployment_server.yaml")
    with open(spath) as f:
        stext = f.read()
    assert "--tenant-config" in stext, "serving missing --tenant-config"
    assert ".Values.serving.tenants.config" in stext
    assert "if .Values.serving.tenants.config" in stext, \
        "serving --tenant-config must render only when set"

    gpath = os.path.join(CHART, "templates", "gateway",
                         "deployment_gateway.yaml")
    with open(gpath) as f:
        gtext = f.read()
    assert "--tenant-config" in gtext, "gateway missing --tenant-config"
    assert ".Values.gateway.tenants.config" in gtext
    assert "if .Values.gateway.tenants.config" in gtext
    assert "--tenant-quota-attempts" in gtext
    assert ".Values.gateway.tenants.quotaAttempts" in gtext

    with open(os.path.join(CHART, "values.yaml")) as f:
        values = yaml.safe_load(f)
    # chart defaults == code defaults: tenancy OFF out of the box
    assert values["serving"]["tenants"] == {"config": ""}
    assert values["gateway"]["tenants"] == {"config": "",
                                            "quotaAttempts": 2}
    from nos_tpu.cmd.server import ServerConfig

    assert ServerConfig().tenant_config == ""
    from nos_tpu.gateway.router import RouterConfig

    assert RouterConfig().tenant_config is None
    assert RouterConfig().tenant_quota_attempts == \
        values["gateway"]["tenants"]["quotaAttempts"]

    with open(os.path.join(CHART, "README.md")) as f:
        readme = f.read()
    for row in ("serving.tenants.config", "gateway.tenants.config",
                "gateway.tenants.quotaAttempts"):
        assert row in readme, f"helm README missing {row} row"


def test_serving_deployment_passes_role_and_decode_pool_args():
    """The serving Deployment must plumb serving.role / serving.decodePool
    to --role/--decode-pool (ISSUE 15 satellite: prefill/decode
    disaggregation), chart defaults must match the binary's
    ServerConfig defaults, and the knobs must be README-discoverable."""
    path = os.path.join(CHART, "templates", "serving",
                        "deployment_server.yaml")
    with open(path) as f:
        text = f.read()
    assert "--role={{ .Values.serving.role }}" in text
    assert "--decode-pool={{ .Values.serving.decodePool }}" in text
    # decode-pool only renders when set: an empty --decode-pool flag
    # would be a dead arg on every colocated fleet
    assert "if .Values.serving.decodePool" in text
    with open(os.path.join(CHART, "values.yaml")) as f:
        values = yaml.safe_load(f)
    from nos_tpu.cmd.server import ServerConfig
    assert values["serving"]["role"] == ServerConfig().role == "colocated"
    assert values["serving"]["decodePool"] == ServerConfig().decode_pool \
        == ""
    with open(os.path.join(CHART, "README.md")) as f:
        readme = f.read()
    for row in ("serving.role", "serving.decodePool"):
        assert row in readme, f"helm README missing {row}"


def test_serving_deployment_passes_prefill_budget_and_health_args():
    """The serving Deployment must plumb the stall-free colocated
    serving knobs (ISSUE 19): serving.prefillChunk / .prefillBudget /
    .handoffHealthIntervalSeconds rendered to --prefill-chunk /
    --prefill-budget / --handoff-health-interval-s, chart defaults
    equal to the binary's ServerConfig defaults (all off — no behavior
    change on upgrade), and the knobs README-discoverable."""
    path = os.path.join(CHART, "templates", "serving",
                        "deployment_server.yaml")
    with open(path) as f:
        text = f.read()
    for flag, value in (
        ("--prefill-chunk", ".Values.serving.prefillChunk"),
        ("--prefill-budget", ".Values.serving.prefillBudget"),
        ("--handoff-health-interval-s",
         ".Values.serving.handoffHealthIntervalSeconds"),
    ):
        assert f"{flag}={{{{ {value} }}}}" in text, (
            f"serving deployment missing {flag} <- {value}")
    with open(os.path.join(CHART, "values.yaml")) as f:
        values = yaml.safe_load(f)
    from nos_tpu.cmd.server import ServerConfig
    assert values["serving"]["prefillChunk"] \
        == ServerConfig().prefill_chunk == 0
    assert values["serving"]["prefillBudget"] \
        == ServerConfig().prefill_budget == 0
    assert values["serving"]["handoffHealthIntervalSeconds"] \
        == ServerConfig().handoff_health_interval_s == 0
    with open(os.path.join(CHART, "README.md")) as f:
        readme = f.read()
    for row in ("serving.prefillChunk", "serving.prefillBudget",
                "serving.handoffHealthIntervalSeconds"):
        assert row in readme, f"helm README missing {row} row"
