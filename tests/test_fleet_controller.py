"""FleetController unit tests (ISSUE 8 tentpole): observe / decide /
clamp / actuate over the in-process API server with injected stats and
a fake clock — no scheduler, no jax.
"""
import pytest

from nos_tpu import constants
from nos_tpu.api.quota import make_elastic_quota
from nos_tpu.fleet import FleetConfig, FleetController, PolicyConfig
from nos_tpu.kube import ApiServer, Manager
from nos_tpu.kube.client import Client
from nos_tpu.kube.objects import (
    Container, ObjectMeta, Pod, PodSpec, PodStatus,
)


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


FAST_POLICY = PolicyConfig(
    min_replicas=1, max_replicas=5,
    queue_high=4.0, queue_low=0.5,
    up_stable_s=2.0, down_stable_s=2.0,
    up_cooldown_s=5.0, down_cooldown_s=1.0,
    max_step_up=2, max_step_down=1,
)


def busy(depth=40, goodput=None, uptime=100.0, config=None):
    return {
        "healthy": True, "uptime_s": uptime, "active_slots": 8,
        "pending": {"depth": depth, "oldest_wait_s": 1.0},
        "slo": {"goodput": goodput,
                "completed": 10 if goodput is not None else 0},
        "per_request": {}, "config": config or {},
    }


def idle(uptime=100.0, active=0, depth=0):
    return {
        "healthy": True, "uptime_s": uptime, "active_slots": active,
        "pending": {"depth": depth, "oldest_wait_s": 0.0},
        "slo": {"goodput": None, "completed": 0},
        "per_request": {}, "config": {},
    }


@pytest.fixture
def rig():
    server = ApiServer()
    clock = FakeClock()
    mgr = Manager(server, clock=clock)
    stats = {}
    drained = []
    ctl = FleetController(
        FleetConfig(name="f", namespace="serve",
                    chips_per_replica=4.0, policy=FAST_POLICY,
                    reconcile_interval_s=1.0, drain_timeout_s=10.0),
        stats_source=lambda pod: stats.get(pod.metadata.name),
        drain_hook=lambda pod: drained.append(pod.metadata.name),
        clock=clock)
    mgr.add_controller(ctl.controller())
    return server, mgr, clock, ctl, stats, drained


def fleet_pods(server, name="f"):
    return sorted(
        (p for p in server.list("Pod", namespace="serve")
         if p.metadata.labels.get(constants.LABEL_FLEET) == name),
        key=lambda p: p.metadata.name)


def pump(mgr, clock, seconds, dt=1.0):
    t = 0.0
    while t < seconds:
        mgr.run_until_idle()
        clock.advance(dt)
        t += dt
    mgr.run_until_idle()


def mark_running(server, stats, snap=None):
    for p in fleet_pods(server):
        if p.status.phase != "Running":
            server.patch("Pod", p.metadata.name, "serve",
                         lambda o: setattr(o.status, "phase", "Running"))
        if snap is not None:
            stats[p.metadata.name] = snap


# ---------------------------------------------------------------------------
def test_bootstrap_creates_min_replicas(rig):
    server, mgr, clock, ctl, stats, _ = rig
    mgr.run_until_idle()
    pods = fleet_pods(server)
    assert len(pods) == 1
    assert pods[0].spec.scheduler_name == constants.SCHEDULER_NAME
    assert pods[0].request() == {constants.RESOURCE_TPU: 4.0}
    # replica pods enter Pending-unschedulable so the nos scheduler
    # picks them up like any workload pod
    assert pods[0].is_unschedulable()


def test_sustained_queue_pressure_scales_up_with_step_limit(rig):
    server, mgr, clock, ctl, stats, _ = rig
    mgr.run_until_idle()
    mark_running(server, stats, busy(depth=40))
    pump(mgr, clock, 4)
    pods = fleet_pods(server)
    assert len(pods) == 1 + FAST_POLICY.max_step_up  # one step, capped
    # starting (not Running) pods count toward current: no runaway
    # step while the first batch provisions
    pump(mgr, clock, 1)
    assert len(fleet_pods(server)) == len(pods)
    snap = ctl.stats()
    assert snap["replicas"]["starting"] == FAST_POLICY.max_step_up


def test_scale_down_drains_youngest_then_releases_when_idle(rig):
    server, mgr, clock, ctl, stats, drained = rig
    mgr.run_until_idle()
    mark_running(server, stats, busy(depth=40))
    pump(mgr, clock, 4)
    mark_running(server, stats, busy(depth=40))
    pump(mgr, clock, 2)
    names = [p.metadata.name for p in fleet_pods(server)]
    assert len(names) == 3
    # everything goes quiet: fleet shrinks one step per decision
    for n in names:
        stats[n] = idle()
    pump(mgr, clock, 4)
    left = [p.metadata.name for p in fleet_pods(server)]
    assert len(left) == 2
    gone = set(names) - set(left)
    assert gone == {max(names)}         # youngest victim first
    assert list(gone)[0] in drained     # drain hook (stop admitting)


def test_draining_replica_with_work_waits_then_times_out(rig):
    server, mgr, clock, ctl, stats, drained = rig
    mgr.run_until_idle()
    mark_running(server, stats, busy(depth=40))
    pump(mgr, clock, 4)
    mark_running(server, stats, busy(depth=40))
    pump(mgr, clock, 2)
    names = [p.metadata.name for p in fleet_pods(server)]
    # quiet signals but the youngest replica still has in-flight work
    for n in names:
        stats[n] = idle()
    stats[max(names)] = idle(active=2, depth=1)
    pump(mgr, clock, 3)
    pods = {p.metadata.name: p for p in fleet_pods(server)}
    assert max(names) in pods           # not released: work in flight
    assert pods[max(names)].metadata.annotations.get(
        constants.ANNOTATION_FLEET_DRAIN)
    assert ctl.stats()["replicas"]["draining"] == 1
    # drain budget (10s) expires: released anyway — the server's own
    # SIGTERM drain and the supervisor capture own the tail
    pump(mgr, clock, 11)
    assert max(names) not in {p.metadata.name
                              for p in fleet_pods(server)}


def test_quota_clamps_scale_up_to_admissible_chips(rig):
    server, mgr, clock, ctl, stats, _ = rig
    # Σmin = 8 chips -> at 4 chips/replica only 2 replicas are ever
    # admissible, however hard the queue pushes
    server.create(make_elastic_quota(
        "serve-q", "serve", min={constants.RESOURCE_TPU: 8.0}))
    mgr.run_until_idle()
    mark_running(server, stats, busy(depth=80))
    pump(mgr, clock, 10)
    mark_running(server, stats, busy(depth=80))
    pump(mgr, clock, 10)
    assert len(fleet_pods(server)) == 2
    assert ctl.stats()["quota"]["slack_chips"] == 0.0


def test_guaranteed_reclaim_sheds_borrowed_replicas_first(rig):
    server, mgr, clock, ctl, stats, drained = rig
    server.create(make_elastic_quota(
        "serve-q", "serve", min={constants.RESOURCE_TPU: 4.0}))
    server.create(make_elastic_quota(
        "batch-q", "batch", min={constants.RESOURCE_TPU: 8.0}))
    mgr.run_until_idle()
    mark_running(server, stats, busy(depth=80))
    pump(mgr, clock, 10)        # borrows batch's idle min: 3 replicas
    pods = fleet_pods(server)
    mark_running(server, stats, busy(depth=80))
    pump(mgr, clock, 2)
    pods = fleet_pods(server)
    assert len(pods) == 3
    # mark the two youngest as over-quota (the quota reconciler's
    # labeling job) so the reclaim path has its victims
    for p in sorted(pods, key=lambda p: p.metadata.name)[-2:]:
        server.patch("Pod", p.metadata.name, "serve",
                     lambda o: o.metadata.labels.update(
                         {constants.LABEL_CAPACITY:
                          constants.CAPACITY_OVER_QUOTA}))
    # a guaranteed namespace's pod goes Pending-unschedulable: the
    # borrow must be returned
    server.create(Pod(
        metadata=ObjectMeta(name="train-0", namespace="batch"),
        spec=PodSpec(containers=[Container(
            requests={constants.RESOURCE_TPU: 8.0})]),
        status=PodStatus(phase="Pending")))
    server.patch("Pod", "train-0", "batch",
                 lambda o: o.status.conditions.append(
                     __import__("nos_tpu.kube.objects",
                                fromlist=["PodCondition"]).PodCondition(
                         type="PodScheduled", status="False",
                         reason="Unschedulable")))
    for p in fleet_pods(server):
        stats[p.metadata.name] = idle()     # drains release instantly
    pump(mgr, clock, 3)
    left = fleet_pods(server)
    assert len(left) == 1
    # the guaranteed replica survived; the borrowed ones were drained
    assert all(p.metadata.labels.get(constants.LABEL_CAPACITY)
               != constants.CAPACITY_OVER_QUOTA for p in left)
    assert len(drained) >= 2


def test_restarted_replica_not_misread_and_drift_reported(rig):
    server, mgr, clock, ctl, stats, _ = rig
    mgr.run_until_idle()
    mark_running(server, stats, busy(depth=40))
    pump(mgr, clock, 4)
    names = sorted(p.metadata.name for p in fleet_pods(server))
    ref_cfg = {"pipeline_depth": 2, "decode_steps": 1, "kv_blocks": 64}
    stats[names[0]] = busy(depth=0, goodput=1.0, uptime=500.0,
                           config=ref_cfg)
    for n in names[1:]:
        server.patch("Pod", n, "serve",
                     lambda o: setattr(o.status, "phase", "Running"))
        stats[n] = busy(depth=6, goodput=1.0, uptime=500.0,
                        config=ref_cfg)
    pump(mgr, clock, 1)
    # one replica restarts (uptime regresses) and comes back with
    # drifted knobs and an empty ledger
    stats[names[1]] = dict(busy(depth=6, uptime=1.0,
                                config={"pipeline_depth": 1}),
                           slo={"goodput": 0.0, "completed": 0})
    pump(mgr, clock, 1)
    snap = ctl.stats()
    assert snap["signals"]["restarted_replicas"] == 1
    # the fresh process's empty ledger did not crater fleet goodput
    assert snap["signals"]["goodput"] == 1.0
    assert snap["config_drift_replicas"] >= 1


def test_stats_snapshot_shape(rig):
    server, mgr, clock, ctl, stats, _ = rig
    mgr.run_until_idle()
    snap = ctl.stats()
    assert snap["fleet"] == "f"
    assert set(snap["replicas"]) == {"desired", "ready", "starting",
                                     "draining"}
    assert "pending_per_replica" in snap["signals"]
    assert "direction" in snap["decision"]


def test_fleet_binary_build_over_http():
    """The nos-tpu-fleet binary's manager wiring over the real HTTP
    apiserver: bootstrap creates min_replicas through the remote
    client, and the manager exposes the controller's /stats snapshot
    for the HealthServer route."""
    from nos_tpu.cmd import apiserver as cmd_apiserver
    from nos_tpu.cmd import fleet as cmd_fleet
    from nos_tpu.kube.httpapi import RemoteApiServer

    http = cmd_apiserver.build(port=0).start()
    try:
        mgr = cmd_fleet.build(
            RemoteApiServer(http.address),
            FleetConfig(name="web", namespace="serve",
                        chips_per_replica=4.0, policy=FAST_POLICY),
            leader_election=False)
        mgr.run_until_idle()
        client = RemoteApiServer(http.address)
        pods = [p for p in client.list("Pod", namespace="serve")
                if p.metadata.labels.get(constants.LABEL_FLEET) == "web"]
        assert len(pods) == 1
        snap = mgr.stats()
        assert snap["fleet"] == "web"
        assert snap["replicas"]["desired"] == 1
    finally:
        http.stop()


def test_http_replica_client_scrape_and_drain():
    """HttpReplicaClient against a real nos-tpu-server HTTP surface
    (jax-free stub engine), addressed by POD IP (the default template
    — a draining pod leaves Service DNS but keeps its IP): /stats
    scrape parses, replicas without an IP yet and unreachable replicas
    read as None, and drain() flips the replica to draining."""
    import threading

    from test_httpapi import _MillEngine

    from nos_tpu.cmd import fleet as cmd_fleet
    from nos_tpu.cmd.server import ServerConfig, ServingLoop, \
        make_http_server
    from nos_tpu.kube.objects import PodStatus

    loop = ServingLoop(_MillEngine(), config_echo={"max_batch": 8})
    httpd = make_http_server(ServerConfig(port=0), loop)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    port = httpd.server_address[1]
    client = cmd_fleet.HttpReplicaClient("http://{ip}:%d" % port)
    # no IP yet (pod not started): None, no network attempt
    unstarted = Pod(metadata=ObjectMeta(name="web-r1", namespace="serve"))
    assert client.stats(unstarted) is None
    client.drain(unstarted)     # no-op, never raises
    pod = Pod(metadata=ObjectMeta(name="web-r1", namespace="serve"),
              status=PodStatus(phase="Running", pod_ip="127.0.0.1"))
    try:
        snap = client.stats(pod)
        assert snap["config"] == {"max_batch": 8}
        assert snap["uptime_s"] >= 0
        client.drain(pod)
        assert client.stats(pod)["draining"] is True
    finally:
        httpd.shutdown()
        loop.shutdown()
        httpd.server_close()
    # dead replica: None, never an exception
    assert client.stats(pod) is None
