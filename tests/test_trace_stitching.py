"""Cross-process trace stitching (ISSUE 18): ONE trace_id per request
across the disaggregated fleet. The gateway roots the journey
(gateway.request) with each retry a sibling gateway.attempt child; the
winning attempt's context rides the wire as a `traceparent` header so
the replica's serve.request adopts the trace; the prefill->decode
handoff carries it on the same JSON meta plane as deadline_s; and the
KV-fabric peer pull files kvfabric.pull / kvfabric.serve spans into the
same journey. All jax-free: stub engines behind the REAL HTTP surfaces.
"""
import contextlib
import json
import threading
import urllib.request

from nos_tpu.cmd.server import ServerConfig, ServingLoop, make_http_server
from nos_tpu.gateway import (
    GatewayRouter, Replica, ReplicaUnreachable, RouterConfig,
)
from nos_tpu.kvfabric.codec import FABRIC_TOKEN_HEADER
from nos_tpu.obs import tracing
from nos_tpu.obs.tracing import FlightRecorder, SpanContext


@contextlib.contextmanager
def fresh_recorder():
    """Scope the module-level tracer to a private recorder so
    assertions see exactly this test's spans."""
    rec = FlightRecorder()
    old = tracing._default_tracer.recorder
    tracing._default_tracer.recorder = rec
    try:
        yield rec
    finally:
        tracing._default_tracer.recorder = old


def one_trace(rec, name):
    """The single trace containing a span called ``name``."""
    hits = [tid for tid in rec.trace_ids()
            if any(sp.name == name for sp in rec.trace(tid))]
    assert len(hits) == 1, f"expected one {name} trace, got {hits}"
    return rec.trace(hits[0])


def by_name(spans, name):
    out = [sp for sp in spans if sp.name == name]
    assert len(out) == 1, f"expected one {name}, got {len(out)}"
    return out[0]


def assert_no_orphans(spans):
    ids = {sp.span_id for sp in spans}
    roots = [sp for sp in spans if sp.parent_id is None]
    assert len(roots) == 1, \
        f"one root expected, got {[sp.name for sp in roots]}"
    for sp in spans:
        assert sp.parent_id is None or sp.parent_id in ids, \
            f"orphan span {sp.name}: parent {sp.parent_id} not in trace"


# ---------------------------------------------------------------------------
# gateway: retries are SIBLING attempt spans under one root
# ---------------------------------------------------------------------------

def test_retry_attempts_are_sibling_spans_under_one_root():
    reqs = []

    def transport(rep, req):
        reqs.append(req)
        if len(reqs) == 1:
            raise ReplicaUnreachable("first replica down")
        return list(req["prompt"]) + [5]

    router = GatewayRouter(
        RouterConfig(max_attempts=3, backoff_s=0.0),
        transport=transport, sleep=lambda s: None)
    router.update([Replica(name="a"), Replica(name="b")])
    with fresh_recorder() as rec:
        toks, name, attempts = router.dispatch([1, 2], 1)
    assert attempts == 2 and toks == [1, 2, 5]

    spans = one_trace(rec, "gateway.request")
    root = by_name(spans, "gateway.request")
    assert root.parent_id is None
    assert root.attrs["replica"] == name
    assert root.attrs["attempts"] == 2
    att = sorted((sp for sp in spans if sp.name == "gateway.attempt"),
                 key=lambda sp: sp.attrs["attempt"])
    assert len(att) == 2
    # siblings: BOTH parent on the root, not on each other
    assert [sp.parent_id for sp in att] == [root.span_id] * 2
    assert att[0].status == "error"
    assert att[0].attrs["outcome"] == "unreachable"
    assert att[0].attrs["backoff_reason"] == "unreachable"
    assert att[1].attrs["outcome"] == "completed"
    assert att[1].status == "ok"
    # the wire traceparent of each attempt IS that attempt's context —
    # a replica's serve.request parents under the attempt that reached
    # it, never under a failed sibling
    ctxs = [SpanContext.decode(r["traceparent"]) for r in reqs]
    assert [c.span_id for c in ctxs] == [sp.span_id for sp in att]
    assert {c.trace_id for c in ctxs} == {root.trace_id}
    assert_no_orphans(spans)


def test_door_wait_lands_on_the_journey_root():
    """Time parked at the scale-from-zero door is the one TTFT phase
    only the gateway can see: the root span records it so the
    bench_profile decomposition can attribute it."""
    router = GatewayRouter(
        RouterConfig(max_attempts=2, backoff_s=0.0, door_wait_s=10.0),
        transport=lambda rep, req: list(req["prompt"]) + [4],
        sleep=lambda s: None)
    router.update([Replica(name="a", ready=False)])

    def wake():
        router.update([Replica(name="a", ready=True)])

    t = threading.Timer(0.05, wake)
    with fresh_recorder() as rec:
        t.start()
        toks, _, _ = router.dispatch([1], 1)
        t.join()
    assert toks == [1, 4]
    root = by_name(one_trace(rec, "gateway.request"), "gateway.request")
    assert root.attrs["door_wait_s"] > 0.0


def test_stream_cancelled_by_client_is_not_an_error_trace():
    """A client hanging up mid-SSE closes the generator: the journey
    root records outcome=cancelled but must NOT carry error status
    (the recorder would pin every hangup as evidence)."""
    def stream_transport(rep, req):
        yield [1]
        yield [2]
        yield [3]

    router = GatewayRouter(
        RouterConfig(max_attempts=2, backoff_s=0.0),
        transport=lambda rep, req: [0],
        stream_transport=stream_transport, sleep=lambda s: None)
    router.update([Replica(name="a")])
    with fresh_recorder() as rec:
        gen = router.stream([9], 3)
        assert next(gen) == [1]
        gen.close()
    spans = one_trace(rec, "gateway.request")
    root = by_name(spans, "gateway.request")
    assert root.attrs["outcome"] == "cancelled"
    assert root.status == "ok"


# ---------------------------------------------------------------------------
# stub engines: a prefill loop that parks every submit as a handoff,
# and a decode loop that adopts and finishes in a few ticks
# ---------------------------------------------------------------------------

class _InstantEngine:
    """Three-tokens-then-done stub (no split-step protocol)."""

    def __init__(self):
        self.pending, self.done, self._rid = {}, {}, 0

    def submit(self, prompt, n, **kw):
        rid = self._rid
        self._rid += 1
        self.pending[rid] = min(3, n)
        return rid

    def has_work(self):
        return bool(self.pending)

    def step(self):
        for rid, n in list(self.pending.items()):
            self.done[rid] = list(range(n))
            del self.pending[rid]
        return 1

    def progress(self, rid):
        if rid in self.done:
            return list(self.done[rid]), True
        if rid in self.pending:
            return [], False
        return None

    def pop_result(self, rid):
        return self.done.pop(rid, None)


class _PrefillEngine(_InstantEngine):
    """Every submit is immediately a parked handoff state."""

    def __init__(self):
        super().__init__()
        self._handoffs = []

    def submit(self, prompt, n, **kw):
        rid = self._rid
        self._rid += 1
        self._handoffs.append({"rid": rid, "prompt": list(prompt),
                               "max_new_tokens": n})
        return rid

    def pop_handoffs(self):
        out, self._handoffs = self._handoffs, []
        return out


class _AdoptingEngine(_InstantEngine):
    def restore(self, state):
        rid = self._rid
        self._rid += 1
        self.pending[rid] = 3
        return rid

    def cancel(self, rid):
        self.pending.pop(rid, None)


def _serve(loop, **cfg_kw):
    httpd = make_http_server(ServerConfig(port=0, **cfg_kw), loop)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    return httpd, f"http://127.0.0.1:{httpd.server_address[1]}"


# ---------------------------------------------------------------------------
# the whole wire: gateway -> prefill -> decode, one trace_id
# ---------------------------------------------------------------------------

def test_one_trace_spans_gateway_prefill_decode_over_http():
    """The acceptance spine: a request through the REAL gateway HTTP
    door to a REAL prefill-role server whose handoff ships over HTTP
    to a REAL decode-role server — every hop lands in ONE trace with
    correct parenting (gateway.request -> gateway.attempt ->
    serve.request[prefill] -> serve.request[decode]) and zero orphan
    spans, and the stitched span set decomposes into the bench_profile
    TTFT phases."""
    from nos_tpu.cmd.gateway import (
        HttpReplicaTransport, make_http_server as make_gw_server,
    )

    with fresh_recorder() as rec:
        dec_loop = ServingLoop(_AdoptingEngine(), role="decode")
        dec_httpd, dec_url = _serve(dec_loop, role="decode")

        def _http_send(target, data):
            req = urllib.request.Request(
                target.rstrip("/") + "/v1/handoff", data=data,
                headers={"Content-Type": "application/octet-stream"},
                method="POST")
            with urllib.request.urlopen(req, timeout=30) as resp:
                return int(json.loads(resp.read())["rid"])

        pre_loop = ServingLoop(
            _PrefillEngine(), role="prefill",
            handoff_targets=[dec_url], handoff_send=_http_send)
        pre_httpd, pre_url = _serve(pre_loop, role="prefill",
                                    decode_pool=dec_url)

        transport = HttpReplicaTransport(timeout_s=30.0)
        router = GatewayRouter(
            RouterConfig(max_attempts=4, backoff_s=0.01),
            transport=transport.send,
            stream_transport=transport.send_stream,
            resume_transport=transport.resume,
            resume_stream_transport=transport.resume_stream)
        router.update([
            Replica(name="pre-0", handle=pre_url, role="prefill"),
            Replica(name="dec-0", handle=dec_url, role="decode"),
        ])
        gw_httpd = make_gw_server(router, 0, "web")
        threading.Thread(target=gw_httpd.serve_forever,
                         daemon=True).start()
        gw = f"http://127.0.0.1:{gw_httpd.server_address[1]}"
        try:
            req = urllib.request.Request(
                gw + "/v1/generate",
                data=json.dumps({"prompt": [1, 2, 3],
                                 "max_new_tokens": 6,
                                 "deadline_s": 30}).encode(),
                headers={"Content-Type": "application/json"},
                method="POST")
            with urllib.request.urlopen(req, timeout=30) as r:
                body = json.loads(r.read())
            assert body["tokens"] == [1, 2, 3, 0, 1, 2]
        finally:
            gw_httpd.shutdown()
            pre_httpd.shutdown()
            dec_httpd.shutdown()
            pre_loop.shutdown()
            dec_loop.shutdown()

        spans = one_trace(rec, "gateway.request")
        root = by_name(spans, "gateway.request")
        attempt = by_name(spans, "gateway.attempt")
        serves = [sp for sp in spans if sp.name == "serve.request"]
        pre_sp = next(sp for sp in serves
                      if sp.attrs.get("role") == "prefill")
        dec_sp = next(sp for sp in serves
                      if sp.attrs.get("role") == "decode")
        # the parenting chain IS the journey
        assert attempt.parent_id == root.span_id
        assert pre_sp.parent_id == attempt.span_id
        assert dec_sp.parent_id == pre_sp.span_id
        assert dec_sp.attrs["adopted"] is True
        assert len({sp.trace_id for sp in spans}) == 1
        assert_no_orphans(spans)
        # every span closed: a stitched journey has no dangling work
        assert all(sp.end_time is not None for sp in spans)

        # the stitched spans ARE bench_profile's input: the TTFT
        # decomposition finds the journey and its disagg phases
        import bench_profile
        doc = bench_profile.ttft_section([sp.to_dict() for sp in spans])
        assert doc["journeys"] == 1
        row = doc["requests"][0]
        assert row["trace_id"] == root.trace_id
        assert row["attempts"] == 1
        assert row["door_wait_s"] >= 0.0
        assert row["route_s"] >= 0.0
        assert row["handoff_s"] >= 0.0


def test_tracing_off_still_forwards_the_journey_header():
    """A tracing-disabled prefill replica must not BREAK the fleet's
    stitching: the inbound traceparent is forwarded verbatim through
    the handoff meta plane even though this hop records nothing."""
    shipped = []
    loop = ServingLoop(
        _PrefillEngine(), role="prefill",
        handoff_targets=["http://dec"],
        handoff_send=lambda t, d: shipped.append(d) or 1)
    old = tracing._default_tracer.enabled
    tracing._default_tracer.enabled = False
    try:
        wire = "00-" + "ab" * 16 + "-" + "cd" * 8 + "-01"
        res = loop.prefill([1, 2], 4, timeout=10, traceparent=wire)
        assert res["handoff"]["rid"] == 1
    finally:
        tracing._default_tracer.enabled = old
        loop.shutdown()
    from nos_tpu.models.handoff import decode_handoff
    st = decode_handoff(shipped[0])
    assert st["traceparent"] == wire


# ---------------------------------------------------------------------------
# KV-fabric legs: pull/serve/denial all join the request's trace
# ---------------------------------------------------------------------------

def test_fabric_pull_and_serve_spans_join_the_request_trace():
    """A kv_sources offer honored at the puller files a kvfabric.pull
    child under the request's inbound context; the holder's
    /v1/kvchain files a kvfabric.serve child under the PULL span (the
    header crossed the wire) — one trace covers both replicas."""
    with fresh_recorder() as rec:
        hold_loop = ServingLoop(_InstantEngine(),
                                fabric_token="fleet-secret")
        hold_httpd, hold_url = _serve(hold_loop,
                                      kv_fabric_token="fleet-secret")
        pull_loop = ServingLoop(_InstantEngine(),
                                fabric_token="fleet-secret")
        pull_httpd, pull_url = _serve(pull_loop,
                                      kv_fabric_token="fleet-secret")
        root = tracing.start_span("gateway.attempt", component="gateway")
        try:
            body = {"prompt": [1, 2], "max_new_tokens": 2,
                    "kv_sources": [{
                        "url": f"{hold_url}/v1/kvchain/d1gest",
                        "digest": "d1gest", "replica": "holder"}]}
            req = urllib.request.Request(
                pull_url + "/v1/generate",
                data=json.dumps(body).encode(),
                headers={"Content-Type": "application/json",
                         FABRIC_TOKEN_HEADER: "fleet-secret",
                         "traceparent": root.context.encode()},
                method="POST")
            with urllib.request.urlopen(req, timeout=30) as r:
                assert json.loads(r.read())["tokens"] == [1, 2, 0, 1]
        finally:
            root.end()
            hold_httpd.shutdown()
            pull_httpd.shutdown()
            hold_loop.shutdown()
            pull_loop.shutdown()

        spans = one_trace(rec, "kvfabric.pull")
        assert {sp.trace_id for sp in spans} == {root.trace_id}
        pull = by_name(spans, "kvfabric.pull")
        serve = by_name(spans, "kvfabric.serve")
        assert pull.parent_id == root.span_id
        # stub engines hold no chains: the holder answers miss, the
        # puller records the miss — the OUTCOME is in the trace either
        # way, which is the point
        assert pull.attrs["outcome"] == "pull_miss"
        assert pull.attrs["digest"] == "d1gest"
        assert serve.parent_id == pull.span_id
        assert serve.attrs["outcome"] == "miss"
        # the request itself rides the same trace
        sreq = by_name(spans, "serve.request")
        assert sreq.parent_id == root.span_id


def test_fabric_denied_pull_is_linked_into_the_trace():
    """An offer arriving WITHOUT the fleet token is refused — and when
    the request carries a trace, the denial is visible inside it as a
    kvfabric.pull span with outcome=pull_denied. A tokenless probe
    with no trace stays counters-only (no fresh recorder roots)."""
    with fresh_recorder() as rec:
        loop = ServingLoop(_InstantEngine())
        httpd, url = _serve(loop, kv_fabric_token="fleet-secret")
        root = tracing.start_span("gateway.attempt", component="gateway")
        try:
            body = {"prompt": [3], "max_new_tokens": 1,
                    "kv_sources": [{"url": "http://evil/v1/kvchain/xx",
                                    "digest": "xx"}]}
            req = urllib.request.Request(
                url + "/v1/generate", data=json.dumps(body).encode(),
                headers={"Content-Type": "application/json",
                         "traceparent": root.context.encode()},
                method="POST")
            with urllib.request.urlopen(req, timeout=30) as r:
                json.loads(r.read())
            # same offer, no trace context: counted, not recorded
            req2 = urllib.request.Request(
                url + "/v1/generate", data=json.dumps(body).encode(),
                headers={"Content-Type": "application/json"},
                method="POST")
            with urllib.request.urlopen(req2, timeout=30) as r:
                json.loads(r.read())
        finally:
            root.end()
            httpd.shutdown()
            loop.shutdown()
        assert loop._pull_counts["pull_denied"] == 2
        spans = one_trace(rec, "kvfabric.pull")
        denied = by_name(spans, "kvfabric.pull")
        assert denied.parent_id == root.span_id
        assert denied.attrs["outcome"] == "pull_denied"
        assert denied.attrs["digest"] == "xx"
        # the traceless denial minted no recorder root
        fab_traces = [tid for tid in rec.trace_ids()
                      if any(sp.component == "kvfabric"
                             for sp in rec.trace(tid))]
        assert fab_traces == [denied.trace_id]
