"""End-to-end check of the multislice example: the SAME plan schedules
as a jobset on a simulated 2-pool cluster AND trains one real step on a
2-slice virtual mesh — the scheduler-side and workload-side halves of
the dp-over-DCN contract exercised from one source of truth."""
import jax
import jax.numpy as jnp
import pytest

from examples.multislice_2xv5e import GLOBAL_LAYOUT, N_SLICES, plan
from nos_tpu import constants
from nos_tpu.kube import ApiServer, Manager
from nos_tpu.kube.objects import (
    Container,
    Node,
    NodeStatus,
    ObjectMeta,
    Pod,
    PodCondition,
    PodSpec,
    PodStatus,
)
from nos_tpu.scheduler import Scheduler

TPU = "google.com/tpu"


def test_plan_is_consistent():
    p = plan()
    assert p["per_slice_layout"]["dp"] == 1          # dp fully crosses DCN
    assert p["per_slice_layout"]["tp"] == GLOBAL_LAYOUT.tp
    assert p["chips_per_slice"] * N_SLICES == GLOBAL_LAYOUT.chips
    assert p["dcn_axes"] == ["dp"]


def test_jobset_schedules_on_two_pools():
    p = plan()
    server = ApiServer()
    mgr = Manager(server)
    mgr.add_controller(Scheduler().controller())
    for pool in ("slice-a", "slice-b"):
        for i in range(p["hosts_per_slice"]):
            server.create(Node(
                metadata=ObjectMeta(
                    name=f"{pool}-w{i}",
                    labels={
                        constants.LABEL_TPU_ACCELERATOR:
                            "tpu-v5-lite-podslice",
                        constants.LABEL_TPU_TOPOLOGY: p["slice_topology"],
                        constants.LABEL_NODEPOOL: pool,
                    }),
                status=NodeStatus(capacity={TPU: 8, "cpu": 96},
                                  allocatable={TPU: 8, "cpu": 96})))
    for s in range(N_SLICES):
        for w in range(p["hosts_per_slice"]):
            labels = dict(p["pod_labels_slice0_worker0"])
            labels[constants.LABEL_JOBSET_SLICE] = str(s)
            labels[constants.LABEL_GANG_NAME] = f"train-slice-{s}"
            labels[constants.LABEL_GANG_WORKER] = str(w)
            server.create(Pod(
                metadata=ObjectMeta(
                    name=f"train-s{s}-w{w}", namespace="team-a",
                    labels=labels, annotations=dict(p["pod_annotation"])),
                spec=PodSpec(containers=[Container(requests={TPU: 8})],
                             scheduler_name=constants.SCHEDULER_NAME),
                status=PodStatus(phase="Pending", conditions=[PodCondition(
                    type="PodScheduled", status="False",
                    reason="Unschedulable")])))
    mgr.run_until_idle()
    pools = set()
    for s in range(N_SLICES):
        for w in range(p["hosts_per_slice"]):
            nn = server.get("Pod", f"train-s{s}-w{w}",
                            "team-a").spec.node_name
            assert nn, (s, w)
            pools.add(nn.rsplit("-w", 1)[0])
    assert pools == {"slice-a", "slice-b"}   # one distinct domain each


@pytest.mark.skipif(len(jax.devices()) < 8,
                    reason="needs 8 virtual devices")
def test_trains_one_step_on_virtual_two_slice_mesh():
    import optax

    from nos_tpu.parallel.layout import ParallelLayout
    from nos_tpu.models import transformer as tfm
    from nos_tpu.parallel.mesh import build_mesh, data_sharding

    # same SHAPE as the example (dp crosses 2 slices, tp x sp inside),
    # scaled to the 8-device test mesh: 2 slices of 4 chips
    layout = ParallelLayout(dp=2, tp=2, sp=2)
    devices = jax.devices()[:layout.chips]
    half = layout.chips // N_SLICES
    slice_ids = [i // half for i in range(layout.chips)]
    mesh = build_mesh(layout, devices, slice_ids=slice_ids)
    cfg = tfm.TransformerConfig(vocab=64, d_model=32, n_layers=2, n_heads=4,
                                n_kv_heads=2, d_ff=64, max_seq=32,
                                dtype=jnp.float32)
    params = jax.device_put(tfm.init_params(jax.random.PRNGKey(0), cfg),
                            tfm.param_shardings(mesh, cfg))
    opt = optax.adamw(1e-3)
    step = jax.jit(tfm.make_train_step(cfg, opt, mesh))
    tok = jax.random.randint(jax.random.PRNGKey(1), (4, 32), 0, cfg.vocab)
    batch = {"tokens": jax.device_put(tok, data_sharding(mesh)),
             "targets": jax.device_put(tok, data_sharding(mesh))}
    _, _, loss = step(params, opt.init(params), batch)
    assert jnp.isfinite(loss)
