"""Annotation codec (model: reference pkg/gpu/annotation_test.go)."""
from nos_tpu.tpu import annotation as ann
from nos_tpu.tpu.device import Device, DeviceList
from nos_tpu.tpu.slice import Profile

P11, P22, P24 = Profile(1, 1), Profile(2, 2), Profile(2, 4)


def test_parse_node_annotations_roundtrip():
    annotations = {
        "nos.ai/spec-tpu-0-1x1": "4",
        "nos.ai/spec-tpu-0-2x2": "1",
        "nos.ai/status-tpu-0-1x1-free": "2",
        "nos.ai/status-tpu-0-1x1-used": "2",
        "nos.ai/status-tpu-0-2x2-used": "1",
        "unrelated": "x",
        "nos.ai/spec-tpu-bad": "7",            # malformed -> ignored
        "nos.ai/spec-tpu-0-1x1-extra": "oops", # malformed -> ignored
    }
    specs, statuses = ann.parse_node_annotations(annotations)
    assert len(specs) == 2
    assert len(statuses) == 3
    desired = ann.spec_from_annotations(specs)
    assert desired == {0: {P11: 4, P22: 1}}
    state = ann.status_to_board_state(statuses)
    assert state[0]["free"] == {P11: 2}
    assert state[0]["used"] == {P11: 2, P22: 1}


def test_spec_annotations_from_partitioning():
    out = ann.spec_annotations_from_partitioning({0: {P11: 4, P22: 1}, 1: {P24: 1}})
    assert out == {
        "nos.ai/spec-tpu-0-1x1": "4",
        "nos.ai/spec-tpu-0-2x2": "1",
        "nos.ai/spec-tpu-1-2x4": "1",
    }
    # zero quantities are omitted
    assert ann.spec_annotations_from_partitioning({0: {P11: 0}}) == {}


def test_status_annotations_from_devices():
    devices = DeviceList([
        Device("d0", 0, P11, "used"),
        Device("d1", 0, P11, "used"),
        Device("d2", 0, P11, "free"),
        Device("d3", 0, P22, "free"),
    ])
    out = ann.status_annotations_from_devices(devices)
    assert out == {
        "nos.ai/status-tpu-0-1x1-used": "2",
        "nos.ai/status-tpu-0-1x1-free": "1",
        "nos.ai/status-tpu-0-2x2-free": "1",
    }


def test_spec_matches_status():
    annotations = {
        "nos.ai/spec-tpu-0-1x1": "2",
        "nos.ai/status-tpu-0-1x1-free": "1",
        "nos.ai/status-tpu-0-1x1-used": "1",
    }
    specs, statuses = ann.parse_node_annotations(annotations)
    assert ann.spec_matches_status(specs, statuses)

    annotations["nos.ai/spec-tpu-0-1x1"] = "3"
    specs, statuses = ann.parse_node_annotations(annotations)
    assert not ann.spec_matches_status(specs, statuses)


def test_spec_matches_status_empty_sides():
    assert ann.spec_matches_status([], [])
    specs, statuses = ann.parse_node_annotations({"nos.ai/spec-tpu-0-1x1": "1"})
    assert not ann.spec_matches_status(specs, statuses)


def test_device_list_groupings():
    devices = DeviceList([
        Device("a", 0, P11, "used"),
        Device("b", 1, P11, "free"),
        Device("c", 0, P22, "free"),
    ])
    assert set(devices.group_by_board().keys()) == {0, 1}
    assert len(devices.group_by_profile()[P11]) == 2
    assert [d.device_id for d in devices.used()] == ["a"]
    assert devices.geometry() == {P11: 2, P22: 1}


def test_parse_rejects_nonpositive_quantities():
    specs, statuses = ann.parse_node_annotations({
        "nos.ai/spec-tpu-0-1x1": "-3",
        "nos.ai/spec-tpu-0-2x2": "0",
        "nos.ai/status-tpu-0-1x1-free": "-1",
    })
    assert specs == [] and statuses == []
