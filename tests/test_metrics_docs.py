"""Metrics <-> docs drift guard (ISSUE 3 satellite).

The `docs/telemetry.md` table is only useful if it is trustworthy: every
metric registered anywhere in `nos_tpu/` must appear in the table, and
every `nos_*` name in the table must correspond to a registration. The
scan is textual (regex over registration calls), so metrics registered
lazily inside functions (cmd/server.py, cmd/trainer.py) are covered
without importing JAX-heavy modules.
"""
import os
import re

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# `<registry>.counter("nos_...")` / `.gauge(` / `.histogram(` with the
# name literal on the same or next line
REGISTRATION = re.compile(
    r'\.(?:counter|gauge|histogram)\(\s*"(nos_[a-z0-9_]+)"')
DOC_NAME = re.compile(r"nos_[a-z0-9_]+")


def registered_metric_names():
    names = set()
    for dirpath, _dirnames, filenames in os.walk(
            os.path.join(REPO, "nos_tpu")):
        if "__pycache__" in dirpath:
            continue
        for fn in filenames:
            if not fn.endswith(".py"):
                continue
            with open(os.path.join(dirpath, fn)) as f:
                names.update(REGISTRATION.findall(f.read()))
    return names


def documented_metric_names():
    names = set()
    with open(os.path.join(REPO, "docs", "telemetry.md")) as f:
        for line in f:
            if line.strip().startswith("|"):
                names.update(DOC_NAME.findall(line))
    # histogram rows may cite the _bucket/_sum/_count series; normalize
    return {re.sub(r"_(bucket|sum|count)$", "", n) for n in names}


def test_every_registered_metric_is_documented():
    code = registered_metric_names()
    assert code, "scan must find the registered metrics"
    doc = documented_metric_names()
    missing = sorted(code - doc)
    assert not missing, (
        f"metrics registered but missing from docs/telemetry.md: {missing} "
        f"— add a table row for each")


def test_every_documented_metric_is_registered():
    doc = documented_metric_names()
    assert doc, "telemetry.md table must not be empty"
    code = registered_metric_names()
    stale = sorted(doc - code)
    assert not stale, (
        f"docs/telemetry.md documents metrics no code registers: {stale} "
        f"— remove the rows or restore the metrics")
