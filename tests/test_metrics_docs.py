"""Metrics <-> docs drift guard (ISSUE 3 satellite), metric-name lint
(ISSUE 5 satellite), and span-name registry lint (ISSUE 18 satellite).

The `docs/telemetry.md` table is only useful if it is trustworthy: every
metric registered anywhere in `nos_tpu/` must appear in the table, and
every `nos_*` name in the table must correspond to a registration. The
scan is textual (regex over registration calls), so metrics registered
lazily inside functions (cmd/server.py, cmd/trainer.py) are covered
without importing JAX-heavy modules.

The lint keeps future instruments Prometheus-conventional: `nos_`
prefix, counters end `_total`, timing/size series end `_seconds` /
`_bytes`, nothing collides with the reserved histogram sample suffixes.

The span registry works the same way for traces: every span-name
literal minted anywhere in `nos_tpu/` must have a row in the
`docs/tracing.md` taxonomy table, and names must read as dotted
`component.verb` so a trace is legible without the source open.
"""
import os
import re

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# `<registry>.counter("nos_...")` / `.gauge(` / `.histogram(` with the
# name literal on the same or next line
REGISTRATION = re.compile(
    r'\.(?:counter|gauge|histogram)\(\s*"(nos_[a-z0-9_]+)"')
# lint variant: capture the kind AND any first-arg string literal, so a
# registration that fails the nos_ prefix is caught, not just missed
KIND_REGISTRATION = re.compile(
    r'\.(counter|gauge|histogram)\(\s*"([A-Za-z0-9_:]+)"')
DOC_NAME = re.compile(r"nos_[a-z0-9_]+")


def registered_metric_names():
    names = set()
    for _path, text in _metric_sources():
        names.update(REGISTRATION.findall(text))
    return names


def _metric_sources():
    for dirpath, _dirnames, filenames in os.walk(
            os.path.join(REPO, "nos_tpu")):
        if "__pycache__" in dirpath:
            continue
        for fn in filenames:
            if not fn.endswith(".py"):
                continue
            path = os.path.join(dirpath, fn)
            with open(path) as f:
                yield path, f.read()


def documented_metric_names():
    names = set()
    with open(os.path.join(REPO, "docs", "telemetry.md")) as f:
        for line in f:
            if line.strip().startswith("|"):
                names.update(DOC_NAME.findall(line))
    # histogram rows may cite the _bucket/_sum/_count series; normalize
    return {re.sub(r"_(bucket|sum|count)$", "", n) for n in names}


def test_every_registered_metric_is_documented():
    code = registered_metric_names()
    assert code, "scan must find the registered metrics"
    doc = documented_metric_names()
    missing = sorted(code - doc)
    assert not missing, (
        f"metrics registered but missing from docs/telemetry.md: {missing} "
        f"— add a table row for each")


def test_every_documented_metric_is_registered():
    doc = documented_metric_names()
    assert doc, "telemetry.md table must not be empty"
    code = registered_metric_names()
    stale = sorted(doc - code)
    assert not stale, (
        f"docs/telemetry.md documents metrics no code registers: {stale} "
        f"— remove the rows or restore the metrics")


# ---------------------------------------------------------------------------
# metric-name lint: keep future instruments Prometheus-conventional
# ---------------------------------------------------------------------------

# count-valued histograms allowed by explicit exception; new histograms
# must end _seconds or _bytes unless their value is GENUINELY a count
# distribution (reviewed here, one line of justification each)
HISTOGRAM_COUNT_NOUNS = {
    "nos_partitioning_batch_pods",
    "nos_scheduler_sweep_nodes_visited",
    # accepted speculative proposals per verify window: an integer in
    # [0, n_draft] — a token count, not a duration or size
    "nos_tpu_serve_spec_accepted_per_window",
}

# gauges whose noun phrase qualifies the unit (`..._bytes_in_use`): the
# unit still reads unambiguously, so they pass as gauge nouns — also a
# CLOSED list; prefer a terminal unit suffix for new gauges
GAUGE_UNIT_NOUNS = {
    "nos_tpu_device_hbm_bytes_in_use",
    "nos_tpu_device_hbm_bytes_limit",
}


def test_metric_names_follow_prometheus_conventions():
    seen = []
    for path, text in _metric_sources():
        for kind, name in KIND_REGISTRATION.findall(text):
            seen.append((path, kind, name))
    assert seen, "scan must find the registered metrics"
    for path, kind, name in seen:
        where = f"{os.path.relpath(path, REPO)}: {kind} {name}"
        assert name.startswith("nos_"), \
            f"{where} — every metric must carry the nos_ prefix"
        assert re.fullmatch(r"nos_[a-z0-9_]+", name), \
            f"{where} — lowercase snake_case only"
        # reserved suffixes: the exposition appends these to histogram
        # families, so a base name using them breaks scrapers
        assert not name.endswith(("_bucket", "_count", "_sum")), \
            f"{where} — reserved histogram sample suffix"
        if kind == "counter":
            assert name.endswith("_total"), \
                f"{where} — counters must end _total"
        else:
            assert not name.endswith("_total"), \
                f"{where} — only counters may end _total"
        if kind == "histogram":
            assert name.endswith(("_seconds", "_bytes")) \
                or name in HISTOGRAM_COUNT_NOUNS, (
                f"{where} — histograms must be unit-suffixed "
                f"(_seconds/_bytes); count-valued shapes belong in "
                f"HISTOGRAM_COUNT_NOUNS only by explicit exception")
        # unit words must BE the unit suffix, not buried mid-name
        # (gauge nouns that qualify the unit are grandfathered above)
        if name in GAUGE_UNIT_NOUNS:
            assert kind == "gauge", f"{where} — exception is gauge-only"
            continue
        for unit in ("seconds", "bytes"):
            if f"_{unit}" in name:
                # counters accumulating a unit quantity end
                # _<unit>_total (process_cpu_seconds_total-style)
                ok = name.endswith(f"_{unit}") or (
                    kind == "counter"
                    and name.endswith(f"_{unit}_total"))
                assert ok, (
                    f"{where} — '{unit}' must be the terminal unit "
                    f"suffix (before _total on counters)")


# ---------------------------------------------------------------------------
# span-name registry: every span minted in code has a tracing.md row
# ---------------------------------------------------------------------------

# any span construction site with its name as a string literal: the
# context-manager form (`tracing.span("...")`), the explicit form
# (`start_span("...")`), and raw Span(...) synthesis (trace_export
# inputs). Dynamic names (f-strings, "prefix" + var) are linted at
# their literal prefix when one exists, else invisible to the scan —
# keep span names literal so the registry stays complete.
SPAN_SITE = re.compile(
    r'(?:\bstart_span|\.span|\bSpan)\(\s*["\']([A-Za-z0-9_.]+)["\']')

# tracing.md documents families with placeholders (`tick.<phase>`): a
# code literal matches a doc name either exactly or as the prefix left
# of the placeholder
DOC_SPAN = re.compile(r"`([a-z][a-z0-9_.<>]*)`")

def minted_span_names():
    sites = []
    for path, text in _metric_sources():
        for name in SPAN_SITE.findall(text):
            sites.append((path, name))
    return sites


def documented_span_names():
    names = set()
    in_table = False
    with open(os.path.join(REPO, "docs", "tracing.md")) as f:
        for line in f:
            if line.startswith("| Span |"):
                in_table = True
                continue
            if in_table and not line.strip().startswith("|"):
                in_table = False
            if in_table:
                first_cell = line.split("|")[1]
                names.update(DOC_SPAN.findall(first_cell))
    return names


def _doc_covers(name, doc):
    if name in doc:
        return True
    for d in doc:
        if "<" in d and name.rstrip(".") == d.split("<")[0].rstrip("."):
            return True
    return False


def test_every_minted_span_is_documented():
    sites = minted_span_names()
    assert sites, "scan must find the span sites"
    doc = documented_span_names()
    assert doc, "tracing.md span table must not be empty"
    missing = sorted({name for _p, name in sites
                      if not _doc_covers(name, doc)})
    assert not missing, (
        f"spans minted in code but missing from the docs/tracing.md "
        f"taxonomy table: {missing} — add a row for each")


def test_span_names_are_dotted_component_verb():
    for path, name in minted_span_names():
        where = f"{os.path.relpath(path, REPO)}: span {name!r}"
        if name.endswith("."):
            # a prefix literal ("tick." + phase) mints a dotted family;
            # the component segment must still be well-formed
            assert re.fullmatch(r"[a-z][a-z0-9_]*\.", name), (
                f"{where} — span-family prefix must be a lowercase "
                f"snake component followed by a dot")
            continue
        assert re.fullmatch(
            r"[a-z][a-z0-9_]*\.[a-z][a-z0-9_]*", name), (
            f"{where} — span names are dotted component.verb "
            f"(lowercase snake segments)")


# ---------------------------------------------------------------------------
# /stats drift guard (ISSUE 20 satellite): the snapshot's TOP-LEVEL
# keys are a wire contract — the fleet controller, the gateway scrape
# and the KV fabric all consume them. The literal sets here are the
# authoritative lists; docs/telemetry.md's tables must match them
# exactly, and the HTTP integration tests check real payloads against
# these sets (so a key added in code without a doc row fails there).
# ---------------------------------------------------------------------------

REPLICA_STATS_KEYS = {
    "engine", "role", "handoff", "max_batch", "max_len", "slots",
    "active_slots", "pending", "prefill_sched", "pipeline",
    "prefix_cache", "prefix_index", "kv", "tenants", "compiles",
    "tokens_emitted", "healthy", "draining", "recovering", "uptime_s",
    "config", "per_request", "supervisor", "deadline", "slo", "rates",
    "kv_fabric_pulls", "tick_phases", "slo_budget", "chip_ledger",
}

GATEWAY_STATS_KEYS = {
    "door_queue", "door_queue_peak", "replicas", "ready_replicas",
    "handoffs", "requests", "shed", "tenant_shed", "routes", "retries",
    "ring", "kv_fabric", "slo", "config", "fleet",
}

STATS_KEY = re.compile(r"`([a-z_0-9]+)`")


def documented_stats_keys(which):
    keys = set()
    in_table = False
    with open(os.path.join(REPO, "docs", "telemetry.md")) as f:
        for line in f:
            if line.startswith(f"| {which} `/stats` key |"):
                in_table = True
                continue
            if in_table and not line.strip().startswith("|"):
                in_table = False
            if in_table:
                first_cell = line.split("|")[1]
                keys.update(STATS_KEY.findall(first_cell))
    return keys


def test_replica_stats_keys_match_docs():
    doc = documented_stats_keys("Replica")
    assert doc, "telemetry.md replica /stats table must not be empty"
    assert doc == REPLICA_STATS_KEYS, (
        f"replica /stats keys drifted — docs-only: "
        f"{sorted(doc - REPLICA_STATS_KEYS)}, undocumented: "
        f"{sorted(REPLICA_STATS_KEYS - doc)}; update the "
        f"docs/telemetry.md table AND this set together")


def test_gateway_stats_keys_match_docs():
    doc = documented_stats_keys("Gateway")
    assert doc, "telemetry.md gateway /stats table must not be empty"
    assert doc == GATEWAY_STATS_KEYS, (
        f"gateway /stats keys drifted — docs-only: "
        f"{sorted(doc - GATEWAY_STATS_KEYS)}, undocumented: "
        f"{sorted(GATEWAY_STATS_KEYS - doc)}; update the "
        f"docs/telemetry.md table AND this set together")
