"""The long-context example (examples/long_context_1m_v5e.py): plan
numbers, gang placement on a v5e 16x16 pool, and a scaled-down run of
the exact layout shape (fsdp x sp ring attention) on the test mesh."""
import importlib.util
import os

import pytest

from nos_tpu.scheduler import framework as fw
from nos_tpu.scheduler.gang import GangScheduler

from conftest import example_pod_from_manifest, example_pool


def load_example():
    path = os.path.join(os.path.dirname(__file__), "..", "examples",
                        "long_context_1m_v5e.py")
    spec = importlib.util.spec_from_file_location("long_context_1m_v5e", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


EX = load_example()


def test_plan_numbers():
    p = EX.plan()
    assert p["chips"] == 256
    assert p["topology"] == "16x16"
    assert p["hosts"] == 32
    assert p["tokens_per_chip"] == 16384
    # the point of the example: per-chip activations stay tiny while the
    # materialized-scores counterfactual is absurd
    assert p["activation_gb_per_chip_per_layer"] < 0.2
    assert p["scores_tb_if_materialized"] > 100


def test_gang_admitted_and_placed_on_v5e_256():
    members = [example_pod_from_manifest(m) for m in EX.worker_pods()]
    assert len(members) == 32
    gs = GangScheduler(fw.SchedulerFramework())
    admission = gs.admit(members)
    assert admission.ok, admission.reason

    snapshot = fw.Snapshot.build(
        example_pool("v5e-256-pool", 32, "tpu-v5-lite-podslice", "16x16", 8),
        [])
    placement, reason = gs.place(members, snapshot)
    assert placement is not None, reason
    assert len(placement.nodes) == 32


def test_scaled_down_layout_trains_on_test_mesh():
    """The example's axis shape (fsdp x sp, ring attention, minimal remat,
    chunked head) at toy size on the 8-device mesh: fsdp=2, sp=4."""
    import jax

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")

    from nos_tpu.cmd.trainer import TrainerConfig, train

    loss = train(TrainerConfig(
        vocab=64, d_model=32, n_layers=2, n_heads=8, n_kv_heads=4,
        d_ff=64, max_seq=64, steps=2, batch_size=2, seq_len=32,
        bf16=False, fsdp=2, sp=4, remat_policy="minimal", loss_chunk=8))
    assert loss == loss and loss < 100
