"""The trainer binary (nos_tpu/cmd/trainer.py): trains, checkpoints,
resumes, and supports every parallel layout on the virtual mesh."""
import jax
import pytest

from nos_tpu.cmd.trainer import TrainerConfig, train

needs_partial_auto = pytest.mark.skipif(
    not hasattr(jax, "shard_map"),
    reason="pp x auto-axis composition needs modern jax.shard_map "
           "(0.4.x XLA:CPU SPMD lacks PartitionId in partial-auto)")

pytestmark = [
    pytest.mark.skipif(
        len(jax.devices()) < 8, reason="needs 8 virtual devices"),
    # orbax async saves crash native-side when executables come out of
    # the suite-wide persistent compilation cache — run this module
    # cache-less (see _no_xla_compilation_cache)
    pytest.mark.usefixtures("_no_xla_compilation_cache"),
]


def _child_json(env, prog, payload):
    """Run ``prog`` (a -c program that prints OUT=<json>) in a fresh
    child process and return the decoded value."""
    import json
    import os
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.run(
        [sys.executable, "-c", prog, payload], env=env, cwd=repo,
        capture_output=True, text=True, timeout=420)
    assert proc.returncode == 0, proc.stderr[-3000:]
    line = [ln for ln in proc.stdout.splitlines()
            if ln.startswith("OUT=")][-1]
    return json.loads(line[len("OUT="):])


def train_in_subprocess(env, *cfgs):
    """Run train() over each config in ONE fresh child process and
    return the losses. jax.profiler tracing, the data-pipeline runs
    (prefetch threads + orbax async saves), and repeated train+save
    cycles are unsafe in the suite's long-lived runtime (see
    _fresh_jax_subprocess_env) — these tests exercise the identical
    trainer code path, just in a clean process."""
    import json

    prog = (
        "import json, sys\n"
        "from nos_tpu.cmd.trainer import TrainerConfig, train\n"
        "out = [train(TrainerConfig(**kw)) for kw in json.loads(sys.argv[1])]\n"
        "print('OUT=' + json.dumps([float(x) for x in out]))\n"
    )
    return _child_json(env, prog, json.dumps([c.__dict__ for c in cfgs]))


def tiny(**kw):
    base = dict(vocab=64, d_model=32, n_layers=2, n_heads=4, d_ff=64,
                max_seq=32, steps=4, batch_size=4, seq_len=16,
                bf16=False, log_every=2)
    base.update(kw)
    return TrainerConfig(**base)


def test_trains_and_loss_finite():
    loss = train(tiny(dp=2, tp=2))
    assert loss == loss and loss < 100


@needs_partial_auto
def test_trains_pipelined():
    loss = train(tiny(pp=2, dp=2, n_microbatches=2))
    assert loss == loss


def test_trains_moe_with_ep():
    loss = train(tiny(ep=2, dp=2, n_experts=2))
    assert loss == loss


def test_checkpoint_resume_continues_from_latest(tmp_path, caplog):
    import logging

    d = str(tmp_path / "ckpt")
    cfg = tiny(dp=2, steps=4, checkpoint_dir=d, checkpoint_every=2)
    train(cfg)
    # second run resumes at step 4 and has nothing left to do
    with caplog.at_level(logging.INFO, logger="nos_tpu.trainer"):
        train(cfg)
    assert any("resumed from checkpoint step 4" in r.getMessage()
               for r in caplog.records)


def test_config_from_yaml(tmp_path):
    p = tmp_path / "trainer.yaml"
    p.write_text("steps: 3\ndp: 2\nvocab: 64\nd_model: 32\nn_layers: 2\n"
                 "n_heads: 4\nd_ff: 64\nmax_seq: 32\nbatch_size: 4\n"
                 "seq_len: 16\nbf16: false\n")
    cfg = TrainerConfig.from_yaml_file(str(p))
    assert cfg.steps == 3 and cfg.dp == 2
    with pytest.raises(ValueError, match="unknown"):
        bad = tmp_path / "bad.yaml"
        bad.write_text("nope: 1\n")
        TrainerConfig.from_yaml_file(str(bad))


def test_lowered_steps_does_not_relabel_checkpoints(tmp_path):
    from nos_tpu.train import CheckpointManager

    d = str(tmp_path / "ckpt")
    train(tiny(dp=2, steps=4, checkpoint_dir=d, checkpoint_every=2))
    # operator lowers steps below the restored step: nothing must be saved
    train(tiny(dp=2, steps=2, checkpoint_dir=d, checkpoint_every=2))
    mgr = CheckpointManager(d)
    assert mgr.latest() == 4
    assert sorted(mgr.manager.all_steps()) == [2, 4]
    mgr.close()


def test_profiler_trace_written(tmp_path, _fresh_jax_subprocess_env):
    d = str(tmp_path / "trace")
    train_in_subprocess(
        _fresh_jax_subprocess_env,
        tiny(dp=2, steps=4, profile_dir=d, profile_start=1,
             profile_steps=2))
    import os
    found = [os.path.join(r, f) for r, _, fs in os.walk(d) for f in fs]
    assert found, "profiler trace directory is empty"


# slow tier: the heaviest test in the suite (two cache-less child jax
# startups, ~25s) probing one profiler edge; tier-1 keeps the profiler
# path covered via test_profiler_trace_written
@pytest.mark.slow
def test_profiler_fires_on_resume_past_start(tmp_path,
                                             _fresh_jax_subprocess_env):
    import os

    ckpt = str(tmp_path / "ckpt")
    d = str(tmp_path / "trace")
    # resume at step 4 with profile_start=2 (already passed): still
    # traces. Both runs share the child process (one jax startup).
    train_in_subprocess(
        _fresh_jax_subprocess_env,
        tiny(dp=2, steps=4, checkpoint_dir=ckpt, checkpoint_every=4),
        tiny(dp=2, steps=6, checkpoint_dir=ckpt, checkpoint_every=4,
             profile_dir=d, profile_start=2, profile_steps=10))
    found = [os.path.join(r, f) for r, _, fs in os.walk(d) for f in fs]
    assert found, "resumed run wrote no trace (window also ran past end)"


def test_trains_from_token_shards(tmp_path, _fresh_jax_subprocess_env):
    import numpy as np

    from nos_tpu.train.data import write_token_shards

    rng = np.random.default_rng(0)
    write_token_shards(
        str(tmp_path), [rng.integers(0, 64, size=400, dtype=np.uint32)])
    (loss,) = train_in_subprocess(
        _fresh_jax_subprocess_env,
        tiny(dp=2, data_path=str(tmp_path / "shard_*.bin")))
    assert loss == loss and loss < 100


# slow tier (three cache-less child train() runs, ~12s): tier-1 keeps
# the resume-reproduces-the-uninterrupted-stream property covered via
# test_stop_event_checkpoints_and_resumes
@pytest.mark.slow
def test_dataset_resume_reproduces_uninterrupted_run(
        tmp_path, _fresh_jax_subprocess_env):
    """Resume-stability through train() itself: checkpoint at step 2,
    resume to step 4, and land on exactly the loss of an uninterrupted
    4-step run — only possible if the resumed process feeds the same
    dataset batches for steps 2-3. (All three runs share one child
    process: the data-pipeline + orbax combination is what crashes the
    suite's long-lived runtime — see train_in_subprocess.)"""
    import numpy as np

    from nos_tpu.train.data import write_token_shards

    rng = np.random.default_rng(1)
    write_token_shards(
        str(tmp_path / "data"),
        [rng.integers(0, 64, size=2000, dtype=np.uint32)])
    data = str(tmp_path / "data" / "shard_*.bin")

    ck = str(tmp_path / "ckpt")
    straight, _, resumed = train_in_subprocess(
        _fresh_jax_subprocess_env,
        tiny(data_path=data, steps=4),
        tiny(data_path=data, steps=2, checkpoint_dir=ck,
             checkpoint_every=2),
        tiny(data_path=data, steps=4, checkpoint_dir=ck,
             checkpoint_every=2))
    assert resumed == pytest.approx(straight, rel=1e-5)


def test_eval_loop_logs_heldout_loss(tmp_path, caplog):
    import logging

    import numpy as np

    from nos_tpu.train.data import write_token_shards

    rng = np.random.default_rng(2)
    write_token_shards(str(tmp_path / "train"),
                       [rng.integers(0, 64, size=600, dtype=np.uint32)])
    write_token_shards(str(tmp_path / "val"),
                       [rng.integers(0, 64, size=300, dtype=np.uint32)])
    with caplog.at_level(logging.INFO, logger="nos_tpu.trainer"):
        loss = train(tiny(
            data_path=str(tmp_path / "train" / "shard_*.bin"),
            eval_data_path=str(tmp_path / "val" / "shard_*.bin"),
            eval_every=2, eval_steps=2))
    assert loss == loss
    evals = [r for r in caplog.records if "eval loss" in r.getMessage()]
    assert len(evals) == 2          # steps 2 and 4 of a 4-step run


def test_stop_event_checkpoints_and_resumes(tmp_path,
                                            _fresh_jax_subprocess_env):
    """A pre-set stop event (the injectable preemption path) banks the
    first step, labels it truthfully, and a restart finishes the run
    with the exact stream an uninterrupted run would have seen. (All
    three runs share one child process: three back-to-back train+orbax
    save cycles are exactly the native-crash surface the suite's
    long-lived runtime can't carry this late — observed SIGABRT inside
    step_fn on this toolchain; see _fresh_jax_subprocess_env.)"""
    import json

    cfg = tiny(steps=6, checkpoint_dir=str(tmp_path), checkpoint_every=100)
    prog = (
        "import json, sys, threading\n"
        "from nos_tpu.cmd.trainer import TrainerConfig, train\n"
        "from nos_tpu.train import CheckpointManager\n"
        "ck, plain = json.loads(sys.argv[1])\n"
        "ev = threading.Event(); ev.set()\n"
        "train(TrainerConfig(**ck), stop_event=ev)\n"
        "banked = CheckpointManager(ck['checkpoint_dir']).latest()\n"
        "straight = train(TrainerConfig(**plain))\n"
        "resumed = train(TrainerConfig(**ck))\n"       # no event: 1 -> 6
        "final = CheckpointManager(ck['checkpoint_dir']).latest()\n"
        "print('OUT=' + json.dumps(\n"
        "    [banked, float(straight), float(resumed), final]))\n"
    )
    banked, straight, resumed, final = _child_json(
        _fresh_jax_subprocess_env, prog,
        json.dumps([cfg.__dict__, tiny(steps=6).__dict__]))
    assert banked == 1
    assert final == 6
    assert resumed == pytest.approx(straight, rel=1e-4)


def test_sigterm_checkpoints_midrun(tmp_path):
    """The real signal path: SIGTERM delivered mid-train (from a timer
    thread, handled in the main thread) stops the loop at whatever step
    it reached and checkpoints it — the GKE eviction contract."""
    import os
    import signal
    import threading

    from nos_tpu.train import CheckpointManager

    before = signal.getsignal(signal.SIGTERM)
    cfg = tiny(steps=100000, checkpoint_dir=str(tmp_path),
               checkpoint_every=10**6, log_every=10**6)
    t = threading.Timer(2.0, lambda: os.kill(os.getpid(), signal.SIGTERM))
    t.start()
    try:
        train(cfg)
    finally:
        t.cancel()
    latest = CheckpointManager(str(tmp_path)).latest()
    assert latest is not None and 1 <= latest < 100000
    # handler restored: a later SIGTERM must not be swallowed silently
    assert signal.getsignal(signal.SIGTERM) == before


def test_checkpoint_config_stamp_guards_drift(tmp_path):
    """A checkpoint carries its architecture; resuming or serving with
    different dims fails by FIELD NAME, not an orbax shape error."""
    train(tiny(steps=2, checkpoint_dir=str(tmp_path), checkpoint_every=1))
    import json
    import os

    stamp = json.load(open(os.path.join(tmp_path, "model_config.json")))
    assert stamp["d_model"] == 32 and stamp["n_layers"] == 2

    with pytest.raises(ValueError, match="d_ff: checkpoint has 64"):
        train(tiny(steps=4, d_ff=128, checkpoint_dir=str(tmp_path)))

    from nos_tpu.cmd.generate import GenerateConfig, load_params

    with pytest.raises(ValueError, match="d_model"):
        load_params(GenerateConfig(
            vocab=64, d_model=48, n_layers=2, n_heads=4, d_ff=64,
            max_seq=32, bf16=False, checkpoint_dir=str(tmp_path)))
    # matching dims restore fine — with a LONGER max_seq (not a param
    # shape, deliberately unstamped: long-context serving of an old
    # checkpoint is legitimate) and explicit n_kv_heads == n_heads
    # (normalized against the trained default 0)
    _, params = load_params(GenerateConfig(
        vocab=64, d_model=32, n_layers=2, n_heads=4, n_kv_heads=4,
        d_ff=64, max_seq=128, bf16=False, checkpoint_dir=str(tmp_path)))
    assert params is not None


def test_stale_stamp_without_checkpoints_is_replaced(tmp_path):
    """An aborted mis-configured launch (stamp written, no checkpoint
    ever saved) must not dead-end the directory."""
    from nos_tpu.train import CheckpointManager

    m = CheckpointManager(str(tmp_path))
    m.write_model_config({"d_model": 999})
    m.close()
    train(tiny(steps=2, checkpoint_dir=str(tmp_path), checkpoint_every=2))
    import json
    import os

    stamp = json.load(open(os.path.join(tmp_path, "model_config.json")))
    assert stamp["d_model"] == 32   # restamped, not rejected


def test_metrics_exported(tmp_path):
    """nos_tpu_train_* metrics move with the run: steps/tokens count,
    loss gauge lands, checkpoint saves and preemption exits counted."""
    import threading

    from nos_tpu.utils.metrics import default_registry

    reg = default_registry()
    steps0 = reg.counter("nos_tpu_train_steps_total", "x").value()
    saves0 = reg.counter("nos_tpu_train_checkpoint_saves_total", "x").value()
    pre0 = reg.counter("nos_tpu_train_preemptions_total", "x").value()

    train(tiny(steps=4, checkpoint_dir=str(tmp_path / "a"),
               checkpoint_every=2))
    assert reg.counter("nos_tpu_train_steps_total", "x").value() \
        == steps0 + 4
    assert reg.counter("nos_tpu_train_tokens_total", "x").value() > 0
    # saves at steps 2 and 4 (periodic covers the final step)
    assert reg.counter("nos_tpu_train_checkpoint_saves_total",
                       "x").value() == saves0 + 2
    exposed = reg.expose()
    assert "nos_tpu_train_loss" in exposed
    assert "nos_tpu_train_step_seconds" in exposed

    ev = threading.Event()
    ev.set()
    train(tiny(steps=4, checkpoint_dir=str(tmp_path / "b")), stop_event=ev)
    assert reg.counter("nos_tpu_train_preemptions_total", "x").value() \
        == pre0 + 1


@needs_partial_auto
def test_trains_gpipe_with_sp():
    # the dense long-context + depth recipe is reachable from the binary:
    # pipeline_schedule="gpipe" composes pp with sp/ring attention
    loss = train(tiny(pp=2, sp=2, dp=2, n_microbatches=2,
                      pipeline_schedule="gpipe"))
    assert loss == loss


def test_1f1b_with_sp_fails_loudly():
    with pytest.raises(ValueError, match="1F1B does not compose with sp"):
        train(tiny(pp=2, sp=2, dp=2, n_microbatches=2))


def test_prestamp_checkpoints_never_get_caller_stamp(tmp_path):
    """A directory holding checkpoints from before the stamp feature
    must NOT be stamped with the (untrustworthy) caller dims."""
    import os

    train(tiny(steps=2, checkpoint_dir=str(tmp_path), checkpoint_every=2))
    os.remove(os.path.join(tmp_path, "model_config.json"))  # pre-stamp era
    # drifted relaunch: restore fails on shapes, but must not stamp
    with pytest.raises(Exception):
        train(tiny(steps=4, d_ff=128, checkpoint_dir=str(tmp_path)))
    assert not os.path.exists(os.path.join(tmp_path, "model_config.json"))


def test_wall_clock_checkpoint_cadence(tmp_path):
    """checkpoint_every_s: with the step cadence effectively off, a
    tiny wall-clock budget saves on (nearly) every step; cadence 0
    keeps the old behavior."""
    from nos_tpu.train import CheckpointManager

    d = str(tmp_path / "timed")
    train(tiny(steps=4, checkpoint_dir=d, checkpoint_every=10**6,
               checkpoint_every_s=1e-9))
    mgr = CheckpointManager(d)
    # every step was past the (absurdly small) time budget; retention
    # keeps the most recent ones and latest is the final step
    assert mgr.latest() == 4
    assert len(mgr.manager.all_steps()) >= 2
    mgr.close()

    d2 = str(tmp_path / "stepcad")
    train(tiny(steps=4, checkpoint_dir=d2, checkpoint_every=10**6))
    mgr2 = CheckpointManager(d2)
    assert mgr2.manager.all_steps() == [4]   # only the final save
    mgr2.close()


@needs_partial_auto
def test_trains_interleaved_and_resumes(tmp_path):
    """Interleaved schedule reachable from the binary: trains, stamps
    the chunk-major layer order, resumes in kind — and a resume under a
    DIFFERENT schedule fails by field name, not silent layer permutation."""
    d = str(tmp_path / "ckpt")
    cfg = tiny(pp=2, dp=2, n_layers=4, n_microbatches=2,
               pipeline_schedule="interleaved", virtual_stages=2,
               steps=4, checkpoint_dir=d, checkpoint_every=2)
    loss = train(cfg)
    assert loss == loss
    import json
    import os

    stamp = json.load(open(os.path.join(d, "model_config.json")))
    assert stamp["layer_order"] == "interleaved:pp=2,v=2"
    # same schedule resumes cleanly
    loss2 = train(tiny(pp=2, dp=2, n_layers=4, n_microbatches=2,
                       pipeline_schedule="interleaved", virtual_stages=2,
                       steps=6, checkpoint_dir=d, checkpoint_every=2))
    assert loss2 == loss2
    # schedule drift -> named rejection, not permuted layers
    with pytest.raises(ValueError, match="layer_order"):
        train(tiny(pp=2, dp=2, n_layers=4, n_microbatches=2,
                   steps=6, checkpoint_dir=d, checkpoint_every=2))
