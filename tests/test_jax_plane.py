"""JAX workload plane: layouts, mesh, ops (incl. ring attention exactness),
models. Runs on the virtual 8-device CPU mesh (conftest.py)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from nos_tpu.ops.attention import xla_attention
from nos_tpu.ops.layers import apply_rope, rms_norm, rope_frequencies
from nos_tpu.ops.ring_attention import ring_attention_sharded
from nos_tpu.parallel.layout import ParallelLayout, layout_for_chips
from nos_tpu.parallel.mesh import build_mesh, data_sharding


# ---------------------------------------------------------------------------
# layouts
# ---------------------------------------------------------------------------

def test_layout_chips_and_axes():
    l = ParallelLayout(dp=2, tp=4, sp=2)
    assert l.chips == 16
    assert l.axis_names() == ("dp", "tp", "sp")
    assert l.axis_sizes() == (2, 4, 2)
    with pytest.raises(ValueError):
        ParallelLayout(dp=0)


def test_layout_required_topology():
    l = ParallelLayout(dp=8, tp=8)            # 64 chips
    t = l.required_topology("v5e")
    assert t is not None and t.name == "8x8"
    assert l.hosts_required("v5e") == 8
    l2 = ParallelLayout(dp=2, fsdp=4, tp=4, sp=2)   # 64 chips on v5p
    assert l2.required_topology("v5p").chips >= 64
    huge = ParallelLayout(dp=100000)
    assert huge.required_topology("v5e") is None


def test_layout_for_chips_default():
    l = layout_for_chips(32)
    assert l.chips == 32 and l.tp == 8


def test_build_mesh_8_devices():
    l = ParallelLayout(dp=2, tp=2, sp=2)
    mesh = build_mesh(l)
    assert dict(mesh.shape) == {"dp": 2, "tp": 2, "sp": 2}
    with pytest.raises(ValueError):
        build_mesh(ParallelLayout(dp=100))


# ---------------------------------------------------------------------------
# ops
# ---------------------------------------------------------------------------

def test_rms_norm_matches_manual():
    x = jnp.array([[1.0, 2.0, 3.0, 4.0]])
    w = jnp.ones((4,))
    out = rms_norm(x, w)
    manual = x / np.sqrt(np.mean(np.square(x)) + 1e-6)
    np.testing.assert_allclose(out, manual, rtol=1e-5)


def test_rope_preserves_norm_and_relative_positions():
    freqs = rope_frequencies(8, 32)
    x = jax.random.normal(jax.random.PRNGKey(0), (1, 16, 2, 8))
    rotated = apply_rope(x, freqs)
    np.testing.assert_allclose(
        np.linalg.norm(np.asarray(x)), np.linalg.norm(np.asarray(rotated)), rtol=1e-5
    )
    # position 0 is unrotated
    np.testing.assert_allclose(rotated[:, 0], x[:, 0], rtol=1e-5, atol=1e-6)


def test_xla_attention_causal_masks_future():
    q = k = v = jax.random.normal(jax.random.PRNGKey(0), (1, 1, 4, 8))
    out = xla_attention(q, k, v, causal=True)
    # first position can only attend to itself -> output == v[0]
    np.testing.assert_allclose(out[0, 0, 0], v[0, 0, 0], rtol=1e-5)


def test_ring_attention_matches_full_attention():
    """Exactness of ring attention over an 8-way sequence shard."""
    layout = ParallelLayout(sp=8)
    mesh = build_mesh(layout)
    b, h, s, d = 2, 4, 64, 16
    rng = jax.random.PRNGKey(1)
    kq, kk, kv = jax.random.split(rng, 3)
    q = jax.random.normal(kq, (b, h, s, d), jnp.float32)
    k = jax.random.normal(kk, (b, h, s, d), jnp.float32)
    v = jax.random.normal(kv, (b, h, s, d), jnp.float32)

    full = xla_attention(q, k, v, causal=True)
    ringed = ring_attention_sharded(mesh, q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(ringed), np.asarray(full), rtol=2e-4, atol=2e-4)


def test_ring_attention_non_causal():
    layout = ParallelLayout(sp=4)
    mesh = build_mesh(layout, jax.devices()[:4])
    q = jax.random.normal(jax.random.PRNGKey(2), (1, 2, 32, 8))
    full = xla_attention(q, q, q, causal=False)
    ringed = ring_attention_sharded(mesh, q, q, q, causal=False)
    np.testing.assert_allclose(np.asarray(ringed), np.asarray(full), rtol=2e-4, atol=2e-4)


# ---------------------------------------------------------------------------
# models
# ---------------------------------------------------------------------------

def test_vit_forward_shapes_and_params():
    from nos_tpu.models import vit

    cfg = vit.ViTConfig(image_size=32, patch=8, d_model=64, n_layers=2,
                        n_heads=4, d_ff=128, n_classes=10)
    params = vit.init_params(jax.random.PRNGKey(0), cfg)
    images = jax.random.normal(jax.random.PRNGKey(1), (3, 32, 32, 3))
    logits = jax.jit(lambda p, x: vit.forward(p, cfg, x))(params, images)
    assert logits.shape == (3, 10)
    assert logits.dtype == jnp.float32
    assert np.isfinite(np.asarray(logits)).all()


def test_vit_small_param_count():
    from nos_tpu.models import vit

    cfg = vit.ViTConfig()
    params = vit.init_params(jax.random.PRNGKey(0), cfg)
    n = vit.param_count(params)
    assert 20e6 < n < 25e6      # ViT-small ~22M


def test_transformer_forward_and_loss():
    from nos_tpu.models import transformer as tfm

    cfg = tfm.TransformerConfig(vocab=128, d_model=64, n_layers=2, n_heads=4,
                                d_ff=128, max_seq=32, dtype=jnp.float32)
    params = tfm.init_params(jax.random.PRNGKey(0), cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, 128)
    logits = jax.jit(lambda p, t: tfm.forward(p, cfg, t))(params, tokens)
    assert logits.shape == (2, 16, 128)
    batch = {"tokens": tokens, "targets": tokens}
    loss = tfm.loss_fn(params, cfg, batch)
    assert np.isfinite(float(loss))


def test_transformer_train_step_reduces_loss():
    import optax

    from nos_tpu.models import transformer as tfm

    cfg = tfm.TransformerConfig(vocab=64, d_model=32, n_layers=1, n_heads=2,
                                d_ff=64, max_seq=16, dtype=jnp.float32,
                                remat=False)
    params = tfm.init_params(jax.random.PRNGKey(0), cfg)
    opt = optax.adam(1e-2)
    opt_state = opt.init(params)
    step = jax.jit(tfm.make_train_step(cfg, opt))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 8), 0, 64)
    batch = {"tokens": tokens, "targets": tokens}
    losses = []
    for _ in range(5):
        params, opt_state, loss = step(params, opt_state, batch)
        losses.append(float(loss))
    assert losses[-1] < losses[0]


def test_transformer_sharded_train_step_dp_tp_sp():
    """The multi-chip path: dp=2 x tp=2 x sp=2 over the virtual 8-device
    mesh, params sharded, ring attention on the sp axis."""
    import optax

    from nos_tpu.models import transformer as tfm

    layout = ParallelLayout(dp=2, tp=2, sp=2)
    mesh = build_mesh(layout)
    cfg = tfm.TransformerConfig(vocab=64, d_model=32, n_layers=2, n_heads=4,
                                d_ff=64, max_seq=32, dtype=jnp.float32,
                                remat=True)
    params = tfm.init_params(jax.random.PRNGKey(0), cfg)
    shardings = tfm.param_shardings(mesh, cfg)
    params = jax.device_put(params, shardings)
    opt = optax.sgd(1e-2)
    opt_state = opt.init(params)
    step = jax.jit(tfm.make_train_step(cfg, opt, mesh))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 32), 0, 64)
    batch = {
        "tokens": jax.device_put(tokens, data_sharding(mesh)),
        "targets": jax.device_put(tokens, data_sharding(mesh)),
    }
    params, opt_state, loss = step(params, opt_state, batch)
    assert np.isfinite(float(loss))
    # params keep their sharding through the update
    wq_sharding = params["layers"]["wq"].sharding
    assert "tp" in str(wq_sharding.spec) or wq_sharding.is_fully_replicated is False


def test_fsdp_training_shards_params_and_matches_dp():
    """ZeRO-style fsdp: params sharded over the fsdp axis actually execute,
    and one train step produces the same loss as plain dp (both are data
    parallelism; only the param layout differs)."""
    import optax

    from nos_tpu.models import transformer as tfm

    cfg = tfm.TransformerConfig(vocab=64, d_model=32, n_layers=2, n_heads=4,
                                d_ff=64, max_seq=32, dtype=jnp.float32)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (8, 16), 0, cfg.vocab)
    batch = {"tokens": tokens, "targets": tokens}

    losses = {}
    for name, layout in {"dp": ParallelLayout(dp=4),
                         "fsdp": ParallelLayout(fsdp=4)}.items():
        mesh = build_mesh(layout, jax.devices()[:4])
        params = jax.device_put(
            tfm.init_params(jax.random.PRNGKey(0), cfg),
            tfm.param_shardings(mesh, cfg))
        if name == "fsdp":
            spec = params["layers"]["wq"].sharding.spec
            assert any(a == "fsdp" or (isinstance(a, tuple) and "fsdp" in a)
                       for a in spec), spec
        opt = optax.adamw(1e-3)
        step = jax.jit(tfm.make_train_step(cfg, opt, mesh))
        sharded = {k: jax.device_put(v, data_sharding(mesh))
                   for k, v in batch.items()}
        _, _, loss = step(params, opt.init(params), sharded)
        losses[name] = float(loss)
    np.testing.assert_allclose(losses["dp"], losses["fsdp"], rtol=1e-5)
