"""Round-trip property tests for the k8s wire codec
(nos_tpu/kube/k8s_codec.py): for ARBITRARY generated objects,
``from_k8s(to_k8s(obj))`` must reproduce the object (up to documented
canonicalizations). The REST adapter's correctness against a real
apiserver rides on this fidelity — the sim and the real server must
read the same bytes the same way — and example-based tests only cover
the shapes someone thought of.
"""
import json

import pytest

# hypothesis is not in every image: skip cleanly instead of ERRORING
# collection (the PR 6 guard pattern, applied module-level because
# every test here is property-based)
pytest.importorskip("hypothesis")

from hypothesis import given, settings
from hypothesis import strategies as st

from nos_tpu.kube import k8s_codec as kc
from nos_tpu.kube.objects import (
    Affinity, Container, Node, NodeSelectorRequirement, NodeSelectorTerm,
    NodeSpec, NodeStatus, ObjectMeta, Pod, PodCondition,
    PodDisruptionBudget, PodDisruptionBudgetSpec, PodDisruptionBudgetStatus,
    PodSpec, PodStatus, Taint, Toleration,
)

NAME = st.text(alphabet="abcdefgh-0123456789", min_size=1, max_size=12)
LABELS = st.dictionaries(NAME, NAME, max_size=3)
# whole-unit resource quantities: the wire format canonicalizes
# fractional quantities (millicores etc.), so identity round-trips are
# asserted on integral values and canonicalization is tested separately
RESOURCES = st.dictionaries(
    st.sampled_from(["cpu", "memory", "google.com/tpu",
                     "nos.ai/tpu-slice-2x2"]),
    st.integers(0, 512).map(float), max_size=3)

META = st.builds(
    ObjectMeta,
    name=NAME,
    namespace=st.one_of(st.just(""), NAME),
    uid=st.one_of(st.just(""), NAME),
    resource_version=st.integers(0, 10**6),
    labels=LABELS,
    annotations=st.dictionaries(NAME, st.text(max_size=20), max_size=2),
)

CONTAINER = st.builds(
    Container, name=NAME, image=st.one_of(st.just(""), NAME),
    requests=RESOURCES, limits=RESOURCES)

AFFINITY = st.one_of(
    st.none(),
    st.builds(
        Affinity,
        node_affinity_required=st.lists(
            st.builds(
                NodeSelectorTerm,
                match_expressions=st.lists(
                    st.builds(
                        NodeSelectorRequirement,
                        key=NAME,
                        operator=st.sampled_from(
                            ["In", "NotIn", "Exists", "DoesNotExist"]),
                        values=st.lists(NAME, max_size=2)),
                    min_size=1, max_size=2)),
            min_size=1, max_size=2)),
)

TOLERATION = st.builds(
    Toleration,
    key=st.one_of(st.just(""), NAME),
    operator=st.sampled_from(["Exists", "Equal"]),
    value=st.one_of(st.just(""), NAME),
    effect=st.sampled_from(["", "NoSchedule", "NoExecute"]),
)

POD = st.builds(
    Pod,
    metadata=META,
    spec=st.builds(
        PodSpec,
        containers=st.lists(CONTAINER, min_size=1, max_size=3),
        init_containers=st.lists(CONTAINER, max_size=2),
        node_name=st.one_of(st.just(""), NAME),
        scheduler_name=NAME,
        priority=st.one_of(st.none(), st.integers(-100, 100)),
        node_selector=LABELS,
        tolerations=st.lists(TOLERATION, max_size=2),
        affinity=AFFINITY,
    ),
    status=st.builds(
        PodStatus,
        phase=st.sampled_from(["Pending", "Running", "Succeeded", "Failed"]),
        conditions=st.lists(
            st.builds(PodCondition,
                      type=st.just("PodScheduled"),
                      status=st.sampled_from(["True", "False"]),
                      reason=st.one_of(st.just(""), st.just("Unschedulable")),
                      message=st.text(max_size=10)),
            max_size=2),
        nominated_node_name=st.one_of(st.just(""), NAME),
    ),
)

NODE = st.builds(
    Node,
    metadata=META,
    spec=st.builds(
        NodeSpec,
        taints=st.lists(
            st.builds(Taint, key=NAME,
                      value=st.one_of(st.just(""), NAME),
                      effect=st.sampled_from(["NoSchedule", "NoExecute"])),
            max_size=2),
        unschedulable=st.booleans(),
    ),
    status=st.builds(NodeStatus, capacity=RESOURCES, allocatable=RESOURCES),
)

PDB = st.builds(
    PodDisruptionBudget,
    metadata=META,
    spec=st.builds(
        PodDisruptionBudgetSpec,
        selector=LABELS,
        min_available=st.one_of(st.none(), st.integers(0, 50)),
        max_unavailable=st.one_of(st.none(), st.integers(0, 50)),
    ),
    status=st.builds(
        PodDisruptionBudgetStatus,
        disruptions_allowed=st.integers(0, 50),
        current_healthy=st.integers(0, 50),
        desired_healthy=st.integers(0, 50),
        expected_pods=st.integers(0, 50),
        disrupted_pods=st.dictionaries(NAME, st.just("ts"), max_size=2),
    ),
)


def _json_safe(wire: dict) -> dict:
    """The wire dict must survive actual JSON serialization — that is
    what travels over HTTP."""
    return json.loads(json.dumps(wire))


@settings(max_examples=60, deadline=None)
@given(POD)
def test_pod_roundtrip(pod):
    back = kc.from_k8s(_json_safe(kc.pod_to_k8s(pod)))
    assert back.metadata == pod.metadata
    assert back.spec == pod.spec
    assert back.status == pod.status


@settings(max_examples=60, deadline=None)
@given(NODE)
def test_node_roundtrip(node):
    back = kc.from_k8s(_json_safe(kc.node_to_k8s(node)))
    assert back.metadata == node.metadata
    assert back.spec == node.spec
    assert back.status == node.status


@settings(max_examples=60, deadline=None)
@given(PDB)
def test_pdb_roundtrip(pdb):
    back = kc.from_k8s(_json_safe(kc.pdb_to_k8s(pdb)))
    assert back.metadata == pdb.metadata
    assert back.spec == pdb.spec
    assert back.status == pdb.status


@settings(max_examples=40, deadline=None)
@given(st.floats(0.001, 64.0))
def test_cpu_quantity_canonicalization_is_stable(v):
    # fractional cpu canonicalizes to millicores on the wire; a second
    # round-trip must be EXACTLY stable (no drift on repeated encode)
    once = kc._resources_from_k8s(kc._resources_to_k8s({"cpu": v}))
    twice = kc._resources_from_k8s(kc._resources_to_k8s(once))
    assert once == twice
    assert abs(once["cpu"] - v) <= 0.0005 + 1e-9   # millicore resolution
