"""Multislice JobSet scheduling (gang of gangs): co-atomic admission of N
identical slice gangs onto N DISTINCT ICI domains. dp/fsdp ride DCN
between slices; tp/sp/ep/pp never leave a slice's ICI — the same boundary
parallel/mesh.py's arrange_devices enforces on the workload side, now a
scheduler-side contract (VERDICT r4 ask #5; SURVEY §5 "distributed
communication backend").
"""
import pytest

try:
    # hypothesis is not in every image: the PR 6 guard pattern — the
    # one property test skips, the nine example-based tests still run
    # (they were previously lost to a module collection ERROR)
    from hypothesis import given, settings
    from hypothesis import strategies as st
    HAVE_HYPOTHESIS = True
except ImportError:        # pragma: no cover - environment-dependent
    HAVE_HYPOTHESIS = False

    def settings(**kw):
        return lambda f: pytest.mark.skip(
            reason="hypothesis missing")(f)

    def given(*a, **kw):
        return lambda f: f

    class _StStub:
        def __getattr__(self, name):
            return lambda *a, **kw: None

    st = _StStub()

from nos_tpu import constants
from nos_tpu.api.quota import make_elastic_quota
from tests.test_gang import gang_pod, make_pool, rig


def jobset_pod(job, slice_idx, n_slices, worker, size, topo="4x4",
               ns="team-a", tpu=8):
    """A pod that is worker ``worker`` of slice ``slice_idx`` of an
    N-slice JobSet: normal gang labels (gang-name unique per slice) plus
    the jobset labels tying the slices together."""
    pod = gang_pod(f"{job}-slice-{slice_idx}", worker, size, topo=topo,
                   ns=ns, tpu=tpu)
    pod.metadata.name = f"{job}-s{slice_idx}-{worker}"
    pod.metadata.labels[constants.LABEL_JOBSET_NAME] = job
    pod.metadata.labels[constants.LABEL_JOBSET_SLICES] = str(n_slices)
    pod.metadata.labels[constants.LABEL_JOBSET_SLICE] = str(slice_idx)
    return pod


def create_jobset(server, job, n_slices, hosts_per_slice=2, topo="4x4",
                  ns="team-a", skip=()):
    for s in range(n_slices):
        for w in range(hosts_per_slice):
            if (s, w) in skip:
                continue
            server.create(jobset_pod(job, s, n_slices, w, hosts_per_slice,
                                     topo=topo, ns=ns))


def node_of(server, job, s, w, ns="team-a"):
    return server.get("Pod", f"{job}-s{s}-{w}", ns).spec.node_name


# ---------------------------------------------------------------------------


def test_jobset_waits_for_all_slices():
    """Slice 0 complete, slice 1 absent: NOTHING binds (a jobset holding
    one of two slices would deadlock the DCN collective)."""
    server, mgr = rig()
    make_pool(server, "pool-a", 2)
    make_pool(server, "pool-b", 2)
    create_jobset(server, "train", 2, skip={(1, 0), (1, 1)})
    mgr.run_until_idle()
    for w in range(2):
        p = server.get("Pod", f"train-s0-{w}", "team-a")
        assert p.spec.node_name == ""
        assert any("waiting for jobset" in c.message
                   for c in p.status.conditions)
    # the missing slice arrives -> whole jobset binds, one pool per slice
    for w in range(2):
        server.create(jobset_pod("train", 1, 2, w, 2))
    mgr.run_until_idle()
    pools = set()
    for s in range(2):
        slice_pools = {node_of(server, "train", s, w).rsplit("-w", 1)[0]
                       for w in range(2)}
        assert len(slice_pools) == 1, f"slice {s} spans pools {slice_pools}"
        pools |= slice_pools
    assert pools == {"pool-a", "pool-b"}


def test_jobset_incomplete_slice_gang_blocks_all():
    """Every slice has members but slice 1 is missing a worker: nothing
    binds, including the complete slice 0."""
    server, mgr = rig()
    make_pool(server, "pool-a", 2)
    make_pool(server, "pool-b", 2)
    create_jobset(server, "train", 2, skip={(1, 1)})
    mgr.run_until_idle()
    assert node_of(server, "train", 0, 0) == ""
    assert node_of(server, "train", 0, 1) == ""
    assert node_of(server, "train", 1, 0) == ""


def test_jobset_needs_distinct_domains():
    """Two 4x4 slices COULD carve disjoint sub-cuboids of one 8x8 pool,
    but a multislice job's slices must be distinct ICI domains (the job
    expects DCN between them — two halves of one torus are not two
    slices). One pool -> nothing binds; a second pool -> binds."""
    server, mgr = rig()
    make_pool(server, "pool-a", 8, topo="8x8")
    create_jobset(server, "train", 2)
    mgr.run_until_idle()
    for s in range(2):
        for w in range(2):
            assert node_of(server, "train", s, w) == ""
    p = server.get("Pod", "train-s0-0", "team-a")
    assert any("jobset unplaceable" in c.message
               for c in p.status.conditions)
    make_pool(server, "pool-b", 2, topo="4x4")
    mgr.run_until_idle()
    assert all(node_of(server, "train", s, w) for s in range(2)
               for w in range(2))


def test_jobset_slices_must_be_identical():
    """dp-over-DCN contract: slices are interchangeable dp replicas, so a
    topology mismatch between slices is a hard rejection."""
    server, mgr = rig()
    make_pool(server, "pool-a", 2, topo="4x4")
    make_pool(server, "pool-b", 4, topo="4x8")
    for w in range(2):
        server.create(jobset_pod("train", 0, 2, w, 2, topo="4x4"))
    for w in range(4):
        server.create(jobset_pod("train", 1, 2, w, 4, topo="4x8"))
    mgr.run_until_idle()
    assert node_of(server, "train", 0, 0) == ""
    assert node_of(server, "train", 1, 0) == ""
    p = server.get("Pod", "train-s0-0", "team-a")
    assert any("identical dp replicas" in c.message
               for c in p.status.conditions)


def test_jobset_quota_checked_on_union():
    """Each slice alone fits the quota max; the union does not. Nothing
    binds — per-slice admission would have let slice 0 slip through."""
    server, mgr = rig()
    make_pool(server, "pool-a", 2)
    make_pool(server, "pool-b", 2)
    # 2 slices x 2 hosts x 8 chips = 32 requested; max allows one slice
    server.create(make_elastic_quota(
        "q-team-a", "team-a", min={"google.com/tpu": 16},
        max={"google.com/tpu": 16}))
    create_jobset(server, "train", 2)
    mgr.run_until_idle()
    for s in range(2):
        for w in range(2):
            assert node_of(server, "train", s, w) == ""


def test_jobset_partial_bind_recovery_pins_bound_slice():
    """Crash recovery: slice 0 already bound to pool-b. The retry must
    keep slice 0 where it is and place slice 1 on a DIFFERENT pool."""
    server, mgr = rig()
    make_pool(server, "pool-a", 2)
    make_pool(server, "pool-b", 2)
    create_jobset(server, "train", 2)
    # simulate a partial bind from a crashed prior scheduler: slice 0 on
    # pool-b in worker order
    for w in range(2):
        def bind(p, n=f"pool-b-w{w}"):
            p.spec.node_name = n
        server.patch("Pod", f"train-s0-{w}", "team-a", bind)
    mgr.run_until_idle()
    assert node_of(server, "train", 0, 0) == "pool-b-w0"
    assert node_of(server, "train", 0, 1) == "pool-b-w1"
    assert {node_of(server, "train", 1, w) for w in range(2)} == \
        {"pool-a-w0", "pool-a-w1"}


def test_jobset_and_plain_gang_coexist():
    """A 1-slice-equivalent plain gang and a 2-slice jobset compete for
    three pools: everything lands, no pool shared across jobset slices."""
    server, mgr = rig()
    for pool in ("pool-a", "pool-b", "pool-c"):
        make_pool(server, pool, 2)
    create_jobset(server, "big", 2)
    server.create(gang_pod("small", 0, 2))
    server.create(gang_pod("small", 1, 2))
    mgr.run_until_idle()
    jobset_pools = {node_of(server, "big", s, w).rsplit("-w", 1)[0]
                    for s in range(2) for w in range(2)}
    gang_pool = {server.get("Pod", f"small-{w}", "team-a")
                 .spec.node_name.rsplit("-w", 1)[0] for w in range(2)}
    assert len(jobset_pools) == 2
    assert len(gang_pool) == 1
    assert not (jobset_pools & gang_pool)


def test_jobset_malformed_slice_label_named_in_rejection():
    """A bad jobset-slice label must be rejected NAMING the pod, not
    silently filed under slice 0 (which would blame the wrong slice)."""
    server, mgr = rig()
    make_pool(server, "pool-a", 2)
    make_pool(server, "pool-b", 2)
    create_jobset(server, "train", 2, skip={(1, 1)})
    bad = jobset_pod("train", 1, 2, 1, 2)
    bad.metadata.labels[constants.LABEL_JOBSET_SLICE] = "one"
    server.create(bad)
    mgr.run_until_idle()
    p = server.get("Pod", "train-s0-0", "team-a")
    assert p.spec.node_name == ""
    assert any("invalid nos.ai/jobset-slice label" in c.message
               and "train-s1-1" in c.message
               for c in p.status.conditions), \
        [c.message for c in p.status.conditions]


def test_layout_per_slice_contract():
    """ParallelLayout.per_slice: only data axes divide across slices;
    model axes must stay whole inside a slice's ICI."""
    import pytest

    from nos_tpu.parallel.layout import ParallelLayout

    full = ParallelLayout(dp=4, tp=2, sp=2)
    per = full.per_slice(2)
    assert (per.dp, per.tp, per.sp) == (2, 2, 2)
    # both slices carry the SAME topology annotation (8 chips -> 2x4)
    assert per.required_topology("v5e").name == "2x4"
    # dp exhausted -> fsdp covers the remainder
    z = ParallelLayout(dp=2, fsdp=4, tp=2)
    pz = z.per_slice(4)
    assert (pz.dp, pz.fsdp, pz.tp) == (1, 2, 2)
    # a model axis would have to split: hard error
    with pytest.raises(ValueError, match="ICI"):
        ParallelLayout(dp=1, tp=8).per_slice(2)


# ---------------------------------------------------------------------------
# property: for ARBITRARY (n_slices, n_pools), the jobset binds fully iff
# enough distinct feasible pools exist — and never partially.
# ---------------------------------------------------------------------------

@settings(max_examples=20, deadline=None)
@given(st.integers(min_value=1, max_value=4),
       st.integers(min_value=0, max_value=4))
def test_jobset_all_or_nothing_iff_enough_pools(n_slices, n_pools):
    server, mgr = rig()
    for i in range(n_pools):
        make_pool(server, f"pool-{i}", 2)
    create_jobset(server, "js", n_slices)
    mgr.run_until_idle()
    bound = [node_of(server, "js", s, w)
             for s in range(n_slices) for w in range(2)]
    if n_pools >= n_slices:
        assert all(bound), f"feasible jobset left unbound: {bound}"
        # each slice on one pool, slices pairwise distinct
        pools = []
        for s in range(n_slices):
            ps = {node_of(server, "js", s, w).rsplit("-w", 1)[0]
                  for w in range(2)}
            assert len(ps) == 1
            pools.append(ps.pop())
        assert len(set(pools)) == n_slices
    else:
        assert not any(bound), f"partial jobset bind: {bound}"
