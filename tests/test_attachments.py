"""Used-device ground truth at the node boundary (VERDICT r2 next #4).

The native layer's two truth sources — the device-plugin allocation table
and the /proc attachment probe — and the Reporter's reconciliation of both
against the API server's bound-pod view. Reference analog: kubelet
pod-resources (pkg/resource/lister.go:27-39) joined with NVML
(pkg/gpu/mig/client.go:29-120)."""
import pytest

from nos_tpu import constants
from nos_tpu.agents.tpu_native import MockTpuClient, TpuNativeClient, load_native
from nos_tpu.agents.tpuagent import TpuAgent, attachment_drift
from nos_tpu.kube import ApiServer, Manager
from nos_tpu.kube.objects import (
    Container, Node, NodeStatus, ObjectMeta, Pod, PodSpec, PodStatus,
)

UID_A = "11111111-2222-3333-4444-555555555555"
UID_B = "66666666-7777-8888-9999-000000000000"


# ---------------------------------------------------------------------------
# native layer
# ---------------------------------------------------------------------------

@pytest.fixture
def native(tmp_path, monkeypatch):
    lib = load_native()
    if lib is None:
        pytest.skip("native toolchain unavailable")
    monkeypatch.setenv("NOS_TPU_ATTACH_FILE", str(tmp_path / "attach.json"))
    monkeypatch.setenv("NOS_TPU_STATE_FILE", str(tmp_path / "partition.json"))
    monkeypatch.setenv("NOS_TPU_CHIP_COUNT", "4")
    return TpuNativeClient(lib)


def test_native_attachment_table_roundtrip(native, tmp_path):
    assert native.read_attachments() == {}
    table = {
        "0": {"pod_uid": UID_A, "pod": "team-a/train-0", "profile": "1x1"},
        "1": {"pod_uid": UID_A, "pod": "team-a/train-0", "profile": "1x1"},
    }
    native.record_attachments(table)
    assert native.read_attachments() == table
    # atomic write: no .tmp residue left behind
    assert not (tmp_path / "attach.json.tmp").exists()
    native.clear_attachments()
    assert native.read_attachments() == {}


def test_native_attachment_survives_reload(native):
    native.record_attachments({"2": {"pod_uid": UID_B}})
    other = TpuNativeClient(native.lib)
    assert other.read_attachments() == {"2": {"pod_uid": UID_B}}


def test_native_attached_pids_env_seam(native, monkeypatch):
    monkeypatch.setenv("NOS_TPU_ATTACHED_PIDS_0", "101,202")
    monkeypatch.setenv("NOS_TPU_ATTACHED_PIDS_1", "")
    assert native.chip_attached_pids(0) == [101, 202]
    assert native.chip_attached_pids(1) == []
    assert native.chip_attached_pids(2) == []  # /proc scan finds no accel fds


def test_native_pid_pod_uid_env_seam(native, monkeypatch):
    monkeypatch.setenv("NOS_TPU_PID_POD_101", UID_A)
    assert native.pid_pod_uid(101) == UID_A
    # a real but non-pod process (this test runner) resolves to no pod —
    # exercises the actual /proc/<pid>/cgroup parse
    import os

    uid = native.pid_pod_uid(os.getpid())
    assert uid is None or isinstance(uid, str)
    assert native.pid_pod_uid(2 ** 30) is None  # nonexistent pid


def test_running_pod_in_proc_truth_overrides_stale_table():
    # allocation table lost/partial (tmpfs reboot) but the /proc probe
    # shows the pod holding its device: no false "unattached" claim
    mock = MockTpuClient(chips=8)
    server, mgr = rig(mock)
    uid_a = create_pod(server, "train-0")
    mock.record_attachments({"9": {"pod_uid": "someone-else"}})
    mock.attached_pids[0] = [55]
    mock.pid_pods[55] = uid_a
    mgr.run_until_idle()
    node = server.get("Node", "v5e-0")
    drift = node.metadata.annotations.get(
        constants.ANNOTATION_ATTACHMENT_DRIFT, "")
    assert f"unattached:{uid_a}" not in drift


def test_native_single_sweep_matches_per_chip_probe(native, monkeypatch):
    monkeypatch.setenv("NOS_TPU_ATTACHED_PIDS_0", "101,202")
    monkeypatch.setenv("NOS_TPU_ATTACHED_PIDS_2", "303")
    monkeypatch.setenv("NOS_TPU_PID_POD_101", UID_A)
    monkeypatch.setenv("NOS_TPU_PID_POD_202", UID_A)
    monkeypatch.setenv("NOS_TPU_PID_POD_303", "")
    truth = native.attachment_truth()   # one tpu_attached_pids_all call
    assert truth == {0: {UID_A}, 2: {"<host>"}}


def test_native_attachment_truth_joins_pids_to_pods(native, monkeypatch):
    monkeypatch.setenv("NOS_TPU_ATTACHED_PIDS_0", "101")
    monkeypatch.setenv("NOS_TPU_ATTACHED_PIDS_3", "303")
    monkeypatch.setenv("NOS_TPU_PID_POD_101", UID_A)
    # pid 303 intentionally unmapped -> "<host>" (a non-pod process)
    monkeypatch.setenv("NOS_TPU_PID_POD_303", "")
    truth = native.attachment_truth()
    assert truth[0] == {UID_A}
    assert truth[3] == {"<host>"}
    assert 1 not in truth


# ---------------------------------------------------------------------------
# reporter reconciliation
# ---------------------------------------------------------------------------

def tpu_pod(name, uid="", phase="Running", node="v5e-0", tpu=4):
    # note: the API server assigns the real uid on create (as kube does);
    # tests that need it read it back from the created object
    return Pod(
        metadata=ObjectMeta(name=name, namespace="team-a", uid=uid),
        spec=PodSpec(containers=[Container(requests={constants.RESOURCE_TPU: tpu})],
                     node_name=node),
        status=PodStatus(phase=phase),
    )


def create_pod(server, name, **kw):
    server.create(tpu_pod(name, **kw))
    return server.get("Pod", name, "team-a").metadata.uid


def rig(mock):
    server = ApiServer()
    mgr = Manager(server)
    agent = TpuAgent("v5e-0", mock, report_interval_s=None)
    for c in agent.controllers():
        mgr.add_controller(c)
    server.create(Node(
        metadata=ObjectMeta(name="v5e-0"),
        status=NodeStatus(capacity={constants.RESOURCE_TPU: 8},
                          allocatable={constants.RESOURCE_TPU: 8}),
    ))
    return server, mgr


def test_no_truth_no_drift_annotation():
    mock = MockTpuClient(chips=8)
    server, mgr = rig(mock)
    server.create(tpu_pod("train-0", UID_A))
    mgr.run_until_idle()
    node = server.get("Node", "v5e-0")
    assert constants.ANNOTATION_ATTACHMENT_DRIFT not in node.metadata.annotations


def test_ghost_attachment_surfaces_in_annotation():
    mock = MockTpuClient(chips=8)
    # the device plugin says UID_B holds chip 0, but no such pod is bound
    mock.record_attachments({"0": {"pod_uid": UID_B, "profile": "1x1"}})
    server, mgr = rig(mock)
    mgr.run_until_idle()
    node = server.get("Node", "v5e-0")
    assert node.metadata.annotations[constants.ANNOTATION_ATTACHMENT_DRIFT] == (
        f"ghost:{UID_B}")


def test_proc_truth_alone_detects_ghost():
    mock = MockTpuClient(chips=8, attached_pids={0: [42]},
                         pid_pods={42: UID_B})
    server, mgr = rig(mock)
    mgr.run_until_idle()
    node = server.get("Node", "v5e-0")
    assert node.metadata.annotations[constants.ANNOTATION_ATTACHMENT_DRIFT] == (
        f"ghost:{UID_B}")


def test_running_pod_missing_from_table_is_unattached():
    mock = MockTpuClient(chips=8)
    server, mgr = rig(mock)
    uid_a = create_pod(server, "train-0")   # attached, fine
    uid_b = create_pod(server, "train-1")   # Running, no device!
    mock.record_attachments({"0": {"pod_uid": uid_a, "profile": "1x1"}})
    mgr.run_until_idle()
    node = server.get("Node", "v5e-0")
    assert node.metadata.annotations[constants.ANNOTATION_ATTACHMENT_DRIFT] == (
        f"unattached:{uid_b}")


def test_pending_pod_is_not_unattached():
    # bound-but-not-started is normal during startup: only Running pods
    # with no device count as drift
    mock = MockTpuClient(chips=8)
    server, mgr = rig(mock)
    uid_a = create_pod(server, "train-0")
    create_pod(server, "warm-1", phase="Pending")
    mock.record_attachments({"0": {"pod_uid": uid_a, "profile": "1x1"}})
    mgr.run_until_idle()
    node = server.get("Node", "v5e-0")
    assert constants.ANNOTATION_ATTACHMENT_DRIFT not in node.metadata.annotations


def test_empty_table_makes_no_unattached_claim():
    # no device plugin recording -> absence of a table entry proves nothing
    mock = MockTpuClient(chips=8)
    server, mgr = rig(mock)
    server.create(tpu_pod("train-0", UID_A))
    mgr.run_until_idle()
    node = server.get("Node", "v5e-0")
    assert constants.ANNOTATION_ATTACHMENT_DRIFT not in node.metadata.annotations


def test_drift_clears_when_resolved():
    mock = MockTpuClient(chips=8)
    mock.record_attachments({"0": {"pod_uid": UID_B}})
    server, mgr = rig(mock)
    mgr.run_until_idle()
    assert constants.ANNOTATION_ATTACHMENT_DRIFT in (
        server.get("Node", "v5e-0").metadata.annotations)
    # the ghost's pod appears bound (restart recovered) -> drift resolves,
    # but the table must now name the REAL uid the server assigned
    uid = create_pod(server, "train-0")
    mock.record_attachments({"0": {"pod_uid": uid}})
    server.patch("Node", "v5e-0", "", lambda n: None)  # nudge a report
    mgr.run_until_idle()
    node = server.get("Node", "v5e-0")
    assert constants.ANNOTATION_ATTACHMENT_DRIFT not in node.metadata.annotations


def test_completed_pod_holding_device_is_ghost():
    mock = MockTpuClient(chips=8)
    server, mgr = rig(mock)
    uid = create_pod(server, "train-0", phase="Succeeded")
    mock.record_attachments({"0": {"pod_uid": uid}})
    server.patch("Node", "v5e-0", "", lambda n: None)  # nudge a report
    mgr.run_until_idle()
    node = server.get("Node", "v5e-0")
    assert node.metadata.annotations[constants.ANNOTATION_ATTACHMENT_DRIFT] == (
        f"ghost:{uid}")


def test_attachment_drift_helper_direct():
    # both kinds at once, deterministic order (ghosts sorted first)
    from nos_tpu.kube.client import Client

    mock = MockTpuClient(chips=8)
    mock.record_attachments({"0": {"pod_uid": UID_B}})
    server = ApiServer()
    uid = create_pod(server, "train-1")
    out = attachment_drift(Client(server), "v5e-0", mock)
    assert out == f"ghost:{UID_B};unattached:{uid}"
