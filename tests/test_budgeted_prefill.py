"""Deadline-slack-budgeted chunked prefill (models/serving.py
prefill_budget): each tick the engine spends at most a token budget on
chunk forwards, picks chunk work EDF-style on TTFT slack, clamps the
budget toward zero when an active decode slot's TPOT slack goes
negative, and may overdraw once per tick for a TTFT-critical prefill —
all while the bit-exactness contract holds: ANY budget schedule yields
token-identical output to the unbudgeted (budget=0) run.

Also covers the prefill-side decode-pool health view: the handoff
pusher scrapes /stats and prefers healthy least-loaded decode
replicas, skipping draining ones BEFORE the first failed attempt."""
import random
import threading
import time

import jax
import jax.numpy as jnp
import pytest

from nos_tpu.models import transformer as tfm
from nos_tpu.models.serving import DecodeServer

CFG = tfm.TransformerConfig(
    vocab=64, d_model=32, n_layers=2, n_heads=4, n_kv_heads=2,
    d_ff=64, max_seq=128, dtype=jnp.float32)
LONG = [(i * 7 + 3) % 64 for i in range(40)]    # >> chunk of 8


@pytest.fixture(scope="module")
def params():
    return tfm.init_params(jax.random.PRNGKey(0), CFG)


def drain_all(srv, reqs):
    rids = [srv.submit(p, n, **kw) for p, n, kw in reqs]
    out = srv.drain()
    return [out[r] for r in rids]


class FakeClock:
    """Injectable slack clock: deadlines and slack math become pure
    functions of test-controlled time."""

    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


# ---------------------------------------------------------------------------
# bit-exactness: any budget schedule == the unbudgeted run
# ---------------------------------------------------------------------------

MIX = [
    (LONG, 6, dict()),
    (LONG[:17], 5, dict(temperature=0.7, top_k=8, seed=5)),
    ([5, 9], 6, dict()),
    (LONG[:33], 4, dict()),
]


def test_budget_invariance_slot_static(params):
    want = drain_all(
        DecodeServer(params, CFG, max_batch=4, prefill_chunk=8), MIX)
    for budget in (4, 16):
        got = drain_all(
            DecodeServer(params, CFG, max_batch=4, prefill_chunk=8,
                         prefill_budget=budget), MIX)
        assert got == want, f"budget={budget}"


@pytest.mark.parametrize("kernel", ["0", "1"])
def test_budget_invariance_paged_kernel_on_and_off(params, monkeypatch,
                                                   kernel):
    """Both paged-attention paths (--paged-kernel on AND off) schedule
    under the budget with tokens identical to unbudgeted."""
    monkeypatch.setenv("NOS_TPU_PAGED_KERNEL", kernel)

    def mk(**kw):
        return DecodeServer(params, CFG, max_batch=2, prefill_chunk=8,
                            kv_block_size=8, kv_blocks=24, **kw)

    reqs = [(LONG, 4, {}), (LONG[:17], 4, {})]
    want = drain_all(mk(), reqs)
    got = drain_all(mk(prefill_budget=8), reqs)
    assert got == want


@pytest.mark.parametrize("seed", range(2))
def test_budget_invariance_seeded_fuzz(params, seed):
    """Seeded fuzz over budget x chunk x concurrent-long-prompt mixes:
    outputs bit-identical to the unbudgeted oracle at the same chunk."""
    rng = random.Random(100 + seed)
    chunk = rng.choice([8, 16])
    budget = rng.choice([2, 4, 8, 16, 40])
    pool = [LONG, LONG[:33], LONG[:17], [5, 9], [1, 2, 3]]
    reqs = []
    for _ in range(rng.randint(3, 5)):
        p = rng.choice(pool)
        kw = {}
        if rng.random() < 0.4:
            kw = dict(temperature=0.8, top_k=8, seed=rng.randint(0, 99))
        reqs.append((p, rng.randint(3, 6), kw))
    want = drain_all(
        DecodeServer(params, CFG, max_batch=4, prefill_chunk=chunk),
        reqs)
    got = drain_all(
        DecodeServer(params, CFG, max_batch=4, prefill_chunk=chunk,
                     prefill_budget=budget), reqs)
    assert got == want, f"chunk={chunk} budget={budget}"


def test_spec_engine_inherits_budgeted_chunking(params):
    """The speculative engine rides the same scheduler: draft chunks
    advance in lockstep with the target's, charged once per pick."""
    from nos_tpu.models.spec_serving import SpeculativeDecodeServer
    dcfg = tfm.TransformerConfig(
        vocab=64, d_model=16, n_layers=1, n_heads=2, n_kv_heads=1,
        d_ff=32, max_seq=128, dtype=jnp.float32)
    dparams = tfm.init_params(jax.random.PRNGKey(1), dcfg)
    reqs = [(LONG, 6, dict()),
            (LONG[:19], 5, dict(temperature=0.7, top_k=8, seed=5))]

    def mk(**kw):
        return SpeculativeDecodeServer(params, CFG, dparams, dcfg,
                                       n_draft=3, max_batch=2,
                                       prefill_chunk=8, **kw)

    want = drain_all(mk(), reqs)
    bud = mk(prefill_budget=8)
    assert bud.prefill_budget == 8
    got = drain_all(bud, reqs)
    assert got == want


# ---------------------------------------------------------------------------
# scheduler behavior: deterministic under injected clock + cost hints
# ---------------------------------------------------------------------------

def test_submit_records_deadline_on_slack_clock(params):
    clk = FakeClock()
    clk.t = 50.0
    srv = DecodeServer(params, CFG, max_batch=1, prefill_chunk=8,
                       prefill_budget=8, slack_clock=clk)
    srv.submit(LONG, 4, deadline_s=7.0)
    assert srv._prefilling[0]["req"].deadline == 57.0
    srv.drain()
    srv2 = DecodeServer(params, CFG, max_batch=1, prefill_chunk=8,
                        prefill_budget=8, slack_clock=clk)
    srv2.submit(LONG, 4)
    assert srv2._prefilling[0]["req"].deadline is None
    srv2.drain()


def test_tpot_clamp_starves_prefill_until_decode_drains(params):
    """When an active decode slot's TPOT slack is negative the budget
    clamps to zero: no chunk runs, the clamp counter ticks, and the
    prefill completes only after the pressured decode finishes."""
    clk = FakeClock()
    srv = DecodeServer(params, CFG, max_batch=2, prefill_chunk=8,
                       prefill_budget=40, slack_clock=clk)
    srv.tick_s_hint = 1.0           # 1 time-unit per decode tick
    srv.prefill_tok_s_hint = 0.0    # prefill looks free: no TTFT urgency
    a = srv.submit([4, 5], 20, deadline_s=5.0)   # needs 20 ticks, has 5
    srv.step()                      # a active and decoding
    srv.submit(LONG, 4)
    chunks_before = len(srv._prefilling[0]["todo"])
    srv.step()
    assert srv.prefill_budget_clamped >= 1
    assert len(srv._prefilling[0]["todo"]) == chunks_before  # starved
    out = srv.drain()               # a finishes; clamp lifts; b drains
    assert srv._prefilling == srv._prefilling.__class__()
    assert len(out[a]) == 2 + 20


def test_ttft_critical_prefill_overdraws_once_per_tick(params):
    """A prefill whose TTFT slack is gone may exceed the budget — but
    only one overdraw per tick, paid back from future credit."""
    clk = FakeClock()
    srv = DecodeServer(params, CFG, max_batch=2, prefill_chunk=8,
                       prefill_budget=2, slack_clock=clk)
    srv.tick_s_hint = 1.0
    srv.prefill_tok_s_hint = 1.0    # 40 remaining tokens ~ 40 units
    srv.submit([4, 5], 30)          # active decode: no liveness free pass
    srv.step()
    b = srv.submit(LONG, 4, deadline_s=10.0)    # hopeless TTFT: slack<0
    chunks_before = len(srv._prefilling[0]["todo"])
    srv.step()
    assert srv.prefill_budget_overrides == 1
    # exactly ONE chunk advanced: the overdraw is once-per-tick and the
    # negative credit blocks a second pick
    assert len(srv._prefilling[0]["todo"]) == chunks_before - 1
    assert srv._prefill_credit < 0
    out = srv.drain()
    assert out[b][:len(LONG)] == LONG


def test_edf_picks_tightest_deadline_first(params):
    """Two queued prefills: the one with less TTFT slack advances
    first even though it was submitted second."""
    clk = FakeClock()
    srv = DecodeServer(params, CFG, max_batch=2, prefill_chunk=8,
                       prefill_budget=8, slack_clock=clk)
    srv.tick_s_hint = 1.0
    srv.prefill_tok_s_hint = 1.0 / 8
    a = srv.submit(LONG, 4, deadline_s=100.0)   # loose
    b = srv.submit(LONG[:32], 4, deadline_s=6.0)   # tight
    before = {a: len(srv._prefilling[0]["todo"]),
              b: len(srv._prefilling[1]["todo"])}
    srv.step()
    by_rid = {e["req"].rid: len(e["todo"]) for e in srv._prefilling}
    assert by_rid[b] == before[b] - 1       # tight one advanced
    assert by_rid[a] == before[a]           # loose one waited
    srv.drain()


def test_no_deadline_falls_back_to_fifo(params):
    srv = DecodeServer(params, CFG, max_batch=2, prefill_chunk=8,
                       prefill_budget=8)
    a = srv.submit(LONG, 3)
    b = srv.submit(LONG[:32], 3)
    before_a = len(srv._prefilling[0]["todo"])
    srv.step()
    by_rid = {e["req"].rid: len(e["todo"]) for e in srv._prefilling}
    assert by_rid[a] == before_a - 1        # FIFO: first submit first
    assert by_rid[b] == 4
    srv.drain()


def test_liveness_tiny_budget_drains_without_decode_work(params):
    """budget << chunk with nothing decoding: the free-advance rule
    keeps one chunk per tick flowing so drain() never spins."""
    srv = DecodeServer(params, CFG, max_batch=1, prefill_chunk=8,
                       prefill_budget=1)
    want = drain_all(
        DecodeServer(params, CFG, max_batch=1, prefill_chunk=8),
        [(LONG, 4, {})])
    got = drain_all(srv, [(LONG, 4, {})])
    assert got == want


def test_credit_accrual_is_capped_and_paces_chunks(params):
    """budget=4, chunk=8: credit accrues to the cap max(budget, chunk)
    and a chunk advances every second tick while decode holds the
    slot — budgeted pacing, not starvation."""
    srv = DecodeServer(params, CFG, max_batch=2, prefill_chunk=8,
                       prefill_budget=4)
    srv.submit([4, 5], 30)
    srv.step()
    srv.submit(LONG, 3)
    advanced = []
    for _ in range(10):
        before = sum(len(e["todo"]) for e in srv._prefilling)
        srv.step()
        after = sum(len(e["todo"]) for e in srv._prefilling)
        advanced.append(before - after)
        assert srv._prefill_credit <= max(srv.prefill_budget,
                                          srv._prefill_chunk)
    # every other tick advances exactly one chunk: 4+4 credit per pair
    assert sum(advanced) == 5
    assert max(advanced) == 1
    srv.drain()


def test_stats_surface_and_backlog_accessors(params):
    srv = DecodeServer(params, CFG, max_batch=2, prefill_chunk=8,
                       prefill_budget=16)
    srv.prefill_tok_s_hint = 0.5
    srv.submit(LONG, 3)
    assert srv.prefill_backlog() == len(LONG)
    assert srv.prefill_backlog_s() == pytest.approx(len(LONG) * 0.5)
    st = srv.stats()["prefill_sched"]
    assert st["budget"] == 16
    assert st["backlog_tokens"] == len(LONG)
    assert set(st) == {"budget", "credit", "backlog_tokens",
                       "chunk_tokens", "budget_spent_tokens",
                       "clamped_ticks", "overrides",
                       "est_prefill_tok_s", "est_tick_s"}
    srv.drain()
    assert srv.stats()["prefill_sched"]["backlog_tokens"] == 0
    # chunking off -> no scheduler section at all
    plain = DecodeServer(params, CFG, max_batch=1)
    assert plain.stats()["prefill_sched"] is None


def test_bad_budget_rejected(params):
    with pytest.raises(ValueError, match="prefill_budget"):
        DecodeServer(params, CFG, max_batch=1, prefill_chunk=8,
                     prefill_budget=-1)


def test_server_config_rejects_budget_without_chunking():
    """build_engine fails on config alone — before any checkpoint."""
    from nos_tpu.cmd.server import ServerConfig, build_engine
    base = dict(vocab=64, d_model=32, n_layers=2, n_heads=4,
                n_kv_heads=2, d_ff=64, max_seq=128, bf16=False)
    with pytest.raises(ValueError, match="prefill_chunk"):
        build_engine(ServerConfig(**base, prefill_budget=64))
    with pytest.raises(ValueError, match=">= 0"):
        build_engine(ServerConfig(**base, prefill_chunk=8,
                                  prefill_budget=-1))


# ---------------------------------------------------------------------------
# chaos: supervised restart mid-budgeted-prefill — recompute-resume
# replays under the same budget, per-request conservation holds
# ---------------------------------------------------------------------------

def test_restart_mid_budgeted_prefill_resumes_bit_exact(params):
    from nos_tpu.cmd.server import ServingLoop
    from nos_tpu.models.generate import generate
    from nos_tpu.models.supervision import FaultInjector

    def mk():
        return DecodeServer(params, CFG, max_batch=2, prefill_chunk=8,
                            prefill_budget=8)

    inj = FaultInjector(schedule={2: "error"})   # trips mid-prefill
    loop = ServingLoop(inj.wrap(mk()), engine_factory=lambda: inj.wrap(mk()),
                       restart_budget=2, restart_backoff_s=0.01)
    prompts = [LONG, [7, 8]]
    outs = {}

    def worker(i):
        outs[i] = loop.generate(prompts[i], 8, timeout=180)

    threads = [threading.Thread(target=worker, args=(i,))
               for i in range(len(prompts))]
    for t in threads:
        t.start()
    for t in threads:
        t.join(300)
    try:
        assert loop._sup.restarts == 1
        assert loop._sup.lost == 0
        for i, p in enumerate(prompts):
            want = [int(t) for t in generate(
                params, CFG, jnp.asarray([p], jnp.int32), 8)[0]]
            assert outs.get(i) == want, (
                f"request {i} diverged across the budgeted restart")
    finally:
        loop.shutdown()


# ---------------------------------------------------------------------------
# prefill-side decode-pool health view
# ---------------------------------------------------------------------------

class _ParkingEngine:
    """Prefill-role stub: submit parks a handoff; release() surfaces it
    to the pusher."""

    def __init__(self):
        self.pending, self.done, self._rid = {}, {}, 0
        self._handoffs, self.parked = [], {}

    def submit(self, prompt, n, **kw):
        rid = self._rid
        self._rid += 1
        self.parked[rid] = {"rid": rid, "prompt": list(prompt)}
        return rid

    def has_work(self):
        return False

    def step(self):
        return 0

    def progress(self, rid):
        return None

    def pop_result(self, rid):
        return self.done.pop(rid, None)

    def release(self, rid):
        self._handoffs.append(self.parked.pop(rid))

    def pop_handoffs(self):
        out, self._handoffs = self._handoffs, []
        return out


def _wait_until(cond, timeout=10.0):
    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout:
        if cond():
            return True
        time.sleep(0.005)
    return False


def _mk_prefill_loop(stats_by_target, shipped, fail=()):
    from nos_tpu.cmd.server import ServingLoop
    eng = _ParkingEngine()

    def send(target, data):
        if target in fail:
            raise ConnectionError("boom")
        shipped.append(target)
        return 1

    loop = ServingLoop(eng, role="prefill",
                       handoff_targets=sorted(stats_by_target),
                       handoff_send=send,
                       handoff_health_interval_s=60.0)
    loop.pool_stats_fetch = lambda t: stats_by_target[t]
    return eng, loop


def test_pusher_prefers_healthy_least_loaded_and_skips_draining():
    """Draining replica skipped BEFORE any attempt; among healthy ones
    the push goes to the smallest scraped queue."""
    from nos_tpu.utils.metrics import default_registry
    stats = {
        "http://a": {"pending": {"depth": 3}},
        "http://b": {"pending": {"depth": 1}},
        "http://c": {"pending": {"depth": 0}, "draining": True},
    }
    shipped = []
    eng, loop = _mk_prefill_loop(stats, shipped)
    skip0 = loop.m_handoff_skipped.value()
    try:
        rid = eng.submit([1, 2], 4)
        eng.release(rid)
        with loop._work:
            loop._work.notify_all()
        assert _wait_until(lambda: shipped)
        assert shipped == ["http://b"]      # least-loaded healthy
        assert loop.m_handoff_skipped.value() == skip0 + 1
        assert loop._pool_health["http://c"]["draining"]
    finally:
        loop.shutdown()


def test_pusher_health_view_unknown_sorts_after_known():
    """A target whose scrape FAILS goes unknown — still eligible, but
    after every known-healthy replica."""
    stats = {
        "http://a": {"pending": {"depth": 9}},
    }

    def fetch(t):
        if t == "http://b":
            raise OSError("scrape down")
        return stats[t]

    shipped = []
    eng, loop = _mk_prefill_loop(
        {"http://a": None, "http://b": None}, shipped)
    loop.pool_stats_fetch = fetch
    try:
        loop._refresh_pool_health(["http://a", "http://b"])
        assert loop._order_pool(["http://b", "http://a"]) == \
            ["http://a", "http://b"]
    finally:
        loop.shutdown()


def test_pusher_whole_pool_draining_falls_back_to_round_robin():
    """The health view degrades to blind RR, never to dropping the
    handoff: with every replica draining the push still lands."""
    stats = {
        "http://a": {"pending": {"depth": 0}, "draining": True},
        "http://b": {"recovering": True},
    }
    shipped = []
    eng, loop = _mk_prefill_loop(stats, shipped)
    try:
        rid = eng.submit([1, 2], 4)
        eng.release(rid)
        with loop._work:
            loop._work.notify_all()
        assert _wait_until(lambda: shipped)
        assert shipped[0] in ("http://a", "http://b")
    finally:
        loop.shutdown()


def test_pusher_health_refresh_respects_cadence():
    """Scrapes are bounded by --handoff-health-interval-s: a second
    refresh inside the window is a no-op."""
    calls = []
    eng, loop = _mk_prefill_loop({"http://a": {"pending": {"depth": 0}}},
                                 [])
    loop.pool_stats_fetch = lambda t: calls.append(t) or {
        "pending": {"depth": 0}}
    try:
        loop._refresh_pool_health(["http://a"])
        loop._refresh_pool_health(["http://a"])
        assert calls == ["http://a"]
    finally:
        loop.shutdown()
