"""Seeded property fuzz for the request-level elastic-quota scheduler
(ISSUE 13 satellite) — jax-free, like the allocator fuzz it sits next
to (hypothesis is not in the image; seeded random is the idiom).

Four properties, each over adversarial seeded mixes:

- WORK CONSERVATION: the pick never returns None for a non-empty
  candidate set — an idle slot is never held back by a ceiling (a
  simulated slot loop with pending work must dispatch every round);
- MIN-GUARANTEE: a tenant under its min is never skipped in favor of
  any tenant at/over its min;
- NO STARVATION: under a stationary adversarial mix, every tenant
  with pending work dispatches within a bounded number of rounds
  (window decay makes a passed-over tenant's rate fall until it wins);
- BORROW-SHARE PROPORTIONALITY: ``borrow_shares`` equals an
  INDEPENDENTLY-built ``QuotaInfos.guaranteed_overquotas`` oracle —
  the pod layer's own math (quota/info.py:207), so the two layers
  cannot disagree about what "fair" means.
"""
import random

import pytest

from nos_tpu.models.tenantquota import (
    RATE_RESOURCE, RATE_SCALE, TenantQuotaConfig, TenantScheduler,
    TenantSpec, validate_tenant_name,
)
from nos_tpu.quota.info import QuotaInfo, QuotaInfos


def _cfg(rng, n_tenants, window_s=8.0):
    tenants = {}
    for i in range(n_tenants):
        name = f"t{i}"
        mn = rng.choice([0.0, 0.0, rng.uniform(1.0, 50.0)])
        mx = rng.choice([0.0, mn + rng.uniform(1.0, 50.0)]) \
            if rng.random() < 0.6 else 0.0
        tenants[name] = TenantSpec(name, min_rate=round(mn, 3),
                                   max_rate=round(mx, 3))
    return TenantQuotaConfig(tenants=tenants, window_s=window_s)


# ---------------------------------------------------------------------------
# work conservation + min-guarantee
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("seed", range(6))
def test_pick_is_work_conserving(seed):
    rng = random.Random(100 + seed)
    cfg = _cfg(rng, rng.randint(2, 6))
    sched = TenantScheduler(cfg)
    names = cfg.names()
    now = 0.0
    for _ in range(400):
        now += rng.uniform(0.1, 1.0)
        # adversarial usage: random tenants burn random tokens
        for _ in range(rng.randint(0, 3)):
            sched.note_tokens(rng.choice(names),
                              rng.randint(1, 200), now)
        cands = rng.sample(names, rng.randint(1, len(names)))
        picked = sched.pick(cands, now)
        # never None with pending work: over-max tenants still admit
        # when nobody else is waiting (idle capacity is lent)
        assert picked in set(cfg.resolve(c) for c in cands)


@pytest.mark.parametrize("seed", range(6))
def test_under_min_tenant_never_skipped_for_borrower(seed):
    rng = random.Random(200 + seed)
    cfg = _cfg(rng, rng.randint(2, 6))
    # force guaranteed tenants into the mix: an all-best-effort config
    # never exercises the property this test exists for
    tenants = dict(cfg.tenants)
    for name in list(tenants)[:2]:
        if name != cfg.default_tenant:
            tenants[name] = TenantSpec(
                name, min_rate=rng.uniform(5.0, 60.0))
    cfg = TenantQuotaConfig(tenants=tenants, window_s=cfg.window_s)
    sched = TenantScheduler(cfg)
    names = cfg.names()
    now = 0.0
    checked = 0
    for _ in range(600):
        now += rng.uniform(0.1, 1.0)
        for _ in range(rng.randint(0, 3)):
            sched.note_tokens(rng.choice(names),
                              rng.randint(1, 300), now)
        cands = rng.sample(names, rng.randint(2, len(names)))
        picked = sched.pick(cands, now)
        guaranteed = [c for c in cands if sched.under_min(c, now)]
        if guaranteed and any(not sched.under_min(c, now)
                              for c in cands):
            # the guarantee: some candidate is under its min while
            # another is at/over its — the pick may not choose the
            # at-or-over one
            assert sched.under_min(picked, now), (
                f"picked {picked} over under-min {guaranteed}")
            checked += 1
    assert checked > 10, "adversarial mix never exercised the guarantee"


# ---------------------------------------------------------------------------
# no starvation
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("seed", range(4))
def test_no_starvation_under_stationary_adversarial_mix(seed):
    """A toy slot loop: every round ONE pending tenant dispatches (the
    pick) and emits a fixed 16-token burst; every tenant always has
    pending work. No tenant may go unpicked longer than a bound —
    window decay drives a passed-over tenant's rate (and so its pick
    key) down until it wins.

    The mins are generated UNDER the loop's capacity (Σmin well below
    16 tokens/round): guarantees above capacity starving best-effort
    traffic is the DESIGNED strict-priority behavior, mirroring the
    pod layer's own sizing invariant ('the cluster never promises more
    than the sum of guarantees', key-concepts.md) — no-starvation is a
    property of provisionable configs, not of over-promised ones."""
    rng = random.Random(300 + seed)
    tenants = {}
    for i in range(rng.randint(3, 5)):
        name = f"t{i}"
        mn = rng.choice([0.0, rng.uniform(0.2, 1.5)])
        mx = mn + rng.uniform(1.0, 30.0) if rng.random() < 0.5 else 0.0
        tenants[name] = TenantSpec(name, min_rate=round(mn, 3),
                                   max_rate=round(mx, 3))
    cfg = TenantQuotaConfig(tenants=tenants, window_s=16.0)
    sched = TenantScheduler(cfg)
    names = cfg.names()
    last_pick = {n: 0 for n in names}
    now = 0.0
    for step in range(1, 1200):
        now += 1.0
        picked = sched.pick(names, now)
        last_pick[picked] = step
        sched.note_tokens(picked, 16, now)
        if step > 100:
            for n in names:
                assert step - last_pick[n] < 80, (
                    f"{n} starved for {step - last_pick[n]} rounds "
                    f"(spec {cfg.tenants[n]})")


# ---------------------------------------------------------------------------
# borrow-share proportionality vs the quota/info.py oracle
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("seed", range(8))
def test_borrow_shares_match_guaranteed_overquotas_oracle(seed):
    """The scheduler's borrow shares must equal guaranteed_overquotas
    computed by an INDEPENDENTLY constructed QuotaInfos over the same
    (min, used-rate) state — the pin that keeps the request layer and
    the pod layer answering 'what is fair' with one voice."""
    rng = random.Random(400 + seed)
    cfg = _cfg(rng, rng.randint(2, 7))
    sched = TenantScheduler(cfg)
    names = cfg.names()
    now = 0.0
    for _ in range(50):
        now += rng.uniform(0.2, 2.0)
        for _ in range(rng.randint(0, 4)):
            sched.note_tokens(rng.choice(names),
                              rng.randint(1, 500), now)
        # oracle: fresh QuotaInfos from the specs + the LIVE rates
        infos = QuotaInfos()
        for name in names:
            spec = cfg.tenants[name]
            infos.add(QuotaInfo(
                name=name, namespace=name, namespaces={name},
                min={RATE_RESOURCE: spec.min_rate * RATE_SCALE},
                max=({RATE_RESOURCE: spec.max_rate * RATE_SCALE}
                     if spec.max_rate else None),
                used={RATE_RESOURCE:
                      sched.rate(name, now) * RATE_SCALE}))
        want = {
            name: infos.guaranteed_overquotas(name).get(
                RATE_RESOURCE, 0.0) / RATE_SCALE
            for name in names}
        got = sched.borrow_shares(now)
        assert got == pytest.approx(want), (got, want)
        # sanity on the oracle itself: shares never exceed the unused
        # aggregate min, and a zero-min tenant gets a zero share
        pool = sum(max(0.0, cfg.tenants[n].min_rate
                       - sched.rate(n, now)) for n in names)
        assert sum(got.values()) <= pool + 1e-6
        for n in names:
            if cfg.tenants[n].min_rate == 0:
                assert got[n] == 0.0


# ---------------------------------------------------------------------------
# config parsing / identity plumbing
# ---------------------------------------------------------------------------

def test_config_parses_inline_json_and_validates():
    cfg = TenantQuotaConfig.from_json(
        '{"tenants": {"gold": {"min_rate": 200}, '
        '"burst": {"max_rate": 50}}, "window_s": 2.5}')
    assert cfg.tenants["gold"].min_rate == 200
    assert cfg.tenants["burst"].max_rate == 50
    assert cfg.window_s == 2.5
    assert "default" in cfg.tenants       # always present
    assert cfg.resolve("gold") == "gold"
    assert cfg.resolve("nobody") == "default"
    assert cfg.resolve(None) == "default"
    with pytest.raises(ValueError):
        TenantQuotaConfig.from_json('{"tenants": {"a": {"min_rate": 9,'
                                    ' "max_rate": 3}}}')
    with pytest.raises(ValueError):
        TenantQuotaConfig.from_json('{"unknown_key": 1}')
    with pytest.raises(ValueError):
        TenantQuotaConfig.from_json('{"window_s": 0}')
    assert TenantQuotaConfig.load("") is None


def test_config_loads_from_file(tmp_path):
    p = tmp_path / "tenants.json"
    p.write_text('{"tenants": {"a": {"min_rate": 5}}}')
    cfg = TenantQuotaConfig.load(str(p))
    assert cfg.tenants["a"].min_rate == 5
    with pytest.raises(ValueError):
        TenantQuotaConfig.load(str(tmp_path / "missing.json"))


def test_tenant_name_validation():
    assert validate_tenant_name("team-a") == "team-a"
    for bad in ("", "x" * 200, 'a"b', "a\nb", 123):
        with pytest.raises(ValueError):
            validate_tenant_name(bad)


def test_rate_window_decays():
    cfg = TenantQuotaConfig(
        tenants={"a": TenantSpec("a", min_rate=10)}, window_s=4.0)
    s = TenantScheduler(cfg)
    s.note_tokens("a", 40, now=0.0)
    assert s.rate("a", 0.0) == pytest.approx(10.0)
    assert s.rate("a", 3.9) == pytest.approx(10.0)
    assert s.rate("a", 4.1) == 0.0          # burst aged out
    assert s.tokens_total["a"] == 40        # cumulative survives
