"""Scheduler framework + CapacityScheduling plugin + scheduling loop
(model: reference capacity_scheduling_test.go, 704 LoC)."""
import pytest

from nos_tpu import constants
from nos_tpu.api.quota import make_composite_elastic_quota, make_elastic_quota
from nos_tpu.kube import ApiServer, Manager
from nos_tpu.kube.objects import (
    Container,
    Node,
    NodeStatus,
    ObjectMeta,
    Pod,
    PodSpec,
    PodStatus,
)
from nos_tpu.scheduler import CapacityScheduling, Scheduler
from nos_tpu.scheduler import framework as fw

TPU = "google.com/tpu"
SCHED = constants.SCHEDULER_NAME


def make_node(name, tpu=8, cpu=96, labels=None):
    return Node(
        metadata=ObjectMeta(name=name, labels=labels or {}),
        status=NodeStatus(
            capacity={TPU: tpu, "cpu": cpu},
            allocatable={TPU: tpu, "cpu": cpu},
        ),
    )


def make_pod(name, ns, tpu=0, cpu=0.0, node="", phase="Pending", priority=None,
             labels=None, selector=None, scheduler=SCHED, created=0.0):
    req = {}
    if tpu:
        req[TPU] = tpu
    if cpu:
        req["cpu"] = cpu
    return Pod(
        metadata=ObjectMeta(name=name, namespace=ns, labels=labels or {},
                            creation_timestamp=created),
        spec=PodSpec(
            containers=[Container(requests=req)],
            node_name=node,
            priority=priority,
            node_selector=selector or {},
            scheduler_name=scheduler,
        ),
        status=PodStatus(phase=phase),
    )


# ---------------------------------------------------------------------------
# framework basics
# ---------------------------------------------------------------------------

def test_snapshot_and_fit_filter():
    snap = fw.Snapshot.build(
        [make_node("n1", tpu=8)],
        [make_pod("running", "a", tpu=6, node="n1", phase="Running")],
    )
    f = fw.NodeResourcesFit()
    ok = f.filter({}, make_pod("p", "a", tpu=2), snap["n1"])
    assert ok.success
    bad = f.filter({}, make_pod("p", "a", tpu=3), snap["n1"])
    assert not bad.success


def test_node_selector_filter():
    snap = fw.Snapshot.build(
        [make_node("v5e", labels={constants.LABEL_TPU_ACCELERATOR: "tpu-v5-lite-podslice"})],
        [],
    )
    f = fw.NodeSelectorFit()
    pod = make_pod("p", "a", tpu=1,
                   selector={constants.LABEL_TPU_ACCELERATOR: "tpu-v5-lite-podslice"})
    assert f.filter({}, pod, snap["v5e"]).success
    pod2 = make_pod("p2", "a", tpu=1,
                    selector={constants.LABEL_TPU_ACCELERATOR: "tpu-v5p-slice"})
    assert not f.filter({}, pod2, snap["v5e"]).success


def test_framework_can_schedule_picks_feasible_node():
    fwk = fw.SchedulerFramework()
    snap = fw.Snapshot.build(
        [make_node("small", tpu=2), make_node("big", tpu=8)],
        [make_pod("r", "a", tpu=2, node="small", phase="Running")],
    )
    node, st = fwk.can_schedule(make_pod("p", "a", tpu=4), snap)
    assert st.success and node == "big"
    node, st = fwk.can_schedule(make_pod("p", "a", tpu=100), snap)
    assert not st.success and node is None


# ---------------------------------------------------------------------------
# CapacityScheduling PreFilter
# ---------------------------------------------------------------------------

def quota_rig(*eqs, ceqs=()):
    cap = CapacityScheduling()
    cap.sync_quotas(list(eqs), list(ceqs))
    return cap


def test_pre_filter_rejects_over_max():
    cap = quota_rig(make_elastic_quota("qa", "team-a", min={TPU: 2}, max={TPU: 4}))
    snap = fw.Snapshot()
    for p in [make_pod("r1", "team-a", tpu=3, node="n1", phase="Running")]:
        cap.track_pod(p)
    st = cap.pre_filter({}, make_pod("p", "team-a", tpu=2), snap)
    assert not st.success and "max" in st.reason


def test_pre_filter_rejects_over_aggregated_min():
    cap = quota_rig(
        make_elastic_quota("qa", "team-a", min={TPU: 4}),
        make_elastic_quota("qb", "team-b", min={TPU: 4}),
    )
    cap.track_pod(make_pod("r1", "team-b", tpu=6, node="n1", phase="Running"))
    # cluster min total 8, used 6: a request of 3 exceeds the ceiling
    st = cap.pre_filter({}, make_pod("p", "team-a", tpu=3), fw.Snapshot())
    assert not st.success and "aggregated" in st.reason
    st2 = cap.pre_filter({}, make_pod("p2", "team-a", tpu=2), fw.Snapshot())
    assert st2.success


def test_pre_filter_allows_borrowing_within_ceiling():
    cap = quota_rig(
        make_elastic_quota("qa", "team-a", min={TPU: 2}),
        make_elastic_quota("qb", "team-b", min={TPU: 6}),
    )
    # team-a borrowing beyond its min but under the aggregate ceiling
    st = cap.pre_filter({}, make_pod("p", "team-a", tpu=5), fw.Snapshot())
    assert st.success


def test_pre_filter_no_quota_namespace_passes():
    cap = quota_rig(make_elastic_quota("qa", "team-a", min={TPU: 2}))
    st = cap.pre_filter({}, make_pod("p", "no-quota-ns", tpu=100), fw.Snapshot())
    assert st.success


# ---------------------------------------------------------------------------
# end-to-end scheduling loop
# ---------------------------------------------------------------------------

def sched_rig():
    server = ApiServer()
    mgr = Manager(server)
    sched = Scheduler()
    mgr.add_controller(sched.controller())
    return server, mgr, sched


def test_schedules_pod_onto_feasible_node():
    server, mgr, _ = sched_rig()
    server.create(make_node("n1", tpu=8))
    server.create(make_pod("p1", "team-a", tpu=4))
    mgr.run_until_idle()
    pod = server.get("Pod", "p1", "team-a")
    assert pod.spec.node_name == "n1"
    assert any(c.type == "PodScheduled" and c.status == "True"
               for c in pod.status.conditions)


def test_marks_unschedulable_when_no_fit():
    server, mgr, _ = sched_rig()
    server.create(make_node("n1", tpu=2))
    server.create(make_pod("p1", "team-a", tpu=4))
    mgr.run_until_idle()
    pod = server.get("Pod", "p1", "team-a")
    assert pod.spec.node_name == ""
    assert pod.is_unschedulable()


def test_pending_pod_scheduled_when_node_appears():
    server, mgr, _ = sched_rig()
    server.create(make_pod("p1", "team-a", tpu=4))
    mgr.run_until_idle()
    assert server.get("Pod", "p1", "team-a").spec.node_name == ""
    server.create(make_node("late", tpu=8))
    mgr.run_until_idle()
    assert server.get("Pod", "p1", "team-a").spec.node_name == "late"


def test_pending_pod_scheduled_when_capacity_freed():
    server, mgr, _ = sched_rig()
    server.create(make_node("n1", tpu=8))
    server.create(make_pod("r1", "team-a", tpu=8, node="n1", phase="Running"))
    server.create(make_pod("p1", "team-a", tpu=4))
    mgr.run_until_idle()
    assert server.get("Pod", "p1", "team-a").spec.node_name == ""
    server.delete("Pod", "r1", "team-a")
    mgr.run_until_idle()
    assert server.get("Pod", "p1", "team-a").spec.node_name == "n1"


def test_ignores_other_schedulers_pods():
    server, mgr, _ = sched_rig()
    server.create(make_node("n1", tpu=8))
    server.create(make_pod("p1", "team-a", tpu=4, scheduler="default-scheduler"))
    mgr.run_until_idle()
    assert server.get("Pod", "p1", "team-a").spec.node_name == ""


def test_respects_max_quota_end_to_end():
    server, mgr, _ = sched_rig()
    server.create(make_node("n1", tpu=8))
    server.create(make_elastic_quota("qa", "team-a", min={TPU: 2}, max={TPU: 2}))
    server.create(make_pod("p1", "team-a", tpu=2))
    server.create(make_pod("p2", "team-a", tpu=2))
    mgr.run_until_idle()
    pods = server.list("Pod", namespace="team-a")
    scheduled = [p for p in pods if p.spec.node_name]
    assert len(scheduled) == 1   # second pod would exceed max=2


# ---------------------------------------------------------------------------
# preemption
# ---------------------------------------------------------------------------

def test_preemption_reclaims_borrowed_quota():
    """team-b borrowed team-a's unused min; when team-a needs it back, the
    over-quota pod of team-b is evicted (reference regime 2:
    preemptor within min reclaims borrowed capacity)."""
    server, mgr, _ = sched_rig()
    server.create(make_node("n1", tpu=8))
    server.create(make_elastic_quota("qa", "team-a", min={TPU: 4}))
    server.create(make_elastic_quota("qb", "team-b", min={TPU: 4}))
    # team-b uses the whole node: 4 in-quota + 4 borrowed (over-quota label)
    server.create(make_pod("b-in", "team-b", tpu=4, node="n1", phase="Running",
                           labels={constants.LABEL_CAPACITY: "in-quota"}))
    server.create(make_pod("b-over", "team-b", tpu=4, node="n1", phase="Running",
                           labels={constants.LABEL_CAPACITY: "over-quota"}))
    server.create(make_pod("a-pod", "team-a", tpu=4))
    mgr.run_until_idle(advance_delayed=True)
    # the borrower's over-quota pod was evicted and team-a's pod scheduled
    assert server.try_get("Pod", "b-over", "team-b") is None
    assert server.try_get("Pod", "b-in", "team-b") is not None
    assert server.get("Pod", "a-pod", "team-a").spec.node_name == "n1"


def test_preemption_same_namespace_by_priority():
    server, mgr, _ = sched_rig()
    server.create(make_node("n1", tpu=8))
    server.create(make_elastic_quota("qa", "team-a", min={TPU: 4}))
    server.create(make_pod("low", "team-a", tpu=8, node="n1", phase="Running",
                           priority=0,
                           labels={constants.LABEL_CAPACITY: "over-quota"}))
    server.create(make_pod("high", "team-a", tpu=4, priority=100))
    mgr.run_until_idle(advance_delayed=True)
    assert server.try_get("Pod", "low", "team-a") is None
    assert server.get("Pod", "high", "team-a").spec.node_name == "n1"


def test_no_preemption_of_in_quota_pods_cross_namespace():
    server, mgr, _ = sched_rig()
    server.create(make_node("n1", tpu=8))
    server.create(make_elastic_quota("qa", "team-a", min={TPU: 4}))
    server.create(make_elastic_quota("qb", "team-b", min={TPU: 4}))
    server.create(make_pod("b-in", "team-b", tpu=4, node="n1", phase="Running",
                           labels={constants.LABEL_CAPACITY: "in-quota"}))
    # team-a wants 8 (over its min); team-b is within min -> no victims
    server.create(make_pod("a-pod", "team-a", tpu=8))
    mgr.run_until_idle(advance_delayed=True)
    assert server.try_get("Pod", "b-in", "team-b") is not None
    assert server.get("Pod", "a-pod", "team-a").spec.node_name == ""


def test_preemption_respects_node_selector():
    """Preemption must not kill pods on nodes the preemptor can't run on."""
    server, mgr, _ = sched_rig()
    server.create(make_node("v5e", tpu=8,
                            labels={constants.LABEL_TPU_ACCELERATOR: "tpu-v5-lite-podslice"}))
    server.create(make_pod("low", "team-a", tpu=8, node="v5e", phase="Running",
                           priority=0))
    server.create(make_pod("high", "team-a", tpu=8, priority=100,
                           selector={constants.LABEL_TPU_ACCELERATOR: "tpu-v5p-slice"}))
    mgr.run_until_idle(advance_delayed=True)
    # the running pod survives; the selector-mismatched preemptor stays pending
    assert server.try_get("Pod", "low", "team-a") is not None
    assert server.get("Pod", "high", "team-a").spec.node_name == ""


def test_sweep_preemption_does_not_overkill():
    """Two pending pods in one sweep: the first's preemption must be visible
    to the second so it doesn't evict additional live pods."""
    server, mgr, _ = sched_rig()
    server.create(make_node("n1", tpu=8))
    server.create(make_node("n2", tpu=8))
    for node in ("n1", "n2"):
        server.create(make_pod(f"low-{node}", "team-a", tpu=8, node=node,
                               phase="Running", priority=0))
    # two high-priority pods arrive in one burst; each needs one full node
    server.create(make_pod("high-1", "team-a", tpu=8, priority=100))
    server.create(make_pod("high-2", "team-a", tpu=8, priority=100))
    mgr.run_until_idle(advance_delayed=True)
    survivors = [p.metadata.name for p in server.list("Pod")]
    # both high pods scheduled, both low pods evicted — but never MORE than
    # the two needed evictions (no over-kill of freshly-freed capacity)
    assert "high-1" in survivors and "high-2" in survivors
    highs = [server.get("Pod", n, "team-a").spec.node_name for n in ("high-1", "high-2")]
    assert sorted(h for h in highs if h) == ["n1", "n2"]


def test_burst_shares_one_state_sync():
    """A burst of pod events must not rebuild scheduler state per pod:
    one _sync_state serves every pending pod (the 1024-node scale
    point's p99 was dominated by per-event rebuilds — O(n^2) in sync
    work — before this was batched)."""
    server = ApiServer()
    sched = Scheduler()
    mgr = Manager(server)
    mgr.add_controller(sched.controller())
    server.create(make_node("n0", tpu=8))
    server.create(make_node("n1", tpu=8))
    server.create(make_elastic_quota("q", "team-a", min={TPU: 16}))
    mgr.run_until_idle()

    syncs = []
    orig = sched._sync_state

    def counting_sync(client):
        syncs.append(1)
        return orig(client)

    sched._sync_state = counting_sync
    for i in range(12):
        server.create(make_pod(f"burst-{i}", "team-a", tpu=1))
    mgr.run_until_idle()

    bound = [p for p in server.list("Pod") if p.spec.node_name]
    assert len(bound) == 12
    # one sync for the first event's batch pass; later per-pod events
    # no-op on the already-bound check. A couple of extra syncs from
    # requeue sweeps are fine; 12 would mean per-pod rebuilds are back.
    assert len(syncs) <= 4, f"{len(syncs)} state syncs for a 12-pod burst"


def test_unschedulable_burst_is_not_quadratic():
    """An unschedulable burst must cost ~a couple of batch passes, not
    one pass per event: the generation guard skips a pass when nothing
    the cache sees has changed since the last one."""
    server = ApiServer()
    sched = Scheduler()
    mgr = Manager(server)
    mgr.add_controller(sched.controller())
    server.create(make_node("n0", tpu=2))
    server.create(make_elastic_quota("q", "team-a", min={TPU: 64}))
    mgr.run_until_idle()

    attempts = []
    orig = sched._schedule_one

    def counting(client, pod, snapshot):
        attempts.append(pod.metadata.name)
        return orig(client, pod, snapshot)

    sched._schedule_one = counting
    n = 16
    for i in range(n):   # each wants more chips than the cluster has
        server.create(make_pod(f"big-{i}", "team-a", tpu=4))
    mgr.run_until_idle()

    bound = [p for p in server.list("Pod") if p.spec.node_name]
    assert not bound
    # old behavior: every event re-attempts every pending pod -> ~n^2
    # (256+); now: one attempt pass + one after the idempotent condition
    # writes land -> ~2n, with headroom for a stray sweep
    assert len(attempts) <= 4 * n, f"{len(attempts)} attempts for {n} pods"


def test_unplaceable_gang_searched_once_per_pass():
    """An unplaceable gang must run gang placement once per batch pass,
    not once per pending member."""
    server = ApiServer()
    sched = Scheduler()
    mgr = Manager(server)
    mgr.add_controller(sched.controller())
    server.create(make_node("n0", tpu=8))
    server.create(make_elastic_quota("q", "team-a", min={TPU: 64}))
    mgr.run_until_idle()

    calls = []
    orig = sched._schedule_gang

    def counting(client, pod, snapshot):
        calls.append(pod.metadata.name)
        return orig(client, pod, snapshot)

    sched._schedule_gang = counting
    for w in range(8):   # needs 8 nodes; cluster has 1
        server.create(make_pod(
            f"gang-{w}", "team-a", tpu=8,
            labels={constants.LABEL_GANG_NAME: "g1",
                    constants.LABEL_GANG_SIZE: "8",
                    constants.LABEL_GANG_WORKER: str(w)}))
    mgr.run_until_idle()

    assert not [p for p in server.list("Pod") if p.spec.node_name]
    # one gang attempt per pass, a handful of passes
    assert len(calls) <= 4, f"{len(calls)} gang placement attempts"
