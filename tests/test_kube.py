"""API server + controller runtime semantics (the envtest-equivalent rig)."""
import pytest

from nos_tpu.kube import (
    ApiServer,
    Client,
    Conflict,
    Controller,
    Manager,
    NotFound,
    AlreadyExists,
    Node,
    ObjectMeta,
    Pod,
    PodSpec,
    PodStatus,
    Container,
    Request,
    Result,
)
from nos_tpu.kube.apiserver import AdmissionDenied
from nos_tpu.kube.controller import Watch
from nos_tpu.kube import predicates


def make_pod(name, ns="default", phase="Pending", node=""):
    return Pod(
        metadata=ObjectMeta(name=name, namespace=ns),
        spec=PodSpec(containers=[Container(requests={"cpu": 1})], node_name=node),
        status=PodStatus(phase=phase),
    )


# ---------------------------------------------------------------------------
# ApiServer CRUD
# ---------------------------------------------------------------------------

def test_create_get_roundtrip_and_metadata_stamping():
    s = ApiServer()
    created = s.create(make_pod("p1"))
    assert created.metadata.uid
    assert created.metadata.resource_version > 0
    assert created.metadata.creation_timestamp > 0
    got = s.get("Pod", "p1", "default")
    assert got.metadata.uid == created.metadata.uid


def test_create_duplicate_rejected():
    s = ApiServer()
    s.create(make_pod("p1"))
    with pytest.raises(AlreadyExists):
        s.create(make_pod("p1"))


def test_get_missing_raises_not_found():
    s = ApiServer()
    with pytest.raises(NotFound):
        s.get("Pod", "nope", "default")
    assert s.try_get("Pod", "nope", "default") is None


def test_update_optimistic_concurrency():
    s = ApiServer()
    s.create(make_pod("p1"))
    a = s.get("Pod", "p1", "default")
    b = s.get("Pod", "p1", "default")
    a.status.phase = "Running"
    s.update(a)
    b.status.phase = "Failed"
    with pytest.raises(Conflict):
        s.update(b)


def test_patch_is_atomic_read_modify_write():
    s = ApiServer()
    s.create(Node(metadata=ObjectMeta(name="n1")))
    s.patch("Node", "n1", "", lambda n: n.metadata.annotations.update({"a": "1"}))
    s.patch("Node", "n1", "", lambda n: n.metadata.annotations.update({"b": "2"}))
    n = s.get("Node", "n1")
    assert n.metadata.annotations == {"a": "1", "b": "2"}


def test_returned_objects_are_copies():
    s = ApiServer()
    s.create(make_pod("p1"))
    got = s.get("Pod", "p1", "default")
    got.status.phase = "Running"  # mutating the copy must not touch the store
    assert s.get("Pod", "p1", "default").status.phase == "Pending"


def test_list_with_namespace_and_labels():
    s = ApiServer()
    p = make_pod("p1")
    p.metadata.labels["team"] = "a"
    s.create(p)
    s.create(make_pod("p2", ns="other"))
    assert len(s.list("Pod")) == 2
    assert [p.metadata.name for p in s.list("Pod", namespace="default")] == ["p1"]
    assert len(s.list("Pod", label_selector={"team": "a"})) == 1
    assert len(s.list("Pod", label_selector={"team": "b"})) == 0


def test_field_index():
    s = ApiServer()
    s.register_index("Pod", "status.phase", lambda p: p.status.phase)
    s.create(make_pod("p1", phase="Running"))
    s.create(make_pod("p2", phase="Pending"))
    running = s.list("Pod", index=("status.phase", "Running"))
    assert [p.metadata.name for p in running] == ["p1"]


def test_admission_hook_blocks_create():
    s = ApiServer()

    def deny_default_ns(server, op, obj, old):
        if obj.metadata.namespace == "default":
            raise AdmissionDenied("no pods in default")

    s.register_admission("Pod", deny_default_ns)
    with pytest.raises(AdmissionDenied):
        s.create(make_pod("p1"))
    s.create(make_pod("p2", ns="ok"))


def test_delete_and_watch_events():
    s = ApiServer()
    sub = s.subscribe(["Pod"])
    s.create(make_pod("p1"))
    p = s.get("Pod", "p1", "default")
    p.status.phase = "Running"
    s.update(p)
    s.delete("Pod", "p1", "default")
    events = []
    while (ev := sub.pop()) is not None:
        events.append((ev.type, ev.obj.metadata.name))
    assert events == [("ADDED", "p1"), ("MODIFIED", "p1"), ("DELETED", "p1")]


def test_watch_modified_carries_old_object():
    s = ApiServer()
    sub = s.subscribe()
    s.create(Node(metadata=ObjectMeta(name="n1")))
    s.patch("Node", "n1", "", lambda n: n.metadata.annotations.update({"k": "v"}))
    sub.pop()  # ADDED
    ev = sub.pop()
    assert ev.type == "MODIFIED"
    assert ev.old.metadata.annotations == {}
    assert ev.obj.metadata.annotations == {"k": "v"}


# ---------------------------------------------------------------------------
# Pod helpers
# ---------------------------------------------------------------------------

def test_pod_request_includes_init_containers_max():
    p = Pod(
        metadata=ObjectMeta(name="p"),
        spec=PodSpec(
            containers=[Container(requests={"cpu": 1}), Container(requests={"cpu": 2, "mem": 5})],
            init_containers=[Container(requests={"cpu": 10})],
        ),
    )
    assert p.request() == {"cpu": 10, "mem": 5}


# ---------------------------------------------------------------------------
# Controller runtime
# ---------------------------------------------------------------------------

def test_controller_reconciles_on_events():
    s = ApiServer()
    mgr = Manager(s)
    seen = []

    def reconcile(client, req):
        seen.append(req.name)
        return Result()

    mgr.add_controller(Controller("t", reconcile, [Watch("Pod")]))
    s.create(make_pod("p1"))
    mgr.run_until_idle()
    assert seen == ["p1"]


def test_controller_requeue_retries_with_backoff_until_success():
    s = ApiServer()
    mgr = Manager(s)
    calls = []

    def reconcile(client, req):
        calls.append(req.name)
        # fail 3 times, then succeed — must converge, not be dropped
        return Result(requeue=len(calls) <= 3)

    mgr.add_controller(Controller("t", reconcile, [Watch("Pod")]))
    s.create(make_pod("p1"))
    mgr.run_until_idle(advance_delayed=True)
    assert len(calls) == 4


def test_controller_requeue_is_delayed_not_immediate():
    s = ApiServer()
    mgr = Manager(s, clock=lambda: 0.0)   # frozen clock: backoff never elapses
    calls = []

    def reconcile(client, req):
        calls.append(req.name)
        return Result(requeue=True)

    mgr.add_controller(Controller("t", reconcile, [Watch("Pod")]))
    s.create(make_pod("p1"))
    # without advancing delayed work, the backoff retry stays parked
    mgr.run_until_idle(advance_delayed=False)
    assert len(calls) == 1


def test_controller_exception_counts_as_requeue():
    s = ApiServer()
    mgr = Manager(s)
    calls = []

    def reconcile(client, req):
        calls.append(1)
        if len(calls) < 2:
            raise RuntimeError("boom")
        return Result()

    mgr.add_controller(Controller("t", reconcile, [Watch("Pod")]))
    s.create(make_pod("p1"))
    mgr.run_until_idle(advance_delayed=True)
    assert len(calls) == 2


def test_requeue_after_takes_precedence_over_requeue():
    s = ApiServer()
    mgr = Manager(s)
    calls = []

    def reconcile(client, req):
        calls.append(1)
        if len(calls) == 1:
            return Result(requeue=True, requeue_after=30.0)
        return Result()

    c = Controller("t", reconcile, [Watch("Pod")])
    mgr.add_controller(c)
    s.create(make_pod("p1"))
    mgr.run_until_idle()
    # the retry is parked at +30s (requeue_after), not immediate
    assert len(calls) == 1
    assert c.next_due() is not None
    mgr.run_until_idle(advance_delayed=True)
    assert len(calls) == 2


def test_initial_sync_reconciles_preexisting_objects():
    s = ApiServer()
    s.create(make_pod("pre-existing"))
    mgr = Manager(s)   # subscribed after the create
    seen = []
    mgr.add_controller(Controller("t", lambda cl, r: seen.append(r.name), [Watch("Pod")]))
    mgr.run_until_idle()
    assert seen == ["pre-existing"]


def test_queue_dedup():
    s = ApiServer()
    mgr = Manager(s)
    calls = []
    c = Controller("t", lambda cl, r: calls.append(r.name), [Watch("Pod")])
    mgr.add_controller(c)
    # three rapid events for the same object before any processing
    s.create(make_pod("p1"))
    p = s.get("Pod", "p1", "default")
    p.status.phase = "Running"
    s.update(p)
    p = s.get("Pod", "p1", "default")
    p.status.phase = "Succeeded"
    s.update(p)
    mgr.run_until_idle()
    assert calls == ["p1"]  # deduped into one level-triggered reconcile


def test_requeue_after_with_advance():
    s = ApiServer()
    mgr = Manager(s)
    calls = []

    def reconcile(client, req):
        calls.append(1)
        if len(calls) == 1:
            return Result(requeue_after=30.0)
        return Result()

    mgr.add_controller(Controller("t", reconcile, [Watch("Pod")]))
    s.create(make_pod("p1"))
    mgr.run_until_idle(advance_delayed=True)
    assert len(calls) == 2


def test_predicates_filter_events():
    s = ApiServer()
    mgr = Manager(s)
    seen = []
    c = Controller(
        "t",
        lambda cl, r: seen.append(r.name),
        [Watch("Node", predicate=predicates.all_of(
            predicates.matching_name("n1"), predicates.annotations_changed))],
    )
    mgr.add_controller(c)
    s.create(Node(metadata=ObjectMeta(name="n1")))
    s.create(Node(metadata=ObjectMeta(name="n2")))
    mgr.run_until_idle()
    assert seen == ["n1"]
    # label-only change on n1 does not trigger (annotations unchanged)
    s.patch("Node", "n1", "", lambda n: n.metadata.labels.update({"x": "y"}))
    mgr.run_until_idle()
    assert seen == ["n1"]
    s.patch("Node", "n1", "", lambda n: n.metadata.annotations.update({"x": "y"}))
    mgr.run_until_idle()
    assert seen == ["n1", "n1"]


def test_multiple_watches_same_kind():
    s = ApiServer()
    mgr = Manager(s)
    seen = []
    c = Controller(
        "t",
        lambda cl, r: seen.append(r.name),
        [
            Watch("Pod", mapper=lambda ev: [Request(name="from-first")]),
            Watch("Pod", mapper=lambda ev: [Request(name="from-second")]),
        ],
    )
    mgr.add_controller(c)
    s.create(make_pod("p1"))
    mgr.run_until_idle()
    assert sorted(seen) == ["from-first", "from-second"]


def test_admission_hook_blocks_delete():
    s = ApiServer()

    def deny_delete(server, op, obj, old):
        if op == "DELETE":
            raise AdmissionDenied("protected")

    s.register_admission("Node", deny_delete)
    s.create(Node(metadata=ObjectMeta(name="n1")))
    with pytest.raises(AdmissionDenied):
        s.delete("Node", "n1")
    assert s.try_get("Node", "n1") is not None


def test_livelock_guard():
    s = ApiServer()
    mgr = Manager(s)

    def always_patch(client, req):
        def bump(n):
            n.metadata.annotations["count"] = str(
                int(n.metadata.annotations.get("count", "0")) + 1
            )
        client.patch("Node", req.name, "", bump)
        return Result()

    mgr.add_controller(Controller("livelock", always_patch, [Watch("Node")]))
    s.create(Node(metadata=ObjectMeta(name="n1")))
    with pytest.raises(RuntimeError, match="livelock"):
        mgr.run_until_idle(max_iterations=50)


def test_unsubscribe_stops_event_delivery():
    s = ApiServer()
    sub = s.subscribe()
    s.create(make_pod("p1"))
    s.unsubscribe(sub)
    s.create(make_pod("p2"))
    events = []
    while (ev := sub.pop()) is not None:
        events.append(ev.obj.metadata.name)
    assert events == ["p1"]


def test_noop_update_emits_no_event_and_keeps_rv():
    s = ApiServer()
    s.create(Node(metadata=ObjectMeta(name="n1")))
    sub = s.subscribe()
    n = s.get("Node", "n1")
    rv = n.metadata.resource_version
    s.update(n)                                    # identical content
    s.patch("Node", "n1", "", lambda x: None)      # no-op patch
    assert len(sub) == 0
    assert s.get("Node", "n1").metadata.resource_version == rv


def test_deep_copy_full_isolation_and_parity_with_deepcopy():
    """deep_copy is a hand-rolled clone (hot path of the apiserver
    double): it must isolate every mutable level and agree with
    copy.deepcopy for the API-object graphs we store."""
    import copy as _copy

    from nos_tpu.kube.objects import (
        Affinity, Container, NodeSelectorRequirement, NodeSelectorTerm,
        Pod, PodCondition, PodSpec, PodStatus, Toleration, deep_copy,
    )

    pod = Pod(
        metadata=ObjectMeta(
            name="p", namespace="ns", labels={"a": "1"},
            annotations={"x": "y"}, uid="u1", resource_version=7),
        spec=PodSpec(
            containers=[Container(requests={"cpu": 2, "google.com/tpu": 4})],
            node_name="n1",
            tolerations=[Toleration(key="k", operator="Exists")],
            affinity=Affinity(node_affinity_required=[NodeSelectorTerm(
                match_expressions=[NodeSelectorRequirement(
                    key="topo", operator="In", values=["2x2"])])]),
        ),
        status=PodStatus(phase="Running", conditions=[
            PodCondition(type="PodScheduled", status="True")]),
    )
    clone = deep_copy(pod)
    assert clone == pod
    assert clone == _copy.deepcopy(pod)
    # full isolation at every level
    clone.metadata.labels["a"] = "2"
    clone.spec.containers[0].requests["cpu"] = 99
    clone.spec.tolerations[0].key = "other"
    clone.status.conditions[0].status = "False"
    assert pod.metadata.labels["a"] == "1"
    assert pod.spec.containers[0].requests["cpu"] == 2
    assert pod.spec.tolerations[0].key == "k"
    assert pod.status.conditions[0].status == "True"


def test_deep_copy_exotic_values_fall_back():
    from nos_tpu.kube.objects import deep_copy

    class Odd:
        def __init__(self):
            self.xs = [1, 2]

    o = Odd()
    c = deep_copy(o)
    assert c is not o and c.xs == [1, 2]
    c.xs.append(3)
    assert o.xs == [1, 2]
    assert deep_copy({("t", 1): {4, 5}}) == {("t", 1): {4, 5}}
