"""Request-level latency ledger (models/serving.py _Ledger): the
host-clock lifecycle stamps behind the TTFT/TPOT/queue/e2e histograms
and the /stats snapshot.

The invariants this file pins (ISSUE 5 satellite):
- every emitted token is attributed to exactly ONE ledger arrival —
  under pipeline_depth in {1, 2} and fused decode alike, the per-token
  TPOT sample count is exactly output_tokens - 1 (first token excluded,
  no duplicates from late observation or rollback);
- rollback (a completion observed up to k ticks late, or a stop token
  detected mid-burst) produces neither negative nor duplicate samples;
- stamps are monotone: submit <= admit <= first token <= done;
- cancelled-while-pending requests close with outcome "cancelled" and
  no TTFT (no token was ever produced);
- first-dispatch-per-shape compile accounting counts warm paths zero.
"""
import jax
import jax.numpy as jnp
import pytest

from nos_tpu.models import transformer as tfm
from nos_tpu.models.generate import generate
from nos_tpu.models.serving import DecodeServer

CFG = tfm.TransformerConfig(vocab=64, d_model=32, n_layers=2, n_heads=4,
                            n_kv_heads=2, d_ff=64, max_seq=64,
                            dtype=jnp.float32)


@pytest.fixture(scope="module")
def params():
    return tfm.init_params(jax.random.PRNGKey(0), CFG)


def ref(params, prompt, n):
    out = generate(params, CFG, jnp.asarray([prompt], jnp.int32), n)
    return [int(t) for t in out[0]]


def tpot_tokens(led):
    return sum(n for _, n in led["tpot"])


@pytest.mark.parametrize("depth,steps", [(1, 1), (2, 1), (2, 4)])
def test_tokens_attributed_exactly_once(params, depth, steps):
    srv = DecodeServer(params, CFG, max_batch=2, pipeline_depth=depth,
                       decode_steps=steps)
    prompts = [([1, 2, 3], 7), ([9, 8], 5), ([4] * 5, 6)]
    rids = [srv.submit(p, n) for p, n in prompts]
    srv.drain()
    for rid, (p, n) in zip(rids, prompts):
        led = srv.pop_ledger(rid)
        assert led is not None and led["outcome"] == "finished"
        assert led["prompt_tokens"] == len(p)
        assert led["output_tokens"] == n
        # the first token came from prefill; every decode token earned
        # exactly one TPOT attribution, overrun ticks earned none
        assert tpot_tokens(led) == n - 1, (depth, steps, led["tpot"])
        assert all(gap >= 0.0 for gap, _ in led["tpot"])
        assert srv.pop_ledger(rid) is None      # handed out exactly once


@pytest.mark.parametrize("depth", [1, 2])
def test_stamps_are_monotone_and_ttft_bounds_e2e(params, depth):
    srv = DecodeServer(params, CFG, max_batch=1, pipeline_depth=depth)
    rid = srv.submit([5, 6, 7], 6)
    srv.drain()
    led = srv.pop_ledger(rid)
    assert led["queue_s"] >= 0.0
    assert led["prefill_s"] >= 0.0
    assert led["ttft_s"] is not None
    # ttft includes queue + prefill; e2e includes ttft + decode
    assert led["ttft_s"] >= led["queue_s"]
    assert led["e2e_s"] >= led["ttft_s"]


def test_rollback_no_duplicate_or_negative_samples(params):
    """A stop token produced early but OBSERVED up to depth*steps ticks
    late truncates the output; the over-decoded tokens the pos-reset
    rollback discards must never have earned TPOT samples."""
    full = ref(params, [4, 5], 16)
    stop = full[2 + 3]
    first_at = full.index(stop, 2)
    srv = DecodeServer(params, CFG, max_batch=1, pipeline_depth=2,
                       decode_steps=4)
    rid = srv.submit([4, 5], 16, stop_tokens=[stop])
    res = srv.drain()
    assert res[rid] == full[:first_at + 1]
    led = srv.pop_ledger(rid)
    n_out = len(res[rid]) - 2                   # generated tokens
    assert led["output_tokens"] == n_out
    assert tpot_tokens(led) == n_out - 1
    assert all(gap >= 0.0 for gap, _ in led["tpot"])


def test_queue_time_measured_behind_a_busy_slot(params):
    srv = DecodeServer(params, CFG, max_batch=1)
    first = srv.submit([1, 2], 12)
    waiter = srv.submit([3, 4], 3)              # pends behind first
    srv.drain()
    led_first = srv.pop_ledger(first)
    led_wait = srv.pop_ledger(waiter)
    # the waiter queued for (at least) the head request's decode run.
    # The lower bound is first's own DECODE span (the sum of its
    # inter-token gaps): the waiter was already pending before first's
    # second token, so every one of those gaps elapsed inside the
    # waiter's queue window. (Comparing against a fraction of first's
    # e2e — the old assertion — is machine-dependent: on a fast-decode
    # box e2e is dominated by first's own synchronous prefill, which
    # the waiter never waits on.)
    assert led_wait["queue_s"] > led_first["queue_s"]
    decode_span = sum(g for g, _ in led_first["tpot"])
    assert decode_span > 0
    assert led_wait["queue_s"] >= decode_span * 0.9


def test_cancel_pending_closes_ledger_without_ttft(params):
    srv = DecodeServer(params, CFG, max_batch=1)
    rid_a = srv.submit([1], 6)
    rid_b = srv.submit([2], 6)                  # pending
    assert srv.cancel(rid_b)
    led = srv.pop_ledger(rid_b)
    assert led["outcome"] == "cancelled"
    assert led["ttft_s"] is None and not led["tpot"]
    assert led["queue_s"] >= 0.0 and led["e2e_s"] >= led["queue_s"]
    srv.drain()
    assert srv.pop_ledger(rid_a)["outcome"] == "finished"


def test_cancel_active_keeps_partial_tpot(params):
    srv = DecodeServer(params, CFG, max_batch=1)
    rid = srv.submit([1, 2], 32)
    for _ in range(4):
        srv.step()
    assert srv.cancel(rid)
    led = srv.pop_ledger(rid)
    assert led["outcome"] == "cancelled"
    assert led["ttft_s"] is not None
    assert tpot_tokens(led) == led["output_tokens"] - 1


def test_ledger_registry_is_fifo_capped(params):
    srv = DecodeServer(params, CFG, max_batch=2)
    srv.ledger_cap = 2
    rids = [srv.submit([i + 1], 2) for i in range(4)]
    srv.drain()
    assert len(srv._ledgers) == 2
    assert srv.pop_ledger(rids[0]) is None      # FIFO-evicted
    assert srv.pop_ledger(rids[-1]) is not None


def test_ledger_disabled_skips_tpot_only(params):
    # the overhead-guard escape hatch: per-arrival stamping off, the
    # request-level milestones (TTFT/e2e) still recorded
    srv = DecodeServer(params, CFG, max_batch=1)
    srv.ledger_enabled = False
    rid = srv.submit([3, 1], 6)
    srv.drain()
    led = srv.pop_ledger(rid)
    assert led["ttft_s"] is not None and led["e2e_s"] > 0
    assert led["tpot"] == []


def test_spec_engine_ledger_attributes_bursts_once(params):
    from nos_tpu.models.spec_serving import SpeculativeDecodeServer

    dcfg = tfm.TransformerConfig(vocab=64, d_model=16, n_layers=1,
                                 n_heads=2, n_kv_heads=1, d_ff=32,
                                 max_seq=64, dtype=jnp.float32)
    dparams = tfm.init_params(jax.random.PRNGKey(1), dcfg)
    srv = SpeculativeDecodeServer(params, CFG, dparams, dcfg, n_draft=3,
                                  max_batch=2)
    rid = srv.submit([4, 5], 9)
    res = srv.drain()
    assert res[rid] == ref(params, [4, 5], 9)
    led = srv.pop_ledger(rid)
    # a verify burst may land several tokens in one arrival (with a
    # random-init draft the acceptance rate is chance, so burst size
    # is not asserted); attribution is still exactly one sample slot
    # per committed decode token
    assert tpot_tokens(led) == 8
    assert all(n >= 1 and gap >= 0.0 for gap, n in led["tpot"])


def test_compile_accounting_counts_cold_shapes_once(params):
    srv = DecodeServer(params, CFG, max_batch=2)
    assert srv.compiles == 0
    rid = srv.submit([1, 2, 3], 4)
    srv.drain()
    cold = srv.compiles
    assert cold >= 2                    # prefill bucket + decode program
    assert srv.compile_s >= 0.0
    assert len(srv.compile_events) == cold
    srv.pop_ledger(rid)
    # identical shape again: fully warm, zero new compile events
    srv.submit([7, 7, 7], 4)
    srv.drain()
    assert srv.compiles == cold


def test_engine_stats_snapshot_mid_flight(params):
    srv = DecodeServer(params, CFG, max_batch=2, pipeline_depth=2,
                       prefix_cache_size=2)
    r0 = srv.submit([1, 2], 16)
    srv.submit([3], 8)
    srv.submit([4, 5], 4)               # pends: both slots busy
    srv.step()
    snap = srv.stats()
    assert snap["engine"] == "DecodeServer"
    assert snap["max_batch"] == 2
    assert {s["rid"] for s in snap["slots"]} == {0, 1}
    for s in snap["slots"]:
        assert s["age_s"] >= 0.0
        assert s["pos"] >= s["tokens_out"] > 0
        assert set(s["sampling"]) == {"temperature", "top_k", "top_p",
                                      "seed"}
    assert snap["pending"]["depth"] == 1
    assert snap["pending"]["oldest_wait_s"] > 0.0
    assert snap["pipeline"]["depth"] == 2
    assert snap["pipeline"]["ticks_dispatched"] >= 1
    assert snap["prefix_cache"]["capacity"] == 2
    assert snap["compiles"]["count"] >= 1
    srv.cancel(r0)
    srv.drain()
    idle = srv.stats()
    assert idle["slots"] == [] and idle["pending"]["depth"] == 0
