"""Property tests for the scheduler's watch-fed ClusterCache
(scheduler/cache.py): under ANY interleaving of watch events — including
stale, duplicated, and out-of-order deliveries — the cache must converge
to the freshest-resourceVersion view, never regress an object to an
older RV, and bump its generation exactly when visible state changes.
The cache replaced per-event relists (the 1024-node scale point rests on
it), so these invariants carry the scheduler's correctness at scale.
"""
import random

from hypothesis import given, settings
from hypothesis import strategies as st

from nos_tpu.kube.objects import ObjectMeta, Pod, PodSpec
from nos_tpu.scheduler.cache import ClusterCache


class Ev:
    def __init__(self, type_, obj):
        self.type = type_
        self.obj = obj


def pod(name, rv, node=""):
    return Pod(metadata=ObjectMeta(name=name, namespace="ns",
                                   resource_version=str(rv)),
               spec=PodSpec(node_name=node))


NAMES = ["a", "b", "c"]


# events drawn natively so Hypothesis can SHRINK a failing interleaving
# to a minimal readable sequence (an opaque PRNG seed cannot shrink):
# (name, type, swap-with-next, duplicate-at-end) per history slot
EVENT_SLOTS = st.lists(
    st.tuples(
        st.sampled_from(NAMES),
        st.sampled_from(["ADDED", "MODIFIED", "MODIFIED", "DELETED"]),
        st.booleans(),
        st.booleans(),
    ),
    min_size=0, max_size=40,
)


@settings(max_examples=80, deadline=None)
@given(EVENT_SLOTS)
def test_cache_converges_to_freshest_view(slots):
    cache = ClusterCache()
    # each object's "true" history is RV-monotone, but delivery may swap
    # adjacent events and append stale duplicates (what a reconnecting
    # watch actually produces)
    history = [Ev(typ, pod(name, rv + 1))
               for rv, (name, typ, _, _) in enumerate(slots)]
    delivered = list(history)
    for i, (_, _, swap, _) in enumerate(slots[:-1]):
        if swap:
            delivered[i], delivered[i + 1] = delivered[i + 1], delivered[i]
    delivered += [ev for ev, (_, _, _, dup) in zip(list(delivered), slots)
                  if dup]

    for ev in delivered:
        cache.apply("Pod", ev)

    got = {(p.metadata.namespace or "", p.metadata.name): p
           for p in cache.list("Pod")}
    # the cache may legitimately differ from the naive model ONLY when a
    # reordered DELETE was followed by a stale re-add the model dropped;
    # assert the core invariant instead: every cached object carries the
    # highest RV ever delivered for its key, and no key exists that only
    # ever saw deletes
    highest = {}
    deleted_last_rv = {}
    for ev in delivered:
        key = (ev.obj.metadata.namespace or "", ev.obj.metadata.name)
        r = int(ev.obj.metadata.resource_version)
        if ev.type != "DELETED":
            highest[key] = max(highest.get(key, 0), r)
        else:
            deleted_last_rv[key] = max(deleted_last_rv.get(key, 0), r)
    for key, p in got.items():
        assert key in highest
        r = int(p.metadata.resource_version)
        if key not in deleted_last_rv:
            # delete-free keys: the cache must hold the freshest RV ever
            # delivered, whatever the delivery order. (A key whose DELETE
            # was reordered before a stale re-add may legitimately hold
            # the stale object until the next prime — real watches are
            # per-object ordered within a connection, and reconnects
            # re-prime; the cache does not try to outguess that.)
            assert r == highest[key], (
                f"{key} cached at rv {r}, but rv {highest[key]} was "
                "delivered — the cache regressed to a stale object")


@settings(max_examples=60, deadline=None)
@given(st.integers(0, 2**32 - 1), st.integers(1, 25))
def test_stale_events_never_regress_after_upsert(seed, n):
    # the bind path: upsert(server-returned object) then stale in-flight
    # events at <= that RV must be ignored (equal-RV events carry no new
    # information and would clobber locally-amended objects)
    rng = random.Random(seed)
    cache = ClusterCache()
    cache.upsert("Pod", pod("a", 10, node="n1"))
    for _ in range(n):
        stale_rv = rng.randint(1, 10)
        cache.apply("Pod", Ev("MODIFIED", pod("a", stale_rv, node="")))
    [p] = cache.list("Pod")
    assert p.spec.node_name == "n1"
    assert int(p.metadata.resource_version) == 10

    # a genuinely newer event lands
    cache.apply("Pod", Ev("MODIFIED", pod("a", 11, node="n2")))
    [p] = cache.list("Pod")
    assert p.spec.node_name == "n2"


@settings(max_examples=60, deadline=None)
@given(st.integers(0, 2**32 - 1), st.integers(0, 30))
def test_generation_bumps_iff_visible_state_changes(seed, n):
    rng = random.Random(seed)
    cache = ClusterCache()
    rv = 0
    for _ in range(n):
        before_objs = {k: dict(v) for k, v in cache._objs.items()}
        before_gen = cache.generation
        kind = rng.choice(["fresh", "stale", "delete_missing"])
        if kind == "fresh":
            rv += 1
            cache.apply("Pod", Ev("MODIFIED", pod("a", rv)))
        elif kind == "stale":
            cache.apply("Pod", Ev("MODIFIED", pod("a", 0)))
        else:
            cache.apply("Pod", Ev("DELETED", pod("zzz-missing", rv)))
        changed = before_objs != {k: dict(v) for k, v in cache._objs.items()}
        bumped = cache.generation != before_gen
        assert bumped == changed, (
            f"generation {'bumped without' if bumped else 'missed'} a "
            f"visible change (op={kind})")


def test_remove_and_upsert_roundtrip_generation():
    cache = ClusterCache()
    p = pod("a", 1)
    g0 = cache.generation
    cache.upsert("Pod", p)
    assert cache.generation == g0 + 1
    cache.remove("Pod", p)
    assert cache.generation == g0 + 2
    cache.remove("Pod", p)                  # absent: no phantom bump
    assert cache.generation == g0 + 2
