"""Cache property tests, two subsystems:

1. The scheduler's watch-fed ClusterCache (scheduler/cache.py): under
   ANY interleaving of watch events — including stale, duplicated, and
   out-of-order deliveries — the cache must converge to the freshest-
   resourceVersion view, never regress an object to an older RV, and
   bump its generation exactly when visible state changes. These use
   hypothesis when available (guarded import: environments without it
   skip rather than failing collection).
2. The paged-KV BlockAllocator / PrefixBlockIndex
   (models/kvblocks.py): fuzzed alloc/free/fork/write sequences must
   keep every referenced block at refcount >= 1, never double-free,
   never lose a block, and never let a COW fork alias a written block.
   Pure seeded-``random`` fuzzing — jax-free and hypothesis-free, so
   the serving engine's memory-safety net runs everywhere.
"""
import random

import pytest

try:
    from hypothesis import given, settings
    from hypothesis import strategies as st
    HAVE_HYPOTHESIS = True
except ImportError:        # pragma: no cover - environment-dependent
    HAVE_HYPOTHESIS = False

from nos_tpu.kube.objects import ObjectMeta, Pod, PodSpec
from nos_tpu.models.kvblocks import (
    BlockAllocator, NoFreeBlocks, PrefixBlockIndex, ScaleLedger,
    blocks_for,
)
from nos_tpu.scheduler.cache import ClusterCache


class Ev:
    def __init__(self, type_, obj):
        self.type = type_
        self.obj = obj


def pod(name, rv, node=""):
    return Pod(metadata=ObjectMeta(name=name, namespace="ns",
                                   resource_version=str(rv)),
               spec=PodSpec(node_name=node))


NAMES = ["a", "b", "c"]


# events drawn natively so Hypothesis can SHRINK a failing interleaving
# to a minimal readable sequence (an opaque PRNG seed cannot shrink):
# (name, type, swap-with-next, duplicate-at-end) per history slot
if HAVE_HYPOTHESIS:
    EVENT_SLOTS = st.lists(
        st.tuples(
            st.sampled_from(NAMES),
            st.sampled_from(["ADDED", "MODIFIED", "MODIFIED", "DELETED"]),
            st.booleans(),
            st.booleans(),
        ),
        min_size=0, max_size=40,
    )
else:       # keep the decorators below importable: skip at run time
    def settings(**kw):
        return lambda f: pytest.mark.skip(reason="hypothesis missing")(f)

    def given(*a, **kw):
        return lambda f: f

    class _StStub:
        def __getattr__(self, name):
            return lambda *a, **kw: None

    st = _StStub()
    EVENT_SLOTS = None


@settings(max_examples=80, deadline=None)
@given(EVENT_SLOTS)
def test_cache_converges_to_freshest_view(slots):
    cache = ClusterCache()
    # each object's "true" history is RV-monotone, but delivery may swap
    # adjacent events and append stale duplicates (what a reconnecting
    # watch actually produces)
    history = [Ev(typ, pod(name, rv + 1))
               for rv, (name, typ, _, _) in enumerate(slots)]
    delivered = list(history)
    for i, (_, _, swap, _) in enumerate(slots[:-1]):
        if swap:
            delivered[i], delivered[i + 1] = delivered[i + 1], delivered[i]
    delivered += [ev for ev, (_, _, _, dup) in zip(list(delivered), slots)
                  if dup]

    for ev in delivered:
        cache.apply("Pod", ev)

    got = {(p.metadata.namespace or "", p.metadata.name): p
           for p in cache.list("Pod")}
    # the cache may legitimately differ from the naive model ONLY when a
    # reordered DELETE was followed by a stale re-add the model dropped;
    # assert the core invariant instead: every cached object carries the
    # highest RV ever delivered for its key, and no key exists that only
    # ever saw deletes
    highest = {}
    deleted_last_rv = {}
    for ev in delivered:
        key = (ev.obj.metadata.namespace or "", ev.obj.metadata.name)
        r = int(ev.obj.metadata.resource_version)
        if ev.type != "DELETED":
            highest[key] = max(highest.get(key, 0), r)
        else:
            deleted_last_rv[key] = max(deleted_last_rv.get(key, 0), r)
    for key, p in got.items():
        assert key in highest
        r = int(p.metadata.resource_version)
        if key not in deleted_last_rv:
            # delete-free keys: the cache must hold the freshest RV ever
            # delivered, whatever the delivery order. (A key whose DELETE
            # was reordered before a stale re-add may legitimately hold
            # the stale object until the next prime — real watches are
            # per-object ordered within a connection, and reconnects
            # re-prime; the cache does not try to outguess that.)
            assert r == highest[key], (
                f"{key} cached at rv {r}, but rv {highest[key]} was "
                "delivered — the cache regressed to a stale object")


@settings(max_examples=60, deadline=None)
@given(st.integers(0, 2**32 - 1), st.integers(1, 25))
def test_stale_events_never_regress_after_upsert(seed, n):
    # the bind path: upsert(server-returned object) then stale in-flight
    # events at <= that RV must be ignored (equal-RV events carry no new
    # information and would clobber locally-amended objects)
    rng = random.Random(seed)
    cache = ClusterCache()
    cache.upsert("Pod", pod("a", 10, node="n1"))
    for _ in range(n):
        stale_rv = rng.randint(1, 10)
        cache.apply("Pod", Ev("MODIFIED", pod("a", stale_rv, node="")))
    [p] = cache.list("Pod")
    assert p.spec.node_name == "n1"
    assert int(p.metadata.resource_version) == 10

    # a genuinely newer event lands
    cache.apply("Pod", Ev("MODIFIED", pod("a", 11, node="n2")))
    [p] = cache.list("Pod")
    assert p.spec.node_name == "n2"


@settings(max_examples=60, deadline=None)
@given(st.integers(0, 2**32 - 1), st.integers(0, 30))
def test_generation_bumps_iff_visible_state_changes(seed, n):
    rng = random.Random(seed)
    cache = ClusterCache()
    rv = 0
    for _ in range(n):
        before_objs = {k: dict(v) for k, v in cache._objs.items()}
        before_gen = cache.generation
        kind = rng.choice(["fresh", "stale", "delete_missing"])
        if kind == "fresh":
            rv += 1
            cache.apply("Pod", Ev("MODIFIED", pod("a", rv)))
        elif kind == "stale":
            cache.apply("Pod", Ev("MODIFIED", pod("a", 0)))
        else:
            cache.apply("Pod", Ev("DELETED", pod("zzz-missing", rv)))
        changed = before_objs != {k: dict(v) for k, v in cache._objs.items()}
        bumped = cache.generation != before_gen
        assert bumped == changed, (
            f"generation {'bumped without' if bumped else 'missed'} a "
            f"visible change (op={kind})")


def test_remove_and_upsert_roundtrip_generation():
    cache = ClusterCache()
    p = pod("a", 1)
    g0 = cache.generation
    cache.upsert("Pod", p)
    assert cache.generation == g0 + 1
    cache.remove("Pod", p)
    assert cache.generation == g0 + 2
    cache.remove("Pod", p)                  # absent: no phantom bump
    assert cache.generation == g0 + 2


# ---------------------------------------------------------------------------
# paged-KV BlockAllocator: fuzzed alloc/free/fork/write sequences
# (ISSUE 6 satellite). A "holder" models one serving slot's block
# table; "write" models the engine's pre-write COW discipline
# (_ensure_blocks): a shared block must be copied, never mutated.
# ---------------------------------------------------------------------------

def _check_conservation(alloc, holders):
    """No lost blocks, no phantom refs: the allocator's refcounts must
    equal exactly the references the model holds, and free + used must
    tile the pool."""
    refs = {}
    for table in holders.values():
        for b in table:
            refs[b] = refs.get(b, 0) + 1
    assert alloc.free_count + alloc.used_count == alloc.capacity
    for b in range(1, alloc.num_blocks):
        assert alloc.ref(b) == refs.get(b, 0), (
            f"block {b}: allocator ref {alloc.ref(b)} != "
            f"model ref {refs.get(b, 0)}")
    for b, n in refs.items():
        assert n >= 1 and alloc.ref(b) >= 1
    # the O(1) shared counter must track the model exactly
    assert alloc.shared_count() == sum(1 for n in refs.values() if n > 1)


@pytest.mark.parametrize("seed", range(8))
def test_allocator_fuzz_alloc_free_fork_write(seed):
    rng = random.Random(seed)
    alloc = BlockAllocator(num_blocks=rng.randint(4, 33),
                           block_size=8)
    holders = {}                        # holder id -> list of block ids
    writes = {}                         # block id -> sole writer id
    next_h = 0
    for _ in range(600):
        op = rng.random()
        if op < 0.35:                                   # alloc
            try:
                b = alloc.alloc()
            except NoFreeBlocks:
                assert alloc.free_count == 0
                continue
            holders.setdefault(next_h, []).append(b)
            next_h += 1
        elif op < 0.55 and holders:                     # free a holder
            h = rng.choice(list(holders))
            for b in holders.pop(h):
                alloc.decref(b)
                writes.pop(b, None) if alloc.ref(b) == 0 else None
        elif op < 0.8 and holders:                      # fork a holder
            h = rng.choice(list(holders))
            holders.setdefault(next_h, []).extend(
                alloc.fork(holders[h]))
            next_h += 1
        elif holders:                                   # write (COW)
            h = rng.choice(list(holders))
            table = holders[h]
            if not table:
                continue
            i = rng.randrange(len(table))
            b = table[i]
            if alloc.writable(b):
                # sole holder: in-place write allowed; record the
                # writer so aliasing would be detectable
                assert writes.get(b, h) == h or alloc.ref(b) == 1
                writes[b] = h
            else:
                # shared: the COW discipline — copy, then write the
                # copy; the original must still be referenced by the
                # OTHER holders and must never gain this write
                try:
                    fresh = alloc.alloc()
                except NoFreeBlocks:
                    continue
                alloc.decref(b)
                table[i] = fresh
                writes[fresh] = h
                assert alloc.ref(b) >= 1, \
                    "COW source lost its other holders' refs"
                assert alloc.writable(fresh), \
                    "freshly COW'd block must be exclusively owned"
        _check_conservation(alloc, holders)
    # drain everything: the pool must come back whole
    for h in list(holders):
        for b in holders.pop(h):
            alloc.decref(b)
    assert alloc.free_count == alloc.capacity
    assert alloc.used_count == 0
    assert alloc.shared_count() == 0


def test_allocator_double_free_and_bad_refs_raise():
    alloc = BlockAllocator(num_blocks=4, block_size=8)
    b = alloc.alloc()
    alloc.decref(b)
    with pytest.raises(ValueError, match="double free"):
        alloc.decref(b)
    with pytest.raises(ValueError, match="unreferenced"):
        alloc.incref(b)
    with pytest.raises(ValueError, match="null block"):
        alloc.decref(0)
    with pytest.raises(ValueError, match="null block"):
        alloc.incref(0)
    with pytest.raises(NoFreeBlocks):
        alloc.alloc_many(99)
    assert alloc.free_count == alloc.capacity   # failed alloc leaked nothing


def test_cow_fork_never_aliases_a_written_block():
    # the acceptance property stated directly: after fork, any write
    # through either holder lands in a block the other cannot see
    alloc = BlockAllocator(num_blocks=8, block_size=8)
    a = alloc.alloc_many(3)
    b = alloc.fork(a)
    assert a == b and all(not alloc.writable(x) for x in a)
    # writer COWs block 1
    fresh = alloc.alloc()
    alloc.decref(b[1])
    b[1] = fresh
    assert b[1] != a[1]
    assert alloc.writable(b[1])         # writer owns its copy
    assert alloc.writable(a[1])         # other holder now sole owner too
    for x in set(a + b):
        while alloc.ref(x):
            alloc.decref(x)
    assert alloc.free_count == alloc.capacity


# ---------------------------------------------------------------------------
# per-block SCALE lifecycle (ISSUE 10 satellite): an int8 arena stores
# quantization scales per PHYSICAL block. The ledger must stay in
# lockstep with the allocator — written blocks carry a scale entry, COW
# copies duplicate it, frees drop it via the allocator's decref hook —
# or a reused block could present a stale scale as fresh data's.
# ---------------------------------------------------------------------------

def _check_scales(alloc, ledger, holders, written):
    referenced = {b for t in holders.values() for b in t}
    for b in list(ledger._ver):
        assert b in referenced, \
            f"block {b} freed but its scale entry survived"
    for b in written:
        if b in referenced:
            assert ledger.version(b) is not None, \
                f"written live block {b} lost its scale entry"


@pytest.mark.parametrize("seed", range(6))
def test_scale_ledger_fuzz_lockstep_with_allocator(seed):
    rng = random.Random(500 + seed)
    alloc = BlockAllocator(num_blocks=rng.randint(4, 25), block_size=8)
    ledger = ScaleLedger()
    alloc.scale_ledger = ledger         # frees drop entries in lockstep
    holders = {}
    written = set()
    next_h = 0
    for _ in range(500):
        op = rng.random()
        if op < 0.3:                                    # alloc + write
            try:
                b = alloc.alloc()
            except NoFreeBlocks:
                continue
            holders.setdefault(next_h, []).append(b)
            next_h += 1
            ledger.note_write(b)        # install quantizes on write
            written.add(b)
        elif op < 0.5 and holders:                      # free a holder
            h = rng.choice(list(holders))
            for b in holders.pop(h):
                alloc.decref(b)
        elif op < 0.7 and holders:                      # fork (shares
            h = rng.choice(list(holders))               # scales by id)
            holders.setdefault(next_h, []).extend(
                alloc.fork(holders[h]))
            next_h += 1
        elif holders:                                   # COW write
            h = rng.choice(list(holders))
            table = holders[h]
            if not table:
                continue
            i = rng.randrange(len(table))
            b = table[i]
            if alloc.writable(b):
                ledger.note_write(b)
                written.add(b)
            else:
                try:
                    fresh = alloc.alloc()
                except NoFreeBlocks:
                    continue
                # the engine's COW order: device-copy data+scales,
                # then the dispatch's scatter stamps the new write
                ledger.note_copy(b, fresh)
                if b in written:
                    assert ledger.version(fresh) == ledger.version(b), \
                        "COW copy must carry the source's scale version"
                alloc.decref(b)
                table[i] = fresh
                ledger.note_write(fresh)
                written.add(fresh)
        _check_scales(alloc, ledger, holders, written)
    for h in list(holders):
        for b in holders.pop(h):
            alloc.decref(b)
    assert alloc.free_count == alloc.capacity
    assert ledger.count == 0, \
        "a fully drained pool must leave no scale entries behind"


def test_scale_ledger_copy_and_free_semantics():
    alloc = BlockAllocator(num_blocks=6, block_size=8)
    led = ScaleLedger()
    alloc.scale_ledger = led
    a = alloc.alloc()
    led.note_write(a)
    v = led.version(a)
    b = alloc.alloc()
    led.note_copy(a, b)
    assert led.version(b) == v          # COW: same data, same version
    led.note_write(b)
    assert led.version(b) != v          # a later write re-stamps
    # copy from an unwritten source is a no-op, not a phantom entry
    c = alloc.alloc()
    d = alloc.alloc()
    led.note_copy(c, d)
    assert led.version(d) is None
    alloc.decref(a)
    assert led.version(a) is None       # freed in lockstep (hook)
    for blk in (b, c, d):
        alloc.decref(blk)
    assert led.count == 0


@pytest.mark.parametrize("seed", range(4))
def test_prefix_index_fuzz_conserves_blocks(seed):
    rng = random.Random(1000 + seed)
    alloc = BlockAllocator(num_blocks=24, block_size=4)
    idx = PrefixBlockIndex(alloc, max_blocks=rng.randint(2, 10))
    live = {}                           # chain tokens -> our own refs
    for _ in range(200):
        op = rng.random()
        if op < 0.5:
            # publish a random prompt (holder allocates, publishes,
            # then drops its own refs — the slot-lifecycle shape)
            plen = rng.randint(1, 16)
            prompt = tuple(rng.randrange(4) for _ in range(plen))
            need = blocks_for(plen, 4)
            try:
                table = alloc.alloc_many(need)
            except NoFreeBlocks:
                continue
            idx.publish(prompt, table)
            for b in table:
                alloc.decref(b)
        elif op < 0.8:
            # match + take, then release (the admission shape)
            plen = rng.randint(2, 16)
            prompt = [rng.randrange(4) for _ in range(plen)]
            m, key = idx.match(prompt, plen - 1)
            assert m % 4 == 0
            if m > 0:
                # chain keys are (scope, tokens); scope None when the
                # engine runs without tenant quota
                assert key[0] is None
                assert tuple(prompt[:m]) == key[1][:m]
                shared = idx.take(key, m)
                assert all(alloc.ref(b) >= 2 for b in shared)
                for b in shared:
                    alloc.decref(b)
        else:
            idx.evict_lru(rng.randint(1, 4))
        assert idx.block_count <= max(idx.max_blocks,
                                      max((blocks_for(len(k[1]), 4)
                                           for k in idx._chains), default=0))
        assert alloc.used_count == idx.block_count
    idx.clear()
    assert alloc.free_count == alloc.capacity, live
