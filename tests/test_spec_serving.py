"""Speculative decoding inside the continuous-batching engine
(models/spec_serving.py): greedy rows bit-identical to plain target
decoding, per-row independent advance (no min-across-rows), slot
recycling, stop tokens mid-round, and seeded sampled rows reproducible
regardless of batch composition.
"""
import jax
import jax.numpy as jnp
import pytest

from nos_tpu.models import transformer as tfm
from nos_tpu.models.generate import generate
from nos_tpu.models.spec_serving import SpeculativeDecodeServer

TARGET = dict(vocab=64, d_model=32, n_layers=2, n_heads=4, n_kv_heads=2,
              d_ff=64, max_seq=64, dtype=jnp.float32)
DRAFT = dict(vocab=64, d_model=16, n_layers=1, n_heads=2, n_kv_heads=1,
             d_ff=32, max_seq=64, dtype=jnp.float32)

TCFG = tfm.TransformerConfig(**TARGET)
DCFG = tfm.TransformerConfig(**DRAFT)


@pytest.fixture(scope="module")
def models():
    return (tfm.init_params(jax.random.PRNGKey(0), TCFG),
            tfm.init_params(jax.random.PRNGKey(1), DCFG))


def ref(tp, prompt, n):
    return [int(t) for t in
            generate(tp, TCFG, jnp.asarray([prompt], jnp.int32), n)[0]]


def mk(models, **kw):
    tp, dp = models
    kw.setdefault("n_draft", 3)
    kw.setdefault("max_batch", 2)
    return SpeculativeDecodeServer(tp, TCFG, dp, DCFG, **kw)


def test_greedy_rows_bit_identical_to_target(models):
    tp, _ = models
    srv = mk(models)
    r1 = srv.submit([4, 5], 10)
    r2 = srv.submit([9, 8, 7], 8)
    res = srv.drain()
    assert res[r1] == ref(tp, [4, 5], 10)
    assert res[r2] == ref(tp, [9, 8, 7], 8)


def test_slot_recycling_and_late_arrival(models):
    tp, _ = models
    srv = mk(models, max_batch=1)
    rids = {srv.submit([p], 6): [p] for p in (3, 1, 9)}   # queue depth 3
    res = srv.drain()
    for rid, prompt in rids.items():
        assert res[rid] == ref(tp, prompt, 6), prompt

    # late arrival joins mid-flight
    ra = srv.submit([4, 5], 12)
    srv.step()
    rb = srv.submit([7], 4)                               # pending
    res = srv.drain()
    assert res[ra] == ref(tp, [4, 5], 12)
    assert res[rb] == ref(tp, [7], 4)


def test_rows_advance_independently(models):
    # the engine must NOT advance all rows by the minimum acceptance:
    # two different prompts finish in the same drain with exact outputs,
    # and a tick can emit more than max_batch tokens total
    srv = mk(models)
    srv.submit([4, 5], 12)
    srv.submit([9], 12)
    total = 0
    ticks = 0
    while srv.has_work():
        total += srv.step()
        ticks += 1
    assert total == 22                    # prefill emitted the first 2
    assert ticks < 22                     # fewer ticks than tokens


def test_stop_token_mid_round(models):
    tp, _ = models
    full = ref(tp, [4, 5], 12)
    stop = full[2 + 4]
    first_at = full.index(stop, 2)
    srv = mk(models)
    rid = srv.submit([4, 5], 12, stop_tokens=[stop])
    res = srv.drain()
    assert res[rid] == full[:first_at + 1]
    assert not srv._active and len(srv._free) == 2        # slot released


def test_sampled_rows_reproducible_and_batch_invariant(models):
    srv = mk(models)
    kw = dict(temperature=0.9, top_k=8, seed=17)
    r1 = srv.submit([4, 5], 8, **kw)
    alone = srv.drain()[r1]

    srv2 = mk(models)
    r2 = srv2.submit([4, 5], 8, **kw)                     # same seed
    r3 = srv2.submit([9, 9], 8, temperature=1.2, seed=5)  # noisy neighbour
    res = srv2.drain()
    assert res[r2] == alone                               # batch-invariant
    assert len(res[r3]) == 2 + 8


def test_mixed_greedy_and_sampled_batch(models):
    tp, _ = models
    srv = mk(models)
    rg = srv.submit([4, 5], 8)                            # greedy row
    rs = srv.submit([9], 8, temperature=0.8, seed=3)      # sampled row
    res = srv.drain()
    assert res[rg] == ref(tp, [4, 5], 8)                  # still bit-exact
    assert len(res[rs]) == 1 + 8


def test_prefix_cache_composes_with_spec(models):
    tp, _ = models
    system = [7, 3, 5, 9, 2, 4, 1, 8, 6, 2]
    srv = mk(models, prefix_cache_size=2)
    srv.submit(system, 1, cache_prefix=True)
    srv.drain()
    rid = srv.submit(system + [11], 8)
    res = srv.drain()
    assert srv.prefix_hits == 1
    assert res[rid] == ref(tp, system + [11], 8)


def test_vocab_mismatch_rejected(models):
    tp, dp = models
    bad = tfm.TransformerConfig(**{**DRAFT, "vocab": 32})
    with pytest.raises(ValueError, match="vocabulary"):
        SpeculativeDecodeServer(tp, TCFG, dp, bad)


# ---------------------------------------------------------------------------
# sampled-row distribution exactness (engine twin of
# test_speculative_sampling.py). The FIRST generated token comes from
# prefill (already distribution-tested for the base engine); spec
# sampling governs tokens 2..N, so exactness is checked on the SECOND
# token conditioned on the observed first, over a small vocab where the
# empirical test has power.
# ---------------------------------------------------------------------------

SVOCAB = 13
ST = tfm.TransformerConfig(vocab=SVOCAB, d_model=16, n_layers=2, n_heads=2,
                           d_ff=32, max_seq=64, dtype=jnp.float32)
SD = tfm.TransformerConfig(vocab=SVOCAB, d_model=8, n_layers=1, n_heads=2,
                           d_ff=16, max_seq=64, dtype=jnp.float32)


def _exact_next_dist(tp, cfg, prompt_row, temperature):
    import numpy as np
    from nos_tpu.models.generate import (
        _truncate_logits, forward_with_cache, init_cache,
    )

    prompt = jnp.asarray([prompt_row], jnp.int32)
    cache = init_cache(cfg, 1, cfg.max_seq)
    logits, _ = forward_with_cache(tp, cfg, prompt, cache)
    t = logits[0, -1] / temperature
    return np.asarray(jax.nn.softmax(_truncate_logits(t, 0, 0.0)))


def test_spec_second_token_distribution_matches_target():
    import numpy as np

    tp = tfm.init_params(jax.random.PRNGKey(0), ST)
    dp = tfm.init_params(jax.random.PRNGKey(9), SD)
    prompt = [1, 7, 3]
    temp = 0.8
    srv = SpeculativeDecodeServer(tp, ST, dp, SD, n_draft=3, max_batch=8)
    n = 512
    rids = [srv.submit(prompt, 2, temperature=temp, seed=s)
            for s in range(n)]
    res = srv.drain()
    pairs = [(res[r][3], res[r][4]) for r in rids]

    # condition on the most frequent first token (biggest cohort)
    firsts = np.bincount([a for a, _ in pairs], minlength=SVOCAB)
    t1 = int(np.argmax(firsts))
    cohort = [b for a, b in pairs if a == t1]
    assert len(cohort) >= 80, f"cohort too small ({len(cohort)})"
    freq = np.bincount(cohort, minlength=SVOCAB) / len(cohort)
    p_exact = _exact_next_dist(tp, ST, prompt + [t1], temp)
    tvd = 0.5 * float(np.abs(freq - p_exact).sum())
    # 13 categories, >=80 samples: sampling noise alone sits ~0.08-0.12
    assert tvd < 0.2, (tvd, len(cohort), freq, p_exact)


def test_spec_sampled_tokens_stay_in_truncated_support():
    import numpy as np
    from nos_tpu.models.generate import (
        _truncate_logits_rows, forward_with_cache, init_cache,
    )

    tp = tfm.init_params(jax.random.PRNGKey(0), ST)
    dp = tfm.init_params(jax.random.PRNGKey(9), SD)
    srv = SpeculativeDecodeServer(tp, ST, dp, SD, n_draft=3, max_batch=4)
    rids = [srv.submit([1, 7, 3], 8, temperature=0.9, top_k=4, seed=s)
            for s in range(8)]
    res = srv.drain()
    for rid in rids:
        seq = jnp.asarray([res[rid]], jnp.int32)
        cache = init_cache(ST, 1, ST.max_seq)
        logits, _ = forward_with_cache(tp, ST, seq, cache)
        # teacher-forced: every generated token must lie in the target's
        # top-4 support given its own prefix
        for pos in range(3, seq.shape[1]):
            prev_logits = logits[:, pos - 1] / 0.9
            trunc = _truncate_logits_rows(
                prev_logits, jnp.asarray([4]), jnp.asarray([0.0]))
            ok = bool(jnp.isfinite(trunc[0, int(seq[0, pos])]))
            assert ok, f"token at {pos} left the top-k support"


def test_headroom_guard_rejects_overrunning_requests(models):
    srv = mk(models, max_batch=1)          # max_len = TCFG.max_seq = 64
    with pytest.raises(ValueError, match="draft window"):
        srv.submit(list(range(1, 59)), 4)  # 58 + 4 + 3 > 64
    # the same request fits the plain engine's check — the spec guard is
    # strictly tighter by k
    assert 58 + 4 <= 64


def test_recursive_admit_keeps_draft_cache_fresh(models):
    tp, _ = models
    # C occupies the slot; A (instant-finish) and B queue behind it.
    # When C completes, _admit prefills A, A finishes INSIDE its own
    # prefill and recursively admits B — the stale-install bug would
    # then overwrite B's draft row with A's prompt on return
    srv = mk(models, max_batch=1)
    rc = srv.submit([2], 2)
    ra = srv.submit([4, 5], 1)
    rb = srv.submit([9, 8, 7], 4)
    while rb not in {r.rid for r in srv._active.values()}:
        srv.step()
    # invariant: processed == committed[:-1], so pos = plen + out - 1
    assert int(srv.d_cache["pos"][0]) == 3 + len(srv._active[0].out) - 1, (
        "draft row does not reflect B's prompt — stale install")
    res = srv.drain()
    assert res[rc] == ref(tp, [2], 2)
    assert res[ra] == ref(tp, [4, 5], 1)
    assert res[rb] == ref(tp, [9, 8, 7], 4)


def test_spec_tokens_invariant_to_tp_mesh(models):
    """Speculative engine over a ('tp',) mesh: target AND draft caches
    sharded across KV heads, tokens identical to the unsharded run."""
    import numpy as np
    from jax.sharding import Mesh, PartitionSpec as P

    tparams, _ = models
    # draft with tp-shardable KV heads (the module DRAFT has kv_heads=1)
    dcfg2 = tfm.TransformerConfig(
        vocab=64, d_model=16, n_layers=1, n_heads=2, n_kv_heads=2,
        d_ff=32, max_seq=64, dtype=jnp.float32)
    dparams2 = tfm.init_params(jax.random.PRNGKey(2), dcfg2)

    def run(srv):
        a = srv.submit([4, 5], 10)
        b = srv.submit([9, 8, 7], 8, temperature=0.7, top_k=8, seed=5)
        out = srv.drain()
        return out[a], out[b]

    want = run(SpeculativeDecodeServer(
        tparams, TCFG, dparams2, dcfg2, n_draft=3, max_batch=2))

    mesh = Mesh(np.asarray(jax.devices()[:2]), ("tp",))
    stp = jax.device_put(tparams, tfm.param_shardings(mesh, TCFG))
    sdp = jax.device_put(dparams2, tfm.param_shardings(mesh, dcfg2))
    srv = SpeculativeDecodeServer(
        stp, TCFG, sdp, dcfg2, n_draft=3, max_batch=2, mesh=mesh)
    assert srv.d_cache["k"].sharding.spec == P(None, None, "tp", None, None)
    assert run(srv) == want


# slow tier (~12s, the biggest fuzz in tier-1): the pairwise
# composition tests above and the plain-engine random-schedule fuzzes
# (test_serving/test_serving_paged/test_serving_pipeline) stay tier-1
@pytest.mark.slow
def test_random_schedules_compose_all_spec_features(models):
    """Composition prober for the SPECULATIVE engine: random config
    (chunked prefill on/off, prefix cache on/off, draft depth), random
    prefix publish/reuse, random mid-flight cancels, random
    interleavings — every surviving greedy request stays bit-exact vs
    plain target decoding. The pairwise tests above localize failures;
    this hunts three-way interactions in the most complex engine."""
    import numpy as np

    tp, _ = models
    rng = np.random.default_rng(11)
    # stratified over the {chunk} x {pcache} grid — a fixed-seed random
    # draw of the config left entire combinations unexercised (reviewer
    # replay showed 3 random trials never enabled the prefix cache)
    for trial, (chunk, pcache) in enumerate(
            [(0, 0), (8, 0), (0, 2), (8, 2)]):
        srv = mk(models, n_draft=int(rng.integers(2, 5)),
                 prefill_chunk=chunk, prefix_cache_size=pcache)
        system = [int(t) for t in rng.integers(0, 64, 10)]
        rids, reqs, canceled = [], [], set()
        for _ in range(int(rng.integers(3, 6))):
            if pcache and rng.random() < 0.5:
                p = system + [int(t) for t in
                              rng.integers(0, 64, rng.integers(1, 12))]
            else:
                p = [int(t) for t in rng.integers(0, 64, rng.integers(1, 30))]
            n = int(rng.integers(1, 7))
            kw = {"cache_prefix": True} \
                if pcache and rng.random() < 0.5 else {}
            rids.append(srv.submit(p, n, **kw))
            reqs.append((p, n))
            if rng.random() < 0.3:
                j = int(rng.integers(0, len(rids)))
                if rids[j] not in canceled and srv.cancel(rids[j]):
                    canceled.add(rids[j])
            for _ in range(int(rng.integers(0, 3))):
                srv.step()
        results = srv.drain()
        for rid, (p, n) in zip(rids, reqs):
            if rid in canceled:
                continue
            assert results[rid] == ref(tp, p, n), (trial, chunk, pcache, rid)
