"""Request-level elastic quota in the serving engine (ISSUE 13
tentpole): weighted tenant admission replacing FIFO, min-guarantee,
preemptive reclaim with bit-exact resume, over-max sheds with the
machine-readable ``tenant_quota`` reason, and tenant-scoped prefix
caches (slot-static and paged alike)."""
import jax
import jax.numpy as jnp
import pytest

from nos_tpu.models import transformer as tfm
from nos_tpu.models.generate import generate
from nos_tpu.models.serving import DecodeServer, TenantQuotaExceeded
from nos_tpu.models.tenantquota import (
    TenantQuotaConfig, TenantSpec,
)

CFG = tfm.TransformerConfig(vocab=64, d_model=32, n_layers=2, n_heads=4,
                            n_kv_heads=2, d_ff=64, max_seq=64,
                            dtype=jnp.float32)


@pytest.fixture(scope="module")
def params():
    return tfm.init_params(jax.random.PRNGKey(0), CFG)


def ref(params, prompt, n):
    out = generate(params, CFG, jnp.asarray([prompt], jnp.int32), n)
    return [int(t) for t in out[0]]


def quota(window_s=8.0, gold_min=100.0, burst_max=5.0,
          share_prefix=False):
    return TenantQuotaConfig(
        tenants={
            "gold": TenantSpec("gold", min_rate=gold_min),
            "burst": TenantSpec("burst", max_rate=burst_max),
        }, window_s=window_s, share_prefix=share_prefix)


def paged_engine(params, tq, clock, **kw):
    kw.setdefault("max_batch", 2)
    kw.setdefault("max_len", 64)
    kw.setdefault("kv_block_size", 8)
    kw.setdefault("kv_blocks", 17)
    return DecodeServer(params, CFG, tenant_quota=tq,
                        tenant_clock=lambda: clock[0], **kw)


# ---------------------------------------------------------------------------
# weighted admission
# ---------------------------------------------------------------------------

def test_guaranteed_tenant_admitted_before_borrower(params):
    """With one slot and both tenants pending, the under-min gold
    tenant's request must admit first even though the burst request
    arrived earlier — the FIFO pop is gone. Slot-static engine:
    reclaim needs paging, so the QUEUE ordering is observed alone
    (the paged reclaim twin is tested below)."""
    clock = [0.0]
    eng = DecodeServer(params, CFG, max_batch=1, tenant_quota=quota(),
                       tenant_clock=lambda: clock[0])
    # occupy the sole slot so both new submissions queue
    holder = eng.submit([9, 9], 4, tenant="burst")
    b = eng.submit([1, 2, 3], 3, tenant="burst")
    g = eng.submit([4, 5, 6], 3, tenant="gold")
    order = []
    while eng.has_work():
        eng.step()
        clock[0] += 0.25
        for led in eng.drain_ledgers():
            order.append(led["rid"])
    eng.drain()
    assert order[0] == holder
    # gold (submitted LAST) finishes before the earlier burst request
    assert order.index(g) < order.index(b)


def test_unlabeled_traffic_is_default_tenant(params):
    clock = [0.0]
    eng = paged_engine(params, quota(), clock)
    rid = eng.submit([1, 2, 3], 2)
    eng.drain()
    led = eng.pop_ledger(rid)
    assert led["tenant"] == "default"
    snap = eng.tenant_snapshot()
    assert snap["default"]["tokens_total"] == 2
    assert set(snap) == {"default", "gold", "burst"}


def test_tenancy_off_keeps_fifo_and_no_snapshot(params):
    eng = DecodeServer(params, CFG, max_batch=1)
    assert eng.tenant_snapshot() is None
    a = eng.submit([1, 2], 2, tenant="whoever")    # tag stored, inert
    b = eng.submit([3, 4], 2)
    out = eng.drain()
    assert set(out) == {a, b}


# ---------------------------------------------------------------------------
# preemptive reclaim
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("kv_swap", [True, False])
def test_guaranteed_arrival_reclaims_over_quota_slot_bit_exact(
        params, kv_swap):
    """Burst fills every slot; a gold arrival preempts the youngest
    burst slot through the existing machinery, gold admits
    immediately, and the preempted request still completes
    token-for-token identical to its undisturbed run."""
    clock = [0.0]
    eng = paged_engine(params, quota(), clock, kv_swap=kv_swap)
    b1 = eng.submit([1, 2, 3], 8, tenant="burst")
    b2 = eng.submit([4, 5, 6], 8, tenant="burst")
    eng.step()
    clock[0] += 0.1
    g = eng.submit([7, 8], 6, tenant="gold")
    assert eng.tenant_reclaims == 1
    mode = "swap" if kv_swap else "recompute"
    assert eng.preempts[mode] == 1
    snap = eng.tenant_snapshot()
    assert snap["gold"]["active"] == 1          # admitted NOW
    assert snap["burst"]["pending"] == 1        # re-queued, not killed
    assert snap["burst"]["preempts"][mode] == 1
    while eng.has_work():
        eng.step()
        clock[0] += 0.1
    out = eng.drain()
    assert out[b1] == ref(params, [1, 2, 3], 8)
    assert out[b2] == ref(params, [4, 5, 6], 8)
    assert out[g] == ref(params, [7, 8], 6)


def test_no_reclaim_from_within_min_tenants(params):
    """A tenant running within its own min is never a reclaim victim:
    with every slot held by gold (still under its large min), a
    second gold (same tenant) or a burst arrival reclaims nothing."""
    clock = [0.0]
    eng = paged_engine(params, quota(), clock)
    eng.submit([1, 2], 8, tenant="gold")
    eng.submit([3, 4], 8, tenant="gold")
    eng.step()
    clock[0] += 0.1
    eng.submit([5, 6], 4, tenant="burst")
    eng.submit([7, 8], 4, tenant="gold")
    assert eng.tenant_reclaims == 0
    assert eng.preempts == {"swap": 0, "recompute": 0}
    while eng.has_work():
        eng.step()
        clock[0] += 0.1
    assert len(eng.drain()) == 4


# ---------------------------------------------------------------------------
# over-max shed (the ladder's last rung)
# ---------------------------------------------------------------------------

def test_over_max_tenant_sheds_tenant_quota_under_contention(params):
    clock = [0.0]
    eng = paged_engine(params, quota(window_s=4.0, burst_max=5.0),
                       clock, max_batch=1)
    eng.submit([1] * 4, 40, tenant="burst")
    for _ in range(25):
        eng.step()              # ~25 tokens in a 4s window: over max
    with pytest.raises(TenantQuotaExceeded) as ei:
        eng.submit([2] * 4, 4, tenant="burst")
    assert ei.value.reason == "tenant_quota"
    assert eng.tenant_snapshot()["burst"]["sheds"] == 1
    # gold is untouched by burst's ceiling
    g = eng.submit([3] * 4, 2, tenant="gold")
    while eng.has_work():
        eng.step()
        clock[0] += 0.1
    assert g in eng.drain()


def test_idle_engine_lends_past_max(params):
    """Work conservation: the same over-max tenant admits when the
    engine is idle — max is a lending ceiling under contention, not a
    refusal to use idle slots."""
    clock = [0.0]
    eng = paged_engine(params, quota(window_s=4.0, burst_max=5.0),
                       clock, max_batch=1)
    eng.submit([1] * 4, 30, tenant="burst")
    while eng.has_work():
        eng.step()              # rate far over max by completion...
    eng.drain()
    rid = eng.submit([2] * 4, 2, tenant="burst")   # ...but engine idle
    assert rid in eng.drain()


# ---------------------------------------------------------------------------
# tenant-scoped prefix caches
# ---------------------------------------------------------------------------

def test_paged_prefix_chains_disjoint_across_tenants(params):
    """Two tenants publishing the IDENTICAL prompt hold disjoint
    chains: tenant B's identical resubmission gets zero reuse from
    tenant A's chain (the timing side-channel the scoping closes),
    while a same-tenant resubmission still hits."""
    clock = [0.0]
    base = list(range(1, 17))               # two full 8-token blocks
    eng = paged_engine(params, quota(), clock, prefix_cache_size=8)
    eng.submit(base + [20], 2, tenant="gold", cache_prefix=True)
    eng.drain()
    hits0 = eng._pindex.hits
    eng.submit(base + [21], 2, tenant="burst", cache_prefix=True)
    eng.drain()
    assert eng._pindex.hits == hits0        # cross-tenant: NO reuse
    assert eng._pindex.stats()["chains"] == 2   # disjoint chains
    eng.submit(base + [22], 2, tenant="gold")
    eng.drain()
    assert eng._pindex.hits == hits0 + 1    # same tenant still hits


def test_share_prefix_opt_out_restores_cross_tenant_reuse(params):
    clock = [0.0]
    base = list(range(1, 17))
    eng = paged_engine(params, quota(share_prefix=True), clock,
                       prefix_cache_size=8)
    eng.submit(base + [20], 2, tenant="gold", cache_prefix=True)
    eng.drain()
    eng.submit(base + [21], 2, tenant="burst")
    eng.drain()
    assert eng._pindex.hits == 1            # trusted fleet: shared
    assert eng._pindex.stats()["chains"] == 1


def test_slot_static_prefix_scoped_by_tenant(params):
    clock = [0.0]
    base = list(range(1, 13))
    eng = DecodeServer(params, CFG, max_batch=1, prefix_cache_size=4,
                       tenant_quota=quota(),
                       tenant_clock=lambda: clock[0])
    eng.submit(base, 1, tenant="gold", cache_prefix=True)
    eng.drain()
    r = eng.submit(base + [30, 31, 32, 33], 2, tenant="burst")
    got = eng.drain()[r]
    assert eng.prefix_hits == 0             # scoped: no cross-tenant hit
    assert got == ref(params, base + [30, 31, 32, 33], 2)
    eng.submit(base + [40, 41, 42, 43], 2, tenant="gold")
    eng.drain()
    assert eng.prefix_hits == 1


# ---------------------------------------------------------------------------
# restart / fork plumbing
# ---------------------------------------------------------------------------

def test_capture_restore_preserves_tenant(params):
    clock = [0.0]
    eng = paged_engine(params, quota(), clock)
    eng.submit([1, 2, 3], 8, tenant="burst")
    eng.step()
    states = eng.capture_resumable()
    assert states[0]["tenant"] == "burst"
    fresh = paged_engine(params, quota(), clock)
    nrid = fresh.restore(states[0])
    while fresh.has_work():
        fresh.step()
        clock[0] += 0.1
    out = fresh.drain()
    assert out[nrid] == ref(params, [1, 2, 3], 8)
    led = fresh.pop_ledger(nrid)
    assert led["tenant"] == "burst"


def test_fork_inherits_tenant(params):
    clock = [0.0]
    eng = paged_engine(params, quota(), clock, max_batch=3)
    rid = eng.submit([1, 2, 3], 6, tenant="burst")
    eng.step()
    nrid = eng.fork(rid)
    snap = eng.tenant_snapshot()
    assert snap["burst"]["active"] == 2
    while eng.has_work():
        eng.step()
        clock[0] += 0.1
    out = eng.drain()
    assert out[rid] == out[nrid] == ref(params, [1, 2, 3], 6)
