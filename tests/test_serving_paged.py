"""Paged KV-cache subsystem (ISSUE 6): block-table decode, COW prefix
sharing and fork, memory-aware admission, preempt-and-resume.

The acceptance invariants this file pins:
- greedy output stays bit-identical to ``generate()`` under paging at
  every (pipeline_depth, decode_steps) in {1,2} x {1,4} — including
  across a COW fork and a preempt-and-resume in BOTH modes (swap and
  recompute);
- sampled streams stay (seed, absolute-position)-keyed, so paging does
  not change them either;
- block accounting balances at every quiescent point (no leaks, no
  double frees), COW forks never alias a written block;
- admission is memory-aware: a request waits for free-block headroom
  instead of thrashing, permanent-infeasible requests raise Infeasible
  (HTTP 400) while transient capacity raises QueueFull (429);
- under pool pressure the engine preempts the lowest-priority slot and
  re-enqueues it at the FRONT of the queue instead of failing.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from nos_tpu.models import transformer as tfm
from nos_tpu.models.generate import generate
from nos_tpu.models.serving import DecodeServer, Infeasible, QueueFull

CFG = tfm.TransformerConfig(vocab=64, d_model=32, n_layers=2, n_heads=4,
                            n_kv_heads=2, d_ff=64, max_seq=64,
                            dtype=jnp.float32)

# the ISSUE acceptance grid: {1, 2} x {1, 4}
GRID = [(d, t) for d in (1, 2) for t in (1, 4)]


@pytest.fixture(scope="module")
def params():
    return tfm.init_params(jax.random.PRNGKey(0), CFG)


@pytest.fixture(scope="module")
def engines(params):
    """Shared drained paged engines keyed by (decode_steps, extras);
    pipeline_depth is host-side state retuned per test (the same
    compiled-program economics as test_serving_pipeline)."""
    cache = {}

    def at(depth, steps=1, mb=2, blocks=24, **kw):
        key = (steps, mb, blocks, tuple(sorted(kw.items())))
        eng = cache.get(key)
        if eng is None:
            eng = DecodeServer(params, CFG, max_batch=mb,
                               decode_steps=steps, kv_block_size=8,
                               kv_blocks=blocks, **kw)
            cache[key] = eng
        assert not eng.has_work(), "previous test left work behind"
        eng.pipeline_depth = depth
        return eng

    return at


def ref(params, prompt, n):
    out = generate(params, CFG, jnp.asarray([prompt], jnp.int32), n)
    return [int(t) for t in out[0]]


def assert_pool_balanced(eng):
    """Quiescent-pool invariant: every block is either free or held by
    the prefix index — no slot references, no leaks, no deferred."""
    assert not eng.has_work()
    held = eng._pindex.block_count if eng._pindex is not None else 0
    assert eng._alloc.used_count == held, (
        eng._alloc.used_count, held)
    assert not eng._deferred
    assert all(not t for t in eng._tables)


# ---------------------------------------------------------------------------
# bit-exactness across the grid
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("depth,steps", GRID)
def test_paged_greedy_bit_exact_across_grid(engines, params, depth, steps):
    # 3 requests over 2 slots: slot recycling and block realloc inside
    srv = engines(depth, steps)
    prompts = [([1, 2, 3], 6), ([60, 61], 9), ([7, 7, 7, 7, 7], 5)]
    rids = [srv.submit(p, n) for p, n in prompts]
    res = srv.drain()
    for rid, (p, n) in zip(rids, prompts):
        assert res[rid] == ref(params, p, n), (depth, steps, rid)
    assert_pool_balanced(srv)


@pytest.mark.parametrize("depth,steps", GRID)
def test_cow_fork_bit_exact_across_grid(engines, params, depth, steps):
    # fork mid-decode: source and fork must BOTH finish bit-identical
    # to generate(), and the shared tail block must COW-copy rather
    # than alias (the pool ends balanced, shared count returns to 0)
    srv = engines(depth, steps)
    r0 = srv.submit([4, 5], 16)
    srv.step()
    f0 = srv.fork(r0)
    assert srv._alloc.shared_count() > 0      # blocks genuinely shared
    res = srv.drain()
    want = ref(params, [4, 5], 16)
    assert res[r0] == want, (depth, steps, "source")
    assert res[f0] == want, (depth, steps, "fork")
    assert srv._alloc.shared_count() == 0
    assert_pool_balanced(srv)


@pytest.mark.parametrize("mode", ["swap", "recompute"])
@pytest.mark.parametrize("depth,steps", GRID)
def test_preempt_resume_bit_exact_across_grid(engines, params, depth,
                                              steps, mode):
    srv = engines(depth, steps)
    # budget large enough that the preempt barrier's flush (up to
    # depth*steps late tokens) cannot finish the victim first
    r0 = srv.submit([4, 5], 24)
    r1 = srv.submit([9, 8, 7], 8)
    for _ in range(2):
        srv.step()
    assert srv.preempt(r0, mode)
    assert srv.kv_stats()["preempts"][mode] >= 1
    # the victim resumes at the FRONT of the pending queue
    assert srv._pending and srv._pending[0].rid == r0
    res = srv.drain()
    assert res[r0] == ref(params, [4, 5], 24), (depth, steps, mode)
    assert res[r1] == ref(params, [9, 8, 7], 8), (depth, steps, mode)
    assert_pool_balanced(srv)


def test_sampled_streams_invariant_to_paging(engines, params):
    kw = dict(temperature=0.9, top_k=8, seed=17)
    base = DecodeServer(params, CFG, max_batch=2)
    r = base.submit([4, 5], 8, **kw)
    want = base.drain()[r]

    srv = engines(2, 1)
    r1 = srv.submit([4, 5], 8, **kw)
    r2 = srv.submit([9, 9], 8, temperature=1.2, seed=5)
    res = srv.drain()
    assert res[r1] == want
    assert len(res[r2]) == 2 + 8


def test_sampled_fork_diverges_by_seed(engines, params):
    # n>1 sampling: fork the same source twice with different seeds —
    # shared history, divergent futures, no cross-corruption
    srv = engines(1, 1, mb=4, blocks=40)
    r0 = srv.submit([4, 5], 10, temperature=0.9, seed=3)
    for _ in range(3):
        srv.step()
    f1 = srv.fork(r0, seed=100)
    f2 = srv.fork(r0, seed=200)
    res = srv.drain()
    base = res[r0]
    # all three share the pre-fork history; the forks diverge after
    pre = 2 + 3  # prompt + tokens produced before the first fork
    assert res[f1][:pre] == base[:pre]
    assert res[f2][:pre] == base[:pre]
    assert res[f1] != res[f2]
    assert_pool_balanced(srv)


# ---------------------------------------------------------------------------
# block-granular prefix sharing
# ---------------------------------------------------------------------------

def test_block_granular_prefix_reuse_is_exact_and_shares_storage(
        engines, params):
    srv = engines(1, 1, mb=2, blocks=40, prefix_cache_size=8)
    sysp = list(range(1, 20))               # 19 tokens -> 2 full blocks
    srv.submit(sysp + [33], 2, cache_prefix=True)
    srv.drain()
    kv0 = srv.kv_stats()
    assert kv0["prefix"]["blocks"] == 2     # published chain parked
    used0 = kv0["blocks_used"]

    r = srv.submit(sysp + [40, 41], 5)
    # while active, the prefix blocks are SHARED, not copied
    assert srv._alloc.shared_count() >= 2
    res = srv.drain()
    assert res[r] == ref(params, sysp + [40, 41], 5)
    kv = srv.kv_stats()
    assert kv["prefix"]["hits"] == 1
    assert kv["prefix"]["tokens_saved"] == 16       # 2 blocks x 8
    assert kv["blocks_used"] == used0               # nothing leaked
    srv._pindex.clear()
    srv.prefix_hits = srv.prefix_tokens_saved = 0
    assert_pool_balanced(srv)


def test_prefix_chains_evicted_under_admission_pressure(engines, params):
    # prefix blocks must yield to live requests: with the pool nearly
    # full of published chains and NO active slot, admission evicts
    # LRU chains instead of deadlocking the queue
    srv = engines(1, 1, mb=2, blocks=7, prefix_cache_size=8)
    srv.submit(list(range(1, 17)) + [20], 2, cache_prefix=True)
    srv.drain()
    assert srv.kv_stats()["prefix"]["blocks"] == 2
    long = [33] * 30                       # needs 4 blocks + headroom
    r = srv.submit(long, 4)
    res = srv.drain()
    assert res[r] == ref(params, long, 4)
    assert srv.kv_stats()["prefix"]["blocks"] == 0     # evicted
    assert_pool_balanced(srv)


# ---------------------------------------------------------------------------
# memory-aware admission + pressure preemption
# ---------------------------------------------------------------------------

def test_admission_waits_for_block_headroom(engines, params):
    # two long requests over a pool that fits ~one: the second shares
    # the engine but must WAIT (pending, not failed) until the first
    # completes and frees its blocks
    srv = engines(1, 1, mb=2, blocks=6)
    r0 = srv.submit([1] * 20, 8)            # needs 4 blocks at full len
    r1 = srv.submit([2] * 20, 8)
    assert len(srv._active) == 1 and len(srv._pending) == 1
    res = srv.drain()
    assert res[r0] == ref(params, [1] * 20, 8)
    assert res[r1] == ref(params, [2] * 20, 8)
    assert_pool_balanced(srv)


def test_pressure_preempts_lowest_priority_youngest(engines, params):
    # three growing requests over a tight pool: the engine preempts to
    # make progress, victims chosen lowest-priority-then-youngest, and
    # every output stays exact. Run at depth 2 so deferred frees and
    # barrier flushes are exercised too.
    for mode_kw, mode in ((dict(kv_swap=True), "swap"),
                          (dict(kv_swap=False), "recompute")):
        srv = DecodeServer(params, CFG, max_batch=3, kv_block_size=8,
                           kv_blocks=7, pipeline_depth=2, **mode_kw)
        protected = srv.submit([1, 2], 20, priority=5)
        rids = [srv.submit([i + 3, i + 4], 20) for i in range(2)]
        res = srv.drain()
        assert res[protected] == ref(params, [1, 2], 20)
        for i, rid in enumerate(rids):
            assert res[rid] == ref(params, [i + 3, i + 4], 20), mode
        kv = srv.kv_stats()
        assert kv["preempts"][mode] > 0, kv
        # the high-priority request was never the victim: preempted
        # requests resume via the preempted flag, which clears — probe
        # indirectly through totals: at least one preemption happened
        # and the protected request finished at full length
        assert len(res[protected]) == 2 + 20


def test_priority_protects_from_preemption(engines, params):
    srv = engines(1, 1, mb=2, blocks=10)
    hi = srv.submit([1, 2], 6, priority=10)
    lo = srv.submit([3, 4], 6, priority=0)
    srv.step()
    assert srv._preempt_victim()
    # the LOW priority slot was vacated
    assert any(r.rid == lo and r.preempted for r in srv._pending) \
        or lo not in {r.rid for r in srv._active.values()}
    assert hi in {r.rid for r in srv._active.values()}
    res = srv.drain()
    assert res[hi] == ref(params, [1, 2], 6)
    assert res[lo] == ref(params, [3, 4], 6)
    assert_pool_balanced(srv)


def test_infeasible_vs_queuefull_split(engines, params):
    srv = engines(1, 1, mb=1, blocks=4)     # 3 usable blocks = 24 tokens
    # permanent: can never fit the pool -> Infeasible (a ValueError)
    with pytest.raises(Infeasible, match="KV blocks"):
        srv.submit([1] * 20, 20)
    # permanent: exceeds the cache length -> Infeasible
    with pytest.raises(Infeasible, match="exceeds cache length"):
        srv.submit([1] * 60, 20)
    # transient: pool is busy and the waiting line is full -> QueueFull
    srv.max_pending = 1
    try:
        first = srv.submit([1, 2], 10)
        srv.submit([3, 4], 10)              # waits
        with pytest.raises(QueueFull, match="max_pending"):
            srv.submit([5, 6], 2)
        res = srv.drain()
        assert res[first] == ref(params, [1, 2], 10)
    finally:
        srv.max_pending = 0
        srv.drain()
    assert_pool_balanced(srv)


def test_memory_blocked_queue_sheds_with_hbm_admission_reason(
        engines, params):
    """Free SLOTS but no KV headroom: the waiting line past max_pending
    sheds with reason="hbm_admission" (ISSUE 8 satellite) instead of
    growing unbounded — the wire tells memory pressure from slot
    scarcity, and before this shed existed the queue had NO bound at
    all whenever memory (not slots) was the bottleneck."""
    srv = engines(1, 1, mb=2, blocks=4)     # 3 usable blocks, 2 slots
    srv.max_pending = 1
    try:
        a = srv.submit([1] * 10, 10)        # needs the whole pool
        srv.submit([2] * 10, 10)            # waits on headroom
        assert srv._admit_blocked and srv._free    # slot free, blocked
        with pytest.raises(QueueFull) as e:
            srv.submit([3] * 10, 2)
        assert e.value.reason == "hbm_admission"
        assert "headroom" in str(e.value)
        res = srv.drain()
        assert res[a] == ref(params, [1] * 10, 10)
    finally:
        srv.max_pending = 0
        srv.drain()
    assert_pool_balanced(srv)


def test_shed_reasons_on_error_types(engines):
    """The machine-readable reason slugs ride the exception types."""
    srv = engines(1, 1, mb=1, blocks=4)
    with pytest.raises(Infeasible) as e:
        srv.submit([1] * 40, 40)
    assert e.value.reason == "infeasible"
    srv.max_pending = 1
    try:
        first = srv.submit([1, 2], 4)
        srv.submit([3, 4], 4)
        with pytest.raises(QueueFull) as e:
            srv.submit([5, 6], 2)
        assert e.value.reason == "queue_full"
        assert first in srv.drain()
    finally:
        srv.max_pending = 0
        srv.drain()
    assert_pool_balanced(srv)


def test_prefix_evicted_for_waiting_request_while_others_decode(
        engines, params):
    # a pending request must not stall behind idle prefix-cache blocks
    # just because another slot is decoding: headroom eviction applies
    # with actives present too
    srv = engines(1, 1, mb=2, blocks=8, prefix_cache_size=8)
    srv.submit(list(range(1, 17)) + [20], 2, cache_prefix=True)
    srv.drain()
    assert srv.kv_stats()["prefix"]["blocks"] == 2
    r0 = srv.submit([1, 2], 16)             # decoding, holds blocks
    long = [33] * 30                        # needs the prefix's blocks
    r1 = srv.submit(long, 4)
    assert len(srv._active) == 2, "r1 admitted via prefix eviction"
    assert srv.kv_stats()["prefix"]["blocks"] == 0
    res = srv.drain()
    assert res[r0] == ref(params, [1, 2], 16)
    assert res[r1] == ref(params, long, 4)
    assert_pool_balanced(srv)


def test_sole_decoder_preempted_when_prefill_reservation_squeezes(
        params):
    # chunked admission reserves its full table upfront; if the only
    # decoder's growth then hits a dry pool, the decoder must yield
    # (resume later) rather than killing the engine with NoFreeBlocks.
    # decode_steps=4 makes the decoder outrun the 6-tick prefill:
    # free after the reservation is 2 blocks, the decoder needs 3 more
    # within 5 ticks — dry mid-prefill by construction.
    srv = DecodeServer(params, CFG, max_batch=2, kv_block_size=8,
                       kv_blocks=10, prefill_chunk=8, decode_steps=4,
                       kv_swap=False)
    r0 = srv.submit(list(range(1, 8)), 20)  # 1 block now, 4 at full len
    long = list(range(1, 49))               # 6 blocks reserved upfront
    r1 = srv.submit(long, 2)
    assert srv._prefilling
    res = srv.drain()
    assert res[r0] == ref(params, list(range(1, 8)), 20)
    assert res[r1] == ref(params, long, 2)
    assert srv.kv_stats()["preempts"]["recompute"] >= 1
    assert_pool_balanced(srv)


def test_fork_finds_slot_freed_by_inflight_completion(engines, params):
    # a completion parked in an unconsumed in-flight tick frees its
    # slot during fork's barrier flush — fork must see that capacity
    srv = engines(4, 1, mb=2)
    r0 = srv.submit([1, 2], 2)              # finishes almost at once
    r1 = srv.submit([4, 5], 16)
    for _ in range(2):
        srv.step()
    # r0 is done but may still occupy its slot pending consumption;
    # fork(r1) must flush, free r0's slot, and succeed
    f1 = srv.fork(r1)
    res = srv.drain()
    want = ref(params, [4, 5], 16)
    assert res[r1] == want and res[f1] == want
    assert res[r0] == ref(params, [1, 2], 2)
    assert_pool_balanced(srv)


def test_cancel_mid_prefill_releases_reserved_blocks(params):
    srv = DecodeServer(params, CFG, max_batch=2, kv_block_size=8,
                       kv_blocks=12, prefill_chunk=8)
    r0 = srv.submit([1, 2, 3], 6)
    long = list(range(1, 31))
    r1 = srv.submit(long, 5)                # chunked: blocks reserved
    assert srv._prefilling
    reserved = srv._alloc.used_count
    assert srv.cancel(r1)
    assert srv._alloc.used_count < reserved
    res = srv.drain()
    assert res[r0] == ref(params, [1, 2, 3], 6)
    assert_pool_balanced(srv)


def test_chunked_prefill_composes_with_paging(params):
    srv = DecodeServer(params, CFG, max_batch=2, kv_block_size=8,
                       kv_blocks=24, prefill_chunk=8, pipeline_depth=2)
    r0 = srv.submit([1, 2, 3], 10)
    for _ in range(2):
        srv.step()
    long = list(range(1, 31))
    r1 = srv.submit(long, 5)
    res = srv.drain()
    assert res[r0] == ref(params, [1, 2, 3], 10)
    assert res[r1] == ref(params, long, 5)
    assert_pool_balanced(srv)


def test_scatter_overrun_routes_to_null_block_not_last_entry():
    # pipeline over-decode can write past a fully-populated table's
    # timeline; the scatter must route those writes to the reserved
    # null block — clamping into the row's LAST entry would wrap the
    # write onto a committed position a COW fork could still read
    from nos_tpu.ops.attention import paged_scatter_kv

    arena = jnp.zeros((4, 2, 8, 4))             # NB=4, Hkv=2, bs=8, D=4
    table = jnp.asarray([[1, 2]], jnp.int32)    # 2 logical blocks
    vals = jnp.ones((1, 2, 1, 4))
    # in-range write: logical block 1 -> physical 2
    out = paged_scatter_kv(arena, table, jnp.asarray([9]), vals)
    assert float(out[2, 0, 1, 0]) == 1.0
    # overrun write at pos 16 (logical block 2 >= nb): null block 0,
    # and physical 2's committed content untouched
    out2 = paged_scatter_kv(out, table, jnp.asarray([16]), vals)
    assert float(out2[0, 0, 0, 0]) == 1.0       # landed in null block
    assert bool(jnp.all(out2[1:] == out[1:]))   # real blocks untouched


def test_fork_beyond_pool_capacity_is_infeasible(params):
    srv = DecodeServer(params, CFG, max_batch=2, kv_block_size=8,
                       kv_blocks=4)             # 3 usable = 24 tokens
    r0 = srv.submit([1, 2], 8)
    srv.step()
    with pytest.raises(Infeasible, match="KV blocks"):
        srv.fork(r0, max_new_tokens=40)
    res = srv.drain()
    assert res[r0] == ref(params, [1, 2], 8)
    assert_pool_balanced(srv)


def test_stats_surface_block_accounting(engines, params):
    srv = engines(1, 1)
    rid = srv.submit([1, 2, 3], 4)
    st = srv.stats()
    kv = st["kv"]
    assert kv["block_size"] == 8
    assert kv["blocks_total"] == kv["blocks_free"] + kv["blocks_used"]
    assert kv["blocks_used"] >= 1
    assert set(kv["preempts"]) == {"swap", "recompute"}
    assert "cow_shared" in kv and "hbm" in kv
    srv.drain()
    srv.pop_result(rid)


def test_validation(params):
    with pytest.raises(ValueError, match="power of two"):
        DecodeServer(params, CFG, kv_block_size=12, kv_blocks=8)
    with pytest.raises(ValueError, match="multiple of"):
        DecodeServer(params, CFG, kv_block_size=32, kv_blocks=8,
                     max_len=48)
    with pytest.raises(ValueError, match="kv_blocks"):
        DecodeServer(params, CFG, kv_block_size=8, kv_blocks=1)


def test_random_schedules_stay_exact_under_paging(engines, params):
    """Crash-prober: random lengths, budgets, arrival points, step
    interleavings, plus a random preemption — every surviving request
    bit-exact on a paged engine at (depth 2, steps 4)."""
    rng = np.random.default_rng(29)
    for trial in range(2):
        srv = engines(2, 4, mb=3, blocks=32)
        n_req = int(rng.integers(3, 6))
        reqs = [([int(t) for t in rng.integers(0, 64, rng.integers(1, 41))],
                 int(rng.integers(1, 7))) for _ in range(n_req)]
        rids = []
        for p, n in reqs:
            rids.append(srv.submit(p, n))
            for _ in range(int(rng.integers(0, 3))):
                srv.step()
        if srv._active and rng.integers(0, 2):
            victim = rng.choice(
                [r.rid for r in srv._active.values()])
            srv.preempt(int(victim),
                        "swap" if rng.integers(0, 2) else "recompute")
        results = srv.drain()
        for rid, (p, n) in zip(rids, reqs):
            assert results[rid] == ref(params, p, n), (trial, rid, p, n)
        assert_pool_balanced(srv)
