"""Mixed A100 + TPU clusters under one quota system (BASELINE config 5).

The reference counts only NVIDIA resources; the rebuild's quota layer must
count TPU chips alongside GPUs, with accelerator memory as the common
borrowing currency (nos.ai/tpu-memory + nos.ai/gpu-memory derived scalars —
analog of reference pkg/gpu/util/resource.go).
"""
from nos_tpu import constants
from nos_tpu.tpu.resource_calc import ResourceCalculator

TPU = constants.RESOURCE_TPU
GPU = constants.RESOURCE_NVIDIA_GPU
TPU_MEM = constants.RESOURCE_TPU_MEMORY
GPU_MEM = constants.RESOURCE_GPU_MEMORY


# ---------------------------------------------------------------------------
# derived-currency parsing across accelerator families
# ---------------------------------------------------------------------------

def test_mig_profile_memory_parsed():
    calc = ResourceCalculator()
    req = calc.compute_request({"nvidia.com/mig-1g.10gb": 2})
    assert req[GPU_MEM] == 20


def test_mps_slice_memory_parsed():
    calc = ResourceCalculator()
    req = calc.compute_request({"nvidia.com/gpu-10gb": 3})
    assert req[GPU_MEM] == 30


def test_whole_gpu_uses_default_memory():
    calc = ResourceCalculator(nvidia_gpu_memory_gb=32)
    req = calc.compute_request({GPU: 2})
    assert req[GPU_MEM] == 64


def test_mixed_pod_derives_both_currencies():
    calc = ResourceCalculator(tpu_memory_gb=16)
    req = calc.compute_request({TPU: 4, "nvidia.com/mig-2g.20gb": 1, "cpu": 8})
    assert req[TPU_MEM] == 64
    assert req[GPU_MEM] == 20
    assert req["cpu"] == 8


def test_unknown_nvidia_resource_ignored():
    calc = ResourceCalculator()
    req = calc.compute_request({"nvidia.com/gpu.shared": 1})
    assert GPU_MEM not in req


# ---------------------------------------------------------------------------
# end-to-end: EQ borrowing across a GPU namespace and a TPU namespace
# ---------------------------------------------------------------------------

def test_quota_borrowing_across_gpu_and_tpu_namespaces(make_cluster):
    """A TPU namespace borrows the GPU namespace's idle chips' worth of
    quota counted in its own resource; each family's min is enforced
    independently while both live under one quota system."""
    c = make_cluster()
    c.add_node("gpu-node", {GPU: 4, "cpu": 32})
    c.add_node("tpu-node", {TPU: 8, "cpu": 32})
    # team-gpu holds idle TPU min that team-tpu can borrow
    c.add_elastic_quota("team-gpu", "q-gpu", {GPU: 4, TPU: 4})
    c.add_elastic_quota("team-tpu", "q-tpu", {TPU: 4})
    # TPU team goes over its min=4 by borrowing team-gpu's idle TPU min
    c.add_pod("team-tpu", "t1", {TPU: 4})
    c.add_pod("team-tpu", "t2", {TPU: 4})
    c.add_pod("team-gpu", "g1", {GPU: 2})
    c.run_until_idle()
    pods = {p.metadata.name: p for p in c.client.list("Pod")}
    assert pods["t1"].spec.node_name == "tpu-node"
    assert pods["t2"].spec.node_name == "tpu-node"   # borrowed TPU quota
    assert pods["g1"].spec.node_name == "gpu-node"


def test_borrowing_blocked_without_aggregated_headroom(make_cluster):
    """With no other quota holding idle TPU min, the aggregated-min ceiling
    rejects the borrower even though the node has free chips."""
    c = make_cluster()
    c.add_node("tpu-node", {TPU: 8, "cpu": 32})
    c.add_elastic_quota("team-gpu", "q-gpu", {GPU: 4})     # no TPU min anywhere else
    c.add_elastic_quota("team-tpu", "q-tpu", {TPU: 4})
    c.add_pod("team-tpu", "t1", {TPU: 4})
    c.add_pod("team-tpu", "t2", {TPU: 4})
    c.run_until_idle()
    pods = {p.metadata.name: p for p in c.client.list("Pod")}
    scheduled = sorted(n for n, p in pods.items() if p.spec.node_name)
    assert scheduled == ["t1"]


def test_over_quota_labeling_is_per_family(make_cluster):
    """The EQ controller labels the borrowing TPU pod over-quota while the
    GPU namespace's pods stay in-quota."""
    c = make_cluster()
    c.add_node("gpu-node", {GPU: 4, "cpu": 32})
    c.add_node("tpu-node", {TPU: 8, "cpu": 32})
    c.add_elastic_quota("team-gpu", "q-gpu", {GPU: 4})
    c.add_elastic_quota("team-tpu", "q-tpu", {TPU: 4})
    c.add_pod("team-tpu", "t1", {TPU: 4}, phase="Running")
    c.add_pod("team-tpu", "t2", {TPU: 4}, phase="Running")
    c.add_pod("team-gpu", "g1", {GPU: 2}, phase="Running")
    c.run_until_idle()
    labels = {
        p.metadata.name: p.metadata.labels.get(constants.LABEL_CAPACITY)
        for p in c.client.list("Pod")
    }
    assert labels["g1"] == constants.CAPACITY_IN_QUOTA
    # one TPU pod fits min=4, the other is borrowing
    tpu_labels = sorted([labels["t1"], labels["t2"]])
    assert tpu_labels == [constants.CAPACITY_IN_QUOTA, constants.CAPACITY_OVER_QUOTA]


def test_eq_status_counts_both_families(make_cluster):
    c = make_cluster()
    c.add_elastic_quota("team-mixed", "q-mixed", {TPU: 8, GPU: 4})
    c.add_pod("team-mixed", "p1", {TPU: 4, GPU: 2}, phase="Running")
    c.run_until_idle()
    eq = c.client.get("ElasticQuota", "q-mixed", "team-mixed")
    assert eq.status.used[TPU] == 4
    assert eq.status.used[GPU] == 2
    # status.used reports only the resources the quota enforces
    assert TPU_MEM not in eq.status.used
    assert GPU_MEM not in eq.status.used


def test_eq_enforces_derived_memory_currency(make_cluster):
    """A quota whose min bounds the derived accelerator-memory scalar
    accounts it across families: MIG slices and TPU chips both charge it."""
    c = make_cluster()
    calc = ResourceCalculator()
    c.add_elastic_quota(
        "team-mixed", "q-mem",
        {TPU_MEM: 100, GPU_MEM: 100},
    )
    c.add_pod("team-mixed", "p1",
              {TPU: 2, "nvidia.com/mig-1g.10gb": 1}, phase="Running")
    c.run_until_idle()
    eq = c.client.get("ElasticQuota", "q-mem", "team-mixed")
    expected = calc.compute_request({TPU: 2})[TPU_MEM]
    assert eq.status.used[TPU_MEM] == expected
    assert eq.status.used[GPU_MEM] == 10
