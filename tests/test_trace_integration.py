"""End-to-end tracing across the control plane.

The acceptance spine of ISSUE 3: one trace per pod journey propagated
through the `nos-tpu/trace-context` annotation (quota -> scheduler ->
lifecycle), repair episodes split into named phase spans, the
`/debug/traces` endpoint, exemplars on the lifecycle histograms, and
trace-correlated JSON logging.
"""
import io
import json
import logging
import re
import urllib.request

from nos_tpu import constants
from nos_tpu.api.quota import make_elastic_quota
from nos_tpu.cmd import JsonLogFormatter
from nos_tpu.kube import ApiServer, Manager
from nos_tpu.kube.objects import (
    Container,
    Node,
    NodeSpec,
    NodeStatus,
    ObjectMeta,
    Pod,
    PodSpec,
    PodStatus,
    Taint,
    Toleration,
)
from nos_tpu.lifecycle.chaos import ChaosHarness
from nos_tpu.obs import tracing
from nos_tpu.scheduler import Scheduler

TPU = constants.RESOURCE_TPU
V5E = "tpu-v5-lite-podslice"


def mini_cluster(nodes=1, chips=8):
    server = ApiServer()
    mgr = Manager(server)
    mgr.add_controller(Scheduler().controller())
    for i in range(nodes):
        server.create(Node(
            metadata=ObjectMeta(
                name=f"n{i}",
                labels={constants.LABEL_TPU_ACCELERATOR: V5E,
                        constants.LABEL_TPU_TOPOLOGY: "2x4",
                        constants.LABEL_NODEPOOL: f"pool-{i}"},
            ),
            spec=NodeSpec(taints=[Taint(key=TPU, value="present",
                                        effect="NoSchedule")]),
            status=NodeStatus(capacity={TPU: chips, "cpu": 96},
                              allocatable={TPU: chips, "cpu": 96}),
        ))
    server.create(make_elastic_quota("q", "ns", min={TPU: nodes * chips}))
    return server, mgr


def plain_pod(name, chips=2):
    return Pod(
        metadata=ObjectMeta(name=name, namespace="ns"),
        spec=PodSpec(
            containers=[Container(requests={TPU: chips})],
            scheduler_name=constants.SCHEDULER_NAME,
            tolerations=[Toleration(key=TPU, operator="Exists")],
        ),
        status=PodStatus(phase="Pending"),
    )


def test_stamp_survives_conflict_style_mutator_rerun():
    """The REST patch adapters re-run the mutate callback on a fresh
    object per Conflict retry: the stamp must be a peek until the patch
    lands, so a retried bind still carries the journey context."""
    from nos_tpu.scheduler.scheduler import Scheduler as S

    s = S()
    sp_pod = plain_pod("retry")
    ctx = tracing.tracer().start_span("j", component="scheduler").context
    s._queue_stamp(sp_pod, ctx)
    # first attempt's object is discarded by a Conflict...
    first = plain_pod("retry")
    s._apply_stamp(first)
    assert tracing.pod_trace_context(first) == ctx
    # ...the retry gets a FRESH object and must still be stamped
    second = plain_pod("retry")
    s._apply_stamp(second)
    assert tracing.pod_trace_context(second) == ctx
    # only once the patch returns does the queue entry drop
    s._stamp_landed(second)
    third = plain_pod("retry")
    s._apply_stamp(third)
    assert tracing.pod_trace_context(third) is None


def test_scheduler_stamps_journey_context_on_bind():
    server, mgr = mini_cluster()
    server.create(plain_pod("p0"))
    mgr.run_until_idle()
    pod = server.get("Pod", "p0", "ns")
    assert pod.spec.node_name, "pod must bind"
    ctx = tracing.pod_trace_context(pod)
    assert ctx is not None, "journey context stamped at admission"
    names = {sp.name for sp in tracing.recorder().trace(ctx.trace_id)}
    assert {"scheduler.attempt", "quota.admit",
            "scheduler.find_node", "scheduler.bind"} <= names
    # the stamped context IS the root attempt span of the trace
    spans = {sp.span_id: sp for sp in tracing.recorder().trace(ctx.trace_id)}
    assert ctx.span_id in spans
    assert spans[ctx.span_id].parent_id is None
    mgr.stop()


def test_gang_members_share_one_journey_trace():
    # one 4x4 v5e pool = 2 hosts x 8 chips; the 2-worker gang must land
    # on both hosts of the one ICI domain
    server = ApiServer()
    mgr = Manager(server)
    mgr.add_controller(Scheduler().controller())
    for i in range(2):
        server.create(Node(
            metadata=ObjectMeta(
                name=f"n{i}",
                labels={constants.LABEL_TPU_ACCELERATOR: V5E,
                        constants.LABEL_TPU_TOPOLOGY: "4x4",
                        constants.LABEL_NODEPOOL: "pool-0"},
            ),
            spec=NodeSpec(taints=[Taint(key=TPU, value="present",
                                        effect="NoSchedule")]),
            status=NodeStatus(capacity={TPU: 8, "cpu": 96},
                              allocatable={TPU: 8, "cpu": 96}),
        ))
    server.create(make_elastic_quota("q", "ns", min={TPU: 16}))
    for w in range(2):
        server.create(Pod(
            metadata=ObjectMeta(
                name=f"g-{w}", namespace="ns",
                labels={constants.LABEL_GANG_NAME: "g",
                        constants.LABEL_GANG_SIZE: "2",
                        constants.LABEL_GANG_WORKER: str(w)},
                annotations={constants.ANNOTATION_TPU_TOPOLOGY: "4x4"},
            ),
            spec=PodSpec(
                containers=[Container(requests={TPU: 8})],
                scheduler_name=constants.SCHEDULER_NAME,
                tolerations=[Toleration(key=TPU, operator="Exists")],
            ),
            status=PodStatus(phase="Pending"),
        ))
    mgr.run_until_idle()
    ctxs = []
    for w in range(2):
        pod = server.get("Pod", f"g-{w}", "ns")
        assert pod.spec.node_name
        ctxs.append(tracing.pod_trace_context(pod))
    assert ctxs[0] is not None
    assert ctxs[0].trace_id == ctxs[1].trace_id, \
        "the whole gang is one journey"
    names = {sp.name for sp in tracing.recorder().trace(ctxs[0].trace_id)}
    assert {"scheduler.attempt", "quota.admit",
            "gang.place", "scheduler.bind"} <= names
    mgr.stop()


# ---------------------------------------------------------------------------
# Chaos: journeys survive eviction; episodes carry the named phases
# ---------------------------------------------------------------------------

def test_chaos_evicted_gang_traces_complete_no_orphans():
    h = ChaosHarness(seed=0, duration_s=40.0, n_faults=5)
    h.run()
    rec = tracing.recorder()
    evicted = [
        p for p in h.server.list("Pod")
        if p.metadata.annotations.get(constants.ANNOTATION_LIFECYCLE_RESTARTS)
    ]
    assert evicted, "seed 0 must displace at least one gang"
    checked = 0
    for pod in evicted:
        ctx = tracing.pod_trace_context(pod)
        assert ctx is not None, \
            f"evicted pod {pod.metadata.name} lost its journey context"
        spans = rec.trace(ctx.trace_id)
        names = {sp.name for sp in spans}
        # the journey passed quota admission, scheduling AND slice repair
        assert "lifecycle.evict" in names, names
        assert "scheduler.attempt" in names and "quota.admit" in names
        # no orphan spans: every parent resolves inside the trace
        ids = {sp.span_id for sp in spans}
        for sp in spans:
            assert sp.parent_id is None or sp.parent_id in ids, \
                f"orphan span {sp.name} in journey {ctx.trace_id}"
        # rebind evidence: a scheduler attempt recorded AFTER the
        # eviction span in the same trace
        evict_t = min(sp.start for sp in spans
                      if sp.name == "lifecycle.evict")
        assert any(sp.name == "scheduler.attempt" and sp.start >= evict_t
                   for sp in spans), "rebind attempt missing from journey"
        checked += 1
    assert checked == len(evicted)


def test_chaos_episode_traces_have_named_phases():
    h = ChaosHarness(seed=0, duration_s=40.0, n_faults=5)
    r = h.run()
    assert r.mttr_phases, "seed 0 must repair at least one fault"
    rec = tracing.recorder()
    for ph in r.mttr_phases:
        assert set(ph) >= {"kind", "node", "trace_id", "detect_s",
                           "fence_s", "drain_s", "gang_evict_s",
                           "rebind_s", "mttr_s"}
        if ph["trace_id"] is None:
            continue
    # the harness flushed every episode via the public API: no open
    # episode spans may leak past the run (node-deletion episodes close
    # on drain; the rest at end of window)
    for node in h.node_names:
        assert h.lifecycle.episode_span(node) is None
    for ph in r.mttr_phases:
        if ph["trace_id"] is None:
            continue
        names = {sp.name for sp in rec.trace(ph["trace_id"])}
        assert "lifecycle.repair" in names
        assert "chaos.rebind" in names
        # phases must account for the MTTR they decompose: detect+rebind
        # span injection->fence and fence->repair back to back
        if ph["detect_s"] is not None and ph["rebind_s"] is not None:
            assert ph["detect_s"] + ph["rebind_s"] <= ph["mttr_s"] + 1e-6 \
                or abs(ph["detect_s"] + ph["rebind_s"] - ph["mttr_s"]) < 1.0


def test_chaos_mttr_histogram_carries_exemplars():
    from nos_tpu.utils.metrics import default_registry

    h = ChaosHarness(seed=0, duration_s=40.0, n_faults=5)
    r = h.run()
    assert r.mttr_s
    om = default_registry().expose(openmetrics=True)
    pat = re.compile(
        r'^nos_lifecycle_mttr_seconds_bucket\{le="[^"]+"\} \d+ '
        r'# \{trace_id="[0-9a-f]{32}"\}', re.M)
    assert pat.search(om), "MTTR buckets must carry a trace exemplar"


# ---------------------------------------------------------------------------
# /debug/traces endpoint
# ---------------------------------------------------------------------------

def test_debug_traces_endpoint_serves_pod_journey():
    from nos_tpu.cmd.serve import HealthServer

    # populate the default recorder with a journey crossing >= 3
    # components: schedule, then evict through the chaos stack
    h = ChaosHarness(seed=0, duration_s=40.0, n_faults=5)
    h.run()
    hs = HealthServer(port=0).start()
    try:
        body = urllib.request.urlopen(
            hs.address + "/debug/traces", timeout=10).read()
        doc = json.loads(body)
        assert doc["trace_count"] >= 1
        want = {"quota", "scheduler", "lifecycle"}
        journeys = [t for t in doc["traces"]
                    if want <= set(t["components"])]
        assert journeys, "a pod journey must span quota+scheduler+lifecycle"
        tid = journeys[0]["trace_id"]
        one = json.loads(urllib.request.urlopen(
            hs.address + f"/debug/traces/{tid}", timeout=10).read())
        assert one["trace_id"] == tid and one["spans"]
        # unknown id -> 404
        try:
            urllib.request.urlopen(
                hs.address + "/debug/traces/" + "0" * 32, timeout=10)
            assert False, "expected 404"
        except urllib.error.HTTPError as e:
            assert e.code == 404
        # openmetrics negotiation on /metrics
        req = urllib.request.Request(
            hs.address + "/metrics",
            headers={"Accept": "application/openmetrics-text"})
        om = urllib.request.urlopen(req, timeout=10)
        assert "openmetrics-text" in om.headers["Content-Type"]
        assert om.read().decode().rstrip().endswith("# EOF")
    finally:
        hs.stop()


# ---------------------------------------------------------------------------
# JSON logging correlates with spans
# ---------------------------------------------------------------------------

def test_json_log_format_injects_trace_ids():
    buf = io.StringIO()
    handler = logging.StreamHandler(buf)
    handler.setFormatter(JsonLogFormatter())
    lg = logging.getLogger("test.tracing.json")
    lg.addHandler(handler)
    lg.setLevel(logging.INFO)
    lg.propagate = False
    try:
        with tracing.span("logged-op", component="scheduler") as sp:
            lg.info("inside span %d", 7)
        lg.info("outside span")
    finally:
        lg.removeHandler(handler)
    lines = [json.loads(ln) for ln in buf.getvalue().splitlines()]
    assert lines[0]["msg"] == "inside span 7"
    assert lines[0]["trace_id"] == sp.trace_id
    assert lines[0]["span_id"] == sp.span_id
    assert lines[0]["level"] == "INFO"
    assert re.fullmatch(r"\d{4}-\d{2}-\d{2}T\d{2}:\d{2}:\d{2}\.\d{3}Z",
                        lines[0]["ts"])
    assert "trace_id" not in lines[1]
