"""The generation binary (cmd/generate.py): checkpoint restore -> decode,
int8 path, ragged prompt batching."""
import jax
import pytest

from nos_tpu.cmd.generate import GenerateConfig, run
from nos_tpu.cmd.trainer import TrainerConfig, train

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs 8 virtual devices")

MODEL = dict(vocab=64, d_model=32, n_layers=2, n_heads=4, d_ff=64,
             max_seq=32, bf16=False)


def test_generates_from_trained_checkpoint(tmp_path):
    ck = str(tmp_path / "ckpt")
    train(TrainerConfig(**MODEL, steps=2, batch_size=4, seq_len=16,
                        checkpoint_dir=ck, checkpoint_every=2))
    cfg = GenerateConfig(**MODEL, checkpoint_dir=ck, max_new_tokens=5)
    out = run(cfg, [[1, 2, 3]])
    assert len(out) == 1 and len(out[0]) == 8
    assert out[0][:3] == [1, 2, 3]
    assert all(0 <= t < 64 for t in out[0])


def test_int8_and_ragged_prompts(tmp_path):
    cfg = GenerateConfig(**MODEL, int8=True, max_new_tokens=4)
    out = run(cfg, [[1, 2], [3, 4, 5], [6, 7]])
    assert [len(s) for s in out] == [6, 7, 6]
    assert out[0][:2] == [1, 2] and out[1][:3] == [3, 4, 5]


def test_deterministic_greedy_across_calls():
    cfg = GenerateConfig(**MODEL, max_new_tokens=6)
    a = run(cfg, [[9, 9]])
    b = run(cfg, [[9, 9]])
    assert a == b


def test_unknown_config_key_rejected(tmp_path):
    p = tmp_path / "bad.yaml"
    p.write_text("vocab: 64\nnot_a_key: 1\n")
    with pytest.raises(ValueError, match="not_a_key"):
        GenerateConfig.from_yaml_file(str(p))


def test_empty_prompt_rejected():
    with pytest.raises(ValueError, match="empty prompt"):
        run(GenerateConfig(**MODEL), [[1, 2], []])
