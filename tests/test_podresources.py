"""Kubelet pod-resources client (agents/podresources.py): wire codec
against hand-encoded protobuf bytes, the real gRPC path over a unix
socket, and the drift reconciliation fed by the kubelet view
(reference pkg/resource/lister.go + client.go)."""
import os
import tempfile

import pytest

from nos_tpu import constants
from nos_tpu.agents.podresources import (
    ContainerDevices,
    KubeletPodResourcesClient,
    MockPodResourcesClient,
    PodResources,
    decode_fields,
)

TPU = constants.RESOURCE_TPU


# ---------------------------------------------------------------------------
# protobuf wire helpers for building test fixtures
# ---------------------------------------------------------------------------

def varint(n: int) -> bytes:
    out = b""
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out += bytes([b | 0x80])
        else:
            return out + bytes([b])


def field_bytes(fnum: int, payload: bytes) -> bytes:
    return varint((fnum << 3) | 2) + varint(len(payload)) + payload


def field_str(fnum: int, s: str) -> bytes:
    return field_bytes(fnum, s.encode())


def container_devices(resource: str, *ids: str) -> bytes:
    out = field_str(1, resource)
    for d in ids:
        out += field_str(2, d)
    return out


def pod_resources_msg(name: str, ns: str, *devs: bytes) -> bytes:
    container = field_str(1, "main")
    for d in devs:
        container += field_bytes(2, d)
    return field_str(1, name) + field_str(2, ns) + field_bytes(3, container)


def list_response(*pods: bytes) -> bytes:
    return b"".join(field_bytes(1, p) for p in pods)


# ---------------------------------------------------------------------------
# codec
# ---------------------------------------------------------------------------

def test_decode_list_response():
    raw = list_response(
        pod_resources_msg("trainer-0", "team-a",
                          container_devices(TPU, "0", "1")),
        pod_resources_msg("infer-0", "team-b",
                          container_devices("nos.ai/tpu-slice-1x1", "s0")),
    )
    fields = decode_fields(raw)
    assert len(fields[1]) == 2

    from nos_tpu.agents.podresources import _decode_pod_resources

    p0 = _decode_pod_resources(fields[1][0])
    assert (p0.name, p0.namespace) == ("trainer-0", "team-a")
    assert p0.device_ids_for(TPU) == {"0", "1"}
    p1 = _decode_pod_resources(fields[1][1])
    assert p1.device_ids_for("nos.ai/tpu-slice-1x1") == {"s0"}
    assert p1.device_ids_for(TPU) == set()


def test_decode_skips_unknown_fields():
    # a future kubelet adding fields (cpu_ids=3 varints, memory=4
    # messages) must not break the decoder
    extra = varint((7 << 3) | 0) + varint(42)        # unknown varint field
    raw = list_response(
        pod_resources_msg("p", "ns", container_devices(TPU, "3")) + extra)
    fields = decode_fields(raw)

    from nos_tpu.agents.podresources import _decode_pod_resources

    assert _decode_pod_resources(fields[1][0]).device_ids_for(TPU) == {"3"}


def test_multibyte_varint_lengths():
    big_id = "x" * 300                               # length needs 2 bytes
    raw = list_response(
        pod_resources_msg("p", "ns", container_devices(TPU, big_id)))
    from nos_tpu.agents.podresources import _decode_pod_resources

    p = _decode_pod_resources(decode_fields(raw)[1][0])
    assert p.device_ids_for(TPU) == {big_id}


# ---------------------------------------------------------------------------
# real gRPC over a unix socket
# ---------------------------------------------------------------------------

@pytest.fixture
def kubelet_sock():
    grpc = pytest.importorskip("grpc")
    tmp = tempfile.mkdtemp()
    sock = os.path.join(tmp, "kubelet.sock")

    response = list_response(
        pod_resources_msg("trainer-0", "team-a",
                          container_devices(TPU, "0", "1", "2", "3")))
    alloc_response = field_bytes(
        1, container_devices(TPU, *[str(i) for i in range(8)]))

    ident = lambda b: b                               # noqa: E731

    def list_handler(request, context):
        return response

    def alloc_handler(request, context):
        return alloc_response

    from concurrent.futures import ThreadPoolExecutor

    server = grpc.server(ThreadPoolExecutor(max_workers=2))
    handlers = grpc.method_handlers_generic_handler(
        "v1.PodResourcesLister",
        {
            "List": grpc.unary_unary_rpc_method_handler(
                list_handler, request_deserializer=ident,
                response_serializer=ident),
            "GetAllocatableResources": grpc.unary_unary_rpc_method_handler(
                alloc_handler, request_deserializer=ident,
                response_serializer=ident),
        },
    )
    server.add_generic_rpc_handlers((handlers,))
    server.add_insecure_port(f"unix://{sock}")
    server.start()
    yield sock
    server.stop(None)


def test_kubelet_client_over_unix_socket(kubelet_sock):
    client = KubeletPodResourcesClient(kubelet_sock, timeout_s=10)
    pods = client.list()
    assert len(pods) == 1
    assert pods[0].namespace == "team-a"
    assert client.used_device_ids(TPU) == {"0", "1", "2", "3"}
    assert client.allocations(TPU) == {("team-a", "trainer-0"):
                                       {"0", "1", "2", "3"}}
    alloc = client.allocatable()
    assert {d for cd in alloc for d in cd.device_ids} == \
        {str(i) for i in range(8)}
    client.close()


# ---------------------------------------------------------------------------
# drift reconciliation with the kubelet view
# ---------------------------------------------------------------------------

def mock_pr(ns, name, *ids, resource=TPU):
    return PodResources(name=name, namespace=ns, devices=[
        ContainerDevices(resource_name=resource, device_ids=tuple(ids))])


def drift_rig(bound_pods, kubelet_pods):
    from nos_tpu.agents.tpu_native import MockTpuClient
    from nos_tpu.agents.tpuagent import attachment_drift
    from nos_tpu.kube import ApiServer
    from nos_tpu.kube.client import Client
    from nos_tpu.kube.objects import (
        Container, ObjectMeta, Pod, PodSpec, PodStatus,
    )

    server = ApiServer()
    for ns, name, uid, phase in bound_pods:
        server.create(Pod(
            metadata=ObjectMeta(name=name, namespace=ns, uid=uid),
            spec=PodSpec(containers=[Container(requests={TPU: 1})],
                         node_name="v5e-0"),
            status=PodStatus(phase=phase),
        ))
    return attachment_drift(
        Client(server), "v5e-0", MockTpuClient(chips=4),
        MockPodResourcesClient(pods=kubelet_pods))


def test_kubelet_ghost_allocation_detected():
    out = drift_rig(
        bound_pods=[("team-a", "trainer-0", "uid-1", "Running")],
        kubelet_pods=[mock_pr("team-a", "trainer-0", "0"),
                      mock_pr("team-b", "gone-pod", "1")])
    assert "ghost-alloc:team-b/gone-pod" in out
    assert "trainer-0" not in out


def test_kubelet_view_suppresses_false_unattached():
    # pod present in the kubelet view but absent from the (empty)
    # device-plugin table: NOT unattached
    out = drift_rig(
        bound_pods=[("team-a", "trainer-0", "uid-1", "Running")],
        kubelet_pods=[mock_pr("team-a", "trainer-0", "0")])
    assert out == ""


def test_missing_everywhere_is_unattached():
    out = drift_rig(
        bound_pods=[("team-a", "trainer-0", "uid-1", "Running")],
        kubelet_pods=[mock_pr("team-b", "other", "1")])
    assert "unattached:uid-1" in out


def test_slice_resources_count_as_kubelet_allocations():
    out = drift_rig(
        bound_pods=[("team-a", "svc-0", "uid-9", "Running")],
        kubelet_pods=[mock_pr("team-a", "svc-0", "s0",
                              resource="nos.ai/tpu-slice-1x1")])
    assert out == ""


def test_kubelet_alloc_for_completed_pod_is_ghost():
    # a Succeeded pod whose devices the kubelet still lists is a leaked
    # allocation: the (ns, name) join must mirror the UID ghost check's
    # Pending/Running filter, not treat any bound pod as legitimate
    out = drift_rig(
        bound_pods=[("team-a", "done-0", "uid-1", "Succeeded")],
        kubelet_pods=[mock_pr("team-a", "done-0", "0")])
    assert "ghost-alloc:team-a/done-0" in out


def test_allocations_accepts_resource_predicate():
    from nos_tpu.agents.podresources import MockPodResourcesClient
    client = MockPodResourcesClient(pods=[
        mock_pr("a", "p0", "0"),
        mock_pr("a", "p1", "1", resource="nos.ai/tpu-slice-2x2"),
        mock_pr("a", "p2", "2", resource="cpu"),
    ])
    allocs = client.allocations(
        lambda r: r == TPU or r.startswith("nos.ai/tpu-slice"))
    assert set(allocs) == {("a", "p0"), ("a", "p1")}
    # exact-name form still works
    assert set(client.allocations(TPU)) == {("a", "p0")}
