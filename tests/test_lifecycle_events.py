"""Lifecycle event model unit tests: notice annotations, unhealthy-chip
parsing, heartbeat lease semantics."""
from nos_tpu import constants
from nos_tpu.kube.apiserver import ApiServer
from nos_tpu.kube.client import Client
from nos_tpu.kube.objects import Node, NodeStatus, ObjectMeta
from nos_tpu.lifecycle.events import (
    NodeHeartbeat,
    deliver_maintenance_notice,
    deliver_preemption_notice,
    maintenance_start,
    preemption_deadline,
    unhealthy_chip_indexes,
)


def _cluster_with_node(name="n0"):
    server = ApiServer()
    client = Client(server)
    server.create(Node(metadata=ObjectMeta(name=name),
                       status=NodeStatus(allocatable={"cpu": 4})))
    return server, client


def test_notice_annotations_roundtrip():
    server, client = _cluster_with_node()
    deliver_maintenance_notice(client, "n0", 123.5)
    deliver_preemption_notice(client, "n0", 99.25)
    node = server.get("Node", "n0")
    assert maintenance_start(node) == 123.5
    assert preemption_deadline(node) == 99.25


def test_malformed_notice_reads_as_none():
    node = Node(metadata=ObjectMeta(name="x", annotations={
        constants.ANNOTATION_MAINTENANCE_START: "soon",
        constants.ANNOTATION_PREEMPTION_DEADLINE: "",
    }))
    assert maintenance_start(node) is None
    assert preemption_deadline(node) is None
    assert maintenance_start(Node(metadata=ObjectMeta(name="y"))) is None


def test_unhealthy_chip_parsing_drops_garbage():
    node = Node(metadata=ObjectMeta(name="x", annotations={
        constants.ANNOTATION_UNHEALTHY_CHIPS: "0, 3,seven,,12",
    }))
    assert unhealthy_chip_indexes(node) == [0, 3, 12]
    assert unhealthy_chip_indexes(Node(metadata=ObjectMeta(name="y"))) == []


def test_heartbeat_creates_then_renews_lease():
    server, client = _cluster_with_node()
    t = [100.0]
    hb = NodeHeartbeat("n0", clock=lambda: t[0])
    assert hb.renew(client)
    lease = server.get("Lease", "n0", constants.NODE_LEASE_NAMESPACE)
    assert lease.spec.holder_identity == "n0"
    assert lease.spec.renew_time == 100.0
    t[0] = 105.0
    assert hb.renew(client)
    lease = server.get("Lease", "n0", constants.NODE_LEASE_NAMESPACE)
    assert lease.spec.renew_time == 105.0


def test_heartbeat_failure_is_quiet():
    class DeadClient:
        def patch(self, *a, **k):
            raise RuntimeError("wire down")

        def create(self, *a, **k):
            raise RuntimeError("wire down")

    hb = NodeHeartbeat("n0")
    assert hb.renew(DeadClient()) is False
