"""Partitioning control plane: tracker, planner, actuator, subslicing module
(model: reference internal/partitioning/core/planner_test.go and the mig/mps
module tests)."""
import pytest

from nos_tpu import constants
from nos_tpu.kube.objects import (
    Container,
    Node,
    NodeStatus,
    ObjectMeta,
    Pod,
    PodCondition,
    PodSpec,
    PodStatus,
)
from nos_tpu.partitioning.actuator import Actuator
from nos_tpu.partitioning.planner import Planner, sort_pods_for_planning
from nos_tpu.partitioning.snapshot import ClusterSnapshot, SnapshotNode
from nos_tpu.partitioning.state import ClusterState, NodePartitioning
from nos_tpu.partitioning.subslicing import (
    NodeInitializer,
    SubslicingPartitioner,
    SubslicingSnapshotTaker,
)
from nos_tpu.partitioning.tracker import SliceTracker
from nos_tpu.scheduler import framework as fw
from nos_tpu.tpu.node import TpuNode
from nos_tpu.tpu.slice import Profile

P11, P22, P24 = Profile(1, 1), Profile(2, 2), Profile(2, 4)
SLICE_11 = "nos.ai/tpu-slice-1x1"
SLICE_22 = "nos.ai/tpu-slice-2x2"
SLICE_24 = "nos.ai/tpu-slice-2x4"


def v5e_node(name, annotations=None, labels=None):
    lab = {
        constants.LABEL_TPU_ACCELERATOR: "tpu-v5-lite-podslice",
        constants.LABEL_TPU_TOPOLOGY: "2x4",
        constants.LABEL_PARTITIONING: constants.PARTITIONING_SUBSLICING,
    }
    lab.update(labels or {})
    return Node(
        metadata=ObjectMeta(name=name, labels=lab, annotations=annotations or {}),
        status=NodeStatus(capacity={"cpu": 96}, allocatable={"cpu": 96}),
    )


def snapshot_of(*nodes) -> ClusterSnapshot:
    out = {}
    for node in nodes:
        tn = TpuNode.from_node(node)
        sn = SnapshotNode(tn, fw.NodeInfo(node, []))
        sn.refresh_allocatable()
        out[node.metadata.name] = sn
    return ClusterSnapshot(out)


def slice_pod(name, profile_resource, qty=1, ns="default", priority=None,
              unschedulable=True):
    conditions = (
        [PodCondition(type="PodScheduled", status="False", reason="Unschedulable")]
        if unschedulable
        else []
    )
    return Pod(
        metadata=ObjectMeta(name=name, namespace=ns),
        spec=PodSpec(
            containers=[Container(requests={profile_resource: qty})],
            priority=priority,
        ),
        status=PodStatus(phase="Pending", conditions=conditions),
    )


# ---------------------------------------------------------------------------
# snapshot fork/commit/revert
# ---------------------------------------------------------------------------

def test_snapshot_fork_commit_revert():
    snap = snapshot_of(v5e_node("n1"))
    sn = snap.get("n1")
    sn.tpu_node.boards[0].init_geometry()
    sn.refresh_allocatable()
    snap.fork()
    snap.get("n1").update_geometry_for({P11: 4})
    assert snap.get("n1").node_info.node.status.allocatable.get(SLICE_11, 0) >= 4
    snap.revert()
    assert snap.get("n1").node_info.node.status.allocatable.get(SLICE_11, 0) == 0
    snap.fork()
    snap.get("n1").update_geometry_for({P11: 4})
    snap.commit()
    assert snap.get("n1").node_info.node.status.allocatable.get(SLICE_11, 0) >= 4


def test_snapshot_double_fork_rejected():
    snap = snapshot_of(v5e_node("n1"))
    snap.fork()
    with pytest.raises(RuntimeError):
        snap.fork()


def test_lacking_resources():
    snap = snapshot_of(v5e_node("n1"))
    snap.get("n1").tpu_node.boards[0].init_geometry()  # 1x(2x4) free
    snap.get("n1").refresh_allocatable()
    pod = slice_pod("p", SLICE_11, qty=3)
    lacking = snap.lacking_resources(pod)
    assert lacking == {SLICE_11: 3}   # no 1x1 slices exist yet
    pod2 = slice_pod("p2", SLICE_24, qty=1)
    assert snap.lacking_resources(pod2) == {}


# ---------------------------------------------------------------------------
# tracker
# ---------------------------------------------------------------------------

def test_tracker_aggregates_and_removes():
    snap = snapshot_of(v5e_node("n1"))
    pods = [slice_pod("a", SLICE_11, 2), slice_pod("b", SLICE_11, 1),
            slice_pod("c", SLICE_22, 1)]
    tracker = SliceTracker(snap, pods)
    assert tracker.lacking == {P11: 3, P22: 1}
    tracker.remove(pods[0])
    assert tracker.lacking == {P11: 1, P22: 1}
    tracker.remove(pods[1])
    tracker.remove(pods[2])
    assert tracker.is_empty()


# ---------------------------------------------------------------------------
# planner
# ---------------------------------------------------------------------------

def test_sort_pods_priority_then_size():
    pods = [
        slice_pod("big-low", SLICE_24, priority=0),
        slice_pod("small-low", SLICE_11, priority=0),
        slice_pod("small-high", SLICE_11, priority=10),
    ]
    assert [p.metadata.name for p in sort_pods_for_planning(pods)] == [
        "small-high", "small-low", "big-low",
    ]


def test_planner_repartitions_virgin_node_for_pending_pods():
    snap = snapshot_of(v5e_node("n1"))
    snap.get("n1").tpu_node.boards[0].init_geometry()   # whole board 2x4
    snap.get("n1").refresh_allocatable()
    pods = [slice_pod("a", SLICE_11, 2), slice_pod("b", SLICE_22, 1)]
    plan = Planner(plan_id_fn=lambda: "t1").plan(snap, pods)
    assert plan.id == "t1"
    geometry = plan.desired_state["n1"].boards[0]
    assert geometry.get(P11, 0) >= 2
    assert geometry.get(P22, 0) >= 1


def test_planner_keeps_geometry_when_pods_cannot_fit():
    """Reference planner_test.go case: 'Cluster geometry cannot be changed
    for pending Pods' — demand that exceeds every node leaves geometry
    untouched."""
    snap = snapshot_of(v5e_node("n1"))
    snap.get("n1").tpu_node.boards[0].init_geometry()
    snap.get("n1").refresh_allocatable()
    before = snap.partitioning_state()
    pods = [slice_pod("impossible", SLICE_24, qty=3)]   # 3 whole boards on 1 node
    plan = Planner(plan_id_fn=lambda: "t1").plan(snap, pods)
    assert plan.desired_state["n1"] == before["n1"]


def test_planner_respects_used_slices():
    node = v5e_node("n1", annotations={
        "nos.ai/status-tpu-0-2x2-used": "1",
        "nos.ai/status-tpu-0-2x2-free": "1",
    })
    snap = snapshot_of(node)
    pods = [slice_pod("a", SLICE_11, 4)]
    plan = Planner(plan_id_fn=lambda: "t1").plan(snap, pods)
    geometry = plan.desired_state["n1"].boards[0]
    assert geometry.get(P22, 0) >= 1          # used 2x2 preserved
    assert geometry.get(P11, 0) >= 4


def test_planner_spreads_over_multiple_nodes():
    snap = snapshot_of(v5e_node("n1"), v5e_node("n2"))
    for n in ("n1", "n2"):
        snap.get(n).tpu_node.boards[0].init_geometry()
        snap.get(n).refresh_allocatable()
    # 16 single-chip slices: 8 per v5e node
    pods = [slice_pod(f"p{i}", SLICE_11, 1) for i in range(16)]
    plan = Planner(plan_id_fn=lambda: "t1").plan(snap, pods)
    assert plan.desired_state["n1"].boards[0] == {P11: 8}
    assert plan.desired_state["n2"].boards[0] == {P11: 8}


def test_planner_only_helps_schedulable_pods():
    """A pod whose node selector matches nothing must not trigger geometry
    churn."""
    snap = snapshot_of(v5e_node("n1"))
    snap.get("n1").tpu_node.boards[0].init_geometry()
    snap.get("n1").refresh_allocatable()
    before = snap.partitioning_state()
    pod = slice_pod("selector-miss", SLICE_11, 1)
    pod.spec.node_selector = {constants.LABEL_TPU_ACCELERATOR: "tpu-v5p-slice"}
    plan = Planner(plan_id_fn=lambda: "t1").plan(snap, [pod])
    assert plan.desired_state["n1"] == before["n1"]


# ---------------------------------------------------------------------------
# actuator + subslicing partitioner
# ---------------------------------------------------------------------------

class RecordingPartitioner:
    def __init__(self):
        self.applied = []

    def apply_partitioning(self, client, node_name, plan_id, partitioning):
        self.applied.append((node_name, plan_id, partitioning))


def test_actuator_applies_only_diffs():
    from nos_tpu.partitioning.planner import PartitioningPlan

    rec = RecordingPartitioner()
    actuator = Actuator(rec)
    current = {
        "n1": NodePartitioning(boards={0: {P24: 1}}),
        "n2": NodePartitioning(boards={0: {P24: 1}}),
    }
    desired = {
        "n1": NodePartitioning(boards={0: {P24: 1}}),      # unchanged
        "n2": NodePartitioning(boards={0: {P11: 8}}),      # changed
    }
    assert actuator.apply(None, current, PartitioningPlan(desired, "plan-1"))
    assert [a[0] for a in rec.applied] == ["n2"]


def test_actuator_noop_on_equal_or_empty():
    from nos_tpu.partitioning.planner import PartitioningPlan

    rec = RecordingPartitioner()
    actuator = Actuator(rec)
    state = {"n1": NodePartitioning(boards={0: {P24: 1}})}
    assert not actuator.apply(None, state, PartitioningPlan(dict(state), "p"))
    assert not actuator.apply(None, state, PartitioningPlan({}, "p"))
    assert rec.applied == []


def test_subslicing_partitioner_writes_wire_format():
    from nos_tpu.kube import ApiServer, Client

    server = ApiServer()
    client = Client(server)
    server.create(v5e_node("n1"))
    SubslicingPartitioner().apply_partitioning(
        client, "n1", "plan-42", NodePartitioning(boards={0: {P11: 4, P22: 1}})
    )
    node = server.get("Node", "n1")
    assert node.metadata.annotations["nos.ai/spec-tpu-0-1x1"] == "4"
    assert node.metadata.annotations["nos.ai/spec-tpu-0-2x2"] == "1"
    assert node.metadata.annotations[constants.ANNOTATION_PARTITIONING_PLAN] == "plan-42"
    assert node.metadata.labels[constants.LABEL_DEVICE_PLUGIN_CONFIG] == "n1-plan-42"
    cm = server.get("ConfigMap", constants.DEVICE_PLUGIN_CONFIGMAP,
                    constants.DEVICE_PLUGIN_NAMESPACE)
    assert "n1-plan-42" in cm.data
    # reapplying replaces stale spec annotations
    SubslicingPartitioner().apply_partitioning(
        client, "n1", "plan-43", NodePartitioning(boards={0: {P24: 1}})
    )
    node = server.get("Node", "n1")
    assert "nos.ai/spec-tpu-0-1x1" not in node.metadata.annotations
    assert node.metadata.annotations["nos.ai/spec-tpu-0-2x4"] == "1"


def test_node_initializer_virgin_node():
    from nos_tpu.kube import ApiServer, Client

    server = ApiServer()
    client = Client(server)
    server.create(v5e_node("n1"))
    init = NodeInitializer(plan_id_fn=lambda: "init-1")
    node = server.get("Node", "n1")
    assert init.initialize(client, node)
    got = server.get("Node", "n1")
    assert got.metadata.annotations["nos.ai/spec-tpu-0-2x4"] == "1"
    # second call is a no-op (already has spec annotations)
    assert not init.initialize(client, got)


def test_snapshot_taker_only_labeled_tpu_nodes():
    state = ClusterState()
    state.upsert_node(v5e_node("tpu-1"))
    plain = Node(metadata=ObjectMeta(
        name="cpu-1", labels={constants.LABEL_PARTITIONING: "subslicing"}))
    state.upsert_node(plain)  # labeled but not a TPU node
    snap = SubslicingSnapshotTaker().take(state)
    assert set(snap.nodes().keys()) == {"tpu-1"}
