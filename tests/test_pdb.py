"""PodDisruptionBudget: status maintenance (quota/pdb — the disruption-
controller analog) and PDB-aware preemption ordering
(scheduler/capacity.filter_units_with_pdb_violation + reprieve order +
candidate-node ranking — reference capacity_scheduling.go:634, :850-889).
"""
from nos_tpu.kube import ApiServer, Manager
from nos_tpu.kube.client import Client
from nos_tpu.kube.objects import (
    Container, ObjectMeta, Pod, PodDisruptionBudget,
    PodDisruptionBudgetSpec, PodSpec, PodStatus,
)
from nos_tpu.quota.pdb import PdbReconciler, compute_status
from nos_tpu.scheduler.capacity import filter_units_with_pdb_violation

TPU = "google.com/tpu"


def mk_pod(name, ns="team-a", phase="Running", labels=None, node="n1",
           priority=0, tpu=1):
    return Pod(
        metadata=ObjectMeta(name=name, namespace=ns, uid=f"uid-{name}",
                            labels=labels or {}),
        spec=PodSpec(containers=[Container(requests={TPU: tpu})],
                     node_name=node, priority=priority),
        status=PodStatus(phase=phase),
    )


def mk_pdb(name="budget", ns="team-a", selector=None, min_available=None,
           max_unavailable=None, allowed=None, disrupted=None):
    pdb = PodDisruptionBudget(
        metadata=ObjectMeta(name=name, namespace=ns),
        spec=PodDisruptionBudgetSpec(
            selector=selector if selector is not None else {"app": "train"},
            min_available=min_available, max_unavailable=max_unavailable,
        ),
    )
    if allowed is not None:
        pdb.status.disruptions_allowed = allowed
    if disrupted is not None:
        pdb.status.disrupted_pods = disrupted
    return pdb


# ---------------------------------------------------------------------------
# compute_status
# ---------------------------------------------------------------------------

def test_min_available_budget():
    pods = [mk_pod(f"t-{i}", labels={"app": "train"}) for i in range(4)]
    pdb = mk_pdb(min_available=3)
    allowed, healthy, desired, expected = compute_status(pdb, pods)
    assert (allowed, healthy, desired, expected) == (1, 4, 3, 4)


def test_max_unavailable_budget():
    pods = [mk_pod(f"t-{i}", labels={"app": "train"}) for i in range(4)]
    pdb = mk_pdb(max_unavailable=1)
    allowed, healthy, desired, expected = compute_status(pdb, pods)
    assert (allowed, desired) == (1, 3)


def test_completed_pods_leave_the_budget():
    pods = [mk_pod("t-0", labels={"app": "train"}),
            mk_pod("t-1", labels={"app": "train"}, phase="Succeeded")]
    pdb = mk_pdb(min_available=1)
    allowed, healthy, desired, expected = compute_status(pdb, pods)
    assert (healthy, expected, allowed) == (1, 1, 0)


def test_pending_pods_count_expected_not_healthy():
    pods = [mk_pod("t-0", labels={"app": "train"}),
            mk_pod("t-1", labels={"app": "train"}, phase="Pending")]
    pdb = mk_pdb(min_available=1)
    allowed, healthy, desired, expected = compute_status(pdb, pods)
    assert (healthy, expected, allowed) == (1, 2, 0)


def test_in_flight_disruption_reserves_budget():
    pods = [mk_pod(f"t-{i}", labels={"app": "train"}) for i in range(4)]
    pdb = mk_pdb(min_available=2, disrupted={"t-0": "ts"})
    allowed, *_ = compute_status(pdb, pods)
    assert allowed == 1  # 4 healthy - 2 desired - 1 in flight


def test_empty_selector_budgets_nothing():
    pods = [mk_pod("t-0", labels={"app": "train"})]
    pdb = mk_pdb(selector={}, min_available=1)
    allowed, healthy, desired, expected = compute_status(pdb, pods)
    assert (healthy, expected) == (0, 0)


# ---------------------------------------------------------------------------
# PdbReconciler end-to-end (ApiServer + Manager pump)
# ---------------------------------------------------------------------------

def _rig():
    server = ApiServer()
    mgr = Manager(server)
    mgr.add_controller(PdbReconciler().controller())
    return server, mgr


def test_reconciler_maintains_status():
    server, mgr = _rig()
    server.create(mk_pdb(min_available=1))
    for i in range(3):
        server.create(mk_pod(f"t-{i}", labels={"app": "train"}))
    mgr.run_until_idle()
    pdb = server.get("PodDisruptionBudget", "budget", "team-a")
    assert pdb.status.disruptions_allowed == 2
    assert pdb.status.current_healthy == 3
    assert pdb.status.expected_pods == 3

    server.delete("Pod", "t-0", "team-a")
    server.delete("Pod", "t-1", "team-a")
    mgr.run_until_idle()
    pdb = server.get("PodDisruptionBudget", "budget", "team-a")
    assert pdb.status.disruptions_allowed == 0
    assert pdb.status.current_healthy == 1


def test_reconciler_prunes_finished_disrupted_pods():
    server, mgr = _rig()
    server.create(mk_pdb(min_available=0, disrupted={"gone": "ts"}))
    server.create(mk_pod("t-0", labels={"app": "train"}))
    mgr.run_until_idle()
    pdb = server.get("PodDisruptionBudget", "budget", "team-a")
    assert pdb.status.disrupted_pods == {}
    assert pdb.status.disruptions_allowed == 1


# ---------------------------------------------------------------------------
# filter_units_with_pdb_violation
# ---------------------------------------------------------------------------

def test_budget_spent_in_order():
    a = [mk_pod("a", labels={"app": "train"})]
    b = [mk_pod("b", labels={"app": "train"})]
    pdb = mk_pdb(allowed=1, min_available=1)
    violating, ok = filter_units_with_pdb_violation([a, b], [pdb])
    assert ok == [a]            # first unit consumes the single allowance
    assert violating == [b]


def test_gang_unit_spends_budget_per_member():
    gang = [mk_pod("g-0", labels={"app": "train"}),
            mk_pod("g-1", labels={"app": "train"})]
    pdb = mk_pdb(allowed=1, min_available=1)
    violating, ok = filter_units_with_pdb_violation([gang], [pdb])
    assert violating == [gang]  # 2 members vs allowance 1


def test_disrupted_pods_never_double_decrement():
    a = [mk_pod("a", labels={"app": "train"})]
    pdb = mk_pdb(allowed=0, min_available=1, disrupted={"a": "ts"})
    violating, ok = filter_units_with_pdb_violation([a], [pdb])
    assert ok == [a]


def test_cross_namespace_pdb_does_not_match():
    a = [mk_pod("a", ns="team-b", labels={"app": "train"})]
    pdb = mk_pdb(allowed=0, min_available=1)   # ns team-a
    violating, ok = filter_units_with_pdb_violation([a], [pdb])
    assert ok == [a]


# ---------------------------------------------------------------------------
# preemption integration (CapacityScheduling)
# ---------------------------------------------------------------------------

def _capacity_rig(pods, pdbs, nodes):
    from nos_tpu.quota.info import QuotaInfo
    from nos_tpu.scheduler import framework as fw
    from nos_tpu.scheduler.capacity import CapacityScheduling

    cs = CapacityScheduling()
    # team-a min 2: the 1-chip preemptor lands over min (borrowing
    # regime), making same-namespace lower-priority pods eligible victims
    for ns, mn in {"team-a": 2, "team-b": 0}.items():
        cs.quotas.add(QuotaInfo(name=f"eq-{ns}", namespace=ns,
                                namespaces={ns}, min={TPU: mn},
                                calculator=cs.calc))
    snap = fw.Snapshot.build(nodes, pods, cs.calc)
    for p in pods:
        cs.track_pod(p)
    cs.sync_pdbs(pdbs)
    return cs, snap


def _node(name, tpu=2):
    from nos_tpu.kube.objects import Node, NodeStatus
    return Node(metadata=ObjectMeta(name=name),
                status=NodeStatus(capacity={TPU: tpu},
                                  allocatable={TPU: tpu}))


def test_pdb_flips_reprieve_order():
    # one eviction suffices; without PDBs the higher-priority pod is
    # reprieved first (lower-priority becomes victim). A PDB with no
    # remaining allowance protecting the LOW-priority pod must flip it:
    # the protected pod is reprieved first and spared, the unprotected
    # high-priority pod becomes the victim.
    low = mk_pod("low", priority=1, labels={"app": "train"})
    high = mk_pod("high", priority=5, labels={"app": "other"})
    preemptor = mk_pod("new", priority=10, node="")
    pdb = mk_pdb(allowed=0, min_available=2)
    cs, snap = _capacity_rig([low, high], [pdb], [_node("n1")])
    state = {}
    cs.pre_filter(state, preemptor, snap)
    victims, num_violating = cs._select_victims_on_node(
        state, preemptor, snap["n1"])
    assert [v.metadata.name for v in victims] == ["high"]
    assert num_violating == 0

    # control: without the PDB the low-priority pod is the victim
    cs2, snap2 = _capacity_rig([low, high], [], [_node("n1")])
    state2 = {}
    cs2.pre_filter(state2, preemptor, snap2)
    victims2, _ = cs2._select_victims_on_node(state2, preemptor, snap2["n1"])
    assert [v.metadata.name for v in victims2] == ["low"]


def test_post_filter_prefers_node_without_pdb_violation():
    # both nodes need one victim; n1's only candidate is PDB-protected
    # (violating), n2's is not — rank (violations, victims) must pick n2
    # even though n1 sorts first lexically.
    v1 = mk_pod("v1", priority=1, labels={"app": "train"}, node="n1")
    v2 = mk_pod("v2", priority=1, labels={"app": "other"}, node="n2")
    preemptor = mk_pod("new", priority=10, node="")
    pdb = mk_pdb(allowed=0, min_available=1)
    cs, snap = _capacity_rig([v1, v2], [pdb],
                             [_node("n1", tpu=1), _node("n2", tpu=1)])
    state = {}
    cs.pre_filter(state, preemptor, snap)
    node, status = cs.post_filter(state, preemptor, snap)
    assert status.success
    assert node == "n2"
    assert [v.metadata.name for v in state["capacity/victims"]] == ["v2"]


def test_codec_roundtrip():
    from nos_tpu.kube import k8s_codec as kc

    pdb = mk_pdb(min_available=2, allowed=1, disrupted={"t-0": "ts"})
    pdb.status.current_healthy = 3
    wire = kc.to_k8s(pdb)
    assert wire["apiVersion"] == "policy/v1"
    assert wire["spec"]["selector"]["matchLabels"] == {"app": "train"}
    back = kc.from_k8s(wire)
    assert back.spec.min_available == 2
    assert back.status.disruptions_allowed == 1
    assert back.status.disrupted_pods == {"t-0": "ts"}
    assert back.matches(mk_pod("x", labels={"app": "train"}))


def test_preemption_records_disruption_in_pdb():
    # the eviction-API side effect: before deleting a victim the
    # scheduler writes it into every matching PDB's disrupted_pods and
    # spends the allowance, so a concurrent pass can't double-spend;
    # the reconciler prunes the entry once the deletion lands.
    from nos_tpu import constants as C
    from nos_tpu.api.quota import ElasticQuota, ElasticQuotaSpec
    from nos_tpu.cmd import operator as op_cmd, scheduler as sched_cmd
    from nos_tpu.kube.objects import Node, NodeStatus

    server = ApiServer()
    op = op_cmd.build(server)
    sched = sched_cmd.build(server)
    server.create(Node(metadata=ObjectMeta(name="n1"),
                       status=NodeStatus(capacity={TPU: 1},
                                         allocatable={TPU: 1})))
    server.create(ElasticQuota(
        metadata=ObjectMeta(name="eq-a", namespace="team-a"),
        spec=ElasticQuotaSpec(min={TPU: 1})))  # preemptor lands over min
    server.create(mk_pdb(min_available=0))     # allowance 1 once reconciled

    victim = mk_pod("victim", labels={"app": "train"}, node="n1")
    victim.spec.scheduler_name = C.SCHEDULER_NAME
    server.create(victim)
    op.run_until_idle()
    assert server.get("PodDisruptionBudget", "budget",
                      "team-a").status.disruptions_allowed == 1

    urgent = mk_pod("urgent", node="", priority=10, phase="Pending")
    urgent.spec.scheduler_name = C.SCHEDULER_NAME
    server.create(urgent)
    sched.run_until_idle()

    import pytest as _pytest
    with _pytest.raises(Exception):            # victim evicted
        server.get("Pod", "victim", "team-a")
    # the scheduler spent the budget and recorded the in-flight eviction
    # (the operator has not reconciled yet, so the entry is still there
    # unless it already pumped — accept either pruned-or-present, but the
    # allowance must never exceed the recomputed truth)
    op.run_until_idle()
    pdb = server.get("PodDisruptionBudget", "budget", "team-a")
    assert pdb.status.disrupted_pods == {}     # pruned after deletion
    assert pdb.status.disruptions_allowed == 0  # no matching pods left
