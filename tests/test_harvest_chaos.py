"""Seeded chaos soak for the harvest plane (ISSUE 12 acceptance): the
conservation invariant must hold under the named faults —

- node death mid-checkpoint (the slice and its in-flight save die),
- harvester crash at arbitrary protocol points (a fresh controller
  re-enters from the annotation journal),
- reclaim racing a harvest scale-up (the flash crowd returns while a
  gang is still binding/admitting),
- hung checkpointers (the degradation ladder's forced arm),

all interleaved by a seeded schedule over the REAL scheduler + quota
reconciler on one fake clock. Pinned per seed:

- **serving displaced == 0**: a bound guaranteed pod is NEVER evicted
  by the borrow — only the driver's own deletions remove serve pods;
- **bounded loss**: a graceful reclaim resumes AT the notice step and
  loses at most the budget window; forced/preempted reclaims add at
  most one budget window on top of what the injected fault had already
  left unbanked; gangs whose saver was never wedged resume from a
  checkpoint at most one interval (+ save duration) old;
- **exactly-once**: reclaim ids are unique, no pod keeps a reclaim
  journal entry after settle, and no gang is double-evicted or left
  fenced — after the storm every slot is Running, admitted and
  provably stepping again.
"""
import json
import random

import pytest

from nos_tpu import constants
from nos_tpu.harvest import HarvestController
from nos_tpu.kube.controller import Request
from tests.test_harvest import (
    BUDGET, CKPT_DURATION, CKPT_INTERVAL, STEP_RATE, Rig, serve_pod,
)

SOAK_S = 360
MARGIN = 3.0            # scheduling/tick slop, in steps


def run_soak(seed: int) -> dict:
    rng = random.Random(seed)
    # drive the harvester BY HAND so a "crash" is a fresh instance with
    # empty memory — the Manager runs only the scheduler + quota loops
    rig = Rig(with_harvester=False)
    req = Request(name="hv", namespace="batch")
    ctl = HarvestController(rig.cfg, trainer=rig.trainer,
                            clock=rig.clock)
    entries = []
    hung_ever = set()
    crashes = 0
    serve_n = 0
    serve_next = 0
    target = 0

    def set_serve(n):
        nonlocal serve_n, serve_next
        while serve_n < n:
            rig.server.create(serve_pod(f"web-{serve_next}"))
            serve_next += 1
            serve_n += 1
        extra = serve_n - n
        live = sorted(
            (p.metadata.name
             for p in rig.server.list("Pod", namespace="serve")
             if p.status.phase in ("Pending", "Running")),
            key=lambda s: int(s.split("-")[1]))
        for name in live[:extra]:
            rig.delete_serve(name)
            serve_n -= 1

    t = 0
    while t < SOAK_S:
        # -- demand schedule: random square wave over the pool --------
        if t >= target:
            set_serve(rng.choice((0, 0, 4, 8, 12)))
            target = t + rng.randint(40, 100)
        # -- chaos -----------------------------------------------------
        roll = rng.random()
        attached = sorted(g for g, st in rig.trainer._gangs.items()
                          if st.attached)
        if roll < 0.012 and attached:
            victim = rng.choice(attached)        # node death (sometimes
            rig.trainer.kill(victim)             # mid-checkpoint)
            for p in rig.gang_pods(victim):
                rig.server.delete("Pod", p.metadata.name, "batch")
        elif roll < 0.022 and attached:
            victim = rng.choice(attached)        # wedge the saver
            rig.trainer.hang_checkpoints(victim)
            hung_ever.add(victim)
        elif roll < 0.034:
            entries.extend(ctl.ledger())         # harvester crash: the
            ctl = HarvestController(             # journal must carry it
                rig.cfg, trainer=rig.trainer, clock=rig.clock)
            crashes += 1
        # -- one tick --------------------------------------------------
        rig.mgr.run_until_idle()
        ctl.reconcile(rig.client, req)
        rig.kubelet.sync(rig.client)
        rig.mgr.run_until_idle()
        rig.trainer.tick(1.0)
        rig._audit()
        rig.clock.advance(1.0)
        t += 1

    # -- settle: storm over, demand gone, savers unwedged --------------
    set_serve(0)
    for gang in hung_ever:
        rig.trainer.hang_checkpoints(gang, hung=False)
    for _ in range(90):
        rig.mgr.run_until_idle()
        ctl.reconcile(rig.client, req)
        rig.kubelet.sync(rig.client)
        rig.mgr.run_until_idle()
        rig.trainer.tick(1.0)
        rig._audit()
        rig.clock.advance(1.0)
    entries.extend(ctl.ledger())
    steps_a = rig.trainer.useful_steps()
    for _ in range(30):
        rig.mgr.run_until_idle()
        ctl.reconcile(rig.client, req)
        rig.kubelet.sync(rig.client)
        rig.mgr.run_until_idle()
        rig.trainer.tick(1.0)
        rig.clock.advance(1.0)
    steps_b = rig.trainer.useful_steps()
    out = {
        "rig": rig, "entries": entries, "hung_ever": hung_ever,
        "crashes": crashes, "steps_a": steps_a, "steps_b": steps_b,
    }
    rig.teardown()
    return out


def check_invariants(seed: int, soak: dict) -> None:
    rig, entries = soak["rig"], soak["entries"]
    tag = f"seed {seed}"
    # 1. serving is NEVER displaced by the borrow
    assert rig.displaced == [], f"{tag}: displaced {rig.displaced}"
    # 2. bounded loss per reclaim
    ids = [e["id"] for e in entries if e["id"]]
    assert len(ids) == len(set(ids)), f"{tag}: duplicate reclaim ids"
    for e in entries:
        unbanked_at_notice = max(0, e["notice_step"] - e["resume_step"])
        protocol_cost = e["steps_lost"] - unbanked_at_notice
        assert protocol_cost <= STEP_RATE * BUDGET + MARGIN, (tag, e)
        if e["outcome"] == "graceful":
            assert e["resume_step"] >= e["notice_step"], (tag, e)
        if e["outcome"] != "preempted" \
                and e["gang"] not in soak["hung_ever"]:
            # a healthy saver keeps the resume lineage at most one
            # interval (+ save duration) behind the notice step
            assert unbanked_at_notice <= STEP_RATE * (
                CKPT_INTERVAL + CKPT_DURATION) + MARGIN, (tag, e)
    # 3. exactly-once / no orphaned state after settle
    pods = rig.batch_pods()
    assert len(pods) == rig.cfg.max_gangs * rig.cfg.gang_size, (
        tag, [p.metadata.name for p in pods])
    for p in pods:
        assert constants.ANNOTATION_HARVEST_RECLAIM \
            not in p.metadata.annotations, (tag, p.metadata.name)
        assert constants.ANNOTATION_RECLAIM_NOTICE \
            not in p.metadata.annotations, (tag, p.metadata.name)
        assert p.status.phase == "Running", (tag, p.metadata.name)
    for gang in (f"hv-g{i}" for i in range(rig.cfg.max_gangs)):
        st = rig.trainer._gangs[gang]
        assert st.attached and st.admitted and not st.fenced, (tag, gang)
    # 4. the storm trained SOMETHING and the settle window proves every
    #    gang is stepping again (no silent fence/hold leak)
    assert soak["steps_a"] > 0, tag
    assert soak["steps_b"] >= soak["steps_a"] + \
        rig.cfg.max_gangs * STEP_RATE * 30 - MARGIN, (
        tag, soak["steps_a"], soak["steps_b"])


@pytest.mark.parametrize("seed", [1, 2, 3])
def test_harvest_chaos_soak(seed):
    soak = run_soak(seed)
    check_invariants(seed, soak)


@pytest.mark.slow
@pytest.mark.parametrize("seed", [4, 5, 6, 7, 8, 9])
def test_harvest_chaos_soak_slow(seed):
    soak = run_soak(seed)
    check_invariants(seed, soak)
