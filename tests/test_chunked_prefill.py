"""Chunked prefill (models/serving.py prefill_chunk): a long prompt's
prefill runs as fixed-size chunks interleaved with decode ticks, so
admission delays active slots' next token by one bounded chunk forward
instead of one whole-prompt forward — with the engine's invariants
intact: tokens identical to the unchunked engine (greedy and sampled),
prefix-cache composition, cancel mid-prefill, slot accounting."""
import jax
import jax.numpy as jnp
import pytest

from nos_tpu.models import transformer as tfm
from nos_tpu.models.serving import DecodeServer

CFG = tfm.TransformerConfig(
    vocab=64, d_model=32, n_layers=2, n_heads=4, n_kv_heads=2,
    d_ff=64, max_seq=128, dtype=jnp.float32)
LONG = [(i * 7 + 3) % 64 for i in range(40)]    # >> chunk of 8


@pytest.fixture(scope="module")
def params():
    return tfm.init_params(jax.random.PRNGKey(0), CFG)


def drain_all(srv, reqs):
    rids = [srv.submit(p, n, **kw) for p, n, kw in reqs]
    out = srv.drain()
    return [out[r] for r in rids]


def test_tokens_invariant_to_chunking(params):
    reqs = [
        (LONG, 8, dict()),
        (LONG[:17], 6, dict(temperature=0.7, top_k=8, seed=5)),
        ([5, 9], 6, dict()),                    # short: one-shot path
    ]
    want = drain_all(DecodeServer(params, CFG, max_batch=2), reqs)
    got = drain_all(
        DecodeServer(params, CFG, max_batch=2, prefill_chunk=8), reqs)
    assert got == want


def test_chunk_exact_multiple_and_one_off(params):
    # prompt lengths around the chunk boundary: exact multiple, +1, -1
    for plen in (16, 17, 15, 8, 9):
        prompt = LONG[:plen]
        want = drain_all(DecodeServer(params, CFG, max_batch=1),
                         [(prompt, 5, {})])
        got = drain_all(
            DecodeServer(params, CFG, max_batch=1, prefill_chunk=8),
            [(prompt, 5, {})])
        assert got == want, f"plen={plen}"


def test_active_slots_decode_during_prefill(params):
    """The whole point: while a long prompt prefills chunk by chunk, an
    already-active request emits one token per step()."""
    srv = DecodeServer(params, CFG, max_batch=2, prefill_chunk=8)
    a = srv.submit([4, 5], 30)
    srv.step()                      # a is decoding
    before = len(srv.progress(a)[0])
    b = srv.submit(LONG, 4)         # 5 chunks of 8 — deferred
    assert srv._prefilling          # admission did not run the forward
    ticks = 0
    while srv._prefilling:
        srv.step()
        ticks += 1
    # a progressed on EVERY tick b spent prefilling
    assert len(srv.progress(a)[0]) - before == ticks
    assert ticks == 5               # ceil(40/8) chunks, one per tick
    out = srv.drain()
    assert out[b][:len(LONG)] == LONG


def test_prefix_cache_composes_with_chunking(params):
    sys_prompt = LONG[:24]

    def run(srv):
        a = srv.submit(sys_prompt + [1], 4, cache_prefix=True)
        srv.drain()
        b = srv.submit(sys_prompt + [2, 3], 4)
        srv.drain()
        return srv.pop_result(a), srv.pop_result(b), srv.prefix_hits

    pa, pb, _ = run(DecodeServer(params, CFG, max_batch=1,
                                 prefix_cache_size=4))
    ca, cb, hits = run(DecodeServer(params, CFG, max_batch=1,
                                    prefix_cache_size=4, prefill_chunk=8))
    assert (ca, cb) == (pa, pb)
    assert hits >= 1


def test_cancel_mid_prefill_frees_slot(params):
    srv = DecodeServer(params, CFG, max_batch=1, prefill_chunk=8)
    b = srv.submit(LONG, 4)
    srv.step()                      # one chunk in
    assert srv._prefilling
    assert srv.cancel(b)
    assert not srv._prefilling and list(srv._free) == [0]
    # the freed slot serves the next request normally
    c = srv.submit([7, 7], 3)
    out = srv.drain()
    assert out[b] == LONG           # canceled: prompt only
    assert len(out[c]) == 5


def test_bad_chunk_sizes_rejected(params):
    for bad in (7, 12, 4, -8):
        with pytest.raises(ValueError, match="power of two"):
            DecodeServer(params, CFG, max_batch=1, prefill_chunk=bad)


def test_spec_server_composes_with_chunking(params):
    """Speculative engine + chunked prefill: the target chunks through
    ticks, the draft prefills whole at install, and tokens (greedy AND
    sampled) match the unchunked speculative engine — which itself
    matches the plain target engine for greedy rows."""
    from nos_tpu.models.spec_serving import SpeculativeDecodeServer
    dcfg = tfm.TransformerConfig(
        vocab=64, d_model=16, n_layers=1, n_heads=2, n_kv_heads=1,
        d_ff=32, max_seq=128, dtype=jnp.float32)
    dparams = tfm.init_params(jax.random.PRNGKey(1), dcfg)
    reqs = [
        (LONG, 6, dict()),
        (LONG[:19], 5, dict(temperature=0.7, top_k=8, seed=5)),
    ]

    def mk(**kw):
        return SpeculativeDecodeServer(params, CFG, dparams, dcfg,
                                       n_draft=3, max_batch=2, **kw)

    want = drain_all(mk(), reqs)
    got = drain_all(mk(prefill_chunk=8), reqs)
    assert got == want
    plain = drain_all(DecodeServer(params, CFG, max_batch=2),
                      [reqs[0]])
    assert got[0] == plain[0]       # greedy spec == plain target


def test_spec_active_slots_tick_during_chunked_prefill(params):
    from nos_tpu.models.spec_serving import SpeculativeDecodeServer
    dcfg = tfm.TransformerConfig(
        vocab=64, d_model=16, n_layers=1, n_heads=2, n_kv_heads=1,
        d_ff=32, max_seq=128, dtype=jnp.float32)
    dparams = tfm.init_params(jax.random.PRNGKey(1), dcfg)
    srv = SpeculativeDecodeServer(params, CFG, dparams, dcfg,
                                  n_draft=3, max_batch=2,
                                  prefill_chunk=8)
    a = srv.submit([4, 5], 30)
    srv.step()
    before = len(srv.progress(a)[0])
    srv.submit(LONG, 4)
    assert srv._prefilling
    # the DRAFT chunks alongside the target: no whole-prompt draft
    # forward can spike the install tick
    assert len(srv._prefilling[0]["dtodo"]) == 5
    ticks = 0
    while srv._prefilling:
        srv.step()
        ticks += 1
    assert ticks == 5
    assert not srv._chunked_drow       # stash consumed at install
    # a emitted on every tick (>= 1 token per speculative tick)
    assert len(srv.progress(a)[0]) - before >= ticks
    srv.drain()


def test_chunking_composes_with_tp_mesh(params):
    import numpy as np
    from jax.sharding import Mesh

    mesh = Mesh(np.asarray(jax.devices()[:2]), ("tp",))
    sp = jax.device_put(params, tfm.param_shardings(mesh, CFG))
    reqs = [(LONG, 6, {}), (LONG[:13], 5, {})]
    want = drain_all(DecodeServer(params, CFG, max_batch=2), reqs)
    got = drain_all(DecodeServer(sp, CFG, max_batch=2, prefill_chunk=8,
                                 mesh=mesh), reqs)
    assert got == want


def test_server_config_rejects_bad_chunk_and_spec_combo_pre_load():
    """build_engine fails on config alone — before any checkpoint load."""
    from nos_tpu.cmd.server import ServerConfig, build_engine
    base = dict(vocab=64, d_model=32, n_layers=2, n_heads=4,
                n_kv_heads=2, d_ff=64, max_seq=128, bf16=False)
    with pytest.raises(ValueError, match="power of two"):
        build_engine(ServerConfig(**base, prefill_chunk=100))
    with pytest.raises(ValueError, match="draft kv_heads"):
        build_engine(ServerConfig(**base, tp=2, draft_n_kv_heads=1,
                                  draft_checkpoint_dir="/nope"))


def test_trivial_prefix_head_not_used_under_chunking(params):
    """A 1-token shared head saves no chunk forwards — the chunked path
    must not count it as a hit (profitability invariant)."""
    srv = DecodeServer(params, CFG, max_batch=1, prefix_cache_size=4,
                       prefill_chunk=8)
    srv.submit([9] + LONG[:20], 3, cache_prefix=True)
    srv.drain()
    hits0 = srv.prefix_hits
    srv.submit([9] + list(reversed(LONG[:20])), 3)   # shares only [9]
    srv.drain()
    assert srv.prefix_hits == hits0
    assert srv.prefix_tokens_saved == 0 or srv.prefix_tokens_saved >= 8
