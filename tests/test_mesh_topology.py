"""Topology-aware mesh construction (VERDICT r1 #5 / r2 next #3): the
"tp lands on ICI neighbors" claim is a tested invariant, not a docstring.
Fabricated-coords devices stand in for a real torus; the CPU fallback is
exercised through build_mesh on the 8-device test platform."""
import itertools

import numpy as np
import pytest

from nos_tpu.parallel.layout import ParallelLayout
from nos_tpu.parallel.mesh import (
    _snake_indices, arrange_devices, build_mesh, device_grid_coords,
)


class FakeDev:
    """Looks enough like a TPU device: coords + core_on_chip."""

    def __init__(self, coords, core=0, id_=0):
        self.coords = tuple(coords)
        self.core_on_chip = core
        self.id = id_

    def __repr__(self):
        return f"FakeDev{self.coords}/{self.core_on_chip}"


def torus(*shape):
    devs = []
    for i, c in enumerate(itertools.product(*(range(s) for s in shape))):
        devs.append(FakeDev(c, id_=i))
    return devs


def hop_distance(a: FakeDev, b: FakeDev, shape):
    """Torus hop count between two chips (wrap links counted)."""
    total = 0
    for ca, cb, s in zip(a.coords + (a.core_on_chip,),
                         b.coords + (b.core_on_chip,),
                         tuple(shape) + (1,)):
        d = abs(ca - cb)
        total += min(d, s - d) if s > 1 else d
    return total


# ---------------------------------------------------------------- snake walk

def test_snake_consecutive_indices_are_unit_steps():
    for shape in [(2, 2, 2), (4, 4, 4), (3, 5), (2, 3, 4, 2)]:
        walk = list(_snake_indices(shape))
        n = int(np.prod(shape))
        assert len(walk) == n and len(set(walk)) == n  # Hamiltonian
        for a, b in zip(walk, walk[1:]):
            diffs = [abs(x - y) for x, y in zip(a, b)]
            assert sum(diffs) == 1, f"{a}->{b} not a unit step"


# ------------------------------------------------------- coords extraction

def test_device_grid_coords_normalizes_offset_subgrid():
    devs = [FakeDev((x + 4, y + 2, 7)) for x in range(2) for y in range(2)]
    norm = device_grid_coords(devs)
    assert set(norm.values()) == {(x, y, 0, 0) for x in range(2) for y in range(2)}


def test_device_grid_coords_rejects_holes():
    devs = torus(2, 2, 2)
    assert device_grid_coords(devs[:-1] + [FakeDev((9, 9, 9))]) is None


def test_device_grid_coords_none_without_coords():
    class Bare:
        pass

    assert device_grid_coords([Bare(), Bare()]) is None


def test_two_core_chips_get_core_dimension():
    devs = [FakeDev((x, 0, 0), core=c, id_=2 * x + c)
            for x in range(2) for c in range(2)]
    grid = arrange_devices(devs, (2, 2))
    # inner axis must vary core (the cheapest "link"), not cross chips
    for row in grid:
        assert row[0].coords == row[1].coords


# --------------------------------------------------- the headline invariant

@pytest.mark.parametrize("shape,sizes", [
    ((2, 2, 2), (2, 4)),       # dp=2, tp=4 on a 2x2x2 cube
    ((2, 2, 2), (2, 2, 2)),
    ((4, 4, 4), (4, 16)),      # fsdp=4, tp=16 on v5p 4x4x4
    ((4, 4, 4), (2, 2, 4, 4)),
    ((4, 4, 1), (4, 4)),       # v5e 2D slice
])
def test_inner_axis_neighbors_are_one_torus_hop(shape, sizes):
    devs = torus(*shape)
    grid = arrange_devices(devs, sizes)
    flat_rows = grid.reshape(-1, sizes[-1])
    for row in flat_rows:
        for a, b in zip(row, row[1:]):
            assert hop_distance(a, b, shape) == 1, (
                f"tp neighbors {a} {b} are {hop_distance(a, b, shape)} hops apart")


def test_whole_walk_is_unit_steps_so_every_axis_stays_local():
    # the flattened mesh order itself is a one-hop walk: outer axes get
    # contiguous physical blocks too (dp blocks are compact sub-regions)
    shape, sizes = (4, 4, 4), (4, 4, 4)
    grid = arrange_devices(torus(*shape), sizes)
    flat = grid.reshape(-1)
    for a, b in zip(flat, flat[1:]):
        assert hop_distance(a, b, shape) == 1


def test_fallback_preserves_enumeration_order_without_coords():
    class Bare:
        def __init__(self, i):
            self.id = i

    devs = [Bare(i) for i in range(8)]
    grid = arrange_devices(devs, (2, 4))
    assert [d.id for d in grid.reshape(-1)] == list(range(8))


def test_build_mesh_on_cpu_devices_still_works():
    import jax

    devs = jax.devices()
    if len(devs) < 8:
        pytest.skip("needs 8 virtual devices")
    layout = ParallelLayout(dp=2, tp=4)
    mesh = build_mesh(layout, devs)
    assert mesh.shape["dp"] == 2 and mesh.shape["tp"] == 4


def test_build_mesh_uses_coords_when_available():
    devs = torus(2, 2, 2)
    grid = arrange_devices(devs, (2, 2, 2))
    # flat order must NOT be plain enumeration (snake reverses odd rows)
    assert [d.id for d in grid.reshape(-1)] != list(range(8))
