"""Topology-aware mesh construction (VERDICT r1 #5 / r2 next #3): the
"tp lands on ICI neighbors" claim is a tested invariant, not a docstring.
Fabricated-coords devices stand in for a real torus; the CPU fallback is
exercised through build_mesh on the 8-device test platform."""
import itertools

import numpy as np
import pytest

from nos_tpu.parallel.layout import ParallelLayout
from nos_tpu.parallel.mesh import (
    _snake_indices, arrange_devices, build_mesh, device_grid_coords,
)


class FakeDev:
    """Looks enough like a TPU device: coords + core_on_chip."""

    def __init__(self, coords, core=0, id_=0):
        self.coords = tuple(coords)
        self.core_on_chip = core
        self.id = id_

    def __repr__(self):
        return f"FakeDev{self.coords}/{self.core_on_chip}"


def torus(*shape):
    devs = []
    for i, c in enumerate(itertools.product(*(range(s) for s in shape))):
        devs.append(FakeDev(c, id_=i))
    return devs


def hop_distance(a: FakeDev, b: FakeDev, shape):
    """Torus hop count between two chips (wrap links counted)."""
    total = 0
    for ca, cb, s in zip(a.coords + (a.core_on_chip,),
                         b.coords + (b.core_on_chip,),
                         tuple(shape) + (1,)):
        d = abs(ca - cb)
        total += min(d, s - d) if s > 1 else d
    return total


# ---------------------------------------------------------------- snake walk

def test_snake_consecutive_indices_are_unit_steps():
    for shape in [(2, 2, 2), (4, 4, 4), (3, 5), (2, 3, 4, 2)]:
        walk = list(_snake_indices(shape))
        n = int(np.prod(shape))
        assert len(walk) == n and len(set(walk)) == n  # Hamiltonian
        for a, b in zip(walk, walk[1:]):
            diffs = [abs(x - y) for x, y in zip(a, b)]
            assert sum(diffs) == 1, f"{a}->{b} not a unit step"


# ------------------------------------------------------- coords extraction

def test_device_grid_coords_normalizes_offset_subgrid():
    devs = [FakeDev((x + 4, y + 2, 7)) for x in range(2) for y in range(2)]
    norm, shape = device_grid_coords(devs)
    assert set(norm.values()) == {(x, y, 0, 0) for x in range(2) for y in range(2)}
    assert shape == (2, 2, 1, 1)


def test_device_grid_coords_rejects_holes():
    devs = torus(2, 2, 2)
    assert device_grid_coords(devs[:-1] + [FakeDev((9, 9, 9))]) is None


def test_device_grid_coords_none_without_coords():
    class Bare:
        pass

    assert device_grid_coords([Bare(), Bare()]) is None


def test_two_core_chips_get_core_dimension():
    devs = [FakeDev((x, 0, 0), core=c, id_=2 * x + c)
            for x in range(2) for c in range(2)]
    grid = arrange_devices(devs, (2, 2))
    # inner axis must vary core (the cheapest "link"), not cross chips
    for row in grid:
        assert row[0].coords == row[1].coords


# --------------------------------------------------- the headline invariant

@pytest.mark.parametrize("shape,sizes", [
    ((2, 2, 2), (2, 4)),       # dp=2, tp=4 on a 2x2x2 cube
    ((2, 2, 2), (2, 2, 2)),
    ((4, 4, 4), (4, 16)),      # fsdp=4, tp=16 on v5p 4x4x4
    ((4, 4, 4), (2, 2, 4, 4)),
    ((4, 4, 1), (4, 4)),       # v5e 2D slice
])
def test_inner_axis_neighbors_are_one_torus_hop(shape, sizes):
    devs = torus(*shape)
    grid = arrange_devices(devs, sizes)
    flat_rows = grid.reshape(-1, sizes[-1])
    for row in flat_rows:
        for a, b in zip(row, row[1:]):
            assert hop_distance(a, b, shape) == 1, (
                f"tp neighbors {a} {b} are {hop_distance(a, b, shape)} hops apart")


def test_whole_walk_is_unit_steps_so_every_axis_stays_local():
    # the flattened mesh order itself is a one-hop walk: outer axes get
    # contiguous physical blocks too (dp blocks are compact sub-regions)
    shape, sizes = (4, 4, 4), (4, 4, 4)
    grid = arrange_devices(torus(*shape), sizes)
    flat = grid.reshape(-1)
    for a, b in zip(flat, flat[1:]):
        assert hop_distance(a, b, shape) == 1


def test_fallback_preserves_enumeration_order_without_coords():
    class Bare:
        def __init__(self, i):
            self.id = i

    devs = [Bare(i) for i in range(8)]
    grid = arrange_devices(devs, (2, 4))
    assert [d.id for d in grid.reshape(-1)] == list(range(8))


def test_build_mesh_on_cpu_devices_still_works():
    import jax

    devs = jax.devices()
    if len(devs) < 8:
        pytest.skip("needs 8 virtual devices")
    layout = ParallelLayout(dp=2, tp=4)
    mesh = build_mesh(layout, devs)
    assert mesh.shape["dp"] == 2 and mesh.shape["tp"] == 4


def test_build_mesh_uses_coords_when_available():
    devs = torus(2, 2, 2)
    grid = arrange_devices(devs, (2, 2, 2))
    # flat order must NOT be plain enumeration (snake reverses odd rows)
    assert [d.id for d in grid.reshape(-1)] != list(range(8))


# ------------------------------------------------------- multi-slice / DCN

class FakeSliceDev(FakeDev):
    def __init__(self, coords, slice_index, id_=0):
        super().__init__(coords, id_=id_)
        self.slice_index = slice_index

    def __repr__(self):
        return f"FakeSliceDev(s{self.slice_index}){self.coords}"


def two_slices(shape=(2, 2, 1)):
    devs = []
    for s in range(2):
        for i, c in enumerate(itertools.product(*(range(x) for x in shape))):
            devs.append(FakeSliceDev(c, s, id_=s * 100 + i))
    return devs


def test_multislice_inner_axes_never_cross_slice_boundary():
    devs = two_slices()                     # 2 slices x 4 chips
    grid = arrange_devices(devs, (2, 4))    # dp=2 outer, tp=4 inner
    for row in grid:                        # each dp row = one slice
        assert len({d.slice_index for d in row}) == 1
    # and within a slice the tp walk is still ICI-unit-step
    for row in grid:
        for a, b in zip(row, row[1:]):
            assert hop_distance(a, b, (2, 2, 1)) == 1


def test_multislice_requires_divisible_data_axes():
    devs = two_slices()
    with pytest.raises(ValueError, match="cross DCN"):
        arrange_devices(devs, (1, 8))       # outer=1 can't split 2 slices
    # axis-identity aware: a model-only layout (tp, pp) must not let tp
    # straddle DCN silently
    with pytest.raises(ValueError, match="dp/fsdp"):
        build_mesh(ParallelLayout(tp=4, pp=2), devs)


def test_multislice_accepts_data_product_across_leading_axes():
    # 4 slices x 2 chips; dp*fsdp = 4 aligns even though dp alone (2) < 4
    devs = []
    for s in range(4):
        for i, c in enumerate(itertools.product(range(2), range(1), range(1))):
            devs.append(FakeSliceDev(c, s, id_=s * 10 + i))
    mesh = build_mesh(ParallelLayout(dp=2, fsdp=2, tp=2), devs)
    for idx_dp in range(2):
        for idx_fs in range(2):
            row = mesh.devices[idx_dp, idx_fs]
            assert len({d.slice_index for d in row}) == 1


def test_multislice_build_mesh_places_dp_across_dcn():
    devs = two_slices()
    mesh = build_mesh(ParallelLayout(dp=2, tp=4), devs)
    arr = mesh.devices
    assert arr.shape == (2, 4)
    assert {d.slice_index for d in arr[0]} != \
        {d.slice_index for d in arr[1]}


def test_ragged_slices_align_or_raise():
    """Unequal per-slice contributions are fine only when every slice
    boundary lands on a model-block stride; otherwise a model-axis
    collective would silently cross DCN — raise instead (advisor r3)."""
    devs = two_slices()[:6]                 # 4 + 2 chips: ragged
    # (3, 2): model blocks of 2; the 4|2 boundary falls at offset 4 —
    # aligned, so the ragged layout is accepted and slice-contiguous
    grid = arrange_devices(devs, (3, 2))
    assert grid.shape == (3, 2)
    flat = list(grid.ravel())
    assert [d.slice_index for d in flat] == [0, 0, 0, 0, 1, 1]
    for row in grid:                        # no row straddles DCN
        assert len({d.slice_index for d in row}) == 1
    # (2, 3): model blocks of 3; boundary at 4 falls mid-block — the
    # middle row would straddle DCN: refuse
    with pytest.raises(ValueError, match="cross DCN"):
        arrange_devices(devs, (2, 3))


def test_truncation_consumes_whole_slices_first():
    devs = two_slices()                     # 2 slices x 4 chips, need 4
    grid = arrange_devices(devs, (2, 2))
    assert {d.slice_index for d in grid.ravel()} == {0}


def test_slice_ids_override_builds_multislice_from_plain_devices():
    """slice_ids fabricates slice identity for devices that carry no
    slice_index attribute (CPU dryruns, megascale env-var runtimes):
    same DCN-boundary guarantees as attribute-carrying devices."""
    class Plain:
        def __init__(self, i):
            self.id = i

        def __repr__(self):
            return f"Plain({self.id})"

    devs = [Plain(i) for i in range(8)]
    grid = arrange_devices(devs, (2, 2, 2), names=("dp", "tp", "sp"),
                           slice_ids=[i // 4 for i in range(8)])
    for r in range(2):                      # dp rows slice-contiguous
        ids = {d.id // 4 for d in grid[r].ravel()}
        assert len(ids) == 1
    with pytest.raises(ValueError, match="cross DCN"):
        arrange_devices(devs, (1, 8), slice_ids=[i // 4 for i in range(8)])
    with pytest.raises(ValueError, match="align"):
        arrange_devices(devs, (2, 4), slice_ids=[0, 1])
