"""Remat-policy and chunked-loss-head coverage (VERDICT r2 next #2b).

The named policies ("except_mlp", "minimal") exist so the flagship batch
can train with near-zero recompute on a 16 GB v5e: "dots" saves the wide
[B, S, d_ff] mlp intermediates (the HBM hog), the named policies save
only the attention-sized tensors tagged with checkpoint_name in
models/transformer.py. All policies are the same math — only the
saved-set differs — so loss and grads must match "full" exactly.
"""
import jax
import jax.numpy as jnp
import pytest

from nos_tpu.models import transformer as tr

BASE = dict(vocab=128, d_model=64, n_layers=2, n_heads=4, n_kv_heads=2,
            d_ff=128, max_seq=64)


def _loss_and_gnorm(cfg):
    params = tr.init_params(jax.random.PRNGKey(0), cfg)
    tok = jax.random.randint(jax.random.PRNGKey(1), (2, 64), 0, cfg.vocab)
    batch = {"tokens": tok, "targets": tok}
    loss, grads = jax.jit(
        jax.value_and_grad(lambda p, b: tr.loss_fn(p, cfg, b)))(params, batch)
    gnorm = jnp.sqrt(sum(jnp.sum(jnp.square(g.astype(jnp.float32)))
                         for g in jax.tree.leaves(grads)))
    return float(loss), float(gnorm)


@pytest.fixture(scope="module")
def full_ref():
    return _loss_and_gnorm(tr.TransformerConfig(**BASE, remat_policy="full"))


@pytest.mark.parametrize("policy", ["dots", "except_mlp", "minimal"])
def test_policy_matches_full(policy, full_ref):
    loss, gnorm = _loss_and_gnorm(
        tr.TransformerConfig(**BASE, remat_policy=policy))
    assert loss == pytest.approx(full_ref[0], abs=1e-4)
    assert gnorm == pytest.approx(full_ref[1], rel=1e-3)


def test_chunked_loss_head_matches_unchunked(full_ref):
    loss, gnorm = _loss_and_gnorm(tr.TransformerConfig(**BASE, loss_chunk=16))
    assert loss == pytest.approx(full_ref[0], abs=1e-3)
    assert gnorm == pytest.approx(full_ref[1], rel=1e-2)


def test_chunked_head_never_materializes_full_logits():
    """The point of loss_chunk: the fp32 [B, S, vocab] logits must not
    appear in the compiled backward's live set. Compare compiled temp
    memory with a vocab big enough to dominate."""
    kw = dict(BASE, vocab=4096, remat_policy="minimal")
    tok = jax.random.randint(jax.random.PRNGKey(1), (2, 64), 0, 4096)
    batch = {"tokens": tok, "targets": tok}

    def temp_bytes(cfg):
        params = tr.init_params(jax.random.PRNGKey(0), cfg)
        c = jax.jit(
            jax.value_and_grad(lambda p, b: tr.loss_fn(p, cfg, b))
        ).lower(params, batch).compile()
        return c.memory_analysis().temp_size_in_bytes

    plain = temp_bytes(tr.TransformerConfig(**kw))
    chunked = temp_bytes(tr.TransformerConfig(**kw, loss_chunk=8))
    # full logits+logp: 2 * 2*64*4096*4B = 4.2 MB of the plain temp set;
    # chunked keeps one 8-token slice live at a time
    assert chunked < plain


def test_named_policies_save_less_than_dots():
    """Compiled temp memory must be ordered full <= minimal <= except_mlp
    <= dots at a shape where the d_ff intermediates dominate."""
    kw = dict(BASE, d_model=128, d_ff=512, n_layers=4, max_seq=256)
    tok = jax.random.randint(jax.random.PRNGKey(1), (4, 256), 0, 128)
    batch = {"tokens": tok, "targets": tok}

    def temp_bytes(policy):
        cfg = tr.TransformerConfig(**kw, remat_policy=policy)
        params = tr.init_params(jax.random.PRNGKey(0), cfg)
        c = jax.jit(
            jax.value_and_grad(lambda p, b: tr.loss_fn(p, cfg, b))
        ).lower(params, batch).compile()
        return c.memory_analysis().temp_size_in_bytes

    sizes = {p: temp_bytes(p) for p in ("full", "minimal", "except_mlp",
                                        "dots")}
    assert sizes["minimal"] <= sizes["except_mlp"] <= sizes["dots"]
    assert sizes["full"] <= sizes["except_mlp"]


def test_unknown_policy_rejected():
    with pytest.raises(ValueError, match="remat_policy"):
        tr.TransformerConfig(**BASE, remat_policy="everything")


def test_loss_chunk_must_divide_seq_len():
    """A loss_chunk that doesn't divide S must raise, not silently
    materialise the full [B, S, vocab] logits (advisor r3)."""
    cfg = tr.TransformerConfig(**BASE, loss_chunk=7)
    params = tr.init_params(jax.random.PRNGKey(0), cfg)
    batch = {
        "tokens": jnp.zeros((2, 32), jnp.int32),
        "targets": jnp.zeros((2, 32), jnp.int32),
    }
    with pytest.raises(ValueError, match="loss_chunk"):
        tr.loss_fn(params, cfg, batch)
