"""Device-plugin hand-off over REAL unix-socket gRPC: the partitioner
writes the ConfigMap + node label, the TPU device plugin reads it and
advertises sub-slice resources to a (mock) kubelet via the v1beta1
Device Plugin API — registration, ListAndWatch streaming updates on plan
changes, and Allocate. This is the previously-simulated consumer made
concrete (VERDICT r4 partial #2), validated to the protocol level."""
import tempfile

import pytest

from nos_tpu import constants
from nos_tpu.agents.deviceplugin import (
    MockKubelet,
    PluginConfig,
    TpuDevicePlugin,
    config_source_from_client,
    decode_allocate_request,
    decode_allocate_response,
    decode_list_and_watch_response,
    decode_register_request,
    devices_from_config,
    encode_allocate_response,
    encode_list_and_watch_response,
    encode_register_request,
)
from nos_tpu.kube import ApiServer
from nos_tpu.kube.objects import Node, NodeStatus, ObjectMeta
from nos_tpu.partitioning.state import NodePartitioning
from nos_tpu.partitioning.subslicing import SubslicingPartitioner

SLICE_1x1 = constants.RESOURCE_TPU_SLICE_PREFIX + "1x1"
SLICE_2x2 = constants.RESOURCE_TPU_SLICE_PREFIX + "2x2"


# ---------------------------------------------------------------------------
# wire codec
# ---------------------------------------------------------------------------

def test_register_request_roundtrip():
    raw = encode_register_request(SLICE_1x1, "nos-tpu-x.sock")
    got = decode_register_request(raw)
    assert got == {"version": "v1beta1", "endpoint": "nos-tpu-x.sock",
                   "resource": SLICE_1x1}


def test_list_and_watch_roundtrip():
    ids = ["b0-1x1-0", "b0-1x1-1", "b1-1x1-0"]
    assert decode_list_and_watch_response(
        encode_list_and_watch_response(ids)) == ids
    assert decode_list_and_watch_response(
        encode_list_and_watch_response([])) == []


def test_allocate_roundtrip():
    from nos_tpu.agents.deviceplugin import _ld, _str

    req = _ld(1, _str(1, "b0-1x1-0") + _str(1, "b0-1x1-1"))
    assert decode_allocate_request(req) == [["b0-1x1-0", "b0-1x1-1"]]
    resp = encode_allocate_response([{"A": "1"}, {"B": "2"}])
    assert decode_allocate_response(resp) == [{"A": "1"}, {"B": "2"}]


def test_devices_from_config_stable_ids():
    cfg = PluginConfig.parse("n1-plan1", """
        {"version": "v1", "boards": {"0": {"1x1": 2}, "1": {"2x2": 1}}}""")
    devs = devices_from_config(cfg)
    assert devs == {SLICE_1x1: ["b0-1x1-0", "b0-1x1-1"],
                    SLICE_2x2: ["b1-2x2-0"]}


# ---------------------------------------------------------------------------
# the full hand-off over real sockets
# ---------------------------------------------------------------------------

@pytest.fixture()
def socket_dir():
    # unix socket paths cap at ~104 bytes: keep it short
    with tempfile.TemporaryDirectory(prefix="dp", dir="/tmp") as d:
        yield d


def test_handoff_end_to_end(socket_dir):
    server = ApiServer()
    server.create(Node(metadata=ObjectMeta(name="n1"),
                       status=NodeStatus(capacity={}, allocatable={})))
    part = SubslicingPartitioner()
    part.apply_partitioning(server, "n1", "plan-1", NodePartitioning(
        boards={0: {"1x1": 2}, 1: {"2x2": 1}}))

    kubelet = MockKubelet(socket_dir)
    plugin = TpuDevicePlugin(
        config_source_from_client(server, "n1"),
        socket_dir, kubelet_socket=kubelet.socket_path)
    try:
        assert plugin.refresh() is True
        assert kubelet.wait_for(
            lambda d: d.get(SLICE_1x1) and d.get(SLICE_2x2))
        assert kubelet.allocatable() == {SLICE_1x1: 2, SLICE_2x2: 1}
        regs = {r["resource"]: r for r in kubelet.registrations}
        assert set(regs) == {SLICE_1x1, SLICE_2x2}
        assert all(r["version"] == "v1beta1" for r in regs.values())

        # no change -> no-op
        assert plugin.refresh() is False

        # plan change: counts move WITHOUT re-registration, via a new
        # frame on the live ListAndWatch stream
        part.apply_partitioning(server, "n1", "plan-2", NodePartitioning(
            boards={0: {"1x1": 4}}))
        assert plugin.refresh() is True
        assert kubelet.wait_for(
            lambda d: len(d.get(SLICE_1x1) or []) == 4
            and (d.get(SLICE_2x2) or []) == [])
        assert kubelet.allocatable() == {SLICE_1x1: 4}
        assert len(kubelet.registrations) == 2   # no re-register

        # Allocate: the env tells the container WHICH sub-slices it got
        envs = kubelet.allocate(regs[SLICE_1x1], ["b0-1x1-1", "b0-1x1-3"])
        assert envs == [{
            "NOS_TPU_SUBSLICE_IDS": "b0-1x1-1,b0-1x1-3",
            "NOS_TPU_RESOURCE": SLICE_1x1,
        }]
    finally:
        plugin.stop()
        kubelet.stop()


def test_plugin_without_handoff_is_inert(socket_dir):
    server = ApiServer()
    server.create(Node(metadata=ObjectMeta(name="n1"),
                       status=NodeStatus(capacity={}, allocatable={})))
    kubelet = MockKubelet(socket_dir)
    plugin = TpuDevicePlugin(
        config_source_from_client(server, "n1"),
        socket_dir, kubelet_socket=kubelet.socket_path)
    try:
        assert plugin.refresh() is False        # no label -> nothing
        assert kubelet.registrations == []
    finally:
        plugin.stop()
        kubelet.stop()


def test_kubelet_restart_triggers_reregistration(socket_dir):
    """A restarting kubelet recreates its socket and forgets every
    plugin: the inode change must force teardown + re-register."""
    server = ApiServer()
    server.create(Node(metadata=ObjectMeta(name="n1"),
                       status=NodeStatus(capacity={}, allocatable={})))
    SubslicingPartitioner().apply_partitioning(
        server, "n1", "plan-1", NodePartitioning(boards={0: {"1x1": 2}}))
    kubelet = MockKubelet(socket_dir)
    plugin = TpuDevicePlugin(
        config_source_from_client(server, "n1"),
        socket_dir, kubelet_socket=kubelet.socket_path)
    try:
        plugin.refresh()
        assert kubelet.wait_for(lambda d: len(d.get(SLICE_1x1) or []) == 2)
        # "restart" the kubelet: new socket file -> new inode
        kubelet.stop()
        kubelet2 = MockKubelet(socket_dir)
        assert plugin.refresh() is True          # same plan, new kubelet
        assert kubelet2.wait_for(
            lambda d: len(d.get(SLICE_1x1) or []) == 2)
        assert len(kubelet2.registrations) == 1
        kubelet2.stop()
    finally:
        plugin.stop()


def test_failed_registration_is_retried(socket_dir):
    """A resource whose Register call failed must not be recorded as
    done: the next refresh retries it (a served-but-unregistered socket
    would advertise devices the kubelet never learns about)."""
    server = ApiServer()
    server.create(Node(metadata=ObjectMeta(name="n1"),
                       status=NodeStatus(capacity={}, allocatable={})))
    SubslicingPartitioner().apply_partitioning(
        server, "n1", "plan-1", NodePartitioning(boards={0: {"1x1": 1}}))
    # no kubelet running yet: registration fails
    plugin = TpuDevicePlugin(
        config_source_from_client(server, "n1"),
        socket_dir,
        kubelet_socket=f"{socket_dir}/kubelet.sock")
    try:
        with pytest.raises(Exception):
            plugin.refresh()
        assert plugin._servers == {}             # nothing half-recorded
        kubelet = MockKubelet(socket_dir)        # kubelet comes up
        assert plugin.refresh() is True
        assert kubelet.wait_for(lambda d: len(d.get(SLICE_1x1) or []) == 1)
        kubelet.stop()
    finally:
        plugin.stop()


def test_stale_socket_from_sigkilled_predecessor_is_replaced(socket_dir):
    """A SIGKILLed plugin leaves its per-resource socket file on the
    hostPath; the replacement must unlink and bind fresh — grpc returns
    0 from add_insecure_port instead of raising, which would leave the
    kubelet registered to an endpoint nobody serves."""
    import os
    import socket as pysocket

    # plant a stale socket file where the plugin will bind
    stale = os.path.join(socket_dir, "nos-tpu-tpu-slice-1x1.sock")
    s = pysocket.socket(pysocket.AF_UNIX)
    s.bind(stale)
    s.close()                                    # file stays behind

    server = ApiServer()
    server.create(Node(metadata=ObjectMeta(name="n1"),
                       status=NodeStatus(capacity={}, allocatable={})))
    SubslicingPartitioner().apply_partitioning(
        server, "n1", "plan-1", NodePartitioning(boards={0: {"1x1": 2}}))
    kubelet = MockKubelet(socket_dir)
    plugin = TpuDevicePlugin(
        config_source_from_client(server, "n1"),
        socket_dir, kubelet_socket=kubelet.socket_path)
    try:
        assert plugin.refresh() is True
        assert kubelet.wait_for(lambda d: len(d.get(SLICE_1x1) or []) == 2)
    finally:
        plugin.stop()
        kubelet.stop()
