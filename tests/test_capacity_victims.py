"""Unit-level victim selection for quota-aware preemption.

Scenario tables for CapacityScheduling._select_victims_on_node / post_filter,
modeling the reference's SelectVictimsOnNode decision structure
(capacity_scheduling.go:468-675) and the guaranteed-overquota fair-sharing
rule (elasticquotainfo.go:81-152). Complements the end-to-end preemption
tests in test_scheduler.py with precise victim-identity assertions.
"""
from nos_tpu import constants
from nos_tpu.kube.objects import (
    Container,
    Node,
    NodeStatus,
    ObjectMeta,
    Pod,
    PodSpec,
    PodStatus,
)
from nos_tpu.quota.info import QuotaInfo, QuotaInfos
from nos_tpu.scheduler import framework as fw
from nos_tpu.scheduler.capacity import CapacityScheduling

TPU = "google.com/tpu"
OVER = {constants.LABEL_CAPACITY: constants.CAPACITY_OVER_QUOTA}
IN = {constants.LABEL_CAPACITY: constants.CAPACITY_IN_QUOTA}


def make_pod(name, ns, tpu, priority=0, labels=None, node="n1"):
    return Pod(
        metadata=ObjectMeta(name=name, namespace=ns, labels=dict(labels or {})),
        spec=PodSpec(containers=[Container(requests={TPU: tpu})],
                     node_name=node, priority=priority),
        status=PodStatus(phase="Running"),
    )


def make_node(name="n1", tpu=8):
    return Node(
        metadata=ObjectMeta(name=name),
        status=NodeStatus(capacity={TPU: tpu}, allocatable={TPU: tpu}),
    )


def rig(quota_mins, running, maxes=None, tpu=8, nodes=None):
    """Build a CapacityScheduling with quotas + used tracked from running
    pods, and a Snapshot of the given nodes."""
    cs = CapacityScheduling()
    cs.quotas = QuotaInfos()
    for name, (ns, mn) in quota_mins.items():
        cs.quotas.add(QuotaInfo(
            name=name, namespace=ns, namespaces={ns}, min={TPU: mn},
            max={TPU: (maxes or {}).get(name)} if name in (maxes or {}) else None,
            calculator=cs.calc,
        ))
    node_objs = nodes or [make_node(tpu=tpu)]
    snap = fw.Snapshot.build(node_objs, running, cs.calc)
    for p in running:
        cs.track_pod(p)
    return cs, snap


def select(cs, snap, pod, node_name="n1"):
    state = {}
    cs.pre_filter(state, pod, snap)   # populates state; status ignored
    out = cs._select_victims_on_node(state, pod, snap[node_name])
    return out[0] if out is not None else None


def names(victims):
    return sorted(p.metadata.name for p in victims) if victims is not None else None


# ---------------------------------------------------------------------------
# regime 1: preemptor borrows beyond its min (fair-sharing rule)
# ---------------------------------------------------------------------------
# Shared numbers: quotas a:min4, b:min4, c:min8 (total min 16). c is idle so
# the aggregated overquota is 8 chips; guaranteed shares: a=2, b=2, c=4.

def fair_share_rig(b_over_chips, node_tpu, a_max=None):
    running = [
        make_pod("a-run", "ns-a", 4),
        make_pod("b-in", "ns-b", 4, labels=IN),
        make_pod("b-over", "ns-b", b_over_chips, labels=OVER),
    ]
    cs, snap = rig(
        {"qa": ("ns-a", 4), "qb": ("ns-b", 4), "qc": ("ns-c", 8)},
        running,
        maxes={"qa": a_max} if a_max is not None else None,
        nodes=[make_node(tpu=node_tpu)],
    )
    return cs, snap


def test_borrowing_preemptor_evicts_over_share_quota():
    # b uses 10 > its min+guaranteed share (4+2); a's request keeps it within
    # its own share (4 used + 2 req == 4+2) -> b's over-quota pod is a victim.
    cs, snap = fair_share_rig(b_over_chips=6, node_tpu=14)
    victims = select(cs, snap, make_pod("a-new", "ns-a", 2, node=""))
    assert names(victims) == ["b-over"]


def test_fair_share_protects_quota_within_its_guaranteed_share():
    # b uses 5 <= its min+guaranteed share (6): its over-quota pod is
    # protected even though b is over min.
    cs, snap = fair_share_rig(b_over_chips=1, node_tpu=9)
    victims = select(cs, snap, make_pod("a-new", "ns-a", 2, node=""))
    assert victims is None


def test_preemptor_beyond_own_share_cannot_evict_cross_namespace():
    # Same cluster as the first scenario, but a asks for 4: 4 used + 4 req
    # exceeds its share bound (6) -> no cross-namespace victims at all.
    cs, snap = fair_share_rig(b_over_chips=6, node_tpu=14)
    victims = select(cs, snap, make_pod("a-new", "ns-a", 4, node=""))
    assert victims is None


def test_max_quota_recheck_blocks_fair_share_eviction():
    # Identical to the eviction scenario, but a's max (5) is below
    # min+guaranteed (6): the post-removal max recheck must veto.
    cs, snap = fair_share_rig(b_over_chips=6, node_tpu=14, a_max=5)
    victims = select(cs, snap, make_pod("a-new", "ns-a", 2, node=""))
    assert victims is None


def test_borrowing_same_namespace_only_lower_priority():
    running = [
        make_pod("a-low", "ns-a", 4, priority=0),
        make_pod("a-high", "ns-a", 4, priority=200),
    ]
    cs, snap = rig({"qa": ("ns-a", 8)}, running)
    victims = select(cs, snap, make_pod("a-new", "ns-a", 4, node="", priority=100))
    assert names(victims) == ["a-low"]   # never the higher-priority pod


# ---------------------------------------------------------------------------
# regime 2: preemptor within min reclaims borrowed capacity
# ---------------------------------------------------------------------------

def test_within_min_reclaims_borrowed_capacity():
    running = [
        make_pod("b-in", "ns-b", 4, labels=IN),
        make_pod("b-over", "ns-b", 4, labels=OVER),
    ]
    cs, snap = rig({"qa": ("ns-a", 4), "qb": ("ns-b", 4)}, running)
    victims = select(cs, snap, make_pod("a-new", "ns-a", 4, node=""))
    assert names(victims) == ["b-over"]


def test_unlabeled_cross_namespace_pod_never_victim():
    # Same as above but the borrower's pod lacks the over-quota label:
    # nothing is eligible in either regime.
    running = [
        make_pod("b-in", "ns-b", 4, labels=IN),
        make_pod("b-extra", "ns-b", 4),      # no capacity label
    ]
    cs, snap = rig({"qa": ("ns-a", 4), "qb": ("ns-b", 4)}, running)
    victims = select(cs, snap, make_pod("a-new", "ns-a", 4, node=""))
    assert victims is None


def test_reprieve_keeps_highest_priority_victims():
    # Two eligible over-quota pods but only one eviction needed: the
    # higher-priority one is reprieved (reference reprieve loop :635-673).
    running = [
        make_pod("b-in", "ns-b", 4, labels=IN),
        make_pod("v-high", "ns-b", 2, priority=50, labels=OVER),
        make_pod("v-low", "ns-b", 2, priority=10, labels=OVER),
    ]
    cs, snap = rig({"qa": ("ns-a", 4), "qb": ("ns-b", 4)}, running)
    victims = select(cs, snap, make_pod("a-new", "ns-a", 2, node=""))
    assert names(victims) == ["v-low"]


# ---------------------------------------------------------------------------
# preemptor without a quota
# ---------------------------------------------------------------------------

def test_no_quota_preemptor_only_evicts_unquotad_lower_priority():
    running = [
        make_pod("y-pod", "ns-y", 4, priority=0),       # no quota covers ns-y
        make_pod("b-in", "ns-b", 4, labels=IN),          # quota'd: untouchable
    ]
    cs, snap = rig({"qb": ("ns-b", 4)}, running)
    victims = select(cs, snap, make_pod("x-pod", "ns-x", 4, node="", priority=100))
    assert names(victims) == ["y-pod"]


def test_no_quota_preemptor_cannot_evict_higher_priority():
    running = [make_pod("y-pod", "ns-y", 8, priority=200)]
    cs, snap = rig({}, running)
    victims = select(cs, snap, make_pod("x-pod", "ns-x", 4, node="", priority=100))
    assert victims is None


# ---------------------------------------------------------------------------
# post_filter node choice
# ---------------------------------------------------------------------------

def test_post_filter_prefers_node_with_fewest_victims():
    nodes = [make_node("n1", tpu=4), make_node("n2", tpu=4)]
    running = [
        make_pod("v1a", "ns-b", 2, labels=OVER, node="n1"),
        make_pod("v1b", "ns-b", 2, labels=OVER, node="n1"),
        make_pod("v2", "ns-b", 4, labels=OVER, node="n2"),
    ]
    cs, snap = rig({"qa": ("ns-a", 4), "qb": ("ns-b", 4)}, running, nodes=nodes)
    pod = make_pod("a-new", "ns-a", 4, node="")
    state = {}
    cs.pre_filter(state, pod, snap)
    node, status = cs.post_filter(state, pod, snap)
    assert status.success
    assert node == "n2"                       # one victim beats two
    assert names(state["capacity/victims"]) == ["v2"]


def test_post_filter_unschedulable_when_no_candidates():
    running = [make_pod("b-in", "ns-b", 8, labels=IN)]
    cs, snap = rig({"qa": ("ns-a", 4), "qb": ("ns-b", 8)}, running)
    pod = make_pod("a-new", "ns-a", 4, node="")
    state = {}
    cs.pre_filter(state, pod, snap)
    node, status = cs.post_filter(state, pod, snap)
    assert node is None and not status.success


# ---------------------------------------------------------------------------
# gang-aware preemption (VERDICT r1 #3): gangs are all-or-nothing victims
# ---------------------------------------------------------------------------

def gang_pod(name, ns, job, worker, size, tpu=8, node="n1", labels=None):
    p = make_pod(name, ns, tpu, node=node, labels=labels)
    p.metadata.labels.update({
        constants.LABEL_GANG_NAME: job,
        constants.LABEL_GANG_SIZE: str(size),
        constants.LABEL_GANG_WORKER: str(worker),
    })
    return p


def test_over_quota_gang_fully_reclaimed_by_in_quota_pod():
    """An in-quota pod needing ONE host's capacity evicts the WHOLE
    over-quota gang (both hosts), not just the colocated member."""
    running = [
        gang_pod("job-0", "ns-b", "job", 0, 2, node="n1", labels=OVER),
        gang_pod("job-1", "ns-b", "job", 1, 2, node="n2", labels=OVER),
    ]
    cs, snap = rig(
        {"qa": ("ns-a", 8), "qb": ("ns-b", 0)},
        running,
        nodes=[make_node("n1"), make_node("n2")],
    )
    preemptor = make_pod("p", "ns-a", 8, node="")
    state = {}
    cs.pre_filter(state, preemptor, snap)
    node, st = cs.post_filter(state, preemptor, snap)
    assert st.success and node in ("n1", "n2")
    assert names(state["capacity/victims"]) == ["job-0", "job-1"]


def test_straddling_gang_reclaimed_whole_never_half():
    """A gang straddling its quota's min gets MIXED capacity labels from
    the EQ controller (first pods under min are in-quota). Reclaim must
    still take the whole gang — any over-quota member makes the atomic
    unit reclaimable; eviction is never partial."""
    running = [
        gang_pod("job-0", "ns-b", "job", 0, 2, node="n1", labels=IN),
        gang_pod("job-1", "ns-b", "job", 1, 2, node="n2", labels=OVER),
    ]
    cs, snap = rig(
        {"qa": ("ns-a", 8), "qb": ("ns-b", 8)},
        running,
        nodes=[make_node("n1"), make_node("n2")],
    )
    preemptor = make_pod("p", "ns-a", 8, node="")
    state = {}
    cs.pre_filter(state, preemptor, snap)
    node, st = cs.post_filter(state, preemptor, snap)
    assert st.success
    assert names(state["capacity/victims"]) == ["job-0", "job-1"]


def test_fully_in_quota_gang_not_preemptible():
    """A gang entirely within its quota's min (no member over-quota) is
    not a reclaim target at all."""
    running = [
        gang_pod("job-0", "ns-b", "job", 0, 2, node="n1", labels=IN),
        gang_pod("job-1", "ns-b", "job", 1, 2, node="n2", labels=IN),
    ]
    cs, snap = rig(
        {"qa": ("ns-a", 8), "qb": ("ns-b", 16)},
        running,
        nodes=[make_node("n1"), make_node("n2")],
    )
    preemptor = make_pod("p", "ns-a", 8, node="")
    state = {}
    cs.pre_filter(state, preemptor, snap)
    node, st = cs.post_filter(state, preemptor, snap)
    assert not st.success


def test_gang_reprieve_is_all_or_nothing():
    """Reclaiming borrowed capacity must evict the gang WHOLE while the
    smaller solo borrower reprieves — never a lone gang member.

    Numbers: Σmin = 8 (qa 4 + qb 4); ns-b borrows 12 (gang 4+4, solo 4).
    An in-quota ns-a pod (4) forces ns-b down to 4 borrowed-total: only one
    unit may stay. Evicting solo alone frees too little (aggregate still
    over Σmin), so the correct minimal outcome is the whole gang out, solo
    reprieved."""
    running = [
        gang_pod("job-0", "ns-b", "job", 0, 2, tpu=4, node="n1", labels=OVER),
        gang_pod("job-1", "ns-b", "job", 1, 2, tpu=4, node="n2", labels=OVER),
        make_pod("solo", "ns-b", 4, node="n1", labels=OVER),
    ]
    cs, snap = rig(
        {"qa": ("ns-a", 4), "qb": ("ns-b", 4)},
        running,
        nodes=[make_node("n1"), make_node("n2")],
    )
    preemptor = make_pod("p", "ns-a", 4, node="")
    state = {}
    cs.pre_filter(state, preemptor, snap)
    node, st = cs.post_filter(state, preemptor, snap)
    assert st.success and node == "n1"
    assert names(state["capacity/victims"]) == ["job-0", "job-1"]


# ---------------------------------------------------------------------------
# VERDICT r2 next #9: edge cases toward elasticquotainfo_test.go depth
# ---------------------------------------------------------------------------

def ceq_rig(running, node_tpu=16):
    """One CompositeElasticQuota over {ns-a, ns-b} (min 8) + an
    ElasticQuota for ns-c (min 8): the composite's members share one
    usage ledger (one QuotaInfo, two namespaces)."""
    cs = CapacityScheduling()
    cs.quotas = QuotaInfos()
    composite = QuotaInfo(
        name="ceq-ab", namespace="", namespaces={"ns-a", "ns-b"},
        min={TPU: 8}, max=None, calculator=cs.calc,
    )
    cs.quotas.add(composite)
    cs.quotas.add(QuotaInfo(
        name="qc", namespace="ns-c", namespaces={"ns-c"}, min={TPU: 8},
        calculator=cs.calc,
    ))
    snap = fw.Snapshot.build([make_node(tpu=node_tpu)], running, cs.calc)
    for p in running:
        cs.track_pod(p)
    return cs, snap


def test_ceq_members_share_one_usage_ledger():
    cs, _ = ceq_rig([
        make_pod("a-run", "ns-a", 5),
        make_pod("b-run", "ns-b", 3),
    ])
    # both namespaces resolve to the same info with combined used=8
    assert cs.quotas.get("ns-a") is cs.quotas.get("ns-b")
    assert cs.quotas.get("ns-a").used[TPU] == 8


def test_ceq_reclaims_from_overquota_third_namespace():
    # ns-c borrowed the composite's idle min (c uses 12 > min 8); a pod in
    # composite-member ns-b within the CEQ min reclaims from c's
    # over-quota pods.
    cs, snap = ceq_rig([
        make_pod("a-run", "ns-a", 2),
        make_pod("c-in", "ns-c", 8, labels=IN),
        make_pod("c-over", "ns-c", 4, labels=OVER),
    ], node_tpu=14)
    victims = select(cs, snap, make_pod("b-new", "ns-b", 4, node=""))
    assert names(victims) == ["c-over"]


def test_ceq_member_preemption_counts_sibling_namespace_usage():
    # ns-a already consumes the whole composite min; a borrowing pod from
    # ns-b is judged against the SHARED ledger: 8 used + 2 req > min 8 ->
    # borrowing regime, and c is within its share -> no victims.
    cs, snap = ceq_rig([
        make_pod("a-run", "ns-a", 8),
        make_pod("c-in", "ns-c", 8, labels=IN),
    ], node_tpu=16)
    victims = select(cs, snap, make_pod("b-new", "ns-b", 2, node=""))
    assert victims is None


def test_max_unset_quota_in_reprieve_loop():
    # preemptor quota has NO max: the post-removal ceiling recheck must
    # treat max-unset as unbounded, not as zero -- victims still found,
    # and reprieve re-admits the highest-priority victim that fits.
    running = [
        make_pod("a-run", "ns-a", 2),
        make_pod("b-ov-hi", "ns-b", 2, priority=100, labels=OVER),
        make_pod("b-ov-lo", "ns-b", 4, priority=0, labels=OVER),
        make_pod("b-in", "ns-b", 2, labels=IN),
    ]
    cs, snap = rig(
        {"qa": ("ns-a", 8), "qb": ("ns-b", 2)}, running,
        nodes=[make_node(tpu=10)],
    )
    # a stays within min (2+4 <= 8): reclaim regime against b (used 8 > min
    # 2). Removing BOTH over-quota pods frees 6; the request needs 4, so
    # the reprieve loop must re-admit the higher-priority victim (2 chips —
    # node 10 and the aggregated-min ceiling 10 both still hold) and evict
    # only the lower-priority one.
    victims = select(cs, snap, make_pod("a-new", "ns-a", 4, node=""))
    assert names(victims) == ["b-ov-lo"]


def test_guaranteed_overquota_floors_at_chip_granularity():
    # mins 3 and 5, 3 chips of headroom: raw shares 1.125 / 1.875 floor to
    # 1 / 1 -- never round up (a fractional chip cannot be guaranteed).
    qs = QuotaInfos()
    for name, ns, mn in (("qa", "ns-a", 3), ("qb", "ns-b", 5)):
        qs.add(QuotaInfo(name=name, namespace=ns, namespaces={ns},
                         min={TPU: mn}))
    qs.get("ns-a").used[TPU] = 3
    qs.get("ns-b").used[TPU] = 2   # headroom: b has 3
    assert qs.aggregated_overquotas() == {TPU: 3}
    assert qs.guaranteed_overquotas("ns-a") == {TPU: 1.0}   # floor(1.125)
    assert qs.guaranteed_overquotas("ns-b") == {TPU: 1.0}   # floor(1.875)
    # floored shares never exceed the pool
    total = (qs.guaranteed_overquotas("ns-a")[TPU]
             + qs.guaranteed_overquotas("ns-b")[TPU])
    assert total <= qs.aggregated_overquotas()[TPU]


def test_guaranteed_overquota_cpu_floors_at_millicores():
    qs = QuotaInfos()
    for name, ns, mn in (("qa", "ns-a", 1), ("qb", "ns-b", 2)):
        qs.add(QuotaInfo(name=name, namespace=ns, namespaces={ns},
                         min={"cpu": mn}))
    qs.get("ns-b").used["cpu"] = 1.9995   # headroom 0.0005 -> sub-milli
    g = qs.guaranteed_overquotas("ns-a")
    # 1.0005 * 1/3 = 0.3335 -> floored to the millicore: 0.333
    assert g["cpu"] == 0.333


def test_guaranteed_overquota_zero_total_min():
    qs = QuotaInfos()
    qs.add(QuotaInfo(name="qa", namespace="ns-a", namespaces={"ns-a"},
                     min={}))
    assert qs.guaranteed_overquotas("ns-a") == {}


def test_borrow_then_reclaim_across_three_quotas():
    # Three quotas a/b/c (min 4 each). a borrowed 4 beyond its min while b
    # and c were idle. Now b needs its min back: b's within-min pod
    # reclaims from a's over-quota pod. c (still idle) is untouched, and
    # a's within-min pod survives.
    running = [
        make_pod("a-in", "ns-a", 4, labels=IN),
        make_pod("a-over", "ns-a", 4, labels=OVER),
    ]
    cs, snap = rig(
        {"qa": ("ns-a", 4), "qb": ("ns-b", 4), "qc": ("ns-c", 4)}, running,
        nodes=[make_node(tpu=8)],
    )
    victims = select(cs, snap, make_pod("b-new", "ns-b", 4, node=""))
    assert names(victims) == ["a-over"]


def test_reclaim_takes_only_what_it_needs_across_borrowers():
    # a borrowed twice (two over-quota pods); b's reclaim of 2 chips must
    # reprieve one of them (highest priority first), not evict both.
    running = [
        make_pod("a-in", "ns-a", 2, labels=IN),
        make_pod("a-ov1", "ns-a", 2, priority=50, labels=OVER),
        make_pod("a-ov2", "ns-a", 2, priority=10, labels=OVER),
    ]
    cs, snap = rig(
        {"qa": ("ns-a", 2), "qb": ("ns-b", 4)}, running,
        nodes=[make_node(tpu=8)],
    )
    victims = select(cs, snap, make_pod("b-new", "ns-b", 2, node=""))
    assert names(victims) == ["a-ov2"]


# ---------------------------------------------------------------------------
# composite quotas (CEQ) under preemption (VERDICT r3 next #7)
# ---------------------------------------------------------------------------

def composite_rig(running, comp_min=8, other_min=4, tpu=16):
    """One CompositeElasticQuota spanning ns-x + ns-y (a single QuotaInfo
    registered for both namespaces) plus a plain quota for ns-b."""
    cs = CapacityScheduling()
    cs.quotas = QuotaInfos()
    cs.quotas.add(QuotaInfo(
        name="ceq", namespace="", namespaces={"ns-x", "ns-y"},
        min={TPU: comp_min}, max=None, calculator=cs.calc))
    cs.quotas.add(QuotaInfo(
        name="qb", namespace="ns-b", namespaces={"ns-b"},
        min={TPU: other_min}, max=None, calculator=cs.calc))
    snap = fw.Snapshot.build([make_node(tpu=tpu)], running, cs.calc)
    for p in running:
        cs.track_pod(p)
    return cs, snap


def test_composite_used_is_shared_across_member_namespaces():
    """A CEQ's used is the SUM over its namespaces: ns-x asking while
    ns-y already consumed the whole composite min is over-min, so a
    not-over-quota foreign pod is not reclaimable."""
    running = [
        make_pod("y-run", "ns-y", 8),             # fills ceq min via ns-y
        make_pod("b-in", "ns-b", 4, labels=IN),   # b within its own min
    ]
    cs, snap = composite_rig(running)
    # over-min preemptor + victim not over-quota -> nothing eligible
    victims = select(cs, snap, make_pod("x-new", "ns-x", 2, node=""))
    assert victims is None


def test_composite_within_min_reclaims_borrower():
    """ns-x within the composite min (ns-y used little) reclaims another
    quota's over-quota borrower — the CEQ behaves as one pool."""
    running = [
        make_pod("y-run", "ns-y", 2),
        make_pod("b-in", "ns-b", 4, labels=IN),
        make_pod("b-over", "ns-b", 10, labels=OVER),
    ]
    cs, snap = composite_rig(running)
    victims = select(cs, snap, make_pod("x-new", "ns-x", 4, node=""))
    assert names(victims) == ["b-over"]


def test_composite_sibling_namespace_follows_cross_namespace_rules():
    """Reference parity (capacity_scheduling.go:534-549 keys the branch
    on pod namespaces, not quota identity): a victim in the composite's
    OTHER namespace takes the cross-namespace path — it must carry the
    over-quota label to be reclaimable, even though it shares the
    preemptor's QuotaInfo."""
    running = [
        make_pod("y-extra", "ns-y", 8),   # no over-quota label
        make_pod("b-in", "ns-b", 4, labels=IN),
    ]
    cs, snap = composite_rig(running)
    victims = select(cs, snap, make_pod("x-new", "ns-x", 4, node=""))
    assert victims is None                # unlabeled sibling: protected

    running2 = [
        make_pod("y-extra", "ns-y", 10, labels=OVER),
        make_pod("b-in", "ns-b", 4, labels=IN),
    ]
    cs2, snap2 = composite_rig(running2)
    # composite used 10 > min 8 marks the labeled sibling reclaimable by
    # an in-share preemptor of the same composite once the guaranteed
    # share math allows it: ceq used 10 + 2 req > min 8, preemptor share
    # bound = min 8 + guaranteed 0 (no idle quota) -> over share: refused
    victims2 = select(cs2, snap2, make_pod("x-new", "ns-x", 2, node=""))
    assert victims2 is None


# ---------------------------------------------------------------------------
# max-unset quotas through the reprieve loop (VERDICT r3 next #7)
# ---------------------------------------------------------------------------

def test_max_unset_preemptor_survives_reprieve_rechecks():
    """A quota with max=None (unenforced) must sail through the
    used_over_max_with rechecks before and inside the reprieve loop; the
    reprieve decision then rests on fit alone."""
    running = [
        make_pod("b-in", "ns-b", 4, labels=IN),
        make_pod("v1", "ns-b", 2, priority=50, labels=OVER),
        make_pod("v2", "ns-b", 2, priority=10, labels=OVER),
    ]
    cs, snap = rig({"qa": ("ns-a", 4), "qb": ("ns-b", 4)}, running)
    assert cs.quotas.get("ns-a").max is None     # max truly unset
    victims = select(cs, snap, make_pod("a-new", "ns-a", 2, node=""))
    # only one eviction needed; higher-priority v1 reprieved despite the
    # preemptor having no max bound to re-check
    assert names(victims) == ["v2"]


def test_max_set_blocks_during_reprieve_recheck():
    """Contrast case: same shape but the preemptor's max makes the
    request itself over-max — victim selection refuses outright."""
    running = [
        make_pod("a-run", "ns-a", 4),
        make_pod("b-in", "ns-b", 4, labels=IN),
        make_pod("v1", "ns-b", 2, labels=OVER),
    ]
    cs, snap = rig({"qa": ("ns-a", 4), "qb": ("ns-b", 4)}, running,
                   maxes={"qa": 5})
    victims = select(cs, snap, make_pod("a-new", "ns-a", 2, node=""))
    assert victims is None


# ---------------------------------------------------------------------------
# three-quota borrow-then-reclaim chain (VERDICT r3 next #7)
# ---------------------------------------------------------------------------

def test_three_quota_borrow_then_reclaim_chain():
    """a borrowed deep into the shared pool; b then c wake up and each
    reclaims its own min back from a's over-quota pods, one preemption
    at a time — the accounting must stay consistent across the chain."""
    a_pods = [make_pod("a-in", "ns-a", 4, labels=IN)] + [
        make_pod(f"a-ov{i}", "ns-a", 4, labels=OVER) for i in range(2)
    ]
    cs, snap = rig(
        {"qa": ("ns-a", 4), "qb": ("ns-b", 4), "qc": ("ns-c", 4)},
        a_pods, nodes=[make_node(tpu=12)],
    )
    # chain step 1: b (idle, within min) reclaims one of a's borrowers
    b_pod = make_pod("b-new", "ns-b", 4, node="")
    victims_b = select(cs, snap, b_pod)
    assert victims_b is not None and len(victims_b) == 1
    assert names(victims_b)[0].startswith("a-ov")

    # apply the eviction + bind b, then re-run for c on the updated world
    evicted = victims_b[0]
    snap["n1"].remove_pod(evicted)
    cs.untrack_pod(evicted)
    bound_b = make_pod("b-new", "ns-b", 4, labels=IN)
    snap["n1"].add_pod(bound_b)
    cs.track_pod(bound_b)

    # chain step 2: c reclaims the remaining borrower
    victims_c = select(cs, snap, make_pod("c-new", "ns-c", 4, node=""))
    assert victims_c is not None and len(victims_c) == 1
    assert names(victims_c)[0].startswith("a-ov")
    assert names(victims_c) != names(victims_b)

    # chain step 3: with both borrowers gone, a sits at min — a fourth
    # reclaim attempt (ns-b asking beyond capacity) finds nothing
    snap["n1"].remove_pod(victims_c[0])
    cs.untrack_pod(victims_c[0])
    bound_c = make_pod("c-new", "ns-c", 4, labels=IN)
    snap["n1"].add_pod(bound_c)
    cs.track_pod(bound_c)
    assert select(cs, snap, make_pod("b-more", "ns-b", 4, node="")) is None


# ---------------------------------------------------------------------------
# three-quota borrow-then-reclaim CHAINS under CEQ precedence (VERDICT r4
# ask #10): the same cluster stepped through borrow -> reclaim ->
# re-borrow, with a CompositeElasticQuota owning two of the namespaces.
# ---------------------------------------------------------------------------

def three_quota_rig(running, node_tpu=24):
    """CEQ over {ns-a, ns-b} (min 8) + EQ ns-c (min 8) + EQ ns-d (min 8):
    three distinct quota ledgers, one shared 24-chip node."""
    cs = CapacityScheduling()
    cs.quotas = QuotaInfos()
    cs.quotas.add(QuotaInfo(
        name="ceq-ab", namespace="", namespaces={"ns-a", "ns-b"},
        min={TPU: 8}, max=None, calculator=cs.calc))
    for name, ns in (("qc", "ns-c"), ("qd", "ns-d")):
        cs.quotas.add(QuotaInfo(
            name=name, namespace=ns, namespaces={ns}, min={TPU: 8},
            calculator=cs.calc))
    snap = fw.Snapshot.build([make_node(tpu=node_tpu)], running, cs.calc)
    for p in running:
        cs.track_pod(p)
    return cs, snap


def test_chain_borrow_reclaim_reborrow_under_ceq():
    """Step 1: c borrows the CEQ's idle min (c used 16 = 8 in + 8 over).
    Step 2: a CEQ member (ns-a) wants 8 back -> exactly c's over-quota
    pod dies, not its in-quota one. Step 3 (post-eviction state): d now
    tries to borrow — headroom is gone (a's pod spoken for), so there is
    nothing to preempt for d beyond priority, and no victims exist."""
    cs, snap = three_quota_rig([
        make_pod("c-in", "ns-c", 8, labels=IN),
        make_pod("c-over", "ns-c", 8, labels=OVER),
    ], node_tpu=16)
    # step 2: the CEQ reclaims through ns-a
    victims = select(cs, snap, make_pod("a-new", "ns-a", 8, node=""))
    assert names(victims) == ["c-over"]

    # apply the eviction + bind for step 3
    cs.untrack_pod(make_pod("c-over", "ns-c", 8, labels=OVER))
    snap["n1"].remove_pod(make_pod("c-over", "ns-c", 8, labels=OVER))
    bound = make_pod("a-new", "ns-a", 8)
    snap["n1"].add_pod(bound)
    cs.track_pod(bound)

    # step 3: d borrowing now must NOT find victims — everyone is within
    # min (c: 8 <= 8, ceq: 8 <= 8), so there is nothing reclaimable and
    # the 16-chip node is full
    victims = select(cs, snap, make_pod("d-new", "ns-d", 8, node=""))
    assert victims is None


def test_chain_ceq_precedence_sibling_is_not_a_reclaim_target():
    """CEQ precedence: ns-a and ns-b share ONE ledger, so a member
    'borrowing' capacity its sibling left idle is IN-quota usage — a
    reclaim by the sibling must target the third-party borrower (ns-c),
    never the sibling's own pods."""
    cs, snap = three_quota_rig([
        make_pod("b-run", "ns-b", 8, labels=IN),      # fills the CEQ min
        make_pod("c-over", "ns-c", 8, labels=OVER),   # c borrows beyond min
        make_pod("c-in", "ns-c", 8, labels=IN),
    ])
    # ns-a requests 4: the CEQ ledger (used 8 + 4 > min 8) is in the
    # fair-share regime; guaranteed overquota of the CEQ is 0 (no idle
    # min anywhere), so reclaim cannot help a beyond-share preemptor...
    victims = select(cs, snap, make_pod("a-new", "ns-a", 4, node=""))
    # ...but b's pod must NEVER be the victim — same ledger
    assert victims is None or "b-run" not in names(victims)


def test_chain_victim_quota_max_unset_still_reclaimable():
    """A victim namespace whose quota has max UNSET (unbounded borrowing)
    is still reclaimable down to its min when the owner returns: max-
    unset governs admission, not protection."""
    cs, snap = three_quota_rig([
        make_pod("c-over-1", "ns-c", 8, priority=10, labels=OVER),
        make_pod("c-over-2", "ns-c", 4, priority=0, labels=OVER),
        make_pod("c-in", "ns-c", 8, labels=IN),
    ], node_tpu=20)
    assert cs.quotas.get("ns-c").max is None
    # ns-d (within min) reclaims 4: the reprieve loop must spare the
    # higher-priority borrower (8 chips still fit after evicting only
    # the low-priority 4-chip pod) — max-unset on ns-c must not bypass
    # the reprieve or over-evict
    victims = select(cs, snap, make_pod("d-new", "ns-d", 4, node=""))
    assert names(victims) == ["c-over-2"]
