"""Property tests for the fleet scaling policy (ISSUE 8 satellite):
the damping guarantees the autoscaler's stability rests on, all driven
deterministically on a fake clock.

- no flapping under a seeded noisy stationary signal (hysteresis +
  stability windows hold);
- monotone response to sustained load steps;
- cooldowns respected across reconcile intervals;
- scale-down never below min replicas, scale-up never past max.
"""
import random

import pytest

from nos_tpu.fleet.policy import (
    FleetSignals, PolicyConfig, ReplicaStats, ScalingPolicy,
    parse_replica_stats,
)

CFG = PolicyConfig(
    min_replicas=1, max_replicas=8,
    queue_high=4.0, queue_low=0.5,
    goodput_floor=0.90, goodput_ceiling=0.98,
    up_stable_s=15.0, down_stable_s=60.0,
    up_cooldown_s=30.0, down_cooldown_s=120.0,
    max_step_up=2, max_step_down=1,
)


def sig(pending_per_replica=0.0, ready=2, goodput=None, ttft=None,
        oldest=0.0):
    return FleetSignals(
        ready_replicas=ready, total_replicas=ready,
        pending_total=int(pending_per_replica * ready),
        pending_per_replica=pending_per_replica,
        goodput=goodput, ttft_p99_s=ttft, oldest_wait_s=oldest)


def drive(policy, signal_fn, current, t0=0.0, steps=600, dt=1.0):
    """Run one decision per dt; apply desired instantly (the
    best-case actuator). Returns the decision log."""
    log = []
    t = t0
    for _ in range(steps):
        s = signal_fn(t, current)
        d = policy.decide(s, current, t)
        log.append((t, current, d))
        current = d.desired
        t += dt
    return log


# ---------------------------------------------------------------------------
# no flapping
# ---------------------------------------------------------------------------
def test_noisy_stationary_signal_never_flaps():
    """Noise around the middle of the dead band — with occasional
    single-sample spikes past queue_high — must produce ZERO scaling
    events: a spike never sustains the stability window, and in-band
    samples reset the pressure timer."""
    rng = random.Random(20260804)

    def noisy(t, current):
        base = 2.0 + rng.uniform(-1.4, 1.4)
        if rng.random() < 0.08:         # isolated spike past the band
            base = CFG.queue_high + rng.uniform(0.1, 3.0)
        return sig(pending_per_replica=base, ready=current)

    policy = ScalingPolicy(CFG)
    log = drive(policy, noisy, current=3, steps=2000)
    moves = [(t, d) for t, _, d in log if d.direction != "hold"]
    assert moves == [], f"noisy stationary signal moved the fleet: " \
                        f"{moves[:5]}"


def test_in_band_oscillation_is_dead():
    """A signal oscillating anywhere inside [queue_low, queue_high]
    accumulates intent in NEITHER direction."""
    policy = ScalingPolicy(CFG)
    log = drive(
        policy,
        lambda t, c: sig(
            pending_per_replica=CFG.queue_low + 0.01
            + (CFG.queue_high - CFG.queue_low - 0.02)
            * (0.5 + 0.5 * ((int(t) % 7) / 6)),
            ready=c),
        current=4, steps=1200)
    assert all(d.direction == "hold" for _, _, d in log)


# ---------------------------------------------------------------------------
# monotone response to sustained load steps
# ---------------------------------------------------------------------------
@pytest.mark.parametrize("lo,hi", [(5.0, 9.0), (4.5, 20.0), (6.0, 8.0)])
def test_sustained_step_response_is_monotone(lo, hi):
    """A fleet under sustained load ``hi`` is never smaller, at any
    time, than the same fleet under sustained load ``lo``."""
    def fleet_sizes(load):
        policy = ScalingPolicy(CFG)
        return [cur for _, cur, _ in drive(
            policy, lambda t, c: sig(pending_per_replica=load, ready=c),
            current=1, steps=400)]

    small, big = fleet_sizes(lo), fleet_sizes(hi)
    assert all(b >= s for s, b in zip(small, big)), \
        "heavier sustained load produced a smaller fleet"
    assert big[-1] >= small[-1]
    assert small[-1] > 1        # sustained pressure did scale up


def test_sustained_pressure_scales_up_and_brief_pressure_does_not():
    policy = ScalingPolicy(CFG)
    # pressure shorter than up_stable_s: no event
    for t in range(10):
        d = policy.decide(sig(pending_per_replica=9.0, ready=2), 2,
                          float(t))
    assert d.direction == "hold" and d.reason.startswith("stabilizing")
    # back in band: timer resets
    policy.decide(sig(pending_per_replica=2.0, ready=2), 2, 10.0)
    # now sustain past the window: exactly one step fires
    got_up = None
    for t in range(11, 40):
        d = policy.decide(sig(pending_per_replica=9.0, ready=2), 2,
                          float(t))
        if d.direction == "up":
            got_up = (t, d)
            break
    assert got_up is not None
    t_up, d = got_up
    assert t_up - 11 >= CFG.up_stable_s
    assert d.desired == 2 + CFG.max_step_up   # magnitude >1 band excess


# ---------------------------------------------------------------------------
# cooldowns
# ---------------------------------------------------------------------------
def test_up_cooldown_respected_across_reconcile_intervals():
    policy = ScalingPolicy(CFG)
    ups = []
    current = 1

    def heavy(t, c):
        return sig(pending_per_replica=50.0, ready=max(1, c))

    t = 0.0
    for _ in range(1000):
        d = policy.decide(heavy(t, current), current, t)
        if d.direction == "up":
            ups.append(t)
        current = d.desired
        t += 1.0
    assert len(ups) >= 2
    gaps = [b - a for a, b in zip(ups, ups[1:])]
    assert all(g >= CFG.up_cooldown_s for g in gaps), gaps


def test_down_cooldown_and_stability_respected():
    policy = ScalingPolicy(CFG)
    downs = []
    current = 8
    t = 0.0
    for _ in range(3000):
        d = policy.decide(sig(pending_per_replica=0.0, ready=current,
                              goodput=1.0), current, t)
        if d.direction == "down":
            downs.append(t)
        current = d.desired
        t += 1.0
    assert len(downs) >= 2
    assert downs[0] >= CFG.down_stable_s
    gaps = [b - a for a, b in zip(downs, downs[1:])]
    assert all(g >= CFG.down_cooldown_s for g in gaps), gaps
    assert current == CFG.min_replicas      # idles all the way down...


# ---------------------------------------------------------------------------
# bounds
# ---------------------------------------------------------------------------
def test_bounds_hold_under_adversarial_signals():
    rng = random.Random(7)
    policy = ScalingPolicy(CFG)
    current = 3
    t = 0.0
    for _ in range(5000):
        load = rng.choice([0.0, 0.0, 100.0, 100.0, 2.0])
        d = policy.decide(
            sig(pending_per_replica=load, ready=max(1, current),
                goodput=rng.choice([None, 0.5, 1.0])),
            current, t)
        assert CFG.min_replicas <= d.desired <= CFG.max_replicas
        # single-decision step limits
        assert d.desired - current <= CFG.max_step_up
        assert current - d.desired <= max(CFG.max_step_down,
                                          current - CFG.min_replicas)
        current = d.desired
        t += 1.0


def test_below_min_restores_immediately_without_damping():
    policy = ScalingPolicy(CFG)
    d = policy.decide(sig(ready=0), 0, 0.0)
    assert d.direction == "up" and d.desired == CFG.min_replicas
    assert d.reason == "min_replicas"


# ---------------------------------------------------------------------------
# signal plumbing: goodput trigger, restart/drift detection
# ---------------------------------------------------------------------------
def test_goodput_floor_triggers_without_queue():
    policy = ScalingPolicy(CFG)
    up = None
    for t in range(100):
        d = policy.decide(
            sig(pending_per_replica=0.1, ready=2, goodput=0.5), 2,
            float(t))
        if d.direction == "up":
            up = d
            break
    assert up is not None and up.reason == "goodput"


def test_restarted_replicas_excluded_from_slo_aggregates():
    """A replica whose uptime regressed (fresh process) contributes its
    queue but not its empty goodput — collapsed-load misreads are the
    failure mode the uptime echo exists to prevent."""
    fresh = parse_replica_stats("r1", {
        "healthy": True, "uptime_s": 2.0, "active_slots": 0,
        "pending": {"depth": 6, "oldest_wait_s": 1.0},
        "slo": {"goodput": 0.0, "completed": 1},
        "per_request": {"ttft_p99_s": 0.0},
    }, prev_uptime_s=500.0)
    assert fresh.restarted
    old = parse_replica_stats("r2", {
        "healthy": True, "uptime_s": 900.0, "active_slots": 4,
        "pending": {"depth": 2, "oldest_wait_s": 0.2},
        "slo": {"goodput": 1.0, "completed": 50},
        "per_request": {"ttft_p99_s": 0.3},
    }, prev_uptime_s=899.0)
    assert not old.restarted
    s = FleetSignals.aggregate([fresh, old])
    assert s.goodput == 1.0             # fresh ledger not misread
    assert s.pending_total == 8         # but its queue is real work
    assert s.ttft_p99_s == 0.3
    assert s.restarted_replicas == 1


def test_unscraped_and_draining_replicas_read_as_not_ready():
    gone = parse_replica_stats("r1", None)
    assert not gone.ready and not gone.healthy
    draining = parse_replica_stats("r2", {
        "healthy": True, "draining": True, "uptime_s": 5.0,
        "pending": {"depth": 0}, "slo": {}, "per_request": {},
    })
    assert not draining.ready
    s = FleetSignals.aggregate([gone, draining])
    assert s.ready_replicas == 0


def test_policy_config_validation():
    with pytest.raises(ValueError, match="hysteresis"):
        ScalingPolicy(PolicyConfig(queue_low=5.0, queue_high=4.0))
    with pytest.raises(ValueError, match="min_replicas"):
        ScalingPolicy(PolicyConfig(min_replicas=5, max_replicas=2))
    with pytest.raises(ValueError, match="goodput_floor"):
        ScalingPolicy(PolicyConfig(goodput_floor=0.99,
                                   goodput_ceiling=0.9))


def test_all_replicas_unready_with_queued_work_is_pressure():
    """A fleet whose replicas are all recovering/draining while clients
    queue must register pressure (no_ready_replicas), not silence:
    queue depth aggregates over every scraped replica, ready or not."""
    recovering = parse_replica_stats("r1", {
        "healthy": True, "recovering": True, "uptime_s": 5.0,
        "pending": {"depth": 5, "oldest_wait_s": 3.0},
        "slo": {}, "per_request": {},
    })
    assert not recovering.ready
    s = FleetSignals.aggregate([recovering, recovering])
    assert s.ready_replicas == 0 and s.pending_total == 10
    policy = ScalingPolicy(CFG)
    up = None
    for t in range(60):
        d = policy.decide(s, 2, float(t))
        if d.direction == "up":
            up = d
            break
    assert up is not None and up.reason == "no_ready_replicas"


def test_step_limit_zero_disables_direction():
    """max_step_up/max_step_down = 0 means 'never scale that way' (the
    HPA idiom) — not a forced 1-replica step."""
    no_down = ScalingPolicy(PolicyConfig(
        min_replicas=1, max_replicas=8, max_step_down=0,
        down_stable_s=1.0, down_cooldown_s=1.0))
    current = 5
    for t in range(200):
        d = no_down.decide(sig(pending_per_replica=0.0, ready=current,
                               goodput=1.0), current, float(t))
        current = d.desired
    assert current == 5                 # never shrank
    no_up = ScalingPolicy(PolicyConfig(
        min_replicas=1, max_replicas=8, max_step_up=0,
        up_stable_s=1.0, up_cooldown_s=1.0))
    current = 2
    for t in range(200):
        d = no_up.decide(sig(pending_per_replica=50.0, ready=current),
                         current, float(t))
        current = d.desired
    assert current == 2                 # never grew
    with pytest.raises(ValueError, match="max_step"):
        ScalingPolicy(PolicyConfig(max_step_up=-1))


def test_scale_to_zero_fleet_does_not_flap_awake():
    """min_replicas=0: an idle fleet scales to zero and STAYS there —
    emptiness alone is not pressure (a zero-replica fleet has no queue
    to observe; waking it needs traffic an activator would route)."""
    policy = ScalingPolicy(PolicyConfig(
        min_replicas=0, max_replicas=4,
        down_stable_s=2.0, down_cooldown_s=1.0,
        up_stable_s=1.0, up_cooldown_s=1.0))
    current = 1
    woke = []
    for t in range(300):
        ready = current
        d = policy.decide(
            FleetSignals(ready_replicas=ready, total_replicas=current,
                         pending_total=0, pending_per_replica=0.0,
                         goodput=None),
            current, float(t))
        if d.direction == "up":
            woke.append((t, d.reason))
        current = d.desired
    assert current == 0
    assert woke == [], f"scaled-to-zero fleet flapped awake: {woke}"
