"""Weight-only int8 quantization (ops/quant.py, models/quant.py): error
bounds, decode-path integration, memory halving."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from nos_tpu.models import transformer as tfm
from nos_tpu.models.generate import forward_with_cache, generate, init_cache
from nos_tpu.models.quant import quantize_params
from nos_tpu.ops.quant import QuantLinear, qdot, quantize_array


def cfg_kw(**kw):
    base = dict(vocab=64, d_model=32, n_layers=2, n_heads=4, d_ff=64,
                max_seq=32, dtype=jnp.float32)
    base.update(kw)
    return tfm.TransformerConfig(**base)


def test_quantize_roundtrip_error_bounded():
    w = jax.random.normal(jax.random.PRNGKey(0), (64, 32))
    ql = quantize_array(w)
    assert ql.q.dtype == jnp.int8 and ql.scale.shape == (32,)
    err = jnp.abs(ql.q.astype(jnp.float32) * ql.scale - w)
    # rounding error is at most half a quantization step per element
    assert float((err - ql.scale[None, :] / 2).max()) <= 1e-6


def test_quantize_stacked_weights_per_layer_scales():
    w = jax.random.normal(jax.random.PRNGKey(1), (3, 16, 8))
    ql = quantize_array(w)
    assert ql.scale.shape == (3, 8)             # per (layer, out_channel)
    # scanning the leading axis must slice q and scale together
    sliced = jax.tree.map(lambda x: x[1], ql)
    np.testing.assert_allclose(
        np.asarray(qdot(jnp.eye(16), sliced)),
        np.asarray(ql.q[1].astype(jnp.float32) * ql.scale[1]),
        rtol=1e-6)


def test_zero_channel_does_not_nan():
    w = jnp.zeros((8, 4))
    ql = quantize_array(w)
    out = qdot(jnp.ones((2, 8)), ql)
    assert not jnp.isnan(out).any() and float(jnp.abs(out).max()) == 0.0


def test_qdot_passthrough_for_plain_arrays():
    x = jnp.ones((2, 4))
    w = jnp.full((4, 3), 2.0)
    np.testing.assert_allclose(np.asarray(qdot(x, w)),
                               np.asarray(jnp.dot(x, w)))


def test_quantized_decode_close_to_fp():
    cfg = cfg_kw(n_kv_heads=2)
    params = tfm.init_params(jax.random.PRNGKey(0), cfg)
    qparams = quantize_params(params)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 8), 0, cfg.vocab)

    fp, _ = forward_with_cache(params, cfg, tokens, init_cache(cfg, 2))
    q8, _ = forward_with_cache(qparams, cfg, tokens, init_cache(cfg, 2))
    # weight-only int8 keeps logits close; compare direction + magnitude
    a, b = np.asarray(fp).ravel(), np.asarray(q8).ravel()
    cos = float(np.dot(a, b) / (np.linalg.norm(a) * np.linalg.norm(b)))
    assert cos > 0.999
    assert np.abs(a - b).max() < 0.15 * max(1.0, np.abs(a).max())


def test_quantized_generate_runs_and_is_deterministic():
    cfg = cfg_kw()
    params = quantize_params(tfm.init_params(jax.random.PRNGKey(0), cfg))
    prompt = jnp.zeros((2, 3), jnp.int32)
    out1 = jax.jit(lambda p, t: generate(p, cfg, t, 5))(params, prompt)
    out2 = generate(params, cfg, prompt, 5)
    assert out1.shape == (2, 8)
    np.testing.assert_array_equal(np.asarray(out1), np.asarray(out2))


def test_quantization_halves_param_bytes():
    cfg = cfg_kw(d_model=64, d_ff=256, dtype=jnp.bfloat16)
    params = tfm.init_params(jax.random.PRNGKey(0), cfg)
    qparams = quantize_params(params)

    def nbytes(t):
        return sum(x.nbytes for x in jax.tree.leaves(t))

    # bf16 -> int8 on the matmul weights: close to half, plus small scales
    assert nbytes(qparams) < 0.65 * nbytes(params)


def test_moe_experts_stay_unquantized_and_decode_runs():
    cfg = cfg_kw(n_experts=2)
    params = tfm.init_params(jax.random.PRNGKey(0), cfg)
    qparams = quantize_params(params)
    assert isinstance(qparams["layers"]["wq"], QuantLinear)
    assert not isinstance(qparams["layers"]["w_gate"], QuantLinear)
    out = generate(qparams, cfg, jnp.zeros((1, 2), jnp.int32), 3)
    assert out.shape == (1, 5)


def test_embed_quantizes_per_row_not_per_column():
    """A rare-token row 100x smaller than the rest must survive
    quantization — per-row scales, not the matmul per-column convention."""
    from nos_tpu.ops.quant import embed_lookup

    table = jnp.ones((16, 8))
    table = table.at[3].set(0.01)           # tiny "rare token" row
    qt = quantize_params(
        {"layers": {"wq": jnp.ones((2, 4, 4)), "wk": jnp.ones((2, 4, 4)),
                    "wv": jnp.ones((2, 4, 4)), "wo": jnp.ones((2, 4, 4)),
                    "w_gate": jnp.ones((2, 4, 4)),
                    "w_up": jnp.ones((2, 4, 4)),
                    "w_down": jnp.ones((2, 4, 4))},
         "embed": table, "unembed": jnp.ones((8, 16)),
         "final_norm": jnp.ones(8)})["embed"]
    assert qt.scale.shape == (16,)          # per row
    rows = embed_lookup(qt, jnp.array([[3]]))
    np.testing.assert_allclose(np.asarray(rows[0, 0]),
                               np.full(8, 0.01), rtol=0.01)
