"""Batcher window semantics (model: reference pkg/util/batcher_test.go, 290 LoC —
but with an injected clock instead of real sleeps)."""
from nos_tpu.utils.batcher import Batcher


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


def make():
    clock = FakeClock()
    return Batcher(timeout_s=60.0, idle_s=10.0, clock=clock), clock


def test_empty_batcher_not_ready():
    b, _ = make()
    assert not b.ready()
    assert b.drain_if_ready() == []
    assert b.seconds_until_ready() is None


def test_idle_window_makes_batch_ready():
    b, clock = make()
    b.add("a")
    assert not b.ready()
    clock.advance(9.9)
    assert not b.ready()
    clock.advance(0.2)
    assert b.ready()
    assert b.drain_if_ready() == ["a"]
    assert not b.ready()


def test_new_items_reset_idle_window():
    b, clock = make()
    b.add("a")
    clock.advance(8)
    b.add("b")
    clock.advance(8)  # 16s since first add, 8s since last -> not ready
    assert not b.ready()
    clock.advance(3)
    assert b.ready()
    assert b.drain_if_ready() == ["a", "b"]


def test_timeout_window_caps_busy_batch():
    b, clock = make()
    # keep adding every 5s so idle never fires; timeout at 60s must.
    for i in range(13):
        b.add(i)
        clock.advance(5)
    # t=65 > 60s after first add
    assert b.ready()
    assert len(b.drain_if_ready()) == 13


def test_timeout_window_restarts_after_drain():
    b, clock = make()
    b.add("a")
    clock.advance(61)
    assert b.drain_if_ready() == ["a"]
    b.add("b")
    assert not b.ready()
    clock.advance(11)
    assert b.drain_if_ready() == ["b"]


def test_seconds_until_ready():
    b, clock = make()
    b.add("a")
    assert abs(b.seconds_until_ready() - 10.0) < 1e-9
    clock.advance(4)
    assert abs(b.seconds_until_ready() - 6.0) < 1e-9


def test_invalid_windows_rejected():
    import pytest

    with pytest.raises(ValueError):
        Batcher(timeout_s=0, idle_s=1)
    with pytest.raises(ValueError):
        Batcher(timeout_s=1, idle_s=0)
