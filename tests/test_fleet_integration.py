"""Fleet <-> ElasticQuota integration (ISSUE 8 acceptance): the fleet
borrows only available slack and sheds borrowed replicas when a
guaranteed namespace reclaims — pinned end-to-end against the REAL
control plane: in-process API server, the nos scheduler (quota
admission + preemption), the quota reconciler (used accounting +
in-quota/over-quota labeling) and the fleet controller, with the
deterministic sim data plane feeding /stats signals. Everything runs
on one fake clock.
"""
import pytest

from nos_tpu import constants
from nos_tpu.api.quota import make_elastic_quota
from nos_tpu.fleet import FleetConfig, FleetController, PolicyConfig
from nos_tpu.fleet.sim import SimFleet, SimKubelet
from nos_tpu.kube import ApiServer, Manager
from nos_tpu.kube.client import Client
from nos_tpu.kube.objects import (
    Container, Node, NodeStatus, ObjectMeta, Pod, PodCondition, PodSpec,
    PodStatus,
)
from nos_tpu.quota.controller import ElasticQuotaReconciler
from nos_tpu.scheduler import Scheduler

CHIPS = 4.0
TPU = constants.RESOURCE_TPU


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


@pytest.fixture
def rig():
    server = ApiServer()
    clock = FakeClock()
    mgr = Manager(server, clock=clock)
    mgr.add_controller(ElasticQuotaReconciler().controller())
    mgr.add_controller(Scheduler().controller())
    client = Client(server)
    for i in range(2):
        server.create(Node(
            metadata=ObjectMeta(name=f"host-{i}"),
            status=NodeStatus(capacity={TPU: 8, "cpu": 32},
                              allocatable={TPU: 8, "cpu": 32})))
    # serve is guaranteed 4 chips, batch 12: Σmin == cluster capacity,
    # so everything serve runs beyond one replica is BORROWED slack
    server.create(make_elastic_quota("serve-q", "serve",
                                     min={TPU: 4.0}, max={TPU: 16.0}))
    server.create(make_elastic_quota("batch-q", "batch",
                                     min={TPU: 12.0}))
    fleet = SimFleet(clock, slo_ttft_s=10.0, max_batch=8,
                     tokens_per_s=50.0)
    ctl = FleetController(
        FleetConfig(name="web", namespace="serve",
                    chips_per_replica=CHIPS,
                    policy=PolicyConfig(
                        min_replicas=1, max_replicas=6,
                        queue_high=4.0, queue_low=0.5,
                        up_stable_s=2.0, down_stable_s=2.0,
                        up_cooldown_s=3.0, down_cooldown_s=1.0,
                        max_step_up=2, max_step_down=2),
                    reconcile_interval_s=1.0, drain_timeout_s=8.0),
        stats_source=fleet.stats_source, clock=clock)
    mgr.add_controller(ctl.controller())
    kubelet = SimKubelet(fleet, clock, fleet_label="web",
                         namespace="serve", startup_s=2.0)
    return server, mgr, clock, client, fleet, kubelet, ctl


def pump(rig_tuple, seconds, rps=0.0, dt=1.0):
    server, mgr, clock, client, fleet, kubelet, ctl = rig_tuple
    t = 0.0
    carry = 0.0
    while t < seconds:
        carry += rps * dt
        while carry >= 1.0:
            carry -= 1.0
            fleet.submit(tokens=40)
        mgr.run_until_idle()
        kubelet.sync(client)
        mgr.run_until_idle()
        fleet.tick(dt)
        clock.advance(dt)
        t += dt
    mgr.run_until_idle()


def serve_pods(server):
    return sorted(
        (p for p in server.list("Pod", namespace="serve")
         if p.metadata.labels.get(constants.LABEL_FLEET) == "web"),
        key=lambda p: p.metadata.name)


def batch_pod(name):
    return Pod(
        metadata=ObjectMeta(name=name, namespace="batch"),
        spec=PodSpec(
            containers=[Container(requests={TPU: CHIPS})],
            scheduler_name=constants.SCHEDULER_NAME),
        status=PodStatus(
            phase="Pending",
            conditions=[PodCondition(type="PodScheduled", status="False",
                                     reason="Unschedulable")]))


def test_fleet_borrows_slack_then_sheds_on_guaranteed_reclaim(rig):
    server, mgr, clock, client, fleet, kubelet, ctl = rig

    # -- phase 1: batch idle, heavy traffic -> the fleet borrows -------
    pump(rig, 40, rps=30.0)
    pods = serve_pods(server)
    running = [p for p in pods if p.status.phase == "Running"]
    assert len(running) == 4, \
        f"fleet should grow to the full 16-chip pool (4 own + 12 " \
        f"borrowed), got {len(running)}"
    # quota admission held: never past Σmin == 16 chips even though
    # max_replicas is 6 — the clamp, not the scheduler queue, stopped it
    assert len(pods) == 4
    assert ctl.stats()["quota"]["slack_chips"] == 0.0
    # the quota reconciler accounted and labeled the borrow
    eq = server.get("ElasticQuota", "serve-q", "serve")
    assert eq.status.used == {TPU: 16.0}
    labels = sorted(p.metadata.labels.get(constants.LABEL_CAPACITY)
                    for p in serve_pods(server))
    assert labels.count(constants.CAPACITY_OVER_QUOTA) == 3
    assert labels.count(constants.CAPACITY_IN_QUOTA) == 1

    # -- phase 2: the guaranteed namespace reclaims its min ------------
    for i in range(3):
        server.create(batch_pod(f"train-{i}"))
    submitted_before = fleet.submitted
    pump(rig, 60, rps=30.0)
    # batch got its guaranteed chips back (scheduler preemption of
    # over-quota pods and/or the controller's graceful shed — both
    # converge here)
    batch = {p.metadata.name: p
             for p in server.list("Pod", namespace="batch")}
    bound = [n for n, p in batch.items() if p.spec.node_name]
    assert len(bound) == 3, f"guaranteed pods still parked: {batch}"
    # the fleet backed off to what its own min affords and did NOT
    # recreate borrowed replicas while the guaranteed namespace is full
    pods = serve_pods(server)
    assert len(pods) == 1, [p.metadata.name for p in pods]
    assert fleet.submitted > submitted_before
    # lossless: every request displaced off a shed replica was requeued
    # — conservation holds at fleet level throughout
    assert fleet.requeued > 0
    assert fleet.conservation_ok()

    # -- phase 3: batch releases -> the fleet may borrow again ---------
    for i in range(3):
        server.delete("Pod", f"train-{i}", "batch")
    pump(rig, 30, rps=30.0)
    assert len(serve_pods(server)) > 1
    assert fleet.conservation_ok()


def test_fleet_reborrows_after_harvester_releases_at_trough_end():
    """ISSUE 12 satellite — the re-borrow round-trip the PR 8 reclaim
    path left untested, now with the harvest plane as the borrower:
    in a trough the harvester borrows the serving namespace's unused
    min for a training gang; when the pressure episode returns the
    fleet creates replicas against its guaranteed min (the clamp must
    NOT strand it at zero slack), the scheduler's reclaim notice fires,
    the harvester checkpoint-then-gang-evicts, and the serving fleet
    actually grows into the released chips; at the next trough the
    harvester borrows them back and training resumes from its durable
    lineage."""
    from nos_tpu.harvest import HarvestConfig, HarvestController
    from nos_tpu.harvest.sim import SimHarvestKubelet, SimTrainer
    from tests.test_harvest import slice_host

    server = ApiServer()
    clock = FakeClock()
    mgr = Manager(server, clock=clock)
    mgr.add_controller(ElasticQuotaReconciler().controller())
    mgr.add_controller(Scheduler(reclaim_grace_s=30.0,
                                 clock=clock).controller())
    client = Client(server)
    for pool in ("a", "b"):
        for w in range(2):
            server.create(slice_host(f"pool-{pool}-w{w}",
                                     f"pool-{pool}"))
    # serve owns the whole 32-chip pool's guarantee; batch scavenges
    server.create(make_elastic_quota("serve-q", "serve",
                                     min={TPU: 32.0}))
    server.create(make_elastic_quota("batch-q", "batch",
                                     min={TPU: 0.0}))
    fleet = SimFleet(clock, slo_ttft_s=10.0, max_batch=8,
                     tokens_per_s=50.0)
    ctl = FleetController(
        FleetConfig(name="web", namespace="serve",
                    chips_per_replica=CHIPS,
                    policy=PolicyConfig(
                        min_replicas=1, max_replicas=6,
                        queue_high=4.0, queue_low=0.5,
                        up_stable_s=2.0, down_stable_s=8.0,
                        up_cooldown_s=3.0, down_cooldown_s=4.0,
                        max_step_up=3, max_step_down=2),
                    reconcile_interval_s=1.0, drain_timeout_s=8.0),
        stats_source=fleet.stats_source, clock=clock)
    mgr.add_controller(ctl.controller())
    kubelet = SimKubelet(fleet, clock, fleet_label="web",
                         namespace="serve", startup_s=2.0)
    trainer = SimTrainer(clock, step_rate=1.0, ckpt_interval_s=20.0,
                         ckpt_duration_s=2.0)
    hctl = HarvestController(
        HarvestConfig(name="hv", namespace="batch", gang_size=2,
                      chips_per_worker=8.0, topology="4x4",
                      max_gangs=1, checkpoint_budget_s=10.0,
                      checkpoint_interval_s=20.0, launch_stable_s=4.0,
                      reconcile_interval_s=1.0),
        trainer=trainer, clock=clock)
    mgr.add_controller(hctl.controller())
    hkubelet = SimHarvestKubelet(trainer, clock, "hv", "batch",
                                 startup_s=2.0)

    def pump(seconds, rps=0.0):
        t = 0.0
        carry = 0.0
        while t < seconds:
            carry += rps
            while carry >= 1.0:
                carry -= 1.0
                fleet.submit(tokens=40)
            mgr.run_until_idle()
            kubelet.sync(client)
            hkubelet.sync(client)
            mgr.run_until_idle()
            fleet.tick(1.0)
            trainer.tick(1.0)
            clock.advance(1.0)
            t += 1.0
        mgr.run_until_idle()

    def gang_pods():
        return [p for p in server.list("Pod", namespace="batch")
                if p.status.phase in ("Pending", "Running")]

    # -- trough: the harvester borrows the serve namespace's unused min
    pump(40, rps=1.0)
    gang = gang_pods()
    assert len(gang) == 2 and all(
        p.status.phase == "Running" for p in gang), \
        [(p.metadata.name, p.status.phase) for p in gang]
    steps_banked = trainer.useful_steps()
    assert steps_banked > 0

    # -- pressure episode: the fleet must grow THROUGH the borrow ------
    pump(50, rps=25.0)
    running = [p for p in serve_pods(server)
               if p.status.phase == "Running"]
    assert len(running) >= 4, \
        "the fleet never re-borrowed the chips the harvester held: " \
        f"{[p.metadata.name for p in serve_pods(server)]}"
    # the gang went through the graceful reclaim and is parked
    ledger = hctl.ledger()
    assert len(ledger) >= 1
    assert all(e["outcome"] in ("graceful", "forced") for e in ledger)
    gang = gang_pods()
    assert all(not p.spec.node_name for p in gang)
    assert all(p.metadata.annotations.get(
        constants.ANNOTATION_SCHEDULING_HOLD) for p in gang)
    # lossless on the serving side throughout
    assert fleet.conservation_ok()

    # -- trough returns: the harvester borrows back, lineage survives --
    # (long enough for the crowd's breached completions to age out of
    # the goodput window — the policy rightly refuses to shrink a fleet
    # whose recent goodput is poor)
    pump(140, rps=0.5)
    gang = gang_pods()
    assert all(p.status.phase == "Running" for p in gang), \
        [(p.metadata.name, p.status.phase) for p in gang]
    assert trainer.useful_steps() >= steps_banked
    st = trainer._gangs["hv-g0"]
    assert st.admitted and not st.fenced
    assert fleet.conservation_ok()
    mgr.stop()


def test_routed_mode_prefix_affinity_through_the_full_control_plane():
    """Routed-mode integration (ISSUE 11 satellite): the sim fleet runs
    the gateway's prefix-affinity ring under the REAL controller/
    scheduler/quota loop — shared prompts keep landing on their home
    replica across scale-up churn, the door queue feeds the
    controller's gateway_source, and the trace stays lossless."""
    server = ApiServer()
    clock = FakeClock()
    mgr = Manager(server, clock=clock)
    mgr.add_controller(ElasticQuotaReconciler().controller())
    mgr.add_controller(Scheduler().controller())
    client = Client(server)
    for i in range(2):
        server.create(Node(
            metadata=ObjectMeta(name=f"host-{i}"),
            status=NodeStatus(capacity={TPU: 8, "cpu": 32},
                              allocatable={TPU: 8, "cpu": 32})))
    server.create(make_elastic_quota("serve-q", "serve",
                                     min={TPU: 16.0}))
    fleet = SimFleet(clock, slo_ttft_s=10.0, max_batch=8,
                     tokens_per_s=50.0, prefill_s=1.0,
                     router="prefix_affinity", block_size=16,
                     affinity_blocks=2, prefix_chains=8,
                     max_imbalance=8.0)
    ctl = FleetController(
        FleetConfig(name="web", namespace="serve",
                    chips_per_replica=CHIPS,
                    policy=PolicyConfig(
                        min_replicas=1, max_replicas=4,
                        queue_high=4.0, queue_low=0.5,
                        up_stable_s=2.0, down_stable_s=10.0,
                        up_cooldown_s=3.0, down_cooldown_s=10.0),
                    reconcile_interval_s=1.0, drain_timeout_s=8.0),
        stats_source=fleet.stats_source,
        gateway_source=fleet.gateway_stats, clock=clock)
    mgr.add_controller(ctl.controller())
    kubelet = SimKubelet(fleet, clock, fleet_label="web",
                         namespace="serve", startup_s=2.0)
    rig_tuple = (server, mgr, clock, client, fleet, kubelet, ctl)

    sys_prompts = [[400 + 37 * p + j for j in range(32)]
                   for p in range(6)]
    import random
    rng = random.Random(5)
    t = 0.0
    carry = 0.0
    while t < 60:
        carry += 20.0
        while carry >= 1.0:
            carry -= 1.0
            fleet.submit(tokens=30,
                         prompt=sys_prompts[rng.randrange(6)])
        mgr.run_until_idle()
        kubelet.sync(client)
        mgr.run_until_idle()
        fleet.tick(1.0)
        clock.advance(1.0)
        t += 1.0
    # the controller grew the fleet under load through real admission
    # (sampled BEFORE the drain-out idles it back down)
    running_peak = [p for p in serve_pods(server)
                    if p.status.phase == "Running"]
    assert len(running_peak) >= 2
    pump(rig_tuple, 60, rps=0.0)        # drain out
    rep = fleet.report()
    assert rep["conservation_ok"]
    assert rep["completed"] == rep["submitted"] > 0
    assert rep["router"] == "prefix_affinity"
    # affinity routing actually decided (not just fallback), and the
    # shared prompts hit replica-resident chains across the scale-up
    assert rep["routes"].get("affinity", 0) > 0
    assert rep["prefix"]["hits"] > 0
    assert rep["prefix"]["hit_rate"] > 0.5
    # the controller's /stats surfaced the door-queue signal wire
    assert "gateway_queued" in ctl.stats()["signals"]
    mgr.stop()
