"""Scheduler filter breadth (VERDICT r1 #8): taints/tolerations, node
affinity, cordoned nodes, and nominated-pod-aware feasibility.

Reference analog: the gpupartitioner wires the FULL k8s plugin suite into
its simulation framework (cmd/gpupartitioner/gpupartitioner.go:294-318),
and preemption re-runs filters with nominated pods
(capacity_scheduling.go:610-673). GKE TPU node pools carry the
google.com/tpu=present:NoSchedule taint, so taint handling is load-bearing
for correct placement on real clusters.
"""
from nos_tpu import constants
from nos_tpu.kube import ApiServer, Manager
from nos_tpu.kube.objects import (
    Affinity,
    Container,
    Node,
    NodeSelectorRequirement,
    NodeSelectorTerm,
    NodeSpec,
    NodeStatus,
    ObjectMeta,
    Pod,
    PodCondition,
    PodSpec,
    PodStatus,
    Taint,
    Toleration,
)
from nos_tpu.kube import serial
from nos_tpu.scheduler import Scheduler
from nos_tpu.scheduler import framework as fw

TPU = "google.com/tpu"
TPU_TAINT = Taint(key=TPU, value="present", effect="NoSchedule")


def tpu_node(name="n1", taints=None, labels=None, unschedulable=False, tpu=8):
    return Node(
        metadata=ObjectMeta(name=name, labels=dict(labels or {})),
        spec=NodeSpec(taints=list(taints or []), unschedulable=unschedulable),
        status=NodeStatus(capacity={TPU: tpu, "cpu": 96},
                          allocatable={TPU: tpu, "cpu": 96}),
    )


def pod(name="p", ns="team-a", tpu=8, tolerations=None, affinity=None,
        priority=0):
    return Pod(
        metadata=ObjectMeta(name=name, namespace=ns),
        spec=PodSpec(
            containers=[Container(requests={TPU: tpu})],
            scheduler_name=constants.SCHEDULER_NAME,
            tolerations=list(tolerations or []),
            affinity=affinity,
            priority=priority,
        ),
        status=PodStatus(phase="Pending", conditions=[PodCondition(
            type="PodScheduled", status="False", reason="Unschedulable")]),
    )


def rig():
    server = ApiServer()
    mgr = Manager(server)
    mgr.add_controller(Scheduler().controller())
    return server, mgr


# ---------------------------------------------------------------------------
# taints / tolerations
# ---------------------------------------------------------------------------

def test_untolerated_taint_blocks_placement():
    server, mgr = rig()
    server.create(tpu_node(taints=[TPU_TAINT]))
    server.create(pod())
    mgr.run_until_idle()
    p = server.get("Pod", "p", "team-a")
    assert p.spec.node_name == ""
    assert any("untolerated taint" in c.message for c in p.status.conditions)


def test_tolerating_pod_lands_on_tainted_tpu_pool():
    server, mgr = rig()
    server.create(tpu_node(taints=[TPU_TAINT]))
    server.create(pod(tolerations=[
        Toleration(key=TPU, operator="Equal", value="present",
                   effect="NoSchedule")]))
    mgr.run_until_idle()
    assert server.get("Pod", "p", "team-a").spec.node_name == "n1"


def test_exists_toleration_and_prefer_no_schedule():
    # Exists toleration matches any value; PreferNoSchedule never filters
    server, mgr = rig()
    server.create(tpu_node(
        taints=[TPU_TAINT, Taint(key="x", value="y", effect="PreferNoSchedule")]))
    server.create(pod(tolerations=[Toleration(key=TPU, operator="Exists")]))
    mgr.run_until_idle()
    assert server.get("Pod", "p", "team-a").spec.node_name == "n1"


def test_cordoned_node_rejected():
    server, mgr = rig()
    server.create(tpu_node(unschedulable=True))
    server.create(pod())
    mgr.run_until_idle()
    p = server.get("Pod", "p", "team-a")
    assert p.spec.node_name == ""
    assert any("unschedulable" in c.message for c in p.status.conditions)


# ---------------------------------------------------------------------------
# node affinity
# ---------------------------------------------------------------------------

def test_required_node_affinity_in_operator():
    server, mgr = rig()
    server.create(tpu_node("v5e", labels={
        constants.LABEL_TPU_ACCELERATOR: "tpu-v5-lite-podslice"}))
    server.create(tpu_node("v5p", labels={
        constants.LABEL_TPU_ACCELERATOR: "tpu-v5p-slice"}, tpu=4))
    server.create(pod(affinity=Affinity(node_affinity_required=[
        NodeSelectorTerm(match_expressions=[NodeSelectorRequirement(
            key=constants.LABEL_TPU_ACCELERATOR, operator="In",
            values=["tpu-v5p-slice"])])]), tpu=4))
    mgr.run_until_idle()
    assert server.get("Pod", "p", "team-a").spec.node_name == "v5p"


def test_affinity_or_of_terms_and_not_in():
    labels_a = {"zone": "a"}
    info_a = fw.NodeInfo(tpu_node("na", labels=labels_a))
    info_b = fw.NodeInfo(tpu_node("nb", labels={"zone": "b"}))
    aff = Affinity(node_affinity_required=[
        NodeSelectorTerm(match_expressions=[NodeSelectorRequirement(
            key="zone", operator="NotIn", values=["a"])]),
        NodeSelectorTerm(match_expressions=[NodeSelectorRequirement(
            key="special", operator="Exists")]),
    ])
    p = pod(affinity=aff)
    f = fw.NodeAffinityFit()
    assert not f.filter({}, p, info_a).success       # zone=a, no 'special'
    assert f.filter({}, p, info_b).success           # zone=b matches NotIn
    info_a.node.metadata.labels["special"] = "1"
    assert f.filter({}, p, info_a).success           # second term matches


def test_affinity_gt_lt_operators():
    info = fw.NodeInfo(tpu_node("n", labels={"chips": "8"}))
    f = fw.NodeAffinityFit()
    gt = Affinity(node_affinity_required=[NodeSelectorTerm(match_expressions=[
        NodeSelectorRequirement(key="chips", operator="Gt", values=["4"])])])
    lt = Affinity(node_affinity_required=[NodeSelectorTerm(match_expressions=[
        NodeSelectorRequirement(key="chips", operator="Lt", values=["4"])])])
    assert f.filter({}, pod(affinity=gt), info).success
    assert not f.filter({}, pod(affinity=lt), info).success


# ---------------------------------------------------------------------------
# nominated pods
# ---------------------------------------------------------------------------

def test_nominated_pod_capacity_is_protected():
    """A pod nominated to a node after preemption holds its capacity
    against lower-priority pods arriving before it binds."""
    snap = fw.Snapshot.build([tpu_node("n1")], [])
    claimant = pod("claimant", priority=100)
    claimant.status.nominated_node_name = "n1"
    snap.add_nominated(claimant)

    framework = fw.SchedulerFramework()
    low = pod("low", priority=0)
    name, st = framework.find_feasible({}, low, snap)
    assert not st.success  # nominated high-priority pod consumes the chips

    high = pod("high", priority=200)
    name, st = framework.find_feasible({}, high, snap)
    assert st.success and name == "n1"  # higher priority ignores nomination


def test_sweep_does_not_give_preempted_capacity_away():
    """End-to-end: preemption nominates the claimant; a lower-priority
    pending pod later in the same sweep must not steal the freed node."""
    from nos_tpu.api.quota import make_elastic_quota
    server, mgr = rig()
    server.create(tpu_node("n1"))
    server.create(make_elastic_quota("qa", "team-a", min={TPU: 8}))
    server.create(make_elastic_quota("qb", "team-b", min={TPU: 0}))
    # team-b over-quota pod occupies the node
    victim = pod("victim", ns="team-b")
    victim.metadata.labels[constants.LABEL_CAPACITY] = constants.CAPACITY_OVER_QUOTA
    victim.spec.node_name = "n1"
    victim.status.phase = "Running"
    server.create(victim)
    # high-priority in-quota claimant + low-priority freeloader (same ns)
    server.create(pod("claimant", priority=100))
    server.create(pod("freeloader", priority=0))
    mgr.run_until_idle()
    claimant = server.get("Pod", "claimant", "team-a")
    freeloader = server.get("Pod", "freeloader", "team-a")
    # claimant either already bound (later sweep) or nominated; the
    # freeloader must NOT hold the node
    assert freeloader.spec.node_name == ""
    assert claimant.spec.node_name == "n1" or (
        claimant.status.nominated_node_name == "n1"
    )


# ---------------------------------------------------------------------------
# wire round-trip of the new fields
# ---------------------------------------------------------------------------

def test_taint_toleration_affinity_wire_roundtrip():
    n = tpu_node(taints=[TPU_TAINT], unschedulable=True)
    n2 = serial.from_wire(serial.to_wire(n))
    assert n2.spec.taints == [TPU_TAINT]
    assert n2.spec.unschedulable is True

    p = pod(tolerations=[Toleration(key=TPU, operator="Exists")],
            affinity=Affinity(node_affinity_required=[
                NodeSelectorTerm(match_expressions=[NodeSelectorRequirement(
                    key="zone", operator="In", values=["a", "b"])])]))
    p2 = serial.from_wire(serial.to_wire(p))
    assert p2.spec.tolerations == p.spec.tolerations
    assert p2.spec.affinity == p.spec.affinity


def test_feasible_node_cap_binds_and_rotates_on_large_clusters():
    """kube percentageOfNodesToScore analog: >MIN_FEASIBLE_TO_FIND feasible
    nodes -> the sweep stops at the cap and the scan start rotates across
    calls (nextStartNodeIndex), so successive sweeps sample different
    windows instead of always the same sorted prefix."""
    framework = fw.SchedulerFramework()
    n_nodes = framework.MIN_FEASIBLE_TO_FIND + 50
    nodes = [tpu_node(f"cap-n{i:03d}") for i in range(n_nodes)]
    snap = fw.Snapshot.build(nodes, [])
    pod_ = pod("cap-p", tpu=8)

    name, st = framework.find_feasible({}, pod_, snap)
    assert st.success and name == "cap-n000"
    # the sweep stopped at the cap, not the cluster size
    assert framework._next_start_node == framework.MIN_FEASIBLE_TO_FIND

    # second sweep starts where the first stopped and wraps
    name2, st2 = framework.find_feasible({}, pod_, snap)
    assert st2.success
    assert framework._next_start_node == (
        2 * framework.MIN_FEASIBLE_TO_FIND) % n_nodes

    # small clusters stay exhaustive: every node is scanned, the scan
    # cursor wraps to where it started, and the best name wins as before
    small = fw.Snapshot.build([tpu_node("s2"), tpu_node("s1")], [])
    fw2 = fw.SchedulerFramework()
    name3, _ = fw2.find_feasible({}, pod_, small)
    assert name3 == "s1" and fw2._next_start_node == 0


# ---------------------------------------------------------------------------
# stock-plugin gap closure (ISSUE 2 satellite; VERDICT §missing-3):
# NodePorts filter + NodeResourcesBalancedAllocation scoring
# ---------------------------------------------------------------------------

def test_node_ports_filter_rejects_conflicting_host_port():
    from nos_tpu.kube.objects import ContainerPort

    framework = fw.SchedulerFramework()
    holder = pod("holder", tpu=0)
    holder.spec.containers[0].requests = {"cpu": 1}
    holder.spec.containers[0].ports = [
        ContainerPort(container_port=8080, host_port=8080)]
    node = tpu_node("ports-n1", taints=[])
    snapshot = fw.Snapshot.build([node], [])
    snapshot["ports-n1"].add_pod(holder)

    claimer = pod("claimer", tpu=0)
    claimer.spec.containers[0].requests = {"cpu": 1}
    claimer.spec.containers[0].ports = [
        ContainerPort(container_port=9999, host_port=8080)]
    state = {}
    framework.run_pre_filter(state, claimer, snapshot)
    st = framework.run_filter(state, claimer, snapshot["ports-n1"])
    assert not st.success and "host port" in st.reason

    # a different port (or protocol) is fine
    ok = pod("ok", tpu=0)
    ok.spec.containers[0].requests = {"cpu": 1}
    ok.spec.containers[0].ports = [
        ContainerPort(container_port=9999, host_port=8081)]
    state = {}
    framework.run_pre_filter(state, ok, snapshot)
    assert framework.run_filter(state, ok, snapshot["ports-n1"]).success
    udp = pod("udp", tpu=0)
    udp.spec.containers[0].requests = {"cpu": 1}
    udp.spec.containers[0].ports = [
        ContainerPort(container_port=53, host_port=8080, protocol="UDP")]
    state = {}
    framework.run_pre_filter(state, udp, snapshot)
    assert framework.run_filter(state, udp, snapshot["ports-n1"]).success


def test_node_ports_inert_without_host_ports():
    """The filter must cost nothing for the overwhelmingly common
    no-hostPort pod: active_filters drops it from the sweep."""
    framework = fw.SchedulerFramework()
    p = pod("plain")
    snapshot = fw.Snapshot.build([tpu_node()], [])
    state = {}
    framework.run_pre_filter(state, p, snapshot)
    names = [f.name for f in framework.active_filters(state, p)]
    assert "NodePorts" not in names


def test_balanced_allocation_prefers_evenly_used_node():
    """Two nodes fit; the one whose cpu/tpu fractions end up balanced
    wins (kube NodeResourcesBalancedAllocation semantics)."""
    server = ApiServer()
    mgr = Manager(server)
    mgr.add_controller(Scheduler().controller())
    # lopsided: cpu nearly exhausted, tpu empty -> placing the mixed pod
    # leaves fractions (1.0, 0.5): stddev 0.25
    lopsided = tpu_node("bal-a", taints=[], tpu=16)
    lopsided.status.allocatable = {TPU: 16, "cpu": 8}
    lopsided.status.capacity = {TPU: 16, "cpu": 8}
    # balanced: placing the pod leaves fractions (0.5, 0.5): stddev 0
    even = tpu_node("bal-b", taints=[], tpu=16)
    even.status.allocatable = {TPU: 16, "cpu": 16}
    even.status.capacity = {TPU: 16, "cpu": 16}
    server.create(lopsided)
    server.create(even)
    filler = pod("filler", tpu=0)
    filler.spec.containers[0].requests = {"cpu": 4}
    filler.spec.node_name = "bal-a"
    filler.status.phase = "Running"
    server.create(filler)

    mixed = pod("mixed", tpu=8)
    mixed.spec.containers[0].requests = {TPU: 8, "cpu": 4}
    server.create(mixed)
    mgr.run_until_idle()
    # name order alone would pick bal-a; balance flips it
    assert server.get("Pod", "mixed", "team-a").spec.node_name == "bal-b"


def test_balanced_allocation_uniform_for_single_resource():
    """One requested resource -> stddev 0 everywhere -> the plugin is
    score-inert and cannot perturb existing orderings."""
    framework = fw.SchedulerFramework()
    plugin = next(p for p in framework.plugins
                  if p.name == "NodeResourcesBalancedAllocation")
    p = pod("single", tpu=8)
    state = {}
    snapshot = fw.Snapshot.build([tpu_node()], [])
    framework.run_pre_filter(state, p, snapshot)
    assert plugin.score_inert(state, p)
