"""TpuNode + ResourceCalculator (model: reference pkg/gpu/mig/node_test.go,
pkg/gpu/util resource tests)."""
import pytest

from nos_tpu import constants
from nos_tpu.kube.objects import Container, Node, ObjectMeta, Pod, PodSpec
from nos_tpu.tpu.node import NotATpuNode, TpuNode
from nos_tpu.tpu.resource_calc import ResourceCalculator
from nos_tpu.tpu.slice import Profile

P11, P22, P24 = Profile(1, 1), Profile(2, 2), Profile(2, 4)


def make_tpu_node(name="n1", gen="tpu-v5-lite-podslice", topo="2x4", annotations=None):
    return Node(
        metadata=ObjectMeta(
            name=name,
            labels={
                constants.LABEL_TPU_ACCELERATOR: gen,
                constants.LABEL_TPU_TOPOLOGY: topo,
            },
            annotations=annotations or {},
        ),
    )


def test_from_node_reads_labels_and_status_annotations():
    node = make_tpu_node(annotations={
        "nos.ai/status-tpu-0-1x1-free": "2",
        "nos.ai/status-tpu-0-1x1-used": "2",
        "nos.ai/status-tpu-0-2x2-used": "1",
    })
    tn = TpuNode.from_node(node)
    assert tn.generation == "tpu-v5-lite-podslice"
    assert tn.topology_name == "2x4"
    assert len(tn.boards) == 1
    assert tn.free_slices() == {P11: 2}
    assert tn.used_slices() == {P11: 2, P22: 1}


def test_from_node_rejects_non_tpu_node():
    node = Node(metadata=ObjectMeta(name="gpu-node"))
    with pytest.raises(NotATpuNode):
        TpuNode.from_node(node)


def test_update_geometry_for_and_partitioning():
    tn = TpuNode.from_node(make_tpu_node())
    tn.boards[0].init_geometry()
    assert tn.update_geometry_for({P11: 2})
    part = tn.partitioning()
    assert 0 in part and part[0].get(P11, 0) >= 2


def test_allocatable_scalar_resources_partitioned():
    node = make_tpu_node(annotations={
        "nos.ai/status-tpu-0-2x2-free": "1",
        "nos.ai/status-tpu-0-1x1-used": "4",
    })
    tn = TpuNode.from_node(node)
    res = tn.allocatable_scalar_resources({"cpu": 8, constants.RESOURCE_TPU: 8})
    # whole-chip resource replaced by sub-slice resources
    assert constants.RESOURCE_TPU not in res
    assert res["nos.ai/tpu-slice-2x2"] == 1
    assert res["nos.ai/tpu-slice-1x1"] == 4
    assert res["cpu"] == 8


def test_allocatable_scalar_resources_unpartitioned():
    tn = TpuNode.from_node(make_tpu_node())
    res = tn.allocatable_scalar_resources({})
    assert res[constants.RESOURCE_TPU] == 8


def test_clone_independence():
    tn = TpuNode.from_node(make_tpu_node())
    tn.boards[0].init_geometry()
    c = tn.clone()
    c.update_geometry_for({P11: 8})
    assert tn.partitioning() == {0: {P24: 1}}


# ---------------------------------------------------------------------------
# ResourceCalculator
# ---------------------------------------------------------------------------

def test_resource_calculator_whole_chips_default_memory():
    calc = ResourceCalculator()
    out = calc.compute_request({constants.RESOURCE_TPU: 4, "cpu": 2})
    assert out[constants.RESOURCE_TPU_MEMORY] == 4 * 16
    assert out["cpu"] == 2


def test_resource_calculator_generation_aware():
    calc = ResourceCalculator(generation="v5p")
    out = calc.compute_request({constants.RESOURCE_TPU: 4})
    assert out[constants.RESOURCE_TPU_MEMORY] == 4 * 95


def test_resource_calculator_subslice_memory():
    calc = ResourceCalculator()  # default 16 GB/chip
    out = calc.compute_request({"nos.ai/tpu-slice-2x2": 2})
    assert out[constants.RESOURCE_TPU_MEMORY] == 2 * 4 * 16


def test_resource_calculator_pod_node_selector_generation():
    calc = ResourceCalculator()
    pod = Pod(
        metadata=ObjectMeta(name="p"),
        spec=PodSpec(
            containers=[Container(requests={constants.RESOURCE_TPU: 1})],
            node_selector={constants.LABEL_TPU_ACCELERATOR: "tpu-v5p-slice"},
        ),
    )
    out = calc.compute_pod_request(pod)
    assert out[constants.RESOURCE_TPU_MEMORY] == 95


def test_resource_calculator_mixed_gpu_cluster():
    calc = ResourceCalculator()
    out = calc.compute_request({
        "nvidia.com/gpu": 1,
        "nvidia.com/mig-1g.10gb": 2,
        constants.RESOURCE_TPU: 1,
    })
    assert out[constants.RESOURCE_GPU_MEMORY] == 32 + 20
    assert out[constants.RESOURCE_TPU_MEMORY] == 16
